"""OpenAI-compatible HTTP server over the LLM engine.

Mirrors the API surface the reference's north-star example serves and its
client exercises (vllm_inference.py:243-345: /health, /v1/models,
/v1/chat/completions with SSE streaming; openai_compatible/client.py).
Stdlib HTTP (fastapi/uvicorn are optional in this image); threads per
connection; the engine's continuous batching does the multiplexing.
"""

from __future__ import annotations

import json
import re
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import math

from ..observability import catalog as _C
from ..observability import reqtrace as _rt
from ..scheduling.admission import ShedError
from ..utils.prometheus import default_registry
from .engine import LLMEngine
from .sampling import SamplingParams


def _extract_images(messages: list) -> tuple[list, object]:
    """OpenAI multimodal content parts -> (text-flattened messages, image).

    Accepts ``content`` as a list of parts ({"type": "text"} /
    {"type": "image_url", "image_url": {"url": "data:image/..;base64,.."}}),
    the shape the reference serves via SGLang (sglang_vlm.py) and queries in
    chat_with_pdf_vision.py. Only data: URIs are accepted — this image has
    zero egress, and fetching remote URLs server-side is a SSRF hazard
    anyway. Single-image prompts only (v1 limit): a second image is a 400.
    """
    import base64
    import io

    image = None
    flat = []
    for m in messages:
        content = m.get("content")
        if not isinstance(content, list):
            flat.append(m)
            continue
        texts = []
        for part in content:
            ptype = part.get("type")
            if ptype == "text":
                texts.append(part.get("text", ""))
            elif ptype == "image_url":
                url = (part.get("image_url") or {}).get("url", "")
                if not url.startswith("data:"):
                    raise ValueError(
                        "only data: URIs are supported for image_url "
                        "(inline base64; this server does not fetch URLs)"
                    )
                if image is not None:
                    # silently answering about only the first image would
                    # return a confidently wrong result for "compare these"
                    raise ValueError(
                        "multiple images per request are not supported"
                    )
                b64 = url.split(",", 1)[1] if "," in url else ""
                raw = base64.b64decode(b64)
                try:
                    from PIL import Image

                    image = Image.open(io.BytesIO(raw))
                    image.load()
                except Exception as e:
                    raise ValueError(f"could not decode image: {e}") from e
            else:
                raise ValueError(f"unsupported content part type {ptype!r}")
        flat.append({**m, "content": "\n".join(t for t in texts if t)})
    return flat, image


def _params_from_body(body: dict, headers=None) -> SamplingParams:
    # per-request deadline: the x-mtpu-deadline-ms header wins over a
    # deadline_ms body field (headers let proxies inject budgets without
    # rewriting payloads)
    deadline_ms = body.get("deadline_ms")
    if headers is not None and headers.get("x-mtpu-deadline-ms"):
        deadline_ms = headers.get("x-mtpu-deadline-ms")
    return SamplingParams(
        temperature=float(body.get("temperature", 1.0)),
        top_p=float(body.get("top_p", 1.0)),
        top_k=int(body.get("top_k", 0)),
        max_tokens=int(body.get("max_tokens", 128)),
        stop=tuple(
            [body["stop"]] if isinstance(body.get("stop"), str)
            else body.get("stop") or []
        ),
        seed=int(body["seed"]) if body.get("seed") is not None else None,
        deadline_s=(
            float(deadline_ms) / 1000.0 if deadline_ms is not None else None
        ),
    )


def _sched_kwargs(body: dict, headers) -> dict:
    """Scheduling identity for one request: priority class from the
    x-mtpu-priority header (or a "priority" body field), tenant from
    x-mtpu-tenant (or OpenAI's own "user" field — the natural tenant key)."""
    from ..scheduling.policy import validate_class

    priority = body.get("priority") or "default"
    tenant = body.get("user") or "default"
    if headers is not None:
        priority = headers.get("x-mtpu-priority") or priority
        tenant = headers.get("x-mtpu-tenant") or tenant
    return {
        "priority": validate_class(str(priority)),  # typo'd class -> 400
        "tenant": str(tenant),
    }


class _Handler(BaseHTTPRequestHandler):
    server_ref: "OpenAIServer"

    def log_message(self, fmt, *args):
        pass

    def _json(self, code: int, obj, extra_headers: dict | None = None) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("content-type", "application/json")
        self.send_header("content-length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _shed_response(self, e: ShedError) -> None:
        """Admission rejected the request: 429 + Retry-After (the OpenAI
        rate_limit_error shape) — overload is a fast honest no, not an
        unbounded queue."""
        self._json(
            429,
            {"error": {
                "message": str(e),
                "type": "rate_limit_error",
                "code": e.reason,
            }},
            extra_headers={"retry-after": str(math.ceil(e.retry_after_s))},
        )

    def do_GET(self):
        srv = self.server_ref
        if self.path == "/health":
            self._json(200, {"status": "ok"})
        elif self.path == "/v1/models":
            self._json(
                200,
                {
                    "object": "list",
                    "data": [
                        {
                            "id": srv.model_name,
                            "object": "model",
                            "owned_by": "modal-examples-tpu",
                        }
                    ],
                },
            )
        elif self.path == "/metrics":
            eng = srv.engine
            s = eng.stats
            active = sum(1 for sl in eng.slots if not sl.free)
            pc = eng.prefix_cache
            # the process registry carries the engine's histogram/gauge series
            # (mtpu_engine_phase_seconds etc., recorded by the batch loop) —
            # without it a scraper could never see the latency distributions
            reg_text = default_registry.expose()
            reg_names = set(re.findall(r"^# TYPE (\S+)", reg_text, re.M))
            # metric names come from the central catalog (no stringly-typed
            # drift; tests/test_static.py enforces this package-wide); series
            # the registry already owns are skipped so names never duplicate
            occ = eng.cache.occupancy()
            hand_built = [
                (_C.GENERATED_TOKENS_TOTAL, f"{s.generated_tokens}"),
                (_C.PROMPT_TOKENS_TOTAL, f"{s.prompt_tokens}"),
                (_C.DECODE_STEPS_TOTAL, f"{s.steps}"),
                (_C.TOKENS_PER_SECOND, f"{s.tokens_per_second():.3f}"),
                (_C.ACTIVE_SLOTS, f"{active}"),
                (_C.WAITING_REQUESTS, f"{eng.policy.total_depth()}"),
                (_C.KV_PAGES_FREE, f"{occ['pages_free']}"),
                (_C.KV_PAGES_USED, f"{occ['pages_used']}"),
                (_C.KV_PAGE_OCCUPANCY, f"{occ['occupancy']:.4f}"),
                (_C.SCHEDULER_ERRORS_TOTAL, f"{eng.error_count}"),
            ]
            if eng.spec_gamma:
                hand_built += [
                    (_C.SPEC_PROPOSED_TOTAL, f"{s.spec_proposed}"),
                    (_C.SPEC_ACCEPTED_TOTAL, f"{s.spec_accepted}"),
                    (_C.SPEC_ACCEPTANCE_RATE, f"{s.acceptance_rate():.4f}"),
                ]
            if pc is not None:
                hand_built += [
                    (_C.PREFIX_CACHE_HITS_TOTAL, f"{pc.hits}"),
                    (_C.PREFIX_CACHE_MISSES_TOTAL, f"{pc.misses}"),
                    (_C.PREFIX_CACHED_PAGES, f"{pc.cached_pages}"),
                    (_C.PREFIX_CACHE_EVICTIONS_TOTAL, f"{pc.evictions}"),
                ]
            lines = [
                f"{name} {value}"
                for name, value in hand_built
                if name not in reg_names
            ]
            if _C.DECODE_IMPL not in reg_names:
                # the engine normally owns this gauge in the registry (with
                # tp + per-shard variant labels); hand-build only when this
                # process' registry never saw an engine init
                lines.append(
                    f'{_C.DECODE_IMPL}{{attention="'
                    f'{eng.impl_plan["attention"]}",scatter='
                    f'"{eng.impl_plan["scatter"]}",kv_dtype='
                    f'"{eng.impl_plan["kv_dtype"]}",tp='
                    f'"{eng.impl_plan.get("tp", 1)}",variant='
                    f'"{eng.impl_plan.get("ragged_variant") or "-"}"}} 1'
                )
            body = ("\n".join(lines) + "\n" + reg_text).encode()
            self.send_response(200)
            self.send_header("content-type", "text/plain; version=0.0.4")
            self.send_header("content-length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._json(404, {"error": "not found"})

    def do_POST(self):
        length = int(self.headers.get("content-length") or 0)
        try:
            body = json.loads(self.rfile.read(length)) if length else {}
        except json.JSONDecodeError:
            self._json(400, {"error": "invalid JSON"})
            return
        if self.path == "/v1/chat/completions":
            self._completions(body, chat=True)
        elif self.path == "/v1/completions":
            self._completions(body, chat=False)
        else:
            self._json(404, {"error": "not found"})

    def _completions(self, body: dict, chat: bool) -> None:
        srv = self.server_ref
        image = None
        try:
            if chat:
                messages = body.get("messages") or []
                messages, image = _extract_images(messages)
                prompt = srv.engine.tokenizer.apply_chat_template(messages)
            else:
                prompt = body.get("prompt") or ""
            if image is not None and srv.engine.vision_cfg is None:
                raise ValueError(
                    "this model does not accept images (engine has no "
                    "vision tower)"
                )
            params = _params_from_body(body, self.headers)
            sched = _sched_kwargs(body, self.headers)
            srv.engine.validate_params(params)
        except ValueError as e:
            self._json(400, {"error": {
                "message": str(e), "type": "invalid_request_error",
            }})
            return
        stream = bool(body.get("stream", False))
        n = max(1, int(body.get("n", 1)))
        rid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
        created = int(time.time())
        kind = "chat.completion" if chat else "text_completion"

        if n > 1 and stream:
            # OpenAI supports streaming multiple choices interleaved; this
            # server intentionally does not (one slot per SSE connection) —
            # reject loudly rather than silently returning one choice
            self._json(400, {"error": {
                "message": "n > 1 with stream=true is not supported",
                "type": "invalid_request_error",
            }})
            return
        if n > 1:
            # OpenAI `n`: fan out engine requests, one choice each (the
            # engine's continuous batching runs them concurrently). A fixed
            # seed derives per-choice seeds (seed+i) — otherwise seeded
            # sampling depends only on (seed, position) and every choice
            # would be identical.
            import dataclasses as _dc

            pairs = []
            try:
                for i in range(n):
                    pairs.append(srv.submit(
                        prompt,
                        _dc.replace(params, seed=params.seed + i)
                        if params.seed is not None
                        else params,
                        image=image,
                        **sched,
                    ))
            except ShedError as e:
                # partial fan-out shed: cancel the admitted siblings (their
                # slots go back to the pool) and reject the whole call
                for r, eng in pairs:
                    srv.abort_request(r, eng)
                    for _ in srv.stream_request(r, eng):
                        pass
                self._shed_response(e)
                return
            reqs = [r for r, _eng in pairs]
            texts = ["".join(srv.stream_request(r, eng)) for r, eng in pairs]
            if any(r.finish_reason == "error" for r in reqs):
                self._json(500, {"error": {
                    "message": "engine error while processing the request",
                    "type": "server_error",
                }})
                return
            choices = []
            for i, text in enumerate(texts):
                content = (
                    {"message": {"role": "assistant", "content": text}}
                    if chat
                    else {"text": text}
                )
                choices.append({
                    "index": i, **content,
                    "finish_reason": reqs[i].finish_reason or "stop",
                })
            n_prompt = len(reqs[0].prompt_tokens or [])
            n_out = sum(
                len(srv.engine.tokenizer.encode(t, add_bos=False)) for t in texts
            )
            self._json(
                200,
                {
                    "id": rid, "object": kind, "created": created,
                    "model": srv.model_name, "choices": choices,
                    "usage": {
                        "prompt_tokens": n_prompt,
                        "completion_tokens": n_out,
                        "total_tokens": n_prompt + n_out,
                        # real OpenAI field: prompt tokens served from the
                        # prefix cache (engine page claim) instead of
                        # recomputed — n>1 rows share one prompt, like
                        # prompt_tokens above
                        "prompt_tokens_details": {
                            "cached_tokens": int(
                                getattr(reqs[0], "cached_prompt_tokens", 0)
                            ),
                        },
                    },
                },
            )
            return

        include_usage = bool(
            (body.get("stream_options") or {}).get("include_usage")
        )
        try:
            req, eng = srv.submit(prompt, params, image=image, **sched)
        except ShedError as e:
            self._shed_response(e)
            return
        if stream:
            self.send_response(200)
            self.send_header("content-type", "text/event-stream")
            self.send_header("cache-control", "no-cache")
            # the engine request id (== distributed trace id): curl it back
            # into `tpurun explain` / GET /traces/<id> to see the lifecycle
            self.send_header("x-mtpu-request-id", req.request_id)
            self.end_headers()
            def chunk_of(**fields) -> dict:
                chunk = {
                    "id": rid,
                    "object": kind + ".chunk",
                    "created": created,
                    "model": srv.model_name,
                    **fields,
                }
                if include_usage and "usage" not in chunk:
                    # OpenAI stream_options.include_usage contract: every
                    # content chunk carries "usage": null; only the final
                    # dedicated chunk carries the totals
                    chunk["usage"] = None
                return chunk

            def usage_chunk() -> dict:
                n_prompt = len(req.prompt_tokens or [])
                return chunk_of(choices=[], usage={
                    "prompt_tokens": n_prompt,
                    "completion_tokens": req.n_generated,
                    "total_tokens": n_prompt + req.n_generated,
                    # real OpenAI field: prefix-cache hits at page claim
                    "prompt_tokens_details": {
                        "cached_tokens": int(
                            getattr(req, "cached_prompt_tokens", 0)
                        ),
                    },
                })

            try:
                for piece in srv.stream_request(req, eng):
                    delta = (
                        {"delta": {"content": piece}} if chat else {"text": piece}
                    )
                    chunk = chunk_of(
                        choices=[{"index": 0, **delta, "finish_reason": None}]
                    )
                    self.wfile.write(f"data: {json.dumps(chunk)}\n\n".encode())
                    self.wfile.flush()
                if req.finish_reason == "error":
                    # headers already sent: surface an SSE error event (the
                    # OpenAI stream-error shape) rather than a fake 'stop'
                    err = {"error": {
                        "message": "engine error while processing the request",
                        "type": "server_error",
                    }}
                    self.wfile.write(f"data: {json.dumps(err)}\n\n".encode())
                else:
                    final = chunk_of(choices=[{
                        "index": 0,
                        **({"delta": {}} if chat else {"text": ""}),
                        "finish_reason": req.finish_reason or "stop",
                    }])
                    self.wfile.write(f"data: {json.dumps(final)}\n\n".encode())
                if include_usage:
                    # usage ships on the error path too: a client doing
                    # billing/accounting still learns what the partial
                    # generation consumed
                    self.wfile.write(
                        f"data: {json.dumps(usage_chunk())}\n\n".encode()
                    )
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
            except BrokenPipeError:
                # client went away mid-stream: stop decoding for it so the
                # slot and its KV pages go back to the pool (vLLM aborts on
                # client disconnect the same way). Only drain when the
                # request is still live — a disconnect during the final
                # chunk/[DONE] writes arrives after the terminal marker was
                # already consumed, and draining then would block forever.
                if req.finish_reason is None:
                    srv.abort_request(req, eng)
                    for _ in srv.stream_request(req, eng):  # drain to _FINISH
                        pass
            return

        text = "".join(srv.stream_request(req, eng))
        if req.finish_reason == "error":
            # engine-side prefill/decode failure: a 5xx, not a fake success
            # with a non-OpenAI finish_reason
            self._json(500, {"error": {
                "message": "engine error while processing the request",
                "type": "server_error",
            }}, extra_headers={"x-mtpu-request-id": req.request_id})
            return
        n_prompt = len(req.prompt_tokens or [])
        n_out = len(srv.engine.tokenizer.encode(text, add_bos=False))
        content = (
            {"message": {"role": "assistant", "content": text}}
            if chat
            else {"text": text}
        )
        self._json(
            200,
            {
                "id": rid,
                "object": kind,
                "created": created,
                "model": srv.model_name,
                "choices": [{
                    "index": 0, **content,
                    "finish_reason": req.finish_reason or "stop",
                }],
                "usage": {
                    "prompt_tokens": n_prompt,
                    "completion_tokens": n_out,
                    "total_tokens": n_prompt + n_out,
                    # real OpenAI field: prefix-cache hits at page claim
                    "prompt_tokens_details": {
                        "cached_tokens": int(
                            getattr(req, "cached_prompt_tokens", 0)
                        ),
                    },
                },
            },
            extra_headers={"x-mtpu-request-id": req.request_id},
        )


class OpenAIServer:
    """HTTP front end; start() binds and serves in a background thread.

    Fronts either ONE engine (``engine=``, the per-process deployed shape)
    or N replicas behind a ``PrefixAffinityRouter`` (``router=``): with a
    router, every submit routes by shared-prefix affinity and streams from
    the replica that owns the request. ``self.engine`` stays the primary
    replica's engine (tokenizer, /metrics, validate_params — replicas serve
    one model, so any replica answers those)."""

    def __init__(self, engine: LLMEngine | None = None,
                 model_name: str = "mtpu-llm",
                 host: str = "0.0.0.0", port: int = 8000, *, router=None):
        if (engine is None) == (router is None):
            raise ValueError("pass exactly one of engine= or router=")
        self.router = router
        if engine is not None:
            self.engine = engine
        else:
            # primary = the first replica that can own a request end to end
            # (skips prefill-role replicas under a disagg coordinator)
            serving = [
                r for r in router.replicas
                if getattr(r, "serves_requests", True)
            ]
            self.engine = (serving or router.replicas)[0].engine
        self.model_name = model_name
        handler = type("BoundHandler", (_Handler,), {"server_ref": self})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: threading.Thread | None = None
        self._canary = None

    def _maybe_start_canary(self) -> None:
        """Env-gated like the MTPU_TSDB sampler: exporting
        ``MTPU_CANARY_INTERVAL`` arms always-on golden-set probing for the
        fleet this server fronts, with zero further wiring
        (docs/observability.md#correctness-canary). Router fronts only —
        the prober walks ``router.replicas`` and down-weights via
        ``set_health_weight``."""
        import os

        from ..observability.canary import INTERVAL_ENV, CanaryProber

        if self.router is None or not os.environ.get(INTERVAL_ENV):
            return
        # a DisaggCoordinator front exposes the weight-bearing router
        # underneath it; a bare PrefixAffinityRouter is its own
        target = getattr(self.router, "router", self.router)
        self._canary = CanaryProber(target).start()

    def submit(self, prompt, params, image=None, **sched):
        """Place one request; returns (request, owning engine). Raises
        ShedError when the target engine's admission rejects it.

        The distributed request trace is minted HERE — the fleet entry
        point — and propagated down through router placement, queues, and
        (under a disagg coordinator) the page-migration wire; the trace id
        becomes the request id, echoed to the client as
        ``x-mtpu-request-id`` so ``tpurun explain <id>`` finds it."""
        trace = _rt.start_request_trace(entry="api")
        if self.router is not None:
            req = self.router.submit(
                prompt, params, image=image, trace=trace, **sched
            )
            return req, self.router.replica_for(req).engine
        return (
            self.engine.submit(prompt, params, image=image, trace=trace,
                               **sched),
            self.engine,
        )

    def stream_request(self, req, eng):
        """Stream one submitted request's text pieces. With a router
        front this rides the failover path (serving/failover.py): a
        replica dying mid-stream is checkpoint-resumed on a healthy peer
        and the SSE stream continues token-identically — already-emitted
        text is deduped at the seam, so the client sees zero errors and
        zero duplicated chars (docs/failover.md)."""
        if self.router is not None:
            return self.router.stream(req)
        return eng.stream(req)

    def abort_request(self, req, eng) -> None:
        """Abort wherever the request now lives — after a failover the
        owning replica may not be the one that first accepted it."""
        if self.router is not None:
            self.router.abort(req)
        else:
            eng.abort(req)

    def _engines(self):
        """Engines whose scheduler loop this server owns. A role-aware
        front (``DisaggCoordinator``) exposes ``serving_engines()`` so
        prefill-role replicas are NEVER started: their engines run the
        synchronous prefill path, and a scheduler loop racing it would
        donate the same cache buffers twice."""
        if self.router is not None:
            serving = getattr(self.router, "serving_engines", None)
            if serving is not None:
                return serving()
            return [r.engine for r in self.router.replicas]
        return [self.engine]

    def start(self) -> "OpenAIServer":
        for eng in self._engines():
            eng.start()
        self._maybe_start_canary()
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        for eng in self._engines():
            eng.start()
        self._maybe_start_canary()
        self.httpd.serve_forever()

    def stop(self) -> None:
        if self._canary is not None:
            self._canary.stop()
            self._canary = None
        self.httpd.shutdown()
        self.httpd.server_close()
        for eng in self._engines():
            eng.stop()
