"""Speculative decoding REFERENCE ORACLE: single-sequence propose/verify.

This module is NOT the serving path. The engine's production speculation is
the fused, batched, paged-KV round in :mod:`serving.spec_runtime`
(docs/speculative.md) — scheduler-integrated, adaptive-depth, harvested
through the multistep plane. What lives here is the textbook algorithm in
its simplest possible form, kept as the correctness yardstick the fused
runtime is tested against (tests/test_speculative.py; the quarantine is
enforced by tests/test_static.py — nothing in the package may import this
module outside spec-parity tests).

The algorithm (SURVEY.md §2.3; vllm_inference.py:115-116,196-205 enables
the same idea via flags): a small draft llama proposes gamma tokens
autoregressively, the target scores all of them in ONE teacher-forced
forward, and standard speculative sampling accepts a prefix (greedy mode:
accept while draft == target argmax; stochastic mode: accept token x with
prob min(1, p_t(x)/p_d(x)), resampling from the adjusted residual on
rejection) — guaranteeing the output distribution equals the target
model's.

Static-shape jit: fixed token buffer, ``lax.while_loop`` over rounds,
``lax.scan`` for the draft chain. Scoring recomputes over the fixed window
(no KV cache) — fine for an oracle, exactly the cost the fused runtime's
paged ``verify_step`` removes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..models import llama


def _logits_at(params, cfg, buf, attn_impl="xla"):
    """[S] token buffer -> [S, V] next-token logits (teacher-forced)."""
    return llama.forward(params, buf[None], cfg, attn_impl=attn_impl)[0]


@functools.partial(
    jax.jit,
    static_argnames=(
        "target_cfg", "draft_cfg", "max_new", "gamma", "greedy", "temperature",
    ),
)
def speculative_generate(
    target_params,
    draft_params,
    target_cfg: llama.LlamaConfig,
    draft_cfg: llama.LlamaConfig,
    prompt: jax.Array,  # [S0] int32
    prompt_len: int | jax.Array,
    key: jax.Array,
    *,
    max_new: int = 32,
    gamma: int = 4,
    greedy: bool = True,
    temperature: float = 1.0,
) -> tuple[jax.Array, jax.Array]:
    """Returns (buffer [S0+max_new], n_generated). Greedy mode reproduces the
    target model's greedy decode exactly; stochastic mode samples from the
    target distribution via accept/reject."""
    S = prompt.shape[0] + max_new
    buf = jnp.zeros((S,), jnp.int32).at[: prompt.shape[0]].set(prompt)
    pos0 = jnp.asarray(prompt_len, jnp.int32)

    def cond(state):
        buf, pos, n_gen, key = state
        return (n_gen < max_new) & (pos < S)

    def body(state):
        buf, pos, n_gen, key = state
        key, k_draft, k_acc, k_res = jax.random.split(key, 4)

        # 1) draft proposes gamma tokens autoregressively
        def draft_step(carry, k):
            buf_d, p = carry
            logits = _logits_at(draft_params, draft_cfg, buf_d)
            lp = logits[jnp.clip(p - 1, 0, S - 1)] / max(temperature, 1e-6)
            tok = jnp.where(
                greedy,
                jnp.argmax(lp).astype(jnp.int32),
                jax.random.categorical(k, lp).astype(jnp.int32),
            )
            buf_d = buf_d.at[jnp.clip(p, 0, S - 1)].set(tok)
            return (buf_d, jnp.minimum(p + 1, S)), (tok, lp)

        (buf_d, _), (draft_toks, draft_logits) = jax.lax.scan(
            draft_step, (buf, pos), jax.random.split(k_draft, gamma)
        )

        # 2) target scores the whole draft chain in one forward
        t_logits_all = _logits_at(target_params, target_cfg, buf_d)
        idx = jnp.clip(pos - 1 + jnp.arange(gamma), 0, S - 1)
        t_logits = t_logits_all[idx] / max(temperature, 1e-6)  # [gamma, V]

        # 3) acceptance
        if greedy:
            t_choice = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
            match = t_choice == draft_toks
            n_acc = jnp.argmin(
                jnp.concatenate([match.astype(jnp.int32), jnp.zeros((1,), jnp.int32)])
            )
            # token written at the first mismatch = target's choice there
            fix_tok = t_choice[jnp.clip(n_acc, 0, gamma - 1)]
        else:
            p_t = jax.nn.softmax(t_logits, axis=-1)
            p_d = jax.nn.softmax(draft_logits, axis=-1)
            tok_pt = jnp.take_along_axis(p_t, draft_toks[:, None], 1)[:, 0]
            tok_pd = jnp.take_along_axis(p_d, draft_toks[:, None], 1)[:, 0]
            u = jax.random.uniform(k_acc, (gamma,))
            accept = u < jnp.minimum(1.0, tok_pt / jnp.maximum(tok_pd, 1e-20))
            n_acc = jnp.argmin(
                jnp.concatenate([accept.astype(jnp.int32), jnp.zeros((1,), jnp.int32)])
            )
            # resample the rejected position from max(p_t - p_d, 0)
            j = jnp.clip(n_acc, 0, gamma - 1)
            residual = jnp.maximum(p_t[j] - p_d[j], 0.0)
            residual = jnp.where(
                residual.sum() > 0, residual / residual.sum(), p_t[j]
            )
            fix_tok = jax.random.categorical(k_res, jnp.log(residual + 1e-20))
            fix_tok = fix_tok.astype(jnp.int32)

        # 4) commit accepted draft tokens, then the fix token. Scatters use
        # mode="drop": masked-out lanes write to index S (out of bounds) and
        # are dropped — no duplicate in-bounds indices, so no nondeterministic
        # clobbering when the budget truncates the accepted run.
        budget = max_new - n_gen
        n_draft_take = jnp.minimum(n_acc, budget)
        keep = jnp.arange(gamma) < n_draft_take
        write_pos = jnp.where(keep, pos + jnp.arange(gamma), S)
        new_buf = buf.at[write_pos].set(draft_toks, mode="drop")
        do_fix = (n_acc < gamma) & (n_acc < budget)
        fix_pos = jnp.where(do_fix, pos + n_acc, S)
        new_buf = new_buf.at[fix_pos].set(fix_tok, mode="drop")
        advanced = n_draft_take + do_fix.astype(jnp.int32)
        return new_buf, pos + advanced, n_gen + advanced, key

    buf, pos, n_gen, _ = jax.lax.while_loop(cond, body, (buf, pos0, jnp.zeros((), jnp.int32), key))
    return buf, n_gen


def greedy_generate(params, cfg, prompt, prompt_len, max_new: int):
    """Plain greedy reference (what speculative greedy must reproduce)."""
    S = prompt.shape[0] + max_new
    buf = jnp.zeros((S,), jnp.int32).at[: prompt.shape[0]].set(prompt)

    def step(carry, _):
        buf, p = carry
        logits = _logits_at(params, cfg, buf)
        tok = jnp.argmax(logits[jnp.clip(p - 1, 0, S - 1)]).astype(jnp.int32)
        buf = buf.at[jnp.clip(p, 0, S - 1)].set(tok)
        return (buf, jnp.minimum(p + 1, S)), None

    (buf, _), _ = jax.lax.scan(step, (buf, jnp.asarray(prompt_len)), None, length=max_new)
    return buf
