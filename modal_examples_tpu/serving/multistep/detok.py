"""Detokenization worker: incremental decode + stream emission off the
scheduler thread.

The PR-14 profiler attributed a steady slice of every accept to
``tokenizer.decode`` (the ``detokenize`` tick phase); with N-step macro
dispatch the scheduler would pay it N times per harvest. This worker
moves it off-thread: the scheduler feeds ACCEPTED token ids (already
bookkept — stats, usage, TTFT, length checks all stay on the scheduler,
where the harvest-boundary invariants live) and the worker owns
everything text: incremental decode, stop-string scan/truncation, the
stop-safe + unstable-tail holdback, emission to ``req.out_queue``, and
the ``req.emitted_len`` mirror failover checkpoints clip against
(put-then-update: ``emitted_len`` never exceeds what the client was
actually sent).

Ordering contract: one FIFO queue. Text chunks and the terminal marker
for a request are delivered in feed order because the engine routes the
finish marker through :meth:`finish` for every request the worker owns —
a marker can never overtake held text. Stop-string hits can only be seen
here, so the worker requests teardown by setting ``req.aborted``; the
scheduler's next-tick reap frees the slot and routes the "stop" marker
back through the queue.

:meth:`flush` is the migration barrier (serving/failover.py): the
scheduler drains the queue before reading ``req.emitted_len`` into a
checkpoint, so mid-macro-step migration resumes from exactly the emitted
cursor. A worker that dies keeps serving degraded: the engine falls back
to inline detokenization and direct marker delivery (``alive`` gates
every route).
"""

from __future__ import annotations

import queue
import threading

from ...utils.log import get_logger

_log = get_logger("detok")


class DetokWorker:
    """One daemon thread per engine, lazily created on the first routed
    token (the engine only routes while ``decode_steps > 1`` or for
    requests this worker already owns — mid-stream knob flips never
    reorder a stream)."""

    def __init__(self, *, tokenizer, deliver, safe_len, unstable_tail,
                 name: str = "engine"):
        self._tokenizer = tokenizer
        self._deliver = deliver  # engine._deliver_finish(req, marker)
        self._safe_len = safe_len
        self._unstable_tail = unstable_tail
        self._states: dict = {}  # request_id -> per-stream text state
        self._lock = threading.Lock()
        self._q: queue.Queue = queue.Queue()
        self._stopping = False
        self._thread = threading.Thread(
            target=self._run, name=f"mtpu-detok-{name}", daemon=True
        )
        self._thread.start()

    # -- scheduler-thread API ------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._thread.is_alive() and not self._stopping

    def owns(self, req) -> bool:
        with self._lock:
            return req.request_id in self._states

    def register(self, req, prior_tokens: list, emitted_len: int) -> None:
        """Adopt a stream. ``prior_tokens``/``emitted_len`` seed the text
        state — empty/0 for fresh requests, the installed history and
        resume cursor for failover-resumed ones."""
        with self._lock:
            self._states[req.request_id] = {
                "req": req,
                "tokens": list(prior_tokens),
                "emitted": int(emitted_len),
                "stopped": False,
            }

    def feed(self, req, token: int) -> None:
        """Enqueue one ACCEPTED (appended) token for decode + emission."""
        self._q.put(("tok", req, token))

    def finish(self, req, marker) -> None:
        """Enqueue the terminal marker behind any pending text."""
        self._q.put(("fin", req, marker))

    def flush(self, timeout: float = 5.0) -> bool:
        """Barrier: wait until everything enqueued so far is processed."""
        if not self.alive:
            return True
        done = threading.Event()
        self._q.put(("flush", done, None))
        return done.wait(timeout)

    def queue_depth(self) -> int:
        return self._q.qsize()

    def stop(self, timeout: float = 5.0) -> None:
        """Drain every pending event, then stop the thread (engine.stop()
        calls this BEFORE releasing callers, so held text lands ahead of
        the release sweep's direct markers)."""
        self._stopping = True
        self._q.put(("end", None, None))
        self._thread.join(timeout)

    # -- worker thread -------------------------------------------------------

    def _run(self) -> None:
        while True:
            kind, a, b = self._q.get()
            if kind == "end":
                return
            try:
                if kind == "flush":
                    a.set()
                elif kind == "tok":
                    self._on_token(a, b)
                else:
                    self._on_finish(a, b)
            except Exception:
                # a text-path bug must not wedge streams: keep draining
                # (the engine's alive-gate handles a dead worker; a
                # throwing event just loses its chunk)
                _log.exception("detok worker event failed")

    def _on_token(self, req, token: int) -> None:
        with self._lock:
            st = self._states.get(req.request_id)
        if st is None or st["stopped"]:
            return
        st["tokens"].append(int(token))
        text = self._tokenizer.decode(st["tokens"])
        stop = req.params.stop
        if stop:
            for stop_s in stop:
                idx = text.find(stop_s)
                if idx >= 0:
                    # truncate, emit the remainder, and hand teardown to
                    # the scheduler: only it may free the slot
                    st["stopped"] = True
                    self._emit(req, st, text[:idx], final=True)
                    req.aborted = True
                    return
        self._emit(req, st, text, final=False)

    def _on_finish(self, req, marker) -> None:
        with self._lock:
            st = self._states.pop(req.request_id, None)
        if st is not None:
            if st["stopped"] and marker.reason == "length":
                # the stop match landed before a same-macro-step length
                # finish: the stream was truncated at the stop, report it
                marker = type(marker)("stop")
            elif not st["stopped"] and marker.reason in ("stop", "length"):
                # normal finish: flush the holdback tail
                text = self._tokenizer.decode(st["tokens"])
                self._emit(req, st, text, final=True)
            # abort/deadline/error: held text drops, like the inline path
        self._deliver(req, marker)

    def _emit(self, req, st: dict, text: str, *, final: bool) -> None:
        safe = len(text) if final else self._safe_len(text, req.params.stop)
        new = text[st["emitted"]:safe]
        if new and (final or not self._unstable_tail(new)):
            req.out_queue.put(new)
            st["emitted"] += len(new)
            req.emitted_len = st["emitted"]
