"""The macro-step decode program: N fused decode+sample steps per dispatch.

Program shape (docs/multistep.md): the same per-step body the classic
block program scans — ``llama.decode_step`` (attention over the paged KV
cache, scatter of the new KV fused in) followed by ``sample`` — wrapped
in :func:`~...ops.scan_loop.masked_scan` so a step whose every lane is
dead skips the transformer entirely. Each lane (slot) carries a ``live``
bit that drops at its stop token or when its per-slot length budget is
spent; the program returns, besides the token matrix, a ``[N, B]``
validity mask — the harvest-boundary contract: the host accepts exactly
the valid prefix per slot and nothing behind it, so checkpoints and live
KV migration taken between harvests see only committed tokens.

Exactness: sampling inside the scan is (seed, position)-keyed
(``serving.sampling.seeded_row_keys``) — a seeded row's token depends
only on its request seed and absolute decode position, never on how many
steps share a dispatch — and the per-step KV arithmetic is the identical
``decode_step`` body the classic block program runs, so N>1 is
token-identical to N=1 on the same replica (asserted across
{greedy, seeded} x {bf16, int8} in tests/test_multistep.py). Cross-TP
exactness is never asserted anywhere in this repo — psum reordering —
only the documented logit-tolerance contract (docs/tensor_parallel.md).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ...models import llama
from ...ops.scan_loop import masked_scan
from ..sampling import sample

#: the runtime knob: decode steps fused into one dispatch (1 = classic)
DECODE_STEPS_ENV = "MTPU_DECODE_STEPS"


def resolve_decode_steps(arg: int | None = None) -> int:
    """Resolve the macro-step count ONCE, the engine's knob rule
    (MTPU_KV_DTYPE / MTPU_PREFILL_BUDGET): explicit arg beats
    ``MTPU_DECODE_STEPS`` beats 1. The result lands on a plain engine
    attribute read per dispatch, so benches and tests mutate it at
    runtime without recompiling anything already traced."""
    if arg is None:
        raw = os.environ.get(DECODE_STEPS_ENV, "")
        arg = int(raw) if raw else 1
    return max(1, int(arg))


def build_multistep_fn(
    cfg,
    *,
    paged_impl: str,
    scatter_impl: str,
    mesh,
    eos_id: int,
    n_steps: int,
):
    """Build the jittable N-step decode program for one engine config.

    Signature matches the classic block program plus a trailing
    ``budgets`` [B] int32 — the per-slot count of tokens the host would
    still accept (min of remaining ``max_tokens`` and remaining context),
    computed at dispatch from the optimistic positions. A lane dies when
    it samples ``eos_id`` or exhausts its budget; the eos / budget-final
    token itself is still valid (the host finishes ON it, mirroring the
    classic accept path's stop/length checks exactly).

    Returns ``(toks [N, B], valid [N, B] bool, last [B], k_pages,
    v_pages)``. ``valid[k, i]`` means lane ``i`` was live entering step
    ``k``; invalid tail tokens are holds and must not be accepted.
    """

    def multistep_fn(
        params, k_pages, v_pages, prev_tokens, override, override_mask,
        positions, page_tables, active, key, temps, top_ps, top_ks, seeds,
        budgets,
    ):
        tok0 = jnp.where(override_mask, override, prev_tokens)
        taken0 = jnp.zeros_like(budgets)

        def step(live, state, k_i):
            tok, pos, taken, kp, vp = state
            logits, kp, vp = llama.decode_step(
                params, tok, pos, kp, vp, page_tables, live, cfg,
                impl=paged_impl, scatter_impl=scatter_impl, mesh=mesh,
            )
            nxt = sample(
                logits, k_i, temps, top_ps, top_ks, seeds=seeds,
                step_ids=pos,
            )
            nxt = jnp.where(live, nxt, tok)  # dead lanes hold steady
            valid = live
            one = live.astype(taken.dtype)
            taken = taken + one
            pos = pos + one  # dead lanes stop advancing (position-keyed)
            live = live & (nxt != eos_id) & (taken < budgets)
            return live, (nxt, pos, taken, kp, vp), (nxt, valid)

        def hold(live, state, k_i):
            # all lanes dead: hold tokens, emit an all-false validity row
            return state[0], live

        live, state, (toks, valid) = masked_scan(
            step,
            hold,
            active,
            (tok0, positions, taken0, k_pages, v_pages),
            jax.random.split(key, n_steps),
        )
        last, _pos, _taken, k_pages, v_pages = state
        return toks, valid, last, k_pages, v_pages

    return multistep_fn
