"""Macro-step decode runtime (docs/multistep.md).

One jitted program runs N decode+sample steps per dispatch — ``lax.scan``
over the engine's decode step with the KV scatter fused between steps,
on-device (seed, position)-keyed sampling, and stop-token/length-budget
early-exit via ``lax.cond`` (ops.scan_loop.masked_scan) — so the host
pays ONE dispatch and ONE blocking read per N tokens instead of per
``decode_block``. The harvest plane returns per-slot validity masks; the
scheduler accepts only valid tokens, keeping the PR-12 checkpoint /
live-migration boundary exact while a slot holds un-harvested tokens.
Detokenization moves off the scheduler thread onto :class:`DetokWorker`.

The knob is ``LLMEngine(decode_steps=...)`` / ``MTPU_DECODE_STEPS``,
runtime-mutable like ``prefill_budget``; 1 (the default) is the classic
one-block-per-dispatch path, byte-identical fall-through.
"""

from .detok import DetokWorker
from .runtime import (
    DECODE_STEPS_ENV,
    build_multistep_fn,
    resolve_decode_steps,
)

__all__ = [
    "DECODE_STEPS_ENV",
    "DetokWorker",
    "build_multistep_fn",
    "resolve_decode_steps",
]
