"""modal_examples_tpu — a TPU-native serverless ML framework.

The programming model of modal-labs/modal-examples (App/Function/Cls,
``.remote/.map/.spawn``, images, volumes, secrets, schedules, sandboxes, web
endpoints, clusters) re-built TPU-first: ``tpu="v5e-8"`` resource specs,
JAX/XLA images, Pallas kernels, and ``pjit``/``shard_map`` collectives over
ICI/DCN. See SURVEY.md for the component-by-component mapping to the
reference.

Typical use (mirrors hello_world.py / text_to_image.py in the reference):

    import modal_examples_tpu as mtpu

    app = mtpu.App("example")

    @app.function(tpu="v5e-1")
    def f(x):
        ...

    @app.cls(tpu="v5e-8")
    class Model:
        @mtpu.enter()
        def load(self): ...
        @mtpu.method()
        def generate(self, prompt): ...
"""

from .core.app import App
from .core.cls import Cls, enter, exit, method, parameter
from .core.executor import FunctionTimeoutError, InputCancelled, current_input_id
from .core.function import (
    Function,
    FunctionCall,
    batched,
    concurrent,
    gather,
)
from .core.image import Image
from .core.resources import TPUSpec, parse_tpu_spec
from .core.retries import Retries
from .core.sandbox import ContainerProcess, Sandbox, forward
from .core.schedules import Cron, Period
from .core.serialization import RemoteError
from .storage.dict_queue import Dict, Queue
from .storage.secret import Secret
from .storage.volume import CloudBucketMount, Volume
from .web.endpoints import (
    asgi_app,
    fastapi_endpoint,
    web_endpoint,
    web_server,
    websocket_endpoint,
    wsgi_app,
)

__version__ = "0.1.0"


class _Experimental:
    """``mtpu.experimental`` — mirrors ``modal.experimental``: the clusters
    API (simple_torch_cluster.py:97-111). Import is lazy so the jax-free
    client layer stays jax-free."""

    @staticmethod
    def clustered(size: int, chips_per_host: int | None = None):
        from .parallel.cluster import clustered as _clustered

        return _clustered(size, chips_per_host)

    @staticmethod
    def get_cluster_info():
        from .parallel.cluster import get_cluster_info as _gci

        return _gci()


experimental = _Experimental()

__all__ = [
    "App",
    "Cls",
    "CloudBucketMount",
    "Cron",
    "Dict",
    "Function",
    "FunctionCall",
    "FunctionTimeoutError",
    "Image",
    "InputCancelled",
    "Period",
    "Queue",
    "RemoteError",
    "Retries",
    "Sandbox",
    "Secret",
    "TPUSpec",
    "Volume",
    "asgi_app",
    "batched",
    "concurrent",
    "enter",
    "exit",
    "fastapi_endpoint",
    "gather",
    "method",
    "parameter",
    "parse_tpu_spec",
    "web_endpoint",
    "web_server",
    "websocket_endpoint",
    "wsgi_app",
]


class _Functions:
    """Compat namespace: ``modal.functions.gather`` spelling."""

    gather = gather
    FunctionCall = FunctionCall


functions = _Functions()
