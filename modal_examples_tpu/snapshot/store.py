"""Content-addressed store for container memory snapshots.

A snapshot entry is the serialized post-``@enter(snap=True)`` state of one
container's user object (see :mod:`.capture`), keyed by everything that could
change what that state looks like:

- the **image digest** (layer chain hash, core/image.py),
- the **function source hash** (source text of the target class, falling back
  to its pickled definition bytes),
- the **env fingerprint** (the container env the spec resolves: image env +
  secrets + TPU spec),
- the **cls-params hash** (``modal.parameter`` overrides), and
- the host **CPU machine tag** (utils/compile_cache.py ``_machine_tag``) —
  captured arrays and the compile-cache entries they pair with are only valid
  on the microarch that produced them.

Layout: one directory per key under the store root (default
``<state_dir>/snapshots``, override with ``MTPU_SNAPSHOT_DIR`` — point it at a
mounted Volume to share snapshots between replicas, or use
:meth:`SnapshotStore.from_volume`), holding ``state.bin`` (payload) and
``meta.json`` (checksum + manifest). Writes are atomic (temp dir + rename,
first writer wins) and reads verify the checksum, deleting corrupt entries —
a bad snapshot degrades to a cold boot, never an error. Eviction is LRU on
``last_used``, bounded by ``MTPU_SNAPSHOT_MAX_ENTRIES`` (default 16) and
optionally ``MTPU_SNAPSHOT_MAX_BYTES``.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import uuid
from pathlib import Path

from .._internal import config as _config
from ..observability import metrics as _obs
from ..utils.compile_cache import _machine_tag

_DISABLED = ("0", "off", "none")

DEFAULT_MAX_ENTRIES = 16


def snapshots_enabled() -> bool:
    """Process-wide kill switch: ``MTPU_SNAPSHOT=0`` disables capture/restore
    even for ``enable_memory_snapshot=True`` functions."""
    return os.environ.get("MTPU_SNAPSHOT", "").lower() not in _DISABLED


def default_root() -> Path:
    env = os.environ.get("MTPU_SNAPSHOT_DIR", "")
    if env:
        return Path(env)
    return _config.state_dir() / "snapshots"


def source_hash_for(target, fn_bytes: bytes = b"") -> str:
    """Code-identity hash of the snapshot target: source text when the class
    is importable from a file, else the cloudpickled definition bytes."""
    import inspect

    obj = target[0] if isinstance(target, tuple) else target
    try:
        src = inspect.getsource(obj)
    except (OSError, TypeError):
        src = ""
    h = hashlib.sha256()
    h.update(getattr(obj, "__qualname__", repr(obj)).encode())
    h.update(src.encode() if src else fn_bytes)
    return h.hexdigest()[:24]


def compute_snapshot_key(
    *,
    image_digest: str,
    source_hash: str,
    env: dict[str, str] | None = None,
    cls_params: bytes | None = None,
    machine_tag: str | None = None,
) -> str:
    env_fp = hashlib.sha256(
        json.dumps(sorted((env or {}).items())).encode()
    ).hexdigest()
    params_fp = hashlib.sha256(cls_params or b"").hexdigest()
    blob = "|".join([image_digest, source_hash, env_fp, params_fp])
    tag = machine_tag or _machine_tag()
    return f"{tag}-{hashlib.sha256(blob.encode()).hexdigest()[:24]}"


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class SnapshotStore:
    """Filesystem-backed snapshot store (get/put/list/inspect/clear)."""

    def __init__(
        self,
        root: str | os.PathLike | None = None,
        *,
        max_entries: int | None = None,
        max_bytes: int | None = None,
    ):
        self.root = Path(root) if root else default_root()
        # malformed env knobs degrade to defaults — snapshot config can
        # never turn into a boot outage (the store runs inside every
        # snapshot-enabled container's boot path)
        if max_entries is None:
            try:
                max_entries = int(
                    os.environ.get("MTPU_SNAPSHOT_MAX_ENTRIES", DEFAULT_MAX_ENTRIES)
                )
            except ValueError:
                max_entries = DEFAULT_MAX_ENTRIES
        if max_bytes is None:
            try:
                max_bytes = int(os.environ.get("MTPU_SNAPSHOT_MAX_BYTES", 0)) or None
            except ValueError:
                max_bytes = None
        self.max_entries = max_entries
        self.max_bytes = max_bytes

    @classmethod
    def from_volume(cls, volume, **kw) -> "SnapshotStore":
        """A Volume-backed store, so autoscaled replicas share snapshots."""
        return cls(root=Path(str(volume.local_path)) / ".snapshots", **kw)

    # -- paths ---------------------------------------------------------------

    def _entry_dir(self, key: str) -> Path:
        return self.root / key

    def _meta_path(self, key: str) -> Path:
        return self._entry_dir(key) / "meta.json"

    def _state_path(self, key: str) -> Path:
        return self._entry_dir(key) / "state.bin"

    # -- read ----------------------------------------------------------------

    def has(self, key: str) -> bool:
        # parse, don't stat: a corrupt meta.json must read as a miss, or the
        # autoscaler gate and put() racers treat a dead entry as live
        return self.inspect(key) is not None

    def inspect(self, key: str) -> dict | None:
        try:
            return json.loads(self._meta_path(key).read_text())
        except (OSError, json.JSONDecodeError):
            return None

    def get(self, key: str) -> tuple[bytes, dict] | None:
        """Payload + meta for ``key``, or None on miss/corruption (corrupt
        entries are deleted so the next boot re-captures). Lookups feed the
        ``mtpu_snapshot_store_gets_total{result=hit|miss}`` hit-ratio
        counters — once per container boot, never a hot path."""
        meta = self.inspect(key)
        if meta is None:
            if self._entry_dir(key).exists():
                self.delete(key)  # corrupt meta.json: self-heal
            _obs.record_snapshot_store_get("miss")
            return None
        try:
            payload = self._state_path(key).read_bytes()
        except OSError:
            self.delete(key)
            _obs.record_snapshot_store_get("miss")
            return None
        if _sha256(payload) != meta.get("checksum"):
            self.delete(key)
            _obs.record_snapshot_store_get("miss")
            return None
        self._touch(key, meta)
        _obs.record_snapshot_store_get("hit")
        return payload, meta

    def _touch(self, key: str, meta: dict) -> None:
        """Bump last_used for LRU (best-effort, atomic)."""
        meta["last_used"] = time.time()
        try:
            tmp = self._entry_dir(key) / f".meta.{os.getpid()}.tmp"
            tmp.write_text(json.dumps(meta, indent=2))
            os.replace(tmp, self._meta_path(key))
        except OSError:
            pass

    # -- write ---------------------------------------------------------------

    def put(self, key: str, payload: bytes, manifest: dict | None = None) -> bool:
        """Atomically publish an entry; first writer wins. Returns True when
        this call's entry (or a racing writer's) is in place."""
        now = time.time()
        meta = {
            "key": key,
            "checksum": _sha256(payload),
            "size_bytes": len(payload),
            "created_at": now,
            "last_used": now,
            "manifest": manifest or {},
        }
        tmp = self.root / f".tmp-{uuid.uuid4().hex[:12]}"
        try:
            tmp.mkdir(parents=True, exist_ok=True)
            (tmp / "state.bin").write_bytes(payload)
            (tmp / "meta.json").write_text(json.dumps(meta, indent=2))
            os.rename(tmp, self._entry_dir(key))
        except OSError:
            if not self.has(key):
                # the blocking dir is a corrupt entry, not a racing capture:
                # replace it so the key can't wedge permanently
                self.delete(key)
                try:
                    os.rename(tmp, self._entry_dir(key))
                except OSError:
                    shutil.rmtree(tmp, ignore_errors=True)
                    return self.has(key)
                self._evict()
                return True
            shutil.rmtree(tmp, ignore_errors=True)
            return True  # lost the race to a concurrent capture
        self._evict()
        return True

    def delete(self, key: str) -> bool:
        d = self._entry_dir(key)
        if not d.exists():
            return False
        shutil.rmtree(d, ignore_errors=True)
        return True

    def clear(self) -> int:
        """Delete every entry dir, including corrupt ones entries() skips."""
        n = 0
        if not self.root.is_dir():
            return 0
        for d in self.root.iterdir():
            if d.name.startswith(".") or not d.is_dir():
                continue
            n += self.delete(d.name)
        self.publish_size_gauges()
        return n

    # -- listing / eviction --------------------------------------------------

    def entries(self) -> list[dict]:
        """All entry metas, most-recently-used first."""
        out = []
        if not self.root.is_dir():
            return out
        for d in self.root.iterdir():
            if d.name.startswith(".") or not d.is_dir():
                continue
            meta = self.inspect(d.name)
            if meta is not None:
                out.append(meta)
        out.sort(key=lambda m: m.get("last_used", 0), reverse=True)
        return out

    def _evict(self) -> None:
        entries = self.entries()
        # entry-count bound
        while len(entries) > self.max_entries:
            victim = entries.pop()
            self.delete(victim["key"])
        # optional byte bound
        if self.max_bytes:
            total = sum(e.get("size_bytes", 0) for e in entries)
            while entries and total > self.max_bytes:
                victim = entries.pop()
                total -= victim.get("size_bytes", 0)
                self.delete(victim["key"])
        self.publish_size_gauges(entries)

    def publish_size_gauges(self, entries: list[dict] | None = None) -> dict:
        """Refresh ``mtpu_snapshot_store_entries`` / ``_bytes`` from the
        store's current contents (called after every put/evict, and by
        anything that wants a fresh reading, e.g. `tpurun top`)."""
        if entries is None:
            entries = self.entries()
        total = sum(e.get("size_bytes", 0) for e in entries)
        _obs.set_snapshot_store_size(entries=len(entries), total_bytes=total)
        return {"entries": len(entries), "bytes": total}
