"""Capture path: serialize a container's post-``@enter(snap=True)`` state.

Runs inside the container worker right after the snapshot-eligible enter
hooks complete (and before the non-snap hooks, matching the reference's
snapshot point — gpu_snapshot.py takes the memory image after ``snap=True``
setup). The user object's ``__dict__`` goes through the pytree codec; attrs
that can't cross the boundary (locks, clients, open handles, jitted
callables on jax versions where cloudpickle can't ship them) become rebuild
markers attributed to the hook that created them, so the restore path knows
to re-run exactly that hook. The manifest also records the compile-cache
linkage: a restored boot pairs its rebuilt ``jax.jit`` wrappers with the
persistent XLA cache entries the capture boot produced.
"""

from __future__ import annotations

import os
import sys
import time

from . import codec
from .store import SnapshotStore


def capture(
    store: SnapshotStore,
    key: str,
    obj,
    *,
    tag: str = "",
    baseline_attrs: set[str] | frozenset[str] = frozenset(),
    hook_attrs: dict[str, list[str]] | None = None,
) -> bool:
    """Snapshot ``obj``'s state under ``key``. Returns True when an entry is
    in place (this capture's or a racing replica's). Never raises."""
    hook_attrs = hook_attrs or {}
    try:
        t0 = time.monotonic()
        payload, rebuild = codec.encode_state(dict(obj.__dict__))
        # Attrs created by __init__/cls-params and untouched by the snap
        # hooks are recreated by fresh construction on every boot; but a
        # baseline attr a hook *rebound* to something uncapturable must stay
        # a rebuild marker so the restore re-runs the owning hook.
        hook_owned = {a for attrs in hook_attrs.values() for a in attrs}
        rebuild = [a for a in rebuild if a not in baseline_attrs or a in hook_owned]
        manifest = {
            "tag": tag,
            "type": type(obj).__name__,
            "hook_attrs": hook_attrs,
            "rebuild": sorted(rebuild),
            "baseline": sorted(baseline_attrs),
            "jax_compile_cache": os.environ.get("JAX_COMPILATION_CACHE_DIR", ""),
            "python": sys.version.split()[0],
            "encode_s": round(time.monotonic() - t0, 4),
        }
        return store.put(key, payload, manifest)
    except Exception:
        return False  # capture must never take down a healthy boot
