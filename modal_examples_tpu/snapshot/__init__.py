"""Memory-snapshot subsystem: checkpoint/restore of initialized containers.

The TPU analog of the reference's GPU memory snapshots (gpu_snapshot.py):
``@app.cls(enable_memory_snapshot=True)`` + ``@mtpu.enter(snap=True)`` mark
the expensive load-once stage of a container boot; after the first warm boot
captures it, every later cold start restores the serialized state instead of
re-running the hooks, and pairs with the persistent XLA compile cache
(utils/compile_cache.py) so rebuilt ``jax.jit`` wrappers recompile from disk.

Pieces:

- :mod:`.store`   — content-addressed, LRU-evicted entry store
- :mod:`.codec`   — jax-pytree-aware state serialization
- :mod:`.capture` — post-``snap=True`` state capture (container side)
- :mod:`.restore` — boot-time restore with cold-boot fallback

:func:`build_and_enter` is the single entry point the executor's container
boot (and the inline backend) calls for every Cls container.
"""

from __future__ import annotations

from .capture import capture
from .codec import CodecError
from .restore import RestoreResult, try_restore
from .store import SnapshotStore, compute_snapshot_key, default_root, snapshots_enabled

__all__ = [
    "CodecError",
    "RestoreResult",
    "SnapshotStore",
    "build_and_enter",
    "capture",
    "compute_snapshot_key",
    "default_root",
    "snapshots_enabled",
    "try_restore",
]


def build_and_enter(
    user_cls: type,
    params: dict | None,
    meta: dict,
    *,
    snapshot_key: str | None = None,
    snapshot_dir: str | None = None,
    tag: str = "",
) -> tuple[object, dict]:
    """Construct the user object and run its ``@enter`` hooks, restoring past
    ``snap=True`` hooks from a memory snapshot when one exists.

    Returns ``(obj, boot_info)`` where ``boot_info["snapshot"]`` is one of:

    - ``"off"``      — snapshots not enabled for this spec (plain boot)
    - ``"hit"``      — restored; covered snap hooks were skipped
    - ``"miss"``     — no entry; cold boot, then first-warm-boot capture
    - ``"fallback"`` — an entry existed but couldn't be used; cold boot

    ``boot_info["captured"]`` reports whether this boot published a snapshot.
    """

    def fresh():
        obj = user_cls()
        for k, v in (params or {}).items():
            setattr(obj, k, v)
        return obj

    enter: list[str] = meta.get("enter", [])
    snap_hooks: list[str] = meta.get("snap_enter", [])

    obj = fresh()
    if not (snapshot_key and snap_hooks and snapshots_enabled()):
        for name in enter:
            getattr(obj, name)()
        return obj, {"snapshot": "off"}

    store = SnapshotStore(root=snapshot_dir)
    had_entry = store.has(snapshot_key)
    res = try_restore(store, snapshot_key, obj, snap_hooks)
    if res is not None:
        ran_non_snap = False
        try:
            for name in enter:
                if name in res.skipped_hooks:
                    continue
                getattr(obj, name)()
                if name not in snap_hooks:
                    ran_non_snap = True
            return obj, {
                "snapshot": "hit",
                "captured": False,
                "skipped_hooks": res.skipped_hooks,
                "rerun_hooks": res.rerun_hooks,
            }
        except Exception:
            # restored state may have broken the hook: the entry could be
            # poison — drop it so the next boot goes cold either way
            store.delete(snapshot_key)
            if ran_non_snap:
                # a non-snap hook already completed this boot; silently
                # re-running it on the cold path would double its side
                # effects — fail the boot exactly like a cold boot whose
                # hook raised, and let the pool retry cold
                raise

    # cold boot; try_restore may have half-applied state, start over
    obj = fresh()
    baseline = set(obj.__dict__)
    baseline_vals = dict(obj.__dict__)
    hook_attrs: dict[str, list[str]] = {}
    seen = set(baseline)
    for name in enter:
        if name in snap_hooks:
            getattr(obj, name)()
            created = set(obj.__dict__) - seen
            # a hook also *owns* baseline attrs it rebinds (identity check):
            # if the new value can't be captured, restore must re-run this
            # hook rather than silently serving the __init__ placeholder
            mutated = {
                a
                for a, v in baseline_vals.items()
                if a in obj.__dict__ and obj.__dict__[a] is not v
            }
            hook_attrs[name] = sorted(created | mutated)
            seen |= created
            for a in mutated:
                baseline_vals[a] = obj.__dict__[a]
    captured = capture(
        store,
        snapshot_key,
        obj,
        tag=tag,
        baseline_attrs=baseline,
        hook_attrs=hook_attrs,
    )
    for name in enter:
        if name not in snap_hooks:
            getattr(obj, name)()
    return obj, {
        "snapshot": "fallback" if had_entry else "miss",
        "captured": captured,
    }
