"""Restore path: resume a container boot past snapshot-eligible enter hooks.

On boot, the container worker checks the store for the spec's snapshot key.
On a hit, the captured attrs are decoded (numpy-captured jax arrays re-put on
device) and applied to a freshly constructed user object, and the boot
**skips** every ``@enter(snap=True)`` hook whose state was fully captured —
the load-once work is already done. Hooks that produced rebuild-marked attrs
(jitted callables etc.) are re-run; with the persistent XLA compile cache
warm, the re-run's compile is a disk hit, so "rebuild" is cheap.

Failure policy: any mismatch — unknown hooks, unattributable rebuild attrs,
checksum/codec errors, a hook raising against restored state — returns the
boot to the cold path. Restore must never be less reliable than a cold
start.
"""

from __future__ import annotations

import dataclasses

from . import codec
from .store import SnapshotStore


@dataclasses.dataclass
class RestoreResult:
    skipped_hooks: list[str]  # snap hooks whose work the snapshot covers
    rerun_hooks: list[str]  # snap hooks that must re-run (rebuild markers)
    restored_attrs: list[str]


def try_restore(
    store: SnapshotStore, key: str, obj, snap_hooks: list[str]
) -> RestoreResult | None:
    """Apply the snapshot under ``key`` to ``obj``. Returns None (cold boot)
    on miss or on any inconsistency; never raises."""
    try:
        entry = store.get(key)
        if entry is None:
            return None
        payload, meta = entry
        manifest = meta.get("manifest") or {}
        hook_attrs: dict[str, list[str]] = manifest.get("hook_attrs") or {}
        if sorted(hook_attrs) != sorted(snap_hooks):
            return None  # lifecycle shape changed under a stale key
        rebuild = set(manifest.get("rebuild") or [])
        rerun = [h for h in snap_hooks if rebuild & set(hook_attrs.get(h, []))]
        attributed = set()
        for h in rerun:
            attributed |= set(hook_attrs.get(h, []))
        if rebuild - attributed:
            # an unpicklable attr no hook owns: nothing can rebuild it
            return None
        state = codec.decode_state(payload)
        for name, value in state.items():
            setattr(obj, name, value)
        return RestoreResult(
            skipped_hooks=[h for h in snap_hooks if h not in rerun],
            rerun_hooks=rerun,
            restored_attrs=sorted(state),
        )
    except Exception:
        return None
