"""Jax-pytree-aware state codec for memory snapshots.

Per-attribute serialization of a user object's ``__dict__``. Values are
walked as pytrees (dict/list/tuple containers); ``jax.Array`` leaves are
devicelessly captured as numpy (``jax.device_get`` semantics) and re-put on
restore (``jnp.asarray`` — the "weights back to HBM" step of a restored
boot). Everything else round-trips through the framework's pickle/cloudpickle
serializer. A value that survives neither pickling path raises
:class:`CodecError`; the capture layer records it as a rebuild-on-restore
marker instead of failing the snapshot.

This module must stay importable without jax (it runs in the jax-free core
boot path); jax/numpy are only touched when a jax array is actually present,
which implies jax is already imported in this process.
"""

from __future__ import annotations

import pickle
import sys

from ..core import serialization as ser

_MAX_DEPTH = 64


class CodecError(Exception):
    """Value cannot cross the snapshot boundary (record a rebuild marker)."""


class _JaxLeaf:
    """Marker wrapper: a jax array captured as host numpy."""

    __slots__ = ("array",)

    def __init__(self, array):
        self.array = array

    def __getstate__(self):
        return self.array

    def __setstate__(self, array):
        self.array = array


def _is_jax_array(v) -> bool:
    if "jax" not in sys.modules:  # no jax imported -> no jax arrays exist
        return False
    mod = type(v).__module__ or ""
    if not mod.startswith(("jax", "jaxlib")):
        return False
    return hasattr(v, "__array__") and hasattr(v, "dtype") and hasattr(v, "shape")


def _encode_tree(v, depth: int = 0):
    if depth > _MAX_DEPTH:
        return v
    try:
        if _is_jax_array(v):
            import numpy as np

            return _JaxLeaf(np.asarray(v))
        if isinstance(v, dict):
            items = {k: _encode_tree(x, depth + 1) for k, x in v.items()}
            return items if type(v) is dict else type(v)(items)
        if isinstance(v, (list, tuple)):
            items = [_encode_tree(x, depth + 1) for x in v]
            if type(v) is list:
                return items
            if isinstance(v, tuple) and hasattr(v, "_fields"):  # namedtuple
                return type(v)(*items)
            return type(v)(items)
    except Exception:
        pass  # exotic container: fall through and pickle the value whole
    return v


def _decode_tree(v, depth: int = 0):
    if depth > _MAX_DEPTH:
        return v
    if isinstance(v, _JaxLeaf):
        try:
            import jax.numpy as jnp

            return jnp.asarray(v.array)
        except Exception:
            return v.array  # jax unavailable here: numpy ducks for most ops
    if isinstance(v, dict):
        items = {k: _decode_tree(x, depth + 1) for k, x in v.items()}
        return items if type(v) is dict else type(v)(items)
    if isinstance(v, (list, tuple)):
        items = [_decode_tree(x, depth + 1) for x in v]
        if type(v) is list:
            return items
        if isinstance(v, tuple) and hasattr(v, "_fields"):
            return type(v)(*items)
        return type(v)(items)
    return v


def encode_attr(value) -> bytes:
    try:
        return ser.serialize(_encode_tree(value))
    except Exception as e:
        raise CodecError(
            f"{type(value).__name__} is not snapshot-serializable: {e}"
        ) from e


def decode_attr(data: bytes):
    return _decode_tree(pickle.loads(data))


def encode_state(state: dict) -> tuple[bytes, list[str]]:
    """Encode an object's ``__dict__``. Returns (payload, rebuild_attrs):
    attrs that cannot be serialized (jitted callables, locks, clients) are
    left out of the payload and listed for the restore path to rebuild."""
    blobs: dict[str, bytes] = {}
    rebuild: list[str] = []
    for name, value in state.items():
        try:
            blobs[name] = encode_attr(value)
        except CodecError:
            rebuild.append(name)
    return pickle.dumps(blobs, protocol=pickle.HIGHEST_PROTOCOL), rebuild


def decode_state(payload: bytes) -> dict:
    blobs = pickle.loads(payload)
    return {name: decode_attr(data) for name, data in blobs.items()}
