"""Parallel layer: meshes, named-axis collectives, multi-host clusters.

Lazy re-exports (PEP 562): ``cluster`` is importable without jax (the
control-plane supervisor needs its env-var protocol), while ``mesh`` /
``collectives`` pull in jax only when first touched.
"""

from .cluster import ClusterInfo, clustered, get_cluster_info, init_jax_distributed

_LAZY = {
    "AXIS_ORDER": "mesh",
    "DATA": "mesh",
    "EXPERT": "mesh",
    "FSDP": "mesh",
    "SEQ": "mesh",
    "TENSOR": "mesh",
    "fsdp_specs": "mesh",
    "make_mesh": "mesh",
    "replicated": "mesh",
    "sharding": "mesh",
    "shard_pytree": "mesh",
    "single_device_mesh": "mesh",
    "collectives": None,
    "mesh": None,
    "cluster": None,
}

__all__ = [
    "ClusterInfo",
    "clustered",
    "get_cluster_info",
    "init_jax_distributed",
    *sorted(_LAZY),
]


def __getattr__(name: str):
    import importlib

    if name in _LAZY:
        target = _LAZY[name]
        if target is None:
            mod = importlib.import_module(f".{name}", __name__)
            globals()[name] = mod
            return mod
        mod = importlib.import_module(f".{target}", __name__)
        value = getattr(mod, name)
        globals()[name] = value
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
