"""Multi-host clusters: ``@clustered(size=n)`` + ``get_cluster_info()``.

Reference spec: ``@modal.experimental.clustered(size=2)`` co-schedules n
containers with a private interconnect; ``get_cluster_info()`` exposes
``rank`` / ``container_ips`` and rank 0 acts as coordinator
(14_clusters/simple_torch_cluster.py:96-111). The reference then launches
torchrun with one *process per GPU* and NCCL for collectives (:118-130).

TPU-native redesign (SURVEY.md §3.4): a pod slice IS the cluster. One process
per host drives all local chips under SPMD; ``get_cluster_info()`` feeds
``jax.distributed.initialize`` (coordinator address = rank 0), and all
collectives are XLA ops over ICI — there is no torchrun, no NCCL, no
proc-per-chip fan-out.

The local control plane gang-schedules n container processes per call and
simulates each "host" with a CPU device mesh
(``--xla_force_host_platform_device_count``), so the full multi-host path —
distributed init, global mesh, cross-process collectives — runs and is
tested on a single machine (the fake backend the reference lacks, SURVEY.md
§4).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable

RANK_ENV = "MTPU_CLUSTER_RANK"
SIZE_ENV = "MTPU_CLUSTER_SIZE"
COORD_ENV = "MTPU_CLUSTER_COORDINATOR"
IPS_ENV = "MTPU_CLUSTER_IPS"
CHIPS_ENV = "MTPU_CLUSTER_CHIPS_PER_HOST"


@dataclasses.dataclass(frozen=True)
class ClusterInfo:
    rank: int
    size: int
    container_ips: list[str]
    coordinator_address: str
    chips_per_host: int
    task_id: str | None = None


def in_cluster() -> bool:
    return RANK_ENV in os.environ


def get_cluster_info() -> ClusterInfo:
    """Inside a clustered container: this host's place in the slice."""
    if not in_cluster():
        raise RuntimeError(
            "get_cluster_info() called outside a @clustered container"
        )
    return ClusterInfo(
        rank=int(os.environ[RANK_ENV]),
        size=int(os.environ[SIZE_ENV]),
        container_ips=os.environ[IPS_ENV].split(","),
        coordinator_address=os.environ[COORD_ENV],
        chips_per_host=int(os.environ.get(CHIPS_ENV, "1")),
        task_id=os.environ.get("MTPU_TASK_ID"),
    )


def clustered(size: int, chips_per_host: int | None = None) -> Callable:
    """Mark a function for gang scheduling over ``size`` hosts.

    Apply *under* ``@app.function`` (like the reference stacks
    ``@app.function`` over ``@modal.experimental.clustered``,
    simple_torch_cluster.py:96-97).
    """
    if size < 1:
        raise ValueError("cluster size must be >= 1")

    def deco(fn):
        if hasattr(fn, "spec") and hasattr(fn, "raw_f"):
            raise TypeError(
                "@clustered must be applied UNDER @app.function (closest to "
                "the def), like the reference stacks them "
                "(simple_torch_cluster.py:96-97)"
            )
        fn.__mtpu_cluster__ = {"size": size, "chips_per_host": chips_per_host}
        return fn

    return deco


def init_jax_distributed() -> "object":
    """Join this host to the slice-wide JAX runtime and return the info.

    The analog of the reference's torchrun rendezvous + ``dist.init_process_
    group("nccl", ...)`` (simple_torch_cluster_script.py:85) — but one call,
    one process per host, and afterwards ``jax.devices()`` is the *global*
    device list so a single ``Mesh`` spans the slice.
    """
    import jax

    info = get_cluster_info()
    jax.distributed.initialize(
        coordinator_address=info.coordinator_address,
        num_processes=info.size,
        process_id=info.rank,
    )
    return info


def global_mesh(axes: dict[str, int] | None = None):
    """Mesh over every chip in the slice (call after init_jax_distributed)."""
    from .mesh import make_mesh

    return make_mesh(axes)
