"""Named-axis collectives — the NCCL replacement (SURVEY.md §2.3, §5.8).

The reference's workloads never call NCCL directly; they go through
``torch.distributed`` (``dist.send/recv/barrier``,
simple_torch_cluster_script.py:53-90) or leave it to the engine. Our analog:
a thin wrapper over XLA collectives with *named mesh axes*, usable inside
``shard_map``/``pjit``-partitioned functions. Intra-slice traffic rides ICI;
multi-slice rides DCN — chosen by XLA from the mesh, never by workload code.

All functions take the axis *name* (str) or a tuple of names.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

AxisName = str | tuple[str, ...]


def psum(x, axis: AxisName):
    """All-reduce sum over a mesh axis (the DDP gradient sync primitive —
    replaces torch.distributed.all_reduce / NCCL allreduce)."""
    return lax.psum(x, axis)


def pmean(x, axis: AxisName):
    return lax.pmean(x, axis)


def pmax(x, axis: AxisName):
    return lax.pmax(x, axis)


def pmin(x, axis: AxisName):
    return lax.pmin(x, axis)


def all_gather(x, axis: AxisName, *, gather_dim: int = 0, tiled: bool = True):
    """Gather shards along ``gather_dim`` (replaces NCCL allgather)."""
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_dim: int = 0):
    """Sum-reduce then scatter shards (replaces NCCL reduce_scatter; the
    memory-efficient half of a ZeRO gradient sync)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def all_to_all(x, axis: AxisName, *, split_dim: int, concat_dim: int, tiled: bool = True):
    """Transpose shards across an axis (MoE dispatch / Ulysses seq-parallel)."""
    return lax.all_to_all(
        x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=tiled
    )


def ppermute(x, axis: AxisName, perm: list[tuple[int, int]]):
    """Point-to-point shifts (replaces dist.send/dist.recv pairs)."""
    return lax.ppermute(x, axis, perm)


def ring_shift(x, axis: AxisName, shift: int = 1):
    """Rotate shards around the axis ring — the ring-attention building block.
    On a TPU torus this maps to neighbor ICI hops."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: AxisName):
    """This shard's coordinate on the axis (the 'rank')."""
    return lax.axis_index(axis)


def axis_size(axis: AxisName) -> int:
    # jax.lax.axis_size landed after 0.4.x; psum(1) is the portable spelling
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def barrier(axis: AxisName):
    """Synchronization fence: a trivial psum all shards must reach
    (replaces dist.barrier, simple_torch_cluster_script.py:88)."""
    return lax.psum(jnp.ones((), jnp.int32), axis)


def unreplicate(tree):
    """First shard of every leaf (host-side convenience for logging)."""
    return jax.tree.map(lambda x: x[0] if getattr(x, "ndim", 0) else x, tree)
