"""Device mesh construction — the TPU-native replacement for process groups.

The reference's distributed story is NCCL process groups wired up by torchrun
(14_clusters/simple_torch_cluster.py:67,118-130). On TPU the unit is a
``jax.sharding.Mesh`` over the slice's chips: axes are *named* (data / fsdp /
tensor / seq / expert), shardings are ``NamedSharding`` partition specs, and
XLA inserts the collectives (psum over ICI, etc.) — nothing in workload code
ever names a transport. This module builds meshes from ``tpu=`` specs or raw
device lists and is the single place axis-name conventions live.

Mental model follows the public scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert collectives.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.resources import TPUSpec, parse_tpu_spec

# Canonical axis names. Order matters: earlier axes get the slower-varying
# device dimension (DCN/across-host first, ICI/within-host last), so tensor/
# seq axes land on the fastest interconnect.
DATA = "data"
FSDP = "fsdp"
TENSOR = "tensor"
SEQ = "seq"
EXPERT = "expert"
AXIS_ORDER = (DATA, FSDP, EXPERT, SEQ, TENSOR)


def shard_map_compat(fn, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across the rename: new jax exposes it top-level
    with ``check_vma``; 0.4.x ships ``jax.experimental.shard_map`` with the
    same knob spelled ``check_rep``. One wrapper so kernels never branch."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=bool(check_vma),
    )


def resolve_axes(
    axes: dict[str, int] | None, n_devices: int
) -> dict[str, int]:
    """Resolve an axis spec against a device count. One axis may be -1
    (fill); omitted spec means pure data parallelism."""
    if not axes:
        return {DATA: n_devices}
    axes = dict(axes)
    fill_keys = [k for k, v in axes.items() if v == -1]
    if len(fill_keys) > 1:
        raise ValueError(f"only one axis may be -1, got {fill_keys}")
    fixed = math.prod(v for v in axes.values() if v != -1)
    if fill_keys:
        if n_devices % fixed:
            raise ValueError(
                f"device count {n_devices} not divisible by fixed axes {axes}"
            )
        axes[fill_keys[0]] = n_devices // fixed
    elif fixed != n_devices:
        raise ValueError(
            f"axes {axes} multiply to {fixed}, but mesh has {n_devices} devices"
        )
    return axes


def make_mesh(
    axes: dict[str, int] | None = None,
    *,
    devices: Sequence | None = None,
    spec: TPUSpec | str | None = None,
) -> Mesh:
    """Build a named mesh.

    ``axes`` maps axis name -> size (one may be -1 to fill). ``devices``
    defaults to all visible devices; ``spec`` (e.g. "v5e-8") validates the
    request against the slice size when given.
    """
    if devices is None:
        devices = jax.devices()
        if axes:
            # a fully-specified request smaller than the machine takes a
            # prefix of the devices (e.g. a seq-4 mesh on an 8-chip host)
            want = math.prod(v for v in axes.values() if v != -1)
            if all(v != -1 for v in axes.values()) and want <= len(devices):
                devices = devices[:want]
    if spec is not None:
        if isinstance(spec, str):
            spec = parse_tpu_spec(spec)
        if len(devices) != spec.chips:
            raise ValueError(
                f"tpu spec {spec} wants {spec.chips} chips but "
                f"{len(devices)} devices are visible"
            )
    resolved = resolve_axes(axes, len(devices))
    # order axes canonically so cross-host axes vary slowest
    names = sorted(
        resolved,
        key=lambda n: AXIS_ORDER.index(n) if n in AXIS_ORDER else len(AXIS_ORDER),
    )
    shape = tuple(resolved[n] for n in names)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(names))


def fsdp_specs(params, mesh: Mesh, *, axis: str = FSDP, min_size: int = 2**12):
    """Derive ZeRO-3/FSDP PartitionSpecs for an arbitrary param pytree: every
    sufficiently large leaf is sharded along its largest axis-divisible dim
    over ``axis``; small leaves (norms, biases) stay replicated.

    Under jit, GSPMD turns these annotations into exactly the FSDP schedule
    the reference delegates to torch FSDP/verl (grpo_verl.py:176-202,
    SURVEY.md §2.3): per-layer all-gather of the shard on use, reduce-scatter
    of the gradients, and optimizer state that lives sharded — optax init
    under jit propagates the param shardings to the moment buffers, so
    per-device memory for params+grads+optimizer shrinks ~linearly with the
    axis size (proven by tests/test_parallel.py::TestFSDP).
    """
    n = mesh.shape[axis]

    def spec_for(leaf):
        shape = getattr(leaf, "shape", ())
        if not shape or math.prod(shape) < min_size:
            return P()
        dims = sorted(range(len(shape)), key=lambda d: shape[d], reverse=True)
        for d in dims:
            if shape[d] % n == 0:
                return P(*(axis if i == d else None for i in range(len(shape))))
        return P()

    return jax.tree.map(spec_for, params)


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]), (DATA,))


def sharding(mesh: Mesh, *axis_per_dim: str | None | tuple) -> NamedSharding:
    """``sharding(mesh, 'data', None, 'tensor')`` -> NamedSharding for a rank-3
    array sharded over data on dim0 and tensor on dim2."""
    return NamedSharding(mesh, P(*axis_per_dim))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_pytree(tree, mesh: Mesh, spec_fn) -> object:
    """Device-put every leaf with the PartitionSpec returned by
    ``spec_fn(path_leafname, leaf)``; used by model loaders to place sharded
    weights without 2x host RAM."""
    import jax.tree_util as jtu

    def place(path, leaf):
        pspec = spec_fn(path, leaf)
        return jax.device_put(leaf, NamedSharding(mesh, pspec))

    return jtu.tree_map_with_path(place, tree)
