"""Native host runtime: ctypes bridge to mtpu_host.cpp.

Builds the shared library on first import (g++ is in the image; no
pybind11 — plain C ABI via ctypes) and caches it next to the source.
Every consumer has a pure-Python fallback, so the framework degrades
gracefully where no compiler exists.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

import numpy as np

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "mtpu_host.cpp"
_LIB = _HERE / "libmtpu_host.so"
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            [
                "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                str(_SRC), "-o", str(_LIB),
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def load():
    """The loaded library, or None when native isn't available."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(str(_LIB))
        except OSError:
            return None
        lib.mtpu_alloc_create.restype = ctypes.c_void_p
        lib.mtpu_alloc_create.argtypes = [ctypes.c_int32]
        lib.mtpu_alloc_destroy.argtypes = [ctypes.c_void_p]
        lib.mtpu_alloc_alloc.restype = ctypes.c_int32
        lib.mtpu_alloc_alloc.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ]
        lib.mtpu_alloc_free.restype = ctypes.c_int32
        lib.mtpu_alloc_free.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ]
        lib.mtpu_alloc_available.restype = ctypes.c_int32
        lib.mtpu_alloc_available.argtypes = [ctypes.c_void_p]
        lib.mtpu_byte_encode_batch.restype = ctypes.c_int32
        lib.mtpu_byte_encode_batch.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.mtpu_levenshtein.restype = ctypes.c_int32
        lib.mtpu_levenshtein.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ]
        _lib = lib
        return _lib


class NativePageAllocator:
    """C++ free-list allocator (drop-in for kv_cache.PageAllocator)."""

    def __init__(self, n_pages: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.n_pages = n_pages
        self._h = lib.mtpu_alloc_create(n_pages)
        if not self._h:
            raise ValueError(f"bad page count {n_pages}")

    def alloc(self, n: int) -> list[int]:
        out = (ctypes.c_int32 * max(n, 1))()
        rc = self._lib.mtpu_alloc_alloc(self._h, n, out)
        if rc != 0:
            from ..serving.kv_cache import OutOfPages

            raise OutOfPages(f"need {n} pages, {self.available} free")
        return list(out[:n])

    def free(self, pages: list[int]) -> None:
        arr = (ctypes.c_int32 * max(len(pages), 1))(*pages)
        self._lib.mtpu_alloc_free(self._h, arr, len(pages))

    @property
    def available(self) -> int:
        return self._lib.mtpu_alloc_available(self._h)

    def __del__(self):
        try:
            self._lib.mtpu_alloc_destroy(self._h)
        except Exception:
            pass


def byte_encode_batch(
    texts: list[str], max_len: int, bos_id: int = 256, pad_id: int = 258
) -> tuple[np.ndarray, np.ndarray, int]:
    """Batched byte tokenization -> (ids [n, max_len] i32, mask, max_true).

    Native single-call path with a numpy fallback.
    """
    n = len(texts)
    blobs = [t.encode("utf-8", errors="replace") for t in texts]
    lib = load()
    if lib is not None and n:
        data = b"".join(blobs)
        buf = np.frombuffer(data, np.uint8) if data else np.zeros(1, np.uint8)
        lengths = np.asarray([len(b) for b in blobs], np.int64)
        ids = np.empty((n, max_len), np.int32)
        mask = np.empty((n, max_len), np.int32)
        max_true = lib.mtpu_byte_encode_batch(
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, max_len, bos_id, pad_id,
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            mask.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return ids, mask, int(max_true)
    # fallback
    ids = np.full((n, max_len), pad_id, np.int32)
    mask = np.zeros((n, max_len), np.int32)
    max_true = 0
    for i, b in enumerate(blobs):
        row = ([bos_id] if bos_id >= 0 else []) + list(b)
        row = row[:max_len]
        ids[i, : len(row)] = row
        mask[i, : len(row)] = 1
        max_true = max(max_true, len(row))
    return ids, mask, max_true


def levenshtein_ids(a: list[int], b: list[int]) -> int:
    lib = load()
    if lib is None:
        from ..utils.metrics import _levenshtein

        return _levenshtein([str(x) for x in a], [str(x) for x in b])
    aa = (ctypes.c_int32 * max(len(a), 1))(*a)
    bb = (ctypes.c_int32 * max(len(b), 1))(*b)
    return lib.mtpu_levenshtein(aa, len(a), bb, len(b))
