// Sanitizer harness for mtpu_host.cpp (PARITY.md §5.2: the reference
// runs its native components under TSAN/ASAN in CI; this is ours).
//
// Exercises every exported entry point, with the allocator under real
// multi-thread contention — the only shared-mutable-state component.
// Built twice by tests/test_native_sanitizers.py: -fsanitize=address,
// undefined and -fsanitize=thread. Exit 0 = clean; sanitizers abort or
// report otherwise.

// asserts ARE the test — keep them alive under any build flags
#undef NDEBUG
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <set>
#include <thread>
#include <vector>

extern "C" {
void* mtpu_alloc_create(int32_t n_pages);
void mtpu_alloc_destroy(void* handle);
int32_t mtpu_alloc_alloc(void* handle, int32_t n, int32_t* out);
int32_t mtpu_alloc_free(void* handle, const int32_t* ids, int32_t n);
int32_t mtpu_alloc_available(void* handle);
int32_t mtpu_byte_encode_batch(const uint8_t* data, const int64_t* lengths,
                               int32_t n, int32_t max_len, int32_t bos_id,
                               int32_t pad_id, int32_t* out_ids,
                               int32_t* out_mask);
int32_t mtpu_levenshtein(const int32_t* a, int32_t la, const int32_t* b,
                         int32_t lb);
}

static void allocator_contention() {
  const int32_t kPages = 4097;
  void* a = mtpu_alloc_create(kPages);
  assert(a != nullptr);
  assert(mtpu_alloc_available(a) == kPages - 1);

  const int kThreads = 8, kIters = 400, kChunk = 16;
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([&a, t]() {
      int32_t ids[kChunk];
      for (int i = 0; i < kIters; ++i) {
        int32_t n = 1 + ((t + i) % kChunk);
        if (mtpu_alloc_alloc(a, n, ids) == 0) {
          for (int32_t j = 0; j < n; ++j) assert(ids[j] > 0);
          mtpu_alloc_free(a, ids, n);
        }
      }
    });
  }
  for (auto& t : ts) t.join();

  // all pages returned, none duplicated
  assert(mtpu_alloc_available(a) == kPages - 1);
  std::vector<int32_t> all(kPages - 1);
  assert(mtpu_alloc_alloc(a, kPages - 1, all.data()) == 0);
  std::set<int32_t> uniq(all.begin(), all.end());
  assert(static_cast<int32_t>(uniq.size()) == kPages - 1);
  assert(uniq.count(0) == 0);
  assert(mtpu_alloc_alloc(a, 1, all.data()) == -1);  // exhausted
  mtpu_alloc_destroy(a);
}

static void tokenize_roundtrip() {
  const char* texts[] = {"hello", "", "a longer line of text"};
  std::vector<uint8_t> data;
  std::vector<int64_t> lens;
  for (const char* t : texts) {
    size_t l = strlen(t);
    data.insert(data.end(), t, t + l);
    lens.push_back(static_cast<int64_t>(l));
  }
  const int32_t n = 3, max_len = 12, bos = 256, pad = 0;
  std::vector<int32_t> ids(n * max_len), mask(n * max_len);
  int32_t max_true = mtpu_byte_encode_batch(
      data.data(), lens.data(), n, max_len, bos, pad, ids.data(),
      mask.data());
  assert(max_true == 12);  // longest row hits the max_len cap
  // row 0: bos + 'h' 'e' 'l' 'l' 'o' then pad
  assert(ids[0] == bos && ids[1] == 'h' && ids[5] == 'o');
  assert(mask[5] == 1 && mask[6] == 0);
  // row 1: bos only
  assert(ids[max_len] == bos && mask[max_len] == 1 && mask[max_len + 1] == 0);
  // row 2: truncated at max_len
  assert(mask[2 * max_len + max_len - 1] == 1);
}

static void levenshtein_cases() {
  int32_t a[] = {1, 2, 3, 4};
  int32_t b[] = {1, 3, 4, 5};
  assert(mtpu_levenshtein(a, 4, b, 4) == 2);
  assert(mtpu_levenshtein(a, 0, b, 4) == 4);
  assert(mtpu_levenshtein(a, 4, a, 4) == 0);
}

int main() {
  allocator_contention();
  tokenize_roundtrip();
  levenshtein_cases();
  std::printf("mtpu_host sanitizer harness: OK\n");
  return 0;
}
