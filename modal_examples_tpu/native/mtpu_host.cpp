// mtpu_host: native host-side runtime for the serving/data path.
//
// The reference's serving engines keep their host-side hot paths native
// (vLLM's C++ block manager + scheduler, TEI's Rust tokenization server,
// TRT-LLM's C++ runtime — SURVEY.md §2.4). This library is the TPU
// framework's equivalent: the per-step host work that sits between Python
// orchestration and the XLA device step.
//
//   1. KV page allocator: thread-safe free-list over physical page ids
//      (page 0 reserved as the trash page).
//   2. Batched byte tokenization: UTF-8 text -> padded int32 id/mask
//      matrices in one call (the request-assembly hot path: one C call per
//      admitted batch instead of a Python loop per token).
//   3. Levenshtein distance over token sequences (WER/CER eval tier).
//
// C ABI only (loaded via ctypes — no pybind11 in the image). Every entry
// point is exception-free and returns negative codes on error.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// 1. Page allocator
// ---------------------------------------------------------------------------

struct MtpuAllocator {
  std::vector<int32_t> free_list;
  std::mutex mu;
  int32_t n_pages;
};

void* mtpu_alloc_create(int32_t n_pages) {
  if (n_pages < 2) return nullptr;
  auto* a = new (std::nothrow) MtpuAllocator();
  if (!a) return nullptr;
  a->n_pages = n_pages;
  a->free_list.reserve(n_pages - 1);
  // page 0 reserved; pop() yields low ids first (matches the Python impl)
  for (int32_t p = n_pages - 1; p >= 1; --p) a->free_list.push_back(p);
  return a;
}

void mtpu_alloc_destroy(void* handle) {
  delete static_cast<MtpuAllocator*>(handle);
}

// Returns 0 on success (ids written to out), -1 if not enough pages.
int32_t mtpu_alloc_alloc(void* handle, int32_t n, int32_t* out) {
  auto* a = static_cast<MtpuAllocator*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  if (n < 0 || static_cast<size_t>(n) > a->free_list.size()) return -1;
  for (int32_t i = 0; i < n; ++i) {
    out[i] = a->free_list.back();
    a->free_list.pop_back();
  }
  return 0;
}

int32_t mtpu_alloc_free(void* handle, const int32_t* ids, int32_t n) {
  auto* a = static_cast<MtpuAllocator*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  for (int32_t i = 0; i < n; ++i) {
    if (ids[i] > 0 && ids[i] < a->n_pages) a->free_list.push_back(ids[i]);
  }
  return 0;
}

int32_t mtpu_alloc_available(void* handle) {
  auto* a = static_cast<MtpuAllocator*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  return static_cast<int32_t>(a->free_list.size());
}

// ---------------------------------------------------------------------------
// 2. Batched byte tokenization
// ---------------------------------------------------------------------------

// texts: n concatenated byte strings with lengths[], encoded into
// out_ids/out_mask [n, max_len] row-major. bos_id < 0 disables BOS.
// pad_id fills the tail. Returns the max true length (for bucket picking).
int32_t mtpu_byte_encode_batch(const uint8_t* data, const int64_t* lengths,
                               int32_t n, int32_t max_len, int32_t bos_id,
                               int32_t pad_id, int32_t* out_ids,
                               int32_t* out_mask) {
  int32_t max_true = 0;
  int64_t offset = 0;
  for (int32_t i = 0; i < n; ++i) {
    const uint8_t* s = data + offset;
    int64_t len = lengths[i];
    offset += len;
    int32_t* ids = out_ids + static_cast<int64_t>(i) * max_len;
    int32_t* mask = out_mask + static_cast<int64_t>(i) * max_len;
    int32_t j = 0;
    if (bos_id >= 0 && j < max_len) {
      ids[j] = bos_id;
      mask[j] = 1;
      ++j;
    }
    for (int64_t k = 0; k < len && j < max_len; ++k, ++j) {
      ids[j] = static_cast<int32_t>(s[k]);
      mask[j] = 1;
    }
    if (j > max_true) max_true = j;
    for (; j < max_len; ++j) {
      ids[j] = pad_id;
      mask[j] = 0;
    }
  }
  return max_true;
}

// ---------------------------------------------------------------------------
// 3. Levenshtein distance (token ids)
// ---------------------------------------------------------------------------

int32_t mtpu_levenshtein(const int32_t* a, int32_t la, const int32_t* b,
                         int32_t lb) {
  if (la == 0) return lb;
  if (lb == 0) return la;
  std::vector<int32_t> prev(lb + 1), cur(lb + 1);
  for (int32_t j = 0; j <= lb; ++j) prev[j] = j;
  for (int32_t i = 1; i <= la; ++i) {
    cur[0] = i;
    for (int32_t j = 1; j <= lb; ++j) {
      int32_t sub = prev[j - 1] + (a[i - 1] != b[j - 1] ? 1 : 0);
      cur[j] = std::min({prev[j] + 1, cur[j - 1] + 1, sub});
    }
    std::swap(prev, cur);
  }
  return prev[lb];
}

}  // extern "C"
