"""True device synchronization for backends with deferred execution.

On the tunneled axon TPU backend, ``jax.block_until_ready`` returns
immediately while execution is still queued (measured: 0.03 ms vs the full
exec+round-trip for ``np.asarray`` on the same value). Anything that needs
"this work has actually run on the chip" semantics — warmup timing, freeing
donated buffers, OOM attribution — must force with a host fetch. ``force``
fetches ONE element per leaf, so the cost is a round trip, not a transfer
of the (possibly multi-GB) array.
"""

from __future__ import annotations

import jax
import numpy as np


def force(tree) -> None:
    """Materialize every array leaf in ``tree`` by fetching one element."""
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "reshape") and getattr(leaf, "size", 0):
            np.asarray(jax.lax.slice(leaf.reshape(-1), (0,), (1,)))
