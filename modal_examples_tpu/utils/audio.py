"""Audio frontend: log-mel spectrograms (whisper-style), pure numpy/jax.

Replaces the reference's ffmpeg+librosa/torchaudio feature path for the
Whisper workloads (openai_whisper/*, speech-to-text/*). Slaney-scale mel
filterbank, 25ms/10ms framing at 16kHz, 80 bins — whisper's geometry.
"""

from __future__ import annotations

import functools

import numpy as np

SAMPLE_RATE = 16000
N_FFT = 400
HOP = 160
N_MELS = 80
CHUNK_SECONDS = 30
N_FRAMES = CHUNK_SECONDS * SAMPLE_RATE // HOP  # 3000


def _hz_to_mel(f):
    # slaney scale: linear below 1kHz, log above
    f = np.asarray(f, np.float64)
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / (200.0 / 3)
    logstep = np.log(6.4) / 27.0
    mel = f / (200.0 / 3)
    above = f >= min_log_hz
    mel = np.where(above, min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz) / logstep, mel)
    return mel


def _mel_to_hz(m):
    m = np.asarray(m, np.float64)
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / (200.0 / 3)
    logstep = np.log(6.4) / 27.0
    f = m * (200.0 / 3)
    above = m >= min_log_mel
    return np.where(above, min_log_hz * np.exp(logstep * (m - min_log_mel)), f)


@functools.lru_cache(maxsize=4)
def mel_filterbank(n_mels: int = N_MELS, n_fft: int = N_FFT, sr: int = SAMPLE_RATE):
    """[n_mels, n_fft//2 + 1] slaney-normalized triangular filters."""
    fft_freqs = np.fft.rfftfreq(n_fft, 1.0 / sr)
    mel_pts = np.linspace(_hz_to_mel(0.0), _hz_to_mel(sr / 2), n_mels + 2)
    hz_pts = _mel_to_hz(mel_pts)
    fb = np.zeros((n_mels, len(fft_freqs)))
    for i in range(n_mels):
        lo, center, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
        up = (fft_freqs - lo) / max(center - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - center, 1e-10)
        fb[i] = np.maximum(0.0, np.minimum(up, down))
        fb[i] *= 2.0 / max(hi - lo, 1e-10)  # slaney area normalization
    return fb.astype(np.float32)


def log_mel_spectrogram(
    audio: np.ndarray, n_mels: int = N_MELS, pad_to_chunk: bool = True
) -> np.ndarray:
    """waveform [T] float32 (16kHz) -> log-mel [n_frames, n_mels]."""
    audio = np.asarray(audio, np.float32)
    if pad_to_chunk:
        target = CHUNK_SECONDS * SAMPLE_RATE
        audio = np.pad(audio[:target], (0, max(0, target - len(audio))))
    elif len(audio) < N_FFT:  # guarantee at least one frame
        audio = np.pad(audio, (0, N_FFT - len(audio)))
    window = np.hanning(N_FFT + 1)[:-1].astype(np.float32)
    n_frames = 1 + (len(audio) - N_FFT) // HOP
    frames = np.lib.stride_tricks.as_strided(
        audio,
        shape=(n_frames, N_FFT),
        strides=(audio.strides[0] * HOP, audio.strides[0]),
    )
    spec = np.abs(np.fft.rfft(frames * window, axis=-1)) ** 2  # [T, F]
    mel = spec @ mel_filterbank(n_mels).T  # [T, n_mels]
    log_spec = np.log10(np.maximum(mel, 1e-10))
    log_spec = np.maximum(log_spec, log_spec.max() - 8.0)
    return ((log_spec + 4.0) / 4.0).astype(np.float32)


def synth_tone_audio(freqs: list[float], seconds: float = 1.0) -> np.ndarray:
    """Deterministic synthetic audio (test/dev corpus in a zero-egress env)."""
    t = np.arange(int(seconds * SAMPLE_RATE)) / SAMPLE_RATE
    wave = sum(np.sin(2 * np.pi * f * t) for f in freqs) / max(len(freqs), 1)
    return wave.astype(np.float32)
