"""Literate-example tooling: discovery + markdown rendering.

Reference parity (SURVEY.md §4): examples ARE the docs — `# `-prefixed
comment blocks render to markdown with code in fences
(internal/utils.py:46-84 render_example_md); discovery walks the numbered
example dirs (internal/utils.py:153-161).
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path

_SKIP_DIRS = {"internal", "misc", "__pycache__"}


@dataclasses.dataclass
class Example:
    path: Path
    module_name: str
    category: str  # e.g. "01_getting_started"
    # frontmatter knobs (the reference's jupytext frontmatter — cmd/env/
    # timeout per example, internal/utils.py:115-140): a leading block of
    #   # ---
    #   # env: {"MTPU_TRAIN_STEPS": "300"}
    #   # timeout: 800
    #   # ---
    # sets per-example cheap-mode env defaults and the runner's bound
    env: dict = dataclasses.field(default_factory=dict)
    timeout: float | None = None

    @property
    def repo_relative(self) -> str:
        return str(self.path)


def _parse_frontmatter(py: Path) -> tuple[dict, float | None]:
    """Read the optional leading `# ---` frontmatter block."""
    import json

    env: dict = {}
    timeout = None
    try:
        lines = py.read_text().splitlines()[:12]
    except OSError:
        return env, timeout
    if not lines or lines[0].strip() != "# ---":
        return env, timeout
    for line in lines[1:]:
        stripped = line.strip()
        if stripped == "# ---":
            break
        if stripped.startswith("# env:"):
            try:
                parsed = json.loads(stripped[len("# env:"):].strip())
            except json.JSONDecodeError:
                parsed = None
            if isinstance(parsed, dict):
                env = parsed
        elif stripped.startswith("# timeout:"):
            try:
                timeout = float(stripped[len("# timeout:"):].strip())
            except ValueError:
                pass
    return env, timeout


def repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def get_examples(root: Path | None = None) -> list[Example]:
    """Walk the numbered example dirs, skipping internal/ and misc/."""
    root = root or (repo_root() / "examples")
    out: list[Example] = []
    if not root.exists():
        return out
    for cat_dir in sorted(root.iterdir()):
        if not cat_dir.is_dir() or cat_dir.name in _SKIP_DIRS:
            continue
        for py in sorted(cat_dir.rglob("*.py")):
            if py.name.startswith("_") or "__pycache__" in py.parts:
                continue
            env, timeout = _parse_frontmatter(py)
            out.append(
                Example(
                    path=py.relative_to(root.parent),
                    module_name=py.stem,
                    category=cat_dir.name,
                    env=env,
                    timeout=timeout,
                )
            )
    return out


def render_example_md(source: str) -> str:
    """Render a literate example: `# ` comment blocks become prose, code
    becomes fenced blocks. The `# # Title` convention maps to headings.
    A leading frontmatter block (`# ---` ... `# ---`) is metadata for the
    example runner, not prose — stripped before rendering (the reference's
    renderer does the same with its jupytext frontmatter)."""
    lines = source.splitlines()
    if lines and lines[0].strip() == "# ---":
        for i in range(1, min(len(lines), 12)):
            if lines[i].strip() == "# ---":
                lines = lines[i + 1:]
                break
    out: list[str] = []
    code_buf: list[str] = []

    def flush_code():
        while code_buf and not code_buf[0].strip():
            code_buf.pop(0)
        while code_buf and not code_buf[-1].strip():
            code_buf.pop()
        if code_buf:
            out.append("```python")
            out.extend(code_buf)
            out.append("```")
            code_buf.clear()

    for line in lines:
        m = re.match(r"^# ?(.*)$", line)
        if m and not line.startswith("#!"):
            flush_code()
            out.append(m.group(1))
        else:
            code_buf.append(line)
    flush_code()
    return "\n".join(out).strip() + "\n"
