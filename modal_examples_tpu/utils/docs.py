"""Literate-example tooling: discovery + markdown rendering.

Reference parity (SURVEY.md §4): examples ARE the docs — `# `-prefixed
comment blocks render to markdown with code in fences
(internal/utils.py:46-84 render_example_md); discovery walks the numbered
example dirs (internal/utils.py:153-161).
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path

_SKIP_DIRS = {"internal", "misc", "__pycache__"}


@dataclasses.dataclass
class Example:
    path: Path
    module_name: str
    category: str  # e.g. "01_getting_started"

    @property
    def repo_relative(self) -> str:
        return str(self.path)


def repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def get_examples(root: Path | None = None) -> list[Example]:
    """Walk the numbered example dirs, skipping internal/ and misc/."""
    root = root or (repo_root() / "examples")
    out: list[Example] = []
    if not root.exists():
        return out
    for cat_dir in sorted(root.iterdir()):
        if not cat_dir.is_dir() or cat_dir.name in _SKIP_DIRS:
            continue
        for py in sorted(cat_dir.rglob("*.py")):
            if py.name.startswith("_") or "__pycache__" in py.parts:
                continue
            out.append(
                Example(
                    path=py.relative_to(root.parent),
                    module_name=py.stem,
                    category=cat_dir.name,
                )
            )
    return out


def render_example_md(source: str) -> str:
    """Render a literate example: `# ` comment blocks become prose, code
    becomes fenced blocks. The `# # Title` convention maps to headings."""
    lines = source.splitlines()
    out: list[str] = []
    code_buf: list[str] = []

    def flush_code():
        while code_buf and not code_buf[0].strip():
            code_buf.pop(0)
        while code_buf and not code_buf[-1].strip():
            code_buf.pop()
        if code_buf:
            out.append("```python")
            out.extend(code_buf)
            out.append("```")
            code_buf.clear()

    for line in lines:
        m = re.match(r"^# ?(.*)$", line)
        if m and not line.startswith("#!"):
            flush_code()
            out.append(m.group(1))
        else:
            code_buf.append(line)
    flush_code()
    return "\n".join(out).strip() + "\n"
