"""Bench regression detector: compare two ``BENCH_r*.json`` files
section-by-section and fail loudly past a threshold.

Every round's ``bench.py`` run leaves a structured JSON (headline tok/s
plus ``token_latency`` / ``scheduling`` / ``kv_cache`` / ``disagg`` /
``spec`` sections — docs/observability.md). This module diffs two of them
so a revalidation round lands with an automatic round-over-round
comparison instead of eyeballing: ``tpurun benchdiff OLD NEW`` (or
``benchmarks/bench_diff.py``) prints a per-metric table and exits nonzero
when any tracked metric regressed beyond the threshold.

Two comparison kinds:

- ``ratio`` metrics (throughputs, latencies) regress when the RELATIVE
  change in the bad direction exceeds the threshold;
- ``abs`` metrics (rates already in [0, 1], e.g. ``shed_rate``) regress on
  an ABSOLUTE change — a shed rate going 0.00 -> 0.15 is a regression no
  relative math can see.

jax-free by construction (``tpurun`` must not attach a chip to diff two
json files).
"""

from __future__ import annotations

import json
from pathlib import Path

DEFAULT_THRESHOLD = 0.10

#: tracked metrics: (dotted path into the bench json, lower_is_better,
#: comparison kind). Paths missing from EITHER file are skipped — configs
#: gain sections over rounds and a diff must not punish the older file.
METRICS: list[tuple[str, bool, str]] = [
    ("value", False, "ratio"),                       # headline tok/s
    ("token_latency.ttft.p50", True, "ratio"),
    ("token_latency.ttft.p95", True, "ratio"),
    ("token_latency.tpot.p50", True, "ratio"),
    ("token_latency.tpot.p95", True, "ratio"),
    ("scheduling.shed_rate", True, "abs"),
    ("disagg.migration_latency.p50", True, "ratio"),
    ("disagg.migration_latency.p95", True, "ratio"),
    ("spec.acceptance_rate", False, "abs"),
    # fused adaptive speculation (docs/speculative.md#series): harvested
    # tokens per fused round on the adaptive arm — the amortization
    # speculation buys; a drop means the controller stopped finding
    # profitable depth (or the fused round silently stopped accepting)
    ("spec.tokens_per_dispatch", False, "ratio"),
    # the "spec can never cost latency" escape hatch: spec-off TPOT p95
    # over adaptive TPOT p95 on the mixed-acceptance A/B — falling below
    # ~1 means adaptivity started taxing the hostile half of the traffic
    ("spec.adaptive_vs_off_tpot_p95", False, "ratio"),
    ("kv_cache.bytes_per_slot", True, "ratio"),
    # stall-free admission (docs/scheduling.md): the budgeted arm's
    # interactive-stream tail latency under long-prompt interference
    ("interference.budgeted.tpot_p95", True, "ratio"),
    # closed fleet loop (docs/fleet.md): the autoscaled arm's goodput and
    # client-observed p99 TPOT at the pinned fleet's saturation knee — a
    # regression here means the autoscaler stopped absorbing the load the
    # single replica cannot serve
    ("fleet.goodput", False, "ratio"),
    ("fleet.p99_tpot_at_knee", True, "ratio"),
    # fleet-wide shared prefix store (docs/prefix_store.md): a COLD
    # replica's TTFT tail over a shared-prefix corpus another replica
    # already spilled — a regression means cross-replica promotion
    # stopped paying and cold replicas recompute prefills again
    ("fleet.shared_prefix_ttft_p95", True, "ratio"),
    # in-flight failover (docs/failover.md): the client-observed takeover
    # tail — how long a stream stalls when its replica dies before a
    # healthy peer resumes it token-identically
    ("failover.takeover_latency.p95", True, "ratio"),
    # gray-failure recovery (docs/health.md): the end-to-end tail from a
    # SILENT wedge (no crash, no error) to every affected stream resumed
    # on a healthy peer — detection by progress watermarks plus the
    # failover takeover; a regression means hangs live longer
    ("recovery.time_to_mitigate.p95", True, "ratio"),
    # hot-path overhead (docs/observability.md#hot-path-profiling): the
    # host share of serving time and the scheduler-tick tail from the
    # profiler's `overhead` section. host_fraction is a 0..1 rate (abs
    # comparison, like shed_rate); a regression in either means the engine
    # got chattier per token — the exact lever ROADMAP #3's multi-step
    # decode loop exists to shrink, so it must fail the gate loudly.
    ("overhead.host_fraction", True, "abs"),
    ("overhead.tick_p95", True, "ratio"),
    # macro-step decode (docs/multistep.md): accepted tokens per decode
    # dispatch on the N-step arm — the amortization the multistep runtime
    # buys; a drop means dispatches got chattier again (early exits firing
    # too soon, or the knob silently off)
    ("multistep.tokens_per_dispatch", False, "ratio"),
    # roofline utilization (docs/observability.md#roofline-and-usage-
    # accounting): achieved-vs-peak fractions are 0..1 rates (abs, like
    # shed_rate); per-chip tok/s is the TP-normalized headline — a drop
    # means the mesh stopped paying for itself
    ("utilization.mfu", False, "abs"),
    ("utilization.mbu", False, "abs"),
    ("utilization.tokens_per_second_per_chip", False, "ratio"),
]

#: identity keys that make two bench jsons comparable AT ALL: a CPU run
#: diffed against a TPU run (or two different chips) produces nonsense
#: verdicts for every hardware-relative metric, so the diff refuses
#: instead of printing a table that looks authoritative.
IDENTITY_KEYS = ("backend", "chip_note")


def identity_mismatches(old: dict, new: dict) -> list[str]:
    """Human-readable identity disagreements between two bench jsons.
    Keys absent from either side are not mismatches (older files predate
    ``chip_note``); only a present-and-different value disqualifies."""
    out = []
    for key in IDENTITY_KEYS:
        ov, nv = old.get(key), new.get(key)
        if ov is not None and nv is not None and ov != nv:
            out.append(f"{key}: {ov!r} != {nv!r}")
    return out


def load_bench(path: str | Path) -> dict:
    """Read one bench json — either the raw line ``bench.py`` prints or
    the driver's ``BENCH_r*.json`` wrapper (whose ``parsed`` key holds
    the same object)."""
    doc = json.loads(Path(path).read_text())
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a bench json object")
    return doc


def _get(doc: dict, dotted: str):
    cur = doc
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur if isinstance(cur, (int, float)) else None


def compare(
    old: dict, new: dict, threshold: float = DEFAULT_THRESHOLD
) -> list[dict]:
    """Rows for every tracked metric present in BOTH files, plus one row
    per shared ``all_configs`` entry. Each row: ``{metric, old, new,
    delta, lower_is_better, regressed}`` — ``delta`` is relative for
    ratio metrics, absolute for rate metrics."""
    rows: list[dict] = []

    def add(metric: str, ov, nv, lower: bool, kind: str) -> None:
        if ov is None or nv is None:
            return
        if kind == "ratio" and ov == 0:
            # a zero baseline makes relative math meaningless: ANY
            # appearance in the bad direction regresses (0 -> 50ms
            # migration p95 must not pass a 10% relative gate), rendered
            # as an absolute delta
            delta = nv - ov
            kind = "abs"
            worse = delta > 0 if lower else delta < 0
            regressed = bool(worse and abs(delta) > 1e-12)
        else:
            delta = nv - ov if kind == "abs" else (nv - ov) / abs(ov)
            worse = delta > 0 if lower else delta < 0
            regressed = bool(worse and abs(delta) > threshold)
        rows.append({
            "metric": metric,
            "old": ov,
            "new": nv,
            "delta": delta,
            "kind": kind,
            "lower_is_better": lower,
            "regressed": regressed,
        })

    for dotted, lower, kind in METRICS:
        add(dotted, _get(old, dotted), _get(new, dotted), lower, kind)
    old_cfgs = old.get("all_configs") or {}
    new_cfgs = new.get("all_configs") or {}
    for cfg in sorted(set(old_cfgs) & set(new_cfgs)):
        ov, nv = old_cfgs[cfg], new_cfgs[cfg]
        if isinstance(ov, (int, float)) and isinstance(nv, (int, float)):
            add(f"all_configs.{cfg}", ov, nv, False, "ratio")
    return rows


def render(rows: list[dict]) -> str:
    lines = [
        f"{'METRIC':<34} {'OLD':>12} {'NEW':>12} {'DELTA':>9}  VERDICT"
    ]
    for r in rows:
        delta = (
            f"{r['delta'] * 100:+8.1f}%"
            if r["kind"] == "ratio"
            else f"{r['delta']:+9.4f}"
        )
        verdict = "REGRESSED" if r["regressed"] else (
            "improved"
            if (r["delta"] < 0) == r["lower_is_better"] and r["delta"] != 0
            else "ok"
        )
        lines.append(
            f"{r['metric']:<34} {r['old']:>12.4f} {r['new']:>12.4f} "
            f"{delta:>9}  {verdict}"
        )
    return "\n".join(lines)


def run_diff(argv: list[str]) -> int:
    """CLI body shared by ``tpurun benchdiff`` and
    ``benchmarks/bench_diff.py``: 0 = no regression, 1 = regressed, 2 =
    usage/read error."""
    usage = (
        "usage: tpurun benchdiff OLD.json NEW.json "
        f"[--threshold PCT (default {DEFAULT_THRESHOLD * 100:.0f})] "
        "[--allow-backend-mismatch]"
    )
    threshold = DEFAULT_THRESHOLD
    args = list(argv)
    allow_mismatch = "--allow-backend-mismatch" in args
    if allow_mismatch:
        args.remove("--allow-backend-mismatch")
    if "--threshold" in args:
        i = args.index("--threshold")
        if i + 1 >= len(args):
            print(usage)
            return 2
        try:
            threshold = float(args[i + 1]) / 100.0
        except ValueError:
            print(usage)
            return 2
        args = args[:i] + args[i + 2:]
    if len(args) != 2:
        print(usage)
        return 2
    try:
        old, new = load_bench(args[0]), load_bench(args[1])
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"benchdiff: {e}")
        return 2
    mismatches = identity_mismatches(old, new)
    if mismatches:
        for m in mismatches:
            print(f"benchdiff: HARDWARE MISMATCH — {m}")
        if not allow_mismatch:
            print(
                "benchdiff: refusing to compare runs from different "
                "hardware (every hardware-relative verdict would be "
                "nonsense); pass --allow-backend-mismatch to override"
            )
            return 2
        print(
            "benchdiff: --allow-backend-mismatch set — verdicts below "
            "compare DIFFERENT hardware and are not regressions"
        )
    rows = compare(old, new, threshold)
    if not rows:
        print("benchdiff: no comparable metrics between the two files")
        return 2
    print(render(rows))
    regressed = [r for r in rows if r["regressed"]]
    if regressed:
        print(
            f"\n{len(regressed)} metric(s) regressed beyond "
            f"{threshold * 100:.0f}%: "
            + ", ".join(r["metric"] for r in regressed)
        )
        return 1
    print(f"\nno regressions beyond {threshold * 100:.0f}%")
    return 0
