"""Tokenizer access: HF tokenizers when a model dir is available, byte-level
fallback otherwise.

Per SURVEY.md §2.4, HF's Rust tokenizers are kept as a host-CPU dependency
(no CUDA involved, porting out of scope). The byte fallback keeps every test
and bench runnable with random weights in a zero-egress environment (the
analog of the reference's dummy-weights dev mode, very_large_models.py:2-3).
"""

from __future__ import annotations


class ByteTokenizer:
    """Reversible byte-level tokenizer: vocab = 256 bytes + BOS/EOS/PAD.

    Reversible for real: decode/encode use ``surrogateescape``, so a byte
    sequence that isn't valid UTF-8 round-trips exactly instead of turning
    into U+FFFD replacement chars — which re-encode to THREE bytes each and
    made decode->re-encode length-unstable (a 5-token generation could
    re-encode to 7 "tokens", tripping every max_tokens accounting built on
    the round trip). Lone surrogates are ordinary str content to Python
    (json.dumps escapes them losslessly); the engine's stream hold-back
    treats a trailing surrogate like a trailing partial codepoint.
    """

    def __init__(self):
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258
        self.vocab_size = 259

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8", errors="surrogateescape"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        data = bytes(i for i in ids if i < 256)
        return data.decode("utf-8", errors="surrogateescape")

    def encode_batch(self, texts: list[str], max_len: int, add_bos: bool = True):
        """Batched encode -> padded (ids, mask) int32 matrices in one native
        call (native/mtpu_host.cpp; numpy fallback inside)."""
        from ..native import byte_encode_batch

        ids, mask, _ = byte_encode_batch(
            texts, max_len,
            bos_id=self.bos_id if add_bos else -1,
            pad_id=self.pad_id,
        )
        return ids, mask

    def apply_chat_template(self, messages: list[dict], **_) -> str:
        return (
            "\n".join(f"{m['role']}: {m['content']}" for m in messages)
            + "\nassistant:"
        )


class HFTokenizer:
    """Thin adapter over transformers.AutoTokenizer (local files only)."""

    def __init__(self, model_dir: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(model_dir, local_files_only=True)
        self.bos_id = self._tok.bos_token_id
        self.eos_id = self._tok.eos_token_id
        self.pad_id = self._tok.pad_token_id or self.eos_id
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict], **kw) -> str:
        return self._tok.apply_chat_template(
            messages, tokenize=False, add_generation_prompt=True, **kw
        )


def load_tokenizer(model_dir: str | None):
    if model_dir is None:
        return ByteTokenizer()
    try:
        return HFTokenizer(model_dir)
    except Exception:
        return ByteTokenizer()
