"""Wedge-proof kernel bring-up: run first-on-chip Mosaic compiles in a
killable subprocess with a hard timeout.

Why this exists: twice (rounds 1 and 4) an in-process first compile of a
new Pallas kernel hung inside the remote compile/claim path and wedged the
single tunneled TPU's device claim for the rest of the session — the
process could not be interrupted from Python, and the claim followed the
process. The standing rule this module enforces: **the first Mosaic
compile of any new or modified kernel never runs in a process you care
about.** A probe child claims the chip, compiles the kernel on tiny legal
shapes, checks numerics against the XLA reference, writes a JSON result
file, and exits — releasing the claim. On a hang the parent SIGKILLs the
whole process group before the timeout can become a session wedge.

Replaces the ad-hoc ``timeout ...`` wrappers in revalidate_chip.sh with an
importable API (`run_probe`, `run_probes`) + CLI:

    python -m modal_examples_tpu.utils.kernel_probe ragged_decode
    python -m modal_examples_tpu.utils.kernel_probe --all

Probe targets are ``"module:function"`` strings; the per-kernel registry
lives in ``modal_examples_tpu.ops.probes.KERNEL_PROBES``. Reference analog:
the reference's serving stacks AOT-build engines in a separate build step
(TRT-LLM engine build, SURVEY §2.4) for the same reason — compile is the
dangerous phase and must be isolable.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import tempfile
import time


@dataclasses.dataclass
class ProbeResult:
    target: str
    status: str  # "ok" | "fail" | "timeout" | "crash"
    elapsed_s: float
    payload: dict | None = None  # probe fn's returned dict (status ok/fail)
    error: str | None = None
    log_tail: str = ""

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def resolve_target(target: str):
    """``"name"`` (registry key) or ``"pkg.mod:fn"`` -> callable."""
    if ":" not in target:
        from modal_examples_tpu.ops.probes import KERNEL_PROBES

        if target not in KERNEL_PROBES:
            raise KeyError(
                f"unknown probe {target!r}; registered: "
                f"{sorted(KERNEL_PROBES)}"
            )
        target = KERNEL_PROBES[target]
    mod_name, fn_name = target.split(":")
    import importlib

    mod = importlib.import_module(mod_name)
    return getattr(mod, fn_name)


def run_probe(
    target: str,
    *,
    timeout_s: float = 300.0,
    env: dict | None = None,
) -> ProbeResult:
    """Run one probe target in a fresh subprocess; SIGKILL its whole
    process group on timeout (SIGTERM is not enough — the round-4 hang sat
    in native code and shrugged it off)."""
    t0 = time.time()
    with tempfile.TemporaryDirectory(prefix="kprobe_") as td:
        result_file = os.path.join(td, "result.json")
        log_file = os.path.join(td, "probe.log")
        child_env = dict(os.environ)
        # the package is run from a source tree, not an install: the child
        # must find it regardless of the parent's cwd
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        child_env["PYTHONPATH"] = (
            repo_root + os.pathsep + child_env["PYTHONPATH"]
            if child_env.get("PYTHONPATH")
            else repo_root
        )
        if env:
            child_env.update(env)
        cmd = [
            sys.executable, "-m", "modal_examples_tpu.utils.kernel_probe",
            "--child", target, "--result-file", result_file,
        ]
        with open(log_file, "wb") as lf:
            proc = subprocess.Popen(
                cmd, stdout=lf, stderr=subprocess.STDOUT,
                env=child_env, start_new_session=True,
            )
            try:
                code = proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                proc.wait()
                return ProbeResult(
                    target, "timeout", round(time.time() - t0, 1),
                    error=f"no result after {timeout_s}s; process group killed",
                    log_tail=_tail(log_file),
                )
        elapsed = round(time.time() - t0, 1)
        if os.path.exists(result_file):
            with open(result_file) as f:
                rec = json.load(f)
            status = "ok" if rec.get("ok") else "fail"
            return ProbeResult(
                target, status, elapsed,
                payload=rec.get("payload"), error=rec.get("error"),
                log_tail="" if status == "ok" else _tail(log_file),
            )
        return ProbeResult(
            target, "crash", elapsed,
            error=f"exit code {code}, no result file",
            log_tail=_tail(log_file),
        )


def run_probes(
    targets: list[str] | None = None,
    *,
    timeout_s: float = 300.0,
    stop_on_timeout: bool = True,
) -> dict[str, ProbeResult]:
    """Run probes in registry order. A *timeout* stops the sequence by
    default — it means the chip claim may now be wedged and every further
    probe would hang the same way; the caller should check chip health
    before anything else touches the device. A mere numeric failure
    continues."""
    if targets is None:
        from modal_examples_tpu.ops.probes import KERNEL_PROBES

        targets = list(KERNEL_PROBES)
    results: dict[str, ProbeResult] = {}
    for t in targets:
        r = run_probe(t, timeout_s=timeout_s)
        results[t] = r
        print(f"[probe {t}] {r.status} {r.elapsed_s}s "
              f"{r.payload or r.error or ''}", flush=True)
        if r.status == "timeout" and stop_on_timeout:
            break
    return results


def _tail(path: str, n: int = 2000) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - n))
            return f.read().decode(errors="replace")
    except OSError:
        return ""


def _child_main(target: str, result_file: str) -> int:
    rec: dict = {"ok": False}
    try:
        if (
            os.environ.get("JAX_PLATFORMS", "").lower() == "cpu"
            or os.environ.get("BENCH_CPU")
        ):
            # the env-var platform route is unreliable once the axon TPU
            # plugin is importable (it still dials the chip — observed
            # blocking 5 min on a wedged claim); pin explicitly. BENCH_CPU
            # is the benchmarks' off-chip smoke switch — honor it here so
            # a CPU bench run never dials the chip from probe children.
            import jax

            jax.config.update("jax_platforms", "cpu")
        fn = resolve_target(target)
        payload = fn() or {}
        rec = {"ok": True, "payload": payload}
    except Exception as e:  # noqa: BLE001 — the whole point is to report it
        import traceback

        traceback.print_exc()
        rec = {"ok": False, "error": f"{type(e).__name__}: {e}"}
    tmp = result_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, result_file)
    return 0 if rec["ok"] else 1


# --- harness self-test targets (used by tests/test_kernel_probe.py) -----
def _selftest_ok() -> dict:
    return {"answer": 42}


def _selftest_fail() -> dict:
    raise AssertionError("deliberate numeric failure")


def _selftest_crash() -> dict:
    os._exit(3)  # simulates a segfaulting compile


def _selftest_hang() -> dict:
    while True:  # simulates the round-1/round-4 claim wedge
        time.sleep(60)


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("target", nargs="?", help="probe name or module:function")
    ap.add_argument("--all", action="store_true", help="run full registry")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--child", metavar="TARGET",
                    help="(internal) run TARGET in-process")
    ap.add_argument("--result-file", help="(internal) child result path")
    args = ap.parse_args(argv)

    if args.child:
        return _child_main(args.child, args.result_file)
    if args.all:
        results = run_probes(timeout_s=args.timeout)
        summary = {k: v.status for k, v in results.items()}
        n_ok = sum(1 for v in results.values() if v.ok)
        print(json.dumps({"probes": summary, "ok": n_ok,
                          "total": len(results)}), flush=True)
        return 0 if n_ok == len(results) else 1
    if not args.target:
        ap.error("give a probe target or --all")
    r = run_probe(args.target, timeout_s=args.timeout)
    print(json.dumps(r.to_json()), flush=True)
    return 0 if r.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
