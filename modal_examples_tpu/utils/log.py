"""Structured logging for framework internals.

Framework code under ``core/`` and ``serving/`` must not ``print()``
(enforced by ``tests/test_static.py``): diagnostics go through this logger
so they carry a level, a component name, and machine-readable fields —
and can be silenced or redirected without grepping stdout.

- ``MTPU_LOG_LEVEL`` sets the threshold (default ``INFO``).
- ``MTPU_LOG_JSON=1`` switches to one-JSON-object-per-line output
  (the greppable shape ``utils/tracking.RunLogger`` uses for run metrics).

Structured fields ride on the stdlib ``extra`` mechanism::

    log = get_logger("executor")
    event(log, logging.WARNING, "volume mount failed", path=p, err=str(e))
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_ROOT_NAME = "mtpu"
_configured = False


class _Formatter(logging.Formatter):
    def __init__(self, json_mode: bool):
        super().__init__()
        self.json_mode = json_mode

    def format(self, record: logging.LogRecord) -> str:
        fields = getattr(record, "fields", None) or {}
        if self.json_mode:
            payload = {
                "ts": round(record.created, 3),
                "level": record.levelname,
                "logger": record.name,
                "msg": record.getMessage(),
                **fields,
            }
            if record.exc_info:
                payload["exc"] = self.formatException(record.exc_info)
            return json.dumps(payload, default=str)
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        extras = "".join(f" {k}={v}" for k, v in fields.items())
        out = (
            f"[{ts} {record.levelname.lower()} {record.name}] "
            f"{record.getMessage()}{extras}"
        )
        if record.exc_info:
            out += "\n" + self.formatException(record.exc_info)
        return out


def _configure() -> None:
    global _configured
    if _configured:
        return
    _configured = True
    root = logging.getLogger(_ROOT_NAME)
    if root.handlers:
        return  # the embedding app already configured it; respect that
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        _Formatter(os.environ.get("MTPU_LOG_JSON", "") not in ("", "0"))
    )
    root.addHandler(handler)
    level = os.environ.get("MTPU_LOG_LEVEL", "INFO").upper()
    root.setLevel(getattr(logging, level, logging.INFO))
    root.propagate = False


def get_logger(name: str = "") -> logging.Logger:
    """Component logger under the ``mtpu`` hierarchy (``get_logger("executor")``
    -> ``mtpu.executor``)."""
    _configure()
    return logging.getLogger(f"{_ROOT_NAME}.{name}" if name else _ROOT_NAME)


def event(logger: logging.Logger, level: int, msg: str, **fields) -> None:
    """Log ``msg`` with structured ``fields`` (rendered as ``k=v`` pairs, or
    merged into the JSON object under ``MTPU_LOG_JSON=1``)."""
    logger.log(level, msg, extra={"fields": fields})
