"""Metrics: counters/gauges/histograms with Prometheus text exposition and a
push-style aggregator.

Reference pattern (SURVEY.md §5.5): scrape-based Prometheus doesn't fit
ephemeral containers, so the reference runs a Pushgateway *as an app*
(10_integrations/pushgateway.py:8-12,62-69) and functions push counters to
it. Here the registry + exposition format are implemented directly (no Go
binary needed), and the aggregator pattern is a Dict-backed push sink any
app can serve via a web endpoint.

Exposition follows the Prometheus text format rules: label values are
escaped (``\\``, ``"``, newline), each metric name carries exactly one
``# HELP``/``# TYPE`` header, and histograms emit cumulative ``_bucket``
series ending in ``le="+Inf"`` plus ``_sum``/``_count``.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import defaultdict

#: default latency buckets (seconds) — sub-ms dispatch up to multi-minute
#: cold boots, roughly log-spaced like prometheus client defaults
DEFAULT_TIME_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


def escape_label_value(value) -> str:
    """Escape a label value per the Prometheus text exposition format."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_le(le: float) -> str:
    if math.isinf(le):
        return "+Inf"
    return f"{le:.10g}"


def _label_str(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in labels
    )
    return "{" + inner + "}"


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._gauges: dict[tuple, float] = {}
        # key -> {"buckets": (le,...), "counts": [per-bucket + overflow],
        #         "sum": float, "count": int}
        self._histograms: dict[tuple, dict] = {}
        self._help: dict[str, str] = {}
        self._types: dict[str, str] = {}

    def _key(self, name: str, labels: dict | None):
        return (name, tuple(sorted((labels or {}).items())))

    def counter_inc(self, name: str, value: float = 1.0, labels: dict | None = None,
                    help: str = ""):
        with self._lock:
            self._counters[self._key(name, labels)] += value
            self._types[name] = "counter"
            if help:
                self._help[name] = help

    def gauge_set(self, name: str, value: float, labels: dict | None = None,
                  help: str = ""):
        with self._lock:
            self._gauges[self._key(name, labels)] = value
            self._types[name] = "gauge"
            if help:
                self._help[name] = help

    def histogram_observe(self, name: str, value: float,
                          labels: dict | None = None,
                          buckets: tuple | None = None, help: str = ""):
        """Observe one value into a histogram series.

        ``buckets`` are upper bounds (``le``); the ``+Inf`` bucket is
        implicit. The bucket layout is fixed by the first observation of a
        series — later ``buckets=`` arguments are ignored for it.
        """
        key = self._key(name, labels)
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                bs = tuple(sorted(buckets or DEFAULT_TIME_BUCKETS))
                h = {"buckets": bs, "counts": [0] * (len(bs) + 1),
                     "sum": 0.0, "count": 0}
                self._histograms[key] = h
            for i, le in enumerate(h["buckets"]):
                if value <= le:
                    h["counts"][i] += 1
                    break
            else:
                h["counts"][-1] += 1  # +Inf overflow
            h["sum"] += value
            h["count"] += 1
            self._types[name] = "histogram"
            if help:
                self._help[name] = help

    def histogram_quantiles(
        self, name: str, labels: dict | None = None,
        quantiles: tuple = (0.5, 0.95, 0.99),
        aggregate: dict | None = None,
    ) -> dict | None:
        """Estimate quantiles from a histogram series (linear interpolation
        within the winning bucket, like PromQL ``histogram_quantile``).
        Returns ``{"p50": ..., ..., "count": n, "sum": s}`` or None when the
        series was never observed (empty histograms never fabricate a 0.0).

        ``aggregate`` sums every series of ``name`` whose label dict contains
        the given items (``{}`` = all of them) before computing — the PromQL
        ``sum by ()`` analog used by SLO evaluation. Series whose bucket
        layout differs from the first matching one are skipped.

        Edge cases (rather than extrapolating nonsense): a quantile landing
        in the ``+Inf`` overflow bucket clamps to the largest finite bound;
        interpolation fractions are clamped to [0, 1] so zero-count buckets
        skipped along the way can never push a value outside its bucket.
        """
        with self._lock:
            if aggregate is not None:
                want = aggregate.items()
                h = None
                for (n, lbls), series in self._histograms.items():
                    if n != name or not (set(want) <= set(lbls)):
                        continue
                    if h is None:
                        h = {
                            "buckets": series["buckets"],
                            "counts": list(series["counts"]),
                            "sum": series["sum"],
                            "count": series["count"],
                        }
                    elif series["buckets"] == h["buckets"]:
                        h["counts"] = [
                            a + b for a, b in zip(h["counts"], series["counts"])
                        ]
                        h["sum"] += series["sum"]
                        h["count"] += series["count"]
            else:
                h = self._histograms.get(self._key(name, labels))
            if h is None or h["count"] == 0:
                return None
            bounds = h["buckets"]
            counts = list(h["counts"])
            total = h["count"]
            out = {"count": total, "sum": h["sum"]}
            for q in quantiles:
                rank = q * total
                cum = 0.0
                value = float(bounds[-1]) if bounds else 0.0
                for i, c in enumerate(counts):
                    prev_cum = cum
                    cum += c
                    if cum >= rank and c > 0:
                        if i >= len(bounds):  # +Inf bucket: clamp to last bound
                            value = float(bounds[-1])
                        else:
                            hi = bounds[i]
                            lo = bounds[i - 1] if i > 0 else 0.0
                            frac = min(1.0, max(0.0, (rank - prev_cum) / c))
                            value = lo + (hi - lo) * frac
                        break
                out[f"p{int(q * 100)}"] = value
            return out

    def total(self, name: str, match: dict | None = None) -> float:
        """Sum a series across label sets (counters/gauges: values sum;
        histograms: observation counts sum). ``match`` filters to label sets
        containing the given items. The PromQL ``sum(name)`` analog for SLO
        ratio targets."""
        want = (match or {}).items()
        out = 0.0
        with self._lock:
            for store in (self._counters, self._gauges):
                for (n, lbls), v in store.items():
                    if n == name and set(want) <= set(lbls):
                        out += v
            for (n, lbls), h in self._histograms.items():
                if n == name and set(want) <= set(lbls):
                    out += h["count"]
        return out

    def peak(self, name: str, match: dict | None = None) -> float:
        """Max of a counter/gauge series across label sets (0.0 when none
        match). For ratio gauges like occupancy fractions, where summing
        per-job series would produce a nonsense >1 value — show the worst."""
        want = (match or {}).items()
        out = 0.0
        with self._lock:
            for store in (self._counters, self._gauges):
                for (n, lbls), v in store.items():
                    if n == name and set(want) <= set(lbls):
                        out = max(out, v)
        return out

    def expose(self) -> str:
        """Prometheus text exposition format."""
        with self._lock:
            lines: list[str] = []
            seen_header = set()

            def header(name: str) -> None:
                if name in seen_header:
                    return
                if name in self._help:
                    lines.append(
                        f"# HELP {name} {_escape_help(self._help[name])}"
                    )
                lines.append(f"# TYPE {name} {self._types.get(name, 'untyped')}")
                seen_header.add(name)

            for store in (self._counters, self._gauges):
                for (name, labels), value in sorted(store.items()):
                    header(name)
                    lines.append(f"{name}{_label_str(labels)} {value}")
            for (name, labels), h in sorted(self._histograms.items()):
                header(name)
                cum = 0
                for le, c in zip(
                    tuple(h["buckets"]) + (math.inf,), h["counts"]
                ):
                    cum += c
                    ls = _label_str(tuple(labels) + (("le", _fmt_le(le)),))
                    lines.append(f"{name}_bucket{ls} {cum}")
                lines.append(f"{name}_sum{_label_str(labels)} {h['sum']}")
                lines.append(f"{name}_count{_label_str(labels)} {h['count']}")
            return "\n".join(lines) + "\n"

    def series(self, name: str) -> list[tuple[dict, float]]:
        """Every label set recorded for ``name`` with its value (counters/
        gauges: the value; histograms: the observation count) — lets the CLI
        enumerate e.g. shed reasons without parsing the exposition."""
        out: list[tuple[dict, float]] = []
        with self._lock:
            for store in (self._counters, self._gauges):
                for (n, lbls), v in store.items():
                    if n == name:
                        out.append((dict(lbls), v))
            for (n, lbls), h in self._histograms.items():
                if n == name:
                    out.append((dict(lbls), float(h["count"])))
        return out

    def value(self, name: str, labels: dict | None = None) -> float:
        """Current value of one series; 0.0 when never written. Counters and
        gauges return their value, histograms their observation count. Lets
        tests and the CLI read series back without parsing the exposition."""
        key = self._key(name, labels)
        with self._lock:
            if key in self._gauges:
                return self._gauges[key]
            if key in self._histograms:
                return float(self._histograms[key]["count"])
            return self._counters.get(key, 0.0)

    def all_series(self) -> list[tuple]:
        """Every series in the registry as ``(name, labels, kind, value,
        hsum)`` tuples: counters/gauges carry their value (``hsum`` 0.0),
        histograms their cumulative observation count with ``hsum`` = the
        cumulative sum — one locked pass, no exposition round trip. The
        time-series sampler's scrape surface (observability/timeseries.py)."""
        out: list[tuple] = []
        with self._lock:
            for (name, lbls), v in self._counters.items():
                out.append((name, dict(lbls), "counter", float(v), 0.0))
            for (name, lbls), v in self._gauges.items():
                out.append((name, dict(lbls), "gauge", float(v), 0.0))
            for (name, lbls), h in self._histograms.items():
                out.append(
                    (name, dict(lbls), "histogram",
                     float(h["count"]), float(h["sum"]))
                )
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": {str(k): v for k, v in self._counters.items()},
                "gauges": {str(k): v for k, v in self._gauges.items()},
                "histograms": {
                    str(k): {"sum": h["sum"], "count": h["count"]}
                    for k, h in self._histograms.items()
                },
            }


#: process-wide default registry
default_registry = Registry()


def push_to_dict(metrics_dict, job: str, registry: Registry | None = None) -> None:
    """Push this process's metrics into a shared Dict — the pushgateway
    pattern for ephemeral containers (each push overwrites the job's slot,
    tagged with a timestamp)."""
    reg = registry or default_registry
    metrics_dict[job] = {"at": time.time(), "metrics": reg.snapshot(),
                         "text": reg.expose()}


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<rest>.+)$"
)

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPE_MAP = {"n": "\n", '"': '"', "\\": "\\"}


def _unescape_label_value(v: str) -> str:
    # one left-to-right pass: sequential .replace() calls corrupt values
    # where an escaped backslash precedes 'n' ('a\\nb' must round-trip to
    # a backslash + 'n', not a newline)
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPE_MAP.get(m.group(1), "\\" + m.group(1)), v
    )


def parse_exposition(text: str) -> Registry:
    """Reconstruct a :class:`Registry` from Prometheus text exposition.

    The inverse of :meth:`Registry.expose` — counters/gauges land as values,
    histogram ``_bucket``/``_sum``/``_count`` child series are de-cumulated
    back into per-bucket counts, so ``histogram_quantiles``/``value``/
    ``total`` work on a *pushed* ``.prom`` file exactly as on the live
    registry (what ``tpurun top`` and SLO evaluation over pushed jobs need).
    Unparseable lines are skipped; untyped samples read as gauges.
    """
    reg = Registry()
    types: dict[str, str] = {}
    helps: dict[str, str] = {}
    # (name, labels_tuple) -> {"buckets": [(le, cum)], "sum": s, "count": n}
    hists: dict[tuple, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            name, _, t = line[len("# TYPE "):].partition(" ")
            types[name] = t.strip()
            continue
        if line.startswith("# HELP "):
            name, _, h = line[len("# HELP "):].partition(" ")
            helps[name] = h
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name = m.group("name")
        try:
            value = float(m.group("rest").split()[0])
        except (ValueError, IndexError):
            continue
        labels = {
            k: _unescape_label_value(v)
            for k, v in _LABEL_RE.findall(m.group("labels") or "")
        }
        base, part = name, None
        for suffix in ("_bucket", "_sum", "_count"):
            stem = name[: -len(suffix)]
            if name.endswith(suffix) and types.get(stem) == "histogram":
                base, part = stem, suffix
                break
        if part is not None:
            le = labels.pop("le", None)
            key = (base, tuple(sorted(labels.items())))
            h = hists.setdefault(key, {"buckets": [], "sum": 0.0, "count": 0})
            if part == "_bucket" and le is not None:
                bound = math.inf if le == "+Inf" else float(le)
                h["buckets"].append((bound, value))
            elif part == "_sum":
                h["sum"] = value
            elif part == "_count":
                h["count"] = int(value)
            continue
        if types.get(name) == "counter":
            reg.counter_inc(name, value, labels=labels or None,
                            help=helps.get(name, ""))
        else:
            reg.gauge_set(name, value, labels=labels or None,
                          help=helps.get(name, ""))
    for (name, lbl_t), h in hists.items():
        pairs = sorted(h["buckets"])
        finite = tuple(le for le, _ in pairs if not math.isinf(le))
        counts, prev = [], 0.0
        for _, cum in pairs:
            counts.append(int(cum - prev))
            prev = cum
        if not any(math.isinf(le) for le, _ in pairs):
            counts.append(max(0, h["count"] - int(prev)))  # missing +Inf
        with reg._lock:
            reg._histograms[(name, lbl_t)] = {
                "buckets": finite,
                "counts": counts,
                "sum": h["sum"],
                "count": h["count"] or int(prev),
            }
            reg._types[name] = "histogram"
            if name in helps:
                reg._help[name] = helps[name]
    return reg


def merge_expositions(jobs: dict[str, str]) -> str:
    """Merge per-job exposition texts into ONE valid exposition.

    Each sample gains a ``job`` label (the pushgateway convention), every
    metric name keeps exactly one ``# HELP``/``# TYPE`` header, and no
    non-format comment lines are emitted — duplicate headers and ``# job:``
    banners both violate the text format and break scrapers.
    """
    helps: dict[str, str] = {}
    types: dict[str, str] = {}
    samples: dict[str, list[str]] = {}
    order: list[str] = []

    def base_name(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            stem = name[: -len(suffix)]
            if name.endswith(suffix) and types.get(stem) in (
                "histogram", "summary"
            ):
                return stem
        return name

    for job, text in sorted(jobs.items()):
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("# HELP "):
                _, _, rest = line.partition("# HELP ")
                name, _, help_text = rest.partition(" ")
                helps.setdefault(name, help_text)
                continue
            if line.startswith("# TYPE "):
                _, _, rest = line.partition("# TYPE ")
                name, _, type_text = rest.partition(" ")
                types.setdefault(name, type_text)
                continue
            if line.startswith("#"):
                continue  # drop free-form comments: not part of the format
            m = _SAMPLE_RE.match(line)
            if m is None:
                continue
            name = m.group("name")
            labels = m.group("labels") or ""
            job_label = f'job="{escape_label_value(job)}"'
            labels = f"{labels},{job_label}" if labels else job_label
            group = base_name(name)
            if group not in samples:
                samples[group] = []
                order.append(group)
            samples[group].append(f"{name}{{{labels}}} {m.group('rest')}")

    lines: list[str] = []
    for group in order:
        if group in helps:
            lines.append(f"# HELP {group} {helps[group]}")
        if group in types:
            lines.append(f"# TYPE {group} {types[group]}")
        lines.extend(samples[group])
    return "\n".join(lines) + ("\n" if lines else "")


def aggregate_exposition(metrics_dict) -> str:
    """Merge all jobs' pushed text expositions (the gateway's /metrics).

    Series from different jobs are distinguished by an added ``job`` label;
    headers are deduplicated so the output is itself a valid exposition.
    """
    jobs = {
        job: payload["text"] for job, payload in sorted(metrics_dict.items())
    }
    return merge_expositions(jobs)
