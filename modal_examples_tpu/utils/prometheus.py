"""Metrics: counters/gauges/histograms with Prometheus text exposition and a
push-style aggregator.

Reference pattern (SURVEY.md §5.5): scrape-based Prometheus doesn't fit
ephemeral containers, so the reference runs a Pushgateway *as an app*
(10_integrations/pushgateway.py:8-12,62-69) and functions push counters to
it. Here the registry + exposition format are implemented directly (no Go
binary needed), and the aggregator pattern is a Dict-backed push sink any
app can serve via a web endpoint.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._gauges: dict[tuple, float] = {}
        self._help: dict[str, str] = {}
        self._types: dict[str, str] = {}

    def _key(self, name: str, labels: dict | None):
        return (name, tuple(sorted((labels or {}).items())))

    def counter_inc(self, name: str, value: float = 1.0, labels: dict | None = None,
                    help: str = ""):
        with self._lock:
            self._counters[self._key(name, labels)] += value
            self._types[name] = "counter"
            if help:
                self._help[name] = help

    def gauge_set(self, name: str, value: float, labels: dict | None = None,
                  help: str = ""):
        with self._lock:
            self._gauges[self._key(name, labels)] = value
            self._types[name] = "gauge"
            if help:
                self._help[name] = help

    def expose(self) -> str:
        """Prometheus text exposition format."""
        with self._lock:
            lines: list[str] = []
            seen_header = set()
            for store in (self._counters, self._gauges):
                for (name, labels), value in sorted(store.items()):
                    if name not in seen_header:
                        if name in self._help:
                            lines.append(f"# HELP {name} {self._help[name]}")
                        lines.append(f"# TYPE {name} {self._types.get(name, 'untyped')}")
                        seen_header.add(name)
                    label_s = (
                        "{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}"
                        if labels
                        else ""
                    )
                    lines.append(f"{name}{label_s} {value}")
            return "\n".join(lines) + "\n"

    def value(self, name: str, labels: dict | None = None) -> float:
        """Current value of one series (counter or gauge); 0.0 when never
        written. Lets tests and the CLI read counters back without parsing
        the text exposition."""
        key = self._key(name, labels)
        with self._lock:
            if key in self._gauges:
                return self._gauges[key]
            return self._counters.get(key, 0.0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": {str(k): v for k, v in self._counters.items()},
                "gauges": {str(k): v for k, v in self._gauges.items()},
            }


#: process-wide default registry
default_registry = Registry()


def push_to_dict(metrics_dict, job: str, registry: Registry | None = None) -> None:
    """Push this process's metrics into a shared Dict — the pushgateway
    pattern for ephemeral containers (each push overwrites the job's slot,
    tagged with a timestamp)."""
    reg = registry or default_registry
    metrics_dict[job] = {"at": time.time(), "metrics": reg.snapshot(),
                         "text": reg.expose()}


def aggregate_exposition(metrics_dict) -> str:
    """Merge all jobs' pushed text expositions (the gateway's /metrics)."""
    parts = []
    for job, payload in sorted(metrics_dict.items()):
        parts.append(f"# job: {job} (pushed at {payload['at']:.0f})")
        parts.append(payload["text"])
    return "\n".join(parts)
