"""Persistent XLA compile cache — the framework-level cold-start lever.

The reference's serving example leans on engine AOT caches and FAST_BOOT
(vllm_inference.py:79-101: cached torch.compile / CUDA graphs on volumes);
the TPU analog is XLA's persistent compilation cache. Round-2 measurement:
llama2-7b engine boot paid 41.5 s build + 62.6 s compile on every start.
With this cache warm, recompiles become disk hits.

Wired in by default at the three places compiles happen:
- ``LLMEngine.__init__`` (serving),
- the executor's containers (via ``JAX_COMPILATION_CACHE_DIR`` in the child
  env — jax reads it natively, and ``core`` stays jax-free),
- ``bench.py`` children.

Opt out with ``MTPU_COMPILE_CACHE=0``; point somewhere else (e.g. a Volume
mount, as examples/06_gpu_and_ml/tpu_snapshot.py does) with
``MTPU_COMPILE_CACHE=/path``.
"""

from __future__ import annotations

import os
from pathlib import Path

_DISABLED = ("0", "off", "none")


import functools


@functools.lru_cache(maxsize=1)
def _machine_tag() -> str:
    """Short fingerprint of the host CPU. XLA:CPU AOT entries bake in the
    compile machine's feature set; loading them on a different microarch
    logs 'could lead to execution errors such as SIGILL' per entry (seen
    when this image migrated hosts between rounds). Segmenting the default
    cache dir by CPU features keeps foreign AOT results out. Covers x86
    ('flags', 'model name') and arm ('Features', 'CPU part') cpuinfo keys."""
    import hashlib
    import platform

    parts = set()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                key = line.split(":", 1)[0].strip()
                if key in ("flags", "Features", "model name", "CPU part"):
                    parts.add(" ".join(line.split(":", 1)[1].split()))
    except OSError:
        pass
    return hashlib.md5(
        (platform.machine() + ":" + "|".join(sorted(parts))).encode()
    ).hexdigest()[:8]


def cache_dir() -> str | None:
    """The resolved cache directory, or None when disabled."""
    env = os.environ.get("MTPU_COMPILE_CACHE", "")
    if env.lower() in _DISABLED:
        return None
    if env:
        return env
    return str(
        Path.home() / ".cache" / "modal_examples_tpu"
        / f"xla-cache-{_machine_tag()}"
    )


def enable_compile_cache(path: str | None = None) -> str | None:
    """Idempotently enable the persistent XLA compile cache.

    Returns the cache dir in use, or None when disabled. Safe to call
    before or after backend init; entries are keyed by HLO + compile flags,
    so CPU and TPU runs coexist in one directory.

    A cache dir the user already configured via ``jax.config`` directly is
    respected (ADVICE r3): only an explicit ``path=`` argument or
    ``MTPU_COMPILE_CACHE`` env overrides it; the built-in default never does.
    """
    import jax

    explicit = path is not None or bool(os.environ.get("MTPU_COMPILE_CACHE"))
    path = path or cache_dir()
    if path is None:
        return None
    current = getattr(jax.config, "jax_compilation_cache_dir", None)
    if current and not explicit:
        return current
    try:
        Path(path).mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # default thresholds skip small-but-hot entries; the engine's decode
        # block alone is worth caching regardless of its compile seconds
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        return None
    return path
