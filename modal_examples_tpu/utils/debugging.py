"""Numerics & correctness debugging — the sanitizer tier the reference lacks
(SURVEY.md §5.2 calls for jax transfer-guard / NaN-check / disable-jit modes
as our addition over the reference's warnings-as-errors + mypy).
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def debug_nans(enable: bool = True):
    """Raise at the first NaN-producing op (jax_debug_nans)."""
    with jax.debug_nans(enable):
        yield


@contextlib.contextmanager
def no_implicit_transfers(level: str = "disallow"):
    """Fail on implicit host<->device transfers — catches accidental device
    syncs in the hot loop (the TPU analog of catching hidden .cpu() calls)."""
    with jax.transfer_guard(level):
        yield


@contextlib.contextmanager
def eager_mode():
    """Run without jit for step-through debugging (--no-enforce-eager analog,
    vllm_inference.py:175-177 — but as a scoped context, not a server flag)."""
    with jax.disable_jit():
        yield


def check_numerics(tree, name: str = "pytree") -> None:
    """Assert every leaf is finite; names the offending path."""
    import jax.numpy as jnp

    def check(path, leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            bad = int(jnp.sum(~jnp.isfinite(leaf)))
            if bad:
                raise FloatingPointError(
                    f"{name}{jax.tree_util.keystr(path)}: {bad} non-finite values"
                )

    jax.tree_util.tree_map_with_path(check, tree)


def tree_summary(tree) -> str:
    """One line per leaf: path, shape, dtype, norm — quick divergence triage."""
    import jax.numpy as jnp

    lines = []

    def add(path, leaf):
        if hasattr(leaf, "shape"):
            norm = float(jnp.linalg.norm(leaf.astype(jnp.float32)))
            lines.append(
                f"{jax.tree_util.keystr(path):40s} {str(leaf.shape):18s} "
                f"{str(leaf.dtype):10s} |x|={norm:.3e}"
            )

    jax.tree_util.tree_map_with_path(add, tree)
    return "\n".join(lines)
