"""Deterministic pseudo-randomness derived from hashing, not RNG state.

The framework never wants *surprising* randomness in its control paths —
retry jitter must not make tests flaky, fault plans must replay from a
seed — but it does want *decorrelation*: N replicas keyed differently must
not act in lockstep. Hashing the inputs gives both: stable across
processes, platforms, and python hash randomization, with no state to
carry. jax-free by construction (the ``core/`` layer imports this).
"""

from __future__ import annotations

import hashlib


def unit_float(*parts) -> float:
    """Deterministic uniform in [0, 1) from the ``:``-joined ``parts``
    (each stringified) — e.g. ``unit_float(key, attempt)`` for retry
    jitter, ``unit_float(seed, point, hit)`` for fault-plan coins."""
    digest = hashlib.sha256(
        ":".join(str(p) for p in parts).encode()
    ).digest()
    return int.from_bytes(digest[:8], "little") / 2**64
