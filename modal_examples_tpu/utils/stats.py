"""Tiny shared statistics helpers (pure stdlib, jax-free).

One nearest-rank percentile for the whole repo: bench.py's latency
sections, the fleet load generator, and the hot-path profiler's
``overhead`` section all quantize through THIS function, so
``tpurun benchdiff`` never compares sections computed under two drifted
rank conventions.
"""

from __future__ import annotations


def percentile_nearest_rank(values, q: float) -> float:
    """Nearest-rank percentile over a small sample (no numpy on purpose:
    callers must emit even when the episode count is tiny). ``values``
    need not be sorted; empty input returns 0.0."""
    vals = sorted(values)
    if not vals:
        return 0.0
    idx = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
    return vals[idx]
