"""Evaluation metrics: word error rate (the Whisper fine-tune eval,
openai_whisper/finetuning/train/train.py:431-490 computes WER; the
end-to-end check asserts WER < 1.0, end_to_end_check.py:29-70)."""

from __future__ import annotations


def _levenshtein(a: list[str], b: list[str]) -> int:
    if not a:
        return len(b)
    if not b:
        return len(a)
    prev = list(range(len(b) + 1))
    for i, wa in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        for j, wb in enumerate(b, 1):
            cur[j] = min(
                prev[j] + 1,  # deletion
                cur[j - 1] + 1,  # insertion
                prev[j - 1] + (wa != wb),  # substitution
            )
        prev = cur
    return prev[-1]


def _edit_distance(a: list[str], b: list[str]) -> int:
    """Native levenshtein over interned symbol ids when available."""
    try:
        from ..native import levenshtein_ids, load

        if load() is not None:
            vocab: dict[str, int] = {}
            ids = lambda seq: [vocab.setdefault(w, len(vocab)) for w in seq]
            return levenshtein_ids(ids(a), ids(b))
    except Exception:
        pass
    return _levenshtein(a, b)


def word_error_rate(references: list[str], hypotheses: list[str]) -> float:
    """Corpus-level WER: total edits / total reference words."""
    edits = 0
    words = 0
    for ref, hyp in zip(references, hypotheses):
        r, h = ref.split(), hyp.split()
        edits += _edit_distance(r, h)
        words += len(r)
    return edits / max(words, 1)


def character_error_rate(references: list[str], hypotheses: list[str]) -> float:
    edits = 0
    chars = 0
    for ref, hyp in zip(references, hypotheses):
        edits += _levenshtein(list(ref), list(hyp))
        chars += len(ref)
    return edits / max(chars, 1)
