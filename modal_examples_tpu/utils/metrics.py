"""Evaluation metrics: word error rate (the Whisper fine-tune eval,
openai_whisper/finetuning/train/train.py:431-490 computes WER; the
end-to-end check asserts WER < 1.0, end_to_end_check.py:29-70).

Also hosts runtime telemetry recorders that feed the prometheus registry
(utils/prometheus.py) — currently cold-start memory-snapshot accounting
(:func:`record_snapshot_boot`), pushed from the executor supervisor on every
snapshot-enabled container boot."""

from __future__ import annotations

#: Prometheus metric names for memory-snapshot cold-start accounting
#: (modal_examples_tpu.snapshot). Labels: function=<spec tag>, and
#: result=hit|miss|fallback on the boots counter. Declared in the central
#: catalog (observability.catalog); re-exported here for back-compat.
from ..observability.catalog import (  # noqa: F401
    SNAPSHOT_BOOTS_METRIC,
    SNAPSHOT_CAPTURES_METRIC,
)


def record_snapshot_boot(
    tag: str, result: str, *, captured: bool = False, registry=None
) -> None:
    """Count one snapshot-enabled container boot.

    ``result`` is the boot's snapshot outcome: ``"hit"`` (restored past
    ``snap=True`` hooks), ``"miss"`` (no entry yet; cold boot + capture), or
    ``"fallback"`` (an entry existed but couldn't be used; cold boot).
    ``captured=True`` additionally counts a published snapshot. The executor
    calls this on the supervisor side from the container's ready message, so
    the registry lives in the client process that serves /metrics."""
    from .prometheus import default_registry

    reg = registry if registry is not None else default_registry
    reg.counter_inc(
        SNAPSHOT_BOOTS_METRIC,
        1.0,
        labels={"function": tag, "result": result},
        help="snapshot-enabled container boots by outcome (hit/miss/fallback)",
    )
    if captured:
        reg.counter_inc(
            SNAPSHOT_CAPTURES_METRIC,
            1.0,
            labels={"function": tag},
            help="memory snapshots captured and published to the store",
        )


def _levenshtein(a: list[str], b: list[str]) -> int:
    if not a:
        return len(b)
    if not b:
        return len(a)
    prev = list(range(len(b) + 1))
    for i, wa in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        for j, wb in enumerate(b, 1):
            cur[j] = min(
                prev[j] + 1,  # deletion
                cur[j - 1] + 1,  # insertion
                prev[j - 1] + (wa != wb),  # substitution
            )
        prev = cur
    return prev[-1]


def _edit_distance(a: list[str], b: list[str]) -> int:
    """Native levenshtein over interned symbol ids when available."""
    try:
        from ..native import levenshtein_ids, load

        if load() is not None:
            vocab: dict[str, int] = {}
            ids = lambda seq: [vocab.setdefault(w, len(vocab)) for w in seq]
            return levenshtein_ids(ids(a), ids(b))
    except Exception:
        pass
    return _levenshtein(a, b)


def word_error_rate(references: list[str], hypotheses: list[str]) -> float:
    """Corpus-level WER: total edits / total reference words."""
    edits = 0
    words = 0
    for ref, hyp in zip(references, hypotheses):
        r, h = ref.split(), hyp.split()
        edits += _edit_distance(r, h)
        words += len(r)
    return edits / max(words, 1)


def character_error_rate(references: list[str], hypotheses: list[str]) -> float:
    edits = 0
    chars = 0
    for ref, hyp in zip(references, hypotheses):
        edits += _levenshtein(list(ref), list(hyp))
        chars += len(ref)
    return edits / max(chars, 1)
