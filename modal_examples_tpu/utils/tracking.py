"""Experiment tracking: local JSONL run logs + optional TensorBoard events.

The reference tracks runs with wandb (unsloth_finetune.py:294-300) and
TensorBoard over Volumes (hp_sweep_gpt.py:396-436, src/logs_manager.py).
Zero-egress equivalent: a run directory (put it on a Volume) holding
``metrics.jsonl`` (one JSON object per step — greppable, diffable) plus
TensorBoard event files when the tensorboard package is present, so a
hosted TB (wsgi pattern, §5.5) renders the same curves.
"""

from __future__ import annotations

import json
import time
from pathlib import Path


class RunLogger:
    def __init__(self, run_dir: str | Path, *, volume=None, tensorboard: bool = True):
        self.run_dir = Path(run_dir)
        self.run_dir.mkdir(parents=True, exist_ok=True)
        self.volume = volume
        self._jsonl = open(self.run_dir / "metrics.jsonl", "a")
        self._tb = None
        if tensorboard:
            try:
                from tensorboard.summary.writer.event_file_writer import (
                    EventFileWriter,
                )
                from tensorboard.compat.proto.summary_pb2 import Summary
                from tensorboard.compat.proto.event_pb2 import Event

                self._tb = EventFileWriter(str(self.run_dir))
                self._Summary = Summary
                self._Event = Event
            except Exception:
                self._tb = None

    def log(self, step: int, metrics: dict) -> None:
        record = {"step": step, "time": time.time()}
        for k, v in metrics.items():
            try:
                record[k] = float(v)
            except (TypeError, ValueError):
                record[k] = str(v)
        self._jsonl.write(json.dumps(record) + "\n")
        self._jsonl.flush()
        if self._tb is not None:
            summary = self._Summary(
                value=[
                    self._Summary.Value(tag=k, simple_value=float(v))
                    for k, v in record.items()
                    if k not in ("step", "time") and isinstance(v, float)
                ]
            )
            self._tb.add_event(
                self._Event(step=step, wall_time=record["time"], summary=summary)
            )

    def history(self) -> list[dict]:
        path = self.run_dir / "metrics.jsonl"
        if not path.exists():
            return []
        return [json.loads(line) for line in path.read_text().splitlines() if line]

    def close(self) -> None:
        """Release the JSONL handle and TB writer, then commit the Volume.
        Idempotent: Trainer.fit and an outer ``with`` block may both close."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        self._jsonl.close()
        if self._tb is not None:
            self._tb.flush()
            self._tb.close()
        if self.volume is not None:
            self.volume.commit()

    def __enter__(self) -> "RunLogger":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
