"""Tracing/profiling: the torch_profiling.py analog on jax.profiler.

Reference pattern (SURVEY.md §5.1): a generic ``profile`` Function wraps any
registered Function by name (app.registered_functions,
torch_profiling.py:131-135), runs it under the profiler with a warmup/active
schedule (:141-161), writes TensorBoard-compatible traces to a Volume
(:116,138-139), and returns a summary table (:164-167).

TPU translation: ``jax.profiler.trace`` emits XPlane traces readable by
TensorBoard's profile plugin / XProf and Perfetto; ``block_until_ready``
replaces the ``.cpu()`` host sync (:100).
"""

from __future__ import annotations

import dataclasses
import time
from pathlib import Path
from typing import Any, Callable


@dataclasses.dataclass
class ProfileResult:
    wall_s: float
    warmup_s: float
    iterations: int
    per_iter_s: float
    trace_dir: str | None

    def summary(self) -> str:
        lines = [
            f"iterations:     {self.iterations}",
            f"warmup:         {self.warmup_s * 1e3:.2f} ms",
            f"total:          {self.wall_s * 1e3:.2f} ms",
            f"per-iteration:  {self.per_iter_s * 1e3:.3f} ms",
        ]
        if self.trace_dir:
            lines.append(f"trace:          {self.trace_dir} (TensorBoard/XProf)")
        return "\n".join(lines)


def _sync(x):
    # utils.sync.force, NOT jax.block_until_ready: on the tunneled axon
    # backend block_until_ready returns while execution is still queued, so
    # every timing here would under-measure (round-4 audit, VERDICT r3 #9)
    from .sync import force

    force(x)
    return x


def profile_call(
    fn: Callable,
    *args,
    warmup: int = 2,
    iterations: int = 10,
    trace_dir: str | Path | None = None,
    **kwargs,
) -> tuple[Any, ProfileResult]:
    """Run ``fn`` under the TPU profiler with a warmup/active schedule.

    Returns (last result, ProfileResult). When ``trace_dir`` is set, the
    active iterations are captured as an XPlane trace for TensorBoard's
    profile plugin.
    """
    import jax

    t0 = time.perf_counter()
    out = None
    for _ in range(max(warmup, 0)):
        out = _sync(fn(*args, **kwargs))
    warmup_s = time.perf_counter() - t0

    ctx = None
    if trace_dir is not None:
        trace_dir = str(trace_dir)
        ctx = jax.profiler.trace(trace_dir)
        ctx.__enter__()
    t0 = time.perf_counter()
    try:
        for _ in range(iterations):
            out = fn(*args, **kwargs)
        _sync(out)
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)
    wall = time.perf_counter() - t0
    return out, ProfileResult(
        wall_s=wall,
        warmup_s=warmup_s,
        iterations=iterations,
        per_iter_s=wall / max(iterations, 1),
        trace_dir=str(trace_dir) if trace_dir else None,
    )


def make_profile_function(app, trace_volume=None, mount_path: str = "/traces"):
    """Register a generic ``profile`` Function on ``app`` that wraps any of
    the app's registered functions by name — the torch_profiling.py:131-139
    pattern, with traces written to a Volume for a hosted TensorBoard.

    Call AFTER the functions you want profilable are registered: the wrapper
    snapshots their raw callables (the App object itself holds live run
    state and never crosses the container boundary).
    """

    volumes = {mount_path: trace_volume} if trace_volume is not None else {}
    targets = {n: f.raw_f for n, f in app.registered_functions.items()}

    @app.function(name="profile", volumes=volumes, timeout=600)
    def profile(function_name: str, *args, iterations: int = 10, **kwargs):
        fn = targets.get(function_name)
        if fn is None:
            raise KeyError(
                f"{function_name!r} is not registered; have {sorted(targets)}"
            )
        trace_dir = (
            f"{mount_path}/{function_name}-{int(time.time())}" if volumes else None
        )
        out, result = profile_call(
            fn, *args, iterations=iterations, trace_dir=trace_dir, **kwargs
        )
        if trace_volume is not None:
            trace_volume.commit()
        print(result.summary())
        return dataclasses.asdict(result)

    return profile


def export_call_trace(call_id: str, out_path: str | Path) -> dict:
    """Write one framework call's lifecycle trace as Chrome-trace/Perfetto
    JSON next to wherever your XPlane traces go — ``jax.profiler.trace``
    answers "what did the chip do", this answers "what did the *framework*
    do around it" (queue/boot/dispatch/execute spans), in the same UI
    (ui.perfetto.dev / chrome://tracing). ``call_id`` is the ``in-...`` id
    from ``FunctionCall.call_id``; raises KeyError when no such trace
    exists. Same converter as ``tpurun trace <id> --perfetto``."""
    from ..observability.export import export_chrome_trace

    doc = export_chrome_trace(call_id, out_path)
    if doc is None:
        raise KeyError(f"no trace recorded for call {call_id!r}")
    return doc


def device_memory_stats() -> dict:
    """HBM usage per device — the nvidia-smi replacement
    (install_cuda.py:17-20 analog)."""
    import jax

    out = {}
    for d in jax.devices():
        stats = d.memory_stats() or {}
        out[str(d)] = {
            "bytes_in_use": stats.get("bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
        }
    return out
