"""Minimal image IO: PNG encode/decode via stdlib zlib (no PIL dependency).

Enough for the diffusion examples to return real image bytes over the web
endpoint (text_to_image.py:107-137 returns PNG responses)."""

from __future__ import annotations

import struct
import zlib

import numpy as np


def _chunk(tag: bytes, data: bytes) -> bytes:
    return (
        struct.pack(">I", len(data))
        + tag
        + data
        + struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF)
    )


def to_png(img: np.ndarray) -> bytes:
    """[H, W, 3] uint8 (or float in [-1,1] / [0,1]) -> PNG bytes."""
    img = np.asarray(img)
    if img.dtype != np.uint8:
        arr = img.astype(np.float32)
        if arr.min() < 0:  # [-1, 1] convention
            arr = (arr + 1.0) / 2.0
        img = (np.clip(arr, 0, 1) * 255).astype(np.uint8)
    if img.ndim == 2:
        img = np.repeat(img[..., None], 3, axis=-1)
    H, W, C = img.shape
    assert C == 3, f"expected RGB, got {C} channels"
    raw = b"".join(b"\x00" + img[r].tobytes() for r in range(H))
    return b"".join(
        [
            b"\x89PNG\r\n\x1a\n",
            _chunk(b"IHDR", struct.pack(">IIBBBBB", W, H, 8, 2, 0, 0, 0)),
            _chunk(b"IDAT", zlib.compress(raw, 6)),
            _chunk(b"IEND", b""),
        ]
    )


def from_png(data: bytes) -> np.ndarray:
    """PNG bytes (as produced by to_png: 8-bit RGB, no filters) -> uint8
    [H, W, 3]. Minimal decoder for round-trip tests."""
    assert data[:8] == b"\x89PNG\r\n\x1a\n"
    pos = 8
    W = H = None
    idat = b""
    while pos < len(data):
        (length,) = struct.unpack(">I", data[pos : pos + 4])
        tag = data[pos + 4 : pos + 8]
        body = data[pos + 8 : pos + 8 + length]
        if tag == b"IHDR":
            W, H = struct.unpack(">II", body[:8])
        elif tag == b"IDAT":
            idat += body
        pos += 12 + length
    raw = zlib.decompress(idat)
    stride = W * 3 + 1
    rows = []
    for r in range(H):
        row = raw[r * stride : (r + 1) * stride]
        assert row[0] == 0, "only filter 0 supported"
        rows.append(np.frombuffer(row[1:], np.uint8).reshape(W, 3))
    return np.stack(rows)
