"""GCS JSON-API client + CloudBucketMount pull/push against a local fake
GCS server (the fake-gcs-server emulator pattern; zero egress means the
real endpoint is unreachable, but the protocol is the real one)."""

import json
import threading
import urllib.parse

import pytest


class _FakeGCS:
    """Just enough of storage.googleapis.com: list/get/upload/delete,
    pagination, bearer-token check."""

    def __init__(self, require_token: str | None = None):
        import http.server

        store = self.store = {}  # (bucket, name) -> bytes
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _auth_ok(self):
                if outer.require_token is None:
                    return True
                return (
                    self.headers.get("Authorization")
                    == f"Bearer {outer.require_token}"
                )

            def _json(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("content-length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if not self._auth_ok():
                    return self._json(401, {"error": "unauthorized"})
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.strip("/").split("/")
                q = {k: v[-1] for k, v in
                     urllib.parse.parse_qs(parsed.query).items()}
                # /storage/v1/b/{bucket}/o  or  .../o/{object}
                if parts[:2] == ["storage", "v1"] and parts[2] == "b":
                    bucket = urllib.parse.unquote(parts[3])
                    if len(parts) == 5 and parts[4] == "o":
                        prefix = q.get("prefix", "")
                        items = [
                            {"name": n, "size": str(len(d))}
                            for (b, n), d in sorted(outer.store.items())
                            if b == bucket and n.startswith(prefix)
                        ]
                        # exercise pagination: 2 items per page
                        start = int(q.get("pageToken", "0"))
                        page = items[start : start + 2]
                        body = {"items": page}
                        if start + 2 < len(items):
                            body["nextPageToken"] = str(start + 2)
                        return self._json(200, body)
                    if len(parts) == 6:
                        name = urllib.parse.unquote(parts[5])
                        data = outer.store.get((bucket, name))
                        if data is None:
                            return self._json(404, {"error": "not found"})
                        self.send_response(200)
                        self.send_header("content-length", str(len(data)))
                        self.end_headers()
                        self.wfile.write(data)
                        return
                self._json(404, {"error": "bad path"})

            def do_POST(self):
                if not self._auth_ok():
                    return self._json(401, {"error": "unauthorized"})
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.strip("/").split("/")
                q = {k: v[-1] for k, v in
                     urllib.parse.parse_qs(parsed.query).items()}
                # /upload/storage/v1/b/{bucket}/o?uploadType=media&name=..
                if parts[:1] == ["upload"]:
                    bucket = urllib.parse.unquote(parts[4])
                    name = q["name"]
                    n = int(self.headers.get("content-length") or 0)
                    outer.store[(bucket, name)] = self.rfile.read(n)
                    return self._json(200, {"name": name, "bucket": bucket})
                self._json(404, {"error": "bad path"})

            def do_DELETE(self):
                if not self._auth_ok():
                    return self._json(401, {"error": "unauthorized"})
                parts = urllib.parse.urlparse(self.path).path.strip("/").split("/")
                bucket = urllib.parse.unquote(parts[3])
                name = urllib.parse.unquote(parts[5])
                outer.store.pop((bucket, name), None)
                self._json(204, {})

        self.require_token = require_token
        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        self.endpoint = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class TestGCSClient:
    def test_put_list_get_delete_roundtrip(self):
        from modal_examples_tpu.storage.gcs import GCSClient

        srv = _FakeGCS()
        try:
            c = GCSClient(endpoint=srv.endpoint)
            c.put_object("data", "a/x.txt", b"one")
            c.put_object("data", "a/y.txt", b"two")
            c.put_object("data", "b/z.txt", b"three")
            names = [o["name"] for o in c.list_objects("data", prefix="a/")]
            assert names == ["a/x.txt", "a/y.txt"]
            assert c.get_object("data", "a/y.txt") == b"two"
            c.delete_object("data", "a/x.txt")
            names = [o["name"] for o in c.list_objects("data", prefix="a/")]
            assert names == ["a/y.txt"]
        finally:
            srv.stop()

    def test_pagination_exercised(self):
        from modal_examples_tpu.storage.gcs import GCSClient

        srv = _FakeGCS()
        try:
            c = GCSClient(endpoint=srv.endpoint)
            for i in range(5):  # fake serves 2 per page -> 3 pages
                c.put_object("pg", f"k{i}", bytes([i]))
            names = [o["name"] for o in c.list_objects("pg")]
            assert names == [f"k{i}" for i in range(5)]
        finally:
            srv.stop()

    def test_bearer_token_sent_and_required(self):
        from modal_examples_tpu.storage.gcs import GCSClient, GCSError

        srv = _FakeGCS(require_token="sekrit")
        try:
            ok = GCSClient(endpoint=srv.endpoint, token="sekrit")
            ok.put_object("b", "k", b"v")
            assert ok.get_object("b", "k") == b"v"
            bad = GCSClient(endpoint=srv.endpoint, token="wrong")
            with pytest.raises(GCSError) as e:
                bad.get_object("b", "k")
            assert e.value.status == 401
        finally:
            srv.stop()

    def test_missing_object_raises_with_status(self):
        from modal_examples_tpu.storage.gcs import GCSClient, GCSError

        srv = _FakeGCS()
        try:
            c = GCSClient(endpoint=srv.endpoint)
            with pytest.raises(GCSError) as e:
                c.get_object("nope", "missing")
            assert e.value.status == 404
        finally:
            srv.stop()


class TestCloudBucketMountGCS:
    def test_pull_and_push_through_mount(self, state_dir):
        import modal_examples_tpu as mtpu
        from modal_examples_tpu.storage.gcs import GCSClient

        srv = _FakeGCS()
        try:
            seed = GCSClient(endpoint=srv.endpoint)
            seed.put_object("datasets", "coco/train/0001.txt", b"imgdata")
            seed.put_object("datasets", "coco/train/0002.txt", b"imgdata2")
            seed.put_object("datasets", "other/x.txt", b"not ours")

            mount = mtpu.CloudBucketMount(
                "datasets", key_prefix="coco",
                bucket_endpoint_url=srv.endpoint,
            )
            n = mount.pull()
            assert n == 2
            assert (mount.local_path / "train/0001.txt").read_bytes() == b"imgdata"

            # write-back: new local file lands in the bucket under the prefix
            (mount.local_path / "train/0003.txt").write_bytes(b"new")
            mount.push()
            assert seed.get_object(
                "datasets", "coco/train/0003.txt"
            ) == b"new"

            ro = mtpu.CloudBucketMount(
                "datasets", key_prefix="coco",
                bucket_endpoint_url=srv.endpoint, read_only=True,
            )
            with pytest.raises(PermissionError):
                ro.push()
        finally:
            srv.stop()
