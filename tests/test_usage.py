"""Hardware-utilization accounting (ISSUE 17,
docs/observability.md#roofline-and-usage-accounting): the analytic work
model hand-checked against the formulas (bf16 AND int8 KV), fake-clock
MFU/MBU determinism, per-tenant conservation under concurrent streams and
sheds (Σ tenants == the engine's own counters, Σ journal == the same), and
the read surfaces — `tpurun usage`, the gateway `/usage` snapshot, the
OpenAI `cached_tokens` usage field, and benchdiff's hardware-identity
refusal."""

import json
import threading
import urllib.request

import pytest

from modal_examples_tpu.observability import catalog as C
from modal_examples_tpu.observability import usage as us
from modal_examples_tpu.utils.prometheus import Registry


class _Req:
    """The slice of ``serving.engine.Request`` the accountant touches."""

    def __init__(self, rid="req-1", tenant="acme", priority="default"):
        self.request_id = rid
        self.tenant = tenant
        self.priority = priority
        self.n_generated = 0
        self.cached_prompt_tokens = 0


# ---------------------------------------------------------------------------
# the analytic work model
# ---------------------------------------------------------------------------


class TestWorkModel:
    def test_formulas_hand_checked(self):
        m = us.WorkModel(
            n_params=1000, n_layers=2, dim=8,
            weight_bytes=2000, kv_bytes_per_token=64.0,
        )
        # prefill: 2·N·T + 2·L·D·ΣT²
        assert m.prefill_flops(10, sq_tokens=100) == (
            2 * 1000 * 10 + 2 * 2 * 8 * 100
        )
        # decode: 2·N per token + 4·L·D·ctx
        assert m.decode_flops(5, ctx_sum=50) == (
            2 * 1000 * 5 + 4 * 2 * 8 * 50
        )
        # prefill bytes: one weight stream per dispatched program + KV write
        assert m.prefill_bytes(10, n_calls=2) == 2 * 2000 + 64 * 10
        # decode bytes: weight stream per token + KV history read
        assert m.decode_bytes(5, ctx_sum=50) == 5 * 2000 + 64 * 50
        # the attention terms need ΣT², not (ΣT)²: two 10-token prompts
        # cost less than one 20-token prompt
        assert m.prefill_flops(20, sq_tokens=2 * 10 * 10) < m.prefill_flops(
            20, sq_tokens=20 * 20
        )

    def test_from_engine_bf16_tiny(self, jax_cpu):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.models.quantize import param_bytes
        from modal_examples_tpu.serving.kv_cache import PagedKVCache

        cfg = llama.LlamaConfig.tiny()  # dim 128, L2, H4, Hkv2 -> hd 32
        cache = PagedKVCache.create(
            n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.dim // cfg.n_heads, n_pages=8, page_size=16,
        )
        params = llama.init_params(jax_cpu.random.PRNGKey(0), cfg)
        m = us.WorkModel.from_engine(
            cfg, cache=cache, weight_bytes=param_bytes(params)
        )
        assert m.n_params == cfg.param_count
        assert m.weight_bytes == 2 * cfg.param_count  # bf16: 2 B/param
        # bf16 KV/token: k+v · L · Hkv · hd · 2 B = 2·2·2·32·2 = 512
        assert m.kv_bytes_per_token == 512.0
        assert m.kv_bytes_per_token == cache.bytes() / (
            cache.n_pages * cache.page_size
        )

    def test_from_engine_int8_halves_kv_bytes(self, jax_cpu):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving.kv_cache import PagedKVCache

        cfg = llama.LlamaConfig.tiny()
        cache = PagedKVCache.create(
            n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.dim // cfg.n_heads, n_pages=8, page_size=16,
            kv_dtype="int8",
        )
        m = us.WorkModel.from_engine(cfg, cache=cache, weight_bytes=1)
        # int8 KV/token: payload k+v·L·Hkv·hd·1 B = 256, plus the f32
        # scale rows k+v·L·Hkv·4 B = 32 -> 288; the model prices the cache
        # the engine actually allocated, so int8 halves modeled traffic
        assert m.kv_bytes_per_token == 288.0
        assert m.kv_bytes_per_token == cache.bytes() / (
            cache.n_pages * cache.page_size
        )
        assert m.kv_bytes_per_token < 512.0


class TestResolvePeaks:
    def test_explicit_beats_env_beats_default(self, monkeypatch):
        monkeypatch.setenv(us.GENERATION_ENV, "v4")
        assert us.resolve_peaks("v5p")["generation"] == "v5p"
        assert us.resolve_peaks()["generation"] == "v4"
        monkeypatch.delenv(us.GENERATION_ENV)
        assert us.resolve_peaks()["generation"] == us.DEFAULT_GENERATION

    def test_unknown_generation_falls_back_and_chips_scale(self):
        p = us.resolve_peaks("tpu9000", chips=4)
        assert p["generation"] == us.DEFAULT_GENERATION
        assert p["chips"] == 4
        assert p["tflops_per_chip"] > 0
        assert p["hbm_gbps_per_chip"] > 0


# ---------------------------------------------------------------------------
# the meter: fake-clock determinism, conservation, delta flush
# ---------------------------------------------------------------------------


def _meter(registry=None, journal_path=None, chips=1):
    model = us.WorkModel(
        n_params=1000, n_layers=2, dim=8,
        weight_bytes=2000, kv_bytes_per_token=64.0,
    )
    return us.EngineUsage(
        model, name="eng-0", generation="v5e", chips=chips,
        registry=registry, journal_path=journal_path,
    )


class TestEngineUsageMeter:
    def test_roofline_is_deterministic_and_hand_checkable(self):
        # 7B-class numbers so the achieved fractions survive summary()'s
        # 6-decimal rounding and land in the regime the meter exists for
        N, L, D = 7_000_000_000, 32, 4096
        WB, KVB = 7_000_000_000, 262_144  # int8 weights, bf16 KV/token

        def drive():
            u = us.EngineUsage(
                us.WorkModel(
                    n_params=N, n_layers=L, dim=D,
                    weight_bytes=WB, kv_bytes_per_token=float(KVB),
                ),
                name="eng-0", generation="v5e",
            )
            req = _Req()
            u.note_prompt(req, 512)
            u.note_phase_seconds("prefill", 0.5)
            for ctx in (512, 513, 514):
                u.note_token(req, ctx)
            u.note_phase_seconds("decode", 2.0)
            return u.summary()

        a, b = drive(), drive()
        assert a == b  # seconds come from the injected brackets: exact
        peaks = us.resolve_peaks("v5e")
        pre = a["phases"]["prefill"]
        pre_flops = 2 * N * 512 + 2 * L * D * 512 * 512
        assert pre["flops"] == pre_flops
        assert pre["bytes"] == WB + KVB * 512  # one dispatched program
        assert pre["mfu"] == pytest.approx(
            pre_flops / (0.5 * peaks["tflops_per_chip"] * 1e12), abs=1e-6
        )
        dec = a["phases"]["decode"]
        ctx_sum = 512 + 513 + 514
        dec_bytes = 3 * WB + KVB * ctx_sum
        assert dec["flops"] == 2 * N * 3 + 4 * L * D * ctx_sum
        assert dec["bytes"] == dec_bytes
        assert dec["mbu"] == pytest.approx(
            dec_bytes / (2.0 * peaks["hbm_gbps_per_chip"] * 1e9), abs=1e-6
        )
        tot = a["phases"]["total"]
        assert tot["flops"] == pre["flops"] + dec["flops"]
        assert tot["device_seconds"] == pytest.approx(2.5)
        # decode streams bytes, not flops: bandwidth-bound by a wide margin
        assert dec["bound"] == "bandwidth"

    def test_zero_seconds_yields_null_bound(self):
        u = _meter()
        u.note_prompt(_Req(), 10)
        s = u.summary()
        assert s["phases"]["prefill"]["mfu"] == 0.0
        assert s["phases"]["prefill"]["bound"] is None
        # ...and the BENCH section defaults the classification to the
        # decode-dominated truth instead of exporting null
        sec = u.utilization_section()
        assert sec["bound"] == "bandwidth"
        assert sec["tokens_per_second_per_chip"] is None

    def test_utilization_section_shape_and_chip_normalization(self):
        u = _meter(chips=2)
        u.note_prompt(_Req(), 10)
        u.note_phase_seconds("prefill", 1.0)
        sec = u.utilization_section(tokens_per_second=100.0)
        assert sec["chips"] == 2
        assert sec["tokens_per_second_per_chip"] == 50.0
        assert set(sec["per_phase"]) == {"prefill", "decode"}
        assert sec["work_model"] == {
            "n_params": 1000, "weight_bytes": 2000,
            "kv_bytes_per_token": 64.0,
        }

    def test_tenant_buckets_conserve_and_sort(self):
        u = _meter()
        a, b = _Req("r1", tenant="a"), _Req("r2", tenant="b", priority="batch")
        u.note_prompt(a, 10)
        u.note_prompt(b, 20)
        u.note_token(a, 10)
        u.note_token(a, 11)
        u.note_token(b, 20)
        u.note_slot_release(a, pages=4, held_s=2.0)
        t = u.tenants()
        assert [r["tenant"] for r in t["tenants"]] == ["a", "b"]
        assert t["totals"]["prompt_tokens"] == 30
        assert t["totals"]["generated_tokens"] == 3
        assert t["totals"]["device_seconds"] == pytest.approx(2.0)
        assert t["totals"]["kv_page_seconds"] == pytest.approx(8.0)
        assert t["totals"]["requests"] == 2

    def test_flush_emits_deltas_not_totals(self):
        reg = Registry()
        u = _meter(registry=reg)
        req = _Req(tenant="a")
        labels = {"tenant": "a", "class": "default"}
        u.note_prompt(req, 10)
        u.note_token(req, 10)
        u.flush()
        assert reg.value(C.USAGE_PROMPT_TOKENS_TOTAL, labels) == 10.0
        assert reg.value(C.USAGE_GENERATED_TOKENS_TOTAL, labels) == 1.0
        u.flush()  # no new work: counters must NOT double
        assert reg.value(C.USAGE_PROMPT_TOKENS_TOTAL, labels) == 10.0
        u.note_token(req, 11)
        u.flush()
        assert reg.value(C.USAGE_GENERATED_TOKENS_TOTAL, labels) == 2.0
        # roofline gauges refresh on every flush, all phases present
        for phase in C.ROOFLINE_PHASES:
            assert reg.value(C.MFU, {"phase": phase}) is not None
            assert reg.value(C.HBM_BW_UTIL, {"phase": phase}) is not None

    def test_finish_journals_once_with_accounted_tokens(self, tmp_path):
        path = tmp_path / "usage.jsonl"
        u = _meter(journal_path=path)
        req = _Req("req-9", tenant="acme", priority="interactive")
        u.note_prompt(req, 12)
        req.n_generated = 3
        req.cached_prompt_tokens = 16
        u.note_finish(req, "stop")
        u.note_finish(req, "stop")  # double-finish: journals exactly once
        recs = [json.loads(l) for l in path.read_text().splitlines()]
        assert len(recs) == 1
        assert recs[0]["request_id"] == "req-9"
        assert recs[0]["tenant"] == "acme"
        assert recs[0]["class"] == "interactive"
        assert recs[0]["prompt_tokens"] == 12  # the ACCOUNTED figure
        assert recs[0]["generated_tokens"] == 3
        assert recs[0]["cached_prompt_tokens"] == 16
        assert recs[0]["finish_reason"] == "stop"
        totals = us.journal_tenant_totals(recs)
        assert totals == {"acme": {
            "prompt_tokens": 12, "generated_tokens": 3, "requests": 1,
        }}

    def test_shed_never_prefilled_journals_zero_prompt(self, tmp_path):
        # conservation depends on the journal recording what was ACCOUNTED:
        # a request shed before prefill contributes 0, not its prompt length
        path = tmp_path / "usage.jsonl"
        u = _meter(journal_path=path)
        req = _Req("req-shed")
        u.note_finish(req, "shed")
        rec = json.loads(path.read_text())
        assert rec["prompt_tokens"] == 0
        assert rec["generated_tokens"] == 0

    def test_admission_shed_charges_the_turned_away_tenant(self):
        from modal_examples_tpu.scheduling.admission import (
            AdmissionConfig, AdmissionController, ShedError,
        )
        from modal_examples_tpu.scheduling.policy import ScheduledRequest

        reg = Registry()
        u = _meter(registry=reg)
        ctl = AdmissionController(AdmissionConfig(max_queue={"default": 0}))
        ctl.usage = u  # the engine wires this at build
        entry = ScheduledRequest(payload=None, tenant="noisy", cost=1)
        with pytest.raises(ShedError):
            ctl.admit(entry, depths={"default": 0}, pages_used=0,
                      pages_total=8)
        assert u.tenants()["totals"]["sheds"] == 1
        # sheds emit immediately (rare events skip the delta flush)
        assert reg.value(
            C.USAGE_SHEDS_TOTAL, {"tenant": "noisy", "class": "default"}
        ) == 1.0


# ---------------------------------------------------------------------------
# live-engine conservation: Σ tenants == engine counters, Σ journal == same
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def engine(jax_cpu):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    cfg = llama.LlamaConfig.tiny()
    eng = LLMEngine(
        cfg, max_slots=4, max_model_len=128, page_size=16,
        prefill_buckets=(32, 64), seed=0,
    )
    yield eng
    eng.stop()


class TestEngineConservation:
    def test_concurrent_streams_conserve_exactly(self, engine):
        from modal_examples_tpu.serving.sampling import SamplingParams

        reqs, errs = [], []

        def run(tenant, klass, prompt):
            try:
                req = engine.submit(
                    prompt, SamplingParams(max_tokens=6, temperature=0.0),
                    tenant=tenant, priority=klass,
                )
                reqs.append(req)
                for _ in engine.stream(req):
                    pass
            except Exception as e:  # surface thread failures in the assert
                errs.append(e)

        threads = [
            threading.Thread(target=run, args=args)
            for args in (
                ("acme", "interactive", "the quick brown fox jumps"),
                ("acme", "default", "pack my box with five dozen jugs"),
                ("globex", "default", "sphinx of black quartz judge my vow"),
                ("globex", "batch", "how vexingly quick daft zebras jump"),
                ("initech", "default", "the five boxing wizards jump"),
            )
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs, errs
        assert len(reqs) == 5

        # Σ per-tenant buckets == the engine's own ledger, EXACTLY — the
        # hooks sit at the same sites that bump EngineStats, so this holds
        # under concurrency without reconciliation
        totals = engine.usage.tenants()["totals"]
        assert totals["prompt_tokens"] == engine.stats.prompt_tokens
        assert totals["generated_tokens"] == engine.stats.generated_tokens
        assert totals["requests"] == 5
        assert totals["device_seconds"] > 0
        assert totals["kv_page_seconds"] > 0

        # Σ journal == the same counters (the offline half): the session
        # state dir is shared, so filter to THIS engine's request ids
        ids = {r.request_id for r in reqs}
        recs = [
            r for r in us.read_usage_journal(n=10_000)
            if r["request_id"] in ids
        ]
        assert len(recs) == 5
        jt = us.journal_tenant_totals(recs)
        assert sum(b["prompt_tokens"] for b in jt.values()) == (
            engine.stats.prompt_tokens
        )
        assert sum(b["generated_tokens"] for b in jt.values()) == (
            engine.stats.generated_tokens
        )
        # per-tenant split matches the buckets, not just the grand total
        by_tenant = {}
        for row in engine.usage.tenants()["tenants"]:
            b = by_tenant.setdefault(row["tenant"], 0)
            by_tenant[row["tenant"]] = b + row["prompt_tokens"]
        assert {t: b["prompt_tokens"] for t, b in jt.items()} == by_tenant

        # device time was attributed to both phases by the clock brackets
        phases = engine.usage.summary()["phases"]
        assert phases["prefill"]["device_seconds"] > 0
        assert phases["decode"]["device_seconds"] > 0
        assert phases["total"]["bound"] in ("compute", "bandwidth")

    def test_prefix_cache_hit_reports_cached_tokens(self, engine):
        from modal_examples_tpu.serving.sampling import SamplingParams

        prompt = "a shared system prompt long enough to fill pages " * 2
        p = SamplingParams(max_tokens=2, temperature=0.0)
        first = engine.submit(prompt, p, tenant="cachet")
        for _ in engine.stream(first):
            pass
        second = engine.submit(prompt, p, tenant="cachet")
        for _ in engine.stream(second):
            pass
        # the repeat prompt serves its full pages from the prefix cache
        assert second.cached_prompt_tokens >= engine.cache.page_size
        assert second.cached_prompt_tokens <= engine.stats.prompt_tokens
        rec = [
            r for r in us.read_usage_journal(n=10_000)
            if r["request_id"] == second.request_id
        ]
        assert rec and rec[0]["cached_prompt_tokens"] == (
            second.cached_prompt_tokens
        )

    def test_openai_usage_carries_cached_tokens_field(self, engine):
        from modal_examples_tpu.serving import OpenAIServer

        srv = OpenAIServer(
            engine, model_name="tiny-usage", host="127.0.0.1", port=0
        )
        srv.start()
        try:
            body = json.dumps({
                "messages": [{"role": "user", "content": "count me"}],
                "max_tokens": 3,
                "temperature": 0.0,
            }).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/chat/completions",
                data=body, headers={"content-type": "application/json"},
            )
            with urllib.request.urlopen(req) as r:
                out = json.load(r)
        finally:
            srv.httpd.shutdown()
        usage = out["usage"]
        details = usage.get("prompt_tokens_details")
        assert details is not None, usage
        assert isinstance(details["cached_tokens"], int)
        assert 0 <= details["cached_tokens"] <= usage["prompt_tokens"]

    def test_gateway_usage_snapshot_sees_live_engine(self, engine):
        from modal_examples_tpu.web.gateway import _usage_snapshot

        snap = _usage_snapshot(last=5)
        eng = snap["engines"].get(engine.usage.replica)
        assert eng is not None, list(snap["engines"])
        assert "phases" in eng["roofline"]
        assert eng["totals"]["prompt_tokens"] == engine.stats.prompt_tokens
        assert isinstance(snap["records"], list)
        assert isinstance(snap["journal_totals"], dict)


# ---------------------------------------------------------------------------
# CLI surface (jax-free)
# ---------------------------------------------------------------------------


class TestCliUsage:
    def test_cmd_usage_json_reads_journal_and_metrics(
        self, tmp_path, capsys
    ):
        from modal_examples_tpu.core.cli import cmd_usage
        from modal_examples_tpu.observability.journal import named_journal

        j = named_journal("usage", path=tmp_path / "usage.jsonl")
        j.record({
            "request_id": "req-1", "tenant": "acme", "class": "default",
            "prompt_tokens": 40, "generated_tokens": 8,
            "cached_prompt_tokens": 0, "finish_reason": "stop",
        })
        # a pushed exposition carrying the per-tenant counters
        reg = Registry()
        reg.counter_inc(
            C.USAGE_PROMPT_TOKENS_TOTAL,
            40.0, {"tenant": "acme", "class": "default"},
        )
        reg.gauge_set(C.MFU, 0.25, {"phase": "total"})
        mdir = tmp_path / "metrics"
        mdir.mkdir()
        (mdir / "job1.prom").write_text(reg.expose())

        rc = cmd_usage(["--json", "--dir", str(tmp_path)])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["journal_totals"]["acme"]["prompt_tokens"] == 40
        assert out["records"][0]["request_id"] == "req-1"
        row = [t for t in out["tenants"] if t["tenant"] == "acme"]
        assert row and row[0]["prompt_tokens"] == 40.0
        assert out["roofline"]["total"]["mfu"] == 0.25

    def test_cmd_usage_text_renders_table(self, tmp_path, capsys):
        from modal_examples_tpu.core.cli import cmd_usage
        from modal_examples_tpu.observability.journal import named_journal

        named_journal("usage", path=tmp_path / "usage.jsonl").record({
            "request_id": "req-2", "tenant": "acme", "class": "batch",
            "prompt_tokens": 5, "generated_tokens": 1,
        })
        assert cmd_usage(["--dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "acme" in out


# ---------------------------------------------------------------------------
# benchdiff: utilization gates + hardware-identity refusal
# ---------------------------------------------------------------------------


def _bench_json(tmp_path, name, **extra):
    doc = {"metric": "m", "value": 100.0, "unit": "tok/s", **extra}
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


class TestBenchDiffIdentity:
    def test_mismatch_needs_both_sides_present(self):
        from modal_examples_tpu.utils import bench_diff as bd

        assert bd.identity_mismatches(
            {"backend": "cpu"}, {"backend": "tpu"}
        ) == ["backend: 'cpu' != 'tpu'"]
        # absent keys never disqualify: older files predate chip_note
        assert bd.identity_mismatches({}, {"backend": "tpu"}) == []
        assert bd.identity_mismatches(
            {"backend": "tpu", "chip_note": "wedged"},
            {"backend": "tpu", "chip_note": "wedged"},
        ) == []

    def test_run_diff_refuses_cross_hardware_compare(self, tmp_path, capsys):
        from modal_examples_tpu.utils.bench_diff import run_diff

        old = _bench_json(tmp_path, "old.json", backend="tpu")
        new = _bench_json(tmp_path, "new.json", backend="cpu")
        assert run_diff([old, new]) == 2
        out = capsys.readouterr().out
        assert "HARDWARE MISMATCH" in out
        assert "refusing" in out

    def test_allow_backend_mismatch_overrides_loudly(self, tmp_path, capsys):
        from modal_examples_tpu.utils.bench_diff import run_diff

        old = _bench_json(tmp_path, "old.json", backend="tpu")
        new = _bench_json(tmp_path, "new.json", backend="cpu")
        rc = run_diff([old, new, "--allow-backend-mismatch"])
        assert rc in (0, 1)  # the diff itself proceeds
        out = capsys.readouterr().out
        assert "HARDWARE MISMATCH" in out
        assert "--allow-backend-mismatch set" in out

    def test_same_hardware_diffs_quietly(self, tmp_path, capsys):
        from modal_examples_tpu.utils.bench_diff import run_diff

        old = _bench_json(tmp_path, "old.json", backend="cpu")
        new = _bench_json(tmp_path, "new.json", backend="cpu")
        assert run_diff([old, new]) == 0
        assert "MISMATCH" not in capsys.readouterr().out

    def test_utilization_metrics_are_gated(self, tmp_path):
        from modal_examples_tpu.utils.bench_diff import compare

        old = {"value": 100.0, "utilization": {
            "mfu": 0.40, "mbu": 0.70, "tokens_per_second_per_chip": 100.0,
        }}
        new = {"value": 100.0, "utilization": {
            "mfu": 0.10, "mbu": 0.70, "tokens_per_second_per_chip": 100.0,
        }}
        rows = {r["metric"]: r for r in compare(old, new)}
        # abs comparison, the shed-rate rule: 0.40 -> 0.10 is a regression
        assert rows["utilization.mfu"]["regressed"] is True
        assert rows["utilization.mbu"]["regressed"] is False
        assert "utilization.tokens_per_second_per_chip" in rows


# ---------------------------------------------------------------------------
# the mbu_collapse alert: guarded threshold
# ---------------------------------------------------------------------------


class TestMbuCollapseAlert:
    def _rule(self):
        from modal_examples_tpu.observability import alerts as al

        rules = [r for r in al.DEFAULT_RULES if r.name == "mbu_collapse"]
        assert len(rules) == 1
        return rules[0]

    def _evaluator(self, tmp_path):
        from modal_examples_tpu.observability import alerts as al

        class Src:
            records: list = []

            def recent(self, window_s=None):
                return list(self.records)

        src = Src()
        src.records = []
        ev = al.AlertEvaluator(
            (self._rule(),), source=src, registry=Registry(),
            journal_path=tmp_path / "alerts.jsonl",
        )
        return ev, src

    @staticmethod
    def _rec(at, mbu, slots):
        return {"at": at, "series": [
            [C.HBM_BW_UTIL, {"phase": "decode"}, "gauge", mbu, 0.0],
            [C.ACTIVE_SLOTS, {}, "gauge", slots, 0.0],
        ]}

    def test_idle_engine_never_fires(self, tmp_path):
        # zero MBU with zero slots is just an idle engine
        ev, src = self._evaluator(tmp_path)
        for at in (10.0, 40.0, 80.0):
            src.records.append(self._rec(at, 0.0, 0))
            assert ev.evaluate_once(now=at) == []

    def test_collapse_under_load_fires_after_for_s(self, tmp_path):
        ev, src = self._evaluator(tmp_path)
        src.records.append(self._rec(10.0, 0.0, 3))
        assert ev.evaluate_once(now=10.0) == []  # held 0s < for_s=20
        src.records.append(self._rec(31.0, 0.0, 3))
        out = ev.evaluate_once(now=31.0)
        assert [t["event"] for t in out] == ["fire"]
        # bandwidth flows again: hysteretic clear
        src.records.append(self._rec(32.0, 0.4, 3))
        assert ev.evaluate_once(now=32.0) == []
        src.records.append(self._rec(43.0, 0.4, 3))
        assert [t["event"] for t in ev.evaluate_once(now=43.0)] == ["clear"]

    def test_healthy_decode_never_fires(self, tmp_path):
        ev, src = self._evaluator(tmp_path)
        for at in (10.0, 35.0, 60.0):
            src.records.append(self._rec(at, 0.55, 3))
            assert ev.evaluate_once(now=at) == []
