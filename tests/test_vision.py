"""Vision detector tests (VERDICT #8): the JAX counterpart of the
reference's torch vision family (yolo/finetune_yolo.py fine-tune loop,
sam/segment_anything.py inference service). e2e contract: a train step
decreases the loss, and a short fine-tune on synthetic shapes localizes an
easy box with IoU > 0.5."""

import pytest

pytestmark = pytest.mark.slow  # heavyweight: excluded from the fast tier

import numpy as np


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def setup(jax):
    from modal_examples_tpu.models import vision

    cfg = vision.DetectorConfig(image_size=64, n_classes=3, width=16, depth=1)
    params = vision.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _xyxy_iou(a, b):
    x1, y1 = max(a[0], b[0]), max(a[1], b[1])
    x2, y2 = min(a[2], b[2]), min(a[3], b[3])
    inter = max(0.0, x2 - x1) * max(0.0, y2 - y1)
    area = lambda r: (r[2] - r[0]) * (r[3] - r[1])  # noqa: E731
    return inter / (area(a) + area(b) - inter + 1e-6)


class TestDetector:
    def test_forward_shapes(self, jax, setup):
        from modal_examples_tpu.models import vision

        cfg, params = setup
        batch = vision.synthetic_batch(jax.random.PRNGKey(1), 2, cfg)
        preds = vision.forward(params, batch["images"], cfg)
        G = cfg.grid
        assert preds["obj"].shape == (2, G, G)
        assert preds["cls"].shape == (2, G, G, 3)
        assert preds["ltrb"].shape == (2, G, G, 4)
        assert float(preds["ltrb"].min()) >= 0  # softplus distances

    def test_cell_targets_roundtrip(self, jax, setup):
        """decode_boxes on the rasterized targets must reproduce the input
        box (assignment and decoding are inverses at the positive cell)."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import vision

        cfg, _ = setup
        boxes = jnp.zeros((cfg.max_boxes, 4)).at[0].set(
            jnp.array([10.0, 18.0, 34.0, 40.0])
        )
        labels = jnp.zeros((cfg.max_boxes,), jnp.int32).at[0].set(2)
        mask = jnp.zeros((cfg.max_boxes,), bool).at[0].set(True)
        obj_t, cls_t, ltrb_t, pos = vision._cell_targets(boxes, labels, mask, cfg)
        assert int(pos.sum()) == 1
        gy, gx = np.unravel_index(int(np.argmax(np.asarray(obj_t))), obj_t.shape)
        assert int(cls_t[gy, gx]) == 2
        preds = {
            "obj": obj_t[None] * 100 - 50,  # logits: positive cell >> 0
            "cls": jnp.eye(3)[cls_t][None] * 10,
            "ltrb": ltrb_t[None],
        }
        bxs, scores, classes = vision.decode_boxes(preds, cfg)
        best = int(np.argmax(np.asarray(scores[0])))
        np.testing.assert_allclose(
            np.asarray(bxs[0, best]), [10.0, 18.0, 34.0, 40.0], atol=1e-3
        )
        assert int(classes[0, best]) == 2

    def test_train_step_decreases_loss(self, jax, setup):
        from modal_examples_tpu.models import vision
        from modal_examples_tpu.training import Trainer, make_optimizer

        cfg, _ = setup
        # fresh params: train_step donates the state, which would delete the
        # module fixture's buffers
        params = vision.init_params(jax.random.PRNGKey(0), cfg)
        batch = vision.synthetic_batch(jax.random.PRNGKey(2), 8, cfg)
        t = Trainer(
            lambda p, b: vision.detection_loss(p, b, cfg), make_optimizer(1e-3)
        )
        state = t.init_state(params)
        first = None
        for _ in range(10):
            state, m = t.train_step(state, batch)
            first = first if first is not None else float(m["loss"])
        assert float(m["loss"]) < first

    def test_short_finetune_localizes_golden_box(self, jax, setup):
        """Fine-tune briefly on the synthetic shapes, then the top
        detection on a held-out image must hit the true box with IoU > 0.5
        (the end-to-end check the reference does by WER/weights-roundtrip
        for ASR — here by localization quality)."""
        from modal_examples_tpu.models import vision
        from modal_examples_tpu.training import Trainer, make_optimizer

        cfg, _ = setup
        params = vision.init_params(jax.random.PRNGKey(0), cfg)
        t = Trainer(
            lambda p, b: vision.detection_loss(p, b, cfg), make_optimizer(3e-3)
        )
        state = t.init_state(params)
        for i in range(60):
            batch = vision.synthetic_batch(jax.random.PRNGKey(100 + i), 16, cfg)
            state, m = t.train_step(state, batch)

        held = vision.synthetic_batch(jax.random.PRNGKey(999), 4, cfg)
        preds = vision.forward(state.params, held["images"], cfg)
        boxes, scores, classes = vision.decode_boxes(preds, cfg)
        hits = 0
        for b in range(4):
            best = int(np.argmax(np.asarray(scores[b])))
            pred_box = np.asarray(boxes[b, best])
            true = np.asarray(held["boxes"][b][np.asarray(held["box_mask"][b])])
            iou = max(_xyxy_iou(pred_box, tb) for tb in true)
            hits += iou > 0.5
        assert hits >= 3, f"only {hits}/4 held-out images localized"

    def test_nms_dedupes_overlaps(self, setup):
        from modal_examples_tpu.models import vision

        boxes = np.array(
            [[10, 10, 30, 30], [11, 11, 31, 31], [50, 50, 60, 60]], np.float32
        )
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        classes = np.array([0, 0, 1])
        keep = vision.nms_host(boxes, scores, classes, iou_thresh=0.5)
        assert keep == [0, 2]
