"""Utils tier: prometheus registry/exposition, debugging contexts, docs
renderer details."""

import pytest


class TestPrometheus:
    def test_counter_and_gauge_exposition(self):
        from modal_examples_tpu.utils.prometheus import Registry

        reg = Registry()
        reg.counter_inc("reqs_total", labels={"route": "a"}, help="requests")
        reg.counter_inc("reqs_total", labels={"route": "a"})
        reg.counter_inc("reqs_total", labels={"route": "b"})
        reg.gauge_set("active_slots", 7)
        text = reg.expose()
        assert '# TYPE reqs_total counter' in text
        assert 'reqs_total{route="a"} 2.0' in text
        assert 'reqs_total{route="b"} 1.0' in text
        assert "active_slots 7" in text

    def test_push_and_aggregate(self):
        import modal_examples_tpu as mtpu
        from modal_examples_tpu.utils.prometheus import (
            Registry, aggregate_exposition, push_to_dict,
        )

        with mtpu.Dict.ephemeral() as store:
            r1, r2 = Registry(), Registry()
            r1.counter_inc("x_total", 3)
            r2.counter_inc("x_total", 4)
            push_to_dict(store, "job1", r1)
            push_to_dict(store, "job2", r2)
            merged = aggregate_exposition(store)
        # the merge is itself a valid exposition: one TYPE header, job
        # labels distinguishing sources, no free-form comment lines
        assert merged.count("# TYPE x_total counter") == 1
        assert 'x_total{job="job1"} 3.0' in merged
        assert 'x_total{job="job2"} 4.0' in merged
        assert "# job:" not in merged


class TestTracking:
    @pytest.mark.slow
    def test_jsonl_roundtrip_and_tb_files(self, tmp_path):
        from modal_examples_tpu.utils.tracking import RunLogger

        with RunLogger(tmp_path / "run1") as log:
            for step in range(3):
                log.log(step, {"loss": 2.0 - step * 0.5, "lr": 1e-3})
        hist = RunLogger(tmp_path / "run1", tensorboard=False).history()
        assert [h["step"] for h in hist] == [0, 1, 2]
        assert hist[-1]["loss"] == 0.5 if False else hist[-1]["loss"] == 1.0
        # tensorboard event file written (package is in the image)
        assert list((tmp_path / "run1").glob("events.out.tfevents.*"))

    def test_volume_commit_on_close(self, state_dir):
        import modal_examples_tpu as mtpu
        from modal_examples_tpu.utils.tracking import RunLogger

        vol = mtpu.Volume.from_name("runlog-vol", create_if_missing=True)
        v0 = vol.version
        with RunLogger(vol.local_path / "exp", volume=vol, tensorboard=False) as log:
            log.log(1, {"x": 1})
        assert vol.version == v0 + 1


class TestRopeScaling:
    def test_llama3_scaling_changes_long_range_only(self, jax_cpu):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from modal_examples_tpu.models import layers

        pos = jnp.asarray([[0, 8000]])  # scaling acts at long range
        base_cos, _ = layers.rotary_embedding(pos, 64, 500000.0)
        scaling = {
            "factor": 8.0, "low_freq_factor": 1.0, "high_freq_factor": 4.0,
            "original_max_position_embeddings": 8192,
        }
        scaled_cos, _ = layers.rotary_embedding(
            pos, 64, 500000.0, rope_scaling=scaling
        )
        diff = np.abs(np.asarray(base_cos - scaled_cos))[0, -1]  # pos 8000
        # highest-frequency channels (early dims) unchanged; stretched bands
        # (mid/low freq) visibly rotated at long range
        assert diff[0] < 1e-6
        assert diff.max() > 0.1
        assert diff[-1] > 1e-4  # lowest channel moves too (cos is flat there)

    def test_from_hf_config_parses_rope_scaling(self, tmp_path):
        import json

        from modal_examples_tpu.models import llama

        cfg_json = {
            "vocab_size": 1000, "hidden_size": 64, "num_hidden_layers": 2,
            "num_attention_heads": 2, "intermediate_size": 128,
            "rope_scaling": {
                "rope_type": "llama3", "factor": 8.0,
                "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                "original_max_position_embeddings": 8192,
            },
        }
        p = tmp_path / "config.json"
        p.write_text(json.dumps(cfg_json))
        cfg = llama.LlamaConfig.from_hf_config(p)
        assert cfg.rope_scaling is not None
        assert dict(cfg.rope_scaling)["factor"] == 8.0
        # forward runs with scaling active
        import jax

        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, 32), 0, 1000)
        out = llama.forward(params, toks, cfg, attn_impl="xla")
        assert out.shape == (1, 32, 1000)


class TestRouting:
    def test_rendezvous_stable_and_balanced(self):
        from modal_examples_tpu.web.routing import rendezvous_pick, rendezvous_rank

        nodes = [f"replica-{i}" for i in range(4)]
        picks = {f"session-{k}": rendezvous_pick(f"session-{k}", nodes) for k in range(200)}
        # deterministic
        assert all(
            rendezvous_pick(k, nodes) == v for k, v in picks.items()
        )
        # reasonably balanced
        from collections import Counter

        counts = Counter(picks.values())
        assert all(20 <= c <= 80 for c in counts.values()), counts
        # minimal disruption: removing one node only moves its keys
        survivors = nodes[:-1]
        moved = sum(
            1
            for k, v in picks.items()
            if v != "replica-3" and rendezvous_pick(k, survivors) != v
        )
        assert moved == 0
        # failover order starts with the primary
        assert rendezvous_rank("session-1", nodes)[0] == picks["session-1"]


class TestRestrictedVolume:
    def test_view_confined_to_subtree(self, state_dir):
        import modal_examples_tpu as mtpu

        vol = mtpu.Volume.from_name("acl-vol", create_if_missing=True)
        vol.write_file("users/alice/doc.txt", b"alice data")
        vol.write_file("users/bob/doc.txt", b"bob data")
        alice = vol.restricted("users/alice")
        assert alice.read_file("doc.txt") == b"alice data"
        alice.write_file("new.txt", b"x")
        assert vol.read_file("users/alice/new.txt") == b"x"
        with pytest.raises(PermissionError):
            alice.read_file("../bob/doc.txt")


class TestDebugging:
    def test_check_numerics_names_bad_leaf(self, jax_cpu):
        import jax.numpy as jnp

        from modal_examples_tpu.utils.debugging import check_numerics

        good = {"a": jnp.ones(3), "b": {"c": jnp.zeros(2)}}
        check_numerics(good)
        bad = {"a": jnp.ones(3), "b": {"c": jnp.array([1.0, jnp.nan])}}
        with pytest.raises(FloatingPointError, match="'c'"):
            check_numerics(bad, "params")

    def test_debug_nans_context(self, jax_cpu):
        import jax
        import jax.numpy as jnp

        from modal_examples_tpu.utils.debugging import debug_nans

        with debug_nans():
            with pytest.raises(FloatingPointError):
                jax.jit(lambda x: 0.0 / x)(jnp.zeros(())).block_until_ready()
        # restored afterwards: same op silently yields nan
        out = jax.jit(lambda x: 0.0 / x)(jnp.zeros(()))
        assert bool(jnp.isnan(out))

    def test_eager_mode(self, jax_cpu):
        import jax

        from modal_examples_tpu.utils.debugging import eager_mode

        with eager_mode():
            # inside disable_jit, tracing doesn't happen; python side effects run
            seen = []

            def f(x):
                seen.append(1)
                return x + 1

            jax.jit(f)(1)
            jax.jit(f)(2)
        assert len(seen) == 2

    def test_tree_summary(self, jax_cpu):
        import jax.numpy as jnp

        from modal_examples_tpu.utils.debugging import tree_summary

        s = tree_summary({"w": jnp.ones((2, 3))})
        assert "(2, 3)" in s and "|x|=" in s


class TestByteTokenizer:
    def test_round_trip_is_length_stable(self):
        """Decode -> re-encode must return EXACTLY the original byte ids —
        the old errors="replace" turned invalid UTF-8 bytes into U+FFFD
        (3 bytes re-encoded), inflating every max_tokens round-trip count
        (the pre-existing tier-1 failure this fixes)."""
        from modal_examples_tpu.utils.tokenizer import ByteTokenizer

        tok = ByteTokenizer()
        # 0xC3 alone is an invalid UTF-8 sequence; 0xF0 starts a 4-byte one
        for ids in ([0xC3], [0xF0, 0x48], [0x68, 0x69], list(range(256))):
            text = tok.decode(ids)
            assert tok.encode(text, add_bos=False) == ids
        # special ids are dropped by decode, never inflated
        assert tok.encode(
            tok.decode([tok.bos_id, 0x41, tok.eos_id]), add_bos=False
        ) == [0x41]

    def test_valid_utf8_unchanged(self):
        from modal_examples_tpu.utils.tokenizer import ByteTokenizer

        tok = ByteTokenizer()
        s = "héllo wörld ✓"
        assert tok.decode(tok.encode(s, add_bos=False)) == s
