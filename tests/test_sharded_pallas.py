"""Sharded Pallas fast paths under tensor parallelism (ROADMAP open item
#2, round 7): the ragged decode kernels, the KV scatter, and flash prefill
run inside shard_map over the kv-head mesh axis — on a CPU mesh
(xla_force_host_platform_device_count, interpreter-mode kernels), so the
multi-chip serving path is exercised by the fast tier without TPUs.

Contracts proven here:
- op level: each sharded wrapper is BIT-exact vs the single-device kernel
  (attention is head-local, scatter is head-local, int8 scales are per
  token-head — sharding the head axis changes no math);
- plan level: ``paged_impl_plan(mesh=...)`` resolves legality against the
  PER-SHARD head counts and reports the variant each device actually runs;
- engine level: ``LLMEngine(mesh=..., paged_impl="pallas",
  scatter_impl="pallas")`` constructs and serves (the old mesh×pallas
  ValueError is gone), token-identical to the sharded XLA path for plain
  caches and within the documented tolerance for int8 — and composes with
  speculative decoding.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def mesh2(jax):
    from modal_examples_tpu.parallel import make_mesh

    return make_mesh({"tensor": 2}, devices=jax.devices()[:2])


def _mk_cache(jax, L, n_pages, ps, Hkv, D, kv_dtype, seed=0):
    import jax.numpy as jnp

    from modal_examples_tpu.ops import quantize_kv

    k = jax.random.normal(
        jax.random.PRNGKey(seed), (L, n_pages, ps, Hkv, D), jnp.float32
    )
    v = jax.random.normal(
        jax.random.PRNGKey(seed + 1), (L, n_pages, ps, Hkv, D), jnp.float32
    )
    if kv_dtype == "int8":
        return quantize_kv(k), quantize_kv(v)
    return k.astype(kv_dtype), v.astype(kv_dtype)


class TestShardedKernelOps:
    """Direct wrapper-vs-kernel exactness on the 2-device CPU mesh."""

    @pytest.mark.parametrize("kv_dtype", ["float32", "int8"])
    @pytest.mark.parametrize("variant", ["flat", "grouped"])
    def test_sharded_ragged_matches_single_device(
        self, jax, mesh2, kv_dtype, variant
    ):
        import jax.numpy as jnp

        from modal_examples_tpu.ops import (
            paged_decode_attention_ragged,
            sharded_ragged_decode,
        )

        L, Pn, ps, Hkv, D, B, Hq = 2, 9, 16, 2, 8, 2, 4
        kp, vp = _mk_cache(jax, L, Pn, ps, Hkv, D, kv_dtype)
        q = jax.random.normal(jax.random.PRNGKey(2), (B, Hq, D), jnp.float32)
        k_new = jax.random.normal(
            jax.random.PRNGKey(3), (B, Hkv, D), jnp.float32
        )
        v_new = jax.random.normal(
            jax.random.PRNGKey(4), (B, Hkv, D), jnp.float32
        )
        tables = jnp.asarray(
            1 + np.arange(B * 4).reshape(B, 4), jnp.int32
        )
        prefix = jnp.asarray([17, 33], jnp.int32)
        layer = jnp.int32(1)

        ref = paged_decode_attention_ragged(
            q, kp, vp, layer, tables, prefix, k_new, v_new, variant=variant
        )
        out = jax.jit(
            lambda *a: sharded_ragged_decode(mesh2, *a, variant=variant)
        )(q, kp, vp, layer, tables, prefix, k_new, v_new)
        if variant == "grouped":
            # per-kv-head contractions are untouched by head sharding: the
            # sharded kernel is BIT-exact vs single-device — int8 too (the
            # scales are per token-head)
            np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
        else:
            # flat's block-diagonal matmul contracts over W = ps*Hkv
            # columns; halving Hkv per shard changes the f32 summation
            # tree, so flat is ulp-exact (measured 7e-9), not bit-exact
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=1e-6, rtol=0
            )

    @pytest.mark.parametrize("kv_dtype", ["float32", "int8"])
    def test_sharded_scatter_matches_xla(self, jax, mesh2, kv_dtype):
        import jax.numpy as jnp

        from modal_examples_tpu.ops import (
            is_quantized,
            kv_scatter,
            sharded_scatter_kv_pages,
        )

        L, Pn, ps, Hkv, D, B = 2, 7, 16, 2, 8, 3
        kp, vp = _mk_cache(jax, L, Pn, ps, Hkv, D, kv_dtype, seed=5)
        k_all = jax.random.normal(
            jax.random.PRNGKey(7), (L, B, Hkv, D), jnp.float32
        )
        v_all = jax.random.normal(
            jax.random.PRNGKey(8), (L, B, Hkv, D), jnp.float32
        )
        page_idx = jnp.asarray([1, 3, 5], jnp.int32)
        slot = jnp.asarray([0, 7, 15], jnp.int32)

        ref_k = kv_scatter(kp, k_all, page_idx, slot)
        ref_v = kv_scatter(vp, v_all, page_idx, slot)
        ok, ov = jax.jit(
            lambda *a: sharded_scatter_kv_pages(mesh2, *a)
        )(kp, vp, k_all, v_all, page_idx, slot)
        for got, want in ((ok, ref_k), (ov, ref_v)):
            if is_quantized(want):
                np.testing.assert_array_equal(
                    np.asarray(got.data), np.asarray(want.data)
                )
                np.testing.assert_array_equal(
                    np.asarray(got.scale), np.asarray(want.scale)
                )
            else:
                np.testing.assert_array_equal(
                    np.asarray(got), np.asarray(want)
                )

    def test_sharded_flash_matches_single_device(self, jax, mesh2):
        import jax.numpy as jnp

        from modal_examples_tpu.ops import (
            flash_attention,
            flash_attention_chunked,
            sharded_flash_attention,
            sharded_flash_attention_chunked,
        )

        B, Hq, Hkv, S, D = 2, 4, 2, 32, 8
        q = jax.random.normal(
            jax.random.PRNGKey(0), (B, Hq, S, D), jnp.float32
        )
        k = jax.random.normal(
            jax.random.PRNGKey(1), (B, Hkv, S, D), jnp.float32
        )
        v = jax.random.normal(
            jax.random.PRNGKey(2), (B, Hkv, S, D), jnp.float32
        )
        ref = flash_attention(q, k, v, True)
        out = jax.jit(lambda q, k, v: sharded_flash_attention(mesh2, q, k, v))(
            q, k, v
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

        # chunked (rectangular) prefill: q chunk at q_offset vs full prefix
        qc = q[:, :, :16, :]
        ref_c = flash_attention_chunked(qc, k, v, q_offset=16)
        out_c = jax.jit(
            lambda q, k, v: sharded_flash_attention_chunked(
                mesh2, q, k, v, q_offset=16
            )
        )(qc, k, v)
        np.testing.assert_array_equal(np.asarray(out_c), np.asarray(ref_c))

    def test_no_mesh_falls_through(self, jax):
        """mesh=None (or a 1-wide tensor axis) must be the plain kernel —
        the single-chip path stays byte-for-byte what it was."""
        import jax.numpy as jnp

        from modal_examples_tpu.ops import (
            paged_decode_attention_ragged,
            sharded_ragged_decode,
        )

        L, Pn, ps, Hkv, D, B, Hq = 1, 5, 16, 2, 8, 1, 4
        kp, vp = _mk_cache(jax, L, Pn, ps, Hkv, D, "float32")
        q = jax.random.normal(jax.random.PRNGKey(2), (B, Hq, D), jnp.float32)
        k_new = jax.random.normal(
            jax.random.PRNGKey(3), (B, Hkv, D), jnp.float32
        )
        v_new = jax.random.normal(
            jax.random.PRNGKey(4), (B, Hkv, D), jnp.float32
        )
        tables = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        prefix = jnp.asarray([21], jnp.int32)
        out = sharded_ragged_decode(
            None, q, kp, vp, jnp.int32(0), tables, prefix, k_new, v_new
        )
        ref = paged_decode_attention_ragged(
            q, kp, vp, jnp.int32(0), tables, prefix, k_new, v_new
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_indivisible_heads_raise(self, jax):
        """Hkv % tp != 0 is the one genuinely illegal sharding — loud
        ValueError, not a wrong-answer shard_map."""
        import jax.numpy as jnp

        from modal_examples_tpu.ops import sharded_ragged_decode
        from modal_examples_tpu.parallel import make_mesh

        mesh4 = make_mesh({"tensor": 4}, devices=jax.devices()[:4])
        kp, vp = _mk_cache(jax, 1, 5, 16, 2, 8, "float32")
        q = jnp.zeros((1, 4, 8), jnp.float32)
        kv = jnp.zeros((1, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="divisible"):
            sharded_ragged_decode(
                mesh4, q, kp, vp, jnp.int32(0),
                jnp.zeros((1, 4), jnp.int32), jnp.zeros((1,), jnp.int32),
                kv, kv,
            )


class TestPerShardLegality:
    """``paged_impl_plan(mesh=...)`` resolves the variant against the
    SHARD-local head counts — the legality table the kernels implicitly
    apply inside shard_map, mirrored in the reporting layer."""

    @pytest.mark.parametrize(
        "n_kv_heads,n_heads,tp,kv_dtype,want_attn,want_variant",
        [
            # flat needs Hkv%16 (bf16) per SHARD: 32 heads stay flat at
            # tp=2 (16 per shard) but 16 heads drop to grouped at tp=2
            (32, 32, 1, "bfloat16", "ragged", "flat"),
            (32, 32, 2, "bfloat16", "ragged", "flat"),
            (16, 32, 2, "bfloat16", "ragged", "grouped"),
            # int8 flat needs Hkv%32 per shard: 32 heads are flat on one
            # chip, grouped the moment the shard halves them
            (32, 32, 1, "int8", "ragged", "flat"),
            (32, 32, 2, "int8", "ragged", "grouped"),
            # GQA (llama-3 geometry) is grouped everywhere
            (8, 32, 2, "bfloat16", "ragged", "grouped"),
            (2, 4, 2, "float32", "ragged", "grouped"),
            # heads not divisible by tp: loud downgrade to the XLA gather
            (2, 4, 4, "bfloat16", "xla-gather", None),
        ],
    )
    def test_plan_table(
        self, jax, n_kv_heads, n_heads, tp, kv_dtype, want_attn, want_variant
    ):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.parallel import make_mesh

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=n_heads * 128, n_layers=1,
            n_heads=n_heads, n_kv_heads=n_kv_heads, ffn_dim=128,
        )
        mesh = (
            make_mesh({"tensor": tp}, devices=jax.devices()[:tp])
            if tp > 1
            else None
        )
        plan = llama.paged_impl_plan(
            cfg, 16, "pallas", "pallas", kv_dtype=kv_dtype, mesh=mesh,
            warn=False,
        )
        assert plan["tp"] == tp
        assert plan["attention"] == want_attn
        assert plan["ragged_variant"] == want_variant
        if want_attn == "xla-gather":
            assert plan["scatter"] == "xla"
            assert any("tp=" in m for m in plan["downgraded"])
        else:
            assert plan["scatter"] == "pallas"
            assert plan["downgraded"] == []


class TestEngineShardedPallas:
    """The acceptance contract: mesh= + pallas impls construct and serve,
    token-identical to the sharded XLA path (plain caches) / within the
    documented tolerance (int8)."""

    def _cfg_params(self, jax):
        from modal_examples_tpu.models import llama

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        return cfg, llama.init_params(jax.random.PRNGKey(0), cfg)

    def test_tp2_pallas_matches_tp2_xla_bitexact(self, jax, mesh2):
        import jax.numpy as jnp

        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg, params = self._cfg_params(jax)
        kw = dict(
            max_slots=2, max_model_len=64, page_size=16,
            prefill_buckets=(32,), seed=0, kv_dtype=jnp.bfloat16,
        )
        sp = SamplingParams(max_tokens=16, temperature=0.0)
        prompts = ["sharded pallas decode", "fast path under tp"]
        xla_tp = LLMEngine(cfg, params, mesh=mesh2, **kw)
        pal_tp = LLMEngine(cfg, params, mesh=mesh2, paged_impl="pallas", **kw)
        # the acceptance-criterion spelling: both impls as engine kwargs
        pal_sc = LLMEngine(
            cfg, params, mesh=mesh2, paged_impl="pallas",
            scatter_impl="pallas", **kw,
        )
        try:
            want = [xla_tp.generate(p, sp) for p in prompts]
            got = [pal_tp.generate(p, sp) for p in prompts]
            got_sc = [pal_sc.generate(p, sp) for p in prompts]
            assert want == got == got_sc
            assert pal_tp.error_count == 0 and pal_sc.error_count == 0
            assert pal_tp.impl_plan["attention"] == "ragged"
            assert pal_tp.impl_plan["tp"] == 2
            assert pal_sc.impl_plan["scatter"] == "pallas"
            assert len(pal_tp.cache.k_pages.sharding.device_set) == 2
        finally:
            xla_tp.stop()
            pal_tp.stop()
            pal_sc.stop()

    def test_tp2_pallas_int8_tolerance(self, jax, mesh2):
        """int8 × TP × pallas: all four cache leaves shard, the plan
        reports the per-shard variant, and decode logits stay within the
        documented int8 tolerance of the sharded-XLA int8 path (the in-VMEM
        dequant and the gather dequant compute the same math)."""
        import functools

        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.ops.kv_quant import shard_kv
        from modal_examples_tpu.serving import LLMEngine, SamplingParams
        from modal_examples_tpu.serving.engine import _shard_params
        from modal_examples_tpu.serving.kv_cache import PagedKVCache
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        cfg, params = self._cfg_params(jax)
        eng = LLMEngine(
            cfg, params, mesh=mesh2, paged_impl="pallas", max_slots=2,
            max_model_len=64, page_size=16, prefill_buckets=(32,), seed=0,
            kv_dtype="int8",
        )
        try:
            out = eng.generate(
                "quantized sharded kernels",
                SamplingParams(max_tokens=12, temperature=0.0),
            )
            assert isinstance(out, str) and eng.error_count == 0
            assert eng.impl_plan["kv_dtype"] == "int8"
            # Hkv//tp = 1: int8 flat needs Hkv%32 -> grouped per shard
            assert eng.impl_plan["ragged_variant"] == "grouped"
            kp = eng.cache.k_pages
            assert len(kp.data.sharding.device_set) == 2
            assert len(kp.scale.sharding.device_set) == 2
        finally:
            eng.stop()

        # direct decode_step: sharded pallas vs sharded xla, same int8 cache
        sharded_params = _shard_params(params, cfg, mesh2)
        toks = jax.random.randint(jax.random.PRNGKey(9), (2, 16), 0, 128)
        tables = jnp.asarray(
            1 + np.arange(2 * 4).reshape(2, 4), jnp.int32
        )
        seq_lens = jnp.asarray([12, 16], jnp.int32)
        active = jnp.ones((2,), bool)

        def run(impl):
            cache = PagedKVCache.create(
                n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
                head_dim=cfg.head_dim, n_pages=9, page_size=16,
                kv_dtype="int8", prefer_native=False,
            )
            dsh = NamedSharding(mesh2, P(None, None, None, "tensor", None))
            ssh = NamedSharding(mesh2, P(None, None, None, "tensor"))
            kp = shard_kv(cache.k_pages, dsh, ssh)
            vp = shard_kv(cache.v_pages, dsh, ssh)
            lo, kp, vp = jax.jit(
                functools.partial(
                    llama.prefill, cfg=cfg, attn_impl="flash", mesh=mesh2
                )
            )(sharded_params, toks, kp, vp, tables, seq_lens)
            nxt = jnp.argmax(lo, -1).astype(jnp.int32)
            l2, _, _ = jax.jit(
                functools.partial(
                    llama.decode_step, cfg=cfg, impl=impl, mesh=mesh2
                )
            )(sharded_params, nxt, seq_lens, kp, vp, tables, active)
            return np.asarray(l2)

        l_pallas, l_xla = run("pallas"), run("xla")
        assert float(np.max(np.abs(l_pallas - l_xla))) < 1e-4

    def test_spec_tp_int8_pallas_compose(self, jax, mesh2):
        """The full stack composes: speculative decoding × tensor
        parallelism × int8 KV × the sharded pallas kernels — draft chain,
        target verify, and both caches' scatters all run under the same
        sharded jit without error (token exactness deliberately NOT
        asserted: int8 + psum reordering, docs/kv_cache.md)."""
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg, params = self._cfg_params(jax)
        eng = LLMEngine(
            cfg, params, mesh=mesh2, paged_impl="pallas",
            speculative=(cfg, 2), draft_params=params, max_slots=2,
            max_model_len=64, page_size=16, prefill_buckets=(32,), seed=0,
            kv_dtype="int8",
        )
        try:
            out = eng.generate(
                "compose spec tp int8 pallas",
                SamplingParams(max_tokens=12, temperature=0.0),
            )
            assert isinstance(out, str) and out
            assert eng.error_count == 0, eng.error_log
            # identical draft == target: proposals must mostly be accepted
            assert eng.stats.acceptance_rate() > 0.5
        finally:
            eng.stop()
