"""Test harness configuration.

Forces the CPU backend with an 8-device virtual mesh (the reference has no
fake backend — SURVEY.md §4 calls out that we add one so multi-chip SPMD
paths are testable without TPUs: ``xla_force_host_platform_device_count``),
and isolates the framework's state dir per test session.
"""

import os
import sys
import tempfile
from pathlib import Path

_REPO_ROOT = str(Path(__file__).resolve().parent.parent)
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# Must happen before any jax import anywhere in the test process.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Isolate the local control plane (volumes/dicts/queues/apps) per test
# session.
_state_tmp = tempfile.mkdtemp(prefix="mtpu-test-state-")
os.environ.setdefault("MTPU_STATE_DIR", _state_tmp)

# Engine strict mode: a scheduler-loop exception stops the engine and
# releases callers with finish_reason="error" instead of being swallowed
# (the round-2 flake postmortem — NOTES.md "engine flake closeout").
os.environ.setdefault("MTPU_ENGINE_STRICT", "1")

# Persistent XLA compile cache (utils/compile_cache.py): the suite is
# compile-bound on CPU, so warm runs trade recompiles for disk hits. jax
# reads these env vars natively, including in executor child processes.
from modal_examples_tpu.utils.compile_cache import cache_dir as _cache_dir

_cache = _cache_dir()  # None = disabled via MTPU_COMPILE_CACHE; owns policy
if _cache is not None:
    Path(_cache).mkdir(parents=True, exist_ok=True)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache)
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import pytest  # noqa: E402


@pytest.fixture()
def state_dir():
    return Path(os.environ["MTPU_STATE_DIR"])


def force_cpu_jax():
    """Import jax pinned to CPU even with the axon TPU plugin registered."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return jax


@pytest.fixture(scope="session")
def jax_cpu():
    return force_cpu_jax()


@pytest.fixture(scope="session", autouse=True)
def _engine_error_sentinel():
    """Assert that NO engine anywhere in the suite recorded a scheduler
    exception — the regression net for the round-2 intermittent
    output-mismatch flake (NOTES.md). Reads the eagerly-recorded class-level
    report list, so engines garbage-collected mid-session are still
    covered."""
    yield
    try:
        from modal_examples_tpu.serving.engine import LLMEngine
    except Exception:
        return
    reports = list(LLMEngine._error_reports)
    assert not reports, f"engines recorded scheduler errors: {reports}"
