"""DiT diffusion + PNG utility tests."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


class TestPNG:
    def test_roundtrip(self):
        from modal_examples_tpu.utils.images import from_png, to_png

        img = np.random.default_rng(0).integers(0, 255, (16, 24, 3), np.uint8)
        assert (from_png(to_png(img)) == img).all()

    def test_float_range_conversion(self):
        from modal_examples_tpu.utils.images import from_png, to_png

        img = np.full((8, 8, 3), -1.0, np.float32)  # [-1,1] convention
        out = from_png(to_png(img))
        assert out.max() == 0


class TestDiT:
    def test_patchify_roundtrip(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import diffusion

        cfg = diffusion.DiTConfig.tiny()
        x = jnp.arange(2 * 16 * 16 * 3, dtype=jnp.float32).reshape(2, 16, 16, 3)
        p = diffusion.patchify(x, cfg)
        assert p.shape == (2, cfg.n_patches, cfg.patch_dim)
        np.testing.assert_array_equal(
            np.asarray(diffusion.unpatchify(p, cfg)), np.asarray(x)
        )

    def test_forward_shape(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import diffusion

        cfg = diffusion.DiTConfig.tiny()
        params = diffusion.init_params(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
        t = jnp.array([0.3, 0.9])
        text = jax.random.normal(jax.random.PRNGKey(2), (2, 8, cfg.text_dim))
        v = diffusion.forward(params, x, t, text, cfg)
        assert v.shape == x.shape

    def test_zero_init_outputs_zero_velocity(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import diffusion

        cfg = diffusion.DiTConfig.tiny()
        params = diffusion.init_params(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16, 3))
        text = jax.random.normal(jax.random.PRNGKey(2), (1, 8, cfg.text_dim))
        v = diffusion.forward(params, x, jnp.array([0.5]), text, cfg)
        # adaLN-zero + zero-init final proj: the raw model is the zero flow
        assert float(jnp.abs(v).max()) == 0.0

    def test_flow_loss_decreases(self, jax):
        from modal_examples_tpu.models import diffusion
        from modal_examples_tpu.training import Trainer, make_optimizer

        cfg = diffusion.DiTConfig.tiny()
        params = diffusion.init_params(jax.random.PRNGKey(0), cfg)
        images = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3)) * 0.5
        text = jax.random.normal(jax.random.PRNGKey(2), (8, 8, cfg.text_dim))

        def loss_fn(p, batch):
            return diffusion.flow_loss(p, batch["rng"], images, text, cfg)

        t = Trainer(loss_fn, make_optimizer(1e-3))
        state = t.init_state(params)
        first = None
        key = jax.random.PRNGKey(3)
        for _ in range(10):
            key, sub = jax.random.split(key)
            state, m = t.train_step(state, {"rng": sub})
            first = first if first is not None else float(m["loss"])
        assert float(m["loss"]) < first

    def test_sample_shape_and_range(self, jax):
        from modal_examples_tpu.models import diffusion

        cfg = diffusion.DiTConfig.tiny()
        params = diffusion.init_params(jax.random.PRNGKey(0), cfg)
        text = jax.random.normal(jax.random.PRNGKey(2), (2, 8, cfg.text_dim))
        out = diffusion.sample(
            params, jax.random.PRNGKey(1), text, cfg, steps=2, guidance=1.5
        )
        assert out.shape == (2, 16, 16, 3)
        assert float(np.abs(np.asarray(out)).max()) <= 1.0