"""DiT diffusion + MMDiT (SD3-class) + PNG utility tests."""

import pytest

pytestmark = pytest.mark.slow  # heavyweight: excluded from the fast tier

import numpy as np


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


class TestPNG:
    def test_roundtrip(self):
        from modal_examples_tpu.utils.images import from_png, to_png

        img = np.random.default_rng(0).integers(0, 255, (16, 24, 3), np.uint8)
        assert (from_png(to_png(img)) == img).all()

    def test_float_range_conversion(self):
        from modal_examples_tpu.utils.images import from_png, to_png

        img = np.full((8, 8, 3), -1.0, np.float32)  # [-1,1] convention
        out = from_png(to_png(img))
        assert out.max() == 0


class TestDiT:
    def test_patchify_roundtrip(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import diffusion

        cfg = diffusion.DiTConfig.tiny()
        x = jnp.arange(2 * 16 * 16 * 3, dtype=jnp.float32).reshape(2, 16, 16, 3)
        p = diffusion.patchify(x, cfg)
        assert p.shape == (2, cfg.n_patches, cfg.patch_dim)
        np.testing.assert_array_equal(
            np.asarray(diffusion.unpatchify(p, cfg)), np.asarray(x)
        )

    def test_forward_shape(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import diffusion

        cfg = diffusion.DiTConfig.tiny()
        params = diffusion.init_params(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
        t = jnp.array([0.3, 0.9])
        text = jax.random.normal(jax.random.PRNGKey(2), (2, 8, cfg.text_dim))
        v = diffusion.forward(params, x, t, text, cfg)
        assert v.shape == x.shape

    def test_zero_init_outputs_zero_velocity(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import diffusion

        cfg = diffusion.DiTConfig.tiny()
        params = diffusion.init_params(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16, 3))
        text = jax.random.normal(jax.random.PRNGKey(2), (1, 8, cfg.text_dim))
        v = diffusion.forward(params, x, jnp.array([0.5]), text, cfg)
        # adaLN-zero + zero-init final proj: the raw model is the zero flow
        assert float(jnp.abs(v).max()) == 0.0

    def test_flow_loss_decreases(self, jax):
        from modal_examples_tpu.models import diffusion
        from modal_examples_tpu.training import Trainer, make_optimizer

        cfg = diffusion.DiTConfig.tiny()
        params = diffusion.init_params(jax.random.PRNGKey(0), cfg)
        images = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3)) * 0.5
        text = jax.random.normal(jax.random.PRNGKey(2), (8, 8, cfg.text_dim))

        def loss_fn(p, batch):
            return diffusion.flow_loss(p, batch["rng"], images, text, cfg)

        t = Trainer(loss_fn, make_optimizer(1e-3))
        state = t.init_state(params)
        first = None
        key = jax.random.PRNGKey(3)
        for _ in range(10):
            key, sub = jax.random.split(key)
            state, m = t.train_step(state, {"rng": sub})
            first = first if first is not None else float(m["loss"])
        assert float(m["loss"]) < first

    def test_sample_shape_and_range(self, jax):
        from modal_examples_tpu.models import diffusion

        cfg = diffusion.DiTConfig.tiny()
        params = diffusion.init_params(jax.random.PRNGKey(0), cfg)
        text = jax.random.normal(jax.random.PRNGKey(2), (2, 8, cfg.text_dim))
        out = diffusion.sample(
            params, jax.random.PRNGKey(1), text, cfg, steps=2, guidance=1.5
        )
        assert out.shape == (2, 16, 16, 3)
        assert float(np.abs(np.asarray(out)).max()) <= 1.0

def _save_diffusers_mmdit(tmp_path, params, cfg):
    """Inverse of load_mmdit_hf_weights: write our tree under diffusers
    SD3Transformer2DModel names (torch [out, in] layout), including the
    context_pre_only final block when the config has one."""
    from safetensors.numpy import save_file

    raw = {}

    def put_lin(name, w, bias):
        raw[name + ".weight"] = np.asarray(w, np.float32).T.copy()
        raw[name + ".bias"] = np.asarray(bias, np.float32).copy()

    D, C, p = cfg.dim, cfg.channels, cfg.patch
    pp = np.asarray(params["patch_proj"], np.float32)  # [p*p*C, D]
    raw["pos_embed.proj.weight"] = (
        pp.reshape(p, p, C, D).transpose(3, 2, 0, 1).copy()
    )
    raw["pos_embed.proj.bias"] = np.asarray(params["patch_bias"], np.float32)
    raw["pos_embed.pos_embed"] = np.asarray(params["pos_emb"], np.float32)[None]
    put_lin("time_text_embed.timestep_embedder.linear_1",
            params["t_mlp1"], params["t_mlp1_b"])
    put_lin("time_text_embed.timestep_embedder.linear_2",
            params["t_mlp2"], params["t_mlp2_b"])
    put_lin("time_text_embed.text_embedder.linear_1",
            params["pool_mlp1"], params["pool_mlp1_b"])
    put_lin("time_text_embed.text_embedder.linear_2",
            params["pool_mlp2"], params["pool_mlp2_b"])
    put_lin("context_embedder", params["ctx_proj"], params["ctx_proj_b"])
    put_lin("norm_out.linear", params["final_mod_w"], params["final_mod_b"])
    put_lin("proj_out", params["final_proj"], params["final_proj_b"])

    blk = params["blocks"]
    vec_names = {
        "img_qnorm": "attn.norm_q.weight", "img_knorm": "attn.norm_k.weight",
        "ctx_qnorm": "attn.norm_added_q.weight",
        "ctx_knorm": "attn.norm_added_k.weight",
    }
    L = cfg.n_layers - int(cfg.context_pre_only_last)
    for i in range(L):
        T = f"transformer_blocks.{i}."
        put_lin(T + "norm1.linear", blk["img_mod_w"][i], blk["img_mod_b"][i])
        put_lin(T + "norm1_context.linear",
                blk["ctx_mod_w"][i], blk["ctx_mod_b"][i])
        put_lin(T + "attn.to_q", blk["img_wq"][i], blk["img_bq"][i])
        put_lin(T + "attn.to_k", blk["img_wk"][i], blk["img_bk"][i])
        put_lin(T + "attn.to_v", blk["img_wv"][i], blk["img_bv"][i])
        put_lin(T + "attn.to_out.0", blk["img_wo"][i], blk["img_bo"][i])
        put_lin(T + "attn.add_q_proj", blk["ctx_wq"][i], blk["ctx_bq"][i])
        put_lin(T + "attn.add_k_proj", blk["ctx_wk"][i], blk["ctx_bk"][i])
        put_lin(T + "attn.add_v_proj", blk["ctx_wv"][i], blk["ctx_bv"][i])
        put_lin(T + "attn.to_add_out", blk["ctx_wo"][i], blk["ctx_bo"][i])
        put_lin(T + "ff.net.0.proj", blk["img_fc1"][i], blk["img_fc1_b"][i])
        put_lin(T + "ff.net.2", blk["img_fc2"][i], blk["img_fc2_b"][i])
        put_lin(T + "ff_context.net.0.proj",
                blk["ctx_fc1"][i], blk["ctx_fc1_b"][i])
        put_lin(T + "ff_context.net.2", blk["ctx_fc2"][i], blk["ctx_fc2_b"][i])
        for ours, theirs in vec_names.items():
            raw[T + theirs] = np.asarray(blk[ours][i], np.float32).copy()
    if cfg.context_pre_only_last:
        lb = params["last_block"]
        T = f"transformer_blocks.{cfg.n_layers - 1}."
        put_lin(T + "norm1.linear", lb["img_mod_w"], lb["img_mod_b"])
        put_lin(T + "norm1_context.linear", lb["ctx_mod_w"], lb["ctx_mod_b"])
        put_lin(T + "attn.to_q", lb["img_wq"], lb["img_bq"])
        put_lin(T + "attn.to_k", lb["img_wk"], lb["img_bk"])
        put_lin(T + "attn.to_v", lb["img_wv"], lb["img_bv"])
        put_lin(T + "attn.to_out.0", lb["img_wo"], lb["img_bo"])
        put_lin(T + "attn.add_q_proj", lb["ctx_wq"], lb["ctx_bq"])
        put_lin(T + "attn.add_k_proj", lb["ctx_wk"], lb["ctx_bk"])
        put_lin(T + "attn.add_v_proj", lb["ctx_wv"], lb["ctx_bv"])
        put_lin(T + "ff.net.0.proj", lb["img_fc1"], lb["img_fc1_b"])
        put_lin(T + "ff.net.2", lb["img_fc2"], lb["img_fc2_b"])
        for ours, theirs in vec_names.items():
            raw[T + theirs] = np.asarray(lb[ours], np.float32).copy()
    save_file(raw, str(tmp_path / "diffusion_pytorch_model.safetensors"))


class TestMMDiT:
    def _rand_params(self, jax, cfg):
        """Init + randomize the zero-init leaves so roundtrips are
        discriminating (zero-init mod weights would hide transposes)."""
        from modal_examples_tpu.models import diffusion

        params = diffusion.mmdit_init(jax.random.PRNGKey(0), cfg)
        leaves, treedef = jax.tree.flatten(params)
        keys = jax.random.split(jax.random.PRNGKey(7), len(leaves))
        leaves = [
            jax.random.normal(k, l.shape, l.dtype) * 0.05
            for k, l in zip(keys, leaves)
        ]
        return jax.tree.unflatten(treedef, leaves)

    def _forward_args(self, jax, cfg, B=2):
        import jax.numpy as jnp

        k = jax.random.PRNGKey(3)
        ks = jax.random.split(k, 4)
        x = jax.random.normal(ks[0], (B, cfg.img_size, cfg.img_size, cfg.channels))
        t = jnp.array([0.25, 0.75])[:B]
        text = jax.random.normal(ks[1], (B, 6, cfg.text_dim))
        pooled = jax.random.normal(ks[2], (B, cfg.pooled_dim))
        return x, t, text, pooled

    def test_forward_shapes_uniform_and_pre_only(self, jax):
        from modal_examples_tpu.models import diffusion

        for pre_only in (False, True):
            cfg = diffusion.MMDiTConfig(context_pre_only_last=pre_only)
            params = self._rand_params(jax, cfg)
            assert ("last_block" in params) == pre_only
            x, t, text, pooled = self._forward_args(jax, cfg)
            v = diffusion.mmdit_forward(params, x, t, text, pooled, cfg)
            assert v.shape == x.shape

    def test_hf_roundtrip_with_context_pre_only_last(self, jax, tmp_path):
        """Synthesized diffusers checkpoint (uniform blocks + pre-only final
        block) loads back to the exact tree, and the forward runs."""
        from modal_examples_tpu.models import diffusion

        cfg = diffusion.MMDiTConfig(context_pre_only_last=True)
        params = self._rand_params(jax, cfg)
        _save_diffusers_mmdit(tmp_path, params, cfg)
        loaded = diffusion.load_mmdit_hf_weights(tmp_path, cfg)
        for (pa, a), (pb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(params), key=str),
            sorted(jax.tree_util.tree_leaves_with_path(loaded), key=str),
        ):
            assert str(pa) == str(pb)
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=2e-2, atol=2e-3, err_msg=str(pa),
            )
        x, t, text, pooled = self._forward_args(jax, cfg)
        va = diffusion.mmdit_forward(params, x, t, text, pooled, cfg)
        vb = diffusion.mmdit_forward(loaded, x, t, text, pooled, cfg)
        np.testing.assert_allclose(
            np.asarray(va), np.asarray(vb), rtol=2e-2, atol=2e-3
        )

    def test_uniform_cfg_rejects_pre_only_checkpoint(self, jax, tmp_path):
        """A real SD3-layout checkpoint (pre-only last block) must fail
        LOUDLY when loaded with context_pre_only_last=False — the silent
        KeyError/shape-mismatch class ADVICE r2 flagged."""
        from modal_examples_tpu.models import diffusion

        cfg = diffusion.MMDiTConfig(context_pre_only_last=True)
        params = self._rand_params(jax, cfg)
        _save_diffusers_mmdit(tmp_path, params, cfg)
        bad = diffusion.MMDiTConfig(context_pre_only_last=False)
        with pytest.raises((KeyError, ValueError)):
            diffusion.load_mmdit_hf_weights(tmp_path, bad)

    def test_final_modulation_is_scale_then_shift(self, jax):
        """norm_out is AdaLayerNormContinuous: chunk order (scale, shift),
        applied as norm(x) * (1 + scale) + shift. Craft scale = -1 so the
        normed image vanishes: output must equal shift @ proj for every
        patch, which only holds with the diffusers order."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import diffusion

        cfg = diffusion.MMDiTConfig(context_pre_only_last=False)
        params = self._rand_params(jax, cfg)
        D = cfg.dim
        shift = np.random.default_rng(0).normal(size=(D,)).astype(np.float32)
        params["final_mod_w"] = jnp.zeros((D, 2 * D), jnp.float32)
        params["final_mod_b"] = jnp.asarray(
            np.concatenate([np.full((D,), -1.0, np.float32), shift])
        )
        x, t, text, pooled = self._forward_args(jax, cfg)
        v = diffusion.mmdit_forward(params, x, t, text, pooled, cfg)
        expect_patch = shift @ np.asarray(params["final_proj"]) + np.asarray(
            params["final_proj_b"]
        )
        got = np.asarray(diffusion.patchify(v, diffusion.DiTConfig(
            img_size=cfg.img_size, channels=cfg.channels, patch=cfg.patch
        )))
        np.testing.assert_allclose(
            got, np.broadcast_to(expect_patch, got.shape), rtol=1e-4, atol=1e-4
        )


@pytest.mark.slow
class TestControlNet:
    def test_control_conditions_generation(self, jax):
        """Train the DiT on 'control box -> filled box' scenes; sampling
        with a NEW control layout must put its mass inside that layout —
        the spatial-conditioning property (controlnet_gradio_demos.py's
        capability, diffusers-side there). Zero-init control_proj means an
        untrained model ignores the control entirely."""
        import jax.numpy as jnp
        import numpy as np
        import optax

        from modal_examples_tpu.models import diffusion

        cfg = diffusion.DiTConfig(
            img_size=16, patch=2, dim=96, n_layers=3, n_heads=4,
            text_dim=16, text_len=4, control=True,
        )
        params = diffusion.init_params(jax.random.PRNGKey(0), cfg)
        # zero-init: control has NO effect before training
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16, 3))
        t = jnp.array([0.5])
        txt = jnp.zeros((1, 4, 16))
        ctrl = jnp.ones((1, 16, 16, 3))
        a = diffusion.forward(params, x, t, txt, cfg, control=None)
        b = diffusion.forward(params, x, t, txt, cfg, control=ctrl)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

        def make_batch(key, bs=16):
            ks = jax.random.split(key, 3)
            cx = jax.random.randint(ks[0], (bs,), 3, 13)
            cy = jax.random.randint(ks[1], (bs,), 3, 13)
            yy, xx = jnp.mgrid[0:16, 0:16]
            inside = (
                (jnp.abs(xx[None] - cx[:, None, None]) <= 3)
                & (jnp.abs(yy[None] - cy[:, None, None]) <= 3)
            ).astype(jnp.float32)
            # control: just the box OUTLINE; image: box FILLED bright
            er = (
                (jnp.abs(xx[None] - cx[:, None, None]) == 3)
                & (jnp.abs(yy[None] - cy[:, None, None]) <= 3)
            ) | (
                (jnp.abs(yy[None] - cy[:, None, None]) == 3)
                & (jnp.abs(xx[None] - cx[:, None, None]) <= 3)
            )
            control = jnp.repeat(
                er.astype(jnp.float32)[:, :, :, None], 3, axis=-1
            )
            img = jnp.repeat(
                (inside * 2.0 - 1.0)[:, :, :, None], 3, axis=-1
            )
            return img, control, inside

        opt = optax.adam(2e-3)
        opt_state = opt.init(params)
        txt_b = jnp.zeros((16, 4, 16))

        @jax.jit
        def step(params, opt_state, key):
            k1, k2 = jax.random.split(key)
            img, control, _ = make_batch(k1)
            loss, grads = jax.value_and_grad(
                lambda p: diffusion.flow_loss(
                    p, k2, img, txt_b, cfg, control=control, null_prob=0.0
                )
            )(params)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        key = jax.random.PRNGKey(3)
        for _ in range(250):
            key, sub = jax.random.split(key)
            params, opt_state, loss = step(params, opt_state, sub)

        # fresh control layout -> generated mass must sit inside it
        img, control, inside = make_batch(jax.random.PRNGKey(77), 4)
        out = diffusion.sample(
            params, jax.random.PRNGKey(5), jnp.zeros((4, 4, 16)), cfg,
            steps=6, guidance=1.0, control=control,
        )
        bright = (np.asarray(out).mean(-1) + 1.0) / 2.0  # [B, 16, 16] in [0,1]
        m = np.asarray(inside) > 0.5
        in_mean = float(bright[m].mean())
        out_mean = float(bright[~m].mean())
        assert in_mean > out_mean + 0.25, (in_mean, out_mean)


class TestDiffusionLoRA:
    """Dreambooth analog (diffusers_lora_finetune.py): subject
    personalization via adapters on the MMDiT attention/MLP projections —
    adapter-only training must move the model's denoising toward the
    subject while the base weights stay bitwise frozen."""

    def _pretrained(self, jax):
        import jax.numpy as jnp
        import optax

        from modal_examples_tpu.models import diffusion

        cfg = diffusion.MMDiTConfig(
            img_size=16, channels=8, patch=2, dim=128, n_layers=2,
            n_heads=4, text_dim=32, pooled_dim=32,
        )
        base = diffusion.mmdit_init(jax.random.PRNGKey(0), cfg)
        # dreambooth personalizes a PRETRAINED model — and the raw tree
        # couldn't learn through adapters anyway: its output head is
        # adaLN-zero (final_proj == 0) and adapters never touch it.
        opt = optax.adam(2e-3)
        o = opt.init(base)

        @jax.jit
        def prestep(params, o, key):
            k1, k2 = jax.random.split(key)
            lat = jnp.tanh(
                jax.random.normal(
                    k1, (8, cfg.img_size, cfg.img_size, cfg.channels)
                )
            )
            loss, g = jax.value_and_grad(diffusion.mmdit_flow_loss)(
                params, k2, lat, jnp.zeros((8, 4, cfg.text_dim)),
                jnp.zeros((8, cfg.pooled_dim)), cfg,
            )
            upd, o = opt.update(g, o)
            return optax.apply_updates(params, upd), o, loss

        for i in range(300):
            base, o, _ = prestep(base, o, jax.random.PRNGKey(100 + i))
        return cfg, base

    def test_adapter_training_personalizes_denoising(self, jax):
        import jax.numpy as jnp
        import optax

        from modal_examples_tpu.models import diffusion, lora

        cfg, base = self._pretrained(jax)
        base_snapshot = [np.asarray(x).copy() for x in jax.tree.leaves(base)]

        lcfg = lora.LoRAConfig(rank=16, alpha=32.0, targets=lora.DIT_TARGETS)
        adapters = lora.init_lora_tree(jax.random.PRNGKey(1), base, lcfg)
        n_ad = lora.param_count(adapters)
        n_base = sum(x.size for x in jax.tree.leaves(base))
        assert 0 < n_ad < n_base * 0.5, (n_ad, n_base)

        # the "subject" bound to a subject-token embedding (the sks-token
        # recipe at demo scale)
        subject = jnp.tanh(
            jax.random.normal(
                jax.random.PRNGKey(3), (cfg.img_size, cfg.img_size,
                                        cfg.channels)
            ) * 2.0
        )
        subj_txt = jax.random.normal(
            jax.random.PRNGKey(4), (1, 4, cfg.text_dim)
        )

        def denoise_err(params):
            """One-step rectified-flow denoise x_hat = x_t - t*v at fixed
            (eps, t): the quantity personalization optimizes."""
            t = 0.7
            eps = jax.random.normal(jax.random.PRNGKey(77), (4, *subject.shape))
            x_t = (1 - t) * subject[None] + t * eps
            ts = jnp.broadcast_to(subj_txt, (4, 4, cfg.text_dim))
            v = diffusion.mmdit_forward(
                params, x_t, jnp.full((4,), t), ts,
                jnp.zeros((4, cfg.pooled_dim)), cfg,
            )
            return float(jnp.mean((x_t - t * v - subject[None]) ** 2))

        # b = 0 at init: merged tree IS the base
        merged0 = lora.merge_tree(base, adapters, lcfg)
        assert abs(denoise_err(merged0) - denoise_err(base)) < 1e-6

        opt = optax.adam(1e-2)
        opt_state = opt.init(adapters)

        @jax.jit
        def step(adapters, opt_state, key):
            def loss_fn(ad):
                merged = lora.merge_tree(base, ad, lcfg)
                lat = jnp.broadcast_to(subject[None], (8, *subject.shape))
                ts = jnp.broadcast_to(subj_txt, (8, 4, cfg.text_dim))
                return diffusion.mmdit_flow_loss(
                    merged, key, lat, ts, jnp.zeros((8, cfg.pooled_dim)), cfg
                )

            loss, g = jax.value_and_grad(loss_fn)(adapters)
            upd, opt_state = opt.update(g, opt_state)
            return optax.apply_updates(adapters, upd), opt_state, loss

        err_before = denoise_err(base)
        for i in range(300):
            adapters, opt_state, _ = step(
                adapters, opt_state, jax.random.PRNGKey(10 + i)
            )
        err_after = denoise_err(lora.merge_tree(base, adapters, lcfg))
        # measured: 0.599 -> 0.238 at these settings; 0.6x is a safe gate
        assert err_after < err_before * 0.6, (err_before, err_after)

        # the base tree is untouched by adapter training
        for leaf, ref in zip(jax.tree.leaves(base), base_snapshot):
            np.testing.assert_array_equal(np.asarray(leaf), ref)

    def test_init_lora_tree_rejects_no_match(self, jax):
        from modal_examples_tpu.models import diffusion, lora

        cfg = diffusion.MMDiTConfig.tiny()
        base = diffusion.mmdit_init(jax.random.PRNGKey(0), cfg)
        with pytest.raises(ValueError, match="no leaves matched"):
            lora.init_lora_tree(
                jax.random.PRNGKey(1), base,
                lora.LoRAConfig(targets=("nonexistent",)),
            )
