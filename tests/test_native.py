"""Native (C++) host library tests: build, semantics parity with the Python
fallbacks, and thread safety under contention."""

import threading

import numpy as np
import pytest

from modal_examples_tpu import native


@pytest.fixture(scope="module")
def lib():
    lib = native.load()
    if lib is None:
        pytest.skip("g++ unavailable; native library not built")
    return lib


class TestNativeAllocator:
    def test_semantics_match_python(self, lib):
        from modal_examples_tpu.serving.kv_cache import OutOfPages, PageAllocator

        n = native.NativePageAllocator(16)
        p = PageAllocator(16)
        assert n.available == p.available == 15
        na, pa = n.alloc(5), p.alloc(5)
        assert na == pa  # same low-ids-first order
        assert 0 not in na
        n.free(na[:2])
        p.free(pa[:2])
        assert n.available == p.available
        with pytest.raises(OutOfPages):
            n.alloc(100)

    def test_thread_safety(self, lib):
        alloc = native.NativePageAllocator(1025)
        got, lock = [], threading.Lock()

        def worker():
            mine = []
            for _ in range(16):
                mine.extend(alloc.alloc(4))
            with lock:
                got.extend(mine)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(got) == 8 * 64
        assert len(set(got)) == len(got)  # no page double-allocated
        assert alloc.available == 1024 - len(got)

    def test_engine_uses_native_allocator(self, lib, jax_cpu):
        from modal_examples_tpu.serving.kv_cache import PagedKVCache

        cache = PagedKVCache.create(
            n_layers=1, n_kv_heads=1, head_dim=8, n_pages=8, page_size=4
        )
        assert type(cache.allocator).__name__ == "NativePageAllocator"


class TestNativeEncode:
    def test_matches_fallback(self, lib):
        texts = ["hello", "", "tpu systolic array", "ünïcødé"]
        ids_n, mask_n, mt_n = native.byte_encode_batch(texts, 16)
        # force the fallback path
        orig, native._lib = native._lib, None
        try:
            ids_p, mask_p, mt_p = native.byte_encode_batch(texts, 16)
        finally:
            native._lib = orig
        np.testing.assert_array_equal(ids_n, ids_p)
        np.testing.assert_array_equal(mask_n, mask_p)
        assert mt_n == mt_p

    def test_truncation(self, lib):
        ids, mask, mt = native.byte_encode_batch(["x" * 100], 8)
        assert mask[0].sum() == 8
        assert mt == 8


class TestNativeLevenshtein:
    def test_known_distances(self, lib):
        assert native.levenshtein_ids([1, 2, 3], [1, 2, 3]) == 0
        assert native.levenshtein_ids([1, 2, 3], [1, 9, 3]) == 1
        assert native.levenshtein_ids([], [1, 2]) == 2
        assert native.levenshtein_ids([1, 2, 3, 4], [2, 3]) == 2
