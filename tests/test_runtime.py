"""End-to-end tests of the serverless runtime: invocation modes, autoscaling,
retries, timeouts, batching, Cls lifecycle — against real container worker
processes (the "process" backend), per the reference's no-mocks philosophy
(SURVEY.md §4)."""

import asyncio
import os
import time

import pytest

import modal_examples_tpu as mtpu
from modal_examples_tpu.core.executor import FunctionTimeoutError

app = mtpu.App("runtime-test")


@app.function(timeout=30)
def square(x: int) -> int:
    return x * x


@app.function(timeout=30)
def fail_always(msg: str):
    raise ValueError(msg)


@app.function(timeout=30)
def countdown(n: int):
    for i in range(n, 0, -1):
        yield i


@app.function(timeout=30, retries=mtpu.Retries(max_retries=3, initial_delay=0.0))
def flaky(path: str):
    # fails until a scratch file accumulates 2 attempts (crosses processes)
    with open(path, "a") as f:
        f.write("x")
    if os.path.getsize(path) < 2:
        raise RuntimeError("transient")
    return "recovered"


@app.function(timeout=2)
def sleeper(seconds: float):
    time.sleep(seconds)
    return "done"


@app.function(timeout=30)
@mtpu.batched(max_batch_size=4, wait_ms=100)
def batch_double(xs: list[int]) -> list[int]:
    assert isinstance(xs, list)
    return [x * 2 for x in xs]


@app.function(timeout=30)
def whoami() -> str:
    return os.environ.get("MTPU_TASK_ID", "")


@app.cls(timeout=30)
class Counter:
    base: int = mtpu.parameter(default=100)

    @mtpu.enter()
    def setup(self):
        self.loaded = True
        self.count = 0

    @mtpu.method()
    def add(self, x: int) -> int:
        assert self.loaded
        self.count += x
        return self.base + self.count

    @mtpu.method()
    def stream(self, n: int):
        for i in range(n):
            yield i

    @mtpu.exit()
    def teardown(self):
        pass


@pytest.fixture(scope="module", autouse=True)
def run_ctx():
    with app.run():
        yield


class TestInvocationModes:
    def test_local(self):
        assert square.local(7) == 49

    def test_remote(self):
        assert square.remote(9) == 81

    def test_remote_runs_in_container(self):
        task_id = whoami.remote()
        assert task_id.startswith("ta-")
        assert task_id != os.environ.get("MTPU_TASK_ID", "")

    def test_map_ordered(self):
        assert list(square.map(range(6))) == [0, 1, 4, 9, 16, 25]

    def test_map_unordered_same_set(self):
        out = list(square.map(range(6), order_outputs=False))
        assert sorted(out) == [0, 1, 4, 9, 16, 25]

    def test_starmap(self):
        @app.function(timeout=30)
        def add(a, b):
            return a + b

        assert list(add.starmap([(1, 2), (3, 4)])) == [3, 7]

    def test_spawn_get_and_gather(self):
        c1 = square.spawn(3)
        c2 = square.spawn(4)
        assert c1.get(timeout=20) == 9
        assert mtpu.gather(c1, c2) == [9, 16]

    def test_functioncall_from_id(self):
        call = square.spawn(5)
        again = mtpu.FunctionCall.from_id(call.object_id)
        assert again.get(timeout=20) == 25

    def test_remote_gen(self):
        assert list(countdown.remote_gen(3)) == [3, 2, 1]

    def test_for_each(self):
        square.for_each(range(3))

    def test_exceptions_propagate_with_traceback(self):
        with pytest.raises(ValueError, match="boom"):
            fail_always.remote("boom")

    def test_map_return_exceptions(self):
        @app.function(timeout=30)
        def maybe_fail(x):
            if x == 1:
                raise RuntimeError("nope")
            return x

        out = list(maybe_fail.map([0, 1, 2], return_exceptions=True))
        assert out[0] == 0 and out[2] == 2
        assert isinstance(out[1], RuntimeError)

    def test_aio_remote(self):
        async def go():
            return await square.remote.aio(6)

        assert asyncio.run(go()) == 36

    def test_aio_map(self):
        async def go():
            return [x async for x in square.map.aio(range(4))]

        assert asyncio.run(go()) == [0, 1, 4, 9]


class TestFaultTolerance:
    def test_retries_recover(self, tmp_path):
        path = str(tmp_path / "attempts")
        assert flaky.remote(path) == "recovered"
        assert os.path.getsize(path) >= 2

    def test_timeout_kills_input(self):
        with pytest.raises((FunctionTimeoutError, RuntimeError)):
            sleeper.remote(10)

    def test_fast_input_within_timeout(self):
        assert sleeper.remote(0.01) == "done"


class TestFailureAccounting:
    """Each failure path must leave an audit trail: the right
    ``mtpu_retries_total{reason=...}`` / ``mtpu_container_kills_total``
    deltas in the process registry, and error-status spans in the call's
    trace (observability.catalog names throughout)."""

    @staticmethod
    def _counter(name, **labels):
        from modal_examples_tpu.utils.prometheus import default_registry

        return default_registry.value(name, labels=labels)

    @staticmethod
    def _trace(call):
        from modal_examples_tpu.observability.trace import default_store

        return default_store.read(call.call_id)

    def test_timeout_accounting(self):
        from modal_examples_tpu.observability import catalog as C

        tag = sleeper.spec.tag
        kills0 = self._counter(
            C.CONTAINER_KILLS_TOTAL, function=tag, reason="timeout"
        )
        call = sleeper.spawn(10)
        with pytest.raises((FunctionTimeoutError, RuntimeError)):
            call.get(timeout=30)
        assert self._counter(
            C.CONTAINER_KILLS_TOTAL, function=tag, reason="timeout"
        ) == kills0 + 1
        spans = self._trace(call)
        root = [s for s in spans if s["name"] == "call"][0]
        assert root["status"] == "error"
        dispatch = [s for s in spans if s["name"] == "dispatch"]
        assert dispatch and dispatch[-1]["status"] == "error"
        assert dispatch[-1]["attrs"]["reason"] == "timeout"

    def test_container_death_orphan_requeued_and_counted(self, tmp_path):
        from modal_examples_tpu.observability import catalog as C

        dapp = mtpu.App("death-test")

        @dapp.function(
            timeout=60, retries=mtpu.Retries(max_retries=2, initial_delay=0.0)
        )
        def die_once(path: str):
            if not os.path.exists(path):
                with open(path, "w") as f:
                    f.write("x")
                os._exit(1)  # hard container death mid-input
            return "survived"

        with dapp.run():
            tag = die_once.spec.tag
            r0 = self._counter(
                C.RETRIES_TOTAL, function=tag, reason="container_death"
            )
            call = die_once.spawn(str(tmp_path / "sentinel"))
            assert call.get(timeout=60) == "survived"
            assert self._counter(
                C.RETRIES_TOTAL, function=tag, reason="container_death"
            ) == r0 + 1
            spans = self._trace(call)
            retries = [s for s in spans if s["name"] == "retry"]
            assert retries and retries[0]["attrs"]["reason"] == "container_death"
            # first dispatch errored, the requeued attempt completed the call
            dispatch = sorted(
                (s for s in spans if s["name"] == "dispatch"),
                key=lambda s: s["start"],
            )
            assert len(dispatch) >= 2
            assert dispatch[0]["status"] == "error"
            assert dispatch[-1]["status"] == "ok"
            root = [s for s in spans if s["name"] == "call"][0]
            assert root["status"] == "ok" and root["attrs"]["attempts"] == 1

    def test_retry_exhaustion_counts_every_attempt(self):
        from modal_examples_tpu.observability import catalog as C

        eapp = mtpu.App("exhaust-test")

        @eapp.function(
            timeout=30, retries=mtpu.Retries(max_retries=2, initial_delay=0.0)
        )
        def always_bad():
            raise ValueError("permanent")

        with eapp.run():
            tag = always_bad.spec.tag
            r0 = self._counter(
                C.RETRIES_TOTAL, function=tag, reason="user_error"
            )
            call = always_bad.spawn()
            with pytest.raises(ValueError, match="permanent"):
                call.get(timeout=30)
            # 3 attempts total -> 2 charged retries, then the exception
            assert self._counter(
                C.RETRIES_TOTAL, function=tag, reason="user_error"
            ) == r0 + 2
            spans = self._trace(call)
            assert len([s for s in spans if s["name"] == "retry"]) == 2
            root = [s for s in spans if s["name"] == "call"][0]
            assert root["status"] == "error"
            assert root["attrs"]["attempts"] == 3
            # every attempt's execute span shipped back, all errored
            executes = [s for s in spans if s["name"] == "execute"]
            assert len(executes) == 3
            assert all(s["status"] == "error" for s in executes)

    def test_inflight_gauge_returns_to_zero(self):
        from modal_examples_tpu.observability import catalog as C

        tag = square.spec.tag
        assert square.remote(2) == 4
        assert self._counter(C.INFLIGHT_INPUTS, function=tag) == 0.0


class TestBatching:
    def test_batched_groups_inputs(self):
        out = list(batch_double.map(range(8)))
        assert out == [0, 2, 4, 6, 8, 10, 12, 14]


class TestCls:
    def test_lifecycle_and_state(self):
        counter = Counter()
        assert counter.add.remote(5) == 105
        # same container: state accumulates across inputs
        assert counter.add.remote(5) == 110

    def test_parameters(self):
        c = Counter(base=1000)
        assert c.add.remote(1) == 1001

    def test_unknown_parameter_rejected(self):
        with pytest.raises(TypeError):
            Counter(nope=1)

    def test_local_instance_runs_enter(self):
        c = Counter()
        assert c.add.local(2) == 102

    def test_method_generator(self):
        c = Counter()
        assert list(c.stream.remote(4)) == [0, 1, 2, 3]

    def test_with_options(self):
        C2 = Counter._cls if hasattr(Counter, "_cls") else Counter
        opt = (
            C2.with_options(max_containers=2)
            if hasattr(C2, "with_options")
            else None
        )
        assert opt is not None
        assert opt._spec.max_containers == 2

    def test_cls_from_name(self):
        assert mtpu.Cls.from_name("runtime-test", "Counter") is not None


class TestConcurrency:
    def test_concurrent_inputs_overlap(self):
        capp = mtpu.App("concurrency-test")

        @capp.function(timeout=30)
        @mtpu.concurrent(max_inputs=4)
        def slow_echo(x):
            start = time.monotonic()
            time.sleep(0.4)
            return x, start, time.monotonic()

        with capp.run():
            out = list(slow_echo.map(range(4)))
        assert sorted(x for x, _, _ in out) == [0, 1, 2, 3]
        # prove overlap by event ordering, not wall-clock (load-immune):
        # CLOCK_MONOTONIC is system-wide, so intervals from different inputs
        # are comparable; at least one pair must have run concurrently
        intervals = [(s, e) for _, s, e in out]
        overlapping = any(
            a_s < b_e and b_s < a_e
            for i, (a_s, a_e) in enumerate(intervals)
            for b_s, b_e in intervals[i + 1 :]
        )
        assert overlapping, intervals

    def test_autoscale_fan_out(self):
        sapp = mtpu.App("scale-test")

        @sapp.function(timeout=60, max_containers=4)
        def task_id_of(_x):
            time.sleep(0.3)
            return os.environ["MTPU_TASK_ID"]

        with sapp.run():
            ids = set(task_id_of.map(range(8)))
        assert len(ids) >= 2  # the pool actually fanned out


class TestSingleUse:
    def test_single_use_containers_fresh_each_input(self):
        suapp = mtpu.App("single-use-test")

        @suapp.function(timeout=60, single_use_containers=True, max_containers=4)
        def tid(_x):
            return os.environ["MTPU_TASK_ID"]

        with suapp.run():
            ids = list(tid.map(range(3)))
        assert len(set(ids)) == 3


class TestAutoscalerJournal:
    """Every autoscale decision must leave a structured journal record
    (observability.journal) with its trigger and pool-state rationale."""

    @staticmethod
    def _decisions(tag, action):
        from modal_examples_tpu.observability.journal import default_journal

        return [
            r for r in default_journal.tail(500, function=tag)
            if r["action"] == action
        ]

    def test_queue_pressure_scale_up_is_journaled(self):
        japp = mtpu.App("journal-scale-test")

        @japp.function(timeout=60, max_containers=3)
        def slow_id(x):
            time.sleep(0.2)
            return x

        with japp.run():
            assert sorted(slow_id.map(range(6))) == list(range(6))
            tag = slow_id.spec.tag
            ups = self._decisions(tag, "scale_up")
            assert ups, "no scale_up journal record"
            first = ups[0]
            assert first["trigger"] == "queue_pressure"
            assert first["queue_depth"] >= 1
            assert first["inflight"] >= 1
            assert first["containers_after"] > first["containers_before"]
            assert first["spawned"] >= 1
            # the prometheus decisions counter mirrors the journal
            from modal_examples_tpu.observability import catalog as C
            from modal_examples_tpu.utils.prometheus import default_registry

            assert default_registry.value(
                C.SCALER_DECISIONS_TOTAL,
                labels={"function": tag, "action": "scale_up"},
            ) == len(ups)

    def test_scaledown_window_reap_is_journaled(self):
        sapp = mtpu.App("journal-reap-test")

        @sapp.function(timeout=30, scaledown_window=0.4)
        def ping() -> str:
            return "pong"

        with sapp.run():
            assert ping.remote() == "pong"
            tag = ping.spec.tag
            # the idle reaper fires from the scheduler tick once the
            # container has been idle past the (short) scaledown window
            deadline = time.monotonic() + 20
            downs = []
            while time.monotonic() < deadline and not downs:
                downs = self._decisions(tag, "scale_down")
                time.sleep(0.1)
            assert downs, "idle container was never reaped into the journal"
            rec = downs[0]
            assert rec["trigger"] == "idle"
            assert rec["idle_ages_s"][0] >= 0.4
            assert rec["scaledown_window_s"] == pytest.approx(0.4)
            assert rec["containers_after"] == rec["containers_before"] - 1


class TestAppRegistry:
    def test_registered_functions(self):
        assert "square" in app.registered_functions

    def test_lookup_in_process(self):
        assert mtpu.App.lookup("runtime-test") is app

    def test_deploy_registry(self, state_dir):
        app.deploy(source_file=__file__)
        import json

        registry = json.loads((state_dir / "apps.json").read_text())
        assert "runtime-test" in registry
        assert "square" in registry["runtime-test"]["functions"]
