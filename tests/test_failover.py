"""ISSUE 12 acceptance: in-flight request failover (docs/failover.md).

The exactness contract, pinned as a matrix: a stream resumed from a
:class:`~modal_examples_tpu.serving.failover.DecodeCheckpoint` — reactive
re-prefill of prompt+generated-prefix, or proactive live KV migration —
is **token-identical** to the uninterrupted run, greedy AND seeded, at
resume positions {first token, mid-stream, last token}, for bf16 AND int8
KV. Plus the failure-hygiene half: abort/deadline during an in-flight live
migration releases pages and reservations on BOTH replicas, and fleet
scale-in of a busy replica completes via migration in bounded time."""

import threading
import time

import pytest


PROMPT = "the quick brown fox jumps over the lazy dog and naps in the sun"


def _drain_queue(req, timeout=60.0) -> str:
    """Drain a request's out_queue to its terminal marker (the engine's
    ``stream()`` without an engine — for requests terminated outside any
    scheduler, e.g. an aborted migration)."""
    import queue as _q

    from modal_examples_tpu.serving.engine import _Finish

    out = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            item = req.out_queue.get(timeout=0.2)
        except _q.Empty:
            continue
        if isinstance(item, _Finish):
            req.finish_reason = item.reason
            return "".join(out)
        out.append(item)
    raise AssertionError("no terminal marker arrived")


def _mk_engine(kv_dtype="bfloat16", params=None, **kw):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    kw.setdefault("max_slots", 2)
    kw.setdefault("max_model_len", 128)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (16, 32))
    return LLMEngine(
        llama.LlamaConfig.tiny(), seed=0, params=params,
        kv_dtype=kv_dtype, **kw,
    )


def _drained(eng) -> list:
    from modal_examples_tpu.faults.chaos import check_drained

    return check_drained({"eng": eng})


def _wait_tokens(req, n, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(req.generated_tokens) >= n:
            return True
        time.sleep(0.005)
    return False


class TestResumeDeterminism:
    """checkpoint -> resubmit -> byte-compare against the uninterrupted
    run: greedy + seeded, resume positions {first, mid, last}, bf16 +
    int8 KV."""

    @pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
    @pytest.mark.parametrize("sampling", ["greedy", "seeded"])
    def test_resume_matrix(self, jax_cpu, kv_dtype, sampling):
        from modal_examples_tpu.serving import SamplingParams

        sp = (
            SamplingParams(max_tokens=12, temperature=0.0)
            if sampling == "greedy"
            else SamplingParams(max_tokens=12, temperature=0.9, seed=7)
        )
        eng = _mk_engine(kv_dtype)
        try:
            ref = eng.submit(PROMPT, sp)
            ref_text = "".join(eng.stream(ref))
            ref_tokens = list(ref.generated_tokens)
            n = ref.n_generated
            assert n == 12 and len(ref_tokens) == 12
            # {first token, mid-stream, last token}: k tokens were
            # accepted before the failure
            for k in (1, n // 2, n - 1):
                req = eng.make_request(PROMPT, sp)
                req.auto_seed = ref.auto_seed  # rides the checkpoint
                eng.submit_resumed(
                    req,
                    prompt_tokens=ref.prompt_tokens,
                    generated=ref_tokens[:k],
                    emitted_len=0,
                )
                out = "".join(eng.stream(req))
                assert req.generated_tokens == ref_tokens, (
                    sampling, kv_dtype, k,
                )
                # emitted_len=0 re-emits from the start: the resumed
                # stream's text IS the full uninterrupted text, byte for
                # byte (tokens identical => detok identical)
                assert out == ref_text, (sampling, kv_dtype, k)
                assert req.finish_reason == ref.finish_reason
            assert _drained(eng) == []
        finally:
            eng.stop()

    def test_resume_emission_cursor_dedupes(self, jax_cpu):
        """The emitted-text cursor: a resume with emitted_len=E emits
        exactly ref_text[E:] — no duplicated chars, no gaps."""
        from modal_examples_tpu.serving import SamplingParams

        sp = SamplingParams(max_tokens=10, temperature=0.0)
        eng = _mk_engine()
        try:
            ref = eng.submit(PROMPT, sp)
            ref_text = "".join(eng.stream(ref))
            ref_tokens = list(ref.generated_tokens)
            for cut in (0, 1, 3, len(ref_text)):
                req = eng.make_request(PROMPT, sp)
                req.auto_seed = ref.auto_seed
                eng.submit_resumed(
                    req,
                    prompt_tokens=ref.prompt_tokens,
                    generated=ref_tokens[:4],
                    emitted_len=cut,
                )
                out = "".join(eng.stream(req))
                assert out == ref_text[cut:], cut
        finally:
            eng.stop()

    def test_resume_past_the_end_finishes_without_a_slot(self, jax_cpu):
        """A checkpoint taken on the final token (max_tokens already
        reached) has nothing left to decode: the resumed stream delivers
        a terminal 'length' immediately — never an extra token."""
        from modal_examples_tpu.serving import SamplingParams

        sp = SamplingParams(max_tokens=8, temperature=0.0)
        eng = _mk_engine()
        try:
            ref = eng.submit(PROMPT, sp)
            ref_text = "".join(eng.stream(ref))
            req = eng.make_request(PROMPT, sp)
            req.auto_seed = ref.auto_seed
            eng.submit_resumed(
                req,
                prompt_tokens=ref.prompt_tokens,
                generated=list(ref.generated_tokens),
                emitted_len=len(ref_text),
            )
            out = "".join(eng.stream(req))
            assert out == ""
            assert req.finish_reason == "length"
            assert req.generated_tokens == ref.generated_tokens
            assert _drained(eng) == []
        finally:
            eng.stop()

    def test_checkpoint_from_request_is_original_prompt_based(self, jax_cpu):
        """A second checkpoint of an already-resumed request must not
        double-count the replayed prefix (the _orig_prompt_tokens rule)."""
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.serving import failover as fo

        sp = SamplingParams(max_tokens=10, temperature=0.0)
        eng = _mk_engine()
        try:
            ref = eng.submit(PROMPT, sp)
            ref_text = "".join(eng.stream(ref))
            ref_tokens = list(ref.generated_tokens)
            req = eng.make_request(PROMPT, sp)
            req.auto_seed = ref.auto_seed
            eng.submit_resumed(
                req, prompt_tokens=ref.prompt_tokens,
                generated=ref_tokens[:3], emitted_len=0,
            )
            "".join(eng.stream(req))
            ckpt = fo.checkpoint_request(req)
            assert ckpt.prompt_tokens == list(ref.prompt_tokens)
            assert ckpt.generated == ref_tokens
            # a SECOND resume from that checkpoint still reproduces
            req.trace = None
            eng.submit_resumed(
                req, prompt_tokens=ckpt.prompt_tokens,
                generated=ckpt.generated[:6], emitted_len=0,
            )
            out = "".join(eng.stream(req))
            assert out == ref_text
            assert req.generated_tokens == ref_tokens
        finally:
            eng.stop()


class TestLiveMigration:
    """Proactive path: extract mid-decode on the victim's scheduler
    thread, ship via the chunked MTKV1 wire (decode-state leg), adopt on
    the target — the stream continues token-identically."""

    def _fleet(self, **eng_kw):
        from modal_examples_tpu.scheduling import EngineReplica

        eng_a = _mk_engine(**eng_kw)
        eng_b = _mk_engine(params=eng_a.params, **eng_kw)
        rep_a = EngineReplica(eng_a, "mig-a", role="unified")
        rep_b = EngineReplica(eng_b, "mig-b", role="unified")
        return eng_a, eng_b, rep_a, rep_b

    @pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
    def test_migrate_mid_decode_token_identical(self, jax_cpu, kv_dtype):
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.serving import failover as fo

        sp = SamplingParams(max_tokens=48, temperature=0.0)
        eng_a, eng_b, rep_a, rep_b = self._fleet(kv_dtype=kv_dtype)
        try:
            ref = eng_b.submit(PROMPT, sp)  # fault-free reference on B
            ref_text = "".join(eng_b.stream(ref))
            ref_tokens = list(ref.generated_tokens)

            req = rep_a.submit(PROMPT, sp)
            pieces: list[str] = []
            t = threading.Thread(
                target=lambda: pieces.extend(eng_a.stream(req))
            )
            t.start()
            assert _wait_tokens(req, 5)
            result = fo.migrate_request(
                rep_a, rep_b, req, chunk_bytes=512
            )
            assert result == "ok"
            t.join(timeout=120)
            assert not t.is_alive()
            assert req.finish_reason == ref.finish_reason
            assert req.generated_tokens == ref_tokens
            assert "".join(pieces) == ref_text
            assert _drained(eng_a) == [] and _drained(eng_b) == []
        finally:
            eng_a.stop()
            eng_b.stop()

    def test_migrate_queued_request_resubmits_fresh(self, jax_cpu):
        """A still-queued request has nothing to ship: migration drains
        its reservation on the victim and resubmits it fresh on the
        target — token-identical (nothing was emitted)."""
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.serving import failover as fo

        sp = SamplingParams(max_tokens=12, temperature=0.0)
        eng_a, eng_b, rep_a, rep_b = self._fleet(max_slots=1)
        try:
            eng_a.start()
            ref = eng_b.submit(PROMPT, sp)
            ref_text = "".join(eng_b.stream(ref))
            blocker = rep_a.submit(
                "blocker " * 3, SamplingParams(max_tokens=48)
            )
            queued = rep_a.submit(PROMPT, sp)
            assert _wait_tokens(blocker, 1)
            result = fo.migrate_request(rep_a, rep_b, queued)
            assert result in ("resumed", "ok")
            out = "".join(eng_b.stream(queued))
            assert out == ref_text
            assert queued.generated_tokens == ref.generated_tokens
            "".join(eng_a.stream(blocker))
            assert _drained(eng_a) == [] and _drained(eng_b) == []
        finally:
            eng_a.stop()
            eng_b.stop()

    def test_abort_during_migration_releases_both_sides(self, jax_cpu):
        """Client abort between transfer chunks: the target's admission
        reservation and the victim's pages both release; the stream
        terminates honestly with 'stop'."""
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.serving import failover as fo
        from modal_examples_tpu.serving.disagg.transport import (
            LoopbackChannel,
        )

        sp = SamplingParams(max_tokens=64, temperature=0.0)
        eng_a, eng_b, rep_a, rep_b = self._fleet()
        try:
            eng_a.start()
            req = rep_a.submit(PROMPT, sp)
            assert _wait_tokens(req, 4)

            class AbortingChannel(LoopbackChannel):
                def send(self, chunk):
                    req.aborted = True  # client disconnects mid-transfer
                    super().send(chunk)

            result = fo.migrate_request(
                rep_a, rep_b, req, chunk_bytes=64,
                channel_factory=AbortingChannel,
            )
            assert result == "aborted"
            _drain_queue(req)
            assert req.finish_reason == "stop"
            assert eng_b.admission.reserved_pages == 0
            assert _drained(eng_a) == [] and _drained(eng_b) == []
        finally:
            eng_a.stop()
            eng_b.stop()

    def test_deadline_during_migration_is_an_honest_deadline(self, jax_cpu):
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.serving import failover as fo

        from modal_examples_tpu.serving.disagg.transport import (
            LoopbackChannel,
        )

        sp = SamplingParams(max_tokens=64, temperature=0.0)
        eng_a, eng_b, rep_a, rep_b = self._fleet()
        try:
            eng_a.start()
            req = rep_a.submit(PROMPT, sp)
            assert _wait_tokens(req, 2)

            class DeadlineChannel(LoopbackChannel):
                def send(self, chunk):
                    # the deadline lapses while chunks are on the wire
                    # (after extraction, so the victim's own deadline
                    # sweep cannot race this)
                    req.deadline = eng_b._clock() - 1.0
                    super().send(chunk)

            result = fo.migrate_request(
                rep_a, rep_b, req, chunk_bytes=64,
                channel_factory=DeadlineChannel,
            )
            assert result == "aborted"
            _drain_queue(req)
            assert req.finish_reason == "deadline"
            assert eng_b.admission.reserved_pages == 0
            assert _drained(eng_a) == [] and _drained(eng_b) == []
        finally:
            eng_a.stop()
            eng_b.stop()

    def test_wire_failure_falls_back_to_reactive_resume(self, jax_cpu):
        """A transfer that cannot complete (dead channel) falls back to
        the checkpoint-only re-prefill resume — still token-identical,
        still zero client-visible errors."""
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.serving import failover as fo
        from modal_examples_tpu.serving.disagg.transport import (
            LoopbackChannel,
        )

        # generous max_tokens: after the gate releases, the reactive
        # fallback races the victim's resumed decode — if the request
        # FINISHES first, migrate_out honestly reports "gone". The long
        # tail keeps the request mid-decode through that window.
        sp = SamplingParams(max_tokens=128, temperature=0.0)
        eng_a, eng_b, rep_a, rep_b = self._fleet()
        try:
            ref = eng_b.submit(PROMPT, sp)
            ref_text = "".join(eng_b.stream(ref))

            req = rep_a.submit(PROMPT, sp)
            pieces: list[str] = []
            t = threading.Thread(
                target=lambda: pieces.extend(eng_a.stream(req))
            )
            t.start()
            assert _wait_tokens(req, 4)
            # park the victim's scheduler on a blocking control command
            # (the bench _measure_failover trick): without it, decode
            # races the migration to max_tokens under CI load and
            # migrate_out honestly reports "gone" — the gate guarantees
            # the migration lands mid-decode, deterministically
            import queue as _queue

            gate = threading.Event()
            eng_a._ctrl.append((gate.wait, _queue.Queue()))

            class BlackholeChannel(LoopbackChannel):
                def send(self, chunk):
                    pass  # every chunk vanishes; rounds exhaust

            box: dict = {}

            def migrate():
                box["result"] = fo.migrate_request(
                    rep_a, rep_b, req, chunk_bytes=512, max_rounds=2,
                    channel_factory=BlackholeChannel,
                )

            mt = threading.Thread(target=migrate)
            mt.start()
            # release the gate only once the migration's own control
            # command is queued behind it
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if eng_a._ctrl and eng_a._ctrl[-1][0] is not gate.wait:
                    break
                time.sleep(0.002)
            gate.set()
            mt.join(timeout=120)
            assert not mt.is_alive()
            assert box.get("result") == "resumed"
            t.join(timeout=120)
            assert not t.is_alive()
            assert req.finish_reason == ref.finish_reason
            assert "".join(pieces) == ref_text
            assert req.generated_tokens == ref.generated_tokens
            assert _drained(eng_a) == [] and _drained(eng_b) == []
        finally:
            eng_a.stop()
            eng_b.stop()


class TestReactiveStreamFailover:
    """Replica death mid-stream: the router-level stream resumes on a
    healthy peer from the request's own checkpoint — the consumer sees
    one uninterrupted, token-identical stream."""

    def test_router_stream_survives_scheduler_crash(self, jax_cpu):
        from modal_examples_tpu.faults.inject import FaultPlan, active
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )
        from modal_examples_tpu.serving import SamplingParams

        sp = SamplingParams(max_tokens=48, temperature=0.0)
        eng_a = _mk_engine()
        eng_b = _mk_engine(params=eng_a.params)
        rep_a = EngineReplica(eng_a, "re-a", role="unified")
        rep_b = EngineReplica(eng_b, "re-b", role="unified")
        router = PrefixAffinityRouter([rep_a, rep_b], reprobe_s=0.2)
        try:
            ref = eng_b.submit(PROMPT, sp)
            ref_text = "".join(eng_b.stream(ref))
            ref_tokens = list(ref.generated_tokens)
            eng_b.stop()  # fresh again for the takeover
            eng_b.revive() if eng_b._stopped_on_error else None

            req = rep_a.submit(PROMPT, sp)
            req._router_replica = rep_a
            pieces: list[str] = []
            t = threading.Thread(
                target=lambda: pieces.extend(router.stream(req))
            )
            t.start()
            assert _wait_tokens(req, 4)
            # only eng_a's loop is running -> the injected crash lands
            # deterministically on the request's owner
            plan = FaultPlan({"engine.scheduler_crash": {"on_hit": 1}})
            with active(plan):
                deadline = time.monotonic() + 30
                while not plan.fired() and time.monotonic() < deadline:
                    time.sleep(0.005)
            assert plan.fired().get("engine.scheduler_crash") == 1
            t.join(timeout=120)
            assert not t.is_alive()
            # zero client-visible errors: the stream finished normally,
            # token-identical, no duplicated or missing chars
            assert req.finish_reason == ref.finish_reason
            assert req.generated_tokens == ref_tokens
            assert "".join(pieces) == ref_text
            assert _drained(eng_a) == [] and _drained(eng_b) == []
        finally:
            eng_a.stop()
            eng_b.stop()

    def test_failover_metrics_and_span_recorded(self, jax_cpu):
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.observability import reqtrace as rt
        from modal_examples_tpu.scheduling import EngineReplica
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.serving import failover as fo
        from modal_examples_tpu.utils.prometheus import default_registry

        sp = SamplingParams(max_tokens=16, temperature=0.0)
        eng_a = _mk_engine()
        eng_b = _mk_engine(params=eng_a.params)
        rep_b = EngineReplica(eng_b, "fm-b", role="unified")
        before = default_registry.total(C.FAILOVER_TOTAL)
        try:
            eng_a.start()
            req = eng_a.submit(PROMPT, sp)
            assert _wait_tokens(req, 3)
            # simulate death: engine A releases everything with "error"
            eng_a.stop()
            from modal_examples_tpu.serving.engine import _Finish

            req.finish_reason = None  # consumer has not drained yet
            assert fo.resume_request(req, rep_b, source="fm-a")
            drained = []
            while True:
                item = req.out_queue.get(timeout=60)
                if isinstance(item, _Finish):
                    req.finish_reason = item.reason
                    break
                drained.append(item)
            assert req.finish_reason in ("stop", "length")
            after = default_registry.total(C.FAILOVER_TOTAL)
            assert after >= before + 1
            # the failover span rides the SAME trace id past the dead
            # replica's terminal close
            if req.trace is not None:
                spans = rt.read_trace(req.request_id)
                names = {s["name"] for s in spans}
                assert "failover" in names
        finally:
            eng_a.stop()
            eng_b.stop()


class TestFleetDrainMigration:
    """Fleet scale-in of a BUSY replica completes via live migration in
    bounded time — one migration per request, not request completion —
    and fleet.jsonl records tokens_migrated (the forced-reap fix)."""

    def test_scale_in_busy_replica_migrates_then_reaps(
        self, jax_cpu, tmp_path
    ):
        import json

        from modal_examples_tpu.fleet.autoscaler import FleetAutoscaler
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )
        from modal_examples_tpu.serving import SamplingParams

        sp = SamplingParams(max_tokens=96, temperature=0.0)
        eng_a = _mk_engine(max_model_len=192)
        eng_b = _mk_engine(params=eng_a.params, max_model_len=192)
        rep_a = EngineReplica(eng_a, "seed-a", role="unified")
        rep_b = EngineReplica(eng_b, "owned-b", role="unified")
        router = PrefixAffinityRouter([rep_a])
        journal = tmp_path / "fleet.jsonl"
        scaler = FleetAutoscaler(
            router,
            factory=lambda name, role: (_ for _ in ()).throw(
                AssertionError("no builds in this test")
            ),
            journal_path=journal,
            drain_timeout_s=60.0,
        )
        try:
            ref = eng_a.submit(PROMPT, sp)
            ref_text = "".join(eng_a.stream(ref))
            ref_tokens = list(ref.generated_tokens)

            router.add_replica(rep_b)
            scaler._owned["decode"].append("owned-b")
            req = rep_b.submit(PROMPT, sp)
            req._router_replica = rep_b
            pieces: list[str] = []
            t = threading.Thread(
                target=lambda: pieces.extend(router.stream(req))
            )
            t.start()
            assert _wait_tokens(req, 5)
            n_before = len(req.generated_tokens)

            # scale-in picks the BUSY owned replica (migration makes it
            # drain-safe) and the next reap pass migrates its stream off
            act = scaler._scale_down("decode", {})
            assert act is not None and act["replica"] == "owned-b"
            assert all(r.name != "owned-b" for r in router.replicas)
            t0 = time.monotonic()
            deadline = time.monotonic() + 60
            while scaler._draining and time.monotonic() < deadline:
                scaler._reap_drained(scaler._clock())
                time.sleep(0.01)
            assert not scaler._draining, "victim did not drain"
            drain_s = time.monotonic() - t0

            t.join(timeout=120)
            assert not t.is_alive()
            # the stream survived scale-in, token-identical
            assert req.finish_reason == ref.finish_reason
            assert req.generated_tokens == ref_tokens
            assert "".join(pieces) == ref_text
            # bounded by the migration, not by request completion: the
            # victim was gone long before the 96-token stream finished
            assert drain_s < 30.0
            assert not eng_b._running  # reaped after the drain
            records = [
                json.loads(line)
                for line in journal.read_text().splitlines()
                if line.strip()
            ]
            drains = [
                r for r in records if r.get("action") == "drain_migrate"
            ]
            assert drains, records
            assert sum(r.get("tokens_migrated", 0) for r in drains) >= min(
                n_before, 5
            )
            # no forced reap killed the stream
            assert not any(
                r.get("trigger") == "drain_timeout" for r in records
            )
            assert _drained(eng_a) == []
        finally:
            scaler.stop(drain=False)
            eng_a.stop()
            eng_b.stop()


class TestWireEnvelopeCompat:
    """The decode-state leg is purely additive: plain PR-6 first-token
    blocks still decode and adopt; extended blocks round-trip."""

    def test_plain_block_still_adopts_first_token_lane(self, jax_cpu):
        from modal_examples_tpu.scheduling import EngineReplica
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.serving.disagg import DisaggCoordinator

        eng_p = _mk_engine()
        eng_d = _mk_engine(params=eng_p.params)
        coord = DisaggCoordinator(
            [
                EngineReplica(eng_p, "cp-pre", role="prefill"),
                EngineReplica(eng_d, "cp-dec", role="decode"),
            ],
            chunk_bytes=256,
        )
        try:
            ref = eng_d.submit(
                PROMPT, SamplingParams(max_tokens=8, temperature=0.0)
            )
            ref_text = "".join(eng_d.stream(ref))
            req = coord.submit(
                PROMPT, SamplingParams(max_tokens=8, temperature=0.0)
            )
            out = "".join(coord.stream(req))
            assert out == ref_text
        finally:
            eng_d.stop()

    def test_extended_block_roundtrips_resume_leg(self, jax_cpu):
        from modal_examples_tpu.serving.disagg.transport import (
            deserialize_block,
            extract_pages,
            serialize_block,
        )

        eng = _mk_engine()
        block = extract_pages(
            eng.cache, [1, 2],
            meta={
                "position": 17,
                "first_token": 42,
                "resume": {"generated": [1, 2, 3], "emitted_len": 5},
            },
        )
        out = deserialize_block(serialize_block(block))
        assert out.meta["resume"] == {
            "generated": [1, 2, 3], "emitted_len": 5,
        }
        assert out.meta["position"] == 17
