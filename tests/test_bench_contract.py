"""Driver-contract tests: bench.py must print exactly one JSON line with the
required schema, and must degrade (not hang) when a model config fails."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]


@pytest.mark.slow
def test_bench_emits_schema_json():
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=500,
        env={**os.environ, "BENCH_CPU": "1", "BENCH_MODEL": "tiny"},
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE line, got {len(lines)}: {lines}"
    payload = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in payload, payload
    assert payload["value"] > 0
    assert payload["unit"] == "tok/s"
    # phase-attributed latency: every BENCH_*.json carries p50/p95/p99 per
    # engine phase from the observability histograms (docs/observability.md)
    pl = payload.get("phase_latency")
    assert pl, payload
    some = pl.get("prefill") or pl.get("decode_wait")
    assert some and {"p50", "p95", "p99", "count"} <= set(some)
    # token-level serving latency (ISSUE-3): TTFT/TPOT p50/p95 + tokens/s
    # ride alongside phase_latency in every BENCH json
    tl = payload.get("token_latency")
    assert tl and "ttft" in tl and "tpot" in tl, payload
    for key in ("ttft", "tpot"):
        assert {"p50", "p95", "count"} <= set(tl[key]), tl
        assert tl[key]["p50"] <= tl[key]["p95"]
        assert tl[key]["count"] >= 1
    # scheduling telemetry (ISSUE-4): per-class admission queue-wait
    # quantiles + shed rate ride in every BENCH json
    sched = payload.get("scheduling")
    assert sched, payload
    assert {"queue_wait", "shed_rate", "sheds_total"} <= set(sched), sched
    dq = sched["queue_wait"].get("default")  # bench traffic is default-class
    assert dq and {"p50", "p95", "count"} <= set(dq), sched
    assert dq["p50"] <= dq["p95"]
    assert 0.0 <= sched["shed_rate"] <= 1.0
    assert sched["shed_rate"] == 0.0  # bench must never overload itself
    # KV-cache footprint (ISSUE-5): dtype-aware bytes + the slots-at-HBM
    # headroom figure ride in every BENCH json (int8 KV shows ~2x here)
    kv = payload.get("kv_cache")
    assert kv, payload
    assert {"dtype", "bytes", "bytes_per_slot", "max_slots_at_hbm"} <= set(kv)
    assert kv["dtype"] in ("bfloat16", "int8", "float32")
    assert kv["bytes"] > 0 and kv["bytes_per_slot"] > 0
    assert kv["max_slots_at_hbm"] > 0  # tiny model: plenty of HBM headroom
    assert payload["tokens_per_second"] == payload["value"]
    # hot-path overhead attribution (docs/observability.md#hot-path-
    # profiling): EVERY bench config's json carries the `overhead` section
    # — bench children run MTPU_PROFILE=1 — with per-phase attribution
    # summing to ~the tick duration (cover ≤ 1 structurally: sequential
    # marks partition the tick) and a nonzero compile ledger. Structure
    # only — wall-clock DIRECTION lives behind the on-chip benchdiff gate.
    ov = payload.get("overhead")
    assert ov, payload
    assert {"ticks", "host_fraction", "tick_p50", "tick_p95", "detok_share",
            "attribution_cover", "phases", "compile_total_s",
            "compiles_n"} <= set(ov), ov
    assert ov["ticks"] >= 1
    assert 0.0 <= ov["host_fraction"] <= 1.0
    assert 0 < ov["tick_p50"] <= ov["tick_p95"]
    assert 0.0 <= ov["detok_share"] <= 1.0
    assert 0.8 <= ov["attribution_cover"] <= 1.0 + 1e-6
    # the full non-spec tick anatomy shows up under real traffic
    for phase in ("admit", "prefill_dispatch", "decode_dispatch", "harvest",
                  "detokenize", "accept"):
        assert phase in ov["phases"], (phase, ov["phases"])
        assert ov["phases"][phase]["p50"] <= ov["phases"][phase]["p95"]
    # nonzero compile ledger: at least the block + one prefill bucket built
    assert ov["compiles_n"] >= 2
    assert ov["compile_total_s"] > 0
    # flight recorder ride-along (docs/observability.md#metrics-history):
    # bench children default MTPU_TSDB=1, so the overhead section carries
    # the sampler's own cost next to the host-overhead numbers the sampler
    # must not move (benchdiff's existing overhead.* gates are the proof)
    ts = ov.get("tsdb")
    assert ts, ov
    assert {"samples", "series", "scrape_p50", "scrape_p95"} <= set(ts), ts
    assert ts["samples"] >= 1
    assert ts["series"] >= 1
    if ts["scrape_p95"] is not None:
        assert 0.0 <= ts["scrape_p50"] <= ts["scrape_p95"]
    # roofline utilization accounting (docs/observability.md#roofline-and-
    # usage-accounting): EVERY bench json carries a deterministic
    # `utilization` section — the work model is analytic, so it exists even
    # on CPU (the achieved fractions are tiny there, but the SHAPE and the
    # work-model constants are the contract benchdiff gates against)
    util = payload.get("utilization")
    assert util, payload
    assert {"mfu", "mbu", "bound", "tokens_per_second_per_chip",
            "generation", "chips", "per_phase", "work_model"} <= set(util)
    assert 0.0 <= util["mfu"] <= 1.5, util  # sanity roof, not a target
    assert 0.0 <= util["mbu"] <= 1.5, util
    assert util["bound"] in ("compute", "bandwidth")
    assert util["tokens_per_second_per_chip"] > 0
    assert util["chips"] >= 1
    for phase in ("prefill", "decode"):
        p = util["per_phase"][phase]
        assert {"flops", "bytes", "device_seconds", "mfu", "mbu"} <= set(p)
        assert p["flops"] > 0 and p["bytes"] > 0
        assert p["device_seconds"] > 0  # the clock brackets really ran
    wm = util["work_model"]
    assert wm["n_params"] > 0 and wm["weight_bytes"] > 0
    assert wm["kv_bytes_per_token"] > 0


@pytest.mark.slow
def test_bench_disagg_config_emits_disagg_section():
    """The two-replica disagg config must ride the same schema plus a
    ``disagg`` section: migration volume, latency quantiles, and the tiered
    prefix cache's hit mix (docs/disagg.md)."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=500,
        env={
            **os.environ,
            "BENCH_CPU": "1",
            "BENCH_MODEL": "tiny-disagg",
            "BENCH_NO_SECONDARY": "1",
        },
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    payload = json.loads(lines[0])
    assert payload["value"] > 0 and payload["unit"] == "tok/s"
    disagg = payload.get("disagg")
    assert disagg, payload
    assert {"pages_migrated", "migration_bytes", "migrations",
            "migration_latency", "tier_hits", "tier_hit_rates"} <= set(disagg)
    assert disagg["pages_migrated"] > 0
    assert disagg["migrations"]["ok"] > 0
    # bench traffic must migrate cleanly, not limp through fallback
    assert disagg["migrations"]["fallback"] == 0
    lat = disagg["migration_latency"]
    assert lat and lat["p50"] <= lat["p95"] and lat["count"] > 0
    rates = disagg["tier_hit_rates"]
    assert all(0.0 <= v <= 1.0 for v in rates.values())


@pytest.mark.slow
def test_bench_chaos_config_emits_faults_section():
    """The chaos config must ride the same schema plus a ``faults``
    section: the seeded episode schedule runs after the measured traffic
    and the report — injected per point, recoveries, zero wedged — rides
    in the json (docs/faults.md). A failure-handling regression breaks the
    bench contract, not just the test suite."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=500,
        env={
            **os.environ,
            "BENCH_CPU": "1",
            "BENCH_MODEL": "tiny-chaos",
            "BENCH_NO_SECONDARY": "1",
        },
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    payload = json.loads(lines[0])
    assert payload["value"] > 0 and payload["unit"] == "tok/s"
    faults = payload.get("faults")
    assert faults, payload
    assert {"injected", "per_point", "recovered", "wedged",
            "points_missed", "invariants", "episodes"} <= set(faults)
    assert faults["wedged"] == 0
    assert faults["invariants"] == "ok"
    assert faults["points_missed"] == []
    assert faults["injected"] >= len(faults["per_point"]) >= 12
    assert faults["recovered"] > 0
    # the measured number itself stays fault-free
    assert payload["engine_errors"] == 0


@pytest.mark.slow
def test_bench_fleet_config_emits_fleet_section():
    """The fleet config must ride the same schema plus a ``fleet``
    section: the calibrated saturating open-loop sweep (pinned vs
    autoscaled arms), the knee, the scaled-fleet A/B, shed rate, and the
    scale events with their snapshot-restored warm boots (docs/fleet.md).
    ``fleet.goodput`` / ``fleet.p99_tpot_at_knee`` are what benchdiff
    gates round over round."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=500,
        env={
            **os.environ,
            "BENCH_CPU": "1",
            "BENCH_MODEL": "tiny-fleet",
            "BENCH_NO_SECONDARY": "1",
        },
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    payload = json.loads(lines[0])
    assert payload["value"] > 0 and payload["unit"] == "tok/s"
    fleet = payload.get("fleet")
    assert fleet, payload
    assert {"arrival", "capacity_rps", "rates", "knee_rps", "goodput",
            "p99_tpot_at_knee", "shed_rate", "ab", "sweep",
            "scale_events"} <= set(fleet)
    assert fleet["capacity_rps"] > 0
    assert len(fleet["rates"]) == 3
    assert fleet["goodput"] > 0
    assert 0.0 <= fleet["shed_rate"] <= 1.0
    # the sweep arms: every step terminal, nothing wedged
    for arm in ("pinned", "autoscaled"):
        steps = fleet["sweep"][arm]
        assert len(steps) == 3
        for s in steps:
            assert s["wedged"] == 0, (arm, s)
            assert s["offered"] >= s["completed"] + s["shed"] - 1
    # the saturating step must actually saturate the pinned replica
    assert fleet["sweep"]["pinned"][-1]["shed"] > 0
    # scale-out happened, via snapshot-restored warm boots, and the
    # idle tail scaled the fleet back to its floor
    ev = fleet["scale_events"]
    assert ev["up"] >= 1 and ev["warm_boots"] == ev["up"]
    assert fleet["scaled_back_to"] == 1
    ab = fleet["ab"]
    assert ab["scaled_out"] is True
    for side in ("pinned", "autoscaled"):
        assert {"goodput_rps", "shed_rate", "ttft_p99", "tpot_p99",
                "wedged"} <= set(ab[side])
        assert ab[side]["wedged"] == 0
    assert ab["improvement_goodput"] > 0
    assert payload["engine_errors"] == 0


@pytest.mark.slow
def test_bench_failover_config_emits_failover_section():
    """The failover config must ride the same schema plus a ``failover``
    section: streams killed mid-decode on one replica and
    checkpoint-resumed on another — client-observed takeover latency
    p50/p95, generated-prefix tokens replayed, and the exactness verdict
    (docs/failover.md). ``failover.takeover_latency.p95`` is what
    benchdiff gates round over round."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=500,
        env={
            **os.environ,
            "BENCH_CPU": "1",
            "BENCH_MODEL": "tiny-failover",
            "BENCH_NO_SECONDARY": "1",
        },
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    payload = json.loads(lines[0])
    assert payload["value"] > 0 and payload["unit"] == "tok/s"
    fo = payload.get("failover")
    assert fo, payload
    assert {"streams", "failovers", "takeover_latency", "tokens_replayed",
            "resumed_identical"} <= set(fo)
    assert fo["streams"] >= 1
    assert fo["failovers"] >= 1
    lat = fo["takeover_latency"]
    assert {"p50", "p95", "count"} <= set(lat)
    assert 0 < lat["p50"] <= lat["p95"] and lat["count"] >= 1
    assert fo["tokens_replayed"] >= 1
    # the exactness contract IS the section's verdict: every resumed
    # stream byte-identical to its fault-free reference
    assert fo["resumed_identical"] is True
    # the measured headline number stays fault-free
    assert payload["engine_errors"] == 0


@pytest.mark.slow
def test_bench_recovery_config_emits_recovery_section():
    """The recovery config must ride the same schema plus a ``recovery``
    section: a replica's scheduler SILENTLY frozen (no crash, no error)
    with streams mid-decode — the progress watchdog detects the wedge from
    stale watermarks, error-stops the replica, and the failover resumes
    every stream token-identically (docs/health.md).
    ``recovery.time_to_mitigate.p95`` is what benchdiff gates round over
    round."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=500,
        env={
            **os.environ,
            "BENCH_CPU": "1",
            "BENCH_MODEL": "tiny-recovery",
            "BENCH_NO_SECONDARY": "1",
        },
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    payload = json.loads(lines[0])
    assert payload["value"] > 0 and payload["unit"] == "tok/s"
    rec = payload.get("recovery")
    assert rec, payload
    assert {"episodes", "streams", "time_to_detect", "time_to_mitigate",
            "goodput_dip", "wedged", "resumed_identical"} <= set(rec)
    assert rec["episodes"] >= 1 and rec["streams"] >= 1
    for key in ("time_to_detect", "time_to_mitigate"):
        assert {"p50", "p95"} <= set(rec[key]), rec
        assert 0 < rec[key]["p50"] <= rec[key]["p95"], rec
    # detection precedes mitigation on the same clock
    assert rec["time_to_detect"]["p50"] <= rec["time_to_mitigate"]["p50"]
    assert 0.0 <= rec["goodput_dip"] <= 1.0
    # the contract headline: a silent hang wedges NOTHING, and every
    # resumed stream is byte-identical to its fault-free reference
    # (on mismatch the bench prints per-request forensics to stderr)
    assert rec["wedged"] == 0, out.stderr[-1200:]
    assert rec["resumed_identical"] is True, out.stderr[-1200:]
    # the measured headline number stays fault-free
    assert payload["engine_errors"] == 0


@pytest.mark.slow
def test_bench_mixed_config_emits_interference_section():
    """The mixed-traffic config must ride the same schema plus an
    ``interference`` section: the budget-on vs budget-off TPOT A/B for an
    interactive stream under long-prompt arrivals, and the decode-stall
    dispatch-gap histogram (docs/scheduling.md, stall-free admission)."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=500,
        env={
            **os.environ,
            "BENCH_CPU": "1",
            "BENCH_MODEL": "tiny-mixed",
            "BENCH_NO_SECONDARY": "1",
        },
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    payload = json.loads(lines[0])
    assert payload["value"] > 0 and payload["unit"] == "tok/s"
    inter = payload.get("interference")
    assert inter, payload
    assert {"budget_tokens", "chunk_tokens", "budgeted", "unbudgeted",
            "improvement_p95", "decode_stall"} <= set(inter)
    assert inter["budget_tokens"] == 64
    for arm in ("budgeted", "unbudgeted"):
        stats = inter[arm]
        assert {"tpot_p50", "tpot_p95", "tpot_max", "pieces"} <= set(stats)
        assert stats["pieces"] > 0
        assert 0.0 <= stats["tpot_p50"] <= stats["tpot_p95"] <= stats["tpot_max"]
    assert inter["improvement_p95"] > 0
    stall = inter["decode_stall"]
    assert {"p50", "p95", "count"} <= set(stall)
    assert stall["count"] >= 1 and stall["p50"] <= stall["p95"]
    # the stall-free contract itself is timing-sensitive on shared CI
    # hardware, so the hard direction assertion (budgeted p95 < unbudgeted)
    # lives in the on-chip revalidation stage, not here — but the mixed run
    # must never error
    assert payload["engine_errors"] == 0


@pytest.mark.slow
def test_bench_multistep_config_emits_multistep_section():
    """The macro-step config must ride the same schema plus a ``multistep``
    section: the N=1 vs N=8 A/B on the same warm engine
    (docs/multistep.md). Direction checks assert the quantities the
    macro-step runtime structurally amortizes — tokens-per-dispatch up,
    per-token tick tail and scheduler-thread seconds per token down. Raw
    host_fraction direction is an on-chip affair (on the CPU path-proof
    the "device" is the host's own cores, so wall-clock attribution is
    contention noise); here it just must be present and sane."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=500,
        env={
            **os.environ,
            "BENCH_CPU": "1",
            "BENCH_MODEL": "tiny-multistep",
            "BENCH_NO_SECONDARY": "1",
        },
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    payload = json.loads(lines[0])
    assert payload["value"] > 0 and payload["unit"] == "tok/s"
    ms = payload.get("multistep")
    assert ms, payload
    assert {"steps", "classic", "multistep", "tokens_per_dispatch"} <= set(ms)
    assert ms["steps"] == 8
    for arm in ("classic", "multistep"):
        stats = ms[arm]
        assert {"dispatches", "tokens", "tokens_per_dispatch",
                "host_fraction", "tick_p95",
                "host_ms_per_token"} <= set(stats), stats
        assert stats["dispatches"] > 0 and stats["tokens"] > 0
        assert 0.0 <= stats["host_fraction"] <= 1.0
        assert stats["tick_p95"] > 0 and stats["host_ms_per_token"] > 0
    # the amortization itself: N=8 harvests several-fold more tokens per
    # blocking device read than one-block-per-dispatch (decode_block=1)
    assert (
        ms["multistep"]["tokens_per_dispatch"]
        > 2 * ms["classic"]["tokens_per_dispatch"]
    ), ms
    assert ms["tokens_per_dispatch"] == ms["multistep"]["tokens_per_dispatch"]
    # ... and it buys real scheduler-thread time per token: the per-token
    # tick tail and host seconds per token must DROP on the macro-step arm
    assert ms["tick_p95_delta"] > 0, ms
    assert ms["host_ms_per_token_delta"] > 0, ms
    assert payload["engine_errors"] == 0


@pytest.mark.slow
def test_bench_tp_config_emits_sharded_plan():
    """The TP=2 config must ride the same schema plus the resolved
    per-shard plan: ``tp`` at the top level and ``impl_plan`` reporting the
    variant each device actually runs (paged_impl_plan(mesh=...)) — the
    CPU path-proof of llama2-7b-tp2-int8-ctx1024's code shape."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=500,
        env={
            **os.environ,
            "BENCH_CPU": "1",
            "BENCH_MODEL": "tiny-tp2",
            "BENCH_NO_SECONDARY": "1",
        },
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    payload = json.loads(lines[0])
    assert payload["value"] > 0 and payload["unit"] == "tok/s"
    assert payload["tp"] == 2
    plan = payload.get("impl_plan")
    assert plan, payload
    assert plan["tp"] == 2
    # tiny (Hkv=2) shards to 1 head/device: the grouped formulation
    assert plan["attention"] == "ragged"
    assert plan["ragged_variant"] == "grouped"
    assert payload["engine_errors"] == 0


@pytest.mark.slow
def test_bench_spec_config_emits_spec_section():
    """The speculative configs must carry the acceptance-rate -> tok/s
    story: a ``spec`` section with mode/gamma/acceptance alongside the
    throughput number (ROADMAP open item #4's measurability half)."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=500,
        env={
            **os.environ,
            "BENCH_CPU": "1",
            "BENCH_MODEL": "tiny-spec-ngram",
            "BENCH_NO_SECONDARY": "1",
        },
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    payload = json.loads(lines[0])
    assert payload["value"] > 0 and payload["unit"] == "tok/s"
    spec = payload.get("spec")
    assert spec, payload
    assert spec["mode"] == "ngram" and spec["gamma"] == 2
    assert spec["proposed"] >= 0 and spec["accepted"] >= 0
    assert 0.0 <= spec["acceptance_rate"] <= 1.0
    assert payload["engine_errors"] == 0


@pytest.mark.slow
def test_bench_spec_adaptive_config_emits_ab_section():
    """tiny-spec-adaptive is the A/B the fused adaptive runtime is gated
    on (docs/speculative.md): two populations (high-acceptance /
    hostile) x three arms (off / fixed-gamma / adaptive) plus the
    benchdiff scalars utils/bench_diff.py tracks. The amortization claim
    — tokens_per_dispatch > 1 at high acceptance — is asserted here;
    the latency claim (adaptive_vs_off_tpot_p95) is asserted present and
    positive but not >= 1, because sub-10ms CPU tails are too noisy for
    a hard absolute gate — benchdiff gates it round-over-round."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=500,
        env={
            **os.environ,
            "BENCH_CPU": "1",
            "BENCH_MODEL": "tiny-spec-adaptive",
            "BENCH_NO_SECONDARY": "1",
        },
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    payload = json.loads(lines[0])
    assert payload["value"] > 0 and payload["unit"] == "tok/s"
    spec = payload.get("spec")
    assert spec, payload
    assert spec["mode"] == "ngram" and spec["gamma"] == 4
    # benchdiff-gated scalars (utils/bench_diff.py METRICS)
    assert {"gamma_p50", "tokens_per_dispatch", "fallback_rounds",
            "adaptive_vs_off_tpot_p95"} <= set(spec), spec
    assert spec["adaptive_vs_off_tpot_p95"] > 0
    # the A/B grid itself
    for pop in ("accept", "hostile"):
        arms = spec.get(pop)
        assert arms and {"off", "fixed", "adaptive"} <= set(arms), spec
        for arm, stats in arms.items():
            assert {"spec_rounds", "fallback_rounds", "gamma_p50",
                    "acceptance_rate", "tpot_p95"} <= set(stats), stats
        # the off arm never dispatches a fused round
        assert arms["off"]["spec_rounds"] == 0
        assert arms["off"]["proposed"] == 0
    accept_ad = spec["accept"]["adaptive"]
    # acceptance gate: on the self-similar population the fused round
    # harvests strictly more than one token per dispatch, at depth > 0
    assert accept_ad["spec_rounds"] > 0, spec
    assert accept_ad["tokens_per_dispatch"] > 1, spec
    assert accept_ad["gamma_p50"] > 0, spec
    assert spec["tokens_per_dispatch"] == accept_ad["tokens_per_dispatch"]
    # the hostile population must actually be hostile (low acceptance on
    # the fixed arm) and the controller must shrink depth relative to it
    hostile = spec["hostile"]
    assert hostile["fixed"]["acceptance_rate"] < 0.6, spec
    assert (
        hostile["adaptive"]["gamma_p50"] <= hostile["fixed"]["gamma_p50"]
    ), spec
    assert payload["engine_errors"] == 0


@pytest.mark.slow
def test_image_child_emits_schema_json():
    """The images/sec secondary metric (BASELINE.json: 'SDXL images/sec'):
    the txt2img pipeline child must print one JSON line; the tiny CPU
    path-proof must never claim the SD baseline."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--child-image"],
        capture_output=True,
        text=True,
        timeout=500,
        env={**os.environ, "BENCH_CPU": "1", "BENCH_IMAGE_TINY": "1"},
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    payload = json.loads(lines[-1])
    assert payload["unit"] == "img/s"
    assert payload["value"] > 0
    assert payload["vs_baseline"] == 0.0  # tiny path-proof: no baseline claim
    assert payload["sec_per_image"] > 0


def test_bench_supervisor_degrades_on_bad_model():
    """An impossible child must yield the error JSON line, not a hang."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=180,
        env={**os.environ, "BENCH_CPU": "1", "BENCH_MODEL": "nonexistent"},
        cwd=str(REPO),
    )
    # unknown BENCH_MODEL: supervisor KeyErrors per config -> error line path
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    if lines:
        payload = json.loads(lines[-1])
        assert "metric" in payload
    else:
        assert out.returncode != 0


@pytest.mark.slow
@pytest.mark.parametrize("flag,unit", [
    ("--child-embed", "tok/s"),
    ("--child-asr", "x-realtime"),
    ("--child-finetune", "train tok/s"),
])
def test_secondary_children_emit_schema_json(flag, unit):
    """Every BASELINE-config secondary child must print one JSON line in
    tiny mode — the same code shape the real TPU run takes (the finetune
    child's quantized base included)."""
    out = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), flag],
        capture_output=True,
        text=True,
        timeout=500,
        env={**os.environ, "BENCH_CPU": "1", "BENCH_TINY": "1"},
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    payload = json.loads(lines[-1])
    assert payload["unit"] == unit
    assert payload["value"] > 0
    assert payload["vs_baseline"] == 0.0  # no hard single-chip ref numbers
