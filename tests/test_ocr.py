"""OCR recognizer (conv + transformer + CTC): codec, decode semantics,
and the training signal (the reference's doc-OCR tier runs marker/datalab
CUDA models; models.ocr is the TPU-native counterpart)."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


class TestCodec:
    def test_text_roundtrip(self):
        from modal_examples_tpu.models import ocr

        for s in ["HELLO", "TOTAL 42.50", "A-1/B#2:"]:
            assert ocr.decode_labels(ocr.encode_text(s)) == s

    def test_unknown_chars_dropped(self):
        from modal_examples_tpu.models import ocr

        assert ocr.decode_labels(ocr.encode_text("a!b@c")) == "ABC"

    def test_render_has_ink_and_static_shape(self):
        from modal_examples_tpu.models import ocr

        cfg = ocr.OCRConfig(width=128)
        img = ocr.render_line("HELLO 123", cfg)
        assert img.shape == (cfg.height, cfg.width, 1)
        assert 0.0 <= img.min() and img.max() <= 1.0
        assert (img > 0.5).sum() > 50  # glyphs actually rendered


class TestGreedyDecode:
    def test_collapses_repeats_and_blanks(self, jax):
        """Hand-built logits: blank,A,A,blank,B,B -> 'AB' (the CTC
        collapse rule)."""
        from modal_examples_tpu.models import ocr

        cfg = ocr.OCRConfig(width=24, dim=16, n_layers=1, n_heads=2)

        # bypass the network: monkeypatch forward to return fixed logits
        a = ocr.CHARSET.index("A") + 1
        b = ocr.CHARSET.index("B") + 1
        T = cfg.seq_len
        path = [0, a, a, 0, b, b] + [0] * (T - 6)
        logits = np.full((1, T, cfg.n_classes), -10.0, np.float32)
        for t, cls in enumerate(path):
            logits[0, t, cls] = 10.0
        orig = ocr.forward
        ocr.forward = lambda p, i, c: logits
        try:
            out = ocr.greedy_decode({}, np.zeros((1, 32, 24, 1)), cfg)
        finally:
            ocr.forward = orig
        assert out == ["AB"]


@pytest.mark.slow
class TestTraining:
    def test_ctc_loss_decreases_and_reads_short_words(self, jax):
        """A few hundred steps on a 4-word closed vocabulary must drive the
        CTC loss down and read the words back — the real-learning proof at
        test budget (the example trains the open charset)."""
        import optax

        from modal_examples_tpu.models import ocr

        cfg = ocr.OCRConfig(width=64, dim=64, n_layers=1, n_heads=2)
        params = ocr.init_params(jax.random.PRNGKey(0), cfg)
        words = ["CAT", "DOG", "SUN", "BOX"]
        rng = np.random.default_rng(0)

        def batch(bs=16):
            texts = [words[int(rng.integers(0, 4))] for _ in range(bs)]
            images = np.stack(
                [ocr.render_line(t, cfg, jitter_rng=rng) for t in texts]
            )
            labels = np.zeros((bs, 5), np.int32)
            for i, t in enumerate(texts):
                ids = ocr.encode_text(t)
                labels[i, : len(ids)] = ids
            return images, labels, texts

        opt = optax.adam(3e-3)
        opt_state = opt.init(params)

        import jax as j

        @j.jit
        def step(params, opt_state, images, labels):
            loss, grads = j.value_and_grad(ocr.ctc_loss)(
                params, images, labels, cfg
            )
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        first = last = None
        for i in range(300):
            images, labels, _ = batch()
            params, opt_state, loss = step(params, opt_state, images, labels)
            if i == 0:
                first = float(loss)
            last = float(loss)
        assert last < first * 0.25, (first, last)

        images, _, texts = batch(8)
        pred = ocr.greedy_decode(params, images, cfg)
        exact = sum(p == t for p, t in zip(pred, texts))
        assert exact >= 6, list(zip(pred, texts))
