"""Vision-language serving: ViT tower correctness, multimodal prefill
exactness vs the dense forward, engine end-to-end with images, and the
OpenAI content-parts endpoint (the reference's sglang_vlm.py /
chat_with_pdf_vision.py workloads)."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def jnp(jax):
    import jax.numpy as jnp

    return jnp


@pytest.fixture(scope="module")
def setup(jax, jnp):
    from modal_examples_tpu.models import llama, vlm

    lcfg = llama.LlamaConfig.tiny()
    vcfg = vlm.VLMConfig(vision=vlm.ViTConfig.tiny(), llm_dim=lcfg.dim)
    lparams = llama.init_params(jax.random.PRNGKey(0), lcfg)
    vparams = vlm.init_vision_params(jax.random.PRNGKey(1), vcfg)
    return lcfg, vcfg, lparams, vparams


class TestViT:
    def test_encode_shapes(self, jax, jnp, setup):
        from modal_examples_tpu.models import vlm

        lcfg, vcfg, _, vparams = setup
        imgs = jax.random.uniform(jax.random.PRNGKey(2), (3, 16, 16, 3))
        out = vlm.encode_image(vparams, imgs, vcfg)
        assert out.shape == (3, vcfg.n_image_tokens, lcfg.dim)
        assert np.isfinite(np.asarray(out)).all()

    def test_patchify_row_major(self, jax, jnp):
        from modal_examples_tpu.models.vlm import patchify

        # image where pixel value encodes position: patch extraction must
        # be row-major with channels innermost
        img = jnp.arange(16 * 16 * 3, dtype=jnp.float32).reshape(1, 16, 16, 3)
        p = patchify(img, 8)
        assert p.shape == (1, 4, 8 * 8 * 3)
        # first element of patch (0, 1) is pixel (0, 8), channel 0
        assert float(p[0, 1, 0]) == float(img[0, 0, 8, 0])
        # first element of patch (1, 0) is pixel (8, 0), channel 0
        assert float(p[0, 2, 0]) == float(img[0, 8, 0, 0])

    def test_hf_vision_roundtrip(self, jax, jnp, setup, tmp_path):
        """Synthesize a CLIPVisionModel-named safetensors checkpoint + LLaVA
        projector, load it, and check the loaded tree encodes identically to
        a reference construction from the same tensors."""
        from safetensors.numpy import save_file

        from modal_examples_tpu.models import vlm

        lcfg, vcfg, _, _ = setup
        v = vcfg.vision
        rng = np.random.RandomState(0)
        raw = {}
        P = "vision_model."
        raw[P + "embeddings.patch_embedding.weight"] = rng.randn(
            v.dim, 3, v.patch_size, v.patch_size
        ).astype(np.float32)
        raw[P + "embeddings.position_embedding.weight"] = rng.randn(
            v.n_patches + 1, v.dim
        ).astype(np.float32)
        raw[P + "embeddings.class_embedding"] = rng.randn(v.dim).astype(
            np.float32
        )
        raw[P + "pre_layrnorm.weight"] = rng.randn(v.dim).astype(np.float32)
        raw[P + "pre_layrnorm.bias"] = rng.randn(v.dim).astype(np.float32)
        for i in range(v.n_layers):
            E = P + f"encoder.layers.{i}."
            for lin, shp in [
                ("self_attn.q_proj", (v.dim, v.dim)),
                ("self_attn.k_proj", (v.dim, v.dim)),
                ("self_attn.v_proj", (v.dim, v.dim)),
                ("self_attn.out_proj", (v.dim, v.dim)),
                ("mlp.fc1", (v.mlp_dim, v.dim)),
                ("mlp.fc2", (v.dim, v.mlp_dim)),
            ]:
                raw[E + lin + ".weight"] = rng.randn(*shp).astype(np.float32)
                raw[E + lin + ".bias"] = rng.randn(shp[0]).astype(np.float32)
            for ln in ["layer_norm1", "layer_norm2"]:
                raw[E + ln + ".weight"] = rng.randn(v.dim).astype(np.float32)
                raw[E + ln + ".bias"] = rng.randn(v.dim).astype(np.float32)
        raw["multi_modal_projector.linear_1.weight"] = rng.randn(
            lcfg.dim, v.dim
        ).astype(np.float32)
        raw["multi_modal_projector.linear_1.bias"] = rng.randn(
            lcfg.dim
        ).astype(np.float32)
        raw["multi_modal_projector.linear_2.weight"] = rng.randn(
            lcfg.dim, lcfg.dim
        ).astype(np.float32)
        raw["multi_modal_projector.linear_2.bias"] = rng.randn(
            lcfg.dim
        ).astype(np.float32)
        save_file(raw, str(tmp_path / "model.safetensors"))

        params = vlm.load_hf_vision_weights(tmp_path, vcfg)
        imgs = jax.random.uniform(jax.random.PRNGKey(3), (2, 16, 16, 3))
        out = vlm.encode_image(params, imgs, vcfg)
        assert out.shape == (2, vcfg.n_image_tokens, lcfg.dim)
        assert np.isfinite(np.asarray(out)).all()

        # spot-check the conv1 -> matmul mapping: a patch of ones through
        # the loaded patch_proj must equal the conv kernel's per-out-channel
        # sum (conv with stride=kernel on a ones image IS that sum)
        conv = raw["vision_model.embeddings.patch_embedding.weight"]
        want = conv.reshape(v.dim, -1).sum(axis=1)
        ones_patch = np.ones((1, v.patch_size * v.patch_size * 3), np.float32)
        got = np.asarray(ones_patch @ np.asarray(params["patch_proj"]))[0]
        np.testing.assert_allclose(got, want, rtol=2e-4)


class TestMultimodalEngine:
    def test_greedy_matches_teacher_forced_forward(self, jax, jnp, setup):
        """Engine generate with an image (greedy) must reproduce the dense
        forward's argmax continuation over [img_embeds; text] exactly — the
        multimodal analog of the paged-decode==forward proofs."""
        from modal_examples_tpu.models import llama, vlm
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        lcfg, vcfg, lparams, vparams = setup
        eng = LLMEngine(
            lcfg, params=lparams, max_slots=2, max_model_len=64,
            page_size=8, prefill_buckets=(16, 32), prefill_batch=2,
            vision=(vcfg, vparams),
        )
        img = np.random.RandomState(5).rand(16, 16, 3).astype(np.float32)
        prompt = "a small test"
        n_new = 6
        req = eng.submit(
            prompt, SamplingParams(max_tokens=n_new, temperature=0.0),
            image=img,
        )
        out = "".join(eng.stream(req))
        assert eng.error_count == 0, eng.error_log
        eng.stop()

        # reference: teacher-forced greedy on the dense forward
        embeds = vlm.encode_image(vparams, jnp.asarray(img)[None], vcfg)
        text = eng.tokenizer.encode(prompt)
        pad = eng.tokenizer.pad_id % lcfg.vocab_size
        seq = [pad] * vcfg.n_image_tokens + list(text)
        got_tokens = []
        for _ in range(n_new):
            logits = llama.forward(
                lparams, jnp.asarray([seq], jnp.int32), lcfg,
                attn_impl="xla", input_embeds=embeds,
            )
            nxt = int(jnp.argmax(logits[0, -1]))
            got_tokens.append(nxt)
            seq.append(nxt)
        want = eng.tokenizer.decode(got_tokens)
        assert out == want, (out, want)

    def test_different_images_different_outputs(self, jax, jnp, setup):
        """Two requests with identical text but different images must NOT
        share prefix-cache KV (their leading token ids are identical
        placeholders — the trie keys multimodal requests by image-content
        hash, so different images land in different branches)."""
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        lcfg, vcfg, lparams, vparams = setup
        eng = LLMEngine(
            lcfg, params=lparams, max_slots=2, max_model_len=64,
            page_size=8, prefill_buckets=(16, 32), prefill_batch=2,
            vision=(vcfg, vparams),
        )
        rng = np.random.RandomState(7)
        img_a = rng.rand(16, 16, 3).astype(np.float32)
        img_b = rng.rand(16, 16, 3).astype(np.float32)
        p = SamplingParams(max_tokens=8, temperature=0.0)
        out_a1 = "".join(eng.stream(eng.submit("describe", p, image=img_a)))
        out_b = "".join(eng.stream(eng.submit("describe", p, image=img_b)))
        out_a2 = "".join(eng.stream(eng.submit("describe", p, image=img_a)))
        assert eng.error_count == 0, eng.error_log
        eng.stop()
        assert out_a1 == out_a2  # deterministic per image
        assert out_a1 != out_b  # image actually conditions the output

    def test_same_image_reuses_prefix_pages(self, jax, jnp, setup):
        """Round 5: multimodal requests key the prefix trie by image
        CONTENT hash, so the same image + prompt hits cached pages on the
        second request (different-image isolation is the sibling test)."""
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        lcfg, vcfg, lparams, vparams = setup
        eng = LLMEngine(
            lcfg, params=lparams, max_slots=2, max_model_len=64,
            page_size=8, prefill_buckets=(16, 32), prefill_batch=2,
            vision=(vcfg, vparams),
        )
        img = np.random.RandomState(21).rand(16, 16, 3).astype(np.float32)
        p = SamplingParams(max_tokens=6, temperature=0.0)
        out1 = "".join(eng.stream(eng.submit("same picture", p, image=img)))
        hits_before = eng.prefix_cache.hits
        out2 = "".join(eng.stream(eng.submit("same picture", p, image=img)))
        assert eng.error_count == 0, eng.error_log
        eng.stop()
        assert out1 == out2
        assert eng.prefix_cache.hits > hits_before  # pages actually shared

    def test_text_only_still_works_alongside_mm(self, jax, jnp, setup):
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        lcfg, vcfg, lparams, vparams = setup
        eng = LLMEngine(
            lcfg, params=lparams, max_slots=2, max_model_len=64,
            page_size=8, prefill_buckets=(16, 32), prefill_batch=2,
            vision=(vcfg, vparams),
        )
        p = SamplingParams(max_tokens=4, temperature=0.0)
        img = np.random.RandomState(9).rand(16, 16, 3).astype(np.float32)
        r1 = eng.submit("plain text", p)
        r2 = eng.submit("with image", p, image=img)
        o1 = "".join(eng.stream(r1))
        o2 = "".join(eng.stream(r2))
        assert eng.error_count == 0, eng.error_log
        eng.stop()
        assert o1 and o2

    def test_image_without_vision_tower_rejected(self, jax, jnp, setup):
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        lcfg, _, lparams, _ = setup
        eng = LLMEngine(
            lcfg, params=lparams, max_slots=2, max_model_len=64,
            page_size=8, prefill_buckets=(16,), prefill_batch=1,
        )
        with pytest.raises(ValueError, match="without vision"):
            eng.submit("x", SamplingParams(max_tokens=2),
                       image=np.zeros((16, 16, 3), np.float32))
        eng.stop()


class TestVLMTensorParallel:
    """TP × vision (VERDICT r4 weak #6; sglang_vlm.py serves VLMs with
    --tp-size): image tokens are ordinary KV entries, so the composition
    runs the same sharded programs as text.

    Accuracy contract (docs/tensor_parallel.md): TP output is NOT asserted
    token-exact against single-device here. Row-parallel projections (wo /
    mlp down) psum partial f32 sums whose reduction order differs from the
    single-device contraction; the resulting ulp-level logit drift
    (measured ~1e-6 on this model, round 7) deterministically flips a
    greedy argmax when a tiny random model puts two logits within it. The
    contract is therefore tolerance on LOGITS + clean sharded serving —
    the same shape as the int8-KV TP tests. (Same-mesh comparisons ARE
    bit-exact: tests/test_sharded_pallas.py holds the pallas-vs-XLA TP
    paths to token equality.)"""

    def test_vlm_engine_tp2_tolerance_contract(self, jax, jnp):
        from modal_examples_tpu.models import llama, vlm
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        lcfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        vcfg = vlm.VLMConfig(vision=vlm.ViTConfig.tiny(), llm_dim=lcfg.dim)
        lparams = llama.init_params(jax.random.PRNGKey(0), lcfg)
        vparams = vlm.init_vision_params(jax.random.PRNGKey(1), vcfg)
        mesh = make_mesh({"tensor": 2}, devices=jax.devices()[:2])
        kw = dict(
            max_slots=2, max_model_len=64, page_size=16,
            prefill_buckets=(16, 32), prefill_batch=2, seed=0,
            kv_dtype=jnp.float32, vision=(vcfg, vparams),
        )
        tp = LLMEngine(lcfg, lparams, mesh=mesh, **kw)
        try:
            img = np.random.RandomState(11).rand(16, 16, 3).astype(np.float32)
            sp = SamplingParams(max_tokens=12, temperature=0.0)
            for prompt, image in [
                ("describe the image", img),
                ("plain text request", None),
            ]:
                got = "".join(tp.stream(tp.submit(prompt, sp, image=image)))
                assert got, (prompt, got)
            assert tp.error_count == 0, tp.error_log
            # the LLM is really sharded; the ViT tower is replicated
            assert len(tp.params["layers"]["wq"].sharding.device_set) == 2
            v_leaf = jax.tree.leaves(tp.vision_params)[0]
            assert len(v_leaf.sharding.device_set) == 2
        finally:
            tp.stop()

    def test_vlm_tp2_logit_drift_vs_single(self, jax, jnp):
        """The tolerance half of the contract, measured where it is
        deterministic: the fused vision-encode + multimodal prefill logits
        under TP2 stay within the documented psum-reordering drift of the
        single-device run, and the vision tower itself (replicated weights,
        replicated image) is BIT-exact — the drift is entirely the LLM's
        row-parallel reductions, not the vision path."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from modal_examples_tpu.models import llama, vlm
        from modal_examples_tpu.ops.kv_quant import shard_kv
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving.engine import _shard_params
        from modal_examples_tpu.serving.kv_cache import PagedKVCache

        lcfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        vcfg = vlm.VLMConfig(vision=vlm.ViTConfig.tiny(), llm_dim=lcfg.dim)
        lparams = llama.init_params(jax.random.PRNGKey(0), lcfg)
        vparams = vlm.init_vision_params(jax.random.PRNGKey(1), vcfg)
        mesh = make_mesh({"tensor": 2}, devices=jax.devices()[:2])
        img = np.random.RandomState(11).rand(16, 16, 3).astype(np.float32)
        images = jnp.asarray(vlm.preprocess_image(img, vcfg.vision.image_size))[
            None
        ]
        n_img = vcfg.n_image_tokens
        toks = np.zeros((1, 32), np.int32)
        toks[0, n_img : n_img + 3] = [5, 9, 11]
        toks = jnp.asarray(toks)
        seq_lens = jnp.asarray([n_img + 3], jnp.int32)
        tables = jnp.asarray(1 + np.arange(4).reshape(1, 4), jnp.int32)

        # vision encode: replicated x replicated must be bit-exact
        enc_single = jax.jit(
            lambda p, im: vlm.encode_image(p, im, vcfg)
        )(vparams, images)
        rep = NamedSharding(mesh, P())
        vparams_tp = jax.tree.map(
            lambda x: jax.device_put(x, rep), vparams
        )
        enc_tp = jax.jit(lambda p, im: vlm.encode_image(p, im, vcfg))(
            vparams_tp, images
        )
        np.testing.assert_array_equal(
            np.asarray(enc_single), np.asarray(enc_tp)
        )

        def run(shard):
            cache = PagedKVCache.create(
                n_layers=lcfg.n_layers, n_kv_heads=lcfg.n_kv_heads,
                head_dim=lcfg.head_dim, n_pages=8, page_size=16,
                kv_dtype=jnp.float32, prefer_native=False,
            )
            p, vp, m = lparams, vparams, None
            if shard:
                p = _shard_params(lparams, lcfg, mesh)
                vp = vparams_tp
                dsh = NamedSharding(
                    mesh, P(None, None, None, "tensor", None)
                )
                ssh = NamedSharding(mesh, P(None, None, None, "tensor"))
                cache.k_pages = shard_kv(cache.k_pages, dsh, ssh)
                cache.v_pages = shard_kv(cache.v_pages, dsh, ssh)
                m = mesh

            def fn(p, vp, kp, vpg, images, toks):
                embeds = vlm.encode_image(vp, images, vcfg)
                return llama.prefill(
                    p, toks, kp, vpg, tables, seq_lens, lcfg,
                    attn_impl="flash", input_embeds=embeds, mesh=m,
                )

            lo, _, _ = jax.jit(fn)(
                p, vp, cache.k_pages, cache.v_pages, images, toks
            )
            return np.asarray(lo)

        lo_s, lo_t = run(False), run(True)
        # documented contract: psum-reordering drift only — orders of
        # magnitude below 0.01, but the argmax CAN flip when two logits
        # land within it (why the serving test above isn't token-exact)
        assert float(np.max(np.abs(lo_s - lo_t))) < 0.01

    def test_mesh_accepts_pallas_impls(self, jax, jnp):
        """Round 7 (ROADMAP open item #2): mesh= + pallas impls no longer
        raise — the kernels run per head shard via ops.sharded's shard_map
        dispatch, and the plan reports the per-shard variant. The one
        genuinely illegal sharding (heads not divisible by the tensor
        axis) still fails loudly at construction."""
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import LLMEngine

        lcfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        lparams = llama.init_params(jax.random.PRNGKey(0), lcfg)
        mesh = make_mesh({"tensor": 2}, devices=jax.devices()[:2])
        eng = LLMEngine(lcfg, lparams, mesh=mesh, paged_impl="pallas")
        try:
            assert eng.impl_plan["attention"] == "ragged"
            assert eng.impl_plan["tp"] == 2
            # per-shard legality: Hkv//tp = 1 -> the grouped formulation
            assert eng.impl_plan["ragged_variant"] == "grouped"
            assert eng.impl_plan["downgraded"] == []
        finally:
            eng.stop()
        # heads not divisible by the tensor axis: loud, actionable error
        mesh4 = make_mesh({"tensor": 4}, devices=jax.devices()[:4])
        with pytest.raises(ValueError, match="divisible"):
            LLMEngine(lcfg, lparams, mesh=mesh4, paged_impl="pallas")


class TestOpenAIMultimodal:
    def test_chat_with_data_uri_image(self, jax, jnp, setup):
        import base64
        import io
        import json
        import urllib.request

        from PIL import Image

        from modal_examples_tpu.serving import LLMEngine, SamplingParams  # noqa
        from modal_examples_tpu.serving.openai_api import OpenAIServer
        from modal_examples_tpu.serving import LLMEngine

        lcfg, vcfg, lparams, vparams = setup
        eng = LLMEngine(
            lcfg, params=lparams, max_slots=2, max_model_len=64,
            page_size=8, prefill_buckets=(16, 32), prefill_batch=2,
            vision=(vcfg, vparams),
        )
        srv = OpenAIServer(eng, port=0).start()
        try:
            buf = io.BytesIO()
            Image.fromarray(
                (np.random.RandomState(3).rand(20, 20, 3) * 255).astype(
                    np.uint8
                )
            ).save(buf, format="PNG")
            uri = "data:image/png;base64," + base64.b64encode(
                buf.getvalue()
            ).decode()
            body = {
                "messages": [{
                    "role": "user",
                    "content": [
                        {"type": "text", "text": "what is this?"},
                        {"type": "image_url", "image_url": {"url": uri}},
                    ],
                }],
                "max_tokens": 4,
                "temperature": 0.0,
            }
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/chat/completions",
                data=json.dumps(body).encode(),
                headers={"content-type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                out = json.loads(r.read())
            assert out["choices"][0]["message"]["content"]
            assert eng.error_count == 0, eng.error_log

            # non-data URL is a 400, not a server-side fetch
            body["messages"][0]["content"][1]["image_url"]["url"] = (
                "http://example.com/x.png"
            )
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/chat/completions",
                data=json.dumps(body).encode(),
                headers={"content-type": "application/json"},
            )
            try:
                urllib.request.urlopen(req, timeout=30)
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            srv.stop()
