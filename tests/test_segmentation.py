"""Promptable segmentation (SAM-family): encode-once/decode-per-prompt
contract, prompt-dependence, and the training signal."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # training loop: excluded from the fast tier


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def jnp(jax):
    import jax.numpy as jnp

    return jnp


class TestSAM:
    def test_shapes_and_encode_once(self, jax, jnp):
        from modal_examples_tpu.models import segmentation as sam

        cfg = sam.SAMConfig(image_size=32, stride=8, dim=64)
        params = sam.init_params(jax.random.PRNGKey(0), cfg)
        imgs = jax.random.uniform(jax.random.PRNGKey(1), (2, 32, 32, 3))
        feats = sam.encode_image(params, imgs, cfg)
        assert feats.shape == (2, 16, 64)
        # many prompts reuse ONE embedding (SAM's interactive contract)
        for px in (0.2, 0.8):
            pts = jnp.full((2, 2), px)
            logits, iou = sam.decode_mask(params, feats, pts, cfg)
            assert logits.shape == (2, 32, 32)
            assert iou.shape == (2,)
            assert np.isfinite(np.asarray(logits)).all()

    def test_training_learns_click_conditioned_masks(self, jax, jnp):
        """After a short train, clicking shape A must segment A (IoU above
        chance) and clicking B must give a DIFFERENT mask — promptability,
        not just foreground detection."""
        import optax

        from modal_examples_tpu.models import segmentation as sam

        # 64 px / grid 8: the encoder downsamples 8x, so 32 px gives a
        # 4x4 grid — too coarse to localize the small shapes
        cfg = sam.SAMConfig(image_size=64, stride=8, dim=96)
        params = sam.init_params(jax.random.PRNGKey(0), cfg)
        opt = optax.adam(2e-3)
        opt_state = opt.init(params)

        import jax as j

        batch_fn = j.jit(
            lambda k: sam.synthetic_batch(k, 16, cfg), backend="cpu"
        )

        @j.jit
        def step(params, opt_state, imgs, pts, msks):
            loss, grads = j.value_and_grad(sam.segmentation_loss)(
                params, imgs, pts, msks, cfg
            )
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        key = jax.random.PRNGKey(1)
        first = last = None
        for i in range(500):
            key, sub = jax.random.split(key)
            imgs, pts, msks = batch_fn(sub)
            params, opt_state, loss = step(params, opt_state, imgs, pts, msks)
            if i == 0:
                first = float(loss)
            last = float(loss)
        assert last < first * 0.6, (first, last)

        # evaluate: mean IoU on fresh scenes must beat chance by a margin
        imgs, pts, msks = sam.synthetic_batch(jax.random.PRNGKey(99), 16, cfg)
        feats = sam.encode_image(params, imgs, cfg)
        logits, _ = sam.decode_mask(params, feats, pts, cfg)
        pred = np.asarray(logits) > 0
        gt = np.asarray(msks) > 0.5
        inter = (pred & gt).sum(axis=(1, 2))
        union = (pred | gt).sum(axis=(1, 2)).clip(1)
        miou = float((inter / union).mean())
        # 500 CPU steps of a demo-scale model: ~0.3 mIoU (chance for these
        # small shapes is ~0.05; the example trains longer for quality)
        assert miou > 0.22, miou

        # promptability: two different clicks on ONE image -> different masks
        img, p0, m0 = sam.synthetic_scene(jax.random.PRNGKey(7), cfg)
        feats1 = sam.encode_image(params, img[None], cfg)
        la, _ = sam.decode_mask(params, feats1, p0[None], cfg)
        other = jnp.clip(1.0 - p0, 0.05, 0.95)
        lb, _ = sam.decode_mask(params, feats1, other[None], cfg)
        assert float(jnp.abs(la - lb).max()) > 0.5, (
            "mask does not depend on the click"
        )
