"""Memory-snapshot subsystem tests (modal_examples_tpu/snapshot/): store,
codec, capture/restore policy, FunctionSpec plumbing, the autoscaler's
first-warm-boot gate, prometheus accounting, and end-to-end second-boot
restores against real container worker processes — including the
examples/06_gpu_and_ml/tpu_snapshot.py Embedder (the gpu_snapshot.py analog
in BASELINE.json)."""

import collections
import json
import os
import threading
import types

import pytest

import modal_examples_tpu as mtpu
from modal_examples_tpu.core.app import load_module_from_path
from modal_examples_tpu.core.executor import FunctionPool
from modal_examples_tpu.snapshot import build_and_enter, codec
from modal_examples_tpu.snapshot.store import (
    SnapshotStore,
    compute_snapshot_key,
    default_root,
    source_hash_for,
)
from modal_examples_tpu.utils.metrics import (
    SNAPSHOT_BOOTS_METRIC,
    SNAPSHOT_CAPTURES_METRIC,
    record_snapshot_boot,
)
from modal_examples_tpu.utils.prometheus import Registry, default_registry


@pytest.fixture()
def store(tmp_path):
    return SnapshotStore(root=tmp_path / "snaps")


# --------------------------------------------------------------------------
# Store
# --------------------------------------------------------------------------


class TestStore:
    def test_roundtrip(self, store):
        assert not store.has("k1")
        assert store.put("k1", b"payload", {"tag": "t"})
        assert store.has("k1")
        payload, meta = store.get("k1")
        assert payload == b"payload"
        assert meta["manifest"]["tag"] == "t"
        assert meta["size_bytes"] == 7

    def test_miss(self, store):
        assert store.get("nope") is None
        assert store.inspect("nope") is None

    def test_corrupt_payload_is_deleted(self, store):
        store.put("k1", b"payload")
        store._state_path("k1").write_bytes(b"garbage")
        assert store.get("k1") is None  # checksum mismatch
        assert not store.has("k1")  # corrupt entry removed

    def test_missing_payload_is_deleted(self, store):
        store.put("k1", b"payload")
        store._state_path("k1").unlink()
        assert store.get("k1") is None
        assert not store.has("k1")

    def test_corrupt_meta_reads_as_miss_and_self_heals(self, store):
        store.put("k1", b"payload")
        store._meta_path("k1").write_text("{not json")
        assert not store.has("k1")  # parse-based: dead entry never reads live
        assert store.get("k1") is None
        assert not store._entry_dir("k1").exists()  # corrupt dir removed

    def test_put_replaces_corrupt_entry(self, store):
        store.put("k1", b"old")
        store._meta_path("k1").write_text("{not json")
        assert store.put("k1", b"new")  # rename onto corrupt dir: replace it
        payload, _ = store.get("k1")
        assert payload == b"new"

    def test_clear_removes_corrupt_entries(self, store):
        store.put("k1", b"x")
        store._meta_path("k1").write_text("{not json")
        assert store.clear() == 1
        assert not store._entry_dir("k1").exists()

    def test_malformed_env_knobs_fall_back_to_defaults(self, monkeypatch, tmp_path):
        from modal_examples_tpu.snapshot.store import DEFAULT_MAX_ENTRIES

        monkeypatch.setenv("MTPU_SNAPSHOT_MAX_ENTRIES", "lots")
        monkeypatch.setenv("MTPU_SNAPSHOT_MAX_BYTES", "1g")
        s = SnapshotStore(root=tmp_path)  # must not raise inside a boot path
        assert s.max_entries == DEFAULT_MAX_ENTRIES
        assert s.max_bytes is None

    def test_delete_and_clear(self, store):
        store.put("a", b"1")
        store.put("b", b"2")
        assert store.delete("a")
        assert not store.delete("a")
        assert store.clear() == 1
        assert store.entries() == []

    def test_lru_eviction_by_count(self, tmp_path):
        store = SnapshotStore(root=tmp_path, max_entries=2)
        store.put("a", b"1")
        store.put("b", b"2")
        store.get("a")  # a is now most recently used
        store.put("c", b"3")  # evicts b (least recently used)
        keys = {e["key"] for e in store.entries()}
        assert keys == {"a", "c"}

    def test_eviction_by_bytes(self, tmp_path):
        store = SnapshotStore(root=tmp_path, max_entries=100, max_bytes=10)
        store.put("a", b"x" * 8)
        store.put("b", b"y" * 8)  # total 16 > 10: oldest goes
        keys = {e["key"] for e in store.entries()}
        assert keys == {"b"}

    def test_first_writer_wins(self, store):
        store.put("k", b"first", {"tag": "one"})
        store.put("k", b"second", {"tag": "two"})
        payload, _ = store.get("k")
        assert payload == b"first"  # os.rename onto an existing dir fails

    def test_default_root_env_override(self, monkeypatch, tmp_path):
        monkeypatch.setenv("MTPU_SNAPSHOT_DIR", str(tmp_path / "custom"))
        assert default_root() == tmp_path / "custom"

    def test_from_volume_shares_across_replicas(self, tmp_path):
        vol = types.SimpleNamespace(local_path=tmp_path / "vol")
        s1 = SnapshotStore.from_volume(vol)
        s1.put("k", b"shared")
        s2 = SnapshotStore.from_volume(vol)
        payload, _ = s2.get("k")
        assert payload == b"shared"


class TestKey:
    BASE = dict(
        image_digest="img1", source_hash="src1", env={"A": "1"}, cls_params=b"p"
    )

    def test_deterministic(self):
        k1 = compute_snapshot_key(machine_tag="mt", **self.BASE)
        k2 = compute_snapshot_key(machine_tag="mt", **self.BASE)
        assert k1 == k2

    @pytest.mark.parametrize(
        "field,value",
        [
            ("image_digest", "img2"),
            ("source_hash", "src2"),
            ("env", {"A": "2"}),
            ("cls_params", b"q"),
        ],
    )
    def test_every_component_changes_key(self, field, value):
        base = compute_snapshot_key(machine_tag="mt", **self.BASE)
        changed = compute_snapshot_key(
            machine_tag="mt", **{**self.BASE, field: value}
        )
        assert base != changed

    def test_machine_tag_prefix(self):
        key = compute_snapshot_key(machine_tag="cafe1234", **self.BASE)
        assert key.startswith("cafe1234-")

    def test_source_hash_tracks_code(self):
        class A:
            def f(self):
                return 1

        class B:
            def f(self):
                return 2

        assert source_hash_for(A) != source_hash_for(B)
        assert source_hash_for(A) == source_hash_for(A)

    def test_source_hash_falls_back_to_fn_bytes(self):
        cls = types.new_class("Synthetic")  # no retrievable source
        assert source_hash_for(cls, b"bytes1") != source_hash_for(cls, b"bytes2")


# --------------------------------------------------------------------------
# Codec
# --------------------------------------------------------------------------

Point = collections.namedtuple("Point", "x y")


class TestCodec:
    def test_plain_roundtrip(self):
        state = {"a": 1, "b": "two", "c": [1, 2, {"d": (3, 4)}]}
        payload, rebuild = codec.encode_state(state)
        assert rebuild == []
        assert codec.decode_state(payload) == state

    def test_namedtuple_roundtrip(self):
        payload, rebuild = codec.encode_state({"p": Point(1, 2)})
        assert rebuild == []
        out = codec.decode_state(payload)
        assert out["p"] == Point(1, 2)
        assert isinstance(out["p"], Point)

    def test_jax_array_roundtrip(self):
        import jax.numpy as jnp
        import numpy as np

        arr = jnp.arange(6.0).reshape(2, 3)
        params = {"layer": {"w": arr, "b": jnp.ones(3)}}
        payload, rebuild = codec.encode_state({"params": params})
        assert rebuild == []
        out = codec.decode_state(payload)["params"]
        assert np.allclose(np.asarray(out["layer"]["w"]), np.asarray(arr))
        # decoded leaves are device arrays again, not numpy
        assert type(out["layer"]["w"]).__module__.startswith(("jax", "jaxlib"))

    def test_unpicklable_becomes_rebuild_marker(self):
        payload, rebuild = codec.encode_state(
            {"ok": 1, "lock": threading.Lock(), "gen": (x for x in range(3))}
        )
        assert sorted(rebuild) == ["gen", "lock"]
        assert codec.decode_state(payload) == {"ok": 1}

    def test_jitted_callable_roundtrips_or_is_marker(self):
        # jax versions differ: when cloudpickle can ship the jit wrapper it
        # round-trips (re-jitting lazily on first call — a compile-cache disk
        # hit); otherwise it must surface as a rebuild marker, never an error
        import jax

        payload, rebuild = codec.encode_state({"fn": jax.jit(lambda x: x + 1)})
        if rebuild:
            assert rebuild == ["fn"]
        else:
            out = codec.decode_state(payload)
            assert int(out["fn"](1)) == 2

    def test_encode_attr_raises_codec_error(self):
        with pytest.raises(codec.CodecError):
            codec.encode_attr(threading.Lock())


# --------------------------------------------------------------------------
# build_and_enter policy (in-process)
# --------------------------------------------------------------------------

_hook_calls = {"snap": 0, "plain": 0}


class Model:
    def snap_load(self):
        _hook_calls["snap"] += 1
        self.weights = {"w": [1.0, 2.0]}

    def plain_enter(self):
        _hook_calls["plain"] += 1
        self.client = object()  # per-boot, never snapshotted

    def exit_hook(self):
        pass


META = {
    "enter": ["snap_load", "plain_enter"],
    "exit": ["exit_hook"],
    "snap_enter": ["snap_load"],
}


@pytest.fixture(autouse=True)
def _reset_hook_calls():
    _hook_calls["snap"] = _hook_calls["plain"] = 0


class TestBuildAndEnter:
    def boot(self, tmp_path, key="key-1", meta=META, cls=Model, params=None):
        return build_and_enter(
            cls,
            params or {},
            meta,
            snapshot_key=key,
            snapshot_dir=str(tmp_path / "snaps"),
            tag="t.Model",
        )

    def test_miss_then_hit_skips_snap_hook(self, tmp_path):
        obj1, info1 = self.boot(tmp_path)
        assert info1 == {"snapshot": "miss", "captured": True}
        assert _hook_calls == {"snap": 1, "plain": 1}

        obj2, info2 = self.boot(tmp_path)
        assert info2["snapshot"] == "hit"
        assert info2["skipped_hooks"] == ["snap_load"]
        # the snap hook body did NOT re-execute; the plain hook ran again
        assert _hook_calls == {"snap": 1, "plain": 2}
        assert obj2.weights == {"w": [1.0, 2.0]}
        assert hasattr(obj2, "client")

    def test_no_key_means_off(self, tmp_path):
        _obj, info = build_and_enter(Model, {}, META, snapshot_key=None)
        assert info == {"snapshot": "off"}
        assert _hook_calls == {"snap": 1, "plain": 1}

    def test_kill_switch_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MTPU_SNAPSHOT", "0")
        _obj, info = self.boot(tmp_path)
        assert info == {"snapshot": "off"}
        store = SnapshotStore(root=tmp_path / "snaps")
        assert store.entries() == []

    def test_corrupted_entry_falls_back_to_cold_boot(self, tmp_path):
        self.boot(tmp_path)
        store = SnapshotStore(root=tmp_path / "snaps")
        store._state_path("key-1").write_bytes(b"garbage")
        obj, info = self.boot(tmp_path)
        assert info["snapshot"] == "fallback"
        assert info["captured"]  # re-captured for the next boot
        assert _hook_calls["snap"] == 2
        assert obj.weights == {"w": [1.0, 2.0]}
        _obj, info3 = self.boot(tmp_path)
        assert info3["snapshot"] == "hit"

    def test_lifecycle_shape_change_falls_back(self, tmp_path):
        self.boot(tmp_path)

        class Model2(Model):
            def extra_snap(self):
                self.extra = True

        meta2 = {
            "enter": ["snap_load", "extra_snap", "plain_enter"],
            "exit": [],
            "snap_enter": ["snap_load", "extra_snap"],
        }
        # same key (stale), different snap-hook set: restore must refuse
        _obj, info = self.boot(tmp_path, meta=meta2, cls=Model2)
        assert info["snapshot"] == "fallback"

    def test_unpicklable_snap_attr_reruns_owning_hook(self, tmp_path):
        calls = {"n": 0}

        class Jitty:
            def load(self):
                calls["n"] += 1
                self.weights = [1.0]
                self.compiled = threading.Lock()  # stands in for jax.jit

        meta = {"enter": ["load"], "exit": [], "snap_enter": ["load"]}
        _obj, info1 = self.boot(tmp_path, meta=meta, cls=Jitty)
        assert info1["captured"]
        obj2, info2 = self.boot(tmp_path, meta=meta, cls=Jitty)
        # still a hit, but the hook owning the rebuild marker re-runs
        assert info2["snapshot"] == "hit"
        assert info2["rerun_hooks"] == ["load"]
        assert calls["n"] == 2
        assert isinstance(obj2.compiled, type(threading.Lock()))

    def test_mutated_baseline_attr_reruns_owning_hook(self, tmp_path):
        calls = {"n": 0}

        class Placeholder:
            def __init__(self):
                self.client = None  # rebound to an unpicklable by the hook

            def load(self):
                calls["n"] += 1
                self.weights = [1.0]
                self.client = threading.Lock()

        meta = {"enter": ["load"], "exit": [], "snap_enter": ["load"]}
        _obj, info1 = self.boot(tmp_path, meta=meta, cls=Placeholder)
        assert info1["captured"]
        obj2, info2 = self.boot(tmp_path, meta=meta, cls=Placeholder)
        # the restored boot must NOT serve the __init__ placeholder: the
        # hook that rebound `client` re-runs
        assert info2["snapshot"] == "hit"
        assert info2["rerun_hooks"] == ["load"]
        assert calls["n"] == 2
        assert obj2.client is not None
        assert obj2.weights == [1.0]

    def test_hit_failure_after_non_snap_side_effects_raises(self, tmp_path):
        effects = []
        flag = tmp_path / "explode"

        class Sideful:
            def load(self):
                self.w = [1.0]

            def effect(self):
                effects.append("ran")  # external side effect (e.g. commit)

            def boom(self):
                if flag.exists():
                    raise RuntimeError("transient failure after side effects")

        meta = {
            "enter": ["load", "effect", "boom"],
            "exit": [],
            "snap_enter": ["load"],
        }
        self.boot(tmp_path, meta=meta, cls=Sideful)
        assert effects == ["ran"]
        flag.touch()
        # on the restored boot, `effect` completes before `boom` raises: a
        # silent cold rerun would double `effect` — the boot must fail like
        # a cold boot whose hook raised (and drop the entry for next time)
        with pytest.raises(RuntimeError, match="transient"):
            self.boot(tmp_path, meta=meta, cls=Sideful)
        assert effects == ["ran", "ran"]  # not tripled by a hidden cold rerun
        assert not SnapshotStore(root=tmp_path / "snaps").has("key-1")

    def test_poison_snapshot_is_deleted_and_boot_goes_cold(self, tmp_path):
        class Fragile:
            def load(self):
                self.mode = getattr(self, "mode", "good")

            def check(self):
                assert self.mode == "good"

        meta = {"enter": ["load", "check"], "exit": [], "snap_enter": ["load"]}
        self.boot(tmp_path, meta=meta, cls=Fragile)
        # poison the stored state: restored attr makes a later hook raise
        store = SnapshotStore(root=tmp_path / "snaps")
        payload, _ = store.get("key-1")
        bad, _ = codec.encode_state({"mode": "poison"})
        store.delete("key-1")
        store.put("key-1", bad, {"hook_attrs": {"load": ["mode"]}, "rebuild": []})
        obj, info = self.boot(tmp_path, meta=meta, cls=Fragile)
        # the boot survived, state is cold-boot-correct, entry was replaced
        assert obj.mode == "good"
        assert info["captured"]

    def test_params_applied_before_hooks(self, tmp_path):
        class P:
            def load(self):
                self.doubled = self.base * 2

        meta = {"enter": ["load"], "exit": [], "snap_enter": ["load"]}
        obj, _ = self.boot(tmp_path, meta=meta, cls=P, params={"base": 21})
        assert obj.doubled == 42


# --------------------------------------------------------------------------
# FunctionSpec / ContainerConfig plumbing (the silently-dropped-kwarg bugfix)
# --------------------------------------------------------------------------


class TestSpecPlumbing:
    def test_function_kwarg_reaches_spec(self):
        app = mtpu.App("snap-plumb-fn")

        @app.function(enable_memory_snapshot=True, serialized=True,
                      experimental_options={"x": 1})
        def f():
            return 1

        assert f.spec.enable_memory_snapshot is True
        assert f.spec.serialized is True
        assert f.spec.experimental_options == {"x": 1}

    def test_cls_kwarg_reaches_spec(self):
        app = mtpu.App("snap-plumb-cls")

        @app.cls(enable_memory_snapshot=True, experimental_options={"y": 2})
        class C:
            @mtpu.method()
            def m(self):
                return 1

        assert C._spec.enable_memory_snapshot is True
        assert C._spec.experimental_options == {"y": 2}

    def test_default_is_off(self):
        app = mtpu.App("snap-plumb-default")

        @app.cls()
        class C:
            @mtpu.method()
            def m(self):
                return 1

        assert C._spec.enable_memory_snapshot is False
        assert C._spec.container_config().snapshot_key is None

    def test_cls_container_config_resolves_key(self, monkeypatch, tmp_path):
        monkeypatch.setenv("MTPU_SNAPSHOT_DIR", str(tmp_path))
        app = mtpu.App("snap-plumb-key")

        @app.cls(enable_memory_snapshot=True)
        class C:
            @mtpu.enter(snap=True)
            def load(self):
                self.ready = True

            @mtpu.method()
            def m(self):
                return 1

        cfg = C._spec.container_config()
        assert cfg.snapshot_key is not None
        assert cfg.snapshot_dir == str(tmp_path)
        # key is stable across recomputation (supervisor/container agreement)
        assert C._spec.container_config().snapshot_key == cfg.snapshot_key

    def test_plain_function_gets_no_key(self):
        app = mtpu.App("snap-plumb-plainfn")

        @app.function(enable_memory_snapshot=True)
        def f():
            return 1

        # snapshots only apply to Cls lifecycles (no @enter hooks on plain fns)
        assert f.spec.container_config().snapshot_key is None

    def test_snap_enter_meta_collected(self):
        class C:
            @mtpu.enter(snap=True)
            def a(self):
                pass

            @mtpu.enter()
            def b(self):
                pass

        from modal_examples_tpu.core.cls import _collect_lifecycle

        meta = _collect_lifecycle(C)
        assert meta["snap_enter"] == ["a"]
        assert meta["enter"][0] == "a"  # snap hooks ordered first


# --------------------------------------------------------------------------
# Autoscaler first-warm-boot gate
# --------------------------------------------------------------------------


class TestSnapshotGate:
    def _fake_pool(self, tmp_path, key="gate-key"):
        cfg = types.SimpleNamespace(snapshot_key=key, snapshot_dir=str(tmp_path))
        return types.SimpleNamespace(
            _snapshot_gate=bool(key), container_config=cfg, containers=[]
        )

    def test_gate_holds_until_entry_or_warm_boot(self, tmp_path):
        pool = self._fake_pool(tmp_path)
        assert FunctionPool._snapshot_pending_first_capture(pool)
        assert FunctionPool._snapshot_pending_first_capture(pool)  # still held

    def test_gate_opens_when_store_has_entry(self, tmp_path):
        pool = self._fake_pool(tmp_path)
        SnapshotStore(root=tmp_path).put("gate-key", b"x")
        assert not FunctionPool._snapshot_pending_first_capture(pool)
        assert not pool._snapshot_gate  # open for good

    def test_gate_opens_after_first_warm_boot_without_capture(self, tmp_path):
        pool = self._fake_pool(tmp_path)
        pool.containers = [types.SimpleNamespace(ever_ready=True)]
        assert not FunctionPool._snapshot_pending_first_capture(pool)
        assert not pool._snapshot_gate

    def test_no_key_no_gate(self, tmp_path):
        pool = self._fake_pool(tmp_path, key=None)
        assert not FunctionPool._snapshot_pending_first_capture(pool)


# --------------------------------------------------------------------------
# Metrics
# --------------------------------------------------------------------------


class TestMetrics:
    def test_record_and_expose(self):
        reg = Registry()
        record_snapshot_boot("a.M", "miss", captured=True, registry=reg)
        record_snapshot_boot("a.M", "hit", registry=reg)
        record_snapshot_boot("a.M", "hit", registry=reg)
        assert reg.value(SNAPSHOT_BOOTS_METRIC, {"function": "a.M", "result": "hit"}) == 2
        assert reg.value(SNAPSHOT_BOOTS_METRIC, {"function": "a.M", "result": "miss"}) == 1
        assert reg.value(SNAPSHOT_CAPTURES_METRIC, {"function": "a.M"}) == 1
        text = reg.expose()
        assert "mtpu_snapshot_boots_total" in text
        assert 'result="hit"' in text
        assert "# TYPE mtpu_snapshot_boots_total counter" in text

    def test_unwritten_series_reads_zero(self):
        reg = Registry()
        assert reg.value(SNAPSHOT_BOOTS_METRIC, {"function": "x", "result": "hit"}) == 0.0


# --------------------------------------------------------------------------
# End-to-end: process backend, second boot restores
# --------------------------------------------------------------------------

e2e_app = mtpu.App("snapshot-e2e")


@e2e_app.cls(timeout=60, enable_memory_snapshot=True)
class SnapService:
    counter_file: str = mtpu.parameter(default="")

    @mtpu.enter(snap=True)
    def load(self):
        # side-effect counter shared across container processes
        with open(self.counter_file, "a") as f:
            f.write("x")
        self.weights = {"w": [3.0, 4.0]}

    @mtpu.method()
    def norm(self) -> float:
        w = self.weights["w"]
        return (w[0] ** 2 + w[1] ** 2) ** 0.5

    @mtpu.method()
    def boots(self) -> int:
        return os.path.getsize(self.counter_file)


def _boot_counts(tag):
    return {
        r: default_registry.value(
            SNAPSHOT_BOOTS_METRIC, {"function": tag, "result": r}
        )
        for r in ("hit", "miss", "fallback")
    }


class TestEndToEnd:
    def test_second_container_boot_restores(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MTPU_SNAPSHOT_DIR", str(tmp_path / "snaps"))
        counter = tmp_path / "enter-count"
        counter.touch()
        tag = "snapshot-e2e.SnapService"
        before = _boot_counts(tag)

        with e2e_app.run():
            svc = SnapService(counter_file=str(counter))
            assert svc.norm.remote() == 5.0
        assert counter.read_text() == "x"  # first boot ran the hook

        with e2e_app.run():
            svc = SnapService(counter_file=str(counter))
            assert svc.norm.remote() == 5.0  # restored state serves correctly
            assert svc.boots.remote() == 1
        # the snap hook body never re-executed in the second container
        assert counter.read_text() == "x"

        after = _boot_counts(tag)
        assert after["miss"] == before["miss"] + 1
        assert after["hit"] == before["hit"] + 1
        # hit/miss visible in the prometheus exposition
        assert "mtpu_snapshot_boots_total" in default_registry.expose()

        # one entry in the store, inspectable, attributed to this service
        store = SnapshotStore(root=tmp_path / "snaps")
        entries = store.entries()
        assert len(entries) == 1
        assert entries[0]["manifest"]["tag"] == tag
        assert entries[0]["manifest"]["hook_attrs"] == {"load": ["weights"]}

    def test_corrupt_store_still_boots(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MTPU_SNAPSHOT_DIR", str(tmp_path / "snaps"))
        counter = tmp_path / "enter-count"
        counter.touch()
        tag = "snapshot-e2e.SnapService"

        with e2e_app.run():
            svc = SnapService(counter_file=str(counter))
            assert svc.norm.remote() == 5.0

        store = SnapshotStore(root=tmp_path / "snaps")
        [entry] = store.entries()
        store._state_path(entry["key"]).write_bytes(b"garbage")
        before = _boot_counts(tag)

        with e2e_app.run():
            svc = SnapService(counter_file=str(counter))
            assert svc.norm.remote() == 5.0  # fallback boot, no error
        assert counter.read_text() == "xx"  # hook re-ran on the cold fallback
        after = _boot_counts(tag)
        assert after["fallback"] == before["fallback"] + 1


# --------------------------------------------------------------------------
# Example smoke: the tpu_snapshot.py Embedder, end-to-end, twice
# --------------------------------------------------------------------------


class TestExampleSmoke:
    def test_embedder_second_boot_is_snapshot_hit(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MTPU_SNAPSHOT_DIR", str(tmp_path / "snaps"))
        from modal_examples_tpu.utils.docs import repo_root

        module = load_module_from_path(
            str(repo_root() / "examples/06_gpu_and_ml/tpu_snapshot.py")
        )
        tag = "example-tpu-snapshot.Embedder"
        before = _boot_counts(tag)

        with module.app.run():
            r1 = module.Embedder().embed.remote(["first boot"])
        mid = _boot_counts(tag)
        assert mid["miss"] == before["miss"] + 1

        with module.app.run():
            r2 = module.Embedder().embed.remote(["second boot"])
        after = _boot_counts(tag)
        assert after["hit"] == mid["hit"] + 1
        assert r1["dim"] == r2["dim"] > 0

        # the captured entry holds the pure-state hook only; the jit warmup
        # hook is per-boot by design (unpicklable executables)
        store = SnapshotStore(root=tmp_path / "snaps")
        [entry] = store.entries()
        assert entry["manifest"]["hook_attrs"] == {"load": ["cfg", "params"]}
        assert entry["manifest"]["rebuild"] == []


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------


class TestCli:
    def test_list_inspect_clear(self, tmp_path, capsys):
        from modal_examples_tpu.core.cli import cmd_snapshot

        store = SnapshotStore(root=tmp_path)
        store.put("key-a", b"123", {"tag": "app.M"})

        assert cmd_snapshot(["list", "--dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "key-a" in out and "app.M" in out

        assert cmd_snapshot(["inspect", "key-a", "--dir", str(tmp_path)]) == 0
        meta = json.loads(capsys.readouterr().out)
        assert meta["key"] == "key-a"
        assert meta["manifest"]["tag"] == "app.M"

        assert cmd_snapshot(["clear", "--dir", str(tmp_path)]) == 0
        assert store.entries() == []
        assert cmd_snapshot(["list", "--dir", str(tmp_path)]) == 0
        assert "no snapshots" in capsys.readouterr().out

    def test_clear_single_key(self, tmp_path):
        from modal_examples_tpu.core.cli import cmd_snapshot

        store = SnapshotStore(root=tmp_path)
        store.put("key-a", b"1")
        store.put("key-b", b"2")
        assert cmd_snapshot(["clear", "key-a", "--dir", str(tmp_path)]) == 0
        assert {e["key"] for e in store.entries()} == {"key-b"}
        assert cmd_snapshot(["clear", "key-a", "--dir", str(tmp_path)]) == 1

    def test_inspect_missing_key_errors(self, tmp_path):
        from modal_examples_tpu.core.cli import cmd_snapshot

        with pytest.raises(SystemExit):
            cmd_snapshot(["inspect", "nope", "--dir", str(tmp_path)])

    def test_dir_flag_requires_value(self):
        from modal_examples_tpu.core.cli import cmd_snapshot

        with pytest.raises(SystemExit, match="usage"):
            cmd_snapshot(["list", "--dir"])
