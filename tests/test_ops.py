"""Kernel correctness tests: every Pallas kernel against its XLA reference
(interpret mode on the CPU backend; the same kernels compile via Mosaic on
TPU — exercised by bench.py and __graft_entry__.py)."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def jnp(jax):
    import jax.numpy as jnp

    return jnp


class TestFlashAttention:
    @pytest.mark.parametrize(
        "B,Hq,Hkv,S,D,causal",
        [
            (2, 4, 4, 256, 64, True),
            (1, 8, 2, 128, 64, False),  # GQA
            (2, 4, 2, 256, 128, True),
            (1, 2, 2, 384, 64, True),  # 3 blocks of 128
        ],
    )
    def test_matches_reference(self, jax, jnp, B, Hq, Hkv, S, D, causal):
        from modal_examples_tpu.ops import flash_attention, reference

        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (B, Hq, S, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, Hkv, S, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, Hkv, S, D), jnp.float32)
        out = flash_attention(q, k, v, causal)
        want = reference.attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)

    def test_gradients_match_reference(self, jax, jnp):
        from modal_examples_tpu.ops import flash_attention, reference

        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q = jax.random.normal(ks[0], (1, 2, 128, 64))
        k = jax.random.normal(ks[1], (1, 2, 128, 64))
        v = jax.random.normal(ks[2], (1, 2, 128, 64))
        g1 = jax.grad(
            lambda q, k, v: flash_attention(q, k, v, True).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        g2 = jax.grad(
            lambda q, k, v: reference.attention(q, k, v, causal=True).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_bwd_kernel_gqa_multiblock(self, jax, jnp, causal):
        """Pallas backward kernels (dq/dkv) vs reference grads: GQA group
        reduction + multiple q/k blocks + causal block skipping."""
        from modal_examples_tpu.ops import flash_attention, reference

        ks = jax.random.split(jax.random.PRNGKey(11), 3)
        q = jax.random.normal(ks[0], (2, 4, 256, 64))
        k = jax.random.normal(ks[1], (2, 2, 256, 64))
        v = jax.random.normal(ks[2], (2, 2, 256, 64))
        gq, gk, gv = jax.grad(
            lambda q, k, v: (flash_attention(q, k, v, causal) ** 2).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        rq, rk, rv = jax.grad(
            lambda q, k, v: (
                reference.attention(q, k, v, causal=causal) ** 2
            ).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip((gq, gk, gv), (rq, rk, rv)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
            )

    def test_lse_is_logsumexp(self, jax, jnp):
        from modal_examples_tpu.ops import flash_attention_with_lse

        ks = jax.random.split(jax.random.PRNGKey(2), 3)
        q = jax.random.normal(ks[0], (1, 1, 128, 64))
        k = jax.random.normal(ks[1], (1, 1, 128, 64))
        v = jax.random.normal(ks[2], (1, 1, 128, 64))
        scale = 64**-0.5
        _, lse = flash_attention_with_lse(q, k, v, causal=False)
        s = (q[0, 0] @ k[0, 0].T) * scale
        want = jax.scipy.special.logsumexp(s, axis=-1)
        np.testing.assert_allclose(np.asarray(lse[0, 0]), np.asarray(want), atol=1e-4)

    @pytest.mark.parametrize("q_offset", [0, 128, 256])
    def test_chunked_prefill_matches_full_rows(self, jax, jnp, q_offset):
        """A query chunk at offset o against the full K/V must equal rows
        [o, o+chunk) of dense causal attention over the whole sequence."""
        from modal_examples_tpu.ops import flash_attention_chunked, reference

        B, H, Skv, D, chunk = 1, 2, 384, 64, 128
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q = jax.random.normal(ks[0], (B, H, Skv, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, H, Skv, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, H, Skv, D), jnp.float32)
        full = reference.attention(q, k, v, causal=True)
        out = flash_attention_chunked(
            q[:, :, q_offset : q_offset + chunk], k, v, q_offset=q_offset
        )
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(full[:, :, q_offset : q_offset + chunk]),
            atol=2e-5,
        )

    def test_rejects_ragged_seq(self, jax, jnp):
        from modal_examples_tpu.ops import flash_attention

        q = jnp.ones((1, 1, 200, 64))
        with pytest.raises(ValueError, match="multiples? of block"):
            flash_attention(q, q, q, True)


class TestPagedAttention:
    def test_matches_reference_ragged_lens(self, jax, jnp):
        from modal_examples_tpu.ops import paged_decode_attention, reference

        B, Hq, Hkv, D = 4, 8, 2, 64
        page_size, n_pages, pages_per_seq = 16, 32, 4
        ks = jax.random.split(jax.random.PRNGKey(1), 4)
        q = jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
        kp = jax.random.normal(ks[1], (n_pages, page_size, Hkv, D), jnp.float32)
        vp = jax.random.normal(ks[2], (n_pages, page_size, Hkv, D), jnp.float32)
        pt = (
            jax.random.permutation(ks[3], n_pages)[: B * pages_per_seq]
            .reshape(B, pages_per_seq)
            .astype(jnp.int32)
        )
        cl = jnp.array([5, 16, 33, 64], jnp.int32)  # ragged, page-unaligned
        want = reference.paged_decode_attention(q, kp, vp, pt, cl)
        for impl in ("xla", "pallas"):  # default fused-gather path + kernel
            out = paged_decode_attention(q, kp, vp, pt, cl, impl=impl)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(want), atol=2e-5, err_msg=impl
            )

    def test_ragged_kernel_matches_inflight(self, jax, jnp):
        """v3 kernel (full [L,P,...] cache + layer scalar + in-flight token)
        must exactly match the XLA inflight formulation the default decode
        path uses — they are interchangeable inside decode_step."""
        from modal_examples_tpu.ops import (
            paged_decode_attention_inflight,
            paged_decode_attention_ragged,
        )

        L, B, Hq, Hkv, D = 3, 4, 8, 2, 64
        page_size, n_pages, pages_per_seq = 16, 40, 4
        ks = jax.random.split(jax.random.PRNGKey(7), 6)
        q = jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
        kp = jax.random.normal(
            ks[1], (L, n_pages, page_size, Hkv, D), jnp.float32
        )
        vp = jax.random.normal(
            ks[2], (L, n_pages, page_size, Hkv, D), jnp.float32
        )
        pt = (
            jax.random.permutation(ks[3], n_pages)[: B * pages_per_seq]
            .reshape(B, pages_per_seq)
            .astype(jnp.int32)
        )
        k_new = jax.random.normal(ks[4], (B, Hkv, D), jnp.float32)
        v_new = jax.random.normal(ks[5], (B, Hkv, D), jnp.float32)
        # ragged, page-unaligned prefixes incl. 0 (fresh slot) and full
        prefix = jnp.array([0, 5, 33, 64], jnp.int32)
        for li in (0, 2):
            want = paged_decode_attention_inflight(
                q, kp[li][pt], vp[li][pt], prefix, k_new, v_new
            )
            got = paged_decode_attention_ragged(
                q, kp, vp, jnp.int32(li), pt, prefix, k_new, v_new
            )
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=2e-5,
                err_msg=f"layer {li}",
            )

    def test_ragged_variants_agree(self, jax, jnp):
        """flat (v3 all-heads matmul) and grouped (v4 per-kv-head, the GQA
        path — round 5) are interchangeable formulations of the same math:
        both must match the XLA inflight reference at MHA and GQA shapes."""
        from modal_examples_tpu.ops import (
            paged_decode_attention_inflight,
            paged_decode_attention_ragged,
        )

        page_size, pages_per_seq = 16, 3
        for Hq, Hkv in [(4, 4), (8, 2)]:  # MHA and GQA (G=4)
            L, B, D = 2, 3, 64
            n_pages = 1 + B * pages_per_seq
            ks = jax.random.split(jax.random.PRNGKey(11), 6)
            q = jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
            kp = jax.random.normal(
                ks[1], (L, n_pages, page_size, Hkv, D), jnp.float32
            )
            vp = jax.random.normal(ks[2], kp.shape, jnp.float32)
            pt = (1 + jnp.arange(B * pages_per_seq, dtype=jnp.int32)).reshape(
                B, pages_per_seq
            )
            k_new = jax.random.normal(ks[3], (B, Hkv, D), jnp.float32)
            v_new = jax.random.normal(ks[4], (B, Hkv, D), jnp.float32)
            prefix = jnp.array([0, 17, 48], jnp.int32)
            want = paged_decode_attention_inflight(
                q, kp[1][pt], vp[1][pt], prefix, k_new, v_new
            )
            for variant in ("flat", "grouped"):
                got = paged_decode_attention_ragged(
                    q, kp, vp, jnp.int32(1), pt, prefix, k_new, v_new,
                    variant=variant,
                )
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), atol=2e-5,
                    err_msg=f"Hq={Hq} Hkv={Hkv} variant={variant}",
                )

    @pytest.mark.parametrize(
        "shape, positions",
        [
            ("mha-tiny", (9, 21)),  # LlamaConfig.tiny: Hq == n_kv_heads path
            ("gqa-g4", (0, 17, 40)),  # llama-3.1 shape class, incl. fresh slot
        ],
    )
    def test_decode_step_pallas_structure_matches_xla(
        self, jax, jnp, shape, positions
    ):
        """decode_step(impl='pallas') (ragged-kernel read-only structure)
        must produce the same logits and cache writes as the default path —
        at MHA-style shapes AND GQA (G=4), where paged_impl_plan
        auto-selects the round-5 grouped variant."""
        from modal_examples_tpu.models import llama

        if shape == "mha-tiny":
            cfg = llama.LlamaConfig.tiny()
        else:
            cfg = llama.LlamaConfig(
                vocab_size=256, dim=64, n_layers=2, n_heads=8, n_kv_heads=2,
                ffn_dim=128, max_seq_len=128, dtype="float32",
            )
            plan = llama.paged_impl_plan(cfg, 16, "pallas", "xla")
            assert plan["ragged_variant"] == "grouped", plan
        params = llama.init_params(jax.random.PRNGKey(4), cfg)
        B, ps, pp = len(positions), 16, 4
        n_pages = 1 + B * pp
        kp = jax.random.normal(
            jax.random.PRNGKey(5),
            (cfg.n_layers, n_pages, ps, cfg.n_kv_heads, cfg.head_dim),
            jnp.float32,
        ) * 0.1
        vp = jax.random.normal(jax.random.PRNGKey(6), kp.shape, jnp.float32) * 0.1
        tables = jnp.asarray(1 + np.arange(B * pp).reshape(B, pp), jnp.int32)
        toks = jnp.asarray(np.arange(3, 3 + B), jnp.int32)
        pos = jnp.asarray(positions, jnp.int32)
        active = jnp.ones((B,), bool)
        outs = {}
        for impl in ("xla", "pallas"):
            lg, k2, v2 = llama.decode_step(
                params, toks, pos, kp, vp, tables, active, cfg, impl=impl
            )
            outs[impl] = (np.asarray(lg), np.asarray(k2), np.asarray(v2))
        for a, b in zip(outs["xla"], outs["pallas"]):
            np.testing.assert_allclose(a, b, atol=3e-5)

    def test_decode_step_writeback_matches_default(self, jax, jnp):
        """The write-then-attend A/B structure (impl='xla-writeback') must
        produce the same logits and cache as the default read-only path —
        kept as the benchmark lever, so it must not rot (it went through
        the round-4 layout migration too)."""
        from modal_examples_tpu.models import llama

        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(jax.random.PRNGKey(2), cfg)
        B, ps, pp = 2, 16, 4
        n_pages = 1 + B * pp
        kp = jnp.zeros((cfg.n_layers, n_pages, ps, cfg.n_kv_heads,
                        cfg.head_dim), jnp.float32)
        vp = jnp.zeros_like(kp)
        tables = jnp.asarray(1 + np.arange(B * pp).reshape(B, pp), jnp.int32)
        toks = jnp.asarray([5, 11], jnp.int32)
        pos = jnp.asarray([7, 30], jnp.int32)
        active = jnp.ones((B,), bool)
        outs = {}
        for impl in ("xla", "xla-writeback"):
            lg, k2, v2 = llama.decode_step(
                params, toks, pos, kp, vp, tables, active, cfg, impl=impl
            )
            outs[impl] = (np.asarray(lg), np.asarray(k2), np.asarray(v2))
        for a, b in zip(outs["xla"], outs["xla-writeback"]):
            np.testing.assert_allclose(a, b, atol=3e-5)

    def test_mha_group_of_one(self, jax, jnp):
        from modal_examples_tpu.ops import paged_decode_attention, reference

        B, H, D = 2, 4, 64
        page_size, n_pages, pages_per_seq = 16, 16, 2
        ks = jax.random.split(jax.random.PRNGKey(5), 4)
        q = jax.random.normal(ks[0], (B, H, D), jnp.float32)
        kp = jax.random.normal(ks[1], (n_pages, page_size, H, D), jnp.float32)
        vp = jax.random.normal(ks[2], (n_pages, page_size, H, D), jnp.float32)
        pt = jnp.arange(B * pages_per_seq, dtype=jnp.int32).reshape(B, -1)
        cl = jnp.array([17, 32], jnp.int32)
        want = reference.paged_decode_attention(q, kp, vp, pt, cl)
        for impl in ("xla", "pallas"):
            out = paged_decode_attention(q, kp, vp, pt, cl, impl=impl)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(want), atol=2e-5, err_msg=impl
            )


class TestQuantizedMatmul:
    def test_quantize_roundtrip(self, jax, jnp):
        from modal_examples_tpu.ops import dequantize_int8, quantize_int8

        w = jax.random.normal(jax.random.PRNGKey(0), (256, 512))
        q, s = quantize_int8(w)
        w2 = dequantize_int8(q, s)
        assert float(jnp.max(jnp.abs(w - w2))) < float(jnp.max(s)) * 0.51

    def test_matmul_matches_dequantized(self, jax, jnp):
        from modal_examples_tpu.ops import dequantize_int8, quantize_int8, quantized_matmul

        ks = jax.random.split(jax.random.PRNGKey(3), 2)
        x = jax.random.normal(ks[0], (256, 512), jnp.float32)
        w = jax.random.normal(ks[1], (512, 256), jnp.float32)
        wq, ws = quantize_int8(w)
        out = quantized_matmul(x, wq, ws, block_m=128, block_n=128, block_k=256)
        want = x @ dequantize_int8(wq, ws)
        # kernel computes in bf16 on the MXU: tolerance = bf16 matmul error
        # (measured ~0.34 max for this size), not f32 error
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=0.5)

    def test_fallback_on_ragged_shapes(self, jax, jnp):
        from modal_examples_tpu.ops import quantize_int8, quantized_matmul

        x = jax.random.normal(jax.random.PRNGKey(0), (100, 300), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (300, 77), jnp.float32)
        wq, ws = quantize_int8(w)
        out = quantized_matmul(x, wq, ws)
        assert out.shape == (100, 77)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, jax, jnp, causal):
        from modal_examples_tpu.ops.ring_attention import ulysses_attention_sharded
        from modal_examples_tpu.ops import reference
        from modal_examples_tpu.parallel import make_mesh

        mesh = make_mesh({"seq": 4})
        B, H, S, D = 1, 8, 512, 64
        ks = jax.random.split(jax.random.PRNGKey(13), 3)
        q = jax.random.normal(ks[0], (B, H, S, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, H, S, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, H, S, D), jnp.float32)
        out = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
        want = reference.attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), atol=3e-5, rtol=1e-4
        )

    def test_rejects_indivisible_heads(self, jax, jnp):
        from modal_examples_tpu.ops.ring_attention import ulysses_attention_sharded
        from modal_examples_tpu.parallel import make_mesh

        mesh = make_mesh({"seq": 4})
        x = jnp.ones((1, 6, 128, 64))  # 6 heads not divisible by 4 shards
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention_sharded(x, x, x, mesh)


class TestRingAttention:
    @pytest.mark.slow
    def test_gradients_match_dense(self, jax, jnp):
        from modal_examples_tpu.ops import reference, ring_attention_sharded
        from modal_examples_tpu.parallel import make_mesh

        mesh = make_mesh({"seq": 2})
        ks = jax.random.split(jax.random.PRNGKey(9), 3)
        q = jax.random.normal(ks[0], (1, 2, 256, 64))
        k = jax.random.normal(ks[1], (1, 2, 256, 64))
        v = jax.random.normal(ks[2], (1, 2, 256, 64))
        g1 = jax.grad(
            lambda q, k, v: ring_attention_sharded(q, k, v, mesh, causal=True).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        g2 = jax.grad(
            lambda q, k, v: reference.attention(q, k, v, causal=True).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-4
            )

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense_over_seq_mesh(self, jax, jnp, causal):
        from modal_examples_tpu.ops import reference, ring_attention_sharded
        from modal_examples_tpu.parallel import make_mesh

        mesh = make_mesh({"seq": 4})
        B, H, S, D = 1, 2, 512, 64  # 4 shards x 128
        ks = jax.random.split(jax.random.PRNGKey(7), 3)
        q = jax.random.normal(ks[0], (B, H, S, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, H, S, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, H, S, D), jnp.float32)
        out = ring_attention_sharded(q, k, v, mesh, causal=causal)
        want = reference.attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), atol=3e-5, rtol=1e-4
        )
