"""Stall-free continuous batching (docs/scheduling.md): the per-tick
prefill token budget, the resumable sliced chunked prefill, the deferred
first-token harvest, and the invariants they must preserve — budgeted
scheduling is token-IDENTICAL to unbudgeted scheduling, an abort or
deadline landing mid-chunked-prefill unwinds the claim without poisoning
the prefix trie, and the new observability series record."""

import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


def _make_engine(jax, budget=0, seed=0, **kw):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    kw.setdefault("max_slots", 4)
    kw.setdefault("max_model_len", 256)
    kw.setdefault("page_size", 16)
    kw.setdefault("prefill_buckets", (16, 32))
    return LLMEngine(
        llama.LlamaConfig.tiny(), seed=seed,
        max_prefill_tokens_per_tick=budget, **kw,
    )


#: > largest test bucket (32), so it takes the chunked-prefill path:
#: 120 byte-tokens = 4 chunks of 32/32/32/24
LONG_PROMPT = "x" * 120


def _drain(req):
    """Collect a step-driven request's stream without start()ing the
    scheduler thread (stream() would)."""
    import queue as _q

    from modal_examples_tpu.serving.engine import _Finish

    out = []
    while True:
        try:
            item = req.out_queue.get_nowait()
        except _q.Empty:
            return out, None
        if isinstance(item, _Finish):
            req.finish_reason = item.reason
            return out, item.reason
        out.append(item)


class TestBudgetResolution:
    def test_ctor_kwarg_beats_env(self, jax, monkeypatch):
        monkeypatch.setenv("MTPU_PREFILL_BUDGET", "7")
        eng = _make_engine(jax, budget=3)
        assert eng.prefill_budget == 3
        eng.stop()

    def test_env_resolves_when_unset(self, jax, monkeypatch):
        monkeypatch.setenv("MTPU_PREFILL_BUDGET", "48")
        eng = _make_engine(jax, budget=None)
        assert eng.prefill_budget == 48
        eng.stop()

    def test_default_is_unlimited(self, jax, monkeypatch):
        monkeypatch.delenv("MTPU_PREFILL_BUDGET", raising=False)
        eng = _make_engine(jax, budget=None)
        assert eng.prefill_budget == 0
        eng.stop()

    def test_prefill_role_replica_runs_unbudgeted(self, jax):
        """Disagg prefill replicas have no decode to protect: wrapping an
        engine as a prefill-role replica zeroes any process-wide budget."""
        from modal_examples_tpu.scheduling import EngineReplica

        eng = _make_engine(jax, budget=64)
        EngineReplica(eng, "pre-0", role="prefill")
        assert eng.prefill_budget == 0
        eng.stop()


class TestSlicedPrefill:
    def test_budget_slices_chunked_prefill_across_ticks(self, jax):
        """budget < one chunk: exactly one chunk dispatches per tick (the
        progress guarantee), the backlog gauge drains chunk by chunk, and
        the sliced counter counts each suspension."""
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.utils.prometheus import default_registry

        sliced_before = default_registry.value(C.PREFILL_SLICED_TOTAL) or 0
        eng = _make_engine(jax, budget=1)
        try:
            req = eng.submit(
                LONG_PROMPT, SamplingParams(max_tokens=4, temperature=0.0)
            )
            n_prompt = len(req.prompt_tokens)  # 120 chars + BOS
            eng.step()
            slot = next(s for s in eng.slots if s.request is req)
            assert slot.prefill is not None
            assert slot.prefill.offset == 32  # exactly one chunk
            assert not slot.decodable
            eng._metrics_wall = 0.0
            eng._refresh_gauges()
            assert (
                default_registry.value(C.PREFILL_BACKLOG_TOKENS)
                == n_prompt - 32
            )
            eng.step()
            assert slot.prefill.offset == 64
            for _ in range(40):
                eng.step()
                if _drain(req)[1] is not None:
                    break
            assert req.finish_reason in ("stop", "length")
            assert slot.prefill is None and not slot.pending_first
            # three suspensions: chunks 1..3 each paused mid-prompt
            assert (
                default_registry.value(C.PREFILL_SLICED_TOTAL) or 0
            ) >= sliced_before + 3
            eng._metrics_wall = 0.0
            eng._refresh_gauges()
            assert default_registry.value(C.PREFILL_BACKLOG_TOKENS) == 0
        finally:
            eng.stop()

    def test_budget_stops_converting_queue_entries(self, jax):
        """Short prompts past the budget stay queued (preemption-safe
        front-requeue, reservations intact) and are admitted on later
        ticks — never dropped."""
        from modal_examples_tpu.serving import SamplingParams

        eng = _make_engine(jax, budget=8)
        try:
            p = SamplingParams(max_tokens=2, temperature=0.0)
            reqs = [eng.submit(f"prompt {i}", p) for i in range(4)]
            eng.step()
            # one tick converts at most ~budget worth: not all four slots
            occupied = sum(1 for s in eng.slots if not s.free)
            assert occupied < 4
            assert eng.policy.total_depth() == 4 - occupied
            for _ in range(60):
                eng.step()
                if all(_drain(r)[1] or r.finish_reason for r in reqs):
                    break
            assert all(
                r.finish_reason in ("stop", "length") for r in reqs
            )
            assert eng.policy.total_depth() == 0
        finally:
            eng.stop()


class TestSchedulingInvariance:
    """Slicing must never change results: per-request sampling is keyed by
    (seed, position), so budget on/off — and sliced vs atomic long
    prefills — produce token-identical outputs."""

    def _run(self, jax, budget, params_fn):
        eng = _make_engine(jax, budget=budget, seed=0)
        try:
            prompts = [LONG_PROMPT, "short a", "short b", "y" * 100, "zz"]
            reqs = [eng.submit(p, params_fn()) for p in prompts]
            outs = ["".join(eng.stream(r)) for r in reqs]
            reasons = [r.finish_reason for r in reqs]
            return outs, reasons
        finally:
            eng.stop()

    def test_greedy_token_identical(self, jax):
        from modal_examples_tpu.serving import SamplingParams

        mk = lambda: SamplingParams(max_tokens=6, temperature=0.0)
        assert self._run(jax, 0, mk) == self._run(jax, 16, mk)

    def test_seeded_sampling_token_identical(self, jax):
        from modal_examples_tpu.serving import SamplingParams

        mk = lambda: SamplingParams(max_tokens=6, temperature=1.0, seed=77)
        assert self._run(jax, 0, mk) == self._run(jax, 16, mk)

    def test_auto_seeded_sampling_token_identical(self, jax):
        """Unseeded temperature>0 requests derive (engine seed, submission
        index) seeds, so even they must survive rescheduling unchanged."""
        from modal_examples_tpu.serving import SamplingParams

        mk = lambda: SamplingParams(max_tokens=6, temperature=1.0)
        assert self._run(jax, 0, mk) == self._run(jax, 16, mk)

    def test_budget_granularities_agree(self, jax):
        from modal_examples_tpu.serving import SamplingParams

        mk = lambda: SamplingParams(max_tokens=5, temperature=1.0)
        a = self._run(jax, 1, mk)  # one chunk per tick
        b = self._run(jax, 64, mk)  # several chunks per tick
        assert a == b

    def test_budget_flip_on_one_engine_token_identical(self, jax):
        """The runtime A/B bench.py runs: flip ``prefill_budget`` on ONE
        live engine between rounds — sliced and atomic prefills of the
        same prompts must emit the same tokens (greedy and seeded)."""
        from modal_examples_tpu.serving import SamplingParams

        eng = _make_engine(jax, budget=0, seed=0)
        try:
            def round_(params):
                reqs = [
                    eng.submit(p, params)
                    for p in (LONG_PROMPT, "short", "y" * 90)
                ]
                return ["".join(eng.stream(r)) for r in reqs]

            for params in (
                SamplingParams(max_tokens=6, temperature=0.0),
                SamplingParams(max_tokens=6, temperature=1.0, seed=123),
            ):
                eng.prefill_budget = 0
                atomic = round_(params)
                eng.prefill_budget = 16
                sliced = round_(params)
                assert atomic == sliced, params
        finally:
            eng.stop()


class TestMidPrefillAbortAndDeadline:
    """Previously unreachable states (the prefill was atomic): an abort or
    deadline landing while a chunked prefill is mid-flight must unwind the
    claim fully, leave the trie unpoisoned, and finish the caller's stream
    with the right reason."""

    def test_abort_mid_chunk_unwinds_claim(self, jax):
        from modal_examples_tpu.serving import SamplingParams

        eng = _make_engine(jax, budget=1)
        try:
            req = eng.submit(
                LONG_PROMPT, SamplingParams(max_tokens=4, temperature=0.0)
            )
            eng.step()
            slot = next(s for s in eng.slots if s.request is req)
            assert slot.prefill is not None and slot.prefill.offset < 120
            eng.abort(req)
            eng.step()
            _, reason = _drain(req)
            assert reason == "stop"
            assert slot.free and slot.prefill is None
            assert not slot.pending_first
            # claim fully unwound: nothing allocated beyond what the trie
            # legitimately caches, and none of the aborted prompt's pages
            # stayed cached (they held partial KV)
            occ = eng.cache.occupancy()
            assert occ["pages_used"] == eng.prefix_cache.cached_pages
            # the trie is not poisoned: rerunning the aborted prompt
            # prefills from scratch and matches a clean engine's output
            fresh = _make_engine(jax, budget=0, seed=0)
            p = SamplingParams(max_tokens=4, temperature=0.0)
            want = fresh.generate(LONG_PROMPT, p)
            fresh.stop()
            assert eng.generate(LONG_PROMPT, p) == want
        finally:
            eng.stop()

    def test_abort_while_first_token_unharvested(self, jax):
        """Abort landing between prefill dispatch and the deferred harvest:
        the reap unwinds the slot and the harvest skips it by request
        identity (like a recycled decode-block row)."""
        from modal_examples_tpu.serving import SamplingParams

        eng = _make_engine(jax, budget=0)
        try:
            req = eng.submit(
                LONG_PROMPT, SamplingParams(max_tokens=4, temperature=0.0)
            )
            eng._expire_deadlines()
            eng._admit()  # unbudgeted: all chunks + sample parked for harvest
            slot = next(s for s in eng.slots if s.request is req)
            assert slot.pending_first
            assert len(eng._pending_harvest) == 1
            eng.abort(req)
            eng._decode_tick()  # reap unwinds, harvest skips the dead row
            _, reason = _drain(req)
            assert reason == "stop"
            assert slot.free and not slot.pending_first
            assert not eng._pending_harvest
            occ = eng.cache.occupancy()
            assert occ["pages_used"] == eng.prefix_cache.cached_pages
        finally:
            eng.stop()

    def test_deadline_mid_prefill_counts_prefill_stage(self, jax):
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.utils.prometheus import default_registry

        t = [0.0]
        eng = _make_engine(jax, budget=1, clock=lambda: t[0])
        try:
            before = (
                default_registry.value(
                    C.DEADLINE_MISSES_TOTAL, {"stage": "prefill"}
                )
                or 0
            )
            req = eng.submit(
                LONG_PROMPT,
                SamplingParams(max_tokens=4, temperature=0.0, deadline_s=5.0),
            )
            eng.step()
            slot = next(s for s in eng.slots if s.request is req)
            assert slot.prefill is not None
            t[0] = 10.0  # blow the deadline while chunks are pending
            eng.step()
            _, reason = _drain(req)
            assert reason == "deadline"
            assert slot.free
            assert (
                default_registry.value(
                    C.DEADLINE_MISSES_TOTAL, {"stage": "prefill"}
                )
                == before + 1
            )
            occ = eng.cache.occupancy()
            assert occ["pages_used"] == eng.prefix_cache.cached_pages
        finally:
            eng.stop()


class TestDeferredHarvest:
    def test_group_first_tokens_harvest_after_decode_dispatch(self, jax):
        """A batch of short prompts admitted in one tick parks its first
        tokens on the harvest queue and still lights every slot up within
        that same tick (no token is lost to the deferral)."""
        from modal_examples_tpu.serving import SamplingParams

        eng = _make_engine(jax, budget=0)
        try:
            p = SamplingParams(max_tokens=3, temperature=0.0)
            reqs = [eng.submit(f"group {i}", p) for i in range(3)]
            eng.step()
            assert not eng._pending_harvest  # harvested inside the tick
            assert sum(1 for s in eng.slots if s.decodable) == 3
            for _ in range(40):
                eng.step()
                if all(_drain(r)[1] or r.finish_reason for r in reqs):
                    break
            assert all(r.finish_reason in ("stop", "length") for r in reqs)
        finally:
            eng.stop()

    def test_decode_stall_histogram_records(self, jax):
        """The dispatch-gap histogram (the stall-free contract's
        measurement) must record under concurrent traffic and ride the
        registry exposition that /metrics serves."""
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.utils.prometheus import default_registry

        eng = _make_engine(jax, budget=16)
        try:
            p = SamplingParams(max_tokens=8, temperature=1.0)
            reqs = [eng.submit(LONG_PROMPT, p)] + [
                eng.submit(f"r{i}", p) for i in range(3)
            ]
            for r in reqs:
                "".join(eng.stream(r))
        finally:
            eng.stop()
        q = default_registry.histogram_quantiles(C.DECODE_STALL_SECONDS)
        assert q is not None and q["count"] >= 1


class TestSlicedPrefillSpans:
    def test_sliced_request_records_prefill_wait_span(self, jax):
        from modal_examples_tpu.observability import reqtrace as rt
        from modal_examples_tpu.serving import SamplingParams

        eng = _make_engine(jax, budget=1)
        try:
            req = eng.submit(
                LONG_PROMPT, SamplingParams(max_tokens=3, temperature=0.0)
            )
            "".join(eng.stream(req))
            assert req.trace is not None
            n_chunks = -(-len(req.prompt_tokens) // 32)
            by = {}
            for s in rt.read_trace(req.request_id):
                by.setdefault(s["name"], []).append(s)
            pf = by["prefill"][0]["attrs"]
            assert pf["chunked"] is True
            assert pf["chunks"] == n_chunks and pf["sliced"] is True
            assert pf["budget"] == 1
            wait = by["prefill_wait"][0]["attrs"]
            assert wait["ticks"] == n_chunks and wait["chunks"] == n_chunks
        finally:
            eng.stop()

    def test_unsliced_long_prefill_has_no_wait_span(self, jax):
        from modal_examples_tpu.observability import reqtrace as rt
        from modal_examples_tpu.serving import SamplingParams

        eng = _make_engine(jax, budget=0)
        try:
            req = eng.submit(
                LONG_PROMPT, SamplingParams(max_tokens=3, temperature=0.0)
            )
            "".join(eng.stream(req))
            assert req.trace is not None
            by = {}
            for s in rt.read_trace(req.request_id):
                by.setdefault(s["name"], []).append(s)
            pf = by["prefill"][0]["attrs"]
            assert pf["chunked"] is True and pf["sliced"] is False
            assert "prefill_wait" not in by
        finally:
            eng.stop()
