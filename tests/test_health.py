"""Gray-failure watchdog acceptance (ISSUE 13, docs/health.md).

Three layers, matching the subsystem's layering:

- **fake-clock unit matrix** — watermark ages, the pure classifier, the
  hysteresis/flap-damping state machine, the quarantine window, the ladder
  ordering, and the journal+metrics closure, all driven tick-by-tick under
  an injectable clock (the ONLY place detection latency is asserted — no
  wall-clock direction asserts, per the tier-1 timing policy).
- **transfer watermarks** — the seq-watermark registry, stall detection,
  and the watchdog abort surfacing as ``TransportError`` inside a live
  ``transfer()`` held by the injected ``disagg.transfer_stall`` fault.
- **E2E** — a real two-replica fleet where a SILENT scheduler freeze (not
  an error) triggers detection, error-stop, and token-identical stream
  resumption via the PR-12 reactive failover.
"""

import threading
import time

import pytest

from modal_examples_tpu.serving.health import (
    ACTIONS,
    STATES,
    EngineWatermarks,
    FleetWatchdog,
    ReplicaMonitor,
    TransferWatermarks,
    WatchdogPolicy,
    classify,
    progress_age,
    replica_snapshot,
)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


class _FakeSlot:
    def __init__(self, request=None, decodable=False):
        self.request = request
        self.decodable = decodable


class _FakeRequest:
    def __init__(self, rid="req-x", last_token_at=None, generated=()):
        self.request_id = rid
        self.last_token_at = last_token_at
        self.generated_tokens = list(generated)


class _FakePolicy:
    def __init__(self):
        self.oldest = None

    def oldest_enqueued_at(self):
        return self.oldest

    def total_depth(self):
        return 0


class _FakeEngine:
    def __init__(self, clock):
        self.watermarks = EngineWatermarks(clock=clock)
        self._clock = clock
        self._running = True
        self.slots = []
        self.policy = _FakePolicy()
        self._trace_store = None
        self.stopped_with = None

    def stop(self, *, reason="stop"):
        self._running = False
        self.stopped_with = reason


class _FakeReplica:
    def __init__(self, name, clock, outstanding=0):
        self.name = name
        self.engine = _FakeEngine(clock)
        self._outstanding = outstanding
        self.serves_requests = True
        self.health_state = "healthy"
        self.quarantined = False

    def outstanding(self):
        return self._outstanding


class _FakeRouter:
    def __init__(self, replicas):
        self.replicas = replicas
        self.weights = {}

    def set_health_weight(self, name, weight):
        self.weights[name] = weight


def _watchdog(replicas, clock, tmp_path, **policy_kw):
    policy = WatchdogPolicy(**policy_kw) if policy_kw else WatchdogPolicy()
    return FleetWatchdog(
        _FakeRouter(replicas),
        policy=policy,
        clock=clock,
        journal_path=tmp_path / "watchdog.jsonl",
        transfer_watermarks=TransferWatermarks(clock=clock),
    )


class TestWatermarks:
    def test_ages_track_the_injected_clock(self):
        clock = FakeClock()
        wm = EngineWatermarks(clock=clock)
        wm.note_tick()
        wm.note_dispatch()
        clock.advance(2.0)
        wm.note_accept()
        clock.advance(1.0)
        snap = wm.snapshot()
        assert snap["tick_seq"] == 1
        assert snap["tick_age"] == pytest.approx(3.0)
        assert snap["dispatch_age"] == pytest.approx(3.0)
        assert snap["accept_age"] == pytest.approx(1.0)

    def test_unset_watermarks_are_none_not_huge(self):
        wm = EngineWatermarks(clock=FakeClock())
        snap = wm.snapshot()
        assert snap["dispatch_age"] is None
        assert snap["accept_age"] is None

    def test_note_start_resets_stale_ages(self):
        """A restarted engine must not present its previous life's ages:
        in the window between start() and the first tick, with resumed
        work already queued, stale watermarks would read as an instant
        wedge of the engine the watchdog just recovered."""
        clock = FakeClock()
        wm = EngineWatermarks(clock=clock)
        wm.note_tick()
        wm.note_dispatch()
        wm.note_accept()
        clock.advance(30.0)  # the engine was stopped for 30s
        wm.note_start()
        snap = wm.snapshot()
        assert snap["tick_age"] == 0.0
        assert snap["dispatch_age"] is None
        assert snap["accept_age"] is None
        policy = WatchdogPolicy(degraded_after_s=1.0, wedged_after_s=2.0)
        snap.update({"outstanding": 4, "decodable": 0,
                     "queue_head_age": None})
        assert classify(snap, policy) == "healthy"

    def test_engine_restart_resets_watermarks(self, jax_cpu):
        """The engine-level half: stop + start clears the stale ages
        (LLMEngine.start calls note_start)."""
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
            prefill_buckets=(16, 32), page_size=8,
        )
        try:
            eng.generate("restart probe", SamplingParams(max_tokens=2))
            eng.stop()
            time.sleep(0.05)
            eng.start()
            snap = eng.watermarks.snapshot()
            # dispatch/accept reset to None; tick age restarts near zero
            assert snap["dispatch_age"] is None
            assert snap["accept_age"] is None
            assert snap["tick_age"] < 5.0
        finally:
            eng.stop()

    def test_engine_publishes_watermarks_through_real_serving(self, jax_cpu):
        """A real tiny engine's generate() moves every watermark, readable
        ONLY through the health API (replica_snapshot)."""
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.scheduling import EngineReplica
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
            prefill_buckets=(16, 32), page_size=8,
        )
        rep = EngineReplica(eng, "wm-0")
        try:
            out = eng.generate(
                "watermark probe", SamplingParams(max_tokens=4)
            )
            assert out is not None
            snap = replica_snapshot(rep)
            assert snap["tick_seq"] > 0
            assert snap["dispatch_age"] is not None
            assert snap["accept_age"] is not None
            assert snap["outstanding"] == 0
            # EngineReplica.stats() carries the same last-progress fields
            stats = rep.stats()
            assert stats["state"] == "healthy"
            assert stats["progress"]["tick_seq"] >= snap["tick_seq"]
        finally:
            eng.stop()


class TestClassification:
    def _snap(self, **kw):
        base = {
            "tick_seq": 10, "tick_age": 0.0, "dispatch_age": 0.0,
            "accept_age": 0.0, "outstanding": 1, "decodable": 1,
            "queue_head_age": None,
        }
        base.update(kw)
        return base

    def test_idle_is_always_healthy(self):
        policy = WatchdogPolicy()
        snap = self._snap(outstanding=0, tick_age=1e9)
        assert progress_age(snap) is None
        assert classify(snap, policy) == "healthy"

    def test_stale_tick_escalates_degraded_then_wedged(self):
        policy = WatchdogPolicy(degraded_after_s=2.0, wedged_after_s=10.0)
        assert classify(self._snap(tick_age=1.0), policy) == "healthy"
        assert classify(self._snap(tick_age=2.0), policy) == "degraded"
        assert classify(self._snap(tick_age=10.0), policy) == "wedged"

    def test_accept_and_dispatch_only_count_with_decodable_slots(self):
        policy = WatchdogPolicy(degraded_after_s=2.0, wedged_after_s=10.0)
        # decodable slot starved of accepts: degraded even though ticks flow
        snap = self._snap(tick_age=0.0, accept_age=3.0, dispatch_age=0.1)
        assert classify(snap, policy) == "degraded"
        # no decodable slots (all mid-prefill): accept age is meaningless
        snap = self._snap(
            tick_age=0.0, accept_age=3.0, dispatch_age=3.0, decodable=0
        )
        assert classify(snap, policy) == "healthy"

    def test_queue_head_age_is_degraded_only(self):
        policy = WatchdogPolicy(
            degraded_after_s=2.0, wedged_after_s=10.0,
            queue_age_degraded_s=5.0,
        )
        snap = self._snap(queue_head_age=6.0)
        assert classify(snap, policy) == "degraded"
        snap = self._snap(queue_head_age=1e9)
        assert classify(snap, policy) == "degraded"  # never wedged on it

    def test_progress_age_is_the_worst_mandatory_signal(self):
        snap = self._snap(tick_age=0.5, dispatch_age=4.0, accept_age=2.0)
        assert progress_age(snap) == pytest.approx(4.0)


class TestMonitorHysteresis:
    def test_downgrade_is_immediate_upgrade_needs_streak(self):
        policy = WatchdogPolicy(clear_ticks=3)
        mon = ReplicaMonitor("r", policy)
        assert mon.observe("degraded", 0.0) == ("degraded", True)
        # one healthy observation is NOT enough
        assert mon.observe("healthy", 1.0) == ("degraded", False)
        assert mon.observe("healthy", 2.0) == ("degraded", False)
        assert mon.observe("healthy", 3.0) == ("healthy", True)

    def test_flap_damping_holds_degraded(self):
        policy = WatchdogPolicy(clear_ticks=2)
        mon = ReplicaMonitor("r", policy)
        mon.observe("degraded", 0.0)
        # alternating healthy/degraded never builds the streak
        for i in range(6):
            raw = "healthy" if i % 2 == 0 else "degraded"
            state, _ = mon.observe(raw, float(i))
            assert state == "degraded"

    def test_wedged_never_softens_to_degraded(self):
        policy = WatchdogPolicy(clear_ticks=2)
        mon = ReplicaMonitor("r", policy)
        mon.observe("wedged", 0.0)
        state, changed = mon.observe("degraded", 1.0)
        assert (state, changed) == ("wedged", False)

    def test_wedge_window_counts(self):
        policy = WatchdogPolicy(clear_ticks=1, wedge_window_s=100.0)
        mon = ReplicaMonitor("r", policy)
        mon.observe("wedged", 0.0)
        mon.observe("healthy", 1.0)
        mon.observe("wedged", 50.0)
        assert mon.wedges_in_window(60.0) == 2
        assert mon.wedges_in_window(140.0) == 1  # the first aged out


class TestWatchdogLadder:
    def test_degraded_down_weights_and_healthy_restores(self, tmp_path):
        clock = FakeClock()
        rep = _FakeReplica("lad-0", clock, outstanding=1)
        wd = _watchdog(
            [rep], clock, tmp_path,
            degraded_after_s=2.0, wedged_after_s=100.0, clear_ticks=2,
            degraded_weight=0.25,
        )
        rep.engine.watermarks.note_tick()
        clock.advance(3.0)  # stale tick while busy -> degraded
        wd.poll_once()
        assert rep.health_state == "degraded"
        assert wd.router.weights["lad-0"] == 0.25
        # progress resumes: two healthy polls restore the weight
        rep.engine.watermarks.note_tick()
        rep._outstanding = 0
        wd.poll_once()
        wd.poll_once()
        assert rep.health_state == "healthy"
        assert wd.router.weights["lad-0"] == 1.0
        actions = [e["action"] for e in wd.events]
        assert "down_weight" in actions and "restore_weight" in actions

    def test_wedged_error_stops_the_engine(self, tmp_path):
        clock = FakeClock()
        rep = _FakeReplica("lad-1", clock, outstanding=2)
        wd = _watchdog(
            [rep], clock, tmp_path,
            degraded_after_s=1.0, wedged_after_s=5.0, quarantine_after=99,
        )
        rep.engine.watermarks.note_tick()
        clock.advance(6.0)
        wd.poll_once()
        assert rep.engine.stopped_with == "error"
        assert rep.health_state == "wedged"
        assert not rep.quarantined
        actions = [e["action"] for e in wd.events]
        assert actions[-1] == "stop_revive"

    def test_ladder_ordering_degraded_before_wedged(self, tmp_path):
        """A slowly-worsening replica walks the ladder IN ORDER: the
        journal shows down_weight strictly before stop_revive — detection
        latency asserted under the injectable clock only."""
        clock = FakeClock()
        rep = _FakeReplica("lad-2", clock, outstanding=1)
        wd = _watchdog(
            [rep], clock, tmp_path,
            degraded_after_s=2.0, wedged_after_s=8.0, quarantine_after=99,
        )
        rep.engine.watermarks.note_tick()
        clock.advance(3.0)
        wd.poll_once()  # degraded at age 3
        assert rep.engine.stopped_with is None
        clock.advance(6.0)
        wd.poll_once()  # wedged at age 9
        actions = [e["action"] for e in wd.events]
        assert actions.index("down_weight") < actions.index("stop_revive")
        # detection latency bound, fake clock: wedged within one poll of
        # the threshold crossing (3.0 -> degraded, 9.0 -> wedged)
        transitions = [
            e for e in wd.events if e["action"] == "transition"
        ]
        assert [t["state"] for t in transitions] == ["degraded", "wedged"]

    def test_repeated_wedges_quarantine_and_expire(self, tmp_path):
        clock = FakeClock()
        rep = _FakeReplica("lad-3", clock, outstanding=1)
        wd = _watchdog(
            [rep], clock, tmp_path,
            degraded_after_s=1.0, wedged_after_s=2.0, clear_ticks=1,
            quarantine_after=2, wedge_window_s=1000.0, quarantine_s=30.0,
        )
        # first wedge: stop_revive only
        rep.engine.watermarks.note_tick()
        clock.advance(3.0)
        wd.poll_once()
        assert not rep.quarantined
        # the replica revives (router probe analog) and wedges again
        rep.engine._running = True
        rep.engine.stopped_with = None
        rep.engine.watermarks.note_tick()
        wd.poll_once()  # healthy observation clears the wedge state
        assert rep.health_state == "healthy"
        clock.advance(3.0)
        wd.poll_once()
        assert rep.quarantined
        assert rep.engine.stopped_with == "error"
        actions = [e["action"] for e in wd.events]
        assert actions[-1] == "quarantine"
        # while quarantined: no new actions, state gauge says quarantined
        rep.engine._running = True
        wd.poll_once()
        assert rep.quarantined
        # expiry lifts the flag (the router's probe path may then revive)
        clock.advance(31.0)
        wd.poll_once()
        assert not rep.quarantined
        assert [e["action"] for e in wd.events].count("unquarantine") == 1

    def test_journal_and_metrics_closure(self, tmp_path):
        """Every transition journals AND counts; every ladder action
        journals AND counts; the state gauge is one-hot."""
        import json

        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.utils.prometheus import Registry

        reg = Registry()
        clock = FakeClock()
        rep = _FakeReplica("jm-0", clock, outstanding=1)
        wd = FleetWatchdog(
            _FakeRouter([rep]),
            policy=WatchdogPolicy(
                degraded_after_s=1.0, wedged_after_s=4.0, quarantine_after=99
            ),
            clock=clock,
            journal_path=tmp_path / "watchdog.jsonl",
            transfer_watermarks=TransferWatermarks(clock=clock),
            registry=reg,
        )
        rep.engine.watermarks.note_tick()
        clock.advance(2.0)
        wd.poll_once()  # degraded
        clock.advance(3.0)
        wd.poll_once()  # wedged
        lines = [
            json.loads(l)
            for l in (tmp_path / "watchdog.jsonl").read_text().splitlines()
        ]
        journal_actions = [l["action"] for l in lines]
        assert journal_actions.count("transition") == 2
        assert "down_weight" in journal_actions
        assert "stop_revive" in journal_actions
        assert reg.value(
            C.WATCHDOG_TRANSITIONS_TOTAL, labels={"state": "degraded"}
        ) == 1
        assert reg.value(
            C.WATCHDOG_TRANSITIONS_TOTAL, labels={"state": "wedged"}
        ) == 1
        assert reg.value(
            C.WATCHDOG_RECOVERIES_TOTAL, labels={"action": "down_weight"}
        ) == 1
        assert reg.value(
            C.WATCHDOG_RECOVERIES_TOTAL, labels={"action": "stop_revive"}
        ) == 1
        # one-hot state gauge: exactly the wedged cell reads 1
        cells = {
            s: reg.value(
                C.WATCHDOG_REPLICA_STATE,
                labels={"replica": "jm-0", "state": s},
            )
            for s in STATES
        }
        assert cells == {
            "healthy": 0.0, "degraded": 0.0, "wedged": 1.0,
            "quarantined": 0.0,
        }
        assert reg.value(
            C.WATCHDOG_PROGRESS_AGE_SECONDS, labels={"replica": "jm-0"}
        ) >= 5.0
        # every journaled ladder action is a declared ACTIONS member
        for a in journal_actions:
            assert a == "transition" or a in ACTIONS

    def test_rewedge_after_revival_fires_the_ladder_again(self, tmp_path):
        """A revived engine that wedges AGAIN before any healthy streak
        accrues must get a SECOND stop_revive: the monitor resets when the
        engine is observed running after a stop, so the re-wedge is a new
        transition, not a masked continuation of the old one (whose
        streams would otherwise hang forever)."""
        clock = FakeClock()
        rep = _FakeReplica("rw-0", clock, outstanding=1)
        wd = _watchdog(
            [rep], clock, tmp_path,
            degraded_after_s=1.0, wedged_after_s=2.0, quarantine_after=99,
        )
        rep.engine.watermarks.note_tick()
        clock.advance(3.0)
        wd.poll_once()  # wedge #1: error-stop
        assert rep.engine.stopped_with == "error"
        wd.poll_once()  # observes the stopped engine (saw_stopped)
        # probe revival: the engine runs again but wedges immediately —
        # its tick watermark goes stale before ANY healthy poll lands
        rep.engine._running = True
        rep.engine.stopped_with = None
        clock.advance(3.0)
        wd.poll_once()
        assert rep.engine.stopped_with == "error", (
            "re-wedge after revival was masked: no second stop"
        )
        actions = [e["action"] for e in wd.events]
        assert actions.count("stop_revive") == 2
        # the quarantine window kept BOTH wedges across the revival
        assert wd._monitors["rw-0"].wedges_in_window(clock()) == 2

    def test_stopped_engine_is_not_observed(self, tmp_path):
        """A stopped scheduler belongs to the router's probe cycle: the
        watchdog must not classify it wedged and double-fire the ladder."""
        clock = FakeClock()
        rep = _FakeReplica("st-0", clock, outstanding=1)
        rep.engine._running = False
        wd = _watchdog(
            [rep], clock, tmp_path, degraded_after_s=1.0, wedged_after_s=2.0
        )
        rep.engine.watermarks.note_tick()
        clock.advance(100.0)
        assert wd.poll_once() == []
        assert rep.engine.stopped_with is None

    def test_degraded_weight_restored_after_external_stop(self, tmp_path):
        """A replica down-weighted while DEGRADED whose engine then stops
        through a non-ladder path (strict-mode crash, fleet reap, operator
        restart) must get its placement weight back on revival: reset()
        forces the monitor healthy, so without an explicit restore the next
        healthy observation is changed=False, _act_recovered never fires,
        and the healthy replica competes at degraded_weight forever."""
        clock = FakeClock()
        rep = _FakeReplica("ex-0", clock, outstanding=1)
        wd = _watchdog(
            [rep], clock, tmp_path,
            degraded_after_s=2.0, wedged_after_s=100.0, degraded_weight=0.25,
        )
        rep.engine.watermarks.note_tick()
        clock.advance(3.0)
        wd.poll_once()  # degraded -> down-weight
        assert wd.router.weights["ex-0"] == 0.25
        rep.engine.stop(reason="stop")  # NOT the watchdog's doing
        wd.poll_once()  # saw_stopped
        rep.engine._running = True  # probe revival
        rep.engine.watermarks.note_tick()
        rep._outstanding = 0
        wd.poll_once()
        assert wd.router.weights["ex-0"] == 1.0
        assert "restore_weight" in [e["action"] for e in wd.events]

    def test_removed_replica_is_forgotten(self, tmp_path):
        """Fleet scale-down/reap removes a replica from the router: the
        watchdog must drop its monitor, quarantine entry, and gauge cells
        — not report the ghost at its last state on every surface
        forever (and leak ``_quarantined_until`` for good)."""
        from modal_examples_tpu.serving.health import decode_watchdog_series
        from modal_examples_tpu.utils.prometheus import Registry

        reg = Registry()
        clock = FakeClock()
        rep = _FakeReplica("gh-0", clock, outstanding=1)
        wd = FleetWatchdog(
            _FakeRouter([rep]),
            policy=WatchdogPolicy(
                degraded_after_s=1.0, wedged_after_s=2.0,
                quarantine_after=1, quarantine_s=1000.0,
            ),
            clock=clock,
            journal_path=tmp_path / "watchdog.jsonl",
            transfer_watermarks=TransferWatermarks(clock=clock),
            registry=reg,
        )
        rep.engine.watermarks.note_tick()
        clock.advance(3.0)
        wd.poll_once()  # wedged -> immediate quarantine (quarantine_after=1)
        assert rep.quarantined
        assert "gh-0" in wd.stats()["replicas"]
        assert decode_watchdog_series(reg)["states"] == {"gh-0": "quarantined"}
        # the fleet reaps it mid-quarantine
        wd.router.replicas.remove(rep)
        wd.poll_once()
        assert "gh-0" not in wd.stats()["replicas"]
        assert wd._quarantined_until == {}
        assert decode_watchdog_series(reg)["states"] == {}


class TestTransferWatermarks:
    def test_stall_detection_and_abort_cycle(self):
        clock = FakeClock()
        tw = TransferWatermarks(clock=clock)
        tw.begin("t-1")
        tw.progress("t-1", 0)
        clock.advance(1.0)
        assert tw.stalled(5.0) == []
        clock.advance(5.0)
        assert tw.stalled(5.0) == ["t-1"]
        assert tw.request_abort("t-1") is True
        assert tw.request_abort("t-1") is False  # idempotent
        assert tw.abort_requested("t-1")
        assert tw.stalled(5.0) == []  # aborted transfers drop out
        tw.end("t-1")
        assert not tw.abort_requested("t-1")
        assert tw.snapshot() == []

    def test_watchdog_aborts_stalled_transfer_once(self, tmp_path):
        clock = FakeClock()
        tw = TransferWatermarks(clock=clock)
        wd = FleetWatchdog(
            _FakeRouter([]),
            policy=WatchdogPolicy(transfer_stall_s=2.0),
            clock=clock,
            journal_path=tmp_path / "watchdog.jsonl",
            transfer_watermarks=tw,
        )
        tw.begin("t-2")
        clock.advance(3.0)
        first = wd.poll_once()
        assert [a["action"] for a in first] == ["abort_transfer"]
        assert tw.abort_requested("t-2")
        assert wd.poll_once() == []  # armed once, journaled once

    def test_live_transfer_stall_breaks_into_transport_error(self, state_dir):
        """The injected ``disagg.transfer_stall`` holds a REAL transfer()
        between chunks with no error; the watchdog-style abort must
        surface as TransportError (the coordinator's unified-fallback
        trigger), not TransferAborted (the client-abort path)."""
        from modal_examples_tpu.faults.inject import FaultPlan, active
        from modal_examples_tpu.serving.disagg.transport import (
            LoopbackChannel,
            TransportError,
            transfer,
        )
        from modal_examples_tpu.serving.health import transfers

        result: dict = {}

        def run():
            try:
                transfer(
                    b"x" * 4096,
                    LoopbackChannel(),
                    transfer_id="t-stall",
                    chunk_bytes=256,
                    backoff=None,
                )
            except Exception as e:  # noqa: BLE001 - recorded for assert
                result["exc"] = e

        plan = FaultPlan({"disagg.transfer_stall": {"on_hit": 1}})
        with active(plan):
            t = threading.Thread(target=run)
            t.start()
            deadline = time.monotonic() + 10
            while (
                time.monotonic() < deadline
                and not plan.fired().get("disagg.transfer_stall")
            ):
                time.sleep(0.005)
            assert plan.fired().get("disagg.transfer_stall") == 1
            # the watchdog's ladder action, driven directly
            assert transfers.request_abort("t-stall")
            t.join(timeout=30)
        assert not t.is_alive(), "stalled transfer never unblocked"
        assert isinstance(result.get("exc"), TransportError)
        assert "watchdog" in str(result["exc"])
        assert transfers.snapshot() == []  # registry drained


class TestRouterDownWeight:
    def _replicas(self, jax_cpu):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )
        from modal_examples_tpu.serving import LLMEngine

        cfg = llama.LlamaConfig.tiny()
        eng_a = LLMEngine(
            cfg, seed=0, max_slots=2, max_model_len=64,
            prefill_buckets=(16, 32), page_size=8,
        )
        eng_b = LLMEngine(
            cfg, params=eng_a.params, max_slots=2, max_model_len=64,
            prefill_buckets=(16, 32), page_size=8,
        )
        rep_a = EngineReplica(eng_a, "dw-a")
        rep_b = EngineReplica(eng_b, "dw-b")
        return rep_a, rep_b, PrefixAffinityRouter([rep_a, rep_b])

    def test_degraded_replica_loses_placement(self, jax_cpu):
        rep_a, rep_b, router = self._replicas(jax_cpu)
        try:
            prompt = "shared system prompt for the affinity key"
            preferred = router._preferred(
                router._prompt_key(prompt), router._serving
            )
            other = rep_b if preferred is rep_a else rep_a
            # healthy: affinity wins
            assert router.route(prompt) is preferred
            # degraded: the preferred replica is down-weighted away
            router.set_health_weight(preferred.name, 0.25)
            assert router.health_weight(preferred.name) == 0.25
            assert router.route(prompt) is other
            # restore: affinity returns
            router.set_health_weight(preferred.name, 1.0)
            assert router.route(prompt) is preferred
            # stats carry the graded surface
            stats = router.stats()["replicas"][preferred.name]
            assert stats["weight"] == 1.0
            assert stats["state"] == "healthy"
            assert "progress" in stats
        finally:
            rep_a.engine.stop()
            rep_b.engine.stop()

    def test_quarantined_replica_refuses_probe_and_health(self, jax_cpu):
        rep_a, rep_b, router = self._replicas(jax_cpu)
        try:
            rep_a.quarantined = True
            assert not rep_a.healthy()
            assert not rep_a.probe()
            # placement never lands on it
            for i in range(6):
                assert router.route(f"probe prompt {i}") is rep_b
            rep_a.quarantined = False
            assert rep_a.healthy()
        finally:
            rep_a.engine.stop()
            rep_b.engine.stop()


class TestFleetQuarantineReplacement:
    def test_quarantine_triggers_scale_up(self, tmp_path):
        """A watchdog-quarantined replica is benched capacity: the fleet
        autoscaler must exclude it from the signals AND scale out a
        replacement with trigger="quarantine" (docs/health.md)."""
        from modal_examples_tpu.fleet.autoscaler import FleetAutoscaler

        class _Policy:
            def total_depth(self):
                return 0

        class _Cache:
            def occupancy(self):
                return {"pages_used": 0, "pages_free": 64, "pages_total": 64}

        class _Eng:
            def __init__(self):
                self.policy = _Policy()
                self.cache = _Cache()
                self.prefix_cache = None
                self.admission = type("A", (), {"reserved_pages": 0})()

            def start(self):
                return self

            def stop(self):
                pass

        class _Rep:
            def __init__(self, name):
                self.name = name
                self.role = "unified"
                self.engine = _Eng()
                self.serves_requests = True
                self.quarantined = False

            def outstanding(self):
                return 0

            def capacity(self):
                return 2

            def healthy(self):
                return not self.quarantined

        class _Router:
            def __init__(self, replicas):
                self.replicas = replicas

            def add_replica(self, r):
                self.replicas.append(r)

        built = []

        def factory(name, role):
            r = _Rep(name)
            built.append(name)
            return r, "warm"

        router = _Router([_Rep("seed-0"), _Rep("seed-1")])
        scaler = FleetAutoscaler(
            router,
            factory,
            max_replicas={"decode": 4},
            up_ticks=1,
            cooldown_s=0.0,
            slos=(),
            journal_path=tmp_path / "fleet.jsonl",
        )
        # healthy fleet: no action
        assert scaler.tick() == []
        # the watchdog benches seed-1
        router.replicas[1].quarantined = True
        sig = scaler.signals(consume_sheds=False)["decode"]
        assert sig["quarantined"] == 1
        assert sig["replicas"] == 1  # benched capacity excluded
        actions = scaler.tick()
        assert [a["trigger"] for a in actions] == ["quarantine"]
        assert built, "no replacement replica was built"
        # the trigger is per-BENCHING, not per-tick: the benched replica is
        # compensated exactly once — a 30s quarantine window must not buy a
        # fresh build every cooldown expiry
        assert scaler.tick() == []
        assert scaler.tick() == []
        assert len(built) == 1
        # quarantine lifts (handled set prunes), the SAME replica is
        # benched again later: a new edge, a new replacement
        router.replicas[1].quarantined = False
        assert scaler.tick() == []
        router.replicas[1].quarantined = True
        actions = scaler.tick()
        assert [a["trigger"] for a in actions] == ["quarantine"]
        assert len(built) == 2


class TestHangFailoverE2E:
    def test_silent_freeze_resumes_streams_token_identical(self, jax_cpu):
        """The acceptance E2E (docs/health.md): a HANG — not an error —
        on the replica holding live streams. The watchdog classifies it
        wedged from stale watermarks, error-stops it, and the PR-12
        reactive failover resumes every stream on the peer with the exact
        fault-free token sequence. Recovery is asserted to HAPPEN (bounded
        by the drain timeout), never how fast — wall-clock latency lives
        in the fake-clock matrix and the benchdiff-gated `recovery`
        section."""
        from modal_examples_tpu.faults.chaos import (
            check_drained,
            check_router_recovered,
        )
        from modal_examples_tpu.faults.inject import FaultPlan, active
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg = llama.LlamaConfig.tiny()

        def engine(**kw):
            return LLMEngine(
                cfg, seed=0, max_slots=4, max_model_len=128, page_size=8,
                prefill_buckets=(16, 32), **kw,
            )

        sp = SamplingParams(max_tokens=48, temperature=0.0)
        prompts = [
            "the quick brown fox jumps over the lazy dog",
            "the quick brown fox naps in the warm sun",
            "a completely different prompt about thundering herds",
        ]
        ref_engine = engine()
        try:
            reference = {p: ref_engine.generate(p, sp) for p in prompts}
        finally:
            ref_engine.stop()

        eng_a = engine()
        eng_b = engine(params=eng_a.params)
        # warm the STANDBY's own jits before any watchdog runs: its
        # first-ever compile otherwise happens at takeover, where the
        # trace stall reads as a wedge of the engine the failover is
        # recovering onto (the watchdog-vs-compile rule, docs/health.md)
        eng_b.generate(prompts[0], sp)
        eng_b.stop()
        rep_a = EngineReplica(eng_a, "hang-a", role="unified")
        rep_b = EngineReplica(eng_b, "hang-b", role="unified")
        router = PrefixAffinityRouter([rep_a, rep_b], reprobe_s=0.2)
        watchdog = FleetWatchdog(
            router,
            policy=WatchdogPolicy(
                degraded_after_s=1.0, wedged_after_s=2.0, quarantine_after=99
            ),
            poll_s=0.1,
        )
        try:
            eng_a.start()  # the victim; B boots lazily at takeover
            reqs, outs, threads = [], {}, []
            for p in prompts:
                req = rep_a.submit(p, sp)  # all streams on the victim
                req._router_replica = rep_a
                reqs.append(req)
                outs[req.request_id] = pieces = []
                t = threading.Thread(
                    target=lambda r=req, buf=pieces: buf.extend(
                        router.stream(r)
                    )
                )
                t.start()
                threads.append(t)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not all(
                len(r.generated_tokens) >= 3 for r in reqs
            ):
                time.sleep(0.005)
            # engines warm, streams mid-decode: NOW the watchdog starts
            # (first-compile stalls must never read as a wedge) and the
            # ONLY running loop silently freezes — no exception, no
            # crash, healthy() still true
            watchdog.start()
            plan = FaultPlan(
                {"engine.scheduler_freeze": {"p": 1.0, "max_fires": 1}}
            )
            with active(plan):
                deadline = time.monotonic() + 30
                while not plan.fired() and time.monotonic() < deadline:
                    time.sleep(0.005)
                assert plan.fired().get("engine.scheduler_freeze") == 1
                for t in threads:
                    t.join(timeout=120)
                    assert not t.is_alive(), "stream wedged after the hang"
            for req in reqs:
                # zero client-visible errors + the fault-free sequence
                assert req.finish_reason in ("stop", "length"), req.request_id
                assert "".join(outs[req.request_id]) == reference[req.prompt]
            # the ladder ran: wedge detected, error-stop taken
            actions = [e["action"] for e in watchdog.events]
            assert "stop_revive" in actions, watchdog.events
            # the stitched timelines show the watchdog seam on at least
            # one affected request (the `watchdog` span event)
            from modal_examples_tpu.observability import reqtrace as rt

            seen_watchdog_event = False
            for req in reqs:
                for s in rt.read_trace(req.request_id):
                    if s["name"] == "watchdog":
                        seen_watchdog_event = True
            assert seen_watchdog_event
            # PR-8 fleet invariants + the router revival leg: a placement
            # after reprobe_s probes, revives, and restarts the victim
            time.sleep(router.reprobe_s + 0.2)
            assert router.route(prompts[0]) is not None
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and (
                check_router_recovered(router)
                or check_drained({"hang-a": eng_a, "hang-b": eng_b})
            ):
                time.sleep(0.1)
                router.route(prompts[0])
            assert check_drained({"hang-a": eng_a, "hang-b": eng_b}) == []
            assert check_router_recovered(router) == []
        finally:
            watchdog.stop()
            eng_a.stop()
            eng_b.stop()
