"""Latent video DiT: shapes, factorized attention actually mixes time,
first-frame pinning, and flow-loss training signal (the reference's
text-to-video / world-models tier, served CUDA-side there)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavyweight: excluded from the fast tier


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def jnp(jax):
    import jax.numpy as jnp

    return jnp


@pytest.fixture(scope="module")
def setup(jax):
    from modal_examples_tpu.models import video

    cfg = video.VideoDiTConfig.tiny()
    params = video.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestVideoDiT:
    def test_forward_shapes_and_finite(self, jax, jnp, setup):
        from modal_examples_tpu.models import video

        cfg, params = setup
        B = 2
        x = jax.random.normal(
            jax.random.PRNGKey(1),
            (B, cfg.frames, cfg.img_size, cfg.img_size, cfg.channels),
        )
        t = jnp.array([0.3, 0.9])
        mask = jnp.zeros((B, cfg.frames))
        text = jax.random.normal(
            jax.random.PRNGKey(2), (B, cfg.text_len, cfg.text_dim)
        )
        v = video.forward(params, x, t, mask, text, cfg)
        assert v.shape == x.shape
        assert np.isfinite(np.asarray(v)).all()

    def test_patchify_roundtrip(self, jax, jnp, setup):
        from modal_examples_tpu.models import video

        cfg, _ = setup
        x = jax.random.normal(
            jax.random.PRNGKey(3),
            (1, cfg.frames, cfg.img_size, cfg.img_size, cfg.channels),
        )
        rt = video.unpatchify(video.patchify(x, cfg), cfg)
        np.testing.assert_allclose(np.asarray(rt), np.asarray(x), atol=1e-6)

    def test_temporal_attention_mixes_frames(self, jax, jnp, setup):
        """Perturbing frame 3's input must change frame 0's output — the
        temporal attention path actually crosses frames (a spatial-only
        model would be frame-local)."""
        from modal_examples_tpu.models import video

        cfg, params = setup
        # gates are zero-init (adaLN-zero), so train-free params give no
        # cross-frame signal; force the temporal gates non-zero via mod_b
        import jax.numpy as jnp2

        p = dict(params)
        layers = dict(p["layers"])
        D = cfg.dim
        mod_b = np.asarray(layers["mod_b"]).copy()
        mod_b[:, 5 * D : 6 * D] = 1.0  # g2: temporal-attention gate
        layers["mod_b"] = jnp2.asarray(mod_b)
        p["layers"] = layers
        # the output head is zero-init (adaLN-zero): un-zero it so the
        # probe is visible at the output at all
        p["final_proj"] = (
            jax.random.normal(jax.random.PRNGKey(99), p["final_proj"].shape)
            * 0.1
        )

        x = jax.random.normal(
            jax.random.PRNGKey(4),
            (1, cfg.frames, cfg.img_size, cfg.img_size, cfg.channels),
        )
        t = jnp.array([0.5])
        mask = jnp.zeros((1, cfg.frames))
        text = jax.random.normal(
            jax.random.PRNGKey(5), (1, cfg.text_len, cfg.text_dim)
        )
        base = video.forward(p, x, t, mask, text, cfg)
        x2 = x.at[:, 3].add(1.0)
        pert = video.forward(p, x2, t, mask, text, cfg)
        delta0 = float(jnp.max(jnp.abs(pert[:, 0] - base[:, 0])))
        assert delta0 > 1e-6, "temporal attention did not propagate"

    def test_sample_pins_first_frame(self, jax, jnp, setup):
        from modal_examples_tpu.models import video

        cfg, params = setup
        text = jax.random.normal(
            jax.random.PRNGKey(6), (1, cfg.text_len, cfg.text_dim)
        )
        key_frame = jax.random.normal(
            jax.random.PRNGKey(7), (1, cfg.img_size, cfg.img_size, cfg.channels)
        )
        out = video.sample(
            params, jax.random.PRNGKey(8), text, cfg,
            first_frame=key_frame, steps=3, guidance=1.5,
        )
        assert out.shape == (
            1, cfg.frames, cfg.img_size, cfg.img_size, cfg.channels
        )
        np.testing.assert_allclose(
            np.asarray(out[:, 0]), np.asarray(key_frame), atol=1e-6
        )
        assert np.isfinite(np.asarray(out)).all()

    def test_flow_loss_decreases_with_training(self, jax, jnp, setup):
        """A few optimizer steps on a fixed synthetic batch must reduce the
        flow loss — the training signal is real (same proof style as the
        image DiT / whisper fine-tune tests)."""
        import optax

        from modal_examples_tpu.models import video

        cfg, _ = setup
        params = video.init_params(jax.random.PRNGKey(10), cfg)
        B = 4
        vid = jax.random.normal(
            jax.random.PRNGKey(11),
            (B, cfg.frames, cfg.img_size, cfg.img_size, cfg.channels),
        ) * 0.5
        text = jax.random.normal(
            jax.random.PRNGKey(12), (B, cfg.text_len, cfg.text_dim)
        )
        opt = optax.adam(1e-3)
        opt_state = opt.init(params)

        import jax as j

        @j.jit
        def step(params, opt_state, key):
            loss, grads = j.value_and_grad(video.flow_loss)(
                params, key, vid, text, cfg
            )
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        key = jax.random.PRNGKey(13)
        first = None
        last = None
        for i in range(30):
            key, sub = jax.random.split(key)
            params, opt_state, loss = step(params, opt_state, sub)
            if i == 0:
                first = float(loss)
            last = float(loss)
        assert last < first * 0.9, (first, last)
