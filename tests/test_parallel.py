"""Tests for the parallel layer: mesh construction, named-axis collectives
under shard_map on the 8-device CPU mesh, and gang-scheduled @clustered
execution with real cross-process jax.distributed collectives (the multi-host
simulation SURVEY.md §4 calls for)."""

import pytest

pytestmark = pytest.mark.slow  # heavyweight: excluded from the fast tier

import numpy as np

import modal_examples_tpu as mtpu


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


class TestMesh:
    def test_default_data_mesh(self, jax):
        from modal_examples_tpu.parallel import make_mesh

        mesh = make_mesh()
        assert mesh.devices.size == 8
        assert mesh.axis_names == ("data",)

    def test_two_axis_mesh_with_fill(self, jax):
        from modal_examples_tpu.parallel import make_mesh

        mesh = make_mesh({"data": -1, "tensor": 4})
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
            "data": 2,
            "tensor": 4,
        }
        # canonical order: data (cross-host) before tensor (ICI)
        assert mesh.axis_names == ("data", "tensor")

    def test_axis_mismatch_raises(self, jax):
        from modal_examples_tpu.parallel import make_mesh

        with pytest.raises(ValueError):
            make_mesh({"data": 3, "tensor": 4})

    def test_spec_validation(self, jax):
        from modal_examples_tpu.parallel import make_mesh

        with pytest.raises(ValueError):
            make_mesh(spec="v5e-4")  # 8 visible devices != 4


class TestCollectives:
    def test_psum_and_axis_index(self, jax):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from modal_examples_tpu.parallel import collectives as col, make_mesh
        from modal_examples_tpu.parallel.mesh import shard_map_compat

        mesh = make_mesh({"data": 8})

        def f(x):
            r = col.axis_index("data")
            total = col.psum(x, "data")
            return total + 0 * r

        out = shard_map_compat(
            f, mesh=mesh, in_specs=P("data"), out_specs=P("data")
        )(jnp.ones((8, 4)))
        np.testing.assert_allclose(np.asarray(out), 8.0)

    def test_ring_shift(self, jax):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from modal_examples_tpu.parallel import collectives as col, make_mesh
        from modal_examples_tpu.parallel.mesh import shard_map_compat

        mesh = make_mesh({"data": 8})
        x = jnp.arange(8.0).reshape(8, 1)
        out = shard_map_compat(
            lambda s: col.ring_shift(s, "data", 1),
            mesh=mesh,
            in_specs=P("data"),
            out_specs=P("data"),
        )(x)
        # shard i's value moves to shard (i+1) % 8
        np.testing.assert_allclose(
            np.asarray(out).ravel(), np.roll(np.arange(8.0), 1)
        )

    def test_all_gather_and_reduce_scatter(self, jax):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from modal_examples_tpu.parallel import collectives as col, make_mesh
        from modal_examples_tpu.parallel.mesh import shard_map_compat

        mesh = make_mesh({"data": 8})
        x = jnp.arange(16.0).reshape(8, 2)

        gathered = shard_map_compat(
            lambda s: col.all_gather(s, "data"),
            mesh=mesh,
            in_specs=P("data"),
            out_specs=P(None),
            check_vma=False,
        )(x)
        np.testing.assert_allclose(np.asarray(gathered), np.asarray(x))

        scattered = shard_map_compat(
            lambda s: col.reduce_scatter(s, "data"),
            mesh=mesh,
            in_specs=P(None),
            out_specs=P("data"),
        )(x)
        np.testing.assert_allclose(np.asarray(scattered), np.asarray(x) * 8)


class TestSharding:
    def test_shard_pytree_places_leaves(self, jax):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from modal_examples_tpu.parallel import make_mesh, shard_pytree

        mesh = make_mesh({"data": 8})
        tree = {"w": jnp.ones((16, 4)), "b": jnp.ones((4,))}
        placed = shard_pytree(
            tree, mesh, lambda path, leaf: P("data") if leaf.ndim == 2 else P()
        )
        assert placed["w"].sharding.spec == P("data")
        assert placed["b"].sharding.spec == P()


class TestClustered:
    def test_gang_scheduled_jax_distributed(self):
        """2 hosts x 4 chips: psum over a global mesh spanning processes —
        the simple_torch_cluster parity test, jax-flavored."""
        app = mtpu.App("cluster-test")

        @app.function(timeout=180)
        @mtpu.experimental.clustered(size=2, chips_per_host=4)
        def allreduce_job():
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            from modal_examples_tpu.parallel import cluster, make_mesh

            info = cluster.init_jax_distributed()
            assert jax.process_count() == 2
            assert jax.device_count() == 8  # global view across both hosts
            mesh = make_mesh({"data": 8})
            x = jax.make_array_from_process_local_data(
                NamedSharding(mesh, P("data")),
                np.full((4, 2), float(info.rank + 1), np.float32),
            )
            total = jax.jit(
                lambda a: jnp.sum(a),
                out_shardings=NamedSharding(mesh, P()),
            )(x)
            # rank0 shards contribute 1.0 * 8, rank1 shards 2.0 * 8
            return float(total), info.rank, info.size

        with app.run():
            total, rank, size = allreduce_job.remote()
        assert total == pytest.approx(24.0)
        assert rank == 0 and size == 2

    def test_cluster_info_outside_raises(self):
        with pytest.raises(RuntimeError):
            mtpu.experimental.get_cluster_info()


class TestFSDP:
    """ZeRO/FSDP semantics proof (VERDICT #10): sharding params + optimizer
    state over the fsdp axis must actually shrink per-device memory ~linearly
    with mesh size, while training stays correct (same losses as unsharded)."""

    @staticmethod
    def _device0_bytes(jax, tree):
        d0 = jax.devices()[0]
        total = 0
        for leaf in jax.tree.leaves(tree):
            if not hasattr(leaf, "addressable_shards"):
                continue
            for sh in leaf.addressable_shards:
                if sh.device == d0:
                    total += sh.data.nbytes
        return total

    def _train(self, jax, n_shards):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.parallel import fsdp_specs, make_mesh
        from modal_examples_tpu.training import (
            Trainer, cross_entropy_loss, make_optimizer,
        )

        cfg = llama.LlamaConfig(
            vocab_size=512, dim=128, n_layers=2, n_heads=4, n_kv_heads=4,
            ffn_dim=256, max_seq_len=64, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)

        def loss_fn(p, batch):
            lg = llama.forward(p, batch["tokens"], cfg, attn_impl="xla")
            return cross_entropy_loss(lg[:, :-1], batch["tokens"][:, 1:])

        mesh = make_mesh({"fsdp": n_shards})
        t = Trainer(
            loss_fn, make_optimizer(1e-2), mesh=mesh,
            param_specs=fsdp_specs(params, mesh), batch_spec=P("fsdp"),
        )
        state = t.init_state(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 512)
        losses = []
        for _ in range(3):
            state, m = t.train_step(state, t.shard_batch({"tokens": tokens}))
            losses.append(float(m["loss"]))
        return state, losses

    def test_memory_shrinks_linearly_and_training_matches(self, jax):
        state1, losses1 = self._train(jax, 1)
        bytes1 = self._device0_bytes(jax, (state1.params, state1.opt_state))
        state8, losses8 = self._train(jax, 8)
        bytes8 = self._device0_bytes(jax, (state8.params, state8.opt_state))

        # params+optimizer on device 0 must shrink ~linearly (small replicated
        # norm leaves keep it from exactly 8x; require > 4x)
        assert bytes8 < bytes1 / 4, (bytes1, bytes8)
        # and the sharded run must train identically (same data, same init)
        np.testing.assert_allclose(losses8, losses1, rtol=2e-3)

    def test_opt_state_is_sharded(self, jax):
        from jax.sharding import PartitionSpec as P

        state8, _ = self._train(jax, 8)
        # adam moments for the big matrices must carry the fsdp spec, not be
        # replicated (ZeRO: optimizer state partitioned like the params)
        sharded = [
            leaf
            for leaf in jax.tree.leaves(state8.opt_state)
            if hasattr(leaf, "sharding")
            and leaf.ndim >= 2
            and any(ax == "fsdp" for axes in (leaf.sharding.spec or ()) if axes
                    for ax in (axes if isinstance(axes, tuple) else (axes,)))
        ]
        assert sharded, "no fsdp-sharded optimizer-state leaves found"
