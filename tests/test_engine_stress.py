"""Concurrent-load engine stress — the regression net for the round-2
intermittent flake (NOTES.md: output-content mismatches on the shared-
fixture engine under heavy machine load, "consistent with an intermittent
scheduler-side exception being swallowed by the serving loop's catch-all").

The suite now runs engines in STRICT mode (conftest sets
MTPU_ENGINE_STRICT=1): any scheduler-loop exception stops the engine and
marks every caller finish_reason="error" instead of being silently
swallowed, and the session-wide sentinel (conftest._engine_error_sentinel)
asserts error_count == 0 over every engine the suite created. This test
recreates the trigger conditions deliberately: concurrent submitters,
mixed sampling params, slot contention (more requests than slots), and
synthetic CPU load — and asserts seeded outputs are byte-identical across
load levels and repeats.
"""

import hashlib
import threading

import pytest

pytestmark = pytest.mark.slow  # heavyweight: excluded from the fast tier


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def engine(jax):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    cfg = llama.LlamaConfig.tiny()
    eng = LLMEngine(
        cfg, max_slots=4, max_model_len=128, page_size=16,
        prefill_buckets=(32, 64), seed=0,
    )
    yield eng
    try:
        eng.stop()
    finally:
        assert eng.error_count == 0, eng.error_log


def _cpu_load(stop: threading.Event) -> None:
    h = hashlib.md5()
    while not stop.is_set():
        h.update(b"x" * 8192)


class TestConcurrentLoadDeterminism:
    def test_seeded_outputs_stable_under_concurrency_and_load(self, engine):
        """3 submitter threads x 8 seeded requests each, twice (quiet run
        then under 3 spinner threads of CPU load): every (prompt, seed)
        must produce byte-identical text both times."""
        from modal_examples_tpu.serving import SamplingParams

        prompts = [
            ("the quick brown", 11),
            ("a model of", 23),
            ("paged attention", 37),
            ("tokens per second", 53),
        ]

        def run_wave() -> dict:
            results = {}
            errors = []  # worker exceptions re-raised in the test thread —
            # threading.Thread would otherwise swallow a failed assert
            lock = threading.Lock()

            def submitter(offset: int):
                try:
                    for i, (prompt, seed) in enumerate(prompts):
                        p = SamplingParams(
                            max_tokens=12,
                            temperature=1.0,
                            seed=seed,
                            # exercise both sampling branches across the wave
                            top_k=5 if (i + offset) % 2 else 0,
                        )
                        req = engine.submit(prompt, p)
                        text = "".join(engine.stream(req))
                        assert req.finish_reason != "error", engine.error_log
                        with lock:
                            results[(prompt, seed, p.top_k)] = text
                        # same (prompt, seed, params) resubmitted
                        # immediately — slot/batch composition differs
                        # between submitters
                        req2 = engine.submit(prompt, p)
                        text2 = "".join(engine.stream(req2))
                        assert text2 == text, (
                            f"same-wave mismatch for {prompt!r} seed={seed}"
                        )
                except BaseException as e:  # noqa: BLE001
                    with lock:
                        errors.append(e)

            threads = [
                threading.Thread(target=submitter, args=(k,)) for k in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            if errors:
                raise errors[0]
            return results

        quiet = run_wave()

        stop = threading.Event()
        spinners = [threading.Thread(target=_cpu_load, args=(stop,))
                    for _ in range(3)]
        for t in spinners:
            t.start()
        try:
            loaded = run_wave()
        finally:
            stop.set()
            for t in spinners:
                t.join(timeout=10)

        assert quiet == loaded, {
            k: (quiet[k], loaded[k])
            for k in quiet
            if quiet[k] != loaded.get(k)
        }
        assert engine.error_count == 0, engine.error_log


class TestPriorityInversion:
    def test_interactive_queue_wait_bounded_under_batch_flood(self, engine):
        """A flood of `batch` requests plus a trickle of `interactive` ones:
        under the fair-share policy the interactive trickle must jump the
        batch backlog — its p95 queue wait (submit -> first token) stays
        bounded and strictly below the flood's, and every interactive
        request starts before the flood finishes draining."""
        from modal_examples_tpu.serving import SamplingParams

        flood = [
            engine.submit(
                f"bulk work item {i}",
                SamplingParams(max_tokens=24, temperature=1.0),
                priority="batch",
                tenant="bulk-job",
            )
            for i in range(24)
        ]
        # interactive trickle lands while the flood is still queued (24
        # batch items over 4 slots take many decode waves to drain)
        trickle = [
            engine.submit(
                f"chat {i}",
                SamplingParams(max_tokens=4, temperature=0.0),
                priority="interactive",
                tenant="chat-user",
            )
            for i in range(6)
        ]
        engine.start()
        for r in trickle + flood:
            "".join(engine.stream(r))
            assert r.finish_reason not in (None, "error")

        def waits(reqs):
            return sorted(r.first_token_at - r.created for r in reqs)

        def p95(xs):
            return xs[min(len(xs) - 1, int(0.95 * len(xs)))]

        chat_waits, bulk_waits = waits(trickle), waits(flood)
        # the flood saturates 4 slots for many blocks; interactive work must
        # not queue behind the whole backlog
        assert p95(chat_waits) < p95(bulk_waits), (chat_waits, bulk_waits)
        # every interactive request started before the flood fully drained
        last_bulk_start = max(r.first_token_at for r in flood)
        assert all(r.first_token_at <= last_bulk_start for r in trickle)
        assert engine.error_count == 0, engine.error_log
