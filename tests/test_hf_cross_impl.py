"""Cross-implementation golden checks: our JAX models vs the transformers
(torch CPU) reference on identical weights.

The round-3 VERDICT asked for a golden-fixture interop test: real published
tensors + reference activations, because self-referential roundtrip tests
(synthesize the HF layout, read it back) cannot catch convention swaps —
exactly the class of the round-2 (scale, shift) AdaLayerNorm bug. This
image has zero egress, so no published checkpoint exists here; the
strongest available equivalent is a CROSS-IMPLEMENTATION check: construct a
tiny transformers model with random weights, `save_pretrained` it to
safetensors, load that through OUR loaders, and assert OUR forward matches
THE TRANSFORMERS forward numerically. Any transpose/RoPE/norm-order/
activation convention mismatch in the loader or the model shows up as a
large divergence; agreement at f32 tolerances is the same evidence a
published-tensor fixture would give (minus weight *values*, which no test
can validate without egress).

torch stays CPU-only here (baked into the image for exactly this kind of
parity work; it is NOT part of the serving/training stack).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # transformers graph construction is heavy


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


def _save_pretrained(model, tmp_path):
    model.save_pretrained(tmp_path, safe_serialization=True)
    return tmp_path


class TestLlamaCrossImpl:
    @pytest.mark.parametrize("gqa", [False, True])
    def test_logits_match_transformers(self, jax, tmp_path, gqa):
        import torch
        from transformers import LlamaConfig as HFConfig
        from transformers import LlamaForCausalLM

        from modal_examples_tpu.models import llama

        hf_cfg = HFConfig(
            vocab_size=128,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2 if gqa else 4,
            max_position_embeddings=64,
            rms_norm_eps=1e-5,
            rope_theta=10000.0,
            tie_word_embeddings=False,
            attention_bias=False,
        )
        torch.manual_seed(0)
        hf = LlamaForCausalLM(hf_cfg).eval()
        d = _save_pretrained(hf, tmp_path / ("gqa" if gqa else "mha"))
        hf.config.save_pretrained(d)

        cfg = llama.LlamaConfig.from_hf_config(d / "config.json")
        params = llama.load_hf_weights(d, cfg, dtype="float32")

        tokens = np.array([[3, 17, 42, 99, 7, 55, 21, 8]], np.int64)
        with torch.no_grad():
            want = hf(torch.from_numpy(tokens)).logits.numpy()
        got = np.asarray(
            llama.forward(
                params, np.asarray(tokens, np.int32), cfg, attn_impl="xla"
            ),
            np.float32,
        )
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)

    def test_rope_scaling_llama3_matches_transformers(self, jax, tmp_path):
        """The llama3.1 rope_scaling path (factor/high/low freq) against
        transformers' implementation — conventions here are easy to get
        subtly wrong and affect only long-range behavior."""
        import torch
        from transformers import LlamaConfig as HFConfig
        from transformers import LlamaForCausalLM

        from modal_examples_tpu.models import llama

        hf_cfg = HFConfig(
            vocab_size=96,
            hidden_size=64,
            intermediate_size=96,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            max_position_embeddings=128,
            rope_theta=500000.0,
            tie_word_embeddings=False,
            attention_bias=False,
            rope_scaling={
                "rope_type": "llama3",
                "factor": 8.0,
                "high_freq_factor": 4.0,
                "low_freq_factor": 1.0,
                "original_max_position_embeddings": 32,
            },
        )
        torch.manual_seed(1)
        hf = LlamaForCausalLM(hf_cfg).eval()
        d = _save_pretrained(hf, tmp_path / "rs")
        hf.config.save_pretrained(d)

        cfg = llama.LlamaConfig.from_hf_config(d / "config.json")
        assert cfg.rope_scaling is not None  # the path under test is active
        params = llama.load_hf_weights(d, cfg, dtype="float32")

        rng = np.random.RandomState(0)
        tokens = rng.randint(0, 96, (2, 48)).astype(np.int64)
        with torch.no_grad():
            want = hf(torch.from_numpy(tokens)).logits.numpy()
        got = np.asarray(
            llama.forward(
                params, np.asarray(tokens, np.int32), cfg, attn_impl="xla"
            ),
            np.float32,
        )
        np.testing.assert_allclose(got, want, atol=3e-4, rtol=3e-3)


class TestWhisperCrossImpl:
    def test_logits_match_transformers(self, jax, tmp_path):
        import torch
        from transformers import WhisperConfig as HFConfig
        from transformers import WhisperForConditionalGeneration

        from modal_examples_tpu.models import whisper

        hf_cfg = HFConfig(
            vocab_size=200,
            num_mel_bins=80,
            encoder_layers=2,
            decoder_layers=2,
            encoder_attention_heads=4,
            decoder_attention_heads=4,
            d_model=64,
            encoder_ffn_dim=256,  # our ffn is 4*dim by construction
            decoder_ffn_dim=256,
            max_source_positions=100,
            max_target_positions=32,
            pad_token_id=0,
            bos_token_id=1,
            eos_token_id=2,
            decoder_start_token_id=1,
        )
        torch.manual_seed(2)
        hf = WhisperForConditionalGeneration(hf_cfg).eval()
        d = _save_pretrained(hf, tmp_path / "whisper")

        cfg = whisper.WhisperConfig(
            n_mels=80, n_audio_ctx=100, n_text_ctx=32, vocab_size=200,
            dim=64, n_heads=4, n_audio_layers=2, n_text_layers=2,
        )
        params = whisper.load_hf_weights(d, cfg, dtype="float32")

        rng = np.random.RandomState(3)
        mel = rng.randn(1, 80, 200).astype(np.float32)  # HF: [B, mels, T]
        toks = rng.randint(0, 200, (1, 8)).astype(np.int64)
        with torch.no_grad():
            want = hf(
                input_features=torch.from_numpy(mel),
                decoder_input_ids=torch.from_numpy(toks),
            ).logits.numpy()
        got = np.asarray(
            whisper.forward(
                params,
                np.asarray(mel.transpose(0, 2, 1), np.float32),  # ours: [B,T,mels]
                np.asarray(toks, np.int32),
                cfg,
            ),
            np.float32,
        )
        np.testing.assert_allclose(got, want, atol=3e-4, rtol=3e-3)


class TestCLIPCrossImpl:
    def test_text_hidden_states_match_transformers(self, jax, tmp_path):
        import torch
        from transformers import CLIPTextConfig as HFConfig
        from transformers import CLIPTextModel

        from modal_examples_tpu.models import clip_text

        hf_cfg = HFConfig(
            vocab_size=99,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            max_position_embeddings=16,
            eos_token_id=2,
            bos_token_id=1,
        )
        torch.manual_seed(4)
        hf = CLIPTextModel(hf_cfg).eval()
        d = _save_pretrained(hf, tmp_path / "clip")

        cfg = clip_text.CLIPTextConfig(
            vocab_size=99, dim=64, n_layers=2, n_heads=4, max_len=16,
            eos_token_id=2,
        )
        params = clip_text.load_hf_weights(d, cfg, dtype="float32")

        toks = np.array([[1, 5, 9, 30, 2, 0, 0, 0]], np.int64)
        with torch.no_grad():
            out = hf(input_ids=torch.from_numpy(toks))
            want_hidden = out.last_hidden_state.numpy()
        got_hidden, _ = clip_text.forward(
            params, np.asarray(toks, np.int32), cfg
        )
        np.testing.assert_allclose(
            np.asarray(got_hidden, np.float32), want_hidden,
            atol=3e-4, rtol=3e-3,
        )

    def test_vision_tower_matches_transformers(self, jax, tmp_path):
        """Our VLM ViT vs transformers CLIPVisionModel on the same weights:
        proves patchify ordering, pre-LN placement, QuickGELU vs GELU, and
        the conv1->matmul mapping in load_hf_vision_weights. The projector
        is ours alone, so compare the tower output (pre-projector) by
        loading with an identity projector."""
        import torch
        from transformers import CLIPVisionConfig as HFConfig
        from transformers import CLIPVisionModel

        from modal_examples_tpu.models import vlm

        hf_cfg = HFConfig(
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            image_size=32,
            patch_size=8,
            hidden_act="quick_gelu",  # what published CLIP towers use
        )
        torch.manual_seed(5)
        hf = CLIPVisionModel(hf_cfg).eval()
        d = tmp_path / "clipv"
        hf.save_pretrained(d, safe_serialization=True)

        # append an identity projector so load_hf_vision_weights finds it
        from safetensors.numpy import load_file, save_file

        raw = load_file(str(d / "model.safetensors"))
        eye = np.eye(64, dtype=np.float32)
        raw["multi_modal_projector.linear_1.weight"] = eye
        raw["multi_modal_projector.linear_1.bias"] = np.zeros(64, np.float32)
        raw["multi_modal_projector.linear_2.weight"] = eye
        raw["multi_modal_projector.linear_2.bias"] = np.zeros(64, np.float32)
        save_file(raw, str(d / "model.safetensors"))

        vcfg = vlm.VLMConfig(
            vision=vlm.ViTConfig(
                image_size=32, patch_size=8, dim=64, n_layers=2, n_heads=4,
                mlp_dim=128,
            ),
            llm_dim=64,
        )
        params = vlm.load_hf_vision_weights(d, vcfg)

        rng = np.random.RandomState(6)
        img = rng.rand(1, 32, 32, 3).astype(np.float32)
        with torch.no_grad():
            want = hf(
                pixel_values=torch.from_numpy(
                    img.transpose(0, 3, 1, 2)  # HF: NCHW
                )
            ).last_hidden_state.numpy()[:, 1:]  # drop the class token

        got = np.asarray(vlm.encode_image(params, img, vcfg), np.float32)
        # with identity projector weights our output is exactly
        # gelu(tower_states) (the projector's exact-GELU with W=I, b=0);
        # apply the same transform to the transformers reference
        from scipy.special import erf

        want_proj = 0.5 * want * (1.0 + erf(want / np.sqrt(2.0)))
        np.testing.assert_allclose(got, want_proj, atol=3e-4, rtol=3e-3)
