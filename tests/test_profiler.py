"""Hot-path profiler (observability/profiler.py, docs/observability.md):
fake-clock phase-attribution matrix, the zero-cost disabled gate (behavioral
AND AST-pinned, like the faults gate), compile-ledger schema + cache-hit
accounting, and the CLI/gateway surfaces."""

import ast
import json
import shutil
from pathlib import Path

import pytest

from modal_examples_tpu.observability import catalog as C
from modal_examples_tpu.observability import profiler as P
from modal_examples_tpu.utils.prometheus import Registry

PKG_ROOT = Path(__file__).resolve().parents[1] / "modal_examples_tpu"


class ManualClock:
    """Monotonic fake clock advanced explicitly between marks."""

    def __init__(self):
        self.t = 100.0

    def advance(self, dt: float) -> None:
        self.t += dt

    def __call__(self) -> float:
        return self.t


# ---------------------------------------------------------------------------
# tick anatomy: fake-clock attribution matrix
# ---------------------------------------------------------------------------


class TestTickAttribution:
    def test_each_phase_lands_in_its_own_series(self, tmp_path):
        """The attribution matrix: a tick marking every phase with a known
        delta puts EXACTLY that delta in that phase's ring slot and
        histogram series — no bleed, no double count — and the deltas sum
        to the tick total."""
        clk = ManualClock()
        reg = Registry()
        prof = P.HotPathProfiler(
            clock=clk, name="t-rep", registry=reg,
            ledger_path=tmp_path / "compiles.jsonl",
        )
        deltas = {
            phase: 0.001 * (i + 1) for i, phase in enumerate(C.TICK_PHASES)
        }
        tick = prof.begin_tick()
        for phase, dt in deltas.items():
            clk.advance(dt)
            tick.mark(phase, device=(phase == "harvest"))
        prof.end_tick(tick, worked=True)

        [entry] = prof.perfetto_snapshot()["ticks"]
        for phase, dt in deltas.items():
            assert entry["phases"][phase] == pytest.approx(dt), phase
            q = reg.histogram_quantiles(
                C.TICK_PHASE_SECONDS, labels={"phase": phase}
            )
            assert q is not None and q["count"] == 1, phase
            assert q["sum"] == pytest.approx(dt), phase
        assert entry["total"] == pytest.approx(sum(deltas.values()))
        assert entry["device"] == pytest.approx(deltas["harvest"])
        total_q = reg.histogram_quantiles(
            C.TICK_PHASE_SECONDS, labels={"phase": C.TICK_TOTAL_PHASE}
        )
        assert total_q["sum"] == pytest.approx(sum(deltas.values()))

        summary = prof.overhead_summary()
        assert summary["ticks"] == 1
        # summary fields are rounded to 6 decimals: compare with abs tol
        assert summary["attribution_cover"] == pytest.approx(1.0, abs=1e-5)
        assert summary["host_fraction"] == pytest.approx(
            1.0 - deltas["harvest"] / sum(deltas.values()), abs=1e-5
        )
        assert summary["detok_share"] == pytest.approx(
            deltas["detokenize"] / sum(deltas.values()), abs=1e-5
        )
        assert summary["tick_p95"] == pytest.approx(
            sum(deltas.values()), abs=1e-5
        )

    def test_idle_ticks_record_nothing(self, tmp_path):
        clk = ManualClock()
        reg = Registry()
        prof = P.HotPathProfiler(
            clock=clk, name="t-idle", registry=reg,
            ledger_path=tmp_path / "compiles.jsonl",
        )
        # worked=False: even a marked tick is discarded
        tick = prof.begin_tick()
        clk.advance(0.5)
        tick.mark("ctrl")
        prof.end_tick(tick, worked=False)
        # worked=True but nothing marked (no phases): also discarded
        prof.end_tick(prof.begin_tick(), worked=True)
        assert prof.perfetto_snapshot()["ticks"] == []
        assert prof.overhead_summary()["ticks"] == 0
        assert reg.histogram_quantiles(
            C.TICK_PHASE_SECONDS, labels={"phase": "ctrl"}
        ) is None

    def test_mark_partitions_are_cumulative(self):
        """Two marks of one phase in a tick accumulate (the _admit path
        marks prefill_resume twice)."""
        clk = ManualClock()
        prof = P.HotPathProfiler(clock=clk, registry=Registry())
        tick = prof.begin_tick()
        clk.advance(0.002)
        tick.mark("prefill_resume")
        clk.advance(0.003)
        tick.mark("prefill_resume")
        prof.end_tick(tick, worked=True)
        [entry] = prof.perfetto_snapshot()["ticks"]
        assert entry["phases"]["prefill_resume"] == pytest.approx(0.005)


# ---------------------------------------------------------------------------
# compile telemetry: ledger schema + cache-hit accounting
# ---------------------------------------------------------------------------


class TestCompileTelemetry:
    def test_ledger_schema_and_cache_hit_accounting(self, tmp_path):
        clk = ManualClock()
        reg = Registry()
        ledger = tmp_path / "compiles.jsonl"
        prof = P.HotPathProfiler(
            clock=clk, name="t-cc", registry=reg, ledger_path=ledger
        )
        # first dispatch: a miss — timed, ledgered (begin THEN end)
        t0 = prof.compile_begin("block", "s4k8")
        assert t0 is not None
        clk.advance(1.5)
        prof.compile_end("block", "s4k8", t0)
        # second dispatch of the same key: a hit — counted, not ledgered
        t1 = prof.compile_begin("block", "s4k8")
        assert t1 is None
        prof.compile_end("block", "s4k8", t1)

        rows = [json.loads(l) for l in ledger.read_text().splitlines()]
        assert [r["event"] for r in rows] == ["begin", "end"]
        begin, end = rows
        assert {"at", "event", "replica", "program", "shape_key"} <= set(
            begin
        )
        assert {"at", "event", "replica", "program", "shape_key", "seconds",
                "cache"} <= set(end)
        assert end["program"] == "block" and end["shape_key"] == "s4k8"
        assert end["seconds"] == pytest.approx(1.5)
        assert end["cache"] == "miss" and end["replica"] == "t-cc"

        assert reg.value(
            C.COMPILES_TOTAL, labels={"program": "block", "cache": "miss"}
        ) == 1.0
        assert reg.value(
            C.COMPILES_TOTAL, labels={"program": "block", "cache": "hit"}
        ) == 1.0
        q = reg.histogram_quantiles(
            C.COMPILE_SECONDS, labels={"program": "block"}
        )
        assert q["count"] == 1 and q["sum"] == pytest.approx(1.5)
        summary = prof.overhead_summary()
        assert summary["compiles_n"] == 1
        assert summary["compile_total_s"] == pytest.approx(1.5)

    def test_unfinished_builds_name_the_ceiling(self, tmp_path):
        """A begin event with no matching end — the process died or hung
        mid-build — is exactly what the ≥40-slot ceiling repro needs named
        offline."""
        clk = ManualClock()
        prof = P.HotPathProfiler(
            clock=clk, name="t-dead", registry=Registry(),
            ledger_path=tmp_path / "compiles.jsonl",
        )
        done = prof.compile_begin("prefill", "b256x4")
        prof.compile_end("prefill", "b256x4", done)
        prof.compile_begin("block", "s44k8")  # never ends: the crash
        rows = P.read_ledger(tmp_path / "compiles.jsonl")
        open_builds = P.unfinished_builds(rows)
        assert [(r["program"], r["shape_key"]) for r in open_builds] == [
            ("block", "s44k8")
        ]


# ---------------------------------------------------------------------------
# the real engine: end-to-end attribution + zero-cost disabled gate
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def profiled_engine(tmp_path_factory):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine, SamplingParams

    eng = LLMEngine(
        llama.LlamaConfig.tiny(),
        max_slots=4,
        max_model_len=128,
        prefill_buckets=(32, 64),
        profile=True,  # explicit arg beats env: no monkeypatching needed
    )
    eng.start()
    reqs = [
        eng.submit(
            "the quick brown fox " * 3,
            SamplingParams(max_tokens=10, temperature=0.0),
        )
        for _ in range(3)
    ]
    for r in reqs:
        "".join(eng.stream(r))
    eng.stop()
    return eng


class TestEngineIntegration:
    def test_phases_attributed_and_sum_to_tick(self, profiled_engine):
        """The CPU path-proof of the acceptance criterion: per-phase
        attribution is present for the whole serving anatomy and sums to
        ~the tick duration (sequential marks partition the tick, so cover
        can never exceed 1)."""
        summary = profiled_engine.profiler.overhead_summary()
        assert summary["ticks"] >= 1
        # a real decode run exercises the full non-spec anatomy
        for phase in (
            "ctrl", "policy", "admit", "prefill_dispatch",
            "decode_dispatch", "harvest", "detokenize", "accept",
        ):
            assert phase in summary["phases"], (phase, summary["phases"])
        assert 0.8 <= summary["attribution_cover"] <= 1.0 + 1e-6
        assert 0.0 <= summary["host_fraction"] <= 1.0
        assert 0.0 <= summary["detok_share"] <= 1.0
        assert summary["tick_p50"] <= summary["tick_p95"]

    def test_engine_compiles_are_ledgered(self, profiled_engine):
        """Nonzero compile ledger: the block program and at least one
        prefill bucket built through the chokepoint, and re-dispatches
        counted as cache hits."""
        summary = profiled_engine.profiler.overhead_summary()
        assert summary["compiles_n"] >= 2
        assert summary["compile_total_s"] > 0
        snap = profiled_engine.profiler.perfetto_snapshot()
        programs = {c["program"] for c in snap["compiles"]}
        assert {"block", "prefill"} <= programs
        rows = P.read_ledger()
        mine = [
            r for r in rows
            if r.get("replica") == profiled_engine.profiler.replica
        ]
        assert {"begin", "end"} <= {r["event"] for r in mine}
        assert not P.unfinished_builds(mine)

    def test_disabled_engine_has_no_profiler(self):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine

        eng = LLMEngine(
            llama.LlamaConfig.tiny(),
            max_slots=2,
            max_model_len=64,
            prefill_buckets=(32,),
            profile=False,
        )
        assert eng.profiler is None
        assert eng._tick is None

    def test_env_resolves_once_like_kv_dtype(self, monkeypatch):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine

        monkeypatch.setenv("MTPU_PROFILE", "1")
        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
            prefill_buckets=(32,),
        )
        assert eng.profiler is not None
        # explicit arg beats env
        monkeypatch.setenv("MTPU_PROFILE", "1")
        eng2 = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
            prefill_buckets=(32,), profile=False,
        )
        assert eng2.profiler is None


class TestDisabledGateShape:
    """The zero-cost contract pinned at the AST level, like
    test_static.test_disabled_fault_gate_is_structurally_a_no_op: with
    profiling off the hot path is a None-check — no timestamp, no
    allocation, no dict write."""

    def _engine_tree(self):
        return ast.parse((PKG_ROOT / "serving" / "engine.py").read_text())

    def _fn(self, tree, name):
        return next(
            n for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef) and n.name == name
        )

    @staticmethod
    def _body(fn):
        return [
            n for n in fn.body
            if not (
                isinstance(n, ast.Expr) and isinstance(n.value, ast.Constant)
            )
        ]

    def test_tm_helpers_are_one_branch(self):
        tree = self._engine_tree()
        for name in ("_tm", "_tm_device"):
            body = self._body(self._fn(tree, name))
            assert len(body) == 1, f"{name} must be ONE statement"
            guard = body[0]
            assert isinstance(guard, ast.If) and not guard.orelse
            test = guard.test
            assert (
                isinstance(test, ast.Compare)
                and isinstance(test.left, ast.Name)
                and test.left.id == "tick"
                and isinstance(test.ops[0], ast.IsNot)
                and test.comparators[0].value is None
            ), f"{name} must test `tick is not None` and nothing else"

    def test_profiled_opens_with_none_fast_path(self):
        body = self._body(self._fn(self._engine_tree(), "_profiled"))
        first, second = body[0], body[1]
        assert (
            isinstance(first, ast.Assign)
            and isinstance(first.value, ast.Attribute)
            and first.value.attr == "profiler"
        ), "_profiled must read self.profiler first"
        assert isinstance(second, ast.If)
        test = second.test
        assert (
            isinstance(test, ast.Compare)
            and isinstance(test.ops[0], ast.Is)
            and test.comparators[0].value is None
        ), "_profiled must test `prof is None` second"
        ret = second.body[0]
        assert (
            isinstance(ret, ast.Return)
            and isinstance(ret.value, ast.Name)
            and ret.value.id == "fn"
        ), "the disabled path must return fn UNWRAPPED (no closure alloc)"

    def test_step_creates_tick_conditionally(self):
        step = self._fn(self._engine_tree(), "step")
        ifexps = [
            n for n in ast.walk(step)
            if isinstance(n, ast.IfExp)
            and isinstance(n.test, ast.Compare)
            and isinstance(n.test.ops[0], ast.Is)
            and n.test.comparators[0].value is None
            and isinstance(n.body, ast.Constant)
            and n.body.value is None
        ]
        assert ifexps, (
            "step() must create the tick via `None if prof is None else "
            "prof.begin_tick()` — the disabled tick path takes no timestamp"
        )


# ---------------------------------------------------------------------------
# surfaces: CLI + gateway
# ---------------------------------------------------------------------------


class TestSurfaces:
    def test_cli_profile_renders_phase_table_and_ledger(
        self, profiled_engine, tmp_path, capsys
    ):
        from modal_examples_tpu._internal import config as _config
        from modal_examples_tpu.core.cli import main as cli_main
        from modal_examples_tpu.observability.export import push_metrics_file

        root = tmp_path / "state"
        (root / "metrics").mkdir(parents=True)
        push_metrics_file("bench-profiled", root=root / "metrics")
        shutil.copy(
            _config.state_dir() / P.LEDGER_NAME, root / P.LEDGER_NAME
        )
        assert cli_main(["profile", "--dir", str(root)]) == 0
        out = capsys.readouterr().out
        for phase in ("decode_dispatch", "harvest", "detokenize", "total"):
            assert phase in out, out
        assert "top compiles" in out
        assert "block" in out

        assert cli_main(["profile", "--json", "--dir", str(root)]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["compiles_n"] >= 2
        assert payload["phases"]["total"]["count"] >= 1
        assert payload["unfinished_builds"] == []

    def test_cli_profile_empty_state_says_so(self, tmp_path, capsys):
        from modal_examples_tpu.core.cli import main as cli_main

        root = tmp_path / "empty"
        (root / "metrics").mkdir(parents=True)
        assert cli_main(["profile", "--dir", str(root)]) == 0
        assert "no tick-phase series" in capsys.readouterr().out

    def test_gateway_profile_snapshot(self, profiled_engine):
        from modal_examples_tpu.web.gateway import _profile_snapshot

        snap = _profile_snapshot()
        name = profiled_engine.profiler.replica
        assert name in snap["replicas"]
        node = snap["replicas"][name]
        assert node["summary"]["ticks"] >= 1
        assert node["perfetto"]["ticks"]
        assert {"at", "total", "device", "phases"} <= set(
            node["perfetto"]["ticks"][0]
        )
        assert isinstance(snap["ledger"], list)
        assert snap["unfinished_builds"] == []
