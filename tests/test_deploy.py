"""Deployment registry: apps deployed from one process are resolvable and
invocable from a DIFFERENT process via App.lookup / Function.from_name."""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path


def test_deploy_then_lookup_cross_process(tmp_path, state_dir):
    app_file = tmp_path / "deployable_app.py"
    app_file.write_text(
        textwrap.dedent(
            """
            import modal_examples_tpu as mtpu

            app = mtpu.App("deployed-cross-process")

            @app.function(timeout=60)
            def triple(x: int) -> int:
                return x * 3
            """
        )
    )
    env = {
        **os.environ,
        "MTPU_STATE_DIR": str(state_dir),
        "PYTHONPATH": str(Path(__file__).resolve().parents[1]),
    }
    # process 1: deploy
    out = subprocess.run(
        [sys.executable, "-m", "modal_examples_tpu", "deploy", "--no-scheduler",
         str(app_file)],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr
    registry = json.loads((state_dir / "apps.json").read_text())
    assert "deployed-cross-process" in registry

    # process 2: lookup + invoke (imports the module from the registry path)
    code = textwrap.dedent(
        """
        import modal_examples_tpu as mtpu

        f = mtpu.Function.from_name("deployed-cross-process", "triple")
        print("RESULT", f.remote(14))
        """
    )
    out2 = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert out2.returncode == 0, out2.stderr
    assert "RESULT 42" in out2.stdout
