"""Scheduling subsystem tests: policies (priority + tenant fair share),
admission control (bounds, KV pressure, shedding, deadlines — fake clock,
fully deterministic), the engine integration, the OpenAI 429 surface, and
prefix-affinity multi-replica routing."""

import json
import urllib.error
import urllib.request

import pytest

from modal_examples_tpu.scheduling import (
    AdmissionConfig,
    AdmissionController,
    FairSharePolicy,
    FIFOPolicy,
    ScheduledRequest,
    ShedError,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _entry(payload=None, priority="default", tenant="default", cost=1,
           deadline=None):
    return ScheduledRequest(
        payload=payload, priority=priority, tenant=tenant, cost=cost,
        deadline=deadline,
    )


class TestFairSharePolicy:
    def test_strict_class_priority(self):
        p = FairSharePolicy(clock=FakeClock())
        for i in range(3):
            p.submit(_entry(payload=f"b{i}", priority="batch"))
        p.submit(_entry(payload="d0", priority="default"))
        p.submit(_entry(payload="i0", priority="interactive"))
        out = [e.payload for e in p.next_batch(3)]
        # interactive first, then default, then batch fills the rest
        assert out == ["i0", "d0", "b0"]
        assert [e.payload for e in p.next_batch(10)] == ["b1", "b2"]

    def test_tenant_fair_share_interleaves_a_flood(self):
        p = FairSharePolicy(clock=FakeClock(), quantum=1)
        for i in range(8):
            p.submit(_entry(payload=f"flood{i}", tenant="flooder"))
        p.submit(_entry(payload="t0", tenant="trickle"))
        p.submit(_entry(payload="t1", tenant="trickle"))
        out = [e.payload for e in p.next_batch(4)]
        # DRR with equal weights: the trickle tenant is served alongside the
        # flood, not behind all 8 of its requests
        assert "t0" in out and "t1" in out, out

    def test_tenant_weights_skew_service(self):
        p = FairSharePolicy(
            clock=FakeClock(), quantum=1, tenant_weights={"heavy": 3.0}
        )
        for i in range(6):
            p.submit(_entry(payload=("heavy", i), tenant="heavy"))
            p.submit(_entry(payload=("light", i), tenant="light"))
        out = p.next_batch(8)
        heavy = sum(1 for e in out if e.payload[0] == "heavy")
        assert heavy > 8 - heavy  # weighted tenant gets the larger share

    def test_requeue_goes_back_to_the_front_in_order(self):
        p = FairSharePolicy(clock=FakeClock())
        for name in ("a", "b", "c"):
            p.submit(_entry(payload=name))
        batch = p.next_batch(2)
        assert [e.payload for e in batch] == ["a", "b"]
        p.requeue(batch)
        assert [e.payload for e in p.next_batch(3)] == ["a", "b", "c"]

    def test_expired_removes_past_deadline_entries(self):
        clock = FakeClock()
        p = FairSharePolicy(clock=clock)
        p.submit(_entry(payload="no-deadline"))
        p.submit(_entry(payload="soon", deadline=1.0))
        p.submit(_entry(payload="later", deadline=10.0))
        assert p.expired() == []
        clock.advance(5.0)
        dead = [e.payload for e in p.expired()]
        assert dead == ["soon"]
        assert p.total_depth() == 2

    def test_remove_queued_entry(self):
        p = FairSharePolicy(clock=FakeClock())
        e = _entry(payload="x", priority="interactive", tenant="t")
        p.submit(e)
        assert p.depths()["interactive"] == 1
        assert p.remove(e) is True
        assert p.remove(e) is False  # already gone
        assert p.total_depth() == 0


class TestFIFOPolicy:
    def test_fifo_ignores_class_for_ordering(self):
        p = FIFOPolicy(clock=FakeClock())
        p.submit(_entry(payload="b", priority="batch"))
        p.submit(_entry(payload="i", priority="interactive"))
        assert [e.payload for e in p.next_batch(2)] == ["b", "i"]

    def test_depths_and_expiry(self):
        clock = FakeClock()
        p = FIFOPolicy(clock=clock)
        p.submit(_entry(payload="x", priority="batch", deadline=1.0))
        assert p.depths()["batch"] == 1
        clock.advance(2.0)
        assert [e.payload for e in p.expired()] == ["x"]


class TestAdmission:
    def _ctl(self, **cfg_kw):
        return AdmissionController(AdmissionConfig(**cfg_kw), clock=FakeClock())

    def test_queue_full_sheds_with_retry_after(self):
        ctl = self._ctl(max_queue={"interactive": 8, "default": 2, "batch": 8})
        with pytest.raises(ShedError) as exc:
            ctl.admit(
                _entry(), depths={"default": 2}, pages_used=0, pages_total=64
            )
        assert exc.value.reason == "queue_full"
        assert exc.value.retry_after_s >= 1.0
        assert ctl.sheds == 1 and ctl.admitted == 0

    def test_too_large_sheds(self):
        ctl = self._ctl()
        with pytest.raises(ShedError) as exc:
            ctl.admit(
                _entry(cost=100), depths={}, pages_used=0, pages_total=64
            )
        assert exc.value.reason == "too_large"

    def test_kv_pressure_sheds_batch_before_interactive(self):
        ctl = self._ctl(kv_ceiling={"batch": 0.5, "default": 0.8})
        # occupancy 40/64 = 0.625: batch (ceiling .5) sheds, default (.8)
        # and interactive (no ceiling) admit
        with pytest.raises(ShedError) as exc:
            ctl.admit(
                _entry(priority="batch"), depths={},
                pages_used=40, pages_total=64,
            )
        assert exc.value.reason == "kv_pressure"
        ctl.admit(_entry(), depths={}, pages_used=40, pages_total=64)
        ctl.admit(
            _entry(priority="interactive"), depths={},
            pages_used=40, pages_total=64,
        )
        assert ctl.admitted == 2

    def test_reservations_count_toward_pressure(self):
        ctl = self._ctl(kv_ceiling={"batch": 0.5})
        e1 = _entry(priority="batch", cost=20)
        ctl.admit(e1, depths={}, pages_used=0, pages_total=64)
        assert ctl.reserved_pages == 20
        # 20 reserved + 20 more = 0.625 > 0.5 -> shed
        with pytest.raises(ShedError):
            ctl.admit(
                _entry(priority="batch", cost=20), depths={},
                pages_used=0, pages_total=64,
            )
        ctl.release(e1)
        assert ctl.reserved_pages == 0
        ctl.admit(
            _entry(priority="batch", cost=20), depths={},
            pages_used=0, pages_total=64,
        )

    def test_shed_metrics_recorded(self):
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.utils.prometheus import default_registry

        before = default_registry.value(
            C.SHEDS_TOTAL, {"class": "batch", "reason": "queue_full"}
        )
        ctl = self._ctl(max_queue={"interactive": 1, "default": 1, "batch": 0})
        with pytest.raises(ShedError):
            ctl.admit(
                _entry(priority="batch"), depths={}, pages_used=0,
                pages_total=8,
            )
        after = default_registry.value(
            C.SHEDS_TOTAL, {"class": "batch", "reason": "queue_full"}
        )
        assert after == before + 1
        assert ctl.shed_rate() == 1.0


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


def _tiny_engine(jax, seed=0, **kw):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    return LLMEngine(
        llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
        page_size=16, prefill_buckets=(32,), seed=seed, **kw,
    )


class TestEngineScheduling:
    def test_queued_deadline_expires_with_fake_clock(self, jax):
        """Fully deterministic: the engine's scheduler thread never runs —
        the test drives step() by hand against a fake clock."""
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.utils.prometheus import default_registry

        clock = FakeClock()
        eng = _tiny_engine(jax, seed=5, clock=clock)
        try:
            # fill both slots so the deadline-armed request stays queued
            hogs = [
                eng.submit("hog", SamplingParams(max_tokens=32))
                for _ in range(2)
            ]
            doomed = eng.submit(
                "doomed", SamplingParams(max_tokens=4, deadline_s=1.0)
            )
            misses_before = default_registry.value(
                C.DEADLINE_MISSES_TOTAL, {"stage": "queued"}
            )
            eng.step()  # hogs take the slots; doomed stays queued
            assert eng.policy.total_depth() == 1
            clock.advance(2.0)  # past the deadline
            eng.step()
            assert eng.policy.total_depth() == 0
            assert eng.admission.reserved_pages == 0
            # the caller's stream terminates with the deadline reason
            item = doomed.out_queue.get(timeout=1)
            assert getattr(item, "reason", None) == "deadline"
            assert default_registry.value(
                C.DEADLINE_MISSES_TOTAL, {"stage": "queued"}
            ) == misses_before + 1
            for r in hogs:
                eng.abort(r)
        finally:
            eng.stop()

    def test_interactive_admitted_before_queued_batch(self, jax):
        from modal_examples_tpu.serving import SamplingParams

        eng = _tiny_engine(jax, seed=6)
        try:
            batch = [
                eng.submit(
                    f"bulk {i}", SamplingParams(max_tokens=8),
                    priority="batch",
                )
                for i in range(4)
            ]
            chat = eng.submit(
                "chat", SamplingParams(max_tokens=2), priority="interactive"
            )
            eng.step()  # one admission pass, 2 slots
            admitted = {
                s.request.request_id for s in eng.slots if not s.free
            }
            assert chat.request_id in admitted, (
                "interactive request must take a slot before queued batch work"
            )
            for r in batch:
                eng.abort(r)
            eng.abort(chat)
        finally:
            eng.stop()

    def test_inflight_deadline_aborts_decode(self, jax):
        from modal_examples_tpu.serving import SamplingParams

        clock = FakeClock()
        eng = _tiny_engine(jax, seed=7, clock=clock)
        try:
            req = eng.submit(
                "never ends",
                SamplingParams(max_tokens=10_000, deadline_s=5.0),
            )
            eng.step()  # admitted into a slot
            assert any(not s.free for s in eng.slots)
            clock.advance(10.0)
            for _ in range(4):  # expire + reap happen on later ticks
                eng.step()
                if all(s.free for s in eng.slots):
                    break
            assert all(s.free for s in eng.slots)
            item = req.out_queue.get(timeout=1)
            while not hasattr(item, "reason"):
                item = req.out_queue.get(timeout=1)  # drain partial text
            assert item.reason == "deadline"
        finally:
            eng.stop()


class TestOverloadSheds429:
    """The acceptance scenario: under a synthetic overload (queue bound
    exceeded) the OpenAI endpoint answers 429 + Retry-After and
    mtpu_sheds_total increments, while admitted interactive requests
    complete within their deadline."""

    @pytest.fixture(scope="class")
    def server(self, jax):
        from modal_examples_tpu.serving import OpenAIServer

        eng = _tiny_engine(
            jax, seed=8,
            admission=AdmissionController(
                # batch is always over its (zero) bound -> deterministic
                # queue_full shedding; interactive/default admit freely
                AdmissionConfig(
                    max_queue={"interactive": 64, "default": 64, "batch": 0}
                )
            ),
        )
        srv = OpenAIServer(eng, model_name="sched-test", host="127.0.0.1", port=0)
        srv.start()
        yield srv
        srv.stop()

    def _post(self, server, body, headers=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/chat/completions",
            data=json.dumps(body).encode(),
            headers={"content-type": "application/json", **(headers or {})},
        )
        return urllib.request.urlopen(req)

    def test_overload_returns_429_with_retry_after(self, server):
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.utils.prometheus import default_registry

        sheds_before = default_registry.value(
            C.SHEDS_TOTAL, {"class": "batch", "reason": "queue_full"}
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            self._post(
                server,
                {"messages": [{"role": "user", "content": "bulk"}],
                 "max_tokens": 4},
                headers={"x-mtpu-priority": "batch"},
            )
        err = exc.value
        assert err.code == 429
        assert int(err.headers["retry-after"]) >= 1
        payload = json.loads(err.read())
        assert payload["error"]["code"] == "queue_full"
        assert default_registry.value(
            C.SHEDS_TOTAL, {"class": "batch", "reason": "queue_full"}
        ) == sheds_before + 1

    def test_admitted_interactive_completes_within_deadline(self, server):
        with self._post(
            server,
            {"messages": [{"role": "user", "content": "hi"}],
             "max_tokens": 4, "temperature": 0.0},
            headers={
                "x-mtpu-priority": "interactive",
                "x-mtpu-deadline-ms": "30000",
            },
        ) as r:
            out = json.load(r)
        # completed (stop/length), NOT cancelled by its deadline
        assert out["choices"][0]["finish_reason"] in ("stop", "length")

    def test_bad_priority_class_is_a_400(self, server):
        with pytest.raises(urllib.error.HTTPError) as exc:
            self._post(
                server,
                {"messages": [{"role": "user", "content": "x"}],
                 "max_tokens": 2},
                headers={"x-mtpu-priority": "urgent"},
            )
        assert exc.value.code == 400


class _FakeReplica:
    """Minimal replica protocol for deterministic router unit tests."""

    def __init__(self, name, outstanding=0, capacity=4, healthy=True):
        self.name = name
        self._outstanding = outstanding
        self._capacity = capacity
        self._healthy = healthy
        self.submitted = []

    def encode(self, prompt):
        return list(prompt.encode())

    def submit(self, prompt, params=None, image=None, **kw):
        self.submitted.append(prompt)

        class _Req:
            request_id = f"req-{self.name}-{len(self.submitted)}"

        return _Req()

    def outstanding(self):
        return self._outstanding + len(self.submitted)

    def capacity(self):
        return self._capacity

    def healthy(self):
        return self._healthy

    def saturated(self):
        return self.outstanding() >= 2 * self._capacity


class TestRouterUnit:
    def test_same_prefix_routes_to_same_replica(self):
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.scheduling import PrefixAffinityRouter
        from modal_examples_tpu.utils.prometheus import default_registry

        a, b = _FakeReplica("a"), _FakeReplica("b")
        router = PrefixAffinityRouter([a, b], prefix_tokens=8)
        hits_before = default_registry.value(C.ROUTER_AFFINITY_HITS_TOTAL)
        shared = "SYSTEM PROMPT: be nice. user says hello"
        first = router.route(shared)
        for _ in range(3):
            assert router.route(shared) is first
        assert router.affinity_hits >= 3
        assert default_registry.value(
            C.ROUTER_AFFINITY_HITS_TOTAL
        ) >= hits_before + 3

    def test_saturated_replica_diverts_to_least_loaded(self):
        from modal_examples_tpu.scheduling import PrefixAffinityRouter

        a, b = _FakeReplica("a"), _FakeReplica("b")
        router = PrefixAffinityRouter([a, b], prefix_tokens=8)
        prompt = "the shared prefix of a very hot conversation"
        preferred = router.route(prompt)
        other = b if preferred is a else a
        preferred._outstanding = 10 * preferred.capacity()  # saturate it
        assert router.route(prompt) is other
        assert router.fallbacks >= 1

    def test_unhealthy_replica_is_skipped(self):
        from modal_examples_tpu.scheduling import PrefixAffinityRouter

        a, b = _FakeReplica("a"), _FakeReplica("b")
        router = PrefixAffinityRouter([a, b], prefix_tokens=8)
        prompt = "route me somewhere alive"
        preferred = router.route(prompt)
        other = b if preferred is a else a
        preferred._healthy = False
        assert router.route(prompt) is other
        preferred._healthy = True
        other._healthy = False
        a._healthy = False
        with pytest.raises(RuntimeError, match="no healthy replicas"):
            router.route(prompt)

    def test_flap_evict_readmit_cycle(self):
        """Regression (round 8): unhealthy used to be a one-way door — a
        replica filtered out of route() never returned. The fake-clock
        cycle: flap -> evicted -> down (cheap healthy() recheck only, no
        expensive probe before reprobe_s) -> healthy() flips back true ->
        re-admitted IMMEDIATELY, counted in
        mtpu_router_readmissions_total."""
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.scheduling import PrefixAffinityRouter
        from modal_examples_tpu.utils.prometheus import default_registry

        clock = FakeClock()
        a, b = _FakeReplica("a"), _FakeReplica("b")
        router = PrefixAffinityRouter(
            [a, b], prefix_tokens=8, reprobe_s=5.0, clock=clock
        )
        prompt = "the flapping conversation"
        preferred = router.route(prompt)
        other = b if preferred is a else a
        readmit_before = default_registry.value(C.ROUTER_READMISSIONS_TOTAL)

        # flap: one unhealthy observation evicts the replica
        preferred._healthy = False
        assert router.route(prompt) is other
        assert router.stats()["replicas"][preferred.name]["down"]

        # while down and still unhealthy: only the cheap health recheck
        # runs — the expensive probe() waits for reprobe_s
        probed = {"n": 0}

        def probe():
            probed["n"] += 1
            return preferred._healthy

        preferred.probe = probe
        clock.advance(1.0)  # before probe time
        assert router.route(prompt) is other
        assert probed["n"] == 0, "probe() must wait for reprobe_s"

        # healthy() flips back true -> immediate re-admission, NO probe
        # wait (the docs/scheduling.md contract), affinity restored
        preferred._healthy = True
        assert router.route(prompt) is preferred
        assert probed["n"] == 0
        assert not router.stats()["replicas"][preferred.name]["down"]
        assert router.readmissions >= 1
        assert default_registry.value(
            C.ROUTER_READMISSIONS_TOTAL
        ) >= (readmit_before or 0) + 1

    def test_failed_probe_pushes_next_probe_out(self):
        from modal_examples_tpu.scheduling import PrefixAffinityRouter

        clock = FakeClock()
        a, b = _FakeReplica("a"), _FakeReplica("b")
        router = PrefixAffinityRouter(
            [a, b], prefix_tokens=8, reprobe_s=5.0, clock=clock
        )
        prompt = "still down after the probe"
        preferred = router.route(prompt)
        other = b if preferred is a else a
        probed = {"n": 0}

        def probe():
            probed["n"] += 1
            return preferred._healthy  # probe can't heal this one

        preferred.probe = probe
        preferred._healthy = False
        assert router.route(prompt) is other  # evicted
        clock.advance(6.0)
        assert router.route(prompt) is other  # probe ran, still unhealthy
        assert probed["n"] == 1
        clock.advance(1.0)
        assert router.route(prompt) is other  # next probe 5s out again
        assert probed["n"] == 1
        clock.advance(6.0)
        assert router.route(prompt) is other  # second probe, still down
        assert probed["n"] == 2

    def test_probe_method_preferred_over_healthy(self):
        """A replica exposing probe() (EngineReplica revives its engine
        there) is probed through it, not bare healthy()."""
        from modal_examples_tpu.scheduling import PrefixAffinityRouter

        clock = FakeClock()
        a, b = _FakeReplica("a"), _FakeReplica("b")
        probed = {"n": 0}

        def probe():
            probed["n"] += 1
            a._healthy = True  # the probe HEALS (revive + restart)
            return True

        a.probe = probe
        b.probe = lambda: b._healthy
        router = PrefixAffinityRouter(
            [a, b], prefix_tokens=8, reprobe_s=5.0, clock=clock
        )
        a._healthy = False
        router.route("x")  # evicts a
        clock.advance(6.0)
        router.route("x")
        assert probed["n"] == 1 and a._healthy
        assert not router.stats()["replicas"]["a"]["down"]


class TestRouterWithEngines:
    def test_two_replica_affinity_and_divert(self, jax):
        """Acceptance: repeated shared-prefix prompts hit the same replica
        (mtpu_router_affinity_hits_total > 0); a saturated replica diverts
        new prompts to the other."""
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.utils.prometheus import default_registry

        e1 = _tiny_engine(jax, seed=11)
        e2 = _tiny_engine(jax, seed=12)
        r1 = EngineReplica(e1, "replica-1", saturation_factor=2.0)
        r2 = EngineReplica(e2, "replica-2", saturation_factor=2.0)
        router = PrefixAffinityRouter([r1, r2], prefix_tokens=16)
        try:
            hits_before = default_registry.value(C.ROUTER_AFFINITY_HITS_TOTAL)
            shared = "You are a helpful assistant. Answer briefly: hello"
            reqs = [
                router.submit(shared, SamplingParams(max_tokens=2))
                for _ in range(3)
            ]
            owners = {router.replica_for(r).name for r in reqs}
            assert len(owners) == 1, f"shared prefix split across {owners}"
            assert router.affinity_hits >= 2
            assert default_registry.value(
                C.ROUTER_AFFINITY_HITS_TOTAL
            ) > hits_before
            for req in reqs:
                text = "".join(router.stream(req))
                assert isinstance(text, str)

            # saturate the affinity owner (without running it): queue more
            # outstanding work than saturation_factor x slots allows
            owner = r1 if "replica-1" in owners else r2
            other = r2 if owner is r1 else r1
            owner.engine.stop()  # hold its queue still
            hold = [
                owner.engine.submit("hold", SamplingParams(max_tokens=2))
                for _ in range(2 * owner.capacity())
            ]
            assert owner.saturated()
            diverted = router.route(shared)
            assert diverted is other, "saturated replica must divert"
            for h in hold:
                owner.engine.abort(h)
        finally:
            try:
                e1.stop()
            finally:
                e2.stop()
