"""Unit tests for utils/compile_cache.py — the main cold-start lever
(round-2 measurement: 41.5 s build + 62.6 s compile per engine boot without
it). Covers the host-CPU cache segmentation (``_machine_tag``), the
``MTPU_COMPILE_CACHE=0`` opt-out, custom-path override, and the
respect-user-config rule of ``enable_compile_cache``."""

import re

import pytest

from modal_examples_tpu.utils import compile_cache


class TestMachineTag:
    def test_format(self):
        tag = compile_cache._machine_tag()
        assert re.fullmatch(r"[0-9a-f]{8}", tag), tag

    def test_stable_within_process(self):
        assert compile_cache._machine_tag() == compile_cache._machine_tag()

    def test_tracks_cpu_features(self, monkeypatch, tmp_path):
        """Different /proc/cpuinfo feature sets must segment to different
        tags — XLA:CPU AOT entries bake in the compile machine's features
        (foreign entries SIGILL)."""

        def tag_for(cpuinfo: str) -> str:
            path = tmp_path / "cpuinfo"
            path.write_text(cpuinfo)
            real_open = open
            monkeypatch.setattr(
                "builtins.open",
                lambda f, *a, **k: real_open(
                    path if f == "/proc/cpuinfo" else f, *a, **k
                ),
            )
            compile_cache._machine_tag.cache_clear()
            try:
                return compile_cache._machine_tag()
            finally:
                monkeypatch.undo()
                compile_cache._machine_tag.cache_clear()

        avx = tag_for("model name\t: X 9999\nflags\t\t: fpu avx avx2\n")
        sse = tag_for("model name\t: X 9999\nflags\t\t: fpu sse sse2\n")
        arm = tag_for("CPU part\t: 0xd40\nFeatures\t: fp asimd sve\n")
        assert len({avx, sse, arm}) == 3

    def test_survives_missing_cpuinfo(self, monkeypatch):
        real_open = open

        def deny(f, *a, **k):
            if f == "/proc/cpuinfo":
                raise OSError("no cpuinfo")
            return real_open(f, *a, **k)

        monkeypatch.setattr("builtins.open", deny)
        compile_cache._machine_tag.cache_clear()
        try:
            assert re.fullmatch(r"[0-9a-f]{8}", compile_cache._machine_tag())
        finally:
            monkeypatch.undo()
            compile_cache._machine_tag.cache_clear()


class TestCacheDir:
    @pytest.mark.parametrize("value", ["0", "off", "none", "OFF", "None"])
    def test_opt_out(self, monkeypatch, value):
        monkeypatch.setenv("MTPU_COMPILE_CACHE", value)
        assert compile_cache.cache_dir() is None

    def test_custom_path_override(self, monkeypatch, tmp_path):
        monkeypatch.setenv("MTPU_COMPILE_CACHE", str(tmp_path / "xla"))
        assert compile_cache.cache_dir() == str(tmp_path / "xla")

    def test_default_is_machine_segmented(self, monkeypatch):
        monkeypatch.delenv("MTPU_COMPILE_CACHE", raising=False)
        d = compile_cache.cache_dir()
        assert d is not None
        assert d.endswith(f"xla-cache-{compile_cache._machine_tag()}")


class TestEnableCompileCache:
    @pytest.fixture()
    def restore_jax_config(self):
        import jax

        prev = getattr(jax.config, "jax_compilation_cache_dir", None)
        yield jax
        jax.config.update("jax_compilation_cache_dir", prev)

    def test_disabled_returns_none(self, monkeypatch, restore_jax_config):
        monkeypatch.setenv("MTPU_COMPILE_CACHE", "0")
        assert compile_cache.enable_compile_cache() is None

    def test_explicit_path_wins(self, monkeypatch, tmp_path, restore_jax_config):
        monkeypatch.delenv("MTPU_COMPILE_CACHE", raising=False)
        jax = restore_jax_config
        path = str(tmp_path / "explicit")
        assert compile_cache.enable_compile_cache(path) == path
        assert jax.config.jax_compilation_cache_dir == path
        assert (tmp_path / "explicit").is_dir()

    def test_respects_user_configured_dir(
        self, monkeypatch, tmp_path, restore_jax_config
    ):
        """A dir the user already set via jax.config is never overridden by
        the built-in default (ADVICE r3) — only explicit path/env wins."""
        monkeypatch.delenv("MTPU_COMPILE_CACHE", raising=False)
        jax = restore_jax_config
        user_dir = str(tmp_path / "user-dir")
        jax.config.update("jax_compilation_cache_dir", user_dir)
        assert compile_cache.enable_compile_cache() == user_dir
        assert jax.config.jax_compilation_cache_dir == user_dir

    def test_env_override_beats_user_config(
        self, monkeypatch, tmp_path, restore_jax_config
    ):
        jax = restore_jax_config
        jax.config.update("jax_compilation_cache_dir", str(tmp_path / "user"))
        env_dir = str(tmp_path / "from-env")
        monkeypatch.setenv("MTPU_COMPILE_CACHE", env_dir)
        assert compile_cache.enable_compile_cache() == env_dir
        assert jax.config.jax_compilation_cache_dir == env_dir
