"""Fleet-wide shared prefix store (docs/prefix_store.md).

Unit layers (no jax: blocks are numpy-built MTKV1 envelopes): content-
addressed dedup, torn/corrupt handling, legacy-layout adoption, rendezvous
ownership + lease takeover, bounded refcounted GC, and the satellite-3
concurrent-writer contract (two replicas spill the same chain, ONE copy
survives, promotes bit-identical for bf16 and int8's 4-leaf form).

E2E layer (tiny engines): a cold replica serves another replica's spilled
corpus token-identically (greedy AND seeded, bf16 and int8), and
``SnapshotWarmFactory`` scale-outs register with the store and boot with a
non-zero store hit rate.
"""

import json
import os

import numpy as np
import pytest

from modal_examples_tpu.serving.disagg.transport import (
    PageBlock,
    chain_hashes,
    deserialize_block,
    serialize_block,
)
from modal_examples_tpu.serving.prefix_store import SharedPrefixStore
from modal_examples_tpu.serving.prefix_store.ownership import (
    LeaseBoard,
    rendezvous_owner,
)
from modal_examples_tpu.serving.prefix_store.store import block_file
from modal_examples_tpu.storage.volume import Volume


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


# -- fixtures: numpy MTKV1 blocks (what a replica's spill serializes) --------


def _np_block(seed: int, kv_dtype: str = "bf16") -> PageBlock:
    """One page's worth of leaves. ``int8`` uses the quantized cache's
    4-leaf form (k/v int8 + per-row scales) — the codec must carry all
    four bit-exactly."""
    rng = np.random.default_rng(seed)
    if kv_dtype == "int8":
        leaves = {
            "k_pages": rng.integers(-128, 127, (2, 1, 8, 2, 4), np.int8),
            "v_pages": rng.integers(-128, 127, (2, 1, 8, 2, 4), np.int8),
            "k_scale": rng.random((2, 1, 8, 2), np.float32),
            "v_scale": rng.random((2, 1, 8, 2), np.float32),
        }
    else:
        leaves = {
            "k_pages": rng.random((2, 1, 8, 2, 4), np.float32),
            "v_pages": rng.random((2, 1, 8, 2, 4), np.float32),
        }
    return PageBlock(leaves=leaves, page_size=8, kv_dtype=kv_dtype)


def _chain(n_pages: int, page_size: int = 8, salt: int = 0) -> list:
    tokens = [(salt * 7 + i) % 251 for i in range(n_pages * page_size)]
    return chain_hashes(tokens, page_size)


@pytest.fixture()
def vol():
    with Volume.ephemeral() as v:
        yield v


class TestStoreCore:
    def test_put_get_roundtrip_and_self_origin(self, vol):
        s = SharedPrefixStore(vol, replica="a", shared=False)
        data = serialize_block(_np_block(0))
        assert s.put("h0", data) == "written"
        assert s.get("h0") == data
        assert s.hits == {"self": 1, "peer": 0}
        # the read deserializes clean: crc-checked leaves, same arrays
        block = deserialize_block(data)
        np.testing.assert_array_equal(
            block.leaves["k_pages"], _np_block(0).leaves["k_pages"]
        )

    def test_second_put_dedups(self, vol):
        s = SharedPrefixStore(vol, replica="a", shared=False)
        data = serialize_block(_np_block(1))
        assert s.put("h1", data) == "written"
        assert s.put("h1", data) == "dedup"
        assert s.writes == 1 and s.dedup_skips == 1
        assert s.dedup_ratio() == 2.0

    def test_peer_origin_and_cross_instance_dedup(self, vol):
        a = SharedPrefixStore(vol, replica="a", shared=False)
        b = SharedPrefixStore(vol, replica="b", shared=False)
        data = serialize_block(_np_block(2))
        assert a.put("h2", data) == "written"
        # b never wrote it, but the fleet has it: dedup + peer-origin hit
        assert b.put("h2", data) == "dedup"
        assert b.get("h2") == data
        assert b.hits == {"self": 0, "peer": 1}
        assert a.get("h2") is not None
        assert a.hits["self"] == 1

    def test_torn_block_dropped_not_served(self, vol):
        s = SharedPrefixStore(vol, replica="a", shared=False)
        data = serialize_block(_np_block(3))
        s.put("h3", data)
        # tear the stored file (a non-atomic writer's crash artifact)
        path = vol.local_path / s.root / block_file("h3")
        path.write_bytes(data[: len(data) // 2])
        assert s.get("h3") is None
        assert s.misses == 1 and s.invalidated == 1
        assert not path.exists(), "torn block must be removed, not retried"

    def test_corrupt_on_disk_dropped_inflight_kept(self, vol):
        s = SharedPrefixStore(vol, replica="a", shared=False)
        data = serialize_block(_np_block(4))
        s.put("h4", data)
        # intact stored bytes: drop_if_corrupt must NOT throw them away
        assert s.drop_if_corrupt("h4") is False
        assert s.get("h4") == data
        # rot the payload on disk (structurally sound, crc fails)
        path = vol.local_path / s.root / block_file("h4")
        raw = bytearray(data)
        raw[-1] ^= 0xFF
        path.write_bytes(bytes(raw))
        assert s.drop_if_corrupt("h4") is True
        assert not path.exists()

    def test_legacy_flat_layout_adopted_read_only(self, vol):
        # a pre-store private tier left flat <root>/block-<h>.kv files
        data = serialize_block(_np_block(5))
        vol.write_file("kv-tier/block-legacy0.kv", data)
        s = SharedPrefixStore(vol, replica="a", root="kv-tier", shared=False)
        assert s.exists("legacy0")
        assert s.get("legacy0") == data
        # new writes land in the content-addressed layout, never flat
        s.put("h5", serialize_block(_np_block(6)))
        assert (vol.local_path / "kv-tier" / block_file("h5")).exists()

    def test_peer_invalidation_is_observed(self, vol):
        """A peer's invalidate (torn/corrupt drop) must not leave stale
        presence in another replica's index — a stale dedup-skip would
        mean the block is never respilled fleet-wide."""
        a = SharedPrefixStore(vol, replica="a", shared=False)
        b = SharedPrefixStore(vol, replica="b", shared=False)
        data = serialize_block(_np_block(7))
        a.put("h7", data)
        assert b.exists("h7")
        a.invalidate("h7")
        assert not b.exists("h7")
        assert b.put("h7", data) == "written", (
            "the respill must write, not dedup against a ghost"
        )

    def test_atomic_writes_leave_no_temp_files(self, vol):
        s = SharedPrefixStore(vol, replica="a", shared=False)
        for i in range(4):
            s.put(f"h8-{i}", serialize_block(_np_block(10 + i)))
        blocks_dir = vol.local_path / s.root / "blocks"
        stray = [p for p in blocks_dir.iterdir() if p.name.startswith(".")]
        assert stray == [], f"dot-temp files survived the rename: {stray}"


class TestOwnership:
    def test_rendezvous_owner_is_deterministic_and_spreads(self, vol):
        names = ["rep-a", "rep-b", "rep-c"]
        chains = [_chain(1, salt=i)[0] for i in range(32)]
        owners = [rendezvous_owner(c, names) for c in chains]
        assert owners == [rendezvous_owner(c, names) for c in chains]
        assert len(set(owners)) > 1, "32 chains all mapped to one owner"
        assert rendezvous_owner(chains[0], []) is None

    def test_membership_ttl(self, vol):
        now = [100.0]
        a = LeaseBoard(vol, "ps", "a", clock=lambda: now[0])
        b = LeaseBoard(vol, "ps", "b", clock=lambda: now[0])
        a.register()
        b.register()
        assert a.alive_replicas() == ["a", "b"]
        now[0] += 61.0  # past DEFAULT_REPLICA_TTL_S
        a.register()  # only a refreshes
        assert a.alive_replicas() == ["a"]
        a.deregister()
        assert b.alive_replicas() == []

    def test_lease_refused_while_live_owner_holds(self, vol):
        now = [100.0]
        a = LeaseBoard(vol, "ps", "a", clock=lambda: now[0])
        b = LeaseBoard(vol, "ps", "b", clock=lambda: now[0])
        a.register()
        b.register()
        chain = _chain(1)[0]
        assert a.acquire(chain) is True
        assert b.acquire(chain) is False
        assert b.takeovers == 0
        # the holder re-acquiring refreshes, never counts as takeover
        assert a.acquire(chain) is True
        assert a.takeovers == 0

    def test_takeover_on_dead_owner_is_counted_and_journaled(
        self, vol, state_dir
    ):
        now = [100.0]
        a = LeaseBoard(vol, "ps", "a", clock=lambda: now[0])
        b = LeaseBoard(vol, "ps", "b", clock=lambda: now[0])
        a.register()
        b.register()
        chain = _chain(1, salt=1)[0]
        assert a.acquire(chain)
        a.deregister()  # the owner-death path deregisters before dying
        assert b.acquire(chain) is True
        assert b.takeovers == 1
        assert b.lease_of(chain)["owner"] == "b"
        recs = [
            json.loads(line)
            for line in (state_dir / "prefix_store.jsonl")
            .read_text().splitlines()
        ]
        mine = [
            r for r in recs
            if r.get("action") == "owner_takeover" and r.get("chain") == chain
        ]
        assert mine and mine[-1]["from"] == "a" and mine[-1]["to"] == "b"
        assert mine[-1]["reason"] == "owner_dead"

    def test_takeover_on_expired_lease(self, vol):
        now = [100.0]
        a = LeaseBoard(vol, "ps", "a", clock=lambda: now[0],
                       lease_ttl_s=5.0, replica_ttl_s=1000.0)
        b = LeaseBoard(vol, "ps", "b", clock=lambda: now[0],
                       lease_ttl_s=5.0, replica_ttl_s=1000.0)
        a.register()
        b.register()
        chain = _chain(1, salt=2)[0]
        assert a.acquire(chain)
        now[0] += 6.0  # owner alive but wedged past its lease
        assert b.acquire(chain) is True
        assert b.takeovers == 1

    def test_release_never_steals(self, vol):
        now = [100.0]
        a = LeaseBoard(vol, "ps", "a", clock=lambda: now[0])
        b = LeaseBoard(vol, "ps", "b", clock=lambda: now[0])
        a.register()
        b.register()
        chain = _chain(1, salt=3)[0]
        a.acquire(chain)
        b.release(chain)  # not b's lease: must be a no-op
        assert a.lease_of(chain)["owner"] == "a"
        a.release(chain)
        assert a.lease_of(chain) is None


class TestGC:
    def _store(self, vol, name="a", **kw):
        return SharedPrefixStore(vol, replica=name, shared=False, **kw)

    def test_lru_sweep_is_bounded_and_skips_pins(self, vol):
        s = self._store(vol)
        data = serialize_block(_np_block(20))
        for i in range(6):
            s.put(f"g{i}", data)
            # stamp strictly increasing mtimes: g0 oldest
            path = vol.local_path / s.root / block_file(f"g{i}")
            os.utime(path, (1000.0 + i, 1000.0 + i))
        s.unpin([f"g{i}" for i in range(6)])
        s.pin(["g0", "g1"])  # oldest two are referenced
        out = s.gc(max_blocks=2, max_remove=2)
        # bounded: 2 removals max, oldest UNPINNED first (g2, g3)
        assert out["removed"] == 2
        assert not s.exists("g2") and not s.exists("g3")
        assert s.exists("g0") and s.exists("g1")
        out = s.gc(max_blocks=2, max_remove=64)
        assert s.exists("g0") and s.exists("g1"), "pins survive every sweep"
        assert out["blocks"] == 2

    def test_hit_refreshes_lru_age(self, vol):
        s = self._store(vol)
        data = serialize_block(_np_block(21))
        for i in range(3):
            s.put(f"t{i}", data)
            path = vol.local_path / s.root / block_file(f"t{i}")
            os.utime(path, (2000.0 + i, 2000.0 + i))
        s.unpin(["t0", "t1", "t2"])
        assert s.get("t0") is not None  # touch: t0 becomes newest
        out = s.gc(max_blocks=2, max_remove=64)
        assert out["removed"] == 1
        assert s.exists("t0") and not s.exists("t1")

    def test_live_peer_pins_protect_cross_replica(self, vol):
        a = SharedPrefixStore(vol, replica="a", shared=True)
        b = SharedPrefixStore(vol, replica="b", shared=True)
        data = serialize_block(_np_block(22))
        a.put("p0", data, chain=None)
        a.pin(["p0"])
        b.unpin(["p0"])
        out = b.gc(max_blocks=0, max_remove=64)
        assert out["removed"] == 0 and b.exists("p0"), (
            "a LIVE peer's refs manifest must protect its blocks"
        )
        a.deregister_replica()  # scale-in: a's pins no longer count
        out = b.gc(max_blocks=0, max_remove=64)
        assert out["removed"] == 1 and not b.exists("p0")


class TestConcurrentWriters:
    """Satellite 3: two replicas spill the SAME chain concurrently."""

    @pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
    def test_one_copy_survives_bit_identical(self, vol, kv_dtype):
        a = SharedPrefixStore(vol, replica="rep-a", shared=True)
        b = SharedPrefixStore(vol, replica="rep-b", shared=True)
        hashes = _chain(4, salt=5)
        chain = hashes[0]
        payloads = {
            h: serialize_block(_np_block(30 + i, kv_dtype))
            for i, h in enumerate(hashes)
        }
        owner = a.owner_for(chain)
        first, second = (a, b) if owner == "rep-a" else (b, a)
        for h in hashes:
            assert first.put(h, payloads[h], chain=chain) == "written"
        # the concurrent non-owner's spill of the same chain: every put
        # skips — the fleet already has the copy
        for h in hashes:
            assert second.put(h, payloads[h], chain=chain) == "dedup"
        blocks_dir = vol.local_path / a.root / "blocks"
        files = sorted(p.name for p in blocks_dir.iterdir())
        assert files == sorted(
            block_file(h).split("/")[-1] for h in hashes
        ), "exactly one physical copy per block"
        # BOTH replicas promote the stored bytes bit-identically
        for reader in (a, b):
            for h in hashes:
                got = reader.get(h)
                assert got == payloads[h]
                blk = deserialize_block(got)
                ref = deserialize_block(payloads[h])
                for name in ref.leaves:
                    np.testing.assert_array_equal(
                        blk.leaves[name], ref.leaves[name]
                    )

    def test_non_owner_defers_fresh_chains(self, vol):
        a = SharedPrefixStore(vol, replica="rep-a", shared=True)
        b = SharedPrefixStore(vol, replica="rep-b", shared=True)
        hashes = _chain(2, salt=6)
        chain = hashes[0]
        owner = a.owner_for(chain)
        non_owner = b if owner == "rep-a" else a
        data = serialize_block(_np_block(40))
        # nothing stored yet: the non-owner DEFERS (the owner will spill
        # its own copy) instead of racing the write
        assert non_owner.put(hashes[0], data, chain=chain) == "deferred"
        assert non_owner.writes == 0

    def test_gc_keeps_chain_while_either_replica_pins(self, vol):
        a = SharedPrefixStore(vol, replica="rep-a", shared=True)
        b = SharedPrefixStore(vol, replica="rep-b", shared=True)
        hashes = _chain(3, salt=7)
        for i, h in enumerate(hashes):
            a.put(h, serialize_block(_np_block(50 + i)), chain=None)
        a.pin(hashes)
        b.unpin(hashes)
        assert b.gc(max_blocks=0, max_remove=64)["removed"] == 0
        a.unpin(hashes)
        b.pin(hashes)
        assert a.gc(max_blocks=0, max_remove=64)["removed"] == 0
        a.unpin(hashes)
        b.unpin(hashes)
        assert a.gc(max_blocks=0, max_remove=64)["removed"] == 3


# -- E2E: engines over one shared store --------------------------------------


PROMPT = "the shared system prompt every fleet tenant reuses verbatim!"


def _tiny_engine(jax, tiered_prefix, seed=0, **kw):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    kw.setdefault("max_slots", 2)
    kw.setdefault("max_model_len", 96)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (32, 64))
    return LLMEngine(
        llama.LlamaConfig.tiny(), seed=seed, tiered_prefix=tiered_prefix,
        **kw,
    )


def _spill_all(engine) -> None:
    """Evict the trie and demote every host block to the store (the same
    lever chaos + bench use to make spills deterministic)."""
    t = engine.tiered
    engine.prefix_cache.evict(10_000)
    with t._lock:
        items = list(t._host.items())
    for h, data in items:
        t._demote_to_volume(h, data)
        with t._lock:
            t._host.pop(h, None)
            t._host_used -= len(data)


class TestPrefixStoreE2E:
    @pytest.mark.parametrize(
        "kv_dtype,params_kw",
        [
            ("bf16", {"temperature": 0.0}),
            ("bf16", {"temperature": 0.8, "seed": 7}),
            ("int8", {"temperature": 0.0}),
            ("int8", {"temperature": 0.8, "seed": 7}),
        ],
    )
    def test_cold_replica_serves_peer_spills_token_identical(
        self, jax, vol, kv_dtype, params_kw
    ):
        from modal_examples_tpu.serving import SamplingParams

        params = SamplingParams(max_tokens=6, **params_kw)
        tp = {"host_bytes": 1 << 20, "volume": vol, "shared": True}
        a = _tiny_engine(
            jax, dict(tp, replica="rep-a"), kv_dtype=kv_dtype
        )
        try:
            ref = a.generate(PROMPT, params)
            # sole member: rep-a owns every chain, the spill all lands
            _spill_all(a)
            assert a.tiered.store.writes > 0
        finally:
            a.stop()
        b = _tiny_engine(
            jax, dict(tp, replica="rep-b"), kv_dtype=kv_dtype
        )
        try:
            out = b.generate(PROMPT, params)
        finally:
            b.stop()
        assert out == ref, "promoted pages must be token-identical"
        st = b.tiered.store.stats()
        assert b.tiered.tier_hits["volume"] > 0
        assert st["hits"]["peer"] > 0, (
            "the cold replica must hit blocks ANOTHER replica wrote"
        )

    def test_tier_hit_metric_counts_pages_not_blocks(self, jax, vol):
        """Satellite 2: promote's tier-hit counters are PAGE units —
        comparable with the hbm counter — not block counts."""
        from modal_examples_tpu.serving import SamplingParams

        tp = {"host_bytes": 1 << 20, "volume": vol, "shared": True}
        a = _tiny_engine(jax, dict(tp, replica="rep-a"))
        try:
            a.generate(PROMPT, SamplingParams(max_tokens=4, temperature=0.0))
            _spill_all(a)
        finally:
            a.stop()
        b = _tiny_engine(jax, dict(tp, replica="rep-b"))
        try:
            b.generate(PROMPT, SamplingParams(max_tokens=4, temperature=0.0))
            assert b.tiered.tier_hits["volume"] > 0
            assert b.tiered.tier_hits["volume"] == b.tiered.promoted, (
                "volume tier hits must count promoted PAGES (the unit "
                "promoted counts), not lookup calls or blocks"
            )
        finally:
            b.stop()

    def test_scale_out_registers_and_boots_with_store_hits(self, jax, vol):
        """A SnapshotWarmFactory scale-out joins the store membership at
        boot and serves the fleet's warm corpus from the store."""
        from modal_examples_tpu.fleet import SnapshotWarmFactory
        from modal_examples_tpu.scheduling import EngineReplica
        from modal_examples_tpu.serving import SamplingParams

        params = SamplingParams(max_tokens=4, temperature=0.0)
        tp = {"host_bytes": 1 << 20, "volume": vol, "shared": True}
        primary = _tiny_engine(jax, dict(tp, replica="primary"))
        try:
            ref = primary.generate(PROMPT, params)
            _spill_all(primary)

            def build(name, role, params=None):
                eng = _tiny_engine(jax, dict(tp, replica=name))
                return EngineReplica(eng, name, role=role)

            factory = SnapshotWarmFactory(
                build, snapshot_key="test-prefix-store-scaleout"
            )
            factory.prime(primary)
            replica, _boot = factory("scale-1", "decode")
            try:
                store = replica.engine.tiered.store
                assert "scale-1" in store.alive_replicas(), (
                    "the factory must register scale-outs with the store"
                )
                out = replica.engine.generate(PROMPT, params)
                assert out == ref
                st = store.stats()
                assert (
                    st["hits"]["peer"] > 0
                    or replica.engine.tiered.tier_hits["volume"] > 0
                ), "a scale-out must boot with a non-zero store hit rate"
            finally:
                replica.engine.stop()
                factory.store.delete("test-prefix-store-scaleout")
        finally:
            primary.stop()
