"""Resource-telemetry + SLO tier: token-level latency histograms (TTFT/
TPOT), KV-page and prefix-cache occupancy gauges, the autoscaler decision
journal, SLO evaluation on /healthz, and Perfetto trace export — the
acceptance surface of the second observability layer (ISSUE 3)."""

import json
import time
import urllib.error
import urllib.request

import pytest

import modal_examples_tpu as mtpu
from modal_examples_tpu.core.cli import main as cli_main
from modal_examples_tpu.observability import catalog as C
from modal_examples_tpu.utils.prometheus import default_registry as REG


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def engine(jax):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    cfg = llama.LlamaConfig.tiny()
    eng = LLMEngine(
        cfg, max_slots=4, max_model_len=128, page_size=16,
        prefill_buckets=(32, 64), seed=0,
    )
    yield eng
    eng.stop()


def _wait_for(predicate, timeout=10.0, every=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(every)
    return predicate()


# ---------------------------------------------------------------------------
# the end-to-end acceptance test
# ---------------------------------------------------------------------------


class TestStreamingTelemetryE2E:
    def test_streaming_generation_feeds_token_histograms_and_gauges(
        self, engine
    ):
        """One streaming generation must populate the TTFT/TPOT histograms,
        move the KV-page + prefix-cache occupancy gauges, and return them to
        baseline once the request's pages are released/evicted."""
        from modal_examples_tpu.serving import SamplingParams

        # clean slate: evict any cached prefix pages from earlier requests
        engine.prefix_cache.evict(10_000)
        assert _wait_for(lambda: engine.cache.occupancy()["pages_used"] == 0)

        ttft0 = REG.value(C.TTFT_SECONDS)
        tpot0 = REG.value(C.TPOT_SECONDS)
        prompt = "the quick brown fox jumps over the lazy dog " * 2
        req = engine.submit(
            prompt, SamplingParams(max_tokens=24, temperature=0.0)
        )
        pieces = []
        occupancy_seen = []
        for piece in engine.stream(req):
            pieces.append(piece)
            occupancy_seen.append(engine.cache.occupancy()["pages_used"])
        assert req.finish_reason in ("stop", "length")
        assert req.n_generated >= 1

        # token-level histograms: exactly one TTFT observation, one TPOT
        # observation per token after the first
        assert REG.value(C.TTFT_SECONDS) == ttft0 + 1
        assert REG.value(C.TPOT_SECONDS) == tpot0 + req.n_generated - 1
        q = REG.histogram_quantiles(C.TTFT_SECONDS)
        assert q is not None and q["p50"] >= 0.0

        # KV occupancy moved: pages were held (the prompt's full pages stay
        # cached in the prefix trie after release — still occupancy). The
        # release runs on the scheduler thread right after the terminal
        # marker, so poll rather than racing it.
        n_trie = len(req.prompt_tokens) // engine.cache.page_size
        assert n_trie >= 1
        assert _wait_for(
            lambda: engine.cache.occupancy()["pages_used"] == n_trie
        ), (engine.cache.occupancy(), n_trie, occupancy_seen)
        held = n_trie
        if occupancy_seen:
            assert max(occupancy_seen) >= held  # pages held while streaming

        # gauges track the allocator (python allocator emits on alloc/free)
        assert _wait_for(lambda: REG.value(C.KV_PAGES_USED) == held)
        assert 0.0 < REG.value(C.KV_PAGE_OCCUPANCY) <= 1.0
        assert _wait_for(
            lambda: REG.value(C.PREFIX_CACHED_PAGES) == n_trie
        )

        # ... and return to baseline once the cached prefix is evicted
        ev0 = REG.value(C.PREFIX_CACHE_EVICTIONS_TOTAL)
        freed = engine.prefix_cache.evict(10_000)
        assert freed == n_trie
        assert engine.cache.occupancy()["pages_used"] == 0
        # under the native allocator the gauges refresh from the engine's
        # throttled loop (no python alloc/free hooks) — poll, don't race
        assert _wait_for(lambda: REG.value(C.KV_PAGES_USED) == 0.0)
        assert REG.value(C.KV_PAGE_OCCUPANCY) == 0.0
        assert _wait_for(lambda: REG.value(C.PREFIX_CACHED_PAGES) == 0.0)
        assert REG.value(C.PREFIX_CACHE_EVICTIONS_TOTAL) == ev0 + freed

    def test_token_counters_flush_prefill_vs_decode(self, engine):
        from modal_examples_tpu.serving import SamplingParams

        gen0 = REG.value(C.GENERATED_TOKENS_TOTAL)
        prompt0 = REG.value(C.PROMPT_TOKENS_TOTAL)
        req = engine.submit(
            "count with me one two three",
            SamplingParams(max_tokens=8, temperature=0.0),
        )
        "".join(engine.stream(req))
        # counters flush from the engine's throttled gauge refresh
        assert _wait_for(
            lambda: REG.value(C.GENERATED_TOKENS_TOTAL)
            >= gen0 + req.n_generated
        )
        assert REG.value(C.PROMPT_TOKENS_TOTAL) >= prompt0 + len(
            req.prompt_tokens
        )


class TestStreamingUsage:
    def test_stream_options_include_usage_emits_usage_chunk(self, engine):
        """OpenAI ``stream_options: {"include_usage": true}`` contract: the
        stream ends with one extra chunk (empty choices) carrying usage
        straight from the engine's per-request token counters."""
        import http.client

        from modal_examples_tpu.serving.openai_api import OpenAIServer

        srv = OpenAIServer(engine, host="127.0.0.1", port=0).start()
        try:
            conn = http.client.HTTPConnection(srv.host, srv.port, timeout=30)
            conn.request(
                "POST", "/v1/completions",
                body=json.dumps({
                    "prompt": "one two three four",
                    "max_tokens": 8,
                    "temperature": 0.0,
                    "stream": True,
                    "stream_options": {"include_usage": True},
                }),
                headers={"content-type": "application/json"},
            )
            resp = conn.getresponse()
            assert resp.status == 200
            chunks = []
            for raw in resp.read().decode().split("\n\n"):
                raw = raw.strip()
                if raw.startswith("data: ") and raw != "data: [DONE]":
                    chunks.append(json.loads(raw[len("data: "):]))
            conn.close()
        finally:
            srv.httpd.shutdown()
            srv.httpd.server_close()
        # OpenAI contract: content chunks carry "usage": null; exactly one
        # final chunk (empty choices) carries the totals, last before [DONE]
        assert all("usage" in c for c in chunks)
        usage_chunks = [c for c in chunks if c["usage"] is not None]
        assert len(usage_chunks) == 1 and usage_chunks[0] is chunks[-1]
        usage = usage_chunks[0]["usage"]
        assert usage_chunks[0]["choices"] == []
        assert usage["prompt_tokens"] >= 1
        assert usage["completion_tokens"] >= 1
        assert usage["total_tokens"] == (
            usage["prompt_tokens"] + usage["completion_tokens"]
        )


# ---------------------------------------------------------------------------
# autoscaler journal + /healthz + perfetto (the app-run half of the e2e)
# ---------------------------------------------------------------------------


app = mtpu.App("telemetry-test")


@app.function(timeout=30)
def t_square(x: int) -> int:
    return x * x


@pytest.fixture(scope="module")
def run_ctx():
    with app.run():
        yield


class TestJournalHealthzPerfetto:
    def test_boot_scale_up_is_journaled(self, run_ctx):
        from modal_examples_tpu.observability.journal import default_journal

        assert t_square.remote(3) == 9
        tag = t_square.spec.tag
        recs = default_journal.tail(200, function=tag)
        ups = [r for r in recs if r["action"] == "scale_up"]
        assert ups, recs
        first = ups[0]
        assert first["trigger"] == "queue_pressure"
        assert first["containers_after"] > first["containers_before"]
        assert first["queue_depth"] >= 1
        # decisions counter mirrors the journal
        assert REG.value(
            C.SCALER_DECISIONS_TOTAL,
            {"function": tag, "action": "scale_up"},
        ) >= len(ups)
        # queryable via the CLI
        assert cli_main(["scaler", "--function", tag]) == 0

    def test_healthz_reports_slo_pass_and_fail(self, run_ctx, monkeypatch):
        from modal_examples_tpu.web.gateway import Gateway

        assert t_square.remote(5) == 25  # guarantees call histograms exist
        # hermetic targets: the default registry is session-global, so pin
        # every default SLO to a generous budget — earlier test files'
        # (deliberate) retries/timeouts must not flip the overall status
        for var in (
            "MTPU_SLO_TTFT_P95_S", "MTPU_SLO_TPOT_P95_S",
            "MTPU_SLO_CALL_P95_S",
        ):
            monkeypatch.setenv(var, "1000000")
        monkeypatch.setenv("MTPU_SLO_ERROR_RATE", "1.0")
        monkeypatch.setenv("MTPU_SLO_RETRY_RATE", "1.0")
        gw = Gateway(app).start()
        try:
            with urllib.request.urlopen(
                f"{gw.base_url}/healthz", timeout=10
            ) as r:
                payload = json.loads(r.read())
            assert payload["status"] == "ok"
            by_name = {s["name"]: s for s in payload["slos"]}
            assert "ttft_p95" in by_name and "call_total_p95" in by_name
            call_slo = by_name["call_total_p95"]
            assert call_slo["observed"] is not None
            assert call_slo["ok"] and call_slo["burn_rate"] <= 1.0

            # impossible target -> degraded + 503 (SLO burn rate > 1)
            monkeypatch.setenv("MTPU_SLO_CALL_P95_S", "0.000001")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"{gw.base_url}/healthz", timeout=10)
            assert e.value.code == 503
            degraded = json.loads(e.value.read())
            assert degraded["status"] == "degraded"
            bad = {
                s["name"]: s for s in degraded["slos"]
            }["call_total_p95"]
            assert not bad["ok"] and bad["burn_rate"] > 1.0
            # burn rate lands in the registry as a gauge
            assert REG.value(C.SLO_BURN_RATE, {"slo": "call_total_p95"}) > 1.0

            # the autoscaler journal is queryable over HTTP too
            with urllib.request.urlopen(
                f"{gw.base_url}/autoscaler?function={t_square.spec.tag}",
                timeout=10,
            ) as r:
                decisions = json.loads(r.read())["decisions"]
            assert any(d["action"] == "scale_up" for d in decisions)
        finally:
            gw.stop()

    def test_trace_perfetto_export_is_valid_chrome_trace(
        self, run_ctx, capsys
    ):
        call = t_square.spawn(7)
        assert call.get(timeout=30) == 49
        assert cli_main(["trace", call.call_id, "--perfetto"]) == 0
        doc = json.loads(capsys.readouterr().out)

        # chrome://tracing / Perfetto Trace Event Format schema
        assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
        assert doc["displayTimeUnit"] in ("ms", "ns")
        names = set()
        for ev in doc["traceEvents"]:
            assert {"ph", "pid", "tid", "name"} <= set(ev), ev
            assert ev["ph"] in ("X", "i", "M"), ev
            if ev["ph"] == "X":
                assert ev["dur"] > 0 and ev["ts"] >= 0
                names.add(ev["name"])
            elif ev["ph"] == "i":
                names.add(ev["name"])
        assert {"call", "queue", "dispatch", "execute"} <= names, names
        # container-side spans land on the container track (tid 2), the
        # supervisor phases on tid 1
        tid_of = {
            ev["name"]: ev["tid"]
            for ev in doc["traceEvents"]
            if ev["ph"] in ("X", "i")
        }
        assert tid_of["execute"] == 2 and tid_of["queue"] == 1

    def test_export_call_trace_writes_file(self, run_ctx, tmp_path):
        from modal_examples_tpu.utils.profiling import export_call_trace

        call = t_square.spawn(8)
        assert call.get(timeout=30) == 64
        out = tmp_path / "trace.json"
        doc = export_call_trace(call.call_id, out)
        on_disk = json.loads(out.read_text())
        assert on_disk["traceEvents"] and len(on_disk["traceEvents"]) == len(
            doc["traceEvents"]
        )
        with pytest.raises(KeyError):
            export_call_trace("in-doesnotexist", tmp_path / "nope.json")


# ---------------------------------------------------------------------------
# SLO evaluator unit surface
# ---------------------------------------------------------------------------


class TestSLOEvaluator:
    def test_latency_slo_pass_fail_and_no_data(self):
        from modal_examples_tpu.observability.slo import SLO, evaluate
        from modal_examples_tpu.utils.prometheus import Registry

        reg = Registry()
        slos = (
            SLO(name="fast", series=C.TTFT_SECONDS, target=1.0),
        )
        # no data: passes with observed None
        (report,) = evaluate(reg, slos)
        assert report["ok"] and report["observed"] is None

        for _ in range(20):
            reg.histogram_observe(
                C.TTFT_SECONDS, 0.1, buckets=C.TOKEN_TIME_BUCKETS
            )
        (report,) = evaluate(reg, slos)
        assert report["ok"] and report["observed"] <= 0.2
        assert 0.0 < report["burn_rate"] <= 1.0

        for _ in range(80):
            reg.histogram_observe(
                C.TTFT_SECONDS, 5.0, buckets=C.TOKEN_TIME_BUCKETS
            )
        (report,) = evaluate(reg, slos)
        assert not report["ok"] and report["burn_rate"] > 1.0

    def test_ratio_slo(self):
        from modal_examples_tpu.observability.slo import SLO, evaluate
        from modal_examples_tpu.utils.prometheus import Registry

        reg = Registry()
        reg.counter_inc(C.SCHEDULER_ERRORS_TOTAL, 5)
        reg.counter_inc(C.DECODE_STEPS_TOTAL, 100)
        slo = SLO(
            name="errs", series=C.SCHEDULER_ERRORS_TOTAL,
            denom_series=C.DECODE_STEPS_TOTAL, target=0.01, kind="ratio",
        )
        (report,) = evaluate(reg, (slo,))
        assert report["observed"] == pytest.approx(0.05)
        assert not report["ok"] and report["burn_rate"] == pytest.approx(5.0)

    def test_env_override(self, monkeypatch):
        from modal_examples_tpu.observability.slo import SLO

        slo = SLO(
            name="x", series=C.TTFT_SECONDS, target=2.0, env="MTPU_SLO_X"
        )
        assert slo.resolved_target() == 2.0
        monkeypatch.setenv("MTPU_SLO_X", "0.5")
        assert slo.resolved_target() == 0.5
        monkeypatch.setenv("MTPU_SLO_X", "garbage")
        assert slo.resolved_target() == 2.0


# ---------------------------------------------------------------------------
# decision journal unit surface
# ---------------------------------------------------------------------------


class TestDecisionJournal:
    def test_ring_and_file_round_trip(self, tmp_path):
        from modal_examples_tpu.observability.journal import (
            DecisionJournal, make_record,
        )

        j = DecisionJournal(path=tmp_path / "scaler.jsonl")
        for i in range(5):
            j.record(make_record(
                function=f"f{i % 2}", action="scale_up",
                trigger="queue_pressure", queue_depth=i,
            ))
        assert len(j.tail(10)) == 5
        assert len(j.tail(2)) == 2
        assert all(r["function"] == "f1" for r in j.tail(10, function="f1"))
        # a fresh process (empty ring) reads the file back
        j2 = DecisionJournal(path=j.path)
        recs = j2.tail(10)
        assert len(recs) == 5 and recs[-1]["queue_depth"] == 4

    def test_file_is_bounded(self, tmp_path):
        from modal_examples_tpu.observability import journal as jmod

        j = jmod.DecisionJournal(path=tmp_path / "scaler.jsonl")
        for i in range(jmod._MAX_FILE_RECORDS + 600):
            j.record({"at": i, "function": "f", "action": "kill"})
        n_lines = len(j.path.read_text().splitlines())
        assert n_lines <= jmod._MAX_FILE_RECORDS + 256  # compaction window


# ---------------------------------------------------------------------------
# `tpurun top` over pushed metrics
# ---------------------------------------------------------------------------


class TestTopCLI:
    def test_top_renders_slos_from_pushed_files(self, tmp_path, capsys):
        from modal_examples_tpu.observability.export import push_metrics_file
        from modal_examples_tpu.utils.prometheus import Registry

        reg = Registry()
        reg.gauge_set(C.TOKENS_PER_SECOND, 123.0)
        reg.gauge_set(C.ACTIVE_SLOTS, 3)
        for _ in range(10):
            reg.histogram_observe(
                C.TTFT_SECONDS, 0.05, buckets=C.TOKEN_TIME_BUCKETS
            )
        (tmp_path / "metrics").mkdir()
        push_metrics_file("engine", reg, root=tmp_path / "metrics")
        assert cli_main(["top", "--dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "tokens/s" in out and "123.0" in out
        assert "ttft_p95" in out and "VIOLATING" not in out
