"""ISSUE 19 acceptance: macro-step decode runtime (docs/multistep.md).

The exactness contract, pinned as a matrix: an engine running N decode
steps per dispatch (``decode_steps`` / ``MTPU_DECODE_STEPS``) is
**token-identical** to the classic one-block-per-dispatch path on the
same replica — greedy AND seeded, bf16 AND int8 KV, N in {1, 4, 8},
including runtime knob flips on a live engine. The harvest boundary is
a first-class failover point: a checkpoint whose resume position lands
*inside* a macro-step (k not a multiple of N) resumes token-identically
on a peer running a *different* N; live migration mid-macro-step ships
only harvested tokens (the detok worker is flushed on the victim's
scheduler thread first) and continues byte-identically. Abort and
deadline landing between harvest boundaries terminate honestly with
nothing leaked, and stop-string truncation through the off-thread
detokenization worker matches the classic in-line path byte for byte.
"""

import threading
import time

import pytest


PROMPT = "the quick brown fox jumps over the lazy dog and naps in the sun"


def _mk_engine(kv_dtype="bfloat16", params=None, **kw):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    kw.setdefault("max_slots", 2)
    kw.setdefault("max_model_len", 128)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (16, 32))
    return LLMEngine(
        llama.LlamaConfig.tiny(), seed=0, params=params,
        kv_dtype=kv_dtype, **kw,
    )


def _drained(eng) -> list:
    from modal_examples_tpu.faults.chaos import check_drained

    return check_drained({"eng": eng})


def _wait_tokens(req, n, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(req.generated_tokens) >= n:
            return True
        time.sleep(0.005)
    return False


def _wait_drained(eng, timeout=30.0) -> list:
    """Abort/deadline reaping is asynchronous (the finish marker is
    delivered immediately; the slot is reaped at the next decode tick) —
    poll until the engine drains instead of asserting instantaneously."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if _drained(eng) == []:
            return []
        time.sleep(0.02)
    return _drained(eng)


class TestTokenIdentityMatrix:
    """classic (N=1) vs macro-step (N in {4, 8}) on the same replica:
    greedy + seeded, bf16 + int8 KV — byte-identical text, identical
    token ids, identical finish reason. N mutates on a LIVE engine
    between runs (the knob is read once per dispatch, like
    prefill_budget), so this also pins the byte-identical fall-through
    back to the classic path at N=1."""

    @pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
    def test_classic_vs_multistep_matrix(self, jax_cpu, kv_dtype):
        from modal_examples_tpu.serving import SamplingParams

        sps = {
            "greedy": SamplingParams(max_tokens=16, temperature=0.0),
            "seeded": SamplingParams(max_tokens=16, temperature=0.9, seed=7),
        }
        ref_eng = _mk_engine(kv_dtype)  # classic: decode_steps unset -> 1
        ms_eng = _mk_engine(kv_dtype, params=ref_eng.params, decode_steps=8)
        try:
            refs = {}
            for name, sp in sps.items():
                r = ref_eng.submit(PROMPT, sp)
                refs[name] = (
                    "".join(ref_eng.stream(r)),
                    list(r.generated_tokens),
                    r.finish_reason,
                )
            for n in (8, 4, 1):
                ms_eng.decode_steps = n
                for name, sp in sps.items():
                    req = ms_eng.submit(PROMPT, sp)
                    out = "".join(ms_eng.stream(req))
                    ref_text, ref_tokens, ref_fin = refs[name]
                    assert req.generated_tokens == ref_tokens, (
                        kv_dtype, name, n,
                    )
                    assert out == ref_text, (kv_dtype, name, n)
                    assert req.finish_reason == ref_fin, (kv_dtype, name, n)
            assert _drained(ref_eng) == [] and _drained(ms_eng) == []
        finally:
            ref_eng.stop()
            ms_eng.stop()


class TestCheckpointMidMacroStep:
    """checkpoint -> resume on a PEER running a different N: resume
    positions deliberately chosen NOT to align with either engine's
    harvest boundary (k not a multiple of 4 or 8) — the continuation is
    still byte-identical, because checkpoints only ever contain
    harvested tokens and sampling is (seed, position)-keyed."""

    @pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
    @pytest.mark.parametrize("sampling", ["greedy", "seeded"])
    def test_resume_matrix(self, jax_cpu, kv_dtype, sampling):
        from modal_examples_tpu.serving import SamplingParams

        sp = (
            SamplingParams(max_tokens=12, temperature=0.0)
            if sampling == "greedy"
            else SamplingParams(max_tokens=12, temperature=0.9, seed=7)
        )
        eng_a = _mk_engine(kv_dtype, decode_steps=4)  # victim
        eng_b = _mk_engine(  # peer on a DIFFERENT macro-step width
            kv_dtype, params=eng_a.params, decode_steps=8,
        )
        try:
            ref = eng_a.submit(PROMPT, sp)
            ref_text = "".join(eng_a.stream(ref))
            ref_tokens = list(ref.generated_tokens)
            assert ref.n_generated == 12
            # k=1/3/6/11: inside a 4-step macro on the victim, inside an
            # 8-step macro on the peer, and the last-token edge
            for k in (1, 3, 6, 11):
                req = eng_b.make_request(PROMPT, sp)
                req.auto_seed = ref.auto_seed  # rides the checkpoint
                eng_b.submit_resumed(
                    req,
                    prompt_tokens=ref.prompt_tokens,
                    generated=ref_tokens[:k],
                    emitted_len=0,
                )
                out = "".join(eng_b.stream(req))
                assert req.generated_tokens == ref_tokens, (
                    sampling, kv_dtype, k,
                )
                assert out == ref_text, (sampling, kv_dtype, k)
                assert req.finish_reason == ref.finish_reason
            assert _drained(eng_a) == [] and _drained(eng_b) == []
        finally:
            eng_a.stop()
            eng_b.stop()


class TestLiveMigrationMidMacroStep:
    """Live KV migration extracted between macro-steps: the victim's
    scheduler flushes the detok worker before checkpointing, so the
    shipped state holds only harvested tokens — the stream continues on
    the target (running a different N) byte-identically."""

    def _fleet(self, **eng_kw):
        from modal_examples_tpu.scheduling import EngineReplica

        steps_a = eng_kw.pop("steps_a", 4)
        steps_b = eng_kw.pop("steps_b", 8)
        eng_a = _mk_engine(decode_steps=steps_a, **eng_kw)
        eng_b = _mk_engine(
            params=eng_a.params, decode_steps=steps_b, **eng_kw,
        )
        rep_a = EngineReplica(eng_a, "ms-mig-a", role="unified")
        rep_b = EngineReplica(eng_b, "ms-mig-b", role="unified")
        return eng_a, eng_b, rep_a, rep_b

    @pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
    def test_migrate_mid_macro_step_token_identical(self, jax_cpu, kv_dtype):
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.serving import failover as fo

        sp = SamplingParams(max_tokens=48, temperature=0.0)
        eng_a, eng_b, rep_a, rep_b = self._fleet(kv_dtype=kv_dtype)
        try:
            ref = eng_b.submit(PROMPT, sp)  # fault-free reference on B
            ref_text = "".join(eng_b.stream(ref))
            ref_tokens = list(ref.generated_tokens)

            req = rep_a.submit(PROMPT, sp)
            pieces: list[str] = []
            t = threading.Thread(
                target=lambda: pieces.extend(eng_a.stream(req))
            )
            t.start()
            assert _wait_tokens(req, 5)
            result = fo.migrate_request(
                rep_a, rep_b, req, chunk_bytes=512
            )
            assert result == "ok"
            t.join(timeout=120)
            assert not t.is_alive()
            assert req.finish_reason == ref.finish_reason
            assert req.generated_tokens == ref_tokens, kv_dtype
            assert "".join(pieces) == ref_text, kv_dtype
            assert _drained(eng_a) == [] and _drained(eng_b) == []
        finally:
            eng_a.stop()
            eng_b.stop()


class TestAbortDeadlineBetweenHarvests:
    """Failure hygiene at the harvest boundary: an abort or deadline
    that lands while the engine is inside a macro-step discards the
    un-harvested tail at the next harvest — honest finish reason, pages
    freed, nothing stuck in the detok worker."""

    def test_abort_between_harvest_boundaries(self, jax_cpu):
        from modal_examples_tpu.serving import SamplingParams

        eng = _mk_engine(decode_steps=8)
        try:
            req = eng.submit(PROMPT, SamplingParams(
                max_tokens=96, temperature=0.0,
            ))
            pieces: list[str] = []
            t = threading.Thread(
                target=lambda: pieces.extend(eng.stream(req))
            )
            t.start()
            # at least one harvest landed; the next macro-step is in
            # flight (or about to be) when the abort arrives
            assert _wait_tokens(req, 4)
            eng.abort(req)
            t.join(timeout=120)
            assert not t.is_alive()
            assert req.finish_reason == "stop"
            assert len(req.generated_tokens) < 96
            assert _wait_drained(eng) == []
        finally:
            eng.stop()

    def test_deadline_between_harvest_boundaries(self, jax_cpu):
        from modal_examples_tpu.serving import SamplingParams

        eng = _mk_engine(decode_steps=8)
        try:
            req = eng.submit(PROMPT, SamplingParams(
                max_tokens=96, temperature=0.0,
            ))
            pieces: list[str] = []
            t = threading.Thread(
                target=lambda: pieces.extend(eng.stream(req))
            )
            t.start()
            assert _wait_tokens(req, 4)
            # the deadline lapses mid-macro-step; the sweep reaps it at
            # the next harvest boundary
            req.deadline = eng._clock() - 1.0
            t.join(timeout=120)
            assert not t.is_alive()
            assert req.finish_reason == "deadline"
            assert len(req.generated_tokens) < 96
            assert _wait_drained(eng) == []
        finally:
            eng.stop()


class TestDetokWorkerStopStrings:
    """Stop-string truncation runs on the detokenization worker when
    decode_steps > 1 (classic path matches stop strings in-line on the
    scheduler thread): both paths emit byte-identical truncated text."""

    def test_stop_string_truncates_identically(self, jax_cpu):
        from modal_examples_tpu.serving import SamplingParams

        eng1 = _mk_engine()  # classic in-line stop matching
        eng8 = _mk_engine(params=eng1.params, decode_steps=8)
        try:
            free = SamplingParams(max_tokens=24, temperature=0.0)
            ref = eng1.submit(PROMPT, free)
            ref_text = "".join(eng1.stream(ref))
            assert len(ref_text) > 8
            # a substring from the middle of the free-running output:
            # guaranteed to match mid-stream on both engines
            stop = ref_text[len(ref_text) // 2:len(ref_text) // 2 + 3]
            sp = SamplingParams(max_tokens=24, temperature=0.0, stop=(stop,))

            c = eng1.submit(PROMPT, sp)
            classic_out = "".join(eng1.stream(c))
            m = eng8.submit(PROMPT, sp)
            ms_out = "".join(eng8.stream(m))

            assert ms_out == classic_out
            assert m.finish_reason == c.finish_reason == "stop"
            # truncation actually happened: shorter than the free run
            assert len(classic_out) < len(ref_text)
            assert stop not in classic_out
            assert _drained(eng1) == [] and _drained(eng8) == []
        finally:
            eng1.stop()
            eng8.stop()
