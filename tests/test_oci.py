"""OCI image-layout export (core/oci.py) — the offline analog of the
reference's server-side image builder (02_building_containers).

Validates against the opencontainers image-spec with our own parser:
blob digests match contents, diff_ids hash the uncompressed tars,
manifests/config parse and cross-reference, local-content layers
round-trip through extraction, and the whole layout is deterministic.
"""

from __future__ import annotations

import gzip
import hashlib
import io
import json
import tarfile
from pathlib import Path

import modal_examples_tpu as mtpu


def _build_image(tmp_path: Path):
    src = tmp_path / "srcdir"
    src.mkdir()
    (src / "model.txt").write_text("weights v1")
    (src / "sub").mkdir()
    (src / "sub" / "cfg.json").write_text('{"a": 1}')
    single = tmp_path / "start.sh"
    single.write_text("#!/bin/sh\necho hi\n")
    return (
        mtpu.Image.debian_slim("3.12")
        .apt_install("curl")
        .pip_install("jax[tpu]")
        .env({"MODEL": "llama", "PRECISION": "bf16"})
        .add_local_dir(str(src), "/assets")
        .add_local_file(str(single), "/start.sh")
        .workdir("/app")
        .entrypoint(["/start.sh"])
    )


def _read_blob(dest: Path, digest: str) -> bytes:
    algo, hexd = digest.split(":")
    data = (dest / "blobs" / algo / hexd).read_bytes()
    assert hashlib.sha256(data).hexdigest() == hexd  # content-addressed
    return data


def test_layout_is_spec_valid_and_digests_check(tmp_path):
    img = _build_image(tmp_path)
    dest = tmp_path / "oci"
    summary = img.export_oci(str(dest), tag="v1")

    assert json.loads((dest / "oci-layout").read_text()) == {
        "imageLayoutVersion": "1.0.0"
    }
    index = json.loads((dest / "index.json").read_text())
    (mdesc,) = index["manifests"]
    assert mdesc["annotations"]["org.opencontainers.image.ref.name"] == "v1"
    manifest = json.loads(_read_blob(dest, mdesc["digest"]))
    assert mdesc["size"] == len(_read_blob(dest, mdesc["digest"]))
    assert summary["manifest_digest"] == mdesc["digest"]

    config = json.loads(_read_blob(dest, manifest["config"]["digest"]))
    # config carries env/workdir/entrypoint
    assert "MODEL=llama" in config["config"]["Env"]
    assert config["config"]["WorkingDir"] == "/app"
    assert config["config"]["Entrypoint"] == ["/start.sh"]
    # two content layers (dir + file); diff_ids hash the UNCOMPRESSED tar
    assert len(manifest["layers"]) == 2
    assert len(config["rootfs"]["diff_ids"]) == 2
    for ldesc, diff_id in zip(manifest["layers"], config["rootfs"]["diff_ids"]):
        gz_bytes = _read_blob(dest, ldesc["digest"])
        assert ldesc["size"] == len(gz_bytes)
        tar_bytes = gzip.decompress(gz_bytes)
        assert (
            "sha256:" + hashlib.sha256(tar_bytes).hexdigest() == diff_id
        )
    # network steps preserved as empty_layer provenance
    empties = [h for h in config["history"] if h.get("empty_layer")]
    assert any("APT" in h["created_by"] for h in empties)
    assert any("PIP" in h["created_by"] for h in empties)
    # content layers count == non-empty history entries
    assert len(config["history"]) - len(empties) == 2


def test_layer_contents_roundtrip(tmp_path):
    img = _build_image(tmp_path)
    dest = tmp_path / "oci"
    img.export_oci(str(dest))
    index = json.loads((dest / "index.json").read_text())
    manifest = json.loads(_read_blob(dest, index["manifests"][0]["digest"]))
    files: dict[str, bytes] = {}
    for ldesc in manifest["layers"]:
        tar_bytes = gzip.decompress(_read_blob(dest, ldesc["digest"]))
        with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tf:
            for m in tf.getmembers():
                if m.isfile():
                    files[m.name] = tf.extractfile(m).read()
    assert files["assets/model.txt"] == b"weights v1"
    assert json.loads(files["assets/sub/cfg.json"]) == {"a": 1}
    assert files["start.sh"].startswith(b"#!/bin/sh")


def test_export_is_deterministic(tmp_path):
    img = _build_image(tmp_path)
    s1 = img.export_oci(str(tmp_path / "a"))
    s2 = img.export_oci(str(tmp_path / "b"))
    assert s1 == s2  # identical digests: content-addressed build cache
    assert (tmp_path / "a" / "index.json").read_bytes() == (
        tmp_path / "b" / "index.json"
    ).read_bytes()


def test_exec_bit_preserved(tmp_path):
    """An executable entrypoint script must stay executable in the layer
    tar or `podman run` would fail with permission denied."""
    import os

    script = tmp_path / "run.sh"
    script.write_text("#!/bin/sh\n")
    script.chmod(0o755)
    plain = tmp_path / "data.txt"
    plain.write_text("x")
    img = (
        mtpu.Image.debian_slim()
        .add_local_file(str(script), "/run.sh")
        .add_local_file(str(plain), "/data.txt")
    )
    dest = tmp_path / "oci"
    img.export_oci(str(dest))
    index = json.loads((dest / "index.json").read_text())
    manifest = json.loads(_read_blob(dest, index["manifests"][0]["digest"]))
    modes = {}
    for ldesc in manifest["layers"]:
        tar_bytes = gzip.decompress(_read_blob(dest, ldesc["digest"]))
        with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tf:
            for m in tf.getmembers():
                modes[m.name] = m.mode
    assert modes["run.sh"] == 0o755
    assert modes["data.txt"] == 0o644


def test_missing_local_path_raises(tmp_path):
    img = mtpu.Image.debian_slim().add_local_file(
        str(tmp_path / "nope.bin"), "/model.bin"
    )
    import pytest

    with pytest.raises(FileNotFoundError, match="does not exist"):
        img.export_oci(str(tmp_path / "oci"))


def test_no_content_image_gets_scratch_layer(tmp_path):
    """image-spec manifests need >= 1 layer; a pure-recipe chain exports
    an empty scratch layer rather than an invalid empty manifest."""
    img = mtpu.Image.debian_slim().env({"A": "b"}).pip_install("jax")
    dest = tmp_path / "oci"
    summary = img.export_oci(str(dest))
    assert summary["n_layers"] == 1
    index = json.loads((dest / "index.json").read_text())
    manifest = json.loads(_read_blob(dest, index["manifests"][0]["digest"]))
    config = json.loads(_read_blob(dest, manifest["config"]["digest"]))
    assert len(config["rootfs"]["diff_ids"]) == 1
    tar_bytes = gzip.decompress(
        _read_blob(dest, manifest["layers"][0]["digest"])
    )
    with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tf:
        assert tf.getnames() == []  # scratch: valid, empty


def test_python_source_layer(tmp_path):
    img = mtpu.Image.debian_slim().add_local_python_source("json")
    dest = tmp_path / "oci"
    summary = img.export_oci(str(dest))
    assert summary["n_layers"] == 1
    index = json.loads((dest / "index.json").read_text())
    manifest = json.loads(_read_blob(dest, index["manifests"][0]["digest"]))
    tar_bytes = gzip.decompress(
        _read_blob(dest, manifest["layers"][0]["digest"])
    )
    with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tf:
        names = tf.getnames()
    assert any(n.startswith("root/json") for n in names), names
