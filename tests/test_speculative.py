"""Speculative decoding correctness: greedy mode must reproduce the target
model's greedy decode token-for-token; self-drafting must accept everything;
stochastic mode must produce a full-length sample."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def models(jax):
    import jax.numpy as jnp

    from modal_examples_tpu.models import llama

    tcfg = llama.LlamaConfig(
        vocab_size=64, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
        ffn_dim=128, max_seq_len=128, dtype="float32",
    )
    dcfg = llama.LlamaConfig(
        vocab_size=64, dim=32, n_layers=1, n_heads=2, n_kv_heads=2,
        ffn_dim=64, max_seq_len=128, dtype="float32",
    )
    tp = llama.init_params(jax.random.PRNGKey(0), tcfg)
    dp = llama.init_params(jax.random.PRNGKey(1), dcfg)
    prompt = jnp.array([1, 2, 3, 4, 5, 6, 7, 8], jnp.int32)
    return tcfg, dcfg, tp, dp, prompt


class TestSpeculative:
    def test_greedy_reproduces_target(self, jax, models):
        from modal_examples_tpu.serving import speculative as spec

        tcfg, dcfg, tp, dp, prompt = models
        want = spec.greedy_generate(tp, tcfg, prompt, 8, 16)
        buf, n = spec.speculative_generate(
            tp, dp, tcfg, dcfg, prompt, 8, jax.random.PRNGKey(2),
            max_new=16, gamma=4, greedy=True,
        )
        assert int(n) == 16
        np.testing.assert_array_equal(np.asarray(buf), np.asarray(want))

    def test_budget_truncation_exact(self, jax, models):
        """gamma does NOT divide max_new: the final round's accepted run is
        truncated by the budget and must still match target greedy exactly
        (regression: duplicate-index scatter clobbered the last token)."""
        from modal_examples_tpu.serving import speculative as spec

        tcfg, _, tp, _, prompt = models
        want = spec.greedy_generate(tp, tcfg, prompt, 8, 14)
        buf, n = spec.speculative_generate(
            tp, tp, tcfg, tcfg, prompt, 8, jax.random.PRNGKey(2),
            max_new=14, gamma=4, greedy=True,
        )
        assert int(n) == 14
        np.testing.assert_array_equal(np.asarray(buf), np.asarray(want))

    def test_self_draft_accepts_everything(self, jax, models):
        from modal_examples_tpu.serving import speculative as spec

        tcfg, _, tp, _, prompt = models
        want = spec.greedy_generate(tp, tcfg, prompt, 8, 16)
        buf, n = spec.speculative_generate(
            tp, tp, tcfg, tcfg, prompt, 8, jax.random.PRNGKey(2),
            max_new=16, gamma=4, greedy=True,
        )
        np.testing.assert_array_equal(np.asarray(buf), np.asarray(want))

    def test_stochastic_generates_full_length(self, jax, models):
        from modal_examples_tpu.serving import speculative as spec

        tcfg, dcfg, tp, dp, prompt = models
        buf, n = spec.speculative_generate(
            tp, dp, tcfg, dcfg, prompt, 8, jax.random.PRNGKey(3),
            max_new=16, gamma=4, greedy=False, temperature=1.0,
        )
        assert int(n) == 16
        out = np.asarray(buf[8:])
        assert (out >= 0).all() and (out < tcfg.vocab_size).all()
