"""Speculative decoding correctness: greedy mode must reproduce the target
model's greedy decode token-for-token; self-drafting must accept everything;
stochastic mode must produce a full-length sample."""

import pytest

pytestmark = pytest.mark.slow  # heavyweight: excluded from the fast tier

import numpy as np


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def models(jax):
    import jax.numpy as jnp

    from modal_examples_tpu.models import llama

    tcfg = llama.LlamaConfig(
        vocab_size=64, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
        ffn_dim=128, max_seq_len=128, dtype="float32",
    )
    dcfg = llama.LlamaConfig(
        vocab_size=64, dim=32, n_layers=1, n_heads=2, n_kv_heads=2,
        ffn_dim=64, max_seq_len=128, dtype="float32",
    )
    tp = llama.init_params(jax.random.PRNGKey(0), tcfg)
    dp = llama.init_params(jax.random.PRNGKey(1), dcfg)
    prompt = jnp.array([1, 2, 3, 4, 5, 6, 7, 8], jnp.int32)
    return tcfg, dcfg, tp, dp, prompt


class TestSpeculative:
    def test_greedy_reproduces_target(self, jax, models):
        from modal_examples_tpu.serving import speculative as spec

        tcfg, dcfg, tp, dp, prompt = models
        want = spec.greedy_generate(tp, tcfg, prompt, 8, 16)
        buf, n = spec.speculative_generate(
            tp, dp, tcfg, dcfg, prompt, 8, jax.random.PRNGKey(2),
            max_new=16, gamma=4, greedy=True,
        )
        assert int(n) == 16
        np.testing.assert_array_equal(np.asarray(buf), np.asarray(want))

    def test_budget_truncation_exact(self, jax, models):
        """gamma does NOT divide max_new: the final round's accepted run is
        truncated by the budget and must still match target greedy exactly
        (regression: duplicate-index scatter clobbered the last token)."""
        from modal_examples_tpu.serving import speculative as spec

        tcfg, _, tp, _, prompt = models
        want = spec.greedy_generate(tp, tcfg, prompt, 8, 14)
        buf, n = spec.speculative_generate(
            tp, tp, tcfg, tcfg, prompt, 8, jax.random.PRNGKey(2),
            max_new=14, gamma=4, greedy=True,
        )
        assert int(n) == 14
        np.testing.assert_array_equal(np.asarray(buf), np.asarray(want))

    def test_self_draft_accepts_everything(self, jax, models):
        from modal_examples_tpu.serving import speculative as spec

        tcfg, _, tp, _, prompt = models
        want = spec.greedy_generate(tp, tcfg, prompt, 8, 16)
        buf, n = spec.speculative_generate(
            tp, tp, tcfg, tcfg, prompt, 8, jax.random.PRNGKey(2),
            max_new=16, gamma=4, greedy=True,
        )
        np.testing.assert_array_equal(np.asarray(buf), np.asarray(want))

    def test_stochastic_generates_full_length(self, jax, models):
        from modal_examples_tpu.serving import speculative as spec

        tcfg, dcfg, tp, dp, prompt = models
        buf, n = spec.speculative_generate(
            tp, dp, tcfg, dcfg, prompt, 8, jax.random.PRNGKey(3),
            max_new=16, gamma=4, greedy=False, temperature=1.0,
        )
        assert int(n) == 16
        out = np.asarray(buf[8:])
        assert (out >= 0).all() and (out < tcfg.vocab_size).all()


class TestEngineSpeculative:
    """Speculative decoding integrated into the continuous-batching engine
    (the reference ships it engine-side: vllm_inference.py:196-205)."""

    @staticmethod
    def _mk_engine(jax, speculative=None, **kw):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine

        cfg = llama.LlamaConfig.tiny()
        return LLMEngine(
            cfg, max_slots=4, max_model_len=128, page_size=16,
            prefill_buckets=(32, 64), seed=0, speculative=speculative, **kw,
        )

    def test_greedy_spec_matches_plain_engine(self, jax):
        """Greedy speculative decode == plain greedy decode token-for-token,
        with an unrelated (random) draft model."""
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import SamplingParams

        plain = self._mk_engine(jax)
        spec = self._mk_engine(
            jax, speculative=(llama.LlamaConfig.tiny(), 3),
        )
        try:
            prompts = ["counting one two three", "the tiny engine test"]
            params = SamplingParams(max_tokens=24, temperature=0.0)
            want = [plain.generate(p, params) for p in prompts]
            got = [spec.generate(p, params) for p in prompts]
            assert want == got
            assert spec.stats.spec_proposed > 0
        finally:
            plain.stop()
            spec.stop()

    def test_self_draft_accepts_everything(self, jax):
        """Draft == target: greedy acceptance must be ~100% (every proposal
        matches the target argmax)."""
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import SamplingParams

        cfg = llama.LlamaConfig.tiny()
        params0 = llama.init_params(jax.random.PRNGKey(0), cfg)
        from modal_examples_tpu.serving import LLMEngine

        eng = LLMEngine(
            cfg, params0, max_slots=2, max_model_len=128, page_size=16,
            prefill_buckets=(32,), seed=0,
            speculative=(cfg, 4), draft_params=params0,
        )
        try:
            out = eng.generate(
                "self draft test", SamplingParams(max_tokens=20, temperature=0.0)
            )
            assert out  # produced text
            assert eng.stats.acceptance_rate() > 0.95
        finally:
            eng.stop()

    def test_sampling_mode_runs(self, jax):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import SamplingParams

        eng = self._mk_engine(jax, speculative=(llama.LlamaConfig.tiny(), 2))
        try:
            out = eng.generate(
                "stochastic run", SamplingParams(max_tokens=16, temperature=1.0)
            )
            assert isinstance(out, str)
        finally:
            eng.stop()

    def test_top_p_accepted_in_spec_mode(self, jax):
        """The fused runtime routes temp>0 lanes through the in-program
        classic sample() call (docs/speculative.md#program-shape), so every
        sampling knob the plain engine takes is legal in spec mode — and
        must match the plain engine token-for-token (same seed, same
        (seed,position)-keyed sampling contract)."""
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import SamplingParams

        plain = self._mk_engine(jax)
        eng = self._mk_engine(jax, speculative=(llama.LlamaConfig.tiny(), 2))
        try:
            params = SamplingParams(
                max_tokens=12, temperature=0.8, top_p=0.5, seed=7
            )
            want = plain.generate("x y z", params)
            got = eng.generate("x y z", params)
            assert got == want
        finally:
            plain.stop()
            eng.stop()


class TestVerifyStep:
    def test_verify_matches_sequential_decode(self, jax):
        """verify_step over a T-token chain == T sequential decode_steps:
        same logits (at matching positions) and same cache contents."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama

        cfg = llama.LlamaConfig(
            vocab_size=64, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        B, T, ps, pps = 2, 4, 16, 4
        n_pages = 1 + B * pps
        shape = (cfg.n_layers, n_pages, ps, cfg.n_kv_heads, cfg.head_dim)
        pt = (1 + jnp.arange(B * pps, dtype=jnp.int32)).reshape(B, pps)
        active = jnp.ones((B,), bool)

        # seed the caches with a short prefix via prefill
        prompt = jnp.array([[1, 2, 3, 5, 0, 0], [7, 8, 9, 11, 13, 2]], jnp.int32)
        seq_lens = jnp.array([4, 6], jnp.int32)
        k1 = jnp.zeros(shape, jnp.float32)
        v1 = jnp.zeros(shape, jnp.float32)
        _, k1, v1 = llama.prefill(params, prompt, k1, v1, pt, seq_lens, cfg)
        k2, v2 = k1, v1

        chain = jnp.array([[3, 5, 2, 9], [1, 4, 6, 8]], jnp.int32)
        pos0 = seq_lens  # chain starts at the next position

        logits_v, k1, v1 = llama.verify_step(
            params, chain, pos0, k1, v1, pt, active, cfg
        )

        seq_logits = []
        for t in range(T):
            lg, k2, v2 = llama.decode_step(
                params, chain[:, t], pos0 + t, k2, v2, pt, active, cfg
            )
            seq_logits.append(lg)
        want = jnp.stack(seq_logits, axis=1)  # [B, T, V]

        np.testing.assert_allclose(
            np.asarray(logits_v), np.asarray(want), atol=2e-4
        )
        np.testing.assert_allclose(np.asarray(k1), np.asarray(k2), atol=2e-5)
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), atol=2e-5)


class TestNgramSpeculative:
    """Prompt-lookup (n-gram) speculative decoding: proposals from the
    sequence's own history, target-verified — no draft model, no draft
    cache (vLLM's [ngram] speculative mode; the reference enables
    engine-side spec decoding at vllm_inference.py:196-205)."""

    @staticmethod
    def _mk(jax, **kw):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine

        cfg = llama.LlamaConfig.tiny()
        return LLMEngine(
            cfg, max_slots=4, max_model_len=128, page_size=16,
            prefill_buckets=(32, 64), seed=0, **kw,
        )

    def test_greedy_matches_plain_engine(self, jax):
        """Greedy ngram-spec == plain greedy token-for-token, including
        prompts repetitive enough that proposals actually get accepted."""
        from modal_examples_tpu.serving import SamplingParams

        plain = self._mk(jax)
        ng = self._mk(jax, speculative=("ngram", 4))
        try:
            prompts = [
                "counting one two three",
                "one two one two one two",
                "red blue red blue red",
                "hello hello hello hello",
            ]
            params = SamplingParams(max_tokens=20, temperature=0.0)
            want = [plain.generate(p, params) for p in prompts]
            got = [ng.generate(p, params) for p in prompts]
            assert want == got
            # repetition makes lookups fire AND get accepted — the mode's
            # entire point (multi-token steps with zero extra model)
            assert ng.stats.spec_proposed > 0
            assert ng.stats.spec_accepted > 0
            assert ng.error_count == 0, ng.error_log
        finally:
            plain.stop()
            ng.stop()

    def test_no_draft_state_allocated(self, jax):
        ng = self._mk(jax, speculative=("ngram", 3))
        try:
            assert ng.spec_mode == "ngram"
            assert ng.spec_gamma == 3
            assert ng.draft_cfg is None
            assert not hasattr(ng, "draft_cache")
        finally:
            ng.stop()

    def test_sampling_temperature_runs(self, jax):
        """temperature>0 uses the degenerate-proposal accept rule; output
        must complete cleanly (distribution equality is the math's
        guarantee; determinism is not promised without seed)."""
        from modal_examples_tpu.serving import SamplingParams

        ng = self._mk(jax, speculative=("ngram", 4))
        try:
            out = ng.generate(
                "repeat repeat repeat repeat",
                SamplingParams(max_tokens=16, temperature=0.8),
            )
            assert isinstance(out, str)
            assert ng.error_count == 0, ng.error_log
        finally:
            ng.stop()

    def test_gamma_validation(self, jax):
        import pytest as _pytest

        with _pytest.raises(ValueError, match="gamma"):
            self._mk(jax, speculative=("ngram", 0))
