"""int8 quantized paged-KV cache (docs/kv_cache.md).

The accuracy contract is tolerance-based, never token-exact (KV
quantization legitimately changes logits — vLLM's fp8 KV does too):

- quantize/dequant round trip is bounded by amax/254 per element;
- interpreter-mode int8 paged decode — BOTH ragged variants and the XLA
  gather fallback — matches the f32-cache reference within the declared
  logit-drift tolerance, and matches the XLA fallback over the SAME
  quantized cache much tighter (identical dequantized values);
- the default (bf16/f32) path constructs no QuantizedKV anywhere: 2-leaf
  cache, pass-through helpers — bit-identical to the pre-int8 code.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from modal_examples_tpu import ops
from modal_examples_tpu.models import llama
from modal_examples_tpu.ops import reference
from modal_examples_tpu.ops.kv_quant import (
    QuantizedKV,
    dequantize_kv,
    is_quantized,
    kv_dtype_name,
    kv_empty,
    kv_gather,
    kv_scatter,
    quantize_kv,
    resolve_kv_dtype,
)
from modal_examples_tpu.serving.kv_cache import PagedKVCache

#: declared logit-drift tolerance for int8 KV vs the f32 cache on the tiny
#: random-weight models (logit scale ~3; per-token-head int8 => ~2% drift)
LOGIT_TOL = 0.25


# -- quantize/dequant primitives --------------------------------------------


class TestQuantizeKV:
    def test_roundtrip_error_bound(self):
        x = jax.random.normal(
            jax.random.PRNGKey(0), (2, 5, 16, 4, 64), jnp.float32
        )
        q = quantize_kv(x)
        assert q.data.dtype == jnp.int8
        assert q.scale.shape == x.shape[:-1]
        deq = dequantize_kv(q, jnp.float32)
        # per (token, head) row: |x - deq| <= scale/2 (+ rounding slack)
        bound = q.scale[..., None] * 0.51
        assert bool(jnp.all(jnp.abs(deq - x) <= bound))

    def test_zero_rows_exact(self):
        x = jnp.zeros((2, 3, 8), jnp.float32)
        q = quantize_kv(x)
        assert bool(jnp.all(q.scale == 1.0))  # no div-by-zero scales
        assert bool(jnp.all(dequantize_kv(q, jnp.float32) == 0.0))

    def test_deterministic(self):
        # the prefix cache relies on same-values => same quantized bytes
        # when concurrent prefills rewrite a shared page
        x = jax.random.normal(jax.random.PRNGKey(1), (3, 16, 4, 32))
        a, b = quantize_kv(x), quantize_kv(x)
        assert bool(jnp.all(a.data == b.data))
        assert bool(jnp.all(a.scale == b.scale))

    def test_pytree_two_leaves_and_scan_slicing(self):
        q = quantize_kv(jnp.ones((4, 2, 8, 3, 16)))
        assert len(jax.tree.leaves(q)) == 2
        # lax.scan over the layer axis must slice data AND scale together
        def body(c, layer_q):
            assert isinstance(layer_q, QuantizedKV)
            return c, layer_q.scale.sum()

        _, sums = jax.lax.scan(body, 0, q)
        assert sums.shape == (4,)

    def test_resolve_kv_dtype(self):
        assert resolve_kv_dtype("int8") == "int8"
        assert resolve_kv_dtype(jnp.int8) == "int8"
        assert resolve_kv_dtype("bf16") == jnp.bfloat16
        assert resolve_kv_dtype("bfloat16") == jnp.bfloat16
        assert resolve_kv_dtype("f32") == jnp.float32
        assert resolve_kv_dtype(jnp.float32) == jnp.float32

    def test_kv_empty_and_dtype_name(self):
        shape = (2, 3, 16, 4, 32)
        plain = kv_empty(shape, jnp.bfloat16)
        assert not is_quantized(plain) and plain.shape == shape
        q = kv_empty(shape, "int8")
        assert is_quantized(q)
        assert q.shape == shape and q.scale.shape == shape[:-1]
        assert bool(jnp.all(dequantize_kv(q, jnp.float32) == 0.0))
        assert kv_dtype_name(q) == "int8"
        assert kv_dtype_name(plain) == "bfloat16"

    def test_gather_scatter_semantics(self):
        pages = quantize_kv(
            jax.random.normal(jax.random.PRNGKey(2), (2, 6, 4, 2, 8))
        )
        tables = jnp.array([[1, 3], [5, 0]], jnp.int32)
        g = kv_gather(pages, tables, layer=1, dtype=jnp.float32)
        want = dequantize_kv(pages, jnp.float32)[1][tables]
        assert np.allclose(np.asarray(g), np.asarray(want))
        # plain arrays: bit-identical pass-through to direct indexing
        plain = jax.random.normal(jax.random.PRNGKey(3), (2, 6, 4, 2, 8))
        assert bool(jnp.all(kv_gather(plain, tables, layer=0) == plain[0][tables]))

        upd = jax.random.normal(jax.random.PRNGKey(4), (2, 3, 2, 8))
        page_idx = jnp.array([1, 4, 2], jnp.int32)
        slot = jnp.array([0, 3, 1], jnp.int32)
        out = kv_scatter(pages, upd, page_idx, slot)
        qu = quantize_kv(upd)
        assert bool(jnp.all(out.data[:, page_idx, slot] == qu.data))
        assert bool(jnp.all(out.scale[:, page_idx, slot] == qu.scale))
        out_p = kv_scatter(plain, upd, page_idx, slot)
        assert bool(
            jnp.all(out_p == plain.at[:, page_idx, slot].set(upd))
        )


# -- kernels vs references ---------------------------------------------------


def _ragged_setup(Hq=16, Hkv=16, dtype=jnp.float32):
    L, B, D, ps, pp = 2, 2, 128, 16, 4
    n_pages = B * pp + 1
    kp = jax.random.normal(
        jax.random.PRNGKey(0), (L, n_pages, ps, Hkv, D), dtype
    )
    vp = jax.random.normal(jax.random.PRNGKey(1), kp.shape, dtype)
    pt = (1 + jnp.arange(B * pp, dtype=jnp.int32)).reshape(B, pp)
    prefix = jnp.array([19, 44], jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(2), (B, Hq, D), dtype)
    k_new = jax.random.normal(jax.random.PRNGKey(3), (B, Hkv, D), dtype)
    v_new = jax.random.normal(jax.random.PRNGKey(4), (B, Hkv, D), dtype)
    return kp, vp, pt, prefix, q, k_new, v_new


class TestInt8RaggedKernels:
    @pytest.mark.parametrize("variant,Hkv", [("flat", 16), ("grouped", 8)])
    def test_int8_matches_f32_reference_within_tolerance(self, variant, Hkv):
        """Interpreter-mode int8 ragged decode vs the f32-cache XLA
        reference: within the declared drift tolerance (attention outputs
        are O(1) at these shapes; observed ~0.01)."""
        Hq = 16 if variant == "flat" else 32
        kp, vp, pt, prefix, q, k_new, v_new = _ragged_setup(Hq, Hkv)
        qkp, qvp = quantize_kv(kp), quantize_kv(vp)
        o = ops.paged_decode_attention_ragged(
            q, qkp, qvp, jnp.int32(1), pt, prefix, k_new, v_new,
            variant=variant,
        )
        ref = ops.paged_decode_attention_inflight(
            q, kp[1][pt], vp[1][pt], prefix, k_new, v_new
        )
        assert float(jnp.max(jnp.abs(o - ref))) < 0.05

    @pytest.mark.parametrize("variant,Hkv", [("flat", 16), ("grouped", 8)])
    def test_int8_kernel_matches_xla_fallback_tight(self, variant, Hkv):
        """Kernel vs the XLA gather fallback over the SAME quantized cache:
        both read identical dequantized values, so only accumulation order
        differs — the bound is the bf16-probe class, not the quant drift."""
        Hq = 16 if variant == "flat" else 32
        kp, vp, pt, prefix, q, k_new, v_new = _ragged_setup(Hq, Hkv)
        qkp, qvp = quantize_kv(kp), quantize_kv(vp)
        o = ops.paged_decode_attention_ragged(
            q, qkp, qvp, jnp.int32(1), pt, prefix, k_new, v_new,
            variant=variant,
        )
        dk = kv_gather(qkp, pt, layer=1, dtype=q.dtype)
        dv = kv_gather(qvp, pt, layer=1, dtype=q.dtype)
        ref = ops.paged_decode_attention_inflight(
            q, dk, dv, prefix, k_new, v_new
        )
        assert float(jnp.max(jnp.abs(o - ref))) < 5e-3

    def test_plain_cache_path_unchanged(self):
        """bf16/f32 caches keep the exact pre-int8 kernel path (no dequant
        multiply, no scale operands): the default stays bit-identical."""
        kp, vp, pt, prefix, q, k_new, v_new = _ragged_setup()
        o = ops.paged_decode_attention_ragged(
            q, kp, vp, jnp.int32(1), pt, prefix, k_new, v_new,
            variant="flat",
        )
        ref = ops.paged_decode_attention_inflight(
            q, kp[1][pt], vp[1][pt], prefix, k_new, v_new
        )
        assert float(jnp.max(jnp.abs(o - ref))) < 1e-5

    def test_reference_paged_ops_accept_quantized(self):
        kp, vp, pt, prefix, q, k_new, v_new = _ragged_setup()
        qkp, qvp = quantize_kv(kp), quantize_kv(vp)
        lens = prefix + 1
        o = reference.paged_decode_attention(q, qkp[1], qvp[1], pt, lens)
        ref = reference.paged_decode_attention(q, kp[1], vp[1], pt, lens)
        assert float(jnp.max(jnp.abs(o - ref))) < 0.05
        # the legacy dense-layer entry point (writeback A/B path) too
        o2 = ops.paged_decode_attention(q, qkp[1], qvp[1], pt, lens)
        assert float(jnp.max(jnp.abs(o2 - ref))) < 0.05

    def test_variant_auto_selection_respects_kv_dtype(self):
        from modal_examples_tpu.ops.paged_attention import ragged_variant_for

        assert ragged_variant_for(32) == "flat"
        assert ragged_variant_for(32, "int8") == "flat"
        assert ragged_variant_for(16) == "flat"
        assert ragged_variant_for(16, "int8") == "grouped"  # int8: Hkv%32
        assert ragged_variant_for(8, "int8") == "grouped"


class TestInt8Scatter:
    def test_scatter_kv_pages_quantized_exact(self):
        L, P, ps, Hkv, D, B = 2, 6, 16, 4, 32, 3
        kp = quantize_kv(
            jax.random.normal(jax.random.PRNGKey(0), (L, P, ps, Hkv, D))
        )
        vp = quantize_kv(
            jax.random.normal(jax.random.PRNGKey(1), (L, P, ps, Hkv, D))
        )
        k_all = jax.random.normal(jax.random.PRNGKey(2), (L, B, Hkv, D))
        v_all = jax.random.normal(jax.random.PRNGKey(3), k_all.shape)
        page_idx = jnp.array([1, 3, 5], jnp.int32)
        slot = jnp.array([0, 7, 15], jnp.int32)
        ok, ov = ops.scatter_kv_pages(kp, vp, k_all, v_all, page_idx, slot)
        qk, qv = quantize_kv(k_all), quantize_kv(v_all)
        assert bool(jnp.all(ok.data[:, page_idx, slot] == qk.data))
        assert bool(jnp.all(ok.scale[:, page_idx, slot] == qk.scale))
        assert bool(jnp.all(ov.data[:, page_idx, slot] == qv.data))
        # non-target pages untouched, data and scale both
        assert bool(jnp.all(ok.data[:, 0] == kp.data[:, 0]))
        assert bool(jnp.all(ok.scale[:, 0] == kp.scale[:, 0]))


# -- model-level: prefill / decode / verify ----------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _mk_cache(cfg, kv_dtype, slots=2, pp=4, ps=16):
    return PagedKVCache.create(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, n_pages=1 + slots * pp, page_size=ps,
        kv_dtype=kv_dtype, prefer_native=False,
    )


class TestModelPaths:
    def _prefilled(self, cfg, params, kv_dtype):
        slots, pp = 2, 4
        cache = _mk_cache(cfg, kv_dtype, slots, pp)
        tables = jnp.asarray(
            1 + np.arange(slots * pp).reshape(slots, pp), jnp.int32
        )
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (slots, 32), 0, cfg.vocab_size
        )
        seq_lens = jnp.array([20, 31], jnp.int32)
        logits, kp, vp = llama.prefill(
            params, toks, cache.k_pages, cache.v_pages, tables, seq_lens,
            cfg, attn_impl="xla",
        )
        return logits, kp, vp, tables, seq_lens

    def test_prefill_quantizes_pages_within_bound(self, tiny_model):
        cfg, params = tiny_model
        _, kp32, _, tables, _ = self._prefilled(cfg, params, jnp.float32)
        _, kp8, _, _, _ = self._prefilled(cfg, params, "int8")
        assert is_quantized(kp8)
        deq = dequantize_kv(kp8, jnp.float32)
        bound = kp8.scale[..., None] * 0.51 + 1e-6
        assert bool(jnp.all(jnp.abs(deq - kp32) <= bound))

    @pytest.mark.parametrize("impl", ["xla", "pallas", "xla-writeback"])
    def test_decode_step_int8_logit_drift(self, tiny_model, impl):
        cfg, params = tiny_model
        lo32, k32, v32, tables, seq_lens = self._prefilled(
            cfg, params, jnp.float32
        )
        _, k8, v8, _, _ = self._prefilled(cfg, params, "int8")
        tok = jnp.argmax(lo32, -1).astype(jnp.int32)
        active = jnp.ones((2,), bool)
        l32, _, _ = llama.decode_step(
            params, tok, seq_lens, k32, v32, tables, active, cfg, impl=impl
        )
        l8, k8n, v8n = llama.decode_step(
            params, tok, seq_lens, k8, v8, tables, active, cfg, impl=impl
        )
        assert is_quantized(k8n) and is_quantized(v8n)  # stays quantized
        assert float(jnp.max(jnp.abs(l8 - l32))) < LOGIT_TOL

    def test_verify_step_int8_logit_drift(self, tiny_model):
        cfg, params = tiny_model
        _, k32, v32, tables, seq_lens = self._prefilled(
            cfg, params, jnp.float32
        )
        _, k8, v8, _, _ = self._prefilled(cfg, params, "int8")
        chain = jax.random.randint(
            jax.random.PRNGKey(2), (2, 3), 0, cfg.vocab_size
        )
        active = jnp.ones((2,), bool)
        l32, _, _ = llama.verify_step(
            params, chain, seq_lens, k32, v32, tables, active, cfg
        )
        l8, k8n, _ = llama.verify_step(
            params, chain, seq_lens, k8, v8, tables, active, cfg
        )
        assert is_quantized(k8n)
        assert float(jnp.max(jnp.abs(l8 - l32))) < LOGIT_TOL

    def test_prefill_chunk_int8(self, tiny_model):
        """Chunked prefill's prefix gather dequantizes: a second chunk over
        an int8 cache lands near the f32-cache logits."""
        cfg, params = tiny_model
        slots, pp, ps, C = 1, 4, 16, 32
        tables = jnp.asarray(
            1 + np.arange(slots * pp).reshape(slots, pp), jnp.int32
        )
        toks = jax.random.randint(
            jax.random.PRNGKey(3), (1, 2 * C), 0, cfg.vocab_size
        )
        outs = {}
        for name, kvd in (("f32", jnp.float32), ("int8", "int8")):
            cache = _mk_cache(cfg, kvd, slots, pp)
            kp, vp = cache.k_pages, cache.v_pages
            lo, kp, vp = llama.prefill_chunk(
                params, toks[:, :C], kp, vp, tables,
                jnp.array([C], jnp.int32), cfg, q_offset=0, attn_impl="xla",
            )
            lo, kp, vp = llama.prefill_chunk(
                params, toks[:, C:], kp, vp, tables,
                jnp.array([C], jnp.int32), cfg, q_offset=C, attn_impl="xla",
            )
            outs[name] = lo
        drift = float(jnp.max(jnp.abs(outs["int8"] - outs["f32"])))
        assert drift < LOGIT_TOL


# -- PagedKVCache container ---------------------------------------------------


class TestPagedKVCacheInt8:
    def test_four_leaf_pytree_and_halved_bytes(self):
        cfg = llama.LlamaConfig.tiny()
        bf16 = _mk_cache(cfg, jnp.bfloat16)
        q8 = _mk_cache(cfg, "int8")
        assert len(jax.tree.leaves(bf16)) == 2
        assert len(jax.tree.leaves(q8)) == 4
        assert bf16.kv_dtype == "bfloat16" and not bf16.quantized
        assert q8.kv_dtype == "int8" and q8.quantized
        # int8 = half the payload + ~3%-scale overhead (D=64 here -> ~6%)
        assert q8.bytes() < 0.6 * bf16.bytes()
        occ = q8.occupancy()
        assert occ["bytes_total"] == q8.bytes()

    def test_create_kv_dtype_and_legacy_dtype_aliases(self):
        cfg = llama.LlamaConfig.tiny()
        a = _mk_cache(cfg, "int8")
        assert is_quantized(a.k_pages)
        b = PagedKVCache.create(
            n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim, n_pages=9, page_size=16,
            dtype=jnp.float32, prefer_native=False,  # legacy spelling
        )
        assert b.k_pages.dtype == jnp.float32
        with pytest.raises(ValueError):
            PagedKVCache.create(
                n_layers=1, n_kv_heads=1, head_dim=8, n_pages=2,
                kv_dtype="int8", dtype=jnp.float32, prefer_native=False,
            )


# -- engine e2e ---------------------------------------------------------------


class TestEngineInt8KV:
    def _mk(self, **kw):
        from modal_examples_tpu.serving import LLMEngine

        cfg = llama.LlamaConfig.tiny()
        return LLMEngine(
            cfg, max_slots=2, page_size=16, max_model_len=128,
            prefill_buckets=(32,), seed=0, **kw,
        )

    def test_generates_and_reports_int8(self):
        from modal_examples_tpu.serving.sampling import SamplingParams

        eng = self._mk(kv_dtype="int8")
        try:
            assert eng.kv_dtype == "int8"
            assert eng.impl_plan["kv_dtype"] == "int8"
            assert len(jax.tree.leaves(eng.cache)) == 4
            out = eng.generate(
                "hello world", SamplingParams(max_tokens=6, temperature=0.0)
            )
            assert isinstance(out, str)
            assert eng.error_count == 0
            # dtype-aware footprint gauge reflects the halved cache
            from modal_examples_tpu.utils.prometheus import default_registry

            eng._metrics_wall = 0.0
            eng._refresh_gauges()
            val = default_registry.value(
                "mtpu_kv_cache_bytes", labels={"dtype": "int8"}
            )
            assert val == eng.cache.bytes()
        finally:
            eng.stop()

    def test_default_stays_two_leaf_bf16(self):
        eng = self._mk()
        try:
            assert eng.kv_dtype == "bfloat16"
            assert len(jax.tree.leaves(eng.cache)) == 2
            assert not is_quantized(eng.cache.k_pages)
        finally:
            eng.stop()

    def test_env_resolution(self, monkeypatch):
        monkeypatch.setenv("MTPU_KV_DTYPE", "int8")
        eng = self._mk()
        try:
            assert eng.kv_dtype == "int8"
        finally:
            eng.stop()
        # explicit arg beats the env
        eng2 = self._mk(kv_dtype=jnp.float32)
        try:
            assert eng2.kv_dtype == "float32"
        finally:
            eng2.stop()

    def test_int8_vs_f32_same_greedy_start(self):
        """Greedy decode over int8 KV tracks the f32-cache engine for a
        short horizon on the tiny model — a sanity check that the drift is
        quantization noise, not a broken read/write path. (Tolerance-based
        contract: long generations MAY diverge; first tokens of this fixed
        tiny model have comfortable argmax margins.)"""
        from modal_examples_tpu.serving.sampling import SamplingParams

        outs = {}
        for name, kvd in (("f32", jnp.float32), ("int8", "int8")):
            eng = self._mk(kv_dtype=kvd)
            try:
                outs[name] = eng.generate(
                    "the quick brown fox",
                    SamplingParams(max_tokens=4, temperature=0.0),
                )
                assert eng.error_count == 0
            finally:
                eng.stop()
        assert outs["int8"] == outs["f32"]


# -- incremental n-gram index (satellite) ------------------------------------


class TestNgramIndex:
    @staticmethod
    def _brute(hist, n, gamma, lookback):
        """The pre-index per-tick rescan (the replaced implementation),
        kept here as the semantics oracle."""
        h = hist[-lookback:]
        if len(h) <= n:
            return []
        tail = h[-n:]
        for j in range(len(h) - n - 1, -1, -1):
            if h[j : j + n] == tail:
                return h[j + n : j + n + gamma]
        return []

    @pytest.mark.parametrize("lookback", [8, 32, 1024])
    @pytest.mark.parametrize("n", [1, 2, 3])
    def test_matches_bruteforce_rescan(self, n, lookback):
        from modal_examples_tpu.serving.engine import _NgramIndex

        rng = np.random.RandomState(n * 1000 + lookback)
        for trial in range(20):
            seq = rng.randint(0, 4, size=rng.randint(1, 60)).tolist()
            cut = rng.randint(0, len(seq) + 1)
            idx = _NgramIndex(n, seq[:cut], lookback)
            for tok in seq[cut:]:
                idx.push(tok)
            for gamma in (1, 3, 5):
                assert idx.propose(gamma) == self._brute(
                    seq, n, gamma, lookback
                ), (seq, n, gamma, lookback)

    def test_incremental_equals_bulk(self):
        from modal_examples_tpu.serving.engine import _NgramIndex

        seq = [1, 2, 3, 1, 2, 3, 1, 2]
        bulk = _NgramIndex(2, seq, 1024)
        inc = _NgramIndex(2, seq[:3], 1024)
        for t in seq[3:]:
            inc.push(t)
        assert bulk.propose(4) == inc.propose(4) == [3, 1, 2]


# -- dense TP cache -----------------------------------------------------------


class TestDenseKVCacheInt8:
    def test_decode_step_dense_int8_drift(self):
        from modal_examples_tpu.serving import tensor_parallel as tp

        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        B, S = 2, 32
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (B,), 0, cfg.vocab_size
        )
        outs = {}
        for kvd in (None, "int8"):
            cache = tp.DenseKVCache.create(
                cfg, B, S, dtype=jnp.float32, kv_dtype=kvd or jnp.float32
            )
            logits = None
            for pos in range(4):  # a few steps so reads hit written KV
                positions = jnp.full((B,), pos, jnp.int32)
                logits, cache = tp.decode_step_dense(
                    params, toks, cache, positions, cfg
                )
            outs[str(kvd)] = logits
            if kvd == "int8":
                assert is_quantized(cache.k)
        drift = float(jnp.max(jnp.abs(outs["int8"] - outs["None"])))
        assert drift < LOGIT_TOL
