"""ISSUE 20 acceptance: fused adaptive speculative decoding.

Two layers, matching the design's split (docs/speculative.md):

- :class:`TestGammaController` — the pure γ-schedule policy
  (serving/spec_runtime/controller.py), exercised with hand-fed
  (proposed, accepted) rounds: acceptance collapse drives γ→0 within K
  rounds, probe/recovery hysteresis can't flap, batch pressure overrides
  without touching per-request state, requests are independent. No jax,
  no clocks — this is the fast tier.
- engine-level classes (slow tier) — the fused round wired into the
  scheduler: a hostile low-acceptance draft makes the controller retreat
  to whole-round classic fallbacks (the "spec can never cost latency"
  escape hatch; the wall-clock A/B lives in bench.py's
  ``tiny-spec-adaptive`` where timing is controlled), and the PR-12
  exactness contract — checkpoint/resume and live migration mid-stream on
  a SPECULATING engine stay token-identical, greedy, bf16 + int8.
"""

import threading

import pytest

import numpy as np


def _mk_ctrl(**kw):
    from modal_examples_tpu.serving.spec_runtime import (
        AdaptiveGammaController,
    )

    kw.setdefault("gamma_max", 4)
    return AdaptiveGammaController(**kw)


class TestGammaController:
    def test_optimistic_start_uses_full_depth(self):
        c = _mk_ctrl()
        assert c.gamma_for("r1") == 4

    def test_acceptance_collapse_drives_gamma_to_zero_within_k_rounds(self):
        """A request whose draft stops predicting it (acceptance 0) must
        stop speculating within a handful of rounds — with the default
        EWMA (α=0.4 from init 1.0) the third zero round crosses the 0.3
        collapse line: 0.6³ = 0.216."""
        c = _mk_ctrl()
        gammas = []
        for _ in range(6):
            g = c.gamma_for("r1")
            gammas.append(g)
            c.observe("r1", proposed=max(g, 1), accepted=0)
        assert gammas[0] == 4
        assert gammas[3] == 0, gammas  # collapsed after round 3's observe
        assert all(g == 0 for g in gammas[3:]), gammas
        assert c.snapshot()["r1"]["collapsed"] is True

    def test_gamma_tracks_ewma_between_full_and_collapse(self):
        """Partial acceptance scales γ smoothly: the budget is
        round(ewma * cap), never 0 while healthy (γ≥1 keeps evidence
        flowing) and never above the cap."""
        c = _mk_ctrl()
        for _ in range(8):
            g = c.gamma_for("r1")
            assert 1 <= g <= 4
            c.observe("r1", proposed=g, accepted=g // 2)
        assert not c.snapshot()["r1"]["collapsed"]

    def test_probe_cadence_and_recovery_hysteresis(self):
        """Collapsed requests emit a single probe every ``probe_every``
        rounds; recovery needs the EWMA back above ``recover_above``
        (0.6 > the 0.3 collapse line — the hysteresis band), so one good
        probe (EWMA 0.216→0.53, inside the band) must NOT re-enable
        speculation, while a second (→0.72) must."""
        c = _mk_ctrl(probe_every=4)
        for _ in range(3):
            c.observe("r1", proposed=4, accepted=0)  # collapse: ewma 0.216
        assert c.snapshot()["r1"]["collapsed"] is True

        # 3 silent rounds, then the probe
        assert [c.gamma_for("r1") for _ in range(4)] == [0, 0, 0, 1]
        c.observe("r1", proposed=1, accepted=1)  # ewma -> 0.5296: in-band
        assert c.snapshot()["r1"]["collapsed"] is True, "must not flap"

        assert [c.gamma_for("r1") for _ in range(4)] == [0, 0, 0, 1]
        c.observe("r1", proposed=1, accepted=1)  # ewma -> 0.7178: recovered
        assert c.snapshot()["r1"]["collapsed"] is False
        assert c.gamma_for("r1") >= 1

    def test_batch_pressure_zeroes_gamma_without_touching_state(self):
        """A full batch speculates for no one — but pressure is not
        evidence of bad acceptance: the EWMA and the probe counter must
        be untouched, so the next uncontended round resumes exactly where
        the request left off."""
        c = _mk_ctrl(probe_every=4)
        assert c.gamma_for("r1", batch_fill=1.0) == 0
        assert "r1" not in c.snapshot()  # no state even created
        for _ in range(3):
            c.observe("r1", proposed=4, accepted=0)  # collapse
        # pressure rounds must not advance the probe countdown
        for _ in range(10):
            assert c.gamma_for("r1", batch_fill=0.99) == 0
        assert [c.gamma_for("r1") for _ in range(4)] == [0, 0, 0, 1]

    def test_prefill_pressure_caps_gamma_at_one(self):
        c = _mk_ctrl()
        assert c.gamma_for("r1", prefill_pressure=True) == 1
        c.observe("r1", proposed=1, accepted=1)
        assert c.gamma_for("r1", prefill_pressure=False) == 4

    def test_gamma_cap_clamps_below_gamma_max(self):
        c = _mk_ctrl()
        assert c.gamma_for("r1", gamma_cap=2) == 2
        assert c.gamma_for("r1", gamma_cap=0) == 0

    def test_requests_are_independent(self):
        """One request's collapse must not leak into its batchmates —
        per-request EWMA is the whole point versus a global knob."""
        c = _mk_ctrl()
        for _ in range(5):
            c.observe("bad", proposed=4, accepted=0)
            c.observe("good", proposed=4, accepted=4)
        assert c.gamma_for("bad") == 0
        assert c.gamma_for("good") == 4

    def test_forget_drops_state(self):
        c = _mk_ctrl()
        for _ in range(5):
            c.observe("r1", proposed=4, accepted=0)
        assert c.gamma_for("r1") == 0
        c.forget("r1")
        assert "r1" not in c.snapshot()
        assert c.gamma_for("r1") == 4  # fresh optimistic start

    def test_zero_proposed_rounds_carry_no_evidence(self):
        """Classic-lane rounds (γ=0 dispatched) and empty n-gram lookups
        report proposed=0 — they must not drag the EWMA toward zero."""
        c = _mk_ctrl()
        for _ in range(50):
            c.observe("r1", proposed=0, accepted=0)
        assert c.gamma_for("r1") == 4

    def test_hysteresis_band_validated(self):
        with pytest.raises(ValueError, match="hysteresis"):
            _mk_ctrl(collapse_below=0.7, recover_above=0.3)

    def test_resolve_spec_adaptive_knob_rule(self, monkeypatch):
        """Explicit arg beats MTPU_SPEC_ADAPTIVE beats off (the
        MTPU_DECODE_STEPS knob rule, resolved once at engine build)."""
        from modal_examples_tpu.serving.spec_runtime import (
            SPEC_ADAPTIVE_ENV,
            resolve_spec_adaptive,
        )

        monkeypatch.delenv(SPEC_ADAPTIVE_ENV, raising=False)
        assert resolve_spec_adaptive(None) is False
        assert resolve_spec_adaptive(True) is True
        monkeypatch.setenv(SPEC_ADAPTIVE_ENV, "1")
        assert resolve_spec_adaptive(None) is True
        assert resolve_spec_adaptive(False) is False


# ---------------------------------------------------------------------------
# engine level: slow tier (compiles tiny models)
# ---------------------------------------------------------------------------

PROMPT = "the quick brown fox jumps over the lazy dog and naps in the sun"


def _mk_engine(jax, speculative=None, params=None, **kw):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    kw.setdefault("max_slots", 2)
    kw.setdefault("max_model_len", 128)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (16, 32))
    return LLMEngine(
        llama.LlamaConfig.tiny(), params=params, seed=0,
        speculative=speculative, **kw,
    )


@pytest.mark.slow
class TestEngineAdaptive:
    def test_hostile_draft_retreats_to_classic_fallbacks(self, jax_cpu):
        """The A/B the controller exists for, structurally: a random
        (unrelated) draft model yields near-chance acceptance, so with
        the controller ON the engine must (a) collapse the request's
        EWMA, (b) dispatch whole-round classic fallbacks instead of
        burning draft+verify flops, and (c) still be token-identical to
        the plain engine. The wall-clock half of the A/B (adaptive TPOT
        ≤ spec-off TPOT under this workload) runs where timing is
        controlled: bench.py ``tiny-spec-adaptive``, gated in benchdiff."""
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import SamplingParams

        plain = _mk_engine(jax_cpu)
        eng = _mk_engine(
            jax_cpu, params=plain.params,
            speculative=(llama.LlamaConfig.tiny(), 4), spec_adaptive=True,
        )
        try:
            assert eng.spec_adaptive is True
            sp = SamplingParams(max_tokens=40, temperature=0.0)
            want = plain.generate(PROMPT, sp)
            got = eng.generate(PROMPT, sp)
            assert got == want
            # near-chance acceptance over a 512-vocab: the controller
            # must have stopped paying for speculation
            assert eng.stats.acceptance_rate() < 0.6
            assert eng._spec_fallbacks > 0, (
                "collapse never produced a whole-round classic fallback"
            )
            assert eng.error_count == 0, eng.error_log
        finally:
            plain.stop()
            eng.stop()

    def test_adaptive_keeps_depth_when_draft_is_perfect(self, jax_cpu):
        """Self-draft (draft == target): acceptance ~1.0, so the
        controller must keep γ at full depth — adaptivity may only ever
        remove unprofitable speculation, never profitable."""
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg = llama.LlamaConfig.tiny()
        params0 = llama.init_params(jax_cpu.random.PRNGKey(0), cfg)
        eng = LLMEngine(
            cfg, params0, max_slots=2, max_model_len=128, page_size=8,
            prefill_buckets=(16, 32), seed=0,
            speculative=(cfg, 4), draft_params=params0, spec_adaptive=True,
        )
        try:
            eng.generate(
                PROMPT, SamplingParams(max_tokens=24, temperature=0.0)
            )
            assert eng.stats.acceptance_rate() > 0.95
            assert eng._spec_fallbacks == 0
            assert eng._spec_rounds > 0
            # tokens-per-dispatch is the win: γ=4 fully accepted → 5
            assert (
                eng._spec_round_tokens / eng._spec_rounds > 2.0
            ), (eng._spec_round_tokens, eng._spec_rounds)
        finally:
            eng.stop()

    def test_spec_depth_runtime_mutable_for_bench_ab(self, jax_cpu):
        """bench.py A/Bs fixed-vs-adaptive on ONE live engine by mutating
        ``spec_depth``/``spec_adaptive`` — γ=0 must behave classic (and
        stay token-identical) without a rebuild."""
        from modal_examples_tpu.serving import SamplingParams

        eng = _mk_engine(jax_cpu, speculative=("ngram", 4))
        try:
            sp = SamplingParams(max_tokens=16, temperature=0.0)
            want = eng.generate("one two one two one two", sp)
            rounds_before = eng._spec_rounds
            assert rounds_before > 0
            eng.spec_depth = 0  # spec OFF: every round is a fallback
            got = eng.generate("one two one two one two", sp)
            assert got == want
            assert eng._spec_rounds == rounds_before
            eng.spec_depth = eng.spec_gamma  # back ON
            got2 = eng.generate("one two one two one two", sp)
            assert got2 == want
            assert eng._spec_rounds > rounds_before
        finally:
            eng.stop()


@pytest.mark.slow
class TestSpecExactnessUnderFailover:
    """PR-12 × PR-20: the failover exactness contract holds on a
    SPECULATING engine — a checkpoint can only be cut at a harvest
    boundary (the PR-19 rule), so a resumed/migrated stream re-enters
    mid-speculation token-identically."""

    @pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
    def test_resume_mid_stream_token_identical(self, jax_cpu, kv_dtype):
        from modal_examples_tpu.serving import SamplingParams

        sp = SamplingParams(max_tokens=12, temperature=0.0)
        eng = _mk_engine(
            jax_cpu, speculative=("ngram", 4), kv_dtype=kv_dtype,
        )
        try:
            ref = eng.submit("one two one two one two", sp)
            ref_text = "".join(eng.stream(ref))
            ref_tokens = list(ref.generated_tokens)
            n = ref.n_generated
            assert eng._spec_rounds > 0  # the ref run really speculated
            for k in (1, n // 2, n - 1):
                req = eng.make_request("one two one two one two", sp)
                req.auto_seed = ref.auto_seed
                eng.submit_resumed(
                    req,
                    prompt_tokens=ref.prompt_tokens,
                    generated=ref_tokens[:k],
                    emitted_len=0,
                )
                out = "".join(eng.stream(req))
                assert req.generated_tokens == ref_tokens, (kv_dtype, k)
                assert out == ref_text, (kv_dtype, k)
            from modal_examples_tpu.faults.chaos import check_drained

            assert check_drained({"eng": eng}) == []
        finally:
            eng.stop()

    def test_migrate_mid_stream_token_identical(self, jax_cpu):
        import time

        from modal_examples_tpu.scheduling import EngineReplica
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.serving import failover as fo

        sp = SamplingParams(max_tokens=32, temperature=0.0)
        eng_a = _mk_engine(jax_cpu, speculative=("ngram", 4))
        eng_b = _mk_engine(
            jax_cpu, speculative=("ngram", 4), params=eng_a.params
        )
        rep_a = EngineReplica(eng_a, "spec-a", role="unified")
        rep_b = EngineReplica(eng_b, "spec-b", role="unified")
        try:
            ref = eng_b.submit("red blue red blue red blue", sp)
            ref_text = "".join(eng_b.stream(ref))
            ref_tokens = list(ref.generated_tokens)

            req = rep_a.submit("red blue red blue red blue", sp)
            pieces: list[str] = []
            t = threading.Thread(
                target=lambda: pieces.extend(eng_a.stream(req))
            )
            t.start()
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if len(req.generated_tokens) >= 5:
                    break
                time.sleep(0.005)
            assert len(req.generated_tokens) >= 5
            result = fo.migrate_request(rep_a, rep_b, req, chunk_bytes=512)
            assert result == "ok"
            t.join(timeout=120)
            assert not t.is_alive()
            assert req.generated_tokens == ref_tokens
            assert "".join(pieces) == ref_text
            # the adopted stream kept speculating on B (ngram index was
            # rebuilt from prompt+generated history at adoption)
            assert eng_b.stats.spec_proposed > 0
        finally:
            eng_a.stop()
            eng_b.stop()


@pytest.mark.slow
class TestSpecObservability:
    def test_gauges_and_trace_events_emitted(self, jax_cpu):
        """Declared⇔emitted, live: a speculating engine's gauge sweep
        must land the mtpu_spec_* series in the registry with real
        values (the static closure test only proves call sites exist)."""
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.utils.prometheus import parse_exposition
        from modal_examples_tpu.serving import SamplingParams

        eng = _mk_engine(jax_cpu, speculative=("ngram", 4))
        try:
            eng.generate(
                "one two one two one two",
                SamplingParams(max_tokens=16, temperature=0.0),
            )
            eng._metrics_wall = 0.0  # defeat the sweep throttle
            eng._refresh_gauges()
            from modal_examples_tpu.utils.prometheus import (
                default_registry,
            )

            exp = parse_exposition(default_registry.expose())
            assert exp.peak(C.SPEC_TOKENS_PER_DISPATCH) >= 1.0
            assert exp.peak(C.SPEC_ACCEPTANCE_RATE) > 0.0
        finally:
            eng.stop()
