"""Tests for bert/gpt/lora: embeddings semantics, SLM training step, LoRA
delta == merged-weight equivalence (the property that licenses on-the-fly
application during training and merged weights for serving)."""

import pytest

pytestmark = pytest.mark.slow  # heavyweight: excluded from the fast tier

import numpy as np


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


class TestBert:
    def test_embed_normalized_and_mask_sensitive(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import bert

        cfg = bert.BertConfig.tiny()
        params = bert.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 500)
        mask = jnp.ones((2, 16), jnp.int32).at[1, 8:].set(0)
        emb = bert.embed(params, tokens, mask, cfg)
        assert emb.shape == (2, cfg.dim)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(emb), axis=-1), 1.0, atol=1e-5
        )
        # padding must not affect the embedding: same row, garbage in pad area
        tokens2 = tokens.at[1, 8:].set(7)
        emb2 = bert.embed(params, tokens2, mask, cfg)
        np.testing.assert_allclose(
            np.asarray(emb[1]), np.asarray(emb2[1]), atol=1e-5
        )

    def test_hf_weight_roundtrip(self, jax, tmp_path):
        """Bit-exact export/import through HF BERT names (the bge loader)."""
        import numpy as np
        from safetensors.numpy import save_file

        from modal_examples_tpu.models import bert

        cfg = bert.BertConfig.tiny()
        params = bert.init_params(jax.random.PRNGKey(0), cfg)
        raw = {
            "embeddings.word_embeddings.weight": np.asarray(params["word_emb"]),
            "embeddings.position_embeddings.weight": np.asarray(params["pos_emb"]),
            "embeddings.token_type_embeddings.weight": np.asarray(params["type_emb"]),
            "embeddings.LayerNorm.weight": np.asarray(params["emb_norm_w"]),
            "embeddings.LayerNorm.bias": np.asarray(params["emb_norm_b"]),
        }
        mapping = {
            "wq": ("attention.self.query.weight", True),
            "bq": ("attention.self.query.bias", False),
            "wk": ("attention.self.key.weight", True),
            "bk": ("attention.self.key.bias", False),
            "wv": ("attention.self.value.weight", True),
            "bv": ("attention.self.value.bias", False),
            "wo": ("attention.output.dense.weight", True),
            "bo": ("attention.output.dense.bias", False),
            "attn_norm_w": ("attention.output.LayerNorm.weight", False),
            "attn_norm_b": ("attention.output.LayerNorm.bias", False),
            "fc_w": ("intermediate.dense.weight", True),
            "fc_b": ("intermediate.dense.bias", False),
            "proj_w": ("output.dense.weight", True),
            "proj_b": ("output.dense.bias", False),
            "mlp_norm_w": ("output.LayerNorm.weight", False),
            "mlp_norm_b": ("output.LayerNorm.bias", False),
        }
        for i in range(cfg.n_layers):
            for ours, (name, transpose) in mapping.items():
                arr = np.asarray(params["layers"][ours][i])
                raw[f"encoder.layer.{i}.{name}"] = np.ascontiguousarray(
                    arr.T if transpose else arr
                )
        save_file(raw, str(tmp_path / "model.safetensors"))
        loaded = bert.load_hf_weights(tmp_path, cfg, dtype=np.float32)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params,
            loaded,
        )

    def test_mean_pooling(self, jax):
        import dataclasses

        from modal_examples_tpu.models import bert

        cfg = dataclasses.replace(bert.BertConfig.tiny(), pooling="mean")
        params = bert.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, 500)
        emb = bert.embed(params, tokens, None, cfg)
        assert emb.shape == (1, cfg.dim)


class TestGPT:
    def test_forward_and_train_step(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import gpt
        from modal_examples_tpu.training import (
            Trainer, cross_entropy_loss, make_optimizer,
        )

        cfg = gpt.GPTConfig.tiny()
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size)
        logits = gpt.forward(params, tokens, cfg)
        assert logits.shape == (2, 64, cfg.vocab_size)

        def loss_fn(p, batch):
            lg = gpt.forward(p, batch["tokens"], cfg, attn_impl="xla")
            return cross_entropy_loss(lg[:, :-1], batch["tokens"][:, 1:])

        t = Trainer(loss_fn, make_optimizer(1e-2))
        state = t.init_state(params)
        first = None
        for _ in range(10):
            state, m = t.train_step(state, {"tokens": tokens})
            first = first or float(m["loss"])
        assert float(m["loss"]) < first

    def test_generate_shape(self, jax):
        from modal_examples_tpu.models import gpt

        cfg = gpt.GPTConfig.tiny()
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        import jax.numpy as jnp

        out = gpt.generate(
            params, cfg, jnp.array([1, 2, 3]), 8, jax.random.PRNGKey(2)
        )
        assert out.shape == (8,)

    def test_char_tokenizer_roundtrip(self):
        from modal_examples_tpu.models.gpt import CharTokenizer

        tok = CharTokenizer("hello world")
        assert tok.decode(tok.encode("hello")) == "hello"


class TestLoRA:
    def test_zero_init_is_identity(self, jax):
        from modal_examples_tpu.models import llama, lora

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        lcfg = lora.LoRAConfig(rank=4)
        adapters = lora.init_lora(jax.random.PRNGKey(1), params, lcfg)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 64), 0, 128)
        base = llama.forward(params, tokens, cfg)
        with_lora = llama.forward(
            params, tokens, cfg, lora=adapters, lora_scale=lcfg.scale
        )
        np.testing.assert_allclose(np.asarray(base), np.asarray(with_lora), atol=1e-5)

    def test_on_the_fly_equals_merged(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama, lora

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        lcfg = lora.LoRAConfig(rank=4)
        adapters = lora.init_lora(jax.random.PRNGKey(1), params, lcfg)
        # give b nonzero values so the delta is real
        adapters = jax.tree.map(
            lambda x: x + 0.01 if x.ndim == 3 else x, adapters
        )
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 64), 0, 128)
        on_fly = llama.forward(
            params, tokens, cfg, lora=adapters, lora_scale=lcfg.scale
        )
        merged = lora.merge(params, adapters, lcfg)
        merged_out = llama.forward(merged, tokens, cfg)
        np.testing.assert_allclose(
            np.asarray(on_fly), np.asarray(merged_out), atol=2e-4
        )

    def test_lora_training_only_touches_adapters(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama, lora
        from modal_examples_tpu.training import (
            Trainer, cross_entropy_loss, make_optimizer,
        )

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, dtype="float32",
        )
        base = llama.init_params(jax.random.PRNGKey(0), cfg)
        lcfg = lora.LoRAConfig(rank=4)
        adapters = lora.init_lora(jax.random.PRNGKey(1), base, lcfg)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 64), 0, 128)

        def loss_fn(adapters, batch):
            lg = llama.forward(
                base, batch["tokens"], cfg, attn_impl="xla",
                lora=adapters, lora_scale=lcfg.scale,
            )
            return cross_entropy_loss(lg[:, :-1], batch["tokens"][:, 1:])

        t = Trainer(loss_fn, make_optimizer(1e-2))
        state = t.init_state(adapters)
        first = None
        for _ in range(8):
            state, m = t.train_step(state, {"tokens": tokens})
            first = first or float(m["loss"])
        assert float(m["loss"]) < first
        # trainable params are tiny vs base
        assert lora.param_count(state.params) < 0.2 * sum(
            x.size for x in jax.tree.leaves(base)
        )
