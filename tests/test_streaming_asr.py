"""Websocket layer (RFC 6455 codec, handshake, gateway routing) and the
streaming transcriber (LocalAgreement commitment semantics) — the
reference's streaming-ASR tier (streaming_kyutai_stt.py et al.)."""

import threading

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


class TestFrameCodec:
    def test_masked_roundtrip_all_sizes(self):
        """Client-masked frames decode exactly at every length-encoding
        tier (7-bit, 16-bit, 64-bit)."""
        import socket

        from modal_examples_tpu.web.websocket import (
            OP_BINARY, WebSocket, build_masked_frame,
        )

        a, b = socket.socketpair()
        try:
            server = WebSocket(a)
            for size in (5, 200, 70_000):
                payload = bytes(range(256)) * (size // 256 + 1)
                payload = payload[:size]
                b.sendall(build_masked_frame(OP_BINARY, payload))
                kind, got = server.receive()
                assert kind == "binary" and got == payload, size
        finally:
            a.close()
            b.close()

    def test_unmasked_client_frame_rejected(self):
        import socket

        from modal_examples_tpu.web.websocket import (
            OP_TEXT, ConnectionClosed, WebSocket, build_frame,
        )

        a, b = socket.socketpair()
        try:
            server = WebSocket(a)
            b.sendall(build_frame(OP_TEXT, b"unmasked"))  # protocol error
            with pytest.raises(ConnectionClosed) as e:
                server.receive()
            assert e.value.code == 1002
        finally:
            a.close()
            b.close()

    def test_fragmented_message_reassembled(self):
        import socket
        import struct

        from modal_examples_tpu.web.websocket import (
            OP_CONT, OP_TEXT, WebSocket,
        )

        def masked(opcode, payload, fin):
            head = bytes([(0x80 if fin else 0) | opcode, 0x80 | len(payload)])
            mask = b"\x01\x02\x03\x04"
            body = bytes(
                c ^ mask[i % 4] for i, c in enumerate(payload)
            )
            return head + mask + body

        a, b = socket.socketpair()
        try:
            server = WebSocket(a)
            b.sendall(masked(OP_TEXT, b"hel", fin=False))
            b.sendall(masked(OP_CONT, b"lo", fin=True))
            assert server.receive() == ("text", b"hello")
        finally:
            a.close()
            b.close()

    def test_orphan_continuation_rejected(self):
        """ADVICE r4: an initial OP_CONT (no message in progress) must fail
        the connection (1002), not accumulate payload forever."""
        import socket

        from modal_examples_tpu.web.websocket import (
            OP_CONT, ConnectionClosed, WebSocket, build_masked_frame,
        )

        a, b = socket.socketpair()
        try:
            server = WebSocket(a)
            b.sendall(build_masked_frame(OP_CONT, b"orphan"))
            with pytest.raises(ConnectionClosed) as e:
                server.receive()
            assert e.value.code == 1002
        finally:
            a.close()
            b.close()

    def test_new_data_frame_inside_fragmented_message_rejected(self):
        import socket

        from modal_examples_tpu.web.websocket import (
            OP_TEXT, ConnectionClosed, WebSocket, build_masked_frame,
        )

        a, b = socket.socketpair()
        try:
            server = WebSocket(a)
            b.sendall(build_masked_frame(OP_TEXT, b"first", fin=False))
            b.sendall(build_masked_frame(OP_TEXT, b"second"))  # RFC 6455 §5.4
            with pytest.raises(ConnectionClosed) as e:
                server.receive()
            assert e.value.code == 1002
        finally:
            a.close()
            b.close()

    def test_oversized_message_closed_1009(self, monkeypatch):
        import socket

        from modal_examples_tpu.web.websocket import (
            OP_BINARY, ConnectionClosed, WebSocket, build_masked_frame,
        )

        monkeypatch.setattr(WebSocket, "MAX_MESSAGE_BYTES", 100)
        a, b = socket.socketpair()
        try:
            server = WebSocket(a)
            b.sendall(build_masked_frame(OP_BINARY, b"x" * 101))
            with pytest.raises(ConnectionClosed) as e:
                server.receive()
            assert e.value.code == 1009
        finally:
            a.close()
            b.close()

    def test_ping_answered_with_pong(self):
        import socket

        from modal_examples_tpu.web.websocket import (
            OP_PING, OP_PONG, OP_TEXT, WebSocket, build_masked_frame,
        )

        a, b = socket.socketpair()
        try:
            server = WebSocket(a)
            b.sendall(build_masked_frame(OP_PING, b"hb"))
            b.sendall(build_masked_frame(OP_TEXT, b"x"))
            assert server.receive() == ("text", b"x")  # ping handled inline
            # the pong went back to the client side
            client = WebSocket(b, client=True)
            opcode, fin, payload = client._read_frame()
            assert opcode == OP_PONG and payload == b"hb"
        finally:
            a.close()
            b.close()


class TestGatewayWebsocket:
    def test_echo_through_gateway(self, state_dir):
        import modal_examples_tpu as mtpu
        from modal_examples_tpu.web.gateway import Gateway
        from modal_examples_tpu.web.websocket import connect

        app = mtpu.App("ws-test-echo")

        @app.function()
        @mtpu.websocket_endpoint()
        def echo(ws, prefix: str = ">"):
            while True:
                kind, payload = ws.receive()
                if payload == b"quit":
                    ws.send_text("bye")
                    return
                ws.send_text(prefix + payload.decode())

        with app.run():
            gw = Gateway(app).start()
            host, port = gw.httpd.server_address[:2]
            ws = connect(host, port, "/echo?prefix=%23")
            ws.send_text("one")
            assert ws.receive() == ("text", b"#one")
            ws.send_text("quit")
            assert ws.receive() == ("text", b"bye")
            ws.close()
            gw.stop()

    def test_plain_get_rejected_with_426(self, state_dir):
        import json
        import urllib.error
        import urllib.request

        import modal_examples_tpu as mtpu
        from modal_examples_tpu.web.gateway import Gateway

        app = mtpu.App("ws-test-426")

        @app.function()
        @mtpu.websocket_endpoint()
        def sock(ws):
            pass

        with app.run():
            gw = Gateway(app).start()
            try:
                urllib.request.urlopen(f"{gw.base_url}/sock", timeout=10)
                assert False, "expected 426"
            except urllib.error.HTTPError as e:
                assert e.code == 426
                assert "upgrade" in json.load(e)["error"]
            finally:
                gw.stop()


@pytest.fixture(scope="module")
def transcriber_setup(jax):
    from modal_examples_tpu.models import whisper

    cfg = whisper.WhisperConfig.test_tiny()
    params = whisper.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _make(params, cfg, **kw):
    from modal_examples_tpu.serving.streaming_asr import StreamingTranscriber

    kw.setdefault("window_s", 2.0)
    kw.setdefault("hop_s", 0.5)
    kw.setdefault("max_tokens", 12)
    return StreamingTranscriber(params, cfg, bos_id=0, eos_id=1, **kw)


class TestStreamingTranscriber:
    def test_chunk_size_invariance(self, jax, transcriber_setup):
        """The final committed transcript must not depend on how the PCM
        was chunked on the way in."""
        from modal_examples_tpu.utils.audio import synth_tone_audio

        cfg, params = transcriber_setup
        audio = synth_tone_audio([440.0, 660.0], 3.0)
        finals = []
        for chunk in (1600, 4000, 16000):
            t = _make(params, cfg)
            for i in range(0, len(audio), chunk):
                t.feed(audio[i : i + chunk])
            finals.append(t.flush().committed_text)
        assert finals[0] == finals[1] == finals[2]
        assert finals[0]

    def test_committed_text_never_retracts(self, jax, transcriber_setup):
        """LocalAgreement contract: committed_text only ever grows by
        appending — earlier commitments are final."""
        from modal_examples_tpu.utils.audio import synth_tone_audio

        cfg, params = transcriber_setup
        audio = synth_tone_audio([440.0, 880.0], 3.0)
        t = _make(params, cfg)
        seen = ""
        for i in range(0, len(audio), 2000):
            r = t.feed(audio[i : i + 2000])
            if r is not None:
                assert r.committed_text.startswith(seen)
                seen = r.committed_text
        r = t.flush()
        assert r.committed_text.startswith(seen)

    def test_single_segment_flush_matches_offline(self, jax, transcriber_setup):
        """For audio shorter than one window, flush() must equal the
        offline transcription of the same (padded) audio — streaming adds
        no transcription error, only incremental delivery."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import whisper
        from modal_examples_tpu.utils.audio import (
            log_mel_spectrogram, synth_tone_audio,
        )

        cfg, params = transcriber_setup
        audio = synth_tone_audio([550.0], 1.5)
        t = _make(params, cfg)
        for i in range(0, len(audio), 4000):
            t.feed(audio[i : i + 4000])
        final = t.flush().committed_text

        padded = np.concatenate(
            [audio.astype(np.float32),
             np.zeros(t.window - len(audio), np.float32)]
        )
        mel = log_mel_spectrogram(padded, n_mels=cfg.n_mels)[None]
        toks = np.asarray(
            whisper.greedy_transcribe(
                params, jnp.asarray(mel), cfg, bos_id=0, eos_id=1,
                max_tokens=12,
            )
        )[0]
        want = []
        for x in toks.tolist():
            if x == 1:
                break
            want.append(x)
        assert final == "".join(chr(x) for x in want)
