"""Static tier: every module in the package byte-compiles and imports, the
jax-free layering invariant holds, and decorator kwargs can't be silently
dropped (the reference's typecheck/lint CI analog, SURVEY.md §4 — mypy isn't
in this image, so the checks are compileall + import + architectural rules)."""

import ast
import compileall
import importlib
import inspect
import pkgutil
import re
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

import modal_examples_tpu

PKG_ROOT = Path(modal_examples_tpu.__file__).parent
REPO_ROOT = PKG_ROOT.parent


def test_package_bytecompiles():
    assert compileall.compile_dir(
        str(PKG_ROOT), quiet=2, force=True
    ), "syntax errors in package"


def test_examples_bytecompile():
    assert compileall.compile_dir(
        str(REPO_ROOT / "examples"), quiet=2, force=True
    ), "syntax errors in examples"


def test_every_module_imports():
    failures = []
    for mod in pkgutil.walk_packages([str(PKG_ROOT)], "modal_examples_tpu."):
        if mod.name.endswith("__main__"):
            continue  # executes the CLI on import by design
        if "libmtpu_host" in mod.name:
            continue  # the raw .so is a ctypes library, not a Python module
        try:
            importlib.import_module(mod.name)
        except Exception as e:
            failures.append(f"{mod.name}: {type(e).__name__}: {e}")
    assert not failures, failures


def test_core_layer_is_jax_free():
    """The client/control-plane layer must never import jax (chip attach +
    multi-second import would leak into every CLI invocation)."""
    code = (
        "import sys\n"
        "import modal_examples_tpu\n"
        "import modal_examples_tpu.core.cli\n"
        "import modal_examples_tpu.core.executor\n"
        "import modal_examples_tpu.storage.volume\n"
        "assert 'jax' not in sys.modules, 'core layer imported jax'\n"
        "print('jax-free')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=60,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin", "PYTHONPATH": str(REPO_ROOT)},
    )
    assert out.returncode == 0 and "jax-free" in out.stdout, out.stderr


#: C-ABI / attribute-marker symbols that share the ``mtpu_`` prefix but are
#: not metric series (ctypes exports from the native host library, etc.)
_NON_METRIC_MTPU_PREFIXES = (
    "mtpu_host",
    "mtpu_alloc_",
    "mtpu_levenshtein",
    "mtpu_byte_encode",
)

#: token that looks like a metric name: ``mtpu_`` at a word start (the
#: lookbehind excludes the ``__mtpu_enter__``-style attribute markers)
_METRIC_TOKEN_RE = re.compile(r"(?<![A-Za-z0-9_])mtpu_[a-z0-9_]+")


def test_metric_names_all_declared_in_catalog():
    """Every ``mtpu_*`` metric name appearing ANYWHERE in the package —
    code, f-strings, comments, docstrings — must be declared in
    ``observability.catalog``. One module owns every name, so two spellings
    of one series or a phantom name in a comment can't drift past review."""
    from modal_examples_tpu.observability.catalog import ALL_METRIC_NAMES

    catalog_path = PKG_ROOT / "observability" / "catalog.py"
    undeclared = []
    for path in sorted(PKG_ROOT.rglob("*.py")):
        if path == catalog_path:
            continue
        for tok in _METRIC_TOKEN_RE.findall(path.read_text()):
            if tok.startswith(_NON_METRIC_MTPU_PREFIXES):
                continue
            # histogram child series reduce to their parent's name
            base = re.sub(r"_(bucket|sum|count)$", "", tok)
            if tok not in ALL_METRIC_NAMES and base not in ALL_METRIC_NAMES:
                undeclared.append(f"{path.relative_to(REPO_ROOT)}: {tok}")
    assert not undeclared, (
        "mtpu_* metric names not declared in observability/catalog.py "
        f"(add them there, or import the constant): {sorted(set(undeclared))}"
    )


def _const_str(node):
    """The literal str of an AST node, or None (f-strings, names, calls)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def test_metric_label_keys_declared_in_catalog():
    """Every *label key* passed to the registry emitters (``counter_inc`` /
    ``gauge_set`` / ``histogram_observe``) with a resolvable metric name and
    a dict-literal ``labels=`` must be declared for that series in
    ``observability.catalog``. The name guard above stops series-name drift;
    this stops **label-cardinality drift** — a call site growing an
    undeclared ``user_id`` label would explode series cardinality without
    any name changing. Dynamic names/labels (e.g. the exposition parser)
    are skipped: the guard is for declared-series call sites."""
    from modal_examples_tpu.observability import catalog

    # constant name -> series name, e.g. RETRIES_TOTAL -> mtpu_retries_total
    const_to_series = {
        attr: val
        for attr, val in vars(catalog).items()
        if isinstance(val, str) and val.startswith("mtpu_")
    }
    emitters = {"counter_inc", "gauge_set", "histogram_observe"}
    violations = []
    for path in sorted(PKG_ROOT.rglob("*.py")):
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in emitters
                and node.args
            ):
                continue
            # resolve the series name: str literal, C.NAME attribute, or a
            # bare imported catalog constant
            name_node = node.args[0]
            series = _const_str(name_node)
            if series is None and isinstance(name_node, ast.Attribute):
                series = const_to_series.get(name_node.attr)
            if series is None and isinstance(name_node, ast.Name):
                series = const_to_series.get(name_node.id)
            if series is None or series not in catalog.CATALOG:
                continue  # dynamic name (parser/merger internals)
            labels_node = next(
                (kw.value for kw in node.keywords if kw.arg == "labels"),
                None,
            )
            if not isinstance(labels_node, ast.Dict):
                continue  # no labels / passed through a variable
            declared = set(catalog.CATALOG[series]["labels"])
            for key_node in labels_node.keys:
                key = _const_str(key_node) if key_node is not None else None
                if key is None:
                    violations.append(
                        f"{path.relative_to(REPO_ROOT)}:{node.lineno}: "
                        f"{series} has a non-literal label key"
                    )
                elif key not in declared:
                    violations.append(
                        f"{path.relative_to(REPO_ROOT)}:{node.lineno}: "
                        f"label {key!r} not declared for {series} "
                        f"(declared: {sorted(declared)})"
                    )
    assert not violations, (
        "label keys not declared in observability/catalog.py "
        f"(add them to the series' labels list): {violations}"
    )


def test_scheduler_policies_implement_full_abc():
    """Every ``SchedulerPolicy`` subclass anywhere in the package must
    implement the FULL ABC — a policy missing ``remove``/``expired`` would
    silently leak aborted or deadline-expired requests, so partial policies
    are rejected here, not discovered at 3am. (The metric-name and
    label-key guards above already cover ``scheduling/`` series: they scan
    the whole package.)"""
    from modal_examples_tpu.scheduling.policy import SchedulerPolicy

    def walk(cls):
        for sub in cls.__subclasses__():
            yield sub
            yield from walk(sub)

    # import every module so subclasses defined anywhere in the package are
    # registered before we enumerate them
    for mod in pkgutil.walk_packages([str(PKG_ROOT)], "modal_examples_tpu."):
        if mod.name.endswith("__main__") or "libmtpu_host" in mod.name:
            continue
        try:
            importlib.import_module(mod.name)
        except Exception:
            pass  # import failures are test_every_module_imports' job
    partial = [
        f"{sub.__module__}.{sub.__qualname__}: missing "
        f"{sorted(sub.__abstractmethods__)}"
        for sub in walk(SchedulerPolicy)
        if getattr(sub, "__abstractmethods__", None)
    ]
    assert not partial, (
        f"SchedulerPolicy subclasses with abstract methods remaining "
        f"(implement the full ABC): {partial}"
    )


#: modules that consume the paged/dense KV cache arrays; every entry point
#: in them must handle BOTH cache forms (plain arrays and the int8 4-leaf
#: QuantizedKV pytree — docs/kv_cache.md)
_KV_CONSUMER_MODULES = (
    "ops/paged_attention.py",
    "ops/reference.py",
    "models/llama.py",
    "serving/tensor_parallel.py",
)

#: referencing any of these marks a function as quantized-cache-aware
_KV_QUANT_TOKENS = {
    "QuantizedKV", "is_quantized", "kv_gather", "kv_scatter", "kv_empty",
    "quantize_kv", "dequantize_kv", "kv_quant", "kv_dtype_name", "shard_kv",
}


def test_kv_cache_consumers_handle_quantized_pytree():
    """Every paged-attention entry point / cache consumer — any top-level
    function taking the page arrays (``k_pages``/``v_pages`` params, or the
    dense ``cache`` in tensor_parallel) — must handle the int8 4-leaf
    QuantizedKV cache: either it references a kv_quant helper directly, or
    it delegates to another checked consumer (transitive closure). A
    consumer that silently indexes plain arrays would make ``kv_dtype=
    "int8"`` crash (best case) or silently read garbage through a pytree
    leaf (worst) — the same unrepresentability treatment as the decorator-
    kwargs guard above. Raw Pallas kernels (``k_hbm``/``k_all_hbm``
    params) are exempt: their wrappers are the checked entry points."""
    funcs: dict[str, ast.FunctionDef] = {}
    consumers: list[str] = []
    for rel in _KV_CONSUMER_MODULES:
        tree = ast.parse((PKG_ROOT / rel).read_text())
        for node in tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            params = {
                a.arg for a in node.args.args + node.args.kwonlyargs
            }
            funcs[node.name] = node
            if {"k_pages", "v_pages"} & params or (
                rel.endswith("tensor_parallel.py") and "cache" in params
            ):
                consumers.append(node.name)

    def refs(fn: ast.FunctionDef) -> set[str]:
        out = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name):
                out.add(node.id)
            elif isinstance(node, ast.Attribute):
                out.add(node.attr)
        return out

    aware = {
        name for name, fn in funcs.items() if refs(fn) & _KV_QUANT_TOKENS
    }
    changed = True
    while changed:  # transitive: delegating to an aware consumer counts
        changed = False
        for name, fn in funcs.items():
            if name not in aware and refs(fn) & aware:
                aware.add(name)
                changed = True
    unaware = sorted(set(consumers) - aware)
    assert not unaware, (
        "KV-cache consumers that never branch on (or delegate to a handler "
        f"of) the quantized 4-leaf cache: {unaware} — use ops.kv_quant "
        "helpers (kv_gather/kv_scatter/is_quantized/...) so kv_dtype="
        "'int8' cannot silently hit an f32-only path"
    )
    # the guard must actually be guarding something
    assert len(consumers) >= 8, consumers


def test_disagg_wire_codec_covers_every_cache_pytree_leaf():
    """The disagg wire codec must enumerate EVERY device leaf of the
    ``PagedKVCache`` pytree and carry each one through
    extract -> serialize -> deserialize intact — for BOTH cache forms
    (2-leaf bf16, 4-leaf int8). This is the int8-scales lesson from PR 5
    made structural: a future 5th leaf (new scale layout, metadata plane)
    that the wire silently failed to ship would corrupt every migrated
    request; here it fails the suite instead."""
    import jax
    import numpy as np

    from modal_examples_tpu.serving.disagg.transport import (
        adopt_pages,
        deserialize_block,
        extract_pages,
        serialize_block,
        wire_leaves,
    )
    from modal_examples_tpu.serving.kv_cache import PagedKVCache

    def make(kv_dtype):
        cache = PagedKVCache.create(
            n_layers=1, n_kv_heads=1, head_dim=4, n_pages=4, page_size=2,
            kv_dtype=kv_dtype, prefer_native=False,
        )
        # distinguishable leaf contents, so a dropped leaf can't hide
        # behind matching zeros
        import jax.numpy as jnp

        flat, treedef = jax.tree_util.tree_flatten(cache)
        rng = np.random.default_rng(7)
        filled = jax.tree_util.tree_unflatten(
            treedef,
            [
                jnp.asarray(
                    rng.normal(size=leaf.shape).astype(np.float32)
                ).astype(leaf.dtype)
                for leaf in flat
            ],
        )
        cache.k_pages, cache.v_pages = filled.k_pages, filled.v_pages
        return cache

    for kv_dtype, expected_leaves in (("bfloat16", 2), ("int8", 4)):
        cache = make(kv_dtype)
        tree_leaves = jax.tree_util.tree_leaves(cache)
        named = wire_leaves(cache)
        assert len(tree_leaves) == expected_leaves, (
            f"{kv_dtype}: cache leaf count changed — update this guard AND "
            "audit every consumer (docs/kv_cache.md)"
        )
        assert len(named) == len(tree_leaves), (
            f"{kv_dtype}: wire codec enumerates {len(named)} leaves but the "
            f"cache pytree has {len(tree_leaves)} — a leaf is not shipped"
        )
        block = deserialize_block(
            serialize_block(extract_pages(cache, [1, 2]))
        )
        assert set(block.leaves) == {n for n, _ in named}, (
            f"{kv_dtype}: leaves lost in (de)serialization"
        )
        # the FULL round trip must reproduce every leaf on the receiving
        # cache too: adoption writing back only a hardcoded subset of
        # fields would ship a future leaf and then silently drop it
        dst = make(kv_dtype)
        adopt_pages(dst, block, [1, 2])
        for (name, src_leaf), (_, dst_leaf) in zip(
            wire_leaves(cache), wire_leaves(dst)
        ):
            assert np.array_equal(
                np.asarray(src_leaf[:, np.asarray([1, 2])]),
                np.asarray(dst_leaf[:, np.asarray([1, 2])]),
            ), f"{kv_dtype}: leaf {name} not adopted"


#: modules whose pallas-reachable PUBLIC entry points form the serving fast
#: path; the guard computes reachability from these files' own ASTs
_PALLAS_KERNEL_MODULES = ("ops/flash_attention.py", "ops/paged_attention.py")

#: serving-path modules that must reach Pallas ONLY through the
#: ops/sharded.py dispatch layer. models/layers.py (training attention) and
#: ops/ring_attention.py (its own shard_map wrapper) are deliberately not
#: listed: they are not under the engine's auto-partitioned serving jits.
_SHARDED_DISPATCH_SCOPE = ("models/llama.py", "serving",)


def _pallas_reachable_entry_points() -> set[str]:
    """Top-level functions of the kernel modules that (transitively within
    their module) execute a ``pl.pallas_call``."""
    entries: set[str] = set()
    for rel in _PALLAS_KERNEL_MODULES:
        tree = ast.parse((PKG_ROOT / rel).read_text())
        funcs = {
            n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)
        }

        def refs(fn):
            out = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Name):
                    out.add(node.id)
                elif isinstance(node, ast.Attribute):
                    out.add(node.attr)
            return out

        reach = {
            name for name, fn in funcs.items() if "pallas_call" in refs(fn)
        }
        changed = True
        while changed:
            changed = False
            for name, fn in funcs.items():
                if name not in reach and refs(fn) & reach:
                    reach.add(name)
                    changed = True
        entries |= {n for n in reach if not n.startswith("_")}
    return entries


def test_serving_path_reaches_pallas_only_through_sharded_dispatch():
    """No ``pallas_call`` may be reachable under the engine's
    auto-partitioned jits without a shard_map wrapper: a raw kernel under a
    sharded jit either fails to compile or forces a full-cache gather per
    device — exactly the failure the old engine-level mesh×pallas
    ValueError guarded against. Round 7 replaced that runtime guard with
    the ``ops/sharded.py`` dispatch layer (falls through single-chip,
    shard_maps over the kv-head axis under a mesh), so the rule becomes
    structural, like PR 5's 4-leaf-pytree guard: serving code
    (models/llama.py + serving/) must never reference a pallas-reachable
    kernel entry point directly — only its ``sharded_*`` dispatcher."""
    entries = _pallas_reachable_entry_points()
    # the guard must actually be guarding the fast-path surface
    assert {
        "flash_attention", "flash_attention_chunked",
        "paged_decode_attention", "paged_decode_attention_ragged",
        "scatter_kv_pages",
    } <= entries, entries

    # completeness: the dispatch layer covers every serving fast-path entry
    sharded_src = (PKG_ROOT / "ops" / "sharded.py").read_text()
    sharded_tree = ast.parse(sharded_src)
    dispatchers = {
        n.name for n in sharded_tree.body if isinstance(n, ast.FunctionDef)
    }
    sharded_refs = {
        node.id
        for node in ast.walk(sharded_tree)
        if isinstance(node, ast.Name)
    }
    uncovered = {
        e for e in entries
        if e in (
            "flash_attention", "flash_attention_chunked",
            "paged_decode_attention", "paged_decode_attention_ragged",
            "scatter_kv_pages",
        )
        and e not in sharded_refs
    }
    assert not uncovered, (
        f"serving fast-path kernels without a shard_map dispatcher in "
        f"ops/sharded.py: {sorted(uncovered)}"
    )

    # exclusivity: serving code references dispatchers, never raw kernels
    paths = []
    for scope in _SHARDED_DISPATCH_SCOPE:
        p = PKG_ROOT / scope
        paths += sorted(p.rglob("*.py")) if p.is_dir() else [p]
    violations = []
    for path in paths:
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            name = None
            if isinstance(node, ast.Name) and node.id in entries:
                name = node.id
            elif isinstance(node, ast.Attribute) and node.attr in entries:
                name = node.attr
            if name is not None:
                violations.append(
                    f"{path.relative_to(REPO_ROOT)}:{node.lineno}: {name}"
                )
    assert not violations, (
        "serving-path code references a pallas-reachable kernel entry "
        "point directly — route it through the ops.sharded dispatch layer "
        f"(sharded_* wrappers: {sorted(dispatchers)}) so it stays legal "
        f"under mesh= tensor parallelism: {violations}"
    )


#: the fault-injection gate's call-site convention: modules import
#: ``from ..faults import inject as _inject`` and call these entry points
#: with a string-literal point name (docs/faults.md)
_FAULT_GATE_FUNCS = {"fire", "check", "corrupt"}


def _fault_call_sites() -> dict[str, list[str]]:
    """point name -> ["path:line", ...] for every ``_inject.<gate>("…")``
    call in the package (the catalog's production call sites)."""
    sites: dict[str, list[str]] = {}
    inject_path = PKG_ROOT / "faults" / "inject.py"
    for path in sorted(PKG_ROOT.rglob("*.py")):
        if path == inject_path:
            continue
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _FAULT_GATE_FUNCS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "_inject"
                and node.args
            ):
                continue
            point = _const_str(node.args[0])
            where = f"{path.relative_to(REPO_ROOT)}:{node.lineno}"
            sites.setdefault(point or f"<non-literal @ {where}>", []).append(
                where
            )
    return sites


def test_fault_points_all_declared_and_all_wired():
    """Both directions of the fault catalog closure (docs/faults.md):
    (a) every ``_inject.fire/check/corrupt("…")`` call site in the package
    names a point declared in ``faults.inject.POINTS`` (no stringly-typed
    drift, no phantom points), and (b) every declared point has at least
    one live production call site — a dead injection point (wired out by a
    refactor but still cataloged) fails here instead of rotting. The
    dynamic half — the default chaos schedule actually FIRES every point —
    is tests/test_chaos.py."""
    from modal_examples_tpu.faults.inject import ALL_FAULT_POINTS

    sites = _fault_call_sites()
    non_literal = [k for k in sites if k.startswith("<non-literal")]
    assert not non_literal, (
        f"fault gate called with a non-literal point name: {non_literal}"
    )
    undeclared = {
        point: where
        for point, where in sites.items()
        if point not in ALL_FAULT_POINTS
    }
    assert not undeclared, (
        "fault points used but not declared in faults/inject.py POINTS: "
        f"{undeclared}"
    )
    unwired = sorted(ALL_FAULT_POINTS - set(sites))
    assert not unwired, (
        "fault points declared in faults/inject.py POINTS but never wired "
        f"into production code: {unwired}"
    )
    # the guard must actually be guarding the full catalog surface
    assert len(sites) >= 10, sites


def test_production_code_never_imports_the_chaos_driver():
    """Layering: production modules may import ``faults.inject`` (the
    zero-cost gate) but NEVER ``faults.chaos`` (the driver that builds
    fleets and injects failure on purpose) — a production import would put
    chaos machinery on the serving path. Tests, bench.py, and the CLI read
    the chaos journal/metrics instead of importing the driver."""
    offenders = []
    chaos_path = PKG_ROOT / "faults" / "chaos.py"
    for path in sorted(PKG_ROOT.rglob("*.py")):
        if path == chaos_path:
            continue
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            names = []
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.endswith("chaos"):
                    names = [mod]
                elif mod.endswith("faults") or mod == "":
                    names = [
                        a.name for a in node.names if a.name == "chaos"
                    ]
            if any("chaos" in n for n in names):
                offenders.append(
                    f"{path.relative_to(REPO_ROOT)}:{node.lineno}"
                )
    assert not offenders, (
        f"production modules importing faults.chaos: {offenders}"
    )


def test_production_code_never_imports_the_load_generator():
    """Layering (the faults.chaos rule applied to the fleet layer):
    production modules may import ``fleet.autoscaler`` (the closed-loop
    controller) but NEVER ``fleet.loadgen`` (the driver that synthesizes
    overload on purpose) — a production import would put traffic
    synthesis on the serving path. Tests, bench.py, and operator tooling
    import it explicitly."""
    offenders = []
    loadgen_path = PKG_ROOT / "fleet" / "loadgen.py"
    for path in sorted(PKG_ROOT.rglob("*.py")):
        if path == loadgen_path:
            continue
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            names = []
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.endswith("loadgen"):
                    names = [mod]
                elif mod.endswith("fleet") or mod == "":
                    names = [
                        a.name for a in node.names if a.name == "loadgen"
                    ]
            if any("loadgen" in n for n in names):
                offenders.append(
                    f"{path.relative_to(REPO_ROOT)}:{node.lineno}"
                )
    assert not offenders, (
        f"production modules importing fleet.loadgen: {offenders}"
    )


def test_fleet_series_declared_and_emitted():
    """Closure for the ``mtpu_fleet_*`` series, both directions: the
    package-wide name guard above already rejects an UNDECLARED fleet
    series; this adds the reverse — every declared ``mtpu_fleet_*``
    catalog constant must be referenced by a live emitter somewhere in
    the package (a series the autoscaler stopped emitting would otherwise
    rot in the catalog, the docs table, and the gateway payload)."""
    from modal_examples_tpu.observability import catalog

    fleet_consts = {
        attr: val
        for attr, val in vars(catalog).items()
        if isinstance(val, str) and val.startswith("mtpu_fleet_")
    }
    assert len(fleet_consts) >= 3, fleet_consts
    catalog_path = PKG_ROOT / "observability" / "catalog.py"
    unused = []
    for attr in fleet_consts:
        referenced = any(
            re.search(rf"\b{attr}\b", path.read_text())
            for path in sorted(PKG_ROOT.rglob("*.py"))
            if path != catalog_path
        )
        if not referenced:
            unused.append(attr)
    assert not unused, (
        "mtpu_fleet_* series declared in the catalog but never referenced "
        f"by an emitter/reader in the package: {unused}"
    )


def test_failover_series_declared_and_emitted():
    """Closure for the ``mtpu_failover_*`` / ``mtpu_migration_live_*``
    series, both directions (the fleet-series guard's pattern): the
    package-wide name guard already rejects an UNDECLARED series; this
    adds the reverse — every declared failover catalog constant must be
    referenced by a live emitter/reader, AND every failover recorder in
    observability/metrics.py must have a call site outside metrics.py
    (a recorder nothing calls means a series that silently stopped
    flowing to dashboards, docs, and the bench `failover` section)."""
    from modal_examples_tpu.observability import catalog

    consts = {
        attr: val
        for attr, val in vars(catalog).items()
        if isinstance(val, str)
        and val.startswith(("mtpu_failover_", "mtpu_migration_live_"))
    }
    assert len(consts) >= 4, consts
    catalog_path = PKG_ROOT / "observability" / "catalog.py"
    package_src = {
        path: path.read_text()
        for path in sorted(PKG_ROOT.rglob("*.py"))
        if path != catalog_path
    }
    unused = [
        attr for attr in consts
        if not any(
            re.search(rf"\b{attr}\b", src) for src in package_src.values()
        )
    ]
    assert not unused, (
        "failover series declared in the catalog but never referenced by "
        f"an emitter/reader in the package: {unused}"
    )
    metrics_path = PKG_ROOT / "observability" / "metrics.py"
    recorders = (
        "record_failover", "record_failover_takeover",
        "record_live_migration", "record_live_migration_seconds",
    )
    orphans = [
        fn for fn in recorders
        if not any(
            re.search(rf"\b{fn}\(", src)
            for path, src in package_src.items()
            if path != metrics_path
        )
    ]
    assert not orphans, (
        f"failover recorders with no call site outside metrics.py: {orphans}"
    )


def test_watchdog_series_declared_and_emitted():
    """Closure for the ``mtpu_watchdog_*`` series, both directions (the
    fleet/failover-series guard pattern): the package-wide name guard
    already rejects an UNDECLARED watchdog series; this adds the reverse —
    every declared watchdog catalog constant must be referenced by a live
    emitter/reader, AND every watchdog recorder in observability/metrics.py
    must have a call site outside metrics.py (a recorder nothing calls
    means a series that silently stopped flowing to `tpurun health`, the
    gateway `/health` view, and the bench `recovery` section)."""
    from modal_examples_tpu.observability import catalog

    consts = {
        attr: val
        for attr, val in vars(catalog).items()
        if isinstance(val, str) and val.startswith("mtpu_watchdog_")
    }
    assert len(consts) >= 4, consts
    catalog_path = PKG_ROOT / "observability" / "catalog.py"
    package_src = {
        path: path.read_text()
        for path in sorted(PKG_ROOT.rglob("*.py"))
        if path != catalog_path
    }
    unused = [
        attr for attr in consts
        if not any(
            re.search(rf"\b{attr}\b", src) for src in package_src.values()
        )
    ]
    assert not unused, (
        "watchdog series declared in the catalog but never referenced by "
        f"an emitter/reader in the package: {unused}"
    )
    metrics_path = PKG_ROOT / "observability" / "metrics.py"
    recorders = (
        "set_watchdog_state", "set_watchdog_progress_age",
        "record_watchdog_transition", "record_watchdog_recovery",
    )
    orphans = [
        fn for fn in recorders
        if not any(
            re.search(rf"\b{fn}\(", src)
            for path, src in package_src.items()
            if path != metrics_path
        )
    ]
    assert not orphans, (
        f"watchdog recorders with no call site outside metrics.py: {orphans}"
    )


def test_profiler_series_declared_and_emitted():
    """Closure for the hot-path profiler series (``mtpu_tick_phase_*``,
    ``mtpu_host_overhead_*``, ``mtpu_compile*``), both directions (the
    fleet/failover/watchdog-series guard pattern): every declared profiler
    catalog constant must be referenced by a live emitter/reader, AND every
    profiler recorder in observability/metrics.py must have a call site
    outside metrics.py (a recorder nothing calls means `tpurun profile`,
    the gateway ``/profile`` view, and the bench `overhead` section went
    quietly blind)."""
    from modal_examples_tpu.observability import catalog

    consts = {
        attr: val
        for attr, val in vars(catalog).items()
        if isinstance(val, str)
        and val.startswith(
            ("mtpu_tick_phase", "mtpu_host_overhead", "mtpu_compile")
        )
    }
    assert len(consts) >= 4, consts
    catalog_path = PKG_ROOT / "observability" / "catalog.py"
    package_src = {
        path: path.read_text()
        for path in sorted(PKG_ROOT.rglob("*.py"))
        if path != catalog_path
    }
    unused = [
        attr for attr in consts
        if not any(
            re.search(rf"\b{attr}\b", src) for src in package_src.values()
        )
    ]
    assert not unused, (
        "profiler series declared in the catalog but never referenced by "
        f"an emitter/reader in the package: {unused}"
    )
    metrics_path = PKG_ROOT / "observability" / "metrics.py"
    recorders = (
        "record_tick_phase", "set_host_overhead_ratio", "record_compile",
    )
    orphans = [
        fn for fn in recorders
        if not any(
            re.search(rf"\b{fn}\(", src)
            for path, src in package_src.items()
            if path != metrics_path
        )
    ]
    assert not orphans, (
        f"profiler recorders with no call site outside metrics.py: {orphans}"
    )


#: the engine's profiler mark helpers — THE call-site convention for tick
#: phase attribution (serving/engine.py `_tm`/`_tm_device`): a string-
#: literal phase name from catalog.TICK_PHASES at positional index 1
_TICK_MARK_FUNCS = {"_tm", "_tm_device"}


def test_tick_phase_names_declared_and_wired():
    """Both directions of the tick-phase taxonomy closure (the metric/
    fault/span-catalog discipline applied to profiler phases): (a) every
    ``_tm(tick, "...")`` / ``_tm_device(tick, "...")`` call in serving/
    names a ``catalog.TICK_PHASES`` member with a literal (no stringly
    drift — two spellings of one phase would silently split a series),
    (b) every declared phase has at least one live mark site (a phase the
    scheduler stopped marking fails here instead of rotting in dashboards
    and the BENCH overhead schema), and (c) serving code never calls a raw
    ``tick.mark(...)`` outside the two helpers — the PR-13 watermark-guard
    lesson applied to timing."""
    from modal_examples_tpu.observability.catalog import TICK_PHASES

    sites: dict[str, list[str]] = {}
    violations: list[str] = []
    for path in sorted((PKG_ROOT / "serving").rglob("*.py")):
        tree = ast.parse(path.read_text())
        # line ranges of the _tm/_tm_device helper bodies (their internal
        # tick.mark(phase) is the gate itself, not a bypass)
        helper_ranges = [
            (n.lineno, n.end_lineno)
            for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef) and n.name in _TICK_MARK_FUNCS
        ]
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            where = f"{path.relative_to(REPO_ROOT)}:{node.lineno}"
            if isinstance(fn, ast.Name) and fn.id in _TICK_MARK_FUNCS:
                phase = (
                    _const_str(node.args[1]) if len(node.args) > 1 else None
                )
                if phase is None:
                    violations.append(f"{where}: non-literal phase name")
                else:
                    sites.setdefault(phase, []).append(where)
            elif isinstance(fn, ast.Attribute) and fn.attr == "mark":
                inside_helper = any(
                    lo <= node.lineno <= hi for lo, hi in helper_ranges
                )
                if not inside_helper:
                    violations.append(
                        f"{where}: raw .mark() outside the _tm gate"
                    )
    assert not violations, violations
    undeclared = sorted(set(sites) - set(TICK_PHASES))
    assert not undeclared, (
        "tick phases marked but not declared in catalog.TICK_PHASES: "
        f"{undeclared}"
    )
    unwired = sorted(set(TICK_PHASES) - set(sites))
    assert not unwired, (
        "tick phases declared in catalog.TICK_PHASES but never marked in "
        f"serving/: {unwired}"
    )
    # the guard must actually be guarding the full taxonomy
    assert len(sites) >= 9, sites


#: (file, qualified function) pairs in serving/ that may call the raw
#: ``time.monotonic()`` — each justified. PHASE timing goes through the
#: profiler (`_tm` + catalog.TICK_PHASES, engine's injectable clock); the
#: survivors are wall-clock token telemetry (TTFT/TPOT are CLIENT-seat
#: numbers, not tick anatomy), gauge throttles, LRU stamps, and one-shot
#: boot/migration timers. Adding ad-hoc timing to serving code means
#: either routing it through the profiler or consciously editing this
#: list — the PR-13 watermark-guard lesson applied to timing.
_SERVING_MONOTONIC_ALLOWLIST = frozenset({
    ("serving/disagg/roles.py", "DisaggCoordinator._submit_disagg"),
    ("serving/disagg/roles.py", "Migration.__init__"),
    ("serving/engine.py", "EngineStats.tokens_per_second"),
    ("serving/engine.py", "LLMEngine._accept_token"),
    ("serving/engine.py", "LLMEngine._dispatch_block"),
    ("serving/engine.py", "LLMEngine._harvest_prefills"),
    ("serving/engine.py", "LLMEngine._prefill_group"),
    ("serving/engine.py", "LLMEngine._prefill_long"),
    ("serving/engine.py", "LLMEngine._prefill_sync_locked"),
    ("serving/engine.py", "LLMEngine._process_block"),
    ("serving/engine.py", "LLMEngine._refresh_gauges"),
    # the fused speculative round is a dispatch site like _dispatch_block:
    # same decode-stall watermark accounting, same raw-clock rationale
    ("serving/engine.py", "LLMEngine._spec_round"),
    ("serving/engine.py", "LLMEngine.submit_resumed"),
    ("serving/engine.py", "LLMEngine.warmup"),
    ("serving/failover.py", "migrate_request"),
    ("serving/failover.py", "resume_request"),
    ("serving/failover.py", "stream_with_failover"),
    ("serving/prefix_cache.py", "PrefixCache.acquire"),
    ("serving/prefix_cache.py", "PrefixCache.insert"),
    ("serving/prefix_cache.py", "_Node.__init__"),
})


def test_serving_monotonic_timing_is_allowlisted():
    """No ad-hoc ``time.monotonic()`` phase timing in serving/ outside the
    profiler API: every raw-clock call site must be on the frozen
    allowlist above (exact match both ways, so a REMOVED site prunes its
    entry too). New timing belongs in the profiler — `_tm` marks against
    the engine's injectable clock — where it lands in a cataloged series
    instead of a local variable someone printf-debugs once and deletes."""
    found = set()
    for path in sorted((PKG_ROOT / "serving").rglob("*.py")):
        tree = ast.parse(path.read_text())
        rel = str(path.relative_to(PKG_ROOT.parent / "modal_examples_tpu"))

        def walk(node, stack):
            for child in ast.iter_child_nodes(node):
                nstack = stack
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    nstack = stack + [child.name]
                if (
                    isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr == "monotonic"
                    and isinstance(child.func.value, ast.Name)
                    and child.func.value.id == "time"
                ):
                    found.add((rel, ".".join(stack) or "<module>"))
                walk(child, nstack)

        walk(tree, [])
    new_sites = found - _SERVING_MONOTONIC_ALLOWLIST
    assert not new_sites, (
        "new time.monotonic() call sites in serving/ — route phase timing "
        "through the profiler (_tm + catalog.TICK_PHASES) or consciously "
        f"extend the allowlist: {sorted(new_sites)}"
    )
    stale = _SERVING_MONOTONIC_ALLOWLIST - found
    assert not stale, (
        f"stale allowlist entries (site removed — prune them): {sorted(stale)}"
    )


#: the ONLY attributes production code may touch on a watermarks object
#: (serving/health.py): the note_* writers the owning threads call, and
#: nothing else — reads go through health.replica_snapshot/classify. A raw
#: timestamp poke (`eng.watermarks.last_tick_at`) would couple consumers to
#: the watermark representation and rot the moment the model evolves.
_WATERMARK_ALLOWED_ATTRS = {
    "note_start", "note_tick", "note_dispatch", "note_accept",
}


def test_production_reads_watermarks_only_through_health_api():
    """Both halves of the health-API boundary (docs/health.md):
    (a) outside serving/health.py, the only attribute access on a
    ``.watermarks`` object is a ``note_*`` write hook (the engine
    publishing progress) — never a raw field read, never ``snapshot``
    bypassing :func:`~modal_examples_tpu.serving.health.replica_snapshot`;
    (b) the transfer registry's internals (``transfers._active``) are
    touched nowhere outside health.py — producers and the watchdog go
    through begin/progress/end/request_abort/abort_requested/stalled/
    snapshot."""
    health_path = PKG_ROOT / "serving" / "health.py"
    violations = []
    for path in sorted(PKG_ROOT.rglob("*.py")):
        if path == health_path:
            continue
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute):
                continue
            # X.watermarks.<attr>: <attr> must be an allowed note_* hook
            val = node.value
            if (
                isinstance(val, ast.Attribute)
                and val.attr == "watermarks"
                and node.attr not in _WATERMARK_ALLOWED_ATTRS
            ):
                violations.append(
                    f"{path.relative_to(REPO_ROOT)}:{node.lineno}: "
                    f".watermarks.{node.attr} (use serving.health."
                    "replica_snapshot)"
                )
            # <transfers object>._active / other privates
            if (
                node.attr.startswith("_")
                and isinstance(val, (ast.Name, ast.Attribute))
                and (
                    getattr(val, "id", None) or getattr(val, "attr", None)
                )
                in ("transfers", "_transfer_watermarks", "_twm")
            ):
                violations.append(
                    f"{path.relative_to(REPO_ROOT)}:{node.lineno}: "
                    f"transfer-registry private {node.attr}"
                )
    assert not violations, (
        "production code pokes watermark internals instead of the health "
        f"API: {violations}"
    )


def test_wire_envelope_decode_state_leg_is_additive():
    """MTKV1 compat guard (docs/failover.md): the live-migration
    decode-state leg must be PURELY ADDITIVE meta — magic/layout
    unchanged, a plain PR-6 first-token block still decodes, and an
    extended block's PR-6 fields read identically with the leg present.
    A byte-layout change here would strand every cross-version migration
    mid-fleet-upgrade."""
    import numpy as np

    from modal_examples_tpu.serving.disagg import transport as T

    assert T._MAGIC == b"MTKV1\n", (
        "wire magic changed: bump breaks rolling-upgrade migrations — "
        "the decode-state leg was designed to avoid exactly this"
    )
    leaves = {"k_pages": np.zeros((1, 2, 2, 1, 4), np.float32)}
    plain = T.PageBlock(
        leaves=dict(leaves), page_size=2, kv_dtype="float32",
        meta={"position": 4, "first_token": 9},
    )
    out_plain = T.deserialize_block(T.serialize_block(plain))
    assert "resume" not in out_plain.meta
    assert out_plain.meta["position"] == 4
    extended = T.PageBlock(
        leaves=dict(leaves), page_size=2, kv_dtype="float32",
        meta={
            "position": 4,
            "first_token": 9,
            "resume": {"generated": [9, 9], "emitted_len": 1},
        },
    )
    out_ext = T.deserialize_block(T.serialize_block(extended))
    # the PR-6 fields a leg-unaware receiver reads are byte-identical
    assert out_ext.meta["position"] == out_plain.meta["position"]
    assert out_ext.meta["first_token"] == out_plain.meta["first_token"]
    assert out_ext.meta["resume"] == {"generated": [9, 9], "emitted_len": 1}
    # and the leg never touches the binary framing: same leaf payloads
    assert np.array_equal(
        out_ext.leaves["k_pages"], out_plain.leaves["k_pages"]
    )


def test_disabled_fault_gate_is_structurally_a_no_op():
    """The gate's zero-cost contract, pinned at the AST level: ``fire``'s
    FIRST statement must be the ``_active_plan is None -> return False``
    fast path — nothing (no counter, no metric, no dict touch) may run
    before it. The behavioral half lives in tests/test_faults.py."""
    inject_src = (PKG_ROOT / "faults" / "inject.py").read_text()
    tree = ast.parse(inject_src)
    fire = next(
        n for n in tree.body
        if isinstance(n, ast.FunctionDef) and n.name == "fire"
    )
    body = [n for n in fire.body if not (
        isinstance(n, ast.Expr) and isinstance(n.value, ast.Constant)
    )]  # skip the docstring
    first = body[0]
    assert isinstance(first, ast.If), "fire() must open with the None guard"
    test = first.test
    assert (
        isinstance(test, ast.Compare)
        and isinstance(test.left, ast.Name)
        and "plan" in test.left.id
        and isinstance(test.ops[0], ast.Is)
        and isinstance(test.comparators[0], ast.Constant)
        and test.comparators[0].value is None
    ), "fire() must test `<plan global> is None` first"
    ret = first.body[0]
    assert (
        isinstance(ret, ast.Return)
        and isinstance(ret.value, ast.Constant)
        and ret.value.value is False
    ), "the disabled path must immediately `return False`"


#: the request-tracer's call-site convention (docs/observability.md):
#: modules import ``from ..observability import reqtrace as _rt`` and mint
#: spans/events through these helpers with a string-literal span name at
#: the given positional index
_SPAN_GATE_FUNCS = {
    "begin": 1, "record_span": 1, "event": 1,
    "begin_ambient": 0, "ambient_event": 0,
}
#: helper kwargs that are plumbing, not span attributes
_SPAN_CONTROL_KWARGS = {"parent", "store", "start", "end", "status"}


def _span_call_sites():
    """span name -> ["path:line", ...] plus attr-key violations, for every
    ``_rt.<helper>("name", attr=...)`` call in the package (and the bare
    helper calls inside reqtrace.py itself)."""
    from modal_examples_tpu.observability.catalog import SPAN_CATALOG

    reqtrace_path = PKG_ROOT / "observability" / "reqtrace.py"
    sites: dict[str, list[str]] = {}
    violations: list[str] = []
    for path in sorted(PKG_ROOT.rglob("*.py")):
        tree = ast.parse(path.read_text())
        in_reqtrace = path == reqtrace_path
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fname = None
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "_rt"
                and fn.attr in _SPAN_GATE_FUNCS
            ):
                fname = fn.attr
            elif (
                in_reqtrace
                and isinstance(fn, ast.Name)
                and fn.id in _SPAN_GATE_FUNCS
            ):
                fname = fn.id
            if fname is None:
                continue
            idx = _SPAN_GATE_FUNCS[fname]
            where = f"{path.relative_to(REPO_ROOT)}:{node.lineno}"
            name_node = node.args[idx] if len(node.args) > idx else None
            if (
                in_reqtrace
                and isinstance(name_node, ast.Name)
                and name_node.id == "name"
            ):
                continue  # a helper delegating to another (name variable)
            name = _const_str(name_node) if name_node is not None else None
            if name is None:
                violations.append(f"{where}: non-literal span name")
                continue
            sites.setdefault(name, []).append(where)
            declared = set(SPAN_CATALOG.get(name, {}).get("attrs", ()))
            for kw in node.keywords:
                if kw.arg is None or kw.arg in _SPAN_CONTROL_KWARGS:
                    continue  # **kwargs / plumbing
                if kw.arg not in declared:
                    violations.append(
                        f"{where}: attr {kw.arg!r} not declared for span "
                        f"{name!r} (declared: {sorted(declared)})"
                    )
    return sites, violations


def test_span_names_and_attr_keys_declared_in_catalog():
    """Both directions of the request-span schema closure, the metric-
    catalog discipline applied to the distributed tracer: (a) every span
    minted through the reqtrace helpers names a ``SPAN_CATALOG`` entry and
    passes only its declared attribute keys (so `tpurun explain` and the
    Perfetto export parse a schema that cannot drift call-site by
    call-site), and (b) every cataloged span name has at least one live
    call site — a span wired out by a refactor fails here instead of
    rotting in the catalog."""
    from modal_examples_tpu.observability.catalog import ALL_SPAN_NAMES

    sites, violations = _span_call_sites()
    assert not violations, violations
    undeclared = sorted(set(sites) - ALL_SPAN_NAMES)
    assert not undeclared, (
        f"span names minted but not declared in catalog.SPAN_CATALOG: "
        f"{undeclared}"
    )
    # the root span is minted by start_request_trace via the ROOT_SPAN
    # constant, not a helper call with a literal — count it as wired after
    # verifying the constant still says so
    reqtrace_src = (PKG_ROOT / "observability" / "reqtrace.py").read_text()
    m = re.search(r'^ROOT_SPAN = "([a-z_]+)"', reqtrace_src, re.M)
    assert m is not None, "reqtrace.ROOT_SPAN constant is gone"
    wired = set(sites) | {m.group(1)}
    unwired = sorted(ALL_SPAN_NAMES - wired)
    assert not unwired, (
        "span names declared in catalog.SPAN_CATALOG but never minted "
        f"anywhere in the package: {unwired}"
    )
    # the guard must actually be guarding the full span surface
    assert len(sites) >= 10, sorted(sites)


#: serving-fleet modules that must mint spans ONLY through the reqtrace
#: layer: a raw Span/contextvar-span here would float outside any request
#: context — unparented, store-less, invisible to `tpurun explain`
_REQTRACE_ONLY_SCOPE = ("serving", "scheduling", "faults")


def test_serving_code_never_mints_raw_spans():
    """Serving/scheduling/faults code may not import the raw span layer
    (``observability.trace``: ``Span``, the contextvar ``span`` manager,
    ``set_context``, ``default_store``) — request-path spans go through
    :mod:`observability.reqtrace`, which anchors every span to a request
    context, registers it for the no-dangling-span sweep, and records it
    to the owning replica's store. The executor call tracer (core/) keeps
    its direct access; this scope is the REQUEST side."""
    banned_names = {
        "Span", "TraceContext", "set_context", "span", "default_store",
        "current_context",
    }
    offenders = []
    for scope in _REQTRACE_ONLY_SCOPE:
        for path in sorted((PKG_ROOT / scope).rglob("*.py")):
            tree = ast.parse(path.read_text())
            for node in ast.walk(tree):
                bad = None
                if isinstance(node, ast.Import):
                    for a in node.names:
                        if a.name.endswith("observability.trace"):
                            bad = a.name
                elif isinstance(node, ast.ImportFrom):
                    mod = node.module or ""
                    if mod.endswith("observability.trace"):
                        bad = mod
                    elif mod.endswith("observability"):
                        hit = banned_names & {a.name for a in node.names}
                        if hit:
                            bad = f"{mod} ({sorted(hit)})"
                if bad is not None:
                    offenders.append(
                        f"{path.relative_to(REPO_ROOT)}:{node.lineno}: {bad}"
                    )
    assert not offenders, (
        "serving-path code imports the raw span layer — mint request "
        f"spans through observability.reqtrace instead: {offenders}"
    )


def test_no_bare_print_in_framework_code():
    """Framework code under ``core/`` and ``serving/`` must not ``print()``:
    diagnostics go through ``utils.log.get_logger`` so they carry a level
    and component and can be silenced/redirected. ``core/cli.py`` is exempt
    — its stdout IS the product."""
    exempt = {PKG_ROOT / "core" / "cli.py"}
    offenders = []
    for sub in ("core", "serving"):
        for path in sorted((PKG_ROOT / sub).rglob("*.py")):
            if path in exempt:
                continue
            tree = ast.parse(path.read_text())
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                ):
                    offenders.append(
                        f"{path.relative_to(REPO_ROOT)}:{node.lineno}"
                    )
    assert not offenders, (
        f"bare print() in framework code (use utils.log): {offenders}"
    )


@pytest.mark.parametrize(
    "decorator",
    [modal_examples_tpu.App.function, modal_examples_tpu.App.cls],
    ids=["app.function", "app.cls"],
)
def test_decorator_kwargs_never_silently_dropped(decorator):
    """Every keyword `@app.function`/`@app.cls` accepts must be *used* in the
    decorator body — forwarded into FunctionSpec, transformed first, or
    explicitly rejected (like gpu=). An accepted-but-unreferenced parameter
    is the `enable_memory_snapshot` bug class: the user sets it, the spec
    never sees it, nothing fails. This guard makes that class unrepresentable.
    """
    src = textwrap.dedent(inspect.getsource(decorator))
    fn = ast.parse(src).body[0]
    accepted = {a.arg for a in fn.args.args + fn.args.kwonlyargs} - {"self"}
    used = {
        node.id
        for node in ast.walk(fn)
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
    }
    dropped = accepted - used
    assert not dropped, (
        f"{decorator.__qualname__} accepts but never reads {sorted(dropped)}; "
        f"forward them into FunctionSpec or reject them explicitly"
    )


@pytest.mark.parametrize(
    "decorator",
    [modal_examples_tpu.App.function, modal_examples_tpu.App.cls],
    ids=["app.function", "app.cls"],
)
def test_decorator_kwargs_exist_on_function_spec(decorator):
    """Scheduling kwargs shared by both decorators should map to a
    FunctionSpec field of the same name, so the forwarding the guard above
    enforces has somewhere real to land. (Params that are transformed or
    consumed client-side are listed as such.)"""
    from modal_examples_tpu.core.function import FunctionSpec

    transformed_or_consumed = {
        "gpu",  # explicitly rejected: TPU-native framework
        "name",  # becomes the spec tag
        "tpu",  # parse_tpu_request -> spec.tpu
        "retries",  # normalize_retries -> spec.retries
    }
    spec_fields = {f.name for f in __import__("dataclasses").fields(FunctionSpec)}
    src = textwrap.dedent(inspect.getsource(decorator))
    fn = ast.parse(src).body[0]
    accepted = {a.arg for a in fn.args.args + fn.args.kwonlyargs} - {"self"}
    unmapped = accepted - spec_fields - transformed_or_consumed
    assert not unmapped, (
        f"{decorator.__qualname__} kwargs with no FunctionSpec field: "
        f"{sorted(unmapped)}"
    )


def test_flight_recorder_series_declared_and_emitted():
    """Closure for the flight-recorder series (``mtpu_tsdb_*``,
    ``mtpu_alerts_*``, ``mtpu_incidents_*``), both directions (the
    fleet/failover/watchdog/profiler-series guard pattern): every declared
    flight-recorder catalog constant must be referenced by a live
    emitter/reader, AND every flight-recorder recorder in
    observability/metrics.py must have a call site outside metrics.py (a
    recorder nothing calls means the tsdb/alerts/incident surfaces went
    quietly blind)."""
    from modal_examples_tpu.observability import catalog

    consts = {
        attr: val
        for attr, val in vars(catalog).items()
        if isinstance(val, str)
        and val.startswith(("mtpu_tsdb_", "mtpu_alerts_", "mtpu_incidents_"))
    }
    assert len(consts) >= 7, consts
    catalog_path = PKG_ROOT / "observability" / "catalog.py"
    package_src = {
        path: path.read_text()
        for path in sorted(PKG_ROOT.rglob("*.py"))
        if path != catalog_path
    }
    unused = [
        attr for attr in consts
        if not any(
            re.search(rf"\b{attr}\b", src) for src in package_src.values()
        )
    ]
    assert not unused, (
        "flight-recorder series declared in the catalog but never "
        f"referenced by an emitter/reader in the package: {unused}"
    )
    metrics_path = PKG_ROOT / "observability" / "metrics.py"
    recorders = (
        "record_tsdb_sample", "record_tsdb_rotation",
        "set_alert_active", "record_alert_fired",
        "record_incident_captured",
    )
    orphans = [
        fn for fn in recorders
        if not any(
            re.search(rf"\b{fn}\(", src)
            for path, src in package_src.items()
            if path != metrics_path
        )
    ]
    assert not orphans, (
        "flight-recorder recorders with no call site outside metrics.py: "
        f"{orphans}"
    )


def test_alert_rules_reference_only_cataloged_series():
    """Every series an AlertRule reads — the rule's own series AND its
    absence guard — must be declared in observability/catalog.py. A rule
    watching a misspelled or refactored-away series would never fire and
    never error; this guard turns that silence into a test failure."""
    from modal_examples_tpu.observability import catalog
    from modal_examples_tpu.observability.alerts import (
        DEFAULT_RULES,
        rule_series,
    )

    assert len(DEFAULT_RULES) >= 5
    unknown = {
        rule.name: [
            s for s in rule_series(rule) if s not in catalog.CATALOG
        ]
        for rule in DEFAULT_RULES
    }
    unknown = {name: missing for name, missing in unknown.items() if missing}
    assert not unknown, (
        f"alert rules referencing series missing from the catalog: {unknown}"
    )
    # incident triggers are a catalog label set the same way: the capture
    # chokepoint validates against TRIGGERS, so the catalog help text and
    # the code can't drift
    from modal_examples_tpu.observability.incident import TRIGGERS

    help_text = catalog.CATALOG[catalog.INCIDENTS_CAPTURED_TOTAL]["help"]
    for trigger in TRIGGERS:
        assert trigger in help_text, (
            f"incident trigger {trigger!r} missing from the "
            "mtpu_incidents_captured_total catalog help"
        )


def test_journals_resolve_only_through_named_journal():
    """One table owns every journal file name (observability/journal.py
    JOURNALS): production code must resolve journals through
    named_journal()/journal_path(), never by constructing DecisionJournal
    directly or hand-building a ``<state_dir>/x.jsonl`` path — the drift
    this PR collapsed (five subsystems each spelling their own
    bounded-JSONL append) stays collapsed."""
    journal_path = PKG_ROOT / "observability" / "journal.py"
    offenders = []
    for path in sorted(PKG_ROOT.rglob("*.py")):
        if path == journal_path:
            continue
        src = path.read_text()
        if re.search(r"\bDecisionJournal\s*\(", src):
            offenders.append(str(path.relative_to(PKG_ROOT)))
    assert not offenders, (
        "DecisionJournal constructed outside observability/journal.py "
        f"(use named_journal): {offenders}"
    )
    # the JOURNALS table must cover every journal the package writes: a
    # new `<state_dir>/*.jsonl` literal outside the table is drift
    from modal_examples_tpu.observability.journal import JOURNALS

    table_files = set(JOURNALS.values())
    for path in sorted(PKG_ROOT.rglob("*.py")):
        if path == journal_path:
            continue
        for name in re.findall(r"state_dir\(\)\s*/\s*\"(\w+\.jsonl)\"",
                               path.read_text()):
            assert name in table_files, (
                f"{path.relative_to(PKG_ROOT)} hand-builds journal path "
                f"{name!r} outside the JOURNALS table"
            )


def test_prefix_store_series_declared_and_emitted():
    """Closure for the ``mtpu_prefix_store_*`` series, both directions
    (the fleet-series guard's pattern): the package-wide name guard
    already rejects an UNDECLARED series; this adds the reverse — every
    declared prefix-store catalog constant must be referenced by a live
    emitter/reader, AND every prefix-store recorder in
    observability/metrics.py must have a call site outside metrics.py
    (a recorder nothing calls means a series that silently stopped
    flowing to the CLI, gateway, and docs table)."""
    from modal_examples_tpu.observability import catalog

    consts = {
        attr: val
        for attr, val in vars(catalog).items()
        if isinstance(val, str) and val.startswith("mtpu_prefix_store_")
    }
    assert len(consts) >= 5, consts
    catalog_path = PKG_ROOT / "observability" / "catalog.py"
    package_src = {
        path: path.read_text()
        for path in sorted(PKG_ROOT.rglob("*.py"))
        if path != catalog_path
    }
    unused = [
        attr for attr in consts
        if not any(
            re.search(rf"\b{attr}\b", src) for src in package_src.values()
        )
    ]
    assert not unused, (
        "prefix-store series declared in the catalog but never referenced "
        f"by an emitter/reader in the package: {unused}"
    )
    metrics_path = PKG_ROOT / "observability" / "metrics.py"
    recorders = (
        "record_prefix_store_hit", "record_prefix_store_miss",
        "set_prefix_store_occupancy", "record_prefix_store_takeover",
    )
    orphans = [
        fn for fn in recorders
        if not any(
            re.search(rf"\b{fn}\(", src)
            for path, src in package_src.items()
            if path != metrics_path
        )
    ]
    assert not orphans, (
        f"prefix-store recorders with no call site outside metrics.py: "
        f"{orphans}"
    )


def test_usage_series_declared_and_emitted():
    """Closure for the usage/roofline series (``mtpu_usage_*``,
    ``mtpu_mfu``, ``mtpu_hbm_bw_util``, ``mtpu_achieved_tflops``), both
    directions (the fleet/failover/watchdog-series guard pattern): every
    declared catalog constant must be referenced by a live emitter/reader,
    AND every usage recorder in observability/metrics.py must have a call
    site outside metrics.py (a recorder nothing calls means per-tenant
    billing or the roofline position silently stopped flowing to `tpurun
    usage`, the gateway `/usage` view, and the bench `utilization`
    section)."""
    from modal_examples_tpu.observability import catalog

    roofline = {"mtpu_mfu", "mtpu_hbm_bw_util", "mtpu_achieved_tflops"}
    consts = {
        attr: val
        for attr, val in vars(catalog).items()
        if isinstance(val, str)
        and (val.startswith("mtpu_usage_") or val in roofline)
    }
    assert len(consts) >= 8, consts
    catalog_path = PKG_ROOT / "observability" / "catalog.py"
    package_src = {
        path: path.read_text()
        for path in sorted(PKG_ROOT.rglob("*.py"))
        if path != catalog_path
    }
    unused = [
        attr for attr in consts
        if not any(
            re.search(rf"\b{attr}\b", src) for src in package_src.values()
        )
    ]
    assert not unused, (
        "usage/roofline series declared in the catalog but never "
        f"referenced by an emitter/reader in the package: {unused}"
    )
    metrics_path = PKG_ROOT / "observability" / "metrics.py"
    recorders = (
        "set_roofline", "record_usage_tokens", "record_usage_seconds",
        "record_usage_shed",
    )
    orphans = [
        fn for fn in recorders
        if not any(
            re.search(rf"\b{fn}\(", src)
            for path, src in package_src.items()
            if path != metrics_path
        )
    ]
    assert not orphans, (
        f"usage recorders with no call site outside metrics.py: {orphans}"
    )


def test_canary_series_declared_and_emitted():
    """Closure for the correctness-canary series (``mtpu_canary_*``),
    both directions (the usage-series guard pattern): every declared
    catalog constant must be referenced by a live emitter/reader, AND
    every canary recorder in observability/metrics.py must have a call
    site outside metrics.py — a recorder nothing calls means the drift
    sentinel silently stopped flowing to `tpurun canary`, the gateway
    `/canary` view, and the `canary_drift` alert rule."""
    from modal_examples_tpu.observability import catalog

    consts = {
        attr: val
        for attr, val in vars(catalog).items()
        if isinstance(val, str) and val.startswith("mtpu_canary_")
    }
    assert len(consts) >= 7, consts
    catalog_path = PKG_ROOT / "observability" / "catalog.py"
    package_src = {
        path: path.read_text()
        for path in sorted(PKG_ROOT.rglob("*.py"))
        if path != catalog_path
    }
    unused = [
        attr for attr in consts
        if not any(
            re.search(rf"\b{attr}\b", src) for src in package_src.values()
        )
    ]
    assert not unused, (
        "canary series declared in the catalog but never referenced by "
        f"an emitter/reader in the package: {unused}"
    )
    metrics_path = PKG_ROOT / "observability" / "metrics.py"
    recorders = (
        "record_canary_probe", "record_canary_drift",
        "record_canary_latency", "record_canary_tokens",
        "set_canary_failing",
    )
    orphans = [
        fn for fn in recorders
        if not any(
            re.search(rf"\b{fn}\(", src)
            for path, src in package_src.items()
            if path != metrics_path
        )
    ]
    assert not orphans, (
        f"canary recorders with no call site outside metrics.py: {orphans}"
    )


def test_multistep_series_declared_and_emitted():
    """Closure for the macro-step decode series (``mtpu_multistep_*``),
    both directions (the canary-series guard pattern): every declared
    catalog constant must be referenced by a live emitter/reader, AND
    every multistep recorder in observability/metrics.py must have a call
    site outside metrics.py — a recorder nothing calls means the
    tokens-per-dispatch A/B the bench gates on silently reads zeros."""
    from modal_examples_tpu.observability import catalog

    consts = {
        attr: val
        for attr, val in vars(catalog).items()
        if isinstance(val, str) and val.startswith("mtpu_multistep_")
    }
    assert len(consts) >= 6, consts
    catalog_path = PKG_ROOT / "observability" / "catalog.py"
    package_src = {
        path: path.read_text()
        for path in sorted(PKG_ROOT.rglob("*.py"))
        if path != catalog_path
    }
    unused = [
        attr for attr in consts
        if not any(
            re.search(rf"\b{attr}\b", src) for src in package_src.values()
        )
    ]
    assert not unused, (
        "multistep series declared in the catalog but never referenced by "
        f"an emitter/reader in the package: {unused}"
    )
    metrics_path = PKG_ROOT / "observability" / "metrics.py"
    recorders = ("record_multistep_dispatch", "set_multistep_gauges")
    orphans = [
        fn for fn in recorders
        if not any(
            re.search(rf"\b{fn}\(", src)
            for path, src in package_src.items()
            if path != metrics_path
        )
    ]
    assert not orphans, (
        f"multistep recorders with no call site outside metrics.py: {orphans}"
    )


def test_spec_series_declared_and_emitted():
    """Closure for the fused-speculative series (``mtpu_spec_*``,
    docs/speculative.md#series), both directions: every declared catalog
    constant must be referenced by a live emitter/reader outside the
    catalog, AND every spec recorder in observability/metrics.py must have
    a call site outside metrics.py — otherwise the γ/acceptance meters the
    adaptive controller is judged by silently read zeros."""
    from modal_examples_tpu.observability import catalog

    consts = {
        attr: val
        for attr, val in vars(catalog).items()
        if isinstance(val, str) and val.startswith("mtpu_spec_")
    }
    # proposed/accepted/acceptance (PR-5 server exposition) + the fused
    # gamma/tokens-per-dispatch/fallback series (PR-20)
    assert len(consts) >= 6, consts
    catalog_path = PKG_ROOT / "observability" / "catalog.py"
    package_src = {
        path: path.read_text()
        for path in sorted(PKG_ROOT.rglob("*.py"))
        if path != catalog_path
    }
    unused = [
        attr for attr in consts
        if not any(
            re.search(rf"\b{attr}\b", src) for src in package_src.values()
        )
    ]
    assert not unused, (
        "spec series declared in the catalog but never referenced by an "
        f"emitter/reader in the package: {unused}"
    )
    metrics_path = PKG_ROOT / "observability" / "metrics.py"
    recorders = ("set_spec_gauges", "record_spec_fallback")
    orphans = [
        fn for fn in recorders
        if not any(
            re.search(rf"\b{fn}\(", src)
            for path, src in package_src.items()
            if path != metrics_path
        )
    ]
    assert not orphans, (
        f"spec recorders with no call site outside metrics.py: {orphans}"
    )


def test_speculative_bypass_quarantined_to_oracle_duty():
    """The standalone ``speculative_generate`` loop is RETIRED from the
    serving path (docs/speculative.md): the engine's fused round in
    serving/spec_runtime/ is the only production speculation. The module
    survives solely as the reference oracle for parity tests, so nothing
    under the package may import it except spec_runtime itself (which
    shares ``serving.speculative``'s n-gram index) — a new import is
    someone re-growing the bypass."""
    offenders = []
    for path in sorted(PKG_ROOT.rglob("*.py")):
        rel = path.relative_to(PKG_ROOT).as_posix()
        if rel.startswith("serving/spec_runtime/"):
            continue  # shares the oracle's n-gram index by design
        if rel == "serving/speculative.py":
            continue  # the oracle itself
        src = path.read_text()
        for node in ast.walk(ast.parse(src, filename=str(path))):
            # `from X.speculative import ...` pulls symbols out of the
            # oracle; `import X.speculative` binds it for use. The one
            # legal form is `from . import speculative` in
            # serving/__init__.py, which only RE-EXPORTS the module so the
            # parity tests can import the oracle.
            if isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[-1] == "speculative":
                    offenders.append((rel, f"line {node.lineno}"))
                elif any(a.name == "speculative" for a in node.names):
                    if rel != "serving/__init__.py":
                        offenders.append((rel, f"line {node.lineno}"))
            elif isinstance(node, ast.Import):
                if any(
                    a.name.split(".")[-1] == "speculative"
                    for a in node.names
                ):
                    offenders.append((rel, f"line {node.lineno}"))
        if "speculative_generate" in src:
            offenders.append((rel, "references speculative_generate"))
    assert not offenders, (
        "serving.speculative is the parity oracle, not a serving-path "
        f"dependency — re-route through serving/spec_runtime/: {offenders}"
    )


#: the decode harvest/accept path (docs/multistep.md#harvest-boundary):
#: these engine functions sit between a harvested token matrix and the
#: client stream, and the multistep plane's whole point is ONE blocking
#: device read per dispatch — so blocking host<-device materialization
#: (np.asarray / np.array / .item()) is banned here outside the blessed
#: harvest reads in ``_process_block``
_HARVEST_PATH_FUNCS = {
    "_process_block", "_accept_token", "_finish_stream", "_deliver_finish",
}
#: the blessed sites: the block-level token + validity reads — exactly the
#: multistep harvest plane, one (rel_path, dotted.func) entry
_HARVEST_READ_ALLOWLIST = {
    ("serving/engine.py", "LLMEngine._process_block"),
}


def test_harvest_path_has_no_per_token_device_reads():
    """AST guard for the macro-step harvest boundary (docs/multistep.md):
    in the engine's decode harvest/accept functions and everywhere in
    serving/multistep/, the only blocking device materialization
    (``np.asarray`` / ``np.array`` / ``.item()``) allowed is the
    block-level harvest in ``_process_block`` — and that function performs
    exactly two (the token matrix and the validity mask). A read anywhere
    else on this path is a per-token host round-trip, the exact overhead
    the N-step dispatch exists to amortize (frozen allowlist, exact match
    both ways — a removed site prunes its entry)."""
    targets = [
        (PKG_ROOT / "serving" / "engine.py", _HARVEST_PATH_FUNCS),
    ] + [
        (path, None)
        for path in sorted((PKG_ROOT / "serving" / "multistep").glob("*.py"))
    ]
    found = set()
    blessed_reads = 0

    def is_blocking_read(call: ast.Call) -> bool:
        f = call.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr in ("asarray", "array")
            and isinstance(f.value, ast.Name)
            and f.value.id == "np"
        ):
            return True
        return isinstance(f, ast.Attribute) and f.attr == "item"

    for path, only_funcs in targets:
        tree = ast.parse(path.read_text())
        rel = str(path.relative_to(PKG_ROOT.parent / "modal_examples_tpu"))

        def walk(node, stack):
            nonlocal blessed_reads
            for child in ast.iter_child_nodes(node):
                nstack = stack
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    nstack = stack + [child.name]
                if isinstance(child, ast.Call) and is_blocking_read(child):
                    in_scope = only_funcs is None or any(
                        name in only_funcs for name in stack
                    )
                    if in_scope:
                        site = (rel, ".".join(stack) or "<module>")
                        found.add(site)
                        if site in _HARVEST_READ_ALLOWLIST:
                            blessed_reads += 1
                walk(child, nstack)

        walk(tree, [])

    new_sites = found - _HARVEST_READ_ALLOWLIST
    assert not new_sites, (
        "blocking device reads on the decode harvest path outside the "
        "multistep harvest plane — accept/detokenize must work from the "
        f"already-harvested block: {sorted(new_sites)}"
    )
    stale = _HARVEST_READ_ALLOWLIST - found
    assert not stale, (
        f"stale allowlist entries (site removed — prune them): {sorted(stale)}"
    )
    assert blessed_reads == 2, (
        "_process_block must perform exactly TWO blocking reads (token "
        f"matrix + validity mask), found {blessed_reads}"
    )


def test_every_journal_has_a_docs_table_row():
    """The docs half of the JOURNALS closure (the catalog-series guard
    applied to the journal table): every named journal in
    ``journal.JOURNALS`` must appear as a ``| `name` |`` table row
    somewhere under ``docs/`` — a journal missing from the docs table is
    a decision record nobody knows to read back after an incident."""
    from modal_examples_tpu.observability.journal import JOURNALS

    rows = set()
    for path in sorted((REPO_ROOT / "docs").glob("*.md")):
        rows |= set(
            re.findall(r"^\|\s*`([a-z0-9_]+)`", path.read_text(), re.M)
        )
    missing = [name for name in JOURNALS if name not in rows]
    assert not missing, (
        "JOURNALS entries with no `| `name` |` table row in docs/*.md "
        "(add one to docs/observability.md#decision-journals): "
        f"{missing}"
    )


def test_every_catalog_series_has_a_docs_table_row():
    """The docs half of the catalog closure: every series declared in
    ``catalog.CATALOG`` must appear as a ``| `name` |`` table row somewhere
    under ``docs/`` (observability.md holds most of them). The catalog is
    the machine-readable half of the metrics reference; a series missing
    from the docs table is invisible to anyone deciding what to dashboard
    — exactly the drift this repo's declare⇔emit guards exist to stop,
    applied to the human-readable half."""
    from modal_examples_tpu.observability import catalog

    rows = set()
    for path in sorted((REPO_ROOT / "docs").glob("*.md")):
        rows |= set(
            re.findall(r"^\|\s*`([a-z0-9_]+)`", path.read_text(), re.M)
        )
    missing = [name for name in catalog.CATALOG if name not in rows]
    assert not missing, (
        "catalog series with no `| `name` |` table row in docs/*.md "
        f"(add one to docs/observability.md): {missing}"
    )


def test_prefix_store_is_sole_writer_of_block_layout():
    """LAYERING (docs/prefix_store.md): ``serving/prefix_store/`` is the
    ONLY package code that spells the store's on-volume block layout
    (``block-<hash>.kv``). Everything else — tiered cache, chaos, fleet,
    benches — goes through :class:`SharedPrefixStore`'s API, so the
    layout (sharding, compression, a manifest) can evolve in one place
    without call-site archaeology. Comments/docstrings are stripped
    before matching so prose ABOUT the layout stays legal."""
    import io
    import tokenize

    store_pkg = PKG_ROOT / "serving" / "prefix_store"
    offenders = []
    for path in sorted(PKG_ROOT.rglob("*.py")):
        if store_pkg in path.parents:
            continue
        code_strings = []
        try:
            for tok in tokenize.generate_tokens(
                io.StringIO(path.read_text()).readline
            ):
                if tok.type == tokenize.STRING:
                    code_strings.append(tok.string)
        except tokenize.TokenizeError:
            code_strings = [path.read_text()]
        # docstrings are STRING tokens too: only flag strings that look
        # like a PATH being built (contain the block- prefix AND the .kv
        # suffix without intervening prose whitespace)
        for s in code_strings:
            if re.search(r"block-[^\s\"']*\.kv", s):
                offenders.append(str(path.relative_to(PKG_ROOT)))
                break
    assert not offenders, (
        "block-file paths constructed outside serving/prefix_store/ "
        f"(use SharedPrefixStore / block_file): {offenders}"
    )
