"""Static tier: every module in the package byte-compiles and imports, and
the jax-free layering invariant holds (the reference's typecheck/lint CI
analog, SURVEY.md §4 — mypy isn't in this image, so the checks are
compileall + import + an architectural rule)."""

import compileall
import importlib
import pkgutil
import subprocess
import sys
from pathlib import Path

import modal_examples_tpu

PKG_ROOT = Path(modal_examples_tpu.__file__).parent
REPO_ROOT = PKG_ROOT.parent


def test_package_bytecompiles():
    assert compileall.compile_dir(
        str(PKG_ROOT), quiet=2, force=True
    ), "syntax errors in package"


def test_examples_bytecompile():
    assert compileall.compile_dir(
        str(REPO_ROOT / "examples"), quiet=2, force=True
    ), "syntax errors in examples"


def test_every_module_imports():
    failures = []
    for mod in pkgutil.walk_packages([str(PKG_ROOT)], "modal_examples_tpu."):
        if mod.name.endswith("__main__"):
            continue  # executes the CLI on import by design
        if "libmtpu_host" in mod.name:
            continue  # the raw .so is a ctypes library, not a Python module
        try:
            importlib.import_module(mod.name)
        except Exception as e:
            failures.append(f"{mod.name}: {type(e).__name__}: {e}")
    assert not failures, failures


def test_core_layer_is_jax_free():
    """The client/control-plane layer must never import jax (chip attach +
    multi-second import would leak into every CLI invocation)."""
    code = (
        "import sys\n"
        "import modal_examples_tpu\n"
        "import modal_examples_tpu.core.cli\n"
        "import modal_examples_tpu.core.executor\n"
        "import modal_examples_tpu.storage.volume\n"
        "assert 'jax' not in sys.modules, 'core layer imported jax'\n"
        "print('jax-free')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=60,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin", "PYTHONPATH": str(REPO_ROOT)},
    )
    assert out.returncode == 0 and "jax-free" in out.stdout, out.stderr
