"""Chaos acceptance (ISSUE 8, docs/faults.md): ONE seeded command drives
the default episode schedule through EVERY cataloged fault point against a
real mixed fleet (unified + disagg prefill/decode, CPU-sized models) and
all fleet invariants hold — zero wedged requests, reservations and pages
drained to zero, request conservation, router recovered, and fault-free
outputs token-identical. The run itself raises ChaosInvariantError on any
violation, so the fixture IS the acceptance; the tests below pin each
contract clause to a named assertion."""

import json
import time

import pytest


@pytest.fixture(scope="module")
def chaos_report(jax_cpu):
    from modal_examples_tpu.faults.chaos import run_chaos

    # strict=True: any invariant violation raises here, failing every test
    return run_chaos(seed=0, strict=True)


class TestChaosAcceptance:
    def test_every_cataloged_fault_point_fires(self, chaos_report):
        """Catalog reachability: the default seeded schedule reaches AND
        fires every declared FaultPoint — a dead injection point (wired
        out by a refactor, never exercised) fails here, not in prod."""
        from modal_examples_tpu.faults import ALL_FAULT_POINTS

        assert chaos_report["points_missed"] == []
        assert set(chaos_report["points_fired"]) == set(ALL_FAULT_POINTS)
        assert chaos_report["injected_total"] >= len(ALL_FAULT_POINTS)

    def test_zero_wedged_requests(self, chaos_report):
        assert chaos_report["wedged"] == 0

    def test_all_invariants_hold_after_every_episode(self, chaos_report):
        assert chaos_report["invariants"] == "ok"
        for ep in chaos_report["episodes"]:
            assert ep["invariants"] == "ok", ep

    def test_request_conservation_per_episode(self, chaos_report):
        """admitted == finished + shed, per episode: nothing vanishes —
        aborted and deadline-expired requests still FINISH."""
        for ep in chaos_report["episodes"]:
            finished = sum(ep["finished"].values())
            assert finished + ep["shed"] > 0, ep
            assert ep["wedged"] == 0, ep

    def test_faults_recovered_not_just_survived(self, chaos_report):
        """Most injected faults must end in RECOVERY (requests finishing
        normally despite the fault), not merely honest failure."""
        assert chaos_report["recovered"] >= len(chaos_report["episodes"])

    def test_router_readmission_happened(self, chaos_report):
        """The flap episode must exercise the re-probe re-admission path
        (the PR's one-way-door bugfix), observable in the metric."""
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.utils.prometheus import default_registry

        assert default_registry.total(C.ROUTER_READMISSIONS_TOTAL) >= 1

    def test_injected_metric_covers_every_point(self, chaos_report):
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.utils.prometheus import default_registry

        counted = {
            labels.get("point"): v
            for labels, v in default_registry.series(C.FAULTS_INJECTED_TOTAL)
        }
        for point, n in chaos_report["injected"].items():
            assert counted.get(point, 0) >= n, (point, counted)

    def test_episode_journal_written(self, chaos_report, state_dir):
        """Every episode appends one JSON record to <state_dir>/chaos.jsonl
        — the `tpurun chaos` / gateway `/chaos` data source."""
        path = state_dir / "chaos.jsonl"
        assert path.exists()
        records = [
            json.loads(line)
            for line in path.read_text().splitlines()
            if line.strip()
        ]
        episodes = {r.get("episode") for r in records}
        for ep in chaos_report["episodes"]:
            assert ep["episode"] in episodes
        for rec in records:
            assert "injected" in rec and "invariants" in rec

    def test_chaos_cli_renders_the_journal(self, chaos_report, capsys):
        """`tpurun chaos` renders the last episodes without error."""
        from modal_examples_tpu.core.cli import main

        assert main(["chaos", "--last", "20"]) == 0
        out = capsys.readouterr().out
        assert "FAULT POINT" in out or "EPISODE" in out
        assert "VIOLATED" not in out

    def test_gateway_chaos_snapshot_shape(self, chaos_report):
        from modal_examples_tpu.web.gateway import _chaos_snapshot

        snap = _chaos_snapshot()
        assert snap["injected_total"] >= chaos_report["injected_total"]
        assert snap["episodes"], "journal episodes must surface"
        assert snap["wedged"] == 0


class TestChaosUnderLoad:
    """ISSUE 11: chaos driven CONCURRENTLY with the open-loop load
    generator — self-healing measured, not just asserted. A two-replica
    fleet serves a fixed offered load for a fault-free baseline window and
    again with a seeded fault episode armed (health flap, decode stall,
    page pressure); the PR-8 fleet invariants must hold afterwards AND the
    goodput dip during the fault window must be bounded: recovery is a
    throughput statement, not a liveness one (docs/fleet.md)."""

    def test_goodput_dip_under_faults_is_bounded(
        self, jax_cpu, state_dir, monkeypatch
    ):
        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "0")
        from modal_examples_tpu.faults.chaos import (
            settle_drained,
            settle_recovered,
        )
        from modal_examples_tpu.faults.inject import FaultPlan, active
        from modal_examples_tpu.fleet.loadgen import LoadGenerator, RequestClass
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )
        from modal_examples_tpu.serving import LLMEngine
        from modal_examples_tpu.serving.openai_api import OpenAIServer

        cfg = llama.LlamaConfig.tiny()
        eng_a = LLMEngine(
            cfg, seed=0, max_slots=2, max_model_len=384, page_size=16,
            prefill_buckets=(64, 128),
        )
        # second replica shares the weight buffers: one init, two engines
        eng_b = LLMEngine(
            cfg, params=eng_a.params, max_slots=2, max_model_len=384,
            page_size=16, prefill_buckets=(64, 128),
        )
        router = PrefixAffinityRouter(
            [
                EngineReplica(eng_a, "uni-a", role="unified"),
                EngineReplica(eng_b, "uni-b", role="unified"),
            ],
            reprobe_s=0.2,
        )
        server = OpenAIServer(router=router, host="127.0.0.1", port=0)
        server.start()
        try:
            classes = (
                RequestClass(
                    "interactive", "interactive", 0.7, (1, 2), 16, 5.0, 1.0
                ),
                RequestClass(
                    "batch", "batch", 0.3, (2, 3), 16, 30.0, 2.0,
                    stream=False,
                ),
            )
            lg = LoadGenerator(
                f"http://127.0.0.1:{server.port}", classes=classes, seed=3,
                request_timeout_s=60.0,
            )
            lg.warm(n_per_class=1)
            capacity = lg.calibrate(duration_s=1.5)
            rate = 0.6 * capacity  # comfortable: the dip isolates the faults
            baseline = lg.run_step(rate, 4.0, label="baseline")
            plan = FaultPlan(
                {
                    "router.health_flap": {"on_hit": 2},
                    "engine.slow_decode": {"on_hit": 3},
                    "engine.out_of_pages": {"on_hit": 4},
                },
                seed=3,
            )
            with active(plan):
                faulted = lg.run_step(rate, 4.0, label="faulted")
            recovered = lg.run_step(rate, 2.0, label="recovered")

            fired = plan.fired()
            assert fired, "the episode never injected anything"
            assert fired.get("router.health_flap"), fired
            # liveness: nothing wedges or errors in ANY window
            for step in (baseline, faulted, recovered):
                assert step["wedged"] == 0, step
                assert step["errors"] == 0, step
            # fleet invariants (PR 8) after the fault window drained
            assert settle_drained({"uni-a": eng_a, "uni-b": eng_b}) == []
            assert settle_recovered(router) == []
            # the measured self-healing clause: the fault window still
            # delivered a bounded fraction of fault-free goodput
            assert baseline["goodput_rps"] > 0
            assert faulted["goodput_rps"] >= 0.25 * baseline["goodput_rps"], (
                baseline, faulted,
            )
        finally:
            server.stop()


class TestDecodeReplicaDeathMidStream:
    """ISSUE 12: kill a decode replica mid-stream — idle fleet AND under
    the PR-11 loadgen — and assert the PR-8 invariants plus the new one:
    every affected stream finishes with its fault-free token sequence,
    zero client-visible errors, zero wedges (docs/failover.md)."""

    def test_idle_fleet_streams_survive_death_token_identical(self, jax_cpu):
        import threading

        from modal_examples_tpu.faults.chaos import (
            settle_drained,
            settle_recovered,
        )
        from modal_examples_tpu.faults.inject import FaultPlan, active
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg = llama.LlamaConfig.tiny()

        def engine(**kw):
            return LLMEngine(
                cfg, seed=0, max_slots=4, max_model_len=128, page_size=8,
                prefill_buckets=(16, 32), **kw,
            )

        sp = SamplingParams(max_tokens=48, temperature=0.0)
        prompts = [
            "the quick brown fox jumps over the lazy dog",
            "the quick brown fox naps in the warm sun",
            "a completely different prompt about thundering herds",
        ]
        ref_engine = engine()
        try:
            reference = {
                p: ref_engine.generate(p, sp) for p in prompts
            }
        finally:
            ref_engine.stop()

        eng_a = engine()
        eng_b = engine(params=eng_a.params)
        rep_a = EngineReplica(eng_a, "death-a", role="unified")
        rep_b = EngineReplica(eng_b, "death-b", role="unified")
        router = PrefixAffinityRouter([rep_a, rep_b], reprobe_s=0.2)
        try:
            eng_a.start()  # the victim; B boots lazily at takeover
            reqs, outs, threads = [], {}, []
            for p in prompts:
                req = rep_a.submit(p, sp)  # all streams on the victim
                req._router_replica = rep_a
                reqs.append(req)
                outs[req.request_id] = pieces = []

                t = threading.Thread(
                    target=lambda r=req, buf=pieces: buf.extend(
                        router.stream(r)
                    )
                )
                t.start()
                threads.append(t)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not all(
                len(r.generated_tokens) >= 3 for r in reqs
            ):
                time.sleep(0.005)
            # ONLY the victim's loop is running: the injected crash lands
            # on it deterministically, releasing every stream with "error"
            plan = FaultPlan({"engine.scheduler_crash": {"on_hit": 1}})
            with active(plan):
                deadline = time.monotonic() + 30
                while not plan.fired() and time.monotonic() < deadline:
                    time.sleep(0.005)
            assert plan.fired().get("engine.scheduler_crash") == 1
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "stream wedged after replica death"
            for req in reqs:
                # zero client-visible errors + the fault-free sequence
                assert req.finish_reason in ("stop", "length"), req.request_id
                assert "".join(outs[req.request_id]) == reference[req.prompt]
            # PR-8 fleet invariants after the episode
            assert settle_drained({"death-a": eng_a, "death-b": eng_b}) == []
            assert settle_recovered(router) == []
        finally:
            eng_a.stop()
            eng_b.stop()

    def test_streams_survive_death_under_load(
        self, jax_cpu, state_dir, monkeypatch
    ):
        """The same death under the PR-11 open-loop load generator: the
        SSE clients observe zero errors and zero wedges through the crash
        window — failover is measured under production-shaped traffic,
        not just asserted on a quiet fleet."""
        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "0")
        from modal_examples_tpu.faults.chaos import (
            settle_drained,
            settle_recovered,
        )
        from modal_examples_tpu.faults.inject import FaultPlan, active
        from modal_examples_tpu.fleet.loadgen import LoadGenerator, RequestClass
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )
        from modal_examples_tpu.serving import LLMEngine
        from modal_examples_tpu.serving.openai_api import OpenAIServer

        cfg = llama.LlamaConfig.tiny()
        eng_a = LLMEngine(
            cfg, seed=0, max_slots=2, max_model_len=384, page_size=16,
            prefill_buckets=(64, 128),
        )
        eng_b = LLMEngine(
            cfg, params=eng_a.params, max_slots=2, max_model_len=384,
            page_size=16, prefill_buckets=(64, 128),
        )
        router = PrefixAffinityRouter(
            [
                EngineReplica(eng_a, "dload-a", role="unified"),
                EngineReplica(eng_b, "dload-b", role="unified"),
            ],
            reprobe_s=0.2,
        )
        server = OpenAIServer(router=router, host="127.0.0.1", port=0)
        server.start()
        try:
            classes = (
                RequestClass(
                    "interactive", "interactive", 1.0, (1, 2), 16, 5.0, 1.0
                ),
            )
            lg = LoadGenerator(
                f"http://127.0.0.1:{server.port}", classes=classes, seed=5,
                request_timeout_s=60.0,
            )
            lg.warm(n_per_class=1)
            capacity = lg.calibrate(duration_s=1.5)
            rate = 0.5 * capacity
            # a decode replica dies mid-window: several in-flight SSE
            # streams fail over to the surviving one
            plan = FaultPlan({"engine.scheduler_crash": {"on_hit": 20}})
            with active(plan):
                faulted = lg.run_step(rate, 5.0, label="death")
            assert plan.fired().get("engine.scheduler_crash"), plan.hits()
            # the new invariant: the crash is CLIENT-INVISIBLE — no SSE
            # error events, no wedged streams, and the fleet drained
            assert faulted["wedged"] == 0, faulted
            assert faulted["errors"] == 0, faulted
            assert faulted["goodput_rps"] > 0
            assert settle_drained({"dload-a": eng_a, "dload-b": eng_b}) == []
            assert settle_recovered(router) == []
        finally:
            server.stop()


class TestSilentHangUnderLoad:
    """ISSUE 13 (docs/health.md): a SILENT scheduler freeze — no crash, no
    error, ``healthy()`` stays true — under the PR-11 open-loop load
    generator. The progress watchdog must detect the wedge from stale
    watermarks, error-stop the replica so every live SSE stream takes the
    PR-12 reactive failover, and the fleet must drain with zero wedges and
    zero client-visible errors. (The idle-fleet token-identity half lives
    in tests/test_health.py; detection-latency numbers live in the
    fake-clock unit matrix — no wall-clock direction asserts here.)"""

    def test_freeze_under_load_recovers(self, jax_cpu, state_dir, monkeypatch):
        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "0")
        from modal_examples_tpu.faults.chaos import (
            settle_drained,
            settle_recovered,
        )
        from modal_examples_tpu.faults.inject import FaultPlan, active
        from modal_examples_tpu.fleet.loadgen import LoadGenerator, RequestClass
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )
        from modal_examples_tpu.serving import LLMEngine
        from modal_examples_tpu.serving.health import (
            FleetWatchdog,
            WatchdogPolicy,
        )
        from modal_examples_tpu.serving.openai_api import OpenAIServer

        cfg = llama.LlamaConfig.tiny()
        eng_a = LLMEngine(
            cfg, seed=0, max_slots=2, max_model_len=384, page_size=16,
            prefill_buckets=(64, 128),
        )
        eng_b = LLMEngine(
            cfg, params=eng_a.params, max_slots=2, max_model_len=384,
            page_size=16, prefill_buckets=(64, 128),
        )
        router = PrefixAffinityRouter(
            [
                EngineReplica(eng_a, "hang-a", role="unified"),
                EngineReplica(eng_b, "hang-b", role="unified"),
            ],
            reprobe_s=0.2,
        )
        server = OpenAIServer(router=router, host="127.0.0.1", port=0)
        server.start()
        watchdog = None
        try:
            classes = (
                RequestClass(
                    "interactive", "interactive", 1.0, (1, 2), 16, 5.0, 1.0
                ),
            )
            lg = LoadGenerator(
                f"http://127.0.0.1:{server.port}", classes=classes, seed=7,
                request_timeout_s=60.0,
            )
            lg.warm(n_per_class=1)
            capacity = lg.calibrate(duration_s=1.5)
            rate = 0.5 * capacity
            # the watchdog starts AFTER warm/calibrate — and after BOTH
            # engines compiled their own jits (a takeover onto a cold
            # standby would otherwise stall in its first trace and read
            # as a wedge — the watchdog-vs-compile rule, docs/health.md)
            from modal_examples_tpu.serving import SamplingParams

            for eng in (eng_a, eng_b):
                eng.generate(
                    "watchdog warm probe", SamplingParams(max_tokens=4)
                )
            watchdog = FleetWatchdog(
                router,
                policy=WatchdogPolicy(
                    degraded_after_s=1.0, wedged_after_s=2.0,
                    quarantine_after=99,
                ),
                poll_s=0.1,
            ).start()
            # one loop silently freezes mid-window; its in-flight SSE
            # streams must fail over with the crash invisible to clients
            plan = FaultPlan(
                {"engine.scheduler_freeze": {"p": 1.0, "max_fires": 1}}
            )
            with active(plan):
                faulted = lg.run_step(rate, 6.0, label="freeze")
            assert plan.fired().get("engine.scheduler_freeze") == 1
            recovered = lg.run_step(rate, 2.0, label="recovered")
            for step in (faulted, recovered):
                assert step["wedged"] == 0, step
                assert step["errors"] == 0, step
            assert faulted["goodput_rps"] > 0
            # the ladder actually ran: a wedge transition + an error-stop
            acted = {e["action"] for e in watchdog.events}
            assert "stop_revive" in acted, watchdog.events
            assert settle_drained({"hang-a": eng_a, "hang-b": eng_b}) == []
            assert settle_recovered(router) == []
        finally:
            if watchdog is not None:
                watchdog.stop()
            server.stop()


class TestTraceUnderChaos:
    def test_chaos_requests_carry_fault_events(self, chaos_report):
        """Acceptance: a chaos episode's injected faults appear as span
        EVENTS on the affected requests' distributed traces — the fleet
        timeline shows per-request what was injected, not just a
        counter."""
        from modal_examples_tpu.observability.trace import default_store

        points_seen = set()
        for tid in default_store.list_traces(limit=2000):
            if not tid.startswith("req-"):
                continue
            for s in default_store.read(tid):
                if s["name"] == "fault":
                    points_seen.add(s["attrs"].get("point"))
        assert points_seen, (
            "no request trace recorded a fault event during the chaos run"
        )


    """ISSUE 9: trace-context propagation under failure — an injected
    scheduler-thread crash must still close every open span of every
    in-flight traced request (no dangling span leak), mark the crash as a
    ``fault`` event on each, and finish the roots with the same honest
    finish_reason="error" the stream reports."""

    def test_scheduler_crash_closes_all_spans_and_marks_fault(self, jax_cpu):
        from modal_examples_tpu.faults.inject import FaultPlan, active
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.observability import reqtrace as rt
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
            prefill_buckets=(16, 32), page_size=4,
        )
        try:
            # crash a few ticks in: the request is mid-decode, its queue
            # span closed and its decode span OPEN when the crash lands
            plan = FaultPlan({"engine.scheduler_crash": {"on_hit": 4}})
            with active(plan):
                req = eng.submit(
                    "crash victim", SamplingParams(max_tokens=64)
                )
                out = "".join(eng.stream(req))
            assert req.finish_reason == "error"
            assert plan.fired().get("engine.scheduler_crash") == 1
            assert req.trace is not None
            assert req.trace.open_spans() == [], "dangling span leaked"
            spans = rt.read_trace(req.request_id)
            assert all(s["end"] is not None for s in spans)
            by = {}
            for s in spans:
                by.setdefault(s["name"], []).append(s)
            root = by["request"][0]
            assert root["attrs"]["finish_reason"] == "error"
            faults = by.get("fault", [])
            assert faults and faults[0]["attrs"]["point"] == (
                "engine.scheduler_crash"
            )
            # the decode span was open at the crash: swept closed with the
            # terminal status, not abandoned
            if "decode" in by:
                assert by["decode"][0]["status"] == "error"
            del out  # partial output is fine; the contract is closure
        finally:
            eng.stop()
