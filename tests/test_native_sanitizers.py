"""TSAN/ASAN job for the C++ host library (PARITY.md §5.2).

The reference's native components (vLLM C++ scheduler, TEI) rely on CI
sanitizer runs; this is the framework's equivalent for
native/mtpu_host.cpp: build the sanitizer harness
(native/mtpu_host_test.cpp — every entry point, allocator under 8-thread
contention) under AddressSanitizer+UBSan and ThreadSanitizer, run it, and
require a clean exit with zero sanitizer reports.
"""

from __future__ import annotations

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow  # compiles twice; excluded from the fast tier

NATIVE = Path(__file__).resolve().parent.parent / "modal_examples_tpu" / "native"
SOURCES = [str(NATIVE / "mtpu_host.cpp"), str(NATIVE / "mtpu_host_test.cpp")]


def _sanitizer_supported(tmp_path: Path, sanitize: str) -> bool:
    """Probe the toolchain with a trivial TU so 'sanitizer runtime not
    installed' skips but a REAL compile error in mtpu_host.cpp fails."""
    probe = tmp_path / "probe.cpp"
    probe.write_text("int main() { return 0; }\n")
    r = subprocess.run(
        ["g++", f"-fsanitize={sanitize}", str(probe), "-o",
         str(tmp_path / "probe")],
        capture_output=True, text=True, timeout=120,
    )
    return r.returncode == 0


def _build_and_run(tmp_path: Path, name: str, sanitize: str, env: dict) -> str:
    if shutil.which("g++") is None:
        pytest.skip("g++ unavailable")
    if not _sanitizer_supported(tmp_path, sanitize):
        pytest.skip(f"toolchain lacks -fsanitize={sanitize}")
    exe = tmp_path / name
    build = subprocess.run(
        ["g++", "-O1", "-g", f"-fsanitize={sanitize}", "-std=c++17",
         *SOURCES, "-o", str(exe)],
        capture_output=True, text=True, timeout=180,
    )
    # the toolchain probe passed, so a failure here is a genuine compile
    # error in the sources — fail loudly, never skip
    assert build.returncode == 0, build.stderr[-2000:]
    run = subprocess.run(
        [str(exe)], capture_output=True, text=True, timeout=300, env=env
    )
    out = run.stdout + run.stderr
    assert run.returncode == 0, out[-2000:]
    assert "mtpu_host sanitizer harness: OK" in out
    return out


def test_asan_ubsan_clean(tmp_path):
    out = _build_and_run(
        tmp_path, "mtpu_asan", "address,undefined",
        env={"ASAN_OPTIONS": "detect_leaks=1", "PATH": "/usr/bin:/bin"},
    )
    assert "AddressSanitizer" not in out, out[-2000:]
    assert "runtime error" not in out, out[-2000:]  # UBSan report marker


def test_tsan_clean(tmp_path):
    out = _build_and_run(
        tmp_path, "mtpu_tsan", "thread",
        env={"TSAN_OPTIONS": "halt_on_error=1", "PATH": "/usr/bin:/bin"},
    )
    assert "ThreadSanitizer" not in out, out[-2000:]


def test_harness_covers_every_export():
    """Every symbol mtpu_host.cpp exports must be CALLED in the harness
    body (not merely declared in its extern block) — a new entry point
    can't land unsanitized."""
    import re

    src = (NATIVE / "mtpu_host.cpp").read_text()
    harness = (NATIVE / "mtpu_host_test.cpp").read_text()
    exports = set(re.findall(r"^\w[\w\s\*]*?\b(mtpu_\w+)\s*\(", src, re.M))
    assert exports, "no exports found — regex drifted?"
    # drop the harness's own extern "C" declaration block, then require a
    # call site for each export in what remains
    body = re.sub(r'extern "C" \{.*?\n\}', "", harness, flags=re.S)
    missing = {
        e for e in exports if not re.search(rf"\b{e}\s*\(", body)
    }
    assert not missing, f"harness never calls: {missing}"
