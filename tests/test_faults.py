"""Fault-injection unit tier (modal_examples_tpu/faults, docs/faults.md):
FaultPlan determinism, the zero-cost gate, seeded retry jitter, transport
fault points with resumable recovery, engine crash-fail-loudly + revive,
and the chaos invariant checkers against hand-built violating states.
(The end-to-end episode schedule lives in tests/test_chaos.py.)"""

import time
from types import SimpleNamespace

import pytest

from modal_examples_tpu.faults import inject as fi


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test must leave the gate disarmed — a leaked plan would inject
    faults into unrelated tests."""
    yield
    assert fi.active_plan() is None, "a test leaked an active FaultPlan"
    fi.deactivate()


class TestFaultPlan:
    def test_unknown_point_rejected(self):
        with pytest.raises(ValueError, match="unknown fault points"):
            fi.FaultPlan({"engine.made_up": {"on_hit": 1}})

    def test_spec_needs_a_rule(self):
        with pytest.raises(ValueError, match="on_hit"):
            fi.FaultPlan({"engine.slow_decode": {}})

    def test_on_hit_fires_exactly_the_named_hits(self):
        plan = fi.FaultPlan(
            {"disagg.chunk_drop": {"on_hit": [2, 4]}}, seed=3
        )
        decisions = [plan.should_fire("disagg.chunk_drop") for _ in range(6)]
        assert decisions == [False, True, False, True, False, False]
        assert plan.hits() == {"disagg.chunk_drop": 6}
        assert plan.fired() == {"disagg.chunk_drop": 2}

    def test_probability_mode_is_seed_deterministic(self):
        def run(seed):
            plan = fi.FaultPlan(
                {"engine.slow_decode": {"p": 0.5}}, seed=seed
            )
            return [plan.should_fire("engine.slow_decode") for _ in range(64)]

        assert run(7) == run(7)  # same seed: identical decision sequence
        assert run(7) != run(8)  # different seed: different sequence
        assert any(run(7)) and not all(run(7))

    def test_max_fires_caps_probability_mode(self):
        plan = fi.FaultPlan(
            {"engine.slow_decode": {"p": 1.0, "max_fires": 2}}, seed=0
        )
        fired = sum(plan.should_fire("engine.slow_decode") for _ in range(10))
        assert fired == 2

    def test_hits_recorded_for_points_outside_the_spec(self):
        """Reachability record: a plan counts every declared point it sees,
        even ones it never fires — chaos uses this to prove coverage."""
        plan = fi.FaultPlan({"disagg.chunk_drop": {"on_hit": 99}})
        assert not plan.should_fire("router.health_flap")
        assert plan.hits() == {"router.health_flap": 1}
        assert plan.fired() == {}


class TestGate:
    def test_disabled_gate_is_a_no_op(self):
        """With no active plan: fire() is False for every declared point
        and nothing is recorded — no metric, no counter, no allocation the
        registry could observe."""
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.utils.prometheus import default_registry

        before = default_registry.total(C.FAULTS_INJECTED_TOTAL)
        for point in sorted(fi.ALL_FAULT_POINTS):
            assert fi.fire(point) is False
        assert default_registry.total(C.FAULTS_INJECTED_TOTAL) == before

    def test_fired_fault_records_the_metric(self):
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.utils.prometheus import default_registry

        before = default_registry.value(
            C.FAULTS_INJECTED_TOTAL, {"point": "engine.slow_decode"}
        )
        with fi.active(fi.FaultPlan({"engine.slow_decode": {"on_hit": 1}})):
            assert fi.fire("engine.slow_decode") is True
        assert default_registry.value(
            C.FAULTS_INJECTED_TOTAL, {"point": "engine.slow_decode"}
        ) == (before or 0) + 1

    def test_active_context_disarms_on_exception(self):
        with pytest.raises(RuntimeError):
            with fi.active(fi.FaultPlan({})):
                raise RuntimeError("boom")
        assert fi.active_plan() is None

    def test_check_raises_requested_exception(self):
        with fi.active(
            fi.FaultPlan({"disagg.replica_death": {"on_hit": 1}})
        ):
            with pytest.raises(ConnectionError, match="injected"):
                fi.check(
                    "disagg.replica_death", ConnectionError, "injected death"
                )

    def test_corrupt_flips_a_byte_only_when_fired(self):
        data = b"hello world"
        assert fi.corrupt("tiered.volume_corrupt", data) == data  # disarmed
        with fi.active(
            fi.FaultPlan({"tiered.volume_corrupt": {"on_hit": 1}})
        ):
            bad = fi.corrupt("tiered.volume_corrupt", data)
            assert bad != data and len(bad) == len(data)
            assert fi.corrupt("tiered.volume_corrupt", data) == data
            assert fi.corrupt("tiered.volume_corrupt", b"") == b""

    def test_env_activation(self, monkeypatch):
        monkeypatch.setenv(
            "MTPU_FAULT_PLAN", '{"engine.slow_decode": {"on_hit": 1}}'
        )
        monkeypatch.setenv("MTPU_FAULT_SEED", "11")
        try:
            fi._activate_from_env()
            plan = fi.active_plan()
            assert plan is not None and plan.seed == 11
            assert fi.fire("engine.slow_decode") is True
        finally:
            fi.deactivate()


class TestRetryJitter:
    def test_bare_schedule_unchanged_without_key(self):
        from modal_examples_tpu.core.retries import Retries

        r = Retries(max_retries=5, initial_delay=1.0, backoff_coefficient=2.0)
        assert r.delay_for_attempt(1) == 1.0
        assert r.delay_for_attempt(3) == 4.0

    def test_keyed_delay_is_bounded_deterministic_and_decorrelated(self):
        from modal_examples_tpu.core.retries import Retries

        r = Retries(initial_delay=1.0, jitter=0.5)
        d = r.delay_for_attempt(3, key="in-abc")
        assert 2.0 <= d <= 4.0  # equal jitter: [d*(1-j), d]
        assert d == r.delay_for_attempt(3, key="in-abc")  # reproducible
        others = {
            r.delay_for_attempt(3, key=f"in-{i}") for i in range(8)
        }
        assert len(others) > 1, "keys must decorrelate the schedule"

    def test_zero_jitter_is_exact_even_with_key(self):
        from modal_examples_tpu.core.retries import Retries

        r = Retries(initial_delay=2.0, jitter=0.0)
        assert r.delay_for_attempt(2, key="x") == 4.0

    def test_invalid_jitter_rejected(self):
        from modal_examples_tpu.core.retries import Retries

        with pytest.raises(ValueError, match="jitter"):
            Retries(jitter=1.5)


class TestTransportFaults:
    def _roundtrip(self, payload=b"z" * 4000, **kw):
        from modal_examples_tpu.serving.disagg.transport import (
            LoopbackChannel,
            transfer,
        )

        kw.setdefault("backoff", None)
        return transfer(
            payload, LoopbackChannel(), transfer_id="tf", chunk_bytes=512,
            **kw,
        )

    def test_injected_chunk_corruption_recovers_by_resend(self):
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.utils.prometheus import default_registry

        payload = bytes(range(256)) * 20
        before = default_registry.total(C.DISAGG_CHUNK_RETRIES_TOTAL)
        with fi.active(
            fi.FaultPlan({"disagg.chunk_corrupt": {"on_hit": 2}})
        ) as plan:
            assert self._roundtrip(payload) == payload
            assert plan.fired() == {"disagg.chunk_corrupt": 1}
        assert default_registry.total(C.DISAGG_CHUNK_RETRIES_TOTAL) > before

    def test_injected_chunk_drop_recovers_by_resend(self):
        payload = b"q" * 3000
        with fi.active(
            fi.FaultPlan({"disagg.chunk_drop": {"on_hit": 1}})
        ) as plan:
            assert self._roundtrip(payload) == payload
            assert plan.fired() == {"disagg.chunk_drop": 1}

    def test_injected_replica_death_is_a_connection_error(self):
        with fi.active(
            fi.FaultPlan({"disagg.replica_death": {"on_hit": 3}})
        ):
            with pytest.raises(ConnectionError, match="mid-transfer"):
                self._roundtrip()

    def test_retry_rounds_back_off_with_jitter(self, monkeypatch):
        """A corrupted first round forces a retry round; the wait between
        rounds is the jittered policy delay, keyed by transfer id."""
        from modal_examples_tpu.core.retries import Retries
        from modal_examples_tpu.serving.disagg import transport

        slept = []
        monkeypatch.setattr(
            transport.time, "sleep", lambda s: slept.append(s)
        )
        backoff = Retries(initial_delay=0.4, jitter=0.5)
        with fi.active(
            fi.FaultPlan({"disagg.chunk_corrupt": {"on_hit": 1}})
        ):
            out = self._roundtrip(b"y" * 2000, backoff=backoff)
        assert out == b"y" * 2000
        assert len(slept) == 1
        assert 0.2 <= slept[0] <= 0.4  # jittered into [d/2, d]
        assert slept[0] == backoff.delay_for_attempt(1, key="tf")


def _tiny_engine(jax, **kw):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    kw.setdefault("max_slots", 2)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (32,))
    return LLMEngine(llama.LlamaConfig.tiny(), seed=0, **kw)


class TestEngineFaults:
    def test_scheduler_crash_fails_inflight_loudly_and_loop_survives(self, jax):
        """The hardening the harness forced: an injected scheduler-thread
        crash terminates every caller's stream with finish_reason="error"
        (no wedge), does NOT poison the engine (strict mode is for real
        bugs), leaves the _error_reports sentinel untouched, and the very
        next request decodes normally."""
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.serving.engine import LLMEngine

        eng = _tiny_engine(jax)
        reports_before = len(LLMEngine._error_reports)
        errors_before = eng.error_count
        try:
            eng.start()
            ref = eng.generate("warm the compiles", SamplingParams(max_tokens=4, temperature=0.0))
            req = eng.submit(
                "a long request to crash", SamplingParams(max_tokens=48, temperature=0.0)
            )
            # wait until it is genuinely in flight (first token emitted)
            deadline = time.monotonic() + 60
            while not req.out_queue.qsize() and time.monotonic() < deadline:
                time.sleep(0.005)
            with fi.active(
                fi.FaultPlan({"engine.scheduler_crash": {"on_hit": 1}})
            ):
                out = "".join(eng.stream(req))
            assert req.finish_reason == "error"
            assert out is not None  # partial output is fine; wedging is not
            assert eng._running and not eng._stopped_on_error
            assert eng.error_count == errors_before
            assert len(LLMEngine._error_reports) == reports_before, (
                "injected crashes must not trip the session error sentinel"
            )
            # the fleet invariant: the engine keeps serving afterwards
            again = eng.generate(
                "warm the compiles", SamplingParams(max_tokens=4, temperature=0.0)
            )
            assert again == ref
        finally:
            eng.stop()

    def test_out_of_pages_pressure_requeues_and_completes(self, jax):
        from modal_examples_tpu.serving import SamplingParams

        eng = _tiny_engine(jax)
        try:
            params = SamplingParams(max_tokens=6, temperature=0.0)
            ref = eng.generate("pressure test prompt", params)
            with fi.active(
                fi.FaultPlan({"engine.out_of_pages": {"on_hit": 1}})
            ) as plan:
                out = eng.generate("pressure test prompt", params)
                assert plan.fired() == {"engine.out_of_pages": 1}
            assert out == ref  # requeued, then admitted and served normally
            assert eng.error_count == 0
        finally:
            eng.stop()

    def test_revive_reopens_a_stopped_on_error_engine(self, jax):
        """EngineReplica.probe() heals the one-way door: a stopped-on-error
        engine refuses start() until revive() clears the poison."""
        from modal_examples_tpu.scheduling import EngineReplica
        from modal_examples_tpu.serving import SamplingParams

        eng = _tiny_engine(jax)
        replica = EngineReplica(eng, "r0")
        try:
            # the poisoned state a strict-mode scheduler error leaves behind
            eng._stopped_on_error = True
            assert not replica.healthy()
            with pytest.raises(RuntimeError, match="stopped after"):
                eng.start()
            assert replica.probe() is True  # revive + restart
            assert replica.healthy() and eng._running
            assert eng.generate("back from the dead", SamplingParams(max_tokens=4, temperature=0.0))
        finally:
            eng.stop()

    def test_probe_never_starts_a_prefill_replica(self, jax):
        from modal_examples_tpu.scheduling import EngineReplica

        eng = _tiny_engine(jax)
        replica = EngineReplica(eng, "p0", role="prefill")
        eng._stopped_on_error = True
        assert replica.probe() is False  # health only: no revive, no start
        assert not eng._running and eng._stopped_on_error


class _FakeAllocator(SimpleNamespace):
    pass


def _fake_engine(*, depth=0, busy_slots=0, reserved=0, used=0, cached=0,
                 n_pages=9):
    return SimpleNamespace(
        policy=SimpleNamespace(total_depth=lambda: depth),
        slots=(
            [SimpleNamespace(free=False)] * busy_slots
            + [SimpleNamespace(free=True)] * (2 - min(busy_slots, 2))
        ),
        admission=SimpleNamespace(reserved_pages=reserved),
        cache=SimpleNamespace(
            n_pages=n_pages,
            allocator=SimpleNamespace(available=(n_pages - 1) - used),
        ),
        prefix_cache=(
            SimpleNamespace(cached_pages=cached) if cached or used else None
        ),
    )


class TestInvariantCheckers:
    """The chaos invariants against hand-built VIOLATING states — the
    checkers must actually detect what they claim to (a checker that
    returns [] for garbage would make every chaos run 'pass')."""

    def test_terminal_detects_wedge_and_missing_reason(self):
        from modal_examples_tpu.faults.chaos import check_terminal

        ok = {"id": "a", "finish_reason": "stop", "wedged": False}
        wedged = {"id": "b", "finish_reason": None, "wedged": True}
        missing = {"id": "c", "finish_reason": "", "wedged": False}
        assert check_terminal([ok]) == []
        out = check_terminal([ok, wedged, missing])
        assert len(out) == 2
        assert any("wedged" in v for v in out)

    def test_conservation_detects_vanished_requests(self):
        from modal_examples_tpu.faults.chaos import check_conservation

        assert check_conservation(5, 4, 1) == []
        out = check_conservation(5, 3, 1)
        assert out and "conservation" in out[0]

    def test_drained_detects_each_leak_class(self):
        from modal_examples_tpu.faults.chaos import check_drained

        assert check_drained({"ok": _fake_engine()}) == []
        assert "queued" in check_drained(
            {"e": _fake_engine(depth=2)}
        )[0]
        assert "slots" in check_drained(
            {"e": _fake_engine(busy_slots=1)}
        )[0]
        assert "reserved" in check_drained(
            {"e": _fake_engine(reserved=3)}
        )[0]
        # 2 pages allocated but only 1 accounted for by the prefix cache
        assert "orphaned" in check_drained(
            {"e": _fake_engine(used=2, cached=1)}
        )[0]
        # warmth is not a leak: used pages all prefix-cached
        assert check_drained({"e": _fake_engine(used=2, cached=2)}) == []

    def test_router_recovered_detects_stuck_down_replicas(self):
        from modal_examples_tpu.faults.chaos import check_router_recovered

        def fake_router(down, healthy=True):
            return SimpleNamespace(
                stats=lambda: {
                    "replicas": {
                        "r0": {"down": down, "healthy": healthy}
                    }
                }
            )

        assert check_router_recovered(fake_router(False)) == []
        assert check_router_recovered(fake_router(True))
        assert check_router_recovered(fake_router(False, healthy=False))

    def test_token_identity_detects_divergence_and_exempts_aborts(self):
        from modal_examples_tpu.faults.chaos import check_token_identity

        ref = {"p": "hello world"}
        good = {"id": "a", "prompt": "p", "output": "hello world",
                "finish_reason": "stop"}
        diverged = {"id": "b", "prompt": "p", "output": "hello wyrld",
                    "finish_reason": "stop"}
        errored = {"id": "c", "prompt": "p", "output": "hel",
                   "finish_reason": "error"}
        aborted = {"id": "d", "prompt": "p", "output": "",
                   "finish_reason": "stop", "aborted": True}
        assert check_token_identity([good, errored, aborted], ref) == []
        out = check_token_identity([diverged], ref)
        assert out and "diverged" in out[0]
