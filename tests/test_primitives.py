"""Unit tests for jax-free primitives: resources, retries, schedules, image,
volumes, secrets, dicts, queues. (Reference test strategy: SURVEY.md §4 —
cheap unit tier.)"""

import datetime as dt
import threading

import pytest

import modal_examples_tpu as mtpu
from modal_examples_tpu.core.resources import (
    InvalidTPUSpec,
    parse_tpu_request,
    parse_tpu_spec,
)
from modal_examples_tpu.core.retries import Retries, normalize_retries
from modal_examples_tpu.core.schedules import Cron, InvalidSchedule, Period
from modal_examples_tpu.storage.dict_queue import Empty


class TestTPUSpec:
    def test_parse_basic(self):
        s = parse_tpu_spec("v5e-8")
        assert s.generation == "v5e"
        assert s.chips == 8
        assert s.hosts == 1
        assert not s.multi_host

    def test_parse_multi_host(self):
        s = parse_tpu_spec("v5p-128")
        assert s.hosts == 32  # 4 chips/host
        assert s.multi_host

    def test_bare_generation_is_one_chip(self):
        assert parse_tpu_spec("v5e").chips == 1

    def test_fallback_list(self):
        specs = parse_tpu_request(["v5e-8", "v4-8"])
        assert [str(s) for s in specs] == ["v5e-8", "v4-8"]

    def test_invalid(self):
        with pytest.raises(InvalidTPUSpec):
            parse_tpu_spec("h100")
        with pytest.raises(InvalidTPUSpec):
            parse_tpu_spec("v5e-0")


class TestRetries:
    def test_backoff(self):
        r = Retries(max_retries=5, initial_delay=1.0, backoff_coefficient=2.0)
        assert r.delay_for_attempt(1) == 1.0
        assert r.delay_for_attempt(3) == 4.0

    def test_int_normalization(self):
        assert normalize_retries(3).max_retries == 3
        assert normalize_retries(None) is None


class TestSchedules:
    def test_period(self):
        p = Period(minutes=5)
        now = dt.datetime(2026, 7, 28, 12, 0, 0)
        assert p.next_fire(now) == now + dt.timedelta(minutes=5)

    def test_cron_every_minute(self):
        c = Cron("* * * * *")
        now = dt.datetime(2026, 7, 28, 12, 0, 30)
        assert c.next_fire(now) == dt.datetime(2026, 7, 28, 12, 1, 0)

    def test_cron_daily_9am(self):
        c = Cron("0 9 * * *")
        now = dt.datetime(2026, 7, 28, 10, 0)
        assert c.next_fire(now) == dt.datetime(2026, 7, 29, 9, 0)

    def test_cron_step_and_range(self):
        c = Cron("*/15 8-17 * * 1-5")
        fire = c.next_fire(dt.datetime(2026, 7, 25, 12, 0))  # a Saturday
        assert fire == dt.datetime(2026, 7, 27, 8, 0)  # Monday 8:00

    def test_cron_invalid(self):
        with pytest.raises(InvalidSchedule):
            Cron("* * *")
        with pytest.raises(InvalidSchedule):
            Cron("61 * * * *")


class TestImage:
    def test_chain_and_env(self):
        img = (
            mtpu.Image.debian_slim()
            .uv_pip_install("jax[tpu]", "flax")
            .apt_install("ffmpeg")
            .env({"HF_HUB_CACHE": "/cache"})
        )
        assert img.env_vars() == {"HF_HUB_CACHE": "/cache"}
        assert "flax" in img.python_packages()

    def test_digest_stable_and_order_sensitive(self):
        a = mtpu.Image.debian_slim().env({"A": "1"})
        b = mtpu.Image.debian_slim().env({"A": "1"})
        c = mtpu.Image.debian_slim().env({"A": "2"})
        assert a.digest() == b.digest()
        assert a.digest() != c.digest()

    def test_imports_ctx_suppresses_locally(self):
        img = mtpu.Image.debian_slim()
        with img.imports():
            import not_a_real_package  # noqa: F401

    def test_run_function_cached(self, state_dir):
        calls = []
        img = mtpu.Image.debian_slim().run_function(lambda: calls.append(1))
        img.build_local()
        img.build_local()
        assert calls == [1]

    def test_tpu_base_has_no_cuda(self):
        img = mtpu.Image.tpu_base()
        assert not any("cuda" in p.lower() for p in img.python_packages())
        assert any("jax" in p for p in img.python_packages())


class TestVolume:
    def test_commit_reload(self):
        vol = mtpu.Volume.from_name("test-vol", create_if_missing=True)
        vol.write_file("weights/model.bin", b"abc")
        v0 = vol.version
        vol.commit()
        assert vol.version == v0 + 1
        vol2 = mtpu.Volume.from_name("test-vol")
        vol2.reload()
        assert vol2.read_file("weights/model.bin") == b"abc"
        assert "weights/model.bin" in list(vol2.listdir("/", recursive=True))

    def test_path_escape_blocked(self):
        vol = mtpu.Volume.from_name("test-vol2", create_if_missing=True)
        with pytest.raises(PermissionError):
            vol.read_file("../../etc/passwd")

    def test_ephemeral(self):
        with mtpu.Volume.ephemeral() as vol:
            vol.write_file("x", b"1")
            assert vol.read_file("x") == b"1"

    def test_missing_raises(self):
        from modal_examples_tpu.storage.volume import VolumeNotFound

        with pytest.raises(VolumeNotFound):
            mtpu.Volume.from_name("never-created-vol")


class TestSecret:
    def test_from_dict_and_name(self):
        mtpu.Secret.create("hf-secret", {"HF_TOKEN": "tok"})
        s = mtpu.Secret.from_name("hf-secret", required_keys=["HF_TOKEN"])
        assert s.env_vars() == {"HF_TOKEN": "tok"}
        with pytest.raises(KeyError):
            mtpu.Secret.from_name("hf-secret", required_keys=["MISSING"])


class TestDictQueue:
    def test_dict_ops(self):
        with mtpu.Dict.ephemeral() as d:
            d["a"] = 1
            d.put("b", {"x": [1, 2]})
            assert d["a"] == 1
            assert d.get("b") == {"x": [1, 2]}
            assert "a" in d
            assert len(d) == 2
            assert d.pop("a") == 1
            assert d.get("a", "gone") == "gone"

    def test_queue_fifo_and_partitions(self):
        with mtpu.Queue.ephemeral() as q:
            q.put_many([1, 2, 3])
            q.put(99, partition="other")
            assert q.get() == 1
            assert q.get_many(5) == [2, 3]
            assert q.get(partition="other") == 99
            with pytest.raises(Empty):
                q.get(block=False)

    def test_queue_blocking_get(self):
        with mtpu.Queue.ephemeral() as q:
            def put_later():
                import time

                time.sleep(0.1)
                q.put("late")

            threading.Thread(target=put_later).start()
            assert q.get(timeout=2.0) == "late"

    def test_queue_timeout(self):
        with mtpu.Queue.ephemeral() as q:
            with pytest.raises(Empty):
                q.get(timeout=0.05)
