"""Flight recorder (ISSUE 15, docs/observability.md): the tsdb sampler's
segment ring + windowed queries, the declarative alert-rule state machine,
incident-bundle capture across every trigger, the CLI/gateway surfaces —
and the acceptance E2E: a forced silent-freeze wedge ships a bundle whose
MANIFEST references a non-empty tsdb window, the watchdog journal tail,
and the victim's open request traces.
"""

import json
import os
import time

import pytest

from modal_examples_tpu.observability import alerts as al
from modal_examples_tpu.observability import catalog as C
from modal_examples_tpu.observability import incident as inc
from modal_examples_tpu.observability import timeseries as ts
from modal_examples_tpu.observability.journal import named_journal
from modal_examples_tpu.utils.prometheus import Registry


def rec(at: float, **series) -> dict:
    """One hand-built scrape record: ``name=value`` for gauges,
    ``name=(kind, value, hsum)`` for anything else."""
    out = []
    for name, v in series.items():
        if isinstance(v, tuple):
            kind, value, hsum = v
        else:
            kind, value, hsum = "gauge", v, 0.0
        out.append([name, {}, kind, float(value), float(hsum)])
    return {"at": at, "series": out}


@pytest.fixture
def no_cooldown(monkeypatch):
    """Incident capture debounce is process-global state: isolate it."""
    monkeypatch.setattr(inc, "_last_capture", {})


# ---------------------------------------------------------------------------
# sampler / segments / windowed queries
# ---------------------------------------------------------------------------


class TestSampler:
    def test_sample_once_writes_ring_disk_and_telemetry(self, tmp_path):
        reg = Registry()
        reg.gauge_set("mtpu_active_slots", 3.0)
        reg.counter_inc("mtpu_generated_tokens_total", 7.0)
        reg.histogram_observe("mtpu_ttft_seconds", 0.5)
        s = ts.TsdbSampler(registry=reg, root=tmp_path, evaluate_alerts=False)
        for _ in range(3):
            s.sample_once()
        assert len(s.ring) == 3
        records = ts.read_window(root=tmp_path)
        assert len(records) == 3
        names = ts.series_names(records)
        assert "mtpu_active_slots" in names
        assert "mtpu_generated_tokens_total" in names
        # histograms carry (count, sum): rate() can recover seconds/s
        pts = ts.series_points(
            "mtpu_ttft_seconds", records, field="sum"
        )
        assert pts and pts[-1][1] == pytest.approx(0.5)
        # the sampler's own cost is recorded into the registry it scrapes
        assert reg.value(C.TSDB_SAMPLES_TOTAL) == 3.0
        assert reg.value(C.TSDB_SERIES) >= 3.0

    def test_segment_rotation_and_lru_prune(self, tmp_path):
        reg = Registry()
        reg.gauge_set("mtpu_active_slots", 1.0)
        s = ts.TsdbSampler(
            registry=reg, root=tmp_path, evaluate_alerts=False,
            segment_records=2, max_segments=2,
        )
        for _ in range(7):
            s.sample_once()
        segs = sorted((tmp_path / "tsdb").glob("seg-*.jsonl"))
        assert len(segs) <= 2  # LRU-pruned past the ring bound
        assert reg.value(C.TSDB_ROTATIONS_TOTAL) >= 2.0
        index = json.loads((tmp_path / "tsdb" / "index.json").read_text())
        assert index["samples"] == 7
        assert index["segments"] == [p.name for p in segs]
        # the newest records survive the prune
        records = ts.read_window(root=tmp_path)
        assert 1 <= len(records) <= 4

    def test_prune_spares_concurrent_writers_active_segment(self, tmp_path):
        reg = Registry()
        reg.gauge_set("mtpu_active_slots", 1.0)
        d = tmp_path / "tsdb"
        d.mkdir()
        # a FOREIGN segment being actively written by another MTPU_TSDB=1
        # process (fresh mtime) vs one from a long-dead run (old mtime)
        fresh = d / "seg-0000000000001-0001.jsonl"
        fresh.write_text(json.dumps(rec(1.0, x=1)) + "\n")
        stale = d / "seg-0000000000000-0001.jsonl"
        stale.write_text(json.dumps(rec(0.5, x=1)) + "\n")
        old = time.time() - ts.SEGMENT_PRUNE_GRACE_S - 5.0
        os.utime(stale, (old, old))
        s = ts.TsdbSampler(
            registry=reg, root=tmp_path, evaluate_alerts=False,
            segment_records=1, max_segments=2,
        )
        for _ in range(4):  # rotations force pruning past the bound
            s.sample_once()
        assert fresh.exists()  # the live writer's segment survived
        assert not stale.exists()  # the dead run's segment was pruned

    def test_read_window_bounds_and_limit(self, tmp_path):
        d = tmp_path / "tsdb"
        d.mkdir()
        lines = [json.dumps(rec(float(at), x=at)) for at in range(10)]
        (d / "seg-0000000000001-0001.jsonl").write_text(
            "\n".join(lines[:5]) + "\n"
        )
        (d / "seg-0000000000002-0002.jsonl").write_text(
            "\n".join(lines[5:]) + "\ntorn-tail-line{{{\n"
        )
        assert len(ts.read_window(root=tmp_path)) == 10
        win = ts.read_window(start=3.0, end=6.0, root=tmp_path)
        assert [r["at"] for r in win] == [3.0, 4.0, 5.0, 6.0]
        # limit keeps the NEWEST n
        assert [r["at"] for r in ts.read_window(root=tmp_path, limit=2)] == [
            8.0, 9.0,
        ]

    def test_series_points_folds_labels_by_agg(self):
        records = [{
            "at": 1.0,
            "series": [
                ["mtpu_kv_page_occupancy", {"r": "a"}, "gauge", 0.5, 0.0],
                ["mtpu_kv_page_occupancy", {"r": "b"}, "gauge", 0.9, 0.0],
            ],
        }]
        # a 0..1 fraction folds by max, never sum (the tpurun top rule)
        assert ts.series_points(
            "mtpu_kv_page_occupancy", records, agg="max"
        ) == [(1.0, 0.9)]
        assert ts.series_points(
            "mtpu_kv_page_occupancy", records,
            labels={"r": "a"}, agg="max",
        ) == [(1.0, 0.5)]

    def test_rate_is_counter_reset_aware(self):
        # restart zeroes the counter mid-window: the new absolute value
        # contributes, the prometheus rate() convention
        pts = [(0.0, 10.0), (1.0, 12.0), (2.0, 3.0)]
        assert ts.rate(pts) == pytest.approx((2.0 + 3.0) / 2.0)
        assert ts.rate(pts[:1]) is None

    def test_zero_cost_when_off(self, monkeypatch):
        monkeypatch.delenv(ts.TSDB_ENV, raising=False)
        assert ts.ensure_sampler() is None
        monkeypatch.setenv(ts.TSDB_ENV, "0")
        assert ts.ensure_sampler() is None
        assert ts.global_sampler() is None


# ---------------------------------------------------------------------------
# alert rules
# ---------------------------------------------------------------------------


class _Src:
    def __init__(self):
        self.records: list[dict] = []

    def recent(self, window_s=None):
        return list(self.records)


def _evaluator(rules, tmp_path, reg=None):
    src = _Src()
    ev = al.AlertEvaluator(
        rules, source=src, registry=reg or Registry(),
        journal_path=tmp_path / "alerts.jsonl",
    )
    return ev, src


class TestAlertRules:
    def test_threshold_fires_after_for_s_and_clears_after_clear_s(
        self, tmp_path
    ):
        reg = Registry()
        rule = al.AlertRule(
            name="kv", series="mtpu_kv_page_occupancy",
            threshold=0.9, for_s=2.0, clear_s=2.0,
        )
        ev, src = _evaluator((rule,), tmp_path, reg)
        src.records.append(rec(10.0, mtpu_kv_page_occupancy=0.95))
        assert ev.evaluate_once(now=10.0) == []  # held 0s < for_s
        src.records.append(rec(12.0, mtpu_kv_page_occupancy=0.96))
        out = ev.evaluate_once(now=12.0)
        assert [t["event"] for t in out] == ["fire"]
        assert ev.active() == ["kv"]
        assert reg.value(C.ALERTS_ACTIVE, {"rule": "kv"}) == 1.0
        assert reg.value(C.ALERTS_FIRED_TOTAL, {"rule": "kv"}) == 1.0
        # condition goes false: hysteresis holds the alert until clear_s
        src.records.append(rec(13.0, mtpu_kv_page_occupancy=0.1))
        assert ev.evaluate_once(now=13.0) == []
        assert ev.active() == ["kv"]
        src.records.append(rec(15.5, mtpu_kv_page_occupancy=0.1))
        out = ev.evaluate_once(now=15.5)
        assert [t["event"] for t in out] == ["clear"]
        assert ev.active() == []
        assert reg.value(C.ALERTS_ACTIVE, {"rule": "kv"}) == 0.0
        # clears don't count as fires
        assert reg.value(C.ALERTS_FIRED_TOTAL, {"rule": "kv"}) == 1.0
        # every transition journaled, replayable after the process dies
        events = [
            r["event"]
            for r in named_journal(
                "alerts", path=tmp_path / "alerts.jsonl"
            ).tail(10)
        ]
        assert events == ["fire", "clear"]

    def test_flap_inside_for_s_never_fires(self, tmp_path):
        rule = al.AlertRule(
            name="kv", series="mtpu_kv_page_occupancy",
            threshold=0.9, for_s=5.0,
        )
        ev, src = _evaluator((rule,), tmp_path)
        for i, v in enumerate((0.95, 0.2, 0.95, 0.2)):
            src.records.append(rec(10.0 + i, mtpu_kv_page_occupancy=v))
            assert ev.evaluate_once(now=10.0 + i) == []
        assert ev.active() == []

    def test_rate_rule_reads_histogram_burn(self, tmp_path):
        rule = al.AlertRule(
            name="stall", series="mtpu_decode_stall_seconds",
            kind="rate", field="sum", agg="sum",
            threshold=0.5, window_s=10.0,
        )
        ev, src = _evaluator((rule,), tmp_path)
        # 3 stall-seconds over 4s of window: 0.75/s > 0.5
        src.records.append(
            rec(10.0, mtpu_decode_stall_seconds=("histogram", 5, 1.0))
        )
        src.records.append(
            rec(14.0, mtpu_decode_stall_seconds=("histogram", 11, 4.0))
        )
        out = ev.evaluate_once(now=14.0)
        assert [t["event"] for t in out] == ["fire"]

    def test_absence_rule_guards_on_outstanding_work(self, tmp_path):
        rule = al.AlertRule(
            name="stuck", series="mtpu_generated_tokens_total",
            kind="absence", agg="sum", window_s=5.0,
            guard_series="mtpu_active_slots",
        )
        ev, src = _evaluator((rule,), tmp_path)
        # idle engine (guard 0): silence is healthy
        src.records.append(
            rec(10.0, mtpu_generated_tokens_total=("counter", 5, 0),
                mtpu_active_slots=0)
        )
        src.records.append(
            rec(12.0, mtpu_generated_tokens_total=("counter", 5, 0),
                mtpu_active_slots=0)
        )
        assert ev.evaluate_once(now=12.0) == []
        # active slots + flat counter = stagnation: fire
        src.records.append(
            rec(13.0, mtpu_generated_tokens_total=("counter", 5, 0),
                mtpu_active_slots=2)
        )
        src.records.append(
            rec(14.0, mtpu_generated_tokens_total=("counter", 5, 0),
                mtpu_active_slots=2)
        )
        out = ev.evaluate_once(now=14.0)
        assert [t["event"] for t in out] == ["fire"]
        # tokens move again: condition false (clear_s=0 clears at once)
        src.records.append(
            rec(15.0, mtpu_generated_tokens_total=("counter", 9, 0),
                mtpu_active_slots=2)
        )
        out = ev.evaluate_once(now=15.0)
        assert [t["event"] for t in out] == ["clear"]

    def test_absence_rule_is_counter_reset_aware(self, tmp_path):
        rule = al.AlertRule(
            name="stuck", series="mtpu_generated_tokens_total",
            kind="absence", agg="sum", window_s=30.0,
            guard_series="mtpu_active_slots",
        )
        ev, src = _evaluator((rule,), tmp_path)
        # a window spanning a process restart: 50000 pre-restart, counter
        # zeroed, 800 post-restart — tokens ARE flowing (rate() convention)
        src.records.append(
            rec(10.0, mtpu_generated_tokens_total=("counter", 50000, 0),
                mtpu_active_slots=2)
        )
        src.records.append(
            rec(15.0, mtpu_generated_tokens_total=("counter", 800, 0),
                mtpu_active_slots=2)
        )
        assert ev.evaluate_once(now=15.0) == []
        # once the window slides past the reset and the counter stays
        # flat, that IS genuine stagnation: fire
        for at in (20.0, 30.0, 46.0):
            src.records.append(
                rec(at, mtpu_generated_tokens_total=("counter", 800, 0),
                    mtpu_active_slots=2)
            )
        out = ev.evaluate_once(now=46.0)
        assert [t["event"] for t in out] == ["fire"]

    def test_capture_rule_ships_an_incident_bundle(
        self, tmp_path, no_cooldown
    ):
        rule = al.AlertRule(
            name="page_me", series="mtpu_kv_page_occupancy",
            threshold=0.9, capture=True,
        )
        src = _Src()
        ev = al.AlertEvaluator(
            (rule,), source=src, registry=Registry(), root=tmp_path,
        )
        src.records.append(rec(10.0, mtpu_kv_page_occupancy=0.95))
        out = ev.evaluate_once(now=10.0)
        assert [t["event"] for t in out] == ["fire"]
        manifests = inc.list_incidents(root=tmp_path)
        assert len(manifests) == 1
        assert manifests[0]["trigger"] == "alert"
        assert "page_me" in manifests[0]["reason"]

    def test_unknown_kind_and_op_fail_loudly(self):
        with pytest.raises(ValueError):
            al.AlertRule(name="x", series="s", kind="bogus")
        with pytest.raises(ValueError):
            al.AlertRule(name="x", series="s", op="!=")


# ---------------------------------------------------------------------------
# incident bundles
# ---------------------------------------------------------------------------


class _BundleFakeEngine:
    """The duck-typed surface _engine_section reads."""

    class _Slot:
        def __init__(self, request):
            self.request = request

    class _Req:
        def __init__(self, rid):
            self.request_id = rid
            self.trace = type("T", (), {"trace_id": rid})()

    def __init__(self):
        self.trace_name = "victim-0"
        self._running = True
        self._stopped_on_error = False
        self.impl_plan = {"attention": "ragged", "tp": 1}
        self.paged_impl = "pallas"
        self.scatter_impl = "xla"
        self.decode_block = 8
        self.error_count = 0
        self.error_log = []
        self.slots = [
            self._Slot(self._Req("req-bundle-1")),
            self._Slot(None),
        ]


class TestIncidentBundles:
    def _seed_state(self, tmp_path):
        """A tsdb window + journal tails for the collector to find."""
        reg = Registry()
        reg.gauge_set("mtpu_active_slots", 2.0)
        s = ts.TsdbSampler(registry=reg, root=tmp_path, evaluate_alerts=False)
        s.sample_once()
        s.sample_once()
        named_journal("watchdog", tmp_path).record(
            {"at": time.time(), "action": "transition", "state": "wedged"}
        )
        named_journal("chaos", tmp_path).record(
            {"at": time.time(), "episode": "seeded"}
        )

    def test_manual_capture_manifest_completeness(
        self, tmp_path, no_cooldown, monkeypatch
    ):
        import hashlib

        self._seed_state(tmp_path)
        monkeypatch.setattr(inc, "_engines", [])
        fake = _BundleFakeEngine()  # keep a strong ref: the registry is weak
        inc.register_engine(fake)
        bundle = inc.capture(
            "manual", reason="completeness", root=tmp_path, force=True
        )
        assert bundle is not None and bundle.is_dir()
        manifest = json.loads((bundle / "MANIFEST.json").read_text())
        assert manifest["trigger"] == "manual"
        assert manifest["tsdb_records"] == 2
        assert manifest["journals"]["watchdog"] == 1
        assert manifest["journals"]["chaos"] == 1
        assert manifest["engines"] == ["victim-0"]
        assert manifest["open_traces"] == ["req-bundle-1"]
        # every manifest file exists with a matching digest — the bundle
        # is content-addressed, a tampered file no longer matches
        for name, meta in manifest["files"].items():
            body = (bundle / name).read_bytes()
            assert len(body) == meta["bytes"]
            assert hashlib.sha256(body).hexdigest() == meta["sha256"]
        assert manifest["id"] == bundle.name
        env = json.loads((bundle / "env.json").read_text())
        assert "MTPU_STATE_DIR" in env["env"]
        engines = json.loads((bundle / "engines.json").read_text())
        assert engines[0]["impl_plan"]["attention"] == "ragged"
        assert engines[0]["occupied_slots"] == [
            {"slot": 0, "request_id": "req-bundle-1",
             "trace_id": "req-bundle-1"},
        ]

    def test_capture_reads_through_the_surfaces(
        self, tmp_path, no_cooldown
    ):
        self._seed_state(tmp_path)
        bundle = inc.capture("manual", root=tmp_path, force=True)
        m = inc.read_manifest(bundle.name, root=tmp_path)
        assert m["id"] == bundle.name
        # unique-prefix resolve, the TraceStore rule
        assert inc.read_manifest(bundle.name[:10], root=tmp_path)["id"] == m["id"]
        body = inc.read_bundle_file(bundle.name, "tsdb.jsonl", root=tmp_path)
        assert body and len(body.splitlines()) == 2
        # a name the manifest never wrote is refused (traversal guard)
        assert inc.read_bundle_file(
            bundle.name, "../../../etc/passwd", root=tmp_path
        ) is None
        assert inc.read_bundle_file(
            bundle.name, "MANIFEST.json", root=tmp_path
        ) is None

    def test_debounce_and_force(self, tmp_path, no_cooldown):
        assert inc.capture("manual", root=tmp_path) is not None
        # same trigger inside the cooldown: debounced
        assert inc.capture("manual", root=tmp_path) is None
        # a different trigger has its own clock
        assert inc.capture("chaos_invariant", root=tmp_path) is not None
        # force skips the debounce (the CLI path)
        named_journal("chaos", tmp_path).record({"at": 1.0, "x": 1})
        assert inc.capture("manual", root=tmp_path, force=True) is not None

    def test_debounce_is_per_replica(self, tmp_path, no_cooldown):
        # a correlated wedge hitting two replicas inside the cooldown must
        # bundle BOTH victims (the second error-stop sweeps its slots)
        assert inc.capture(
            "watchdog_wedge", replica="r0", root=tmp_path
        ) is not None
        assert inc.capture(
            "watchdog_wedge", replica="r1", root=tmp_path
        ) is not None
        assert inc.capture(
            "watchdog_wedge", replica="r0", root=tmp_path
        ) is None  # the same victim IS debounced

    def test_failed_capture_releases_debounce(
        self, tmp_path, no_cooldown, monkeypatch
    ):
        calls = {"n": 0}
        real = inc._capture_locked

        def flaky(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("disk full")
            return real(*a, **kw)

        monkeypatch.setattr(inc, "_capture_locked", flaky)
        assert inc.capture("manual", root=tmp_path) is None
        # the failure must not consume the debounce slot: an immediate
        # retry (the next poll re-firing the ladder) still ships a bundle
        assert inc.capture("manual", root=tmp_path) is not None

    def test_lru_prune(self, tmp_path, no_cooldown, monkeypatch):
        monkeypatch.setattr(inc, "MAX_INCIDENTS", 2)
        ids = []
        for i in range(3):
            # distinct evidence -> distinct content address
            named_journal("chaos", tmp_path).record({"at": float(i), "i": i})
            b = inc.capture("manual", root=tmp_path, force=True)
            ids.append(b.name)
            time.sleep(0.02)
        left = {p.name for p in (tmp_path / "incidents").glob("inc-*")}
        assert len(left) == 2
        assert ids[0] not in left  # oldest pruned first

    def test_unknown_trigger_fails_loudly(self, tmp_path):
        with pytest.raises(ValueError):
            inc.capture("bogus", root=tmp_path)

    def test_scheduler_crash_poison_captures(
        self, jax_cpu, tmp_path, no_cooldown, monkeypatch
    ):
        """The crash-poison trigger end to end: a strict-mode scheduler
        exception poisons the engine AND ships a bundle naming it."""
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine

        monkeypatch.setenv("MTPU_STATE_DIR", str(tmp_path))
        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
            page_size=8, prefill_buckets=(16,),
        )
        monkeypatch.setattr(
            eng, "step", lambda: (_ for _ in ()).throw(
                RuntimeError("forced scheduler bug")
            )
        )
        # the crash here is DELIBERATE: restore the session-wide sentinel
        # (conftest asserts no engine recorded a scheduler error)
        reports_before = list(LLMEngine._error_reports)
        try:
            eng.start()
            # the capture runs ON the dying scheduler thread after the
            # poison flag flips: wait for the bundle, not the flag
            deadline = time.monotonic() + 30
            while (
                not inc.list_incidents(root=tmp_path)
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            assert eng._stopped_on_error
            manifests = inc.list_incidents(root=tmp_path)
            assert [m["trigger"] for m in manifests] == ["scheduler_crash"]
            assert "forced scheduler bug" in manifests[0]["reason"]
            engines = json.loads(inc.read_bundle_file(
                manifests[0]["id"], "engines.json", root=tmp_path
            ))
            assert any(e["stopped_on_error"] for e in engines)
        finally:
            eng.stop()
            LLMEngine._error_reports[:] = reports_before

    def test_chaos_invariant_violation_captures(
        self, tmp_path, no_cooldown, monkeypatch
    ):
        """A failing fleet invariant ships a bundle (strict and lenient
        both) — the harness stubbed down to one violating episode."""
        from modal_examples_tpu.faults import chaos

        monkeypatch.setenv("MTPU_STATE_DIR", str(tmp_path))

        class _StubFleet:
            def __init__(self, seed):
                pass

            def close(self):
                pass

        bad = {
            "at": 1.0, "episode": "stub", "seed": 0, "injected": {},
            "hits": {}, "finished": {}, "shed": 0, "wedged": 1,
            "recovered": 0, "invariants": ["a stream wedged"],
        }
        monkeypatch.setattr(chaos, "_Fleet", _StubFleet)
        monkeypatch.setattr(chaos, "EPISODES", [("stub", {}, {})])
        monkeypatch.setattr(
            chaos, "_run_episode",
            lambda fleet, name, spec, seed, kw: dict(bad),
        )
        report = chaos.run_chaos(
            include_executor=False, strict=False, push=False,
            journal_path=tmp_path / "chaos.jsonl",
        )
        assert report["wedged"] == 1
        manifests = inc.list_incidents(root=tmp_path)
        assert [m["trigger"] for m in manifests] == ["chaos_invariant"]
        assert "stub" in manifests[0]["reason"]


# ---------------------------------------------------------------------------
# CLI / gateway surfaces
# ---------------------------------------------------------------------------


class TestSurfaces:
    def _seed(self, tmp_path):
        reg = Registry()
        reg.gauge_set("mtpu_active_slots", 2.0)
        reg.counter_inc("mtpu_generated_tokens_total", 4.0)
        s = ts.TsdbSampler(registry=reg, root=tmp_path, evaluate_alerts=False)
        for _ in range(3):
            reg.counter_inc("mtpu_generated_tokens_total", 2.0)
            s.sample_once()

    def test_cli_tsdb_summary_series_and_perfetto(self, tmp_path, capsys):
        from modal_examples_tpu.core.cli import main

        self._seed(tmp_path)
        assert main(["tsdb", "--dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "3 samples" in out and "mtpu_active_slots" in out
        assert main([
            "tsdb", "--dir", str(tmp_path),
            "--series", "mtpu_generated_tokens_total", "--rate",
        ]) == 0
        assert "/s over" in capsys.readouterr().out
        out_file = tmp_path / "tsdb.perfetto.json"
        assert main([
            "tsdb", "--dir", str(tmp_path), "--perfetto", str(out_file),
        ]) == 0
        doc = json.loads(out_file.read_text())
        counters = [
            e for e in doc["traceEvents"] if e.get("ph") == "C"
        ]
        assert counters, doc
        assert {"mtpu_active_slots", "mtpu_generated_tokens_total"} <= {
            e["name"] for e in counters
        }
        # the dedicated tsdb track is named
        assert any(
            e.get("name") == "thread_name"
            and e["args"]["name"] == "tsdb"
            for e in doc["traceEvents"]
        )

    def test_cli_metrics_watch_requires_tsdb_hint(self, tmp_path, capsys):
        """--watch with an empty tsdb prints the MTPU_TSDB hint (one
        refresh, then interrupted)."""
        from modal_examples_tpu.core import cli

        calls = {"n": 0}

        def fake_sleep(_s):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise KeyboardInterrupt

        import time as _time

        orig = _time.sleep
        _time.sleep = fake_sleep
        try:
            assert cli.main(
                ["metrics", "--watch", "0.01", "--dir", str(tmp_path)]
            ) == 0
        finally:
            _time.sleep = orig
        assert "MTPU_TSDB=1" in capsys.readouterr().out

    def test_cli_metrics_watch_renders_deltas(self, tmp_path, capsys):
        from modal_examples_tpu.core import cli

        self._seed(tmp_path)
        import time as _time

        orig = _time.sleep
        calls = {"n": 0}

        def fake_sleep(_s):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise KeyboardInterrupt

        _time.sleep = fake_sleep
        try:
            assert cli.main(
                ["metrics", "--watch", "0.01", "--dir", str(tmp_path)]
            ) == 0
        finally:
            _time.sleep = orig
        out = capsys.readouterr().out
        assert "mtpu_generated_tokens_total" in out
        assert "SERIES" in out and "DELTA" in out

    def test_cli_alerts_and_incidents(self, tmp_path, capsys, no_cooldown):
        from modal_examples_tpu.core.cli import main

        self._seed(tmp_path)
        assert main(["alerts", "--json", "--dir", str(tmp_path)]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert {r["rule"] for r in payload["rules"]} == {
            r.name for r in al.DEFAULT_RULES
        }
        assert payload["tsdb_samples"] == 3
        # capture -> list -> show round trip
        assert main([
            "incidents", "capture", "--reason", "cli-test",
            "--dir", str(tmp_path),
        ]) == 0
        bundle_path = capsys.readouterr().out.strip()
        assert bundle_path
        assert main(["incidents", "--json", "--dir", str(tmp_path)]) == 0
        listed = json.loads(capsys.readouterr().out)
        assert len(listed) == 1 and listed[0]["reason"] == "cli-test"
        assert listed[0]["tsdb_records"] == 3
        assert main([
            "incidents", "show", listed[0]["id"], "--dir", str(tmp_path),
        ]) == 0
        shown = json.loads(capsys.readouterr().out)
        assert shown["id"] == listed[0]["id"]
        assert main([
            "incidents", "show", listed[0]["id"],
            "--file", "tsdb.jsonl", "--dir", str(tmp_path),
        ]) == 0
        assert len(capsys.readouterr().out.splitlines()) == 3
        # stage wrapper path: an explicit non-manual trigger
        assert main([
            "incident", "capture", "--trigger", "stage_failure",
            "--reason", "stage 7", "--dir", str(tmp_path),
        ]) == 0
        capsys.readouterr()

    def test_gateway_alerts_and_incidents_routes(
        self, tmp_path, no_cooldown, monkeypatch
    ):
        import urllib.error
        import urllib.request

        from modal_examples_tpu.core.app import App
        from modal_examples_tpu.web.gateway import Gateway

        monkeypatch.setenv("MTPU_STATE_DIR", str(tmp_path))
        self._seed(tmp_path)
        bundle = inc.capture("manual", reason="gw", root=None, force=True)
        assert bundle is not None
        gw = Gateway(App("fr-gw")).start()
        try:
            base = gw.base_url

            def get(path):
                with urllib.request.urlopen(base + path, timeout=5) as r:
                    return r.status, json.loads(r.read().decode())

            status, alerts_payload = get("/alerts")
            assert status == 200
            assert {r["rule"] for r in alerts_payload["rules"]} == {
                r.name for r in al.DEFAULT_RULES
            }
            assert alerts_payload["active"] == []
            status, idx = get("/incidents")
            assert status == 200
            assert [m["id"] for m in idx["incidents"]] == [bundle.name]
            status, manifest = get(f"/incidents/{bundle.name}")
            assert status == 200 and manifest["trigger"] == "manual"
            status, file_payload = get(
                f"/incidents/{bundle.name}?file=env.json"
            )
            assert status == 200
            assert json.loads(file_payload["content"])["pid"]
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    base + "/incidents/inc-nope", timeout=5
                )
            assert exc.value.code == 404
        finally:
            gw.stop()


# ---------------------------------------------------------------------------
# the acceptance E2E: silent freeze -> wedge -> bundle
# ---------------------------------------------------------------------------


class TestWedgeShipsABundle:
    def test_silent_freeze_produces_bundle_with_evidence(
        self, jax_cpu, tmp_path, no_cooldown, monkeypatch
    ):
        """ISSUE 15 acceptance: a forced wedge under the chaos harness's
        silent-freeze fault produces an incident bundle whose MANIFEST
        references a non-empty tsdb window, the watchdog journal tail,
        and the victim's open request traces."""
        from modal_examples_tpu.faults.inject import FaultPlan, active
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )
        from modal_examples_tpu.serving import LLMEngine, SamplingParams
        from modal_examples_tpu.serving.health import (
            FleetWatchdog,
            WatchdogPolicy,
        )

        monkeypatch.setenv("MTPU_STATE_DIR", str(tmp_path))
        monkeypatch.setenv(ts.TSDB_ENV, "1")
        monkeypatch.setenv(ts.INTERVAL_ENV, "0.05")
        ts.stop_sampler()  # a fresh singleton under the patched env
        try:
            eng = LLMEngine(
                llama.LlamaConfig.tiny(), seed=0, max_slots=4,
                max_model_len=128, page_size=8, prefill_buckets=(16, 32),
            )
            assert ts.global_sampler() is not None  # MTPU_TSDB=1 took
            rep = EngineReplica(eng, "victim-a", role="unified")
            router = PrefixAffinityRouter([rep], reprobe_s=60.0)
            watchdog = FleetWatchdog(
                router,
                policy=WatchdogPolicy(
                    degraded_after_s=0.5, wedged_after_s=1.0,
                    quarantine_after=99,
                ),
                poll_s=0.1,
            )
            sp = SamplingParams(max_tokens=64, temperature=0.0)
            try:
                eng.start()
                reqs = [
                    rep.submit("the quick brown fox jumps", sp),
                    rep.submit("a different prompt entirely", sp),
                ]
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline and not all(
                    len(r.generated_tokens) >= 3 for r in reqs
                ):
                    time.sleep(0.005)
                assert all(len(r.generated_tokens) >= 3 for r in reqs)
                # engines warm + mid-decode: NOW freeze silently and let
                # the watchdog walk its ladder
                watchdog.start()
                plan = FaultPlan(
                    {"engine.scheduler_freeze": {"p": 1.0, "max_fires": 1}}
                )
                with active(plan):
                    deadline = time.monotonic() + 30
                    while (
                        not plan.fired() and time.monotonic() < deadline
                    ):
                        time.sleep(0.005)
                    assert plan.fired().get("engine.scheduler_freeze") == 1
                    deadline = time.monotonic() + 60
                    while time.monotonic() < deadline and not any(
                        m["trigger"] == "watchdog_wedge"
                        for m in inc.list_incidents(root=tmp_path)
                    ):
                        time.sleep(0.05)
            finally:
                watchdog.stop()
                eng.stop()
            wedge = [
                m for m in inc.list_incidents(root=tmp_path)
                if m["trigger"] == "watchdog_wedge"
            ]
            assert wedge, inc.list_incidents(root=tmp_path)
            m = wedge[0]
            assert m["replica"] == "victim-a"
            # (a) a non-empty tsdb window: the 0.05s sampler recorded the
            # minutes (well, seconds) leading up to the wedge
            assert m["tsdb_records"] > 0
            tsdb_body = inc.read_bundle_file(
                m["id"], "tsdb.jsonl", root=tmp_path
            )
            names = ts.series_names([
                json.loads(line) for line in tsdb_body.splitlines()
            ])
            assert "mtpu_generated_tokens_total" in names
            # (b) the watchdog journal tail, wedge transition included
            assert m["journals"].get("watchdog", 0) > 0
            wd_body = inc.read_bundle_file(
                m["id"], "journal_watchdog.jsonl", root=tmp_path
            )
            wd_records = [
                json.loads(line) for line in wd_body.splitlines()
            ]
            assert any(
                r.get("state") == "wedged" for r in wd_records
            ), wd_records
            # (c) the victim's open request traces: both mid-flight
            # requests, with the spans recorded so far
            assert set(m["open_traces"]) == {
                r.request_id for r in reqs
            }
            traces = json.loads(inc.read_bundle_file(
                m["id"], "traces.json", root=tmp_path
            ))
            for r in reqs:
                assert traces["open"].get(r.request_id), r.request_id
            # the engine fingerprint names the victim
            engines = json.loads(inc.read_bundle_file(
                m["id"], "engines.json", root=tmp_path
            ))
            assert any(e["replica"] == "victim-a" for e in engines)
        finally:
            ts.stop_sampler()
