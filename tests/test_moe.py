"""MoE tests: routing/capacity semantics, and expert-parallel equivalence —
the sharded all_to_all path must reproduce the single-device ground truth
exactly (same groups => same capacities => same drops)."""

import pytest

pytestmark = pytest.mark.slow  # heavyweight: excluded from the fast tier

import numpy as np


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def setup(jax):
    from modal_examples_tpu.models import moe

    cfg = moe.MoEConfig(n_experts=8, top_k=2, capacity_factor=1.5, d_model=32, d_ff=64)
    params = moe.init_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
    return cfg, params, x


class TestMoEDense:
    def test_output_shape_and_aux(self, jax, setup):
        from modal_examples_tpu.models import moe

        cfg, params, x = setup
        out, aux = moe.moe_mlp(params, x, cfg)
        assert out.shape == x.shape
        assert float(aux) >= 1.0 - 1e-5  # E * sum(f_i * p_i) >= 1 at minimum

    def test_generous_capacity_matches_full_computation(self, jax, setup):
        """With capacity >= tokens, nothing drops: the layer must equal the
        explicit 'every token through its top-k experts' computation."""
        import dataclasses

        import jax.numpy as jnp

        from modal_examples_tpu.models import moe

        cfg, params, x = setup
        big = dataclasses.replace(cfg, capacity_factor=100.0)
        out, _ = moe.moe_mlp(params, x, big)

        logits = x @ params["router"]
        probs = jax.nn.softmax(logits, -1)
        topk_p, topk_i = jax.lax.top_k(probs, big.top_k)
        topk_p = topk_p / topk_p.sum(-1, keepdims=True)
        want = jnp.zeros_like(x)
        for t in range(x.shape[0]):
            for k in range(big.top_k):
                e = int(topk_i[t, k])
                h = jax.nn.gelu(x[t] @ params["w_in"][e]) @ params["w_out"][e]
                want = want.at[t].add(float(topk_p[t, k]) * h)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-4)

    def test_tight_capacity_drops_tokens(self, jax, setup):
        import dataclasses

        import jax.numpy as jnp

        from modal_examples_tpu.models import moe

        cfg, params, x = setup
        tight = dataclasses.replace(cfg, capacity_factor=0.25)
        out, _ = moe.moe_mlp(params, x, tight)
        # some rows must be zero (fully dropped tokens exist at this capacity)
        row_norms = jnp.linalg.norm(out, axis=-1)
        assert float(row_norms.min()) == 0.0


class TestMoEExpertParallel:
    def test_ep_matches_dense_groups(self, jax, setup):
        from modal_examples_tpu.models import moe
        from modal_examples_tpu.parallel import make_mesh

        cfg, params, x = setup
        n_shards = 4
        mesh = make_mesh({"expert": n_shards})
        out_ep, aux_ep = moe.moe_mlp_ep(params, x, cfg, mesh)
        out_dense, aux_dense = moe.moe_mlp(params, x, cfg, groups=n_shards)
        np.testing.assert_allclose(
            np.asarray(out_ep), np.asarray(out_dense), atol=1e-4
        )
        np.testing.assert_allclose(float(aux_ep), float(aux_dense), atol=1e-5)

    def test_moe_llama_trains(self, jax):
        """End-to-end MoE LLM (Mixtral shape): forward + aux loss + a train
        step that decreases the total loss."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.training import (
            Trainer, cross_entropy_loss, make_optimizer,
        )

        cfg = llama.LlamaConfig(
            vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=64, max_seq_len=64, dtype="float32",
            n_experts=4, top_k_experts=2,
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
        logits, aux = llama.forward(
            params, tokens, cfg, attn_impl="xla", return_aux=True
        )
        assert logits.shape == (2, 32, 64)
        assert float(aux) > 0

        def loss_fn(p, batch):
            lg, aux = llama.forward(
                p, batch["tokens"], cfg, attn_impl="xla", return_aux=True
            )
            return (
                cross_entropy_loss(lg[:, :-1], batch["tokens"][:, 1:])
                + 0.01 * aux
            )

        t = Trainer(loss_fn, make_optimizer(1e-2))
        state = t.init_state(params)
        first = None
        for _ in range(8):
            state, m = t.train_step(state, {"tokens": tokens})
            first = first if first is not None else float(m["loss"])
        assert float(m["loss"]) < first

    def test_nodrop_swiglu_matches_explicit_loop(self, jax):
        """The serving MoE (no capacity drops) must equal the explicit
        'each token through its top-k SwiGLU experts' computation."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import moe

        T, D, F, E, k = 16, 32, 64, 4, 2
        keys = jax.random.split(jax.random.PRNGKey(0), 5)
        router = jax.random.normal(keys[0], (D, E)) * D**-0.5
        wg = jax.random.normal(keys[1], (E, D, F)) * D**-0.5
        wu = jax.random.normal(keys[2], (E, D, F)) * D**-0.5
        wd = jax.random.normal(keys[3], (E, F, D)) * F**-0.5
        x = jax.random.normal(keys[4], (T, D))

        out, aux = moe.moe_swiglu_nodrop(router, wg, wu, wd, x, k)
        assert float(aux) >= 1.0 - 1e-5

        probs = jax.nn.softmax(x @ router, -1)
        topk_p, topk_i = jax.lax.top_k(probs, k)
        topk_p = topk_p / topk_p.sum(-1, keepdims=True)
        want = jnp.zeros_like(x)
        for t in range(T):
            for j in range(k):
                e = int(topk_i[t, j])
                h = jax.nn.silu(x[t] @ wg[e]) * (x[t] @ wu[e])
                want = want.at[t].add(float(topk_p[t, j]) * (h @ wd[e]))
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-4)

    def test_ep_under_jit(self, jax, setup):
        import jax.numpy as jnp

        from modal_examples_tpu.models import moe
        from modal_examples_tpu.parallel import make_mesh

        cfg, params, x = setup
        mesh = make_mesh({"expert": 2})
        f = jax.jit(lambda p, x: moe.moe_mlp_ep(p, x, cfg, mesh)[0])
        out = f(params, x)
        assert bool(jnp.isfinite(out).all())


class TestMoEServing:
    """MoE through the serving paths (VERDICT #6): paged decode and prefill
    must reproduce the dense full-sequence forward — routing is per-token, so
    incremental and full-sequence computation agree exactly."""

    @pytest.fixture(scope="class")
    def served(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama

        cfg = llama.LlamaConfig(
            vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=64, max_seq_len=128, dtype="float32",
            n_experts=4, top_k_experts=2,
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        return cfg, params

    def test_paged_decode_matches_dense_forward(self, jax, served):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama

        cfg, params = served
        B, ps, pps = 2, 16, 4
        n_pages = 1 + B * pps
        shape = (cfg.n_layers, n_pages, ps, cfg.n_kv_heads, cfg.head_dim)
        pt = (1 + jnp.arange(B * pps, dtype=jnp.int32)).reshape(B, pps)
        active = jnp.ones((B,), bool)

        prompt = jnp.array([[1, 2, 3, 5, 0, 0], [7, 8, 9, 11, 13, 2]], jnp.int32)
        seq_lens = jnp.array([4, 6], jnp.int32)
        k_pg = jnp.zeros(shape, jnp.float32)
        v_pg = jnp.zeros(shape, jnp.float32)
        logits_p, k_pg, v_pg = llama.prefill(
            params, prompt, k_pg, v_pg, pt, seq_lens, cfg, attn_impl="xla"
        )

        # decode 4 more tokens (teacher-forced so the comparison is exact)
        chain = jnp.array([[3, 5, 2, 9], [1, 4, 6, 8]], jnp.int32)
        dec_logits = []
        for t in range(4):
            lg, k_pg, v_pg = llama.decode_step(
                params, chain[:, t], seq_lens + t, k_pg, v_pg, pt, active, cfg
            )
            dec_logits.append(lg)

        # dense ground truth: full-sequence forward over prompt + chain
        full = []
        for b, L in enumerate([4, 6]):
            seq = jnp.concatenate([prompt[b, :L], chain[b]])
            full.append(jnp.pad(seq, (0, 10 - L)))
        tokens = jnp.stack(full)
        logits_f = llama.forward(params, tokens, cfg, attn_impl="xla")

        for b, L in enumerate([4, 6]):
            # prefill's last-token logits == forward at position L-1
            np.testing.assert_allclose(
                np.asarray(logits_p[b]), np.asarray(logits_f[b, L - 1]),
                atol=2e-4,
            )
            for t in range(4):
                np.testing.assert_allclose(
                    np.asarray(dec_logits[t][b]),
                    np.asarray(logits_f[b, L + t]),
                    atol=2e-4,
                )

    def test_verify_step_matches_decode(self, jax, served):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama

        cfg, params = served
        B, ps, pps = 2, 16, 4
        n_pages = 1 + B * pps
        shape = (cfg.n_layers, n_pages, ps, cfg.n_kv_heads, cfg.head_dim)
        pt = (1 + jnp.arange(B * pps, dtype=jnp.int32)).reshape(B, pps)
        active = jnp.ones((B,), bool)
        prompt = jnp.array([[1, 2, 3, 5], [7, 8, 9, 11]], jnp.int32)
        seq_lens = jnp.array([4, 4], jnp.int32)
        k1 = jnp.zeros(shape, jnp.float32)
        v1 = jnp.zeros(shape, jnp.float32)
        _, k1, v1 = llama.prefill(
            params, prompt, k1, v1, pt, seq_lens, cfg, attn_impl="xla"
        )
        k2, v2 = k1, v1

        chain = jnp.array([[3, 5, 2], [1, 4, 6]], jnp.int32)
        logits_v, k1, v1 = llama.verify_step(
            params, chain, seq_lens, k1, v1, pt, active, cfg
        )
        for t in range(3):
            lg, k2, v2 = llama.decode_step(
                params, chain[:, t], seq_lens + t, k2, v2, pt, active, cfg
            )
            np.testing.assert_allclose(
                np.asarray(logits_v[:, t]), np.asarray(lg), atol=2e-4
            )

    def test_engine_serves_moe(self, jax):
        """End to end: the continuous-batching engine serves the Mixtral-shape
        config, greedy output matches an explicit dense-forward greedy loop
        token-for-token (the exact-vs-dense contract, vllm_inference.py:54-58
        parity)."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg = llama.LlamaConfig(
            vocab_size=512, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=64, max_seq_len=256, dtype="float32",
            n_experts=4, top_k_experts=2,
        )
        params = llama.init_params(jax.random.PRNGKey(3), cfg)
        eng = LLMEngine(
            cfg, params, max_slots=2, max_model_len=64, page_size=16,
            prefill_buckets=(32,), kv_dtype=jnp.float32, seed=0,
        )
        try:
            p = SamplingParams(max_tokens=8, temperature=0.0)
            got = eng.generate("mixture of experts", p)

            ids = list(eng.tokenizer.encode("mixture of experts"))
            gen = []
            for _ in range(8):
                lg = llama.forward(
                    params, jnp.asarray([ids + gen], jnp.int32), cfg,
                    attn_impl="xla",
                )
                nxt = int(jnp.argmax(lg[0, -1]))
                if nxt == eng.tokenizer.eos_id:
                    break
                gen.append(nxt)
            want = eng.tokenizer.decode(gen)
            assert got == want
        finally:
            eng.stop()


class TestMoECapacityRouted:
    def test_capacity_matches_nodrop_when_generous(self, jax):
        """With capacity >= all tokens, the GShard-dispatched SwiGLU path
        equals the no-drop serving path (dropping is the only difference)."""
        from modal_examples_tpu.models import moe

        T, D, F, E, k = 16, 32, 64, 4, 2
        keys = jax.random.split(jax.random.PRNGKey(2), 5)
        router = jax.random.normal(keys[0], (D, E)) * D**-0.5
        wg = jax.random.normal(keys[1], (E, D, F)) * D**-0.5
        wu = jax.random.normal(keys[2], (E, D, F)) * D**-0.5
        wd = jax.random.normal(keys[3], (E, F, D)) * F**-0.5
        x = jax.random.normal(keys[4], (T, D))

        want, aux_a = moe.moe_swiglu_nodrop(router, wg, wu, wd, x, k)
        got, aux_b = moe.moe_swiglu_capacity(
            router, wg, wu, wd, x, k, capacity_factor=100.0
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)
        np.testing.assert_allclose(float(aux_a), float(aux_b), atol=1e-5)

    def test_forward_capacity_impl_trains(self, jax):
        from modal_examples_tpu.models import llama

        cfg = llama.LlamaConfig(
            vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=64, max_seq_len=64, dtype="float32",
            n_experts=4, top_k_experts=2,
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        logits, aux = llama.forward(
            params, tokens, cfg, attn_impl="xla", return_aux=True,
            moe_impl="capacity",
        )
        assert logits.shape == (2, 16, 64)
        assert float(aux) > 0
