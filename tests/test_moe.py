"""MoE tests: routing/capacity semantics, and expert-parallel equivalence —
the sharded all_to_all path must reproduce the single-device ground truth
exactly (same groups => same capacities => same drops)."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def setup(jax):
    from modal_examples_tpu.models import moe

    cfg = moe.MoEConfig(n_experts=8, top_k=2, capacity_factor=1.5, d_model=32, d_ff=64)
    params = moe.init_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
    return cfg, params, x


class TestMoEDense:
    def test_output_shape_and_aux(self, jax, setup):
        from modal_examples_tpu.models import moe

        cfg, params, x = setup
        out, aux = moe.moe_mlp(params, x, cfg)
        assert out.shape == x.shape
        assert float(aux) >= 1.0 - 1e-5  # E * sum(f_i * p_i) >= 1 at minimum

    def test_generous_capacity_matches_full_computation(self, jax, setup):
        """With capacity >= tokens, nothing drops: the layer must equal the
        explicit 'every token through its top-k experts' computation."""
        import dataclasses

        import jax.numpy as jnp

        from modal_examples_tpu.models import moe

        cfg, params, x = setup
        big = dataclasses.replace(cfg, capacity_factor=100.0)
        out, _ = moe.moe_mlp(params, x, big)

        logits = x @ params["router"]
        probs = jax.nn.softmax(logits, -1)
        topk_p, topk_i = jax.lax.top_k(probs, big.top_k)
        topk_p = topk_p / topk_p.sum(-1, keepdims=True)
        want = jnp.zeros_like(x)
        for t in range(x.shape[0]):
            for k in range(big.top_k):
                e = int(topk_i[t, k])
                h = jax.nn.gelu(x[t] @ params["w_in"][e]) @ params["w_out"][e]
                want = want.at[t].add(float(topk_p[t, k]) * h)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-4)

    def test_tight_capacity_drops_tokens(self, jax, setup):
        import dataclasses

        import jax.numpy as jnp

        from modal_examples_tpu.models import moe

        cfg, params, x = setup
        tight = dataclasses.replace(cfg, capacity_factor=0.25)
        out, _ = moe.moe_mlp(params, x, tight)
        # some rows must be zero (fully dropped tokens exist at this capacity)
        row_norms = jnp.linalg.norm(out, axis=-1)
        assert float(row_norms.min()) == 0.0


class TestMoEExpertParallel:
    def test_ep_matches_dense_groups(self, jax, setup):
        from modal_examples_tpu.models import moe
        from modal_examples_tpu.parallel import make_mesh

        cfg, params, x = setup
        n_shards = 4
        mesh = make_mesh({"expert": n_shards})
        out_ep, aux_ep = moe.moe_mlp_ep(params, x, cfg, mesh)
        out_dense, aux_dense = moe.moe_mlp(params, x, cfg, groups=n_shards)
        np.testing.assert_allclose(
            np.asarray(out_ep), np.asarray(out_dense), atol=1e-4
        )
        np.testing.assert_allclose(float(aux_ep), float(aux_dense), atol=1e-5)

    def test_moe_llama_trains(self, jax):
        """End-to-end MoE LLM (Mixtral shape): forward + aux loss + a train
        step that decreases the total loss."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.training import (
            Trainer, cross_entropy_loss, make_optimizer,
        )

        cfg = llama.LlamaConfig(
            vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=64, max_seq_len=64, dtype="float32",
            n_experts=4, top_k_experts=2,
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
        logits, aux = llama.forward(
            params, tokens, cfg, attn_impl="xla", return_aux=True
        )
        assert logits.shape == (2, 32, 64)
        assert float(aux) > 0

        def loss_fn(p, batch):
            lg, aux = llama.forward(
                p, batch["tokens"], cfg, attn_impl="xla", return_aux=True
            )
            return (
                cross_entropy_loss(lg[:, :-1], batch["tokens"][:, 1:])
                + 0.01 * aux
            )

        t = Trainer(loss_fn, make_optimizer(1e-2))
        state = t.init_state(params)
        first = None
        for _ in range(8):
            state, m = t.train_step(state, {"tokens": tokens})
            first = first if first is not None else float(m["loss"])
        assert float(m["loss"]) < first

    def test_ep_under_jit(self, jax, setup):
        import jax.numpy as jnp

        from modal_examples_tpu.models import moe
        from modal_examples_tpu.parallel import make_mesh

        cfg, params, x = setup
        mesh = make_mesh({"expert": 2})
        f = jax.jit(lambda p, x: moe.moe_mlp_ep(p, x, cfg, mesh)[0])
        out = f(params, x)
        assert bool(jnp.isfinite(out).all())
