"""Correctness canary (docs/observability.md#correctness-canary).

Covers the four legs of ISSUE 18:

- identity discipline: the golden store REFUSES cross-fingerprint
  comparison (backend/generation/kv_dtype/tp/impl plan) with a loud
  banner — never a false drift verdict;
- the E2E acceptance chain on a live two-replica fleet: golden recorded,
  injected single-token decode corruption on one replica detected within
  two probe rounds, `canary_drift` incident captured naming the probe,
  replica down-weighted via ``router.set_health_weight`` while the
  healthy replica's canaries keep passing, canary tokens held OUT of
  every tenant's billing totals with conservation still closed;
- the jax-free read surfaces: ``tpurun canary [--json]`` and the gateway
  ``/`` discovery index + endpoint smoke matrix (every registered JSON
  route answers 200 + parseable JSON);
- the two alert rules (`canary_drift` / `canary_latency_burn`) against
  the stub-source evaluator, fed from the REAL emitted counters.
"""

import json
import time
import urllib.request

import pytest

from modal_examples_tpu.observability import canary as cn
from modal_examples_tpu.observability import catalog as C
from modal_examples_tpu.utils.prometheus import Registry

# ---------------------------------------------------------------------------
# identity discipline (jax-free)
# ---------------------------------------------------------------------------


def _fp(**over):
    base = {
        "backend": "cpu", "generation": "v5e", "attention": "xla",
        "ragged_variant": None, "scatter": "xla", "kv_dtype": "bf16",
        "tp": 1,
    }
    base.update(over)
    return base


class TestIdentityDiscipline:
    def test_same_identity_passes_silently(self):
        cn.verify_identity(_fp(), _fp())

    def test_cross_backend_refuses_with_banner(self):
        with pytest.raises(cn.CanaryIdentityError) as e:
            cn.verify_identity(_fp(), _fp(backend="tpu"))
        msg = str(e.value)
        assert "CANARY IDENTITY REFUSED" in msg
        assert "backend" in msg and "'cpu'" in msg and "'tpu'" in msg

    def test_cross_tp_names_the_tolerance_contract(self):
        # cross-TP token exactness is UNDEFINED: the refusal must point at
        # the logit-tolerance contract, not invite a re-record-and-retry
        with pytest.raises(cn.CanaryIdentityError) as e:
            cn.verify_identity(_fp(tp=1), _fp(tp=2))
        msg = str(e.value)
        assert "tensor_parallel" in msg
        assert "logit-tolerance" in msg

    def test_cross_kv_dtype_and_generation_refuse(self):
        for over in ({"kv_dtype": "int8"}, {"generation": "v4"}):
            with pytest.raises(cn.CanaryIdentityError):
                cn.verify_identity(_fp(), _fp(**over))

    def test_store_unrecorded_identity_loads_none(self, tmp_path):
        store = cn.GoldenStore(root=tmp_path)
        assert store.load("m1", _fp()) is None

    def test_store_roundtrip_and_fingerprint_in_path(self, tmp_path):
        store = cn.GoldenStore(root=tmp_path)
        probes = {"g0": {"tokens": [1, 2, 3]}}
        path = store.record("m1", _fp(), probes)
        assert cn.fingerprint_hash(_fp()) in path.name
        doc = store.load("m1", _fp())
        assert doc["probes"] == probes

    def test_store_refuses_hand_copied_cross_identity_file(self, tmp_path):
        # the fingerprint lives in the file NAME (two identities never
        # race one path) AND the BODY — a golden copied from another chip
        # into this identity's slot still refuses at load
        store = cn.GoldenStore(root=tmp_path)
        cpu_fp, tpu_fp = _fp(), _fp(backend="tpu", kv_dtype="int8")
        src = store.record("m1", cpu_fp, {"g0": {"tokens": [1]}})
        src.replace(store.path_for("m1", tpu_fp))
        with pytest.raises(cn.CanaryIdentityError) as e:
            store.load("m1", tpu_fp)
        assert "CANARY IDENTITY REFUSED" in str(e.value)

    def test_store_corrupt_file_refuses_loudly(self, tmp_path):
        store = cn.GoldenStore(root=tmp_path)
        path = store.record("m1", _fp(), {"g0": {"tokens": [1]}})
        path.write_text("{not json")
        with pytest.raises(cn.CanaryIdentityError):
            store.load("m1", _fp())


# ---------------------------------------------------------------------------
# the E2E acceptance chain: live two-replica fleet
# ---------------------------------------------------------------------------


@pytest.fixture
def fleet(jax_cpu):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.scheduling import (
        EngineReplica,
        PrefixAffinityRouter,
    )
    from modal_examples_tpu.serving import LLMEngine

    cfg = llama.LlamaConfig.tiny()
    eng_a = LLMEngine(
        cfg, seed=0, max_slots=2, max_model_len=64,
        prefill_buckets=(16, 32), page_size=8,
    )
    eng_b = LLMEngine(
        cfg, params=eng_a.params, max_slots=2, max_model_len=64,
        prefill_buckets=(16, 32), page_size=8,
    )
    rep_a = EngineReplica(eng_a, "cnry-a")
    rep_b = EngineReplica(eng_b, "cnry-b")
    router = PrefixAffinityRouter([rep_a, rep_b])
    eng_a.start()
    eng_b.start()
    try:
        yield rep_a, rep_b, router
    finally:
        eng_a.stop()
        eng_b.stop()


class TestCanaryE2E:
    def test_drift_detect_downweight_incident_and_clean_billing(
        self, fleet, tmp_path
    ):
        from modal_examples_tpu.faults.inject import FaultPlan, active
        from modal_examples_tpu.observability import incident as _incident

        rep_a, rep_b, router = fleet
        reg = Registry()
        prober = cn.CanaryProber(
            router,
            interval_s=3600.0,
            store=cn.GoldenStore(root=tmp_path),
            registry=reg,
            journal_path=tmp_path / "canary.jsonl",
            fail_threshold=2,
            degraded_weight=0.25,
        )

        # round 1 (clean): first replica records the golden, second
        # compares against it and passes — same seed-0 weights, greedy
        round1 = prober.probe_once()
        assert {r["result"] for r in round1[rep_a.name]} == {"recorded"}
        assert {r["result"] for r in round1[rep_b.name]} == {"pass"}
        model = cn.model_id(rep_a.engine.cfg)
        fp = cn.fingerprint(rep_a.engine)
        assert prober.store.path_for(model, fp).exists()

        # rounds 2-3: ONE flipped decode token per round, armed only
        # around rep_a's probes (the fault is canary-tenant gated, and
        # rep_b accepts no canary tokens while rep_a probes)
        drift_ids = set()
        for seed in (1, 2):
            plan = FaultPlan(
                {"engine.canary_token_corrupt": {"on_hit": 1}}, seed=seed
            )
            with active(plan):
                results_a = prober.probe_replica(rep_a)
            assert plan.fired(), "corruption never reached a probe token"
            drifted = [r for r in results_a if r["result"] == "drift"]
            assert drifted, results_a
            assert drifted[0]["mismatch_at"] == 0
            assert drifted[0]["expected"] != drifted[0]["tokens"]
            drift_ids.update(r["request_id"] for r in drifted)
            results_b = prober.probe_replica(rep_b)
            assert {r["result"] for r in results_b} == {"pass"}, (
                "healthy replica's canaries must keep passing"
            )

        snap = prober.snapshot()
        assert snap["streaks"][rep_a.name] == 2
        assert snap["streaks"][rep_b.name] == 0
        assert snap["downweighted"] == [rep_a.name]
        assert router.health_weight(rep_a.name) == 0.25
        assert router.health_weight(rep_b.name) == 1.0

        # the incident bundle names the mismatching probe request (the
        # per-(trigger, replica) debounce means rounds 2+3 may share one
        # bundle — whichever round captured, its probe id is in drift_ids)
        bundles = [
            m for m in _incident.list_incidents()
            if m.get("trigger") == "canary_drift"
            and m.get("replica") == rep_a.name
        ]
        assert bundles, "drift captured no incident bundle"
        assert any(
            rid in b.get("reason", "") for b in bundles for rid in drift_ids
        ), (drift_ids, [b.get("reason") for b in bundles])

        # series: drift counted on the drifting replica only
        assert reg.value(C.CANARY_DRIFT_TOTAL, {"replica": rep_a.name}) == 2
        assert reg.value(C.CANARY_DRIFT_TOTAL, {"replica": rep_b.name}) == 0
        assert reg.value(C.CANARY_FAILING, {"replica": rep_a.name}) == 2

        # round 4 (clean): the first passing round restores full weight
        results = prober.probe_replica(rep_a)
        assert {r["result"] for r in results} == {"pass"}
        assert router.health_weight(rep_a.name) == 1.0
        assert prober.snapshot()["downweighted"] == []
        actions = [
            r["action"]
            for r in prober._journal.tail(100)
            if "action" in r
        ]
        assert "recorded" in actions and "round" in actions
        assert "down_weight" in actions and "restore_weight" in actions

        # synthetic-traffic hygiene: zero canary tokens in ANY tenant's
        # billing totals, conservation still closed (buckets + canary
        # side-channel == the engine's own counters, exactly)
        for rep in (rep_a, rep_b):
            usage = rep.engine.usage.tenants()
            assert not any(
                row["tenant"] == cn.CANARY_TENANT for row in usage["tenants"]
            )
            stats = rep.engine.stats
            assert (
                usage["totals"]["prompt_tokens"]
                + usage["canary"]["prompt_tokens"]
                == stats.prompt_tokens
            )
            assert (
                usage["totals"]["generated_tokens"]
                + usage["canary"]["generated_tokens"]
                == stats.generated_tokens
            )
            assert usage["canary"]["generated_tokens"] > 0
        # ... and the usage journal (the billing export) carries no
        # canary lines
        from modal_examples_tpu.observability.journal import named_journal

        assert not any(
            r.get("tenant") == cn.CANARY_TENANT
            for r in named_journal("usage").tail(500)
        )
        # the excluded tokens ARE counted in the canary series — the
        # engine's throttled refresh may have flushed part-way through,
        # always into the default registry, so assert there after an
        # explicit flush drains the remainder
        from modal_examples_tpu.utils.prometheus import default_registry

        rep_a.engine.usage.flush()
        assert default_registry.total(
            C.CANARY_TOKENS_TOTAL, {"kind": "generated"}
        ) >= usage["canary"]["generated_tokens"]

    def test_probe_skips_slo_histograms(self, fleet):
        # canary probes must not feed the unlabeled TTFT/TPOT histograms
        # (they drive SLO burn and the autoscaler); the dedicated canary
        # histograms get the measurements instead
        from modal_examples_tpu.utils.prometheus import default_registry

        rep_a, _rep_b, _router = fleet
        reg = Registry()
        before = default_registry.total(C.TTFT_SECONDS)
        results = cn.probe_engine(
            rep_a.engine, replica=rep_a.name, golden=None, registry=reg,
        )
        assert {r["result"] for r in results} == {"recorded"}
        assert default_registry.total(C.TTFT_SECONDS) == before
        assert reg.total(C.CANARY_TTFT_SECONDS) == len(results)
        assert reg.total(C.CANARY_E2E_SECONDS) == len(results)

    def test_identity_refusal_journals_and_keeps_probing(
        self, fleet, tmp_path
    ):
        # a tampered golden for ONE replica's identity must not stop the
        # round: the refusal journals `identity_refused` + an error probe,
        # and the rest of the fleet still gets probed
        rep_a, rep_b, router = fleet
        store = cn.GoldenStore(root=tmp_path)
        model = cn.model_id(rep_a.engine.cfg)
        live_fp = cn.fingerprint(rep_a.engine)
        alien = dict(live_fp, backend="tpu", tp=8)
        path = store.record(model, alien, {"g0": {"tokens": [1]}})
        path.replace(store.path_for(model, live_fp))
        reg = Registry()
        prober = cn.CanaryProber(
            router, interval_s=3600.0, store=store, registry=reg,
            journal_path=tmp_path / "canary.jsonl",
        )
        per_replica = prober.probe_once()
        # both replicas share the identity: both rounds refused
        assert per_replica == {}
        recs = prober._journal.tail(10)
        refused = [r for r in recs if r.get("action") == "identity_refused"]
        assert {r["replica"] for r in refused} == {rep_a.name, rep_b.name}
        assert "CANARY IDENTITY REFUSED" in refused[0]["error"]
        assert reg.value(
            C.CANARY_PROBES_TOTAL,
            {"replica": rep_a.name, "result": "error"},
        ) == 1


class TestProberLoop:
    def test_background_loop_rounds_and_live_registration(self, tmp_path):
        class _Router:
            replicas: list = []

        prober = cn.CanaryProber(
            _Router(), interval_s=0.02,
            store=cn.GoldenStore(root=tmp_path),
            journal_path=tmp_path / "canary.jsonl",
        )
        assert cn.live_prober() is None
        prober.start()
        try:
            assert cn.live_prober() is prober
            deadline = time.monotonic() + 5.0
            while prober.rounds < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert prober.rounds >= 2
        finally:
            prober.stop()
        assert cn.live_prober() is None

    def test_interval_env_is_the_default(self, monkeypatch, tmp_path):
        monkeypatch.setenv(cn.INTERVAL_ENV, "7.5")

        class _Router:
            replicas: list = []

        prober = cn.CanaryProber(
            _Router(), store=cn.GoldenStore(root=tmp_path),
            journal_path=tmp_path / "canary.jsonl",
        )
        assert prober.interval_s == 7.5


# ---------------------------------------------------------------------------
# the alert rules, fed from the real counters via the stub source
# ---------------------------------------------------------------------------


class TestCanaryAlertRules:
    def _evaluator(self, name, tmp_path):
        from modal_examples_tpu.observability import alerts as al

        rules = [r for r in al.DEFAULT_RULES if r.name == name]
        assert len(rules) == 1

        class Src:
            def __init__(self):
                self.records = []

            def recent(self, window_s=None):
                return list(self.records)

        src = Src()
        ev = al.AlertEvaluator(
            (rules[0],), source=src, registry=Registry(),
            journal_path=tmp_path / "alerts.jsonl",
        )
        return ev, src

    def test_canary_drift_fires_on_any_drift_in_window(self, tmp_path):
        ev, src = self._evaluator("canary_drift", tmp_path)

        def rec(at, total):
            return {"at": at, "series": [
                [C.CANARY_DRIFT_TOTAL, {"replica": "r0"}, "counter",
                 total, 0.0],
            ]}

        src.records.append(rec(10.0, 0.0))
        assert ev.evaluate_once(now=10.0) == []
        src.records.append(rec(40.0, 0.0))
        assert ev.evaluate_once(now=40.0) == []  # no drift: quiet
        src.records.append(rec(70.0, 1.0))  # one drifted probe
        out = ev.evaluate_once(now=70.0)
        assert [t["event"] for t in out] == ["fire"]

    def test_canary_latency_burn_fires_on_slow_probes(self, tmp_path):
        ev, src = self._evaluator("canary_latency_burn", tmp_path)

        def rec(at, hsum):
            return {"at": at, "series": [
                [C.CANARY_E2E_SECONDS, {"replica": "r0"}, "histogram",
                 3.0, hsum],
            ]}

        src.records.append(rec(10.0, 0.5))
        assert ev.evaluate_once(now=10.0) == []
        # probe seconds accumulating faster than threshold (2 s/s)
        src.records.append(rec(40.0, 90.5))
        out = ev.evaluate_once(now=40.0)
        assert [t["event"] for t in out] == ["fire"]


# ---------------------------------------------------------------------------
# jax-free read surfaces: CLI + gateway
# ---------------------------------------------------------------------------


class TestCliCanary:
    def test_cmd_canary_json_reads_journal_and_metrics(
        self, tmp_path, capsys
    ):
        from modal_examples_tpu.core.cli import cmd_canary
        from modal_examples_tpu.observability.journal import named_journal

        j = named_journal("canary", path=tmp_path / "canary.jsonl")
        j.record({
            "at": 1.0, "action": "round", "replica": "r0", "streak": 0,
            "results": {"g0": "pass", "g1": "pass", "g2": "pass"},
        })
        j.record({
            "at": 2.0, "action": "down_weight", "replica": "r0",
            "weight": 0.25, "streak": 2,
        })
        reg = Registry()
        reg.counter_inc(
            C.CANARY_PROBES_TOTAL, 5.0,
            {"replica": "r0", "result": "pass"},
        )
        reg.counter_inc(
            C.CANARY_PROBES_TOTAL, 1.0,
            {"replica": "r0", "result": "drift"},
        )
        reg.counter_inc(C.CANARY_DRIFT_TOTAL, 1.0, {"replica": "r0"})
        reg.gauge_set(C.CANARY_FAILING, 2.0, {"replica": "r0"})
        mdir = tmp_path / "metrics"
        mdir.mkdir()
        (mdir / "job1.prom").write_text(reg.expose())

        assert cmd_canary(["--json", "--dir", str(tmp_path)]) == 0
        out = json.loads(capsys.readouterr().out)
        row = [r for r in out["replicas"] if r["replica"] == "r0"]
        assert row and row[0]["pass"] == 5.0 and row[0]["drift"] == 1.0
        assert row[0]["drift_total"] == 1.0
        assert row[0]["failing_streak"] == 2.0
        assert [r["action"] for r in out["records"]] == [
            "round", "down_weight",
        ]

    def test_cmd_canary_text_renders_table(self, tmp_path, capsys):
        from modal_examples_tpu.core.cli import cmd_canary
        from modal_examples_tpu.observability.journal import named_journal

        named_journal("canary", path=tmp_path / "canary.jsonl").record({
            "at": 1.0, "action": "identity_refused", "replica": "r1",
            "error": "banner",
        })
        assert cmd_canary(["--dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "identity_refused" in out and "r1" in out


class TestGatewayDiscoveryAndSmoke:
    def test_root_index_matches_builtin_routes(self):
        from modal_examples_tpu.web import gateway as gw

        idx = gw._root_index()
        assert set(idx["routes"]) == {
            f"/{label}" for label in gw.BUILTIN_ROUTES
        }

    def test_every_builtin_route_answers_on_a_live_gateway(
        self, monkeypatch
    ):
        """The smoke matrix (ISSUE 18 satellite): every registered surface
        answers 200 on a live gateway; every one but ``/metrics``
        (prometheus text) parses as JSON; and the ``/`` discovery index
        lists exactly the registered routes — a surface cannot land
        without being discoverable."""
        import modal_examples_tpu as mtpu
        from modal_examples_tpu.web import gateway as gw

        # generous SLO budgets: the session registry may carry earlier
        # test files' deliberate failures; /healthz must answer 200 here
        for var in (
            "MTPU_SLO_TTFT_P95_S", "MTPU_SLO_TPOT_P95_S",
            "MTPU_SLO_CALL_P95_S",
        ):
            monkeypatch.setenv(var, "1000000")
        monkeypatch.setenv("MTPU_SLO_ERROR_RATE", "1.0")
        monkeypatch.setenv("MTPU_SLO_RETRY_RATE", "1.0")

        server = gw.Gateway(mtpu.App("canary-smoke")).start()
        try:
            with urllib.request.urlopen(
                f"{server.base_url}/", timeout=10
            ) as r:
                index = json.loads(r.read())
            assert set(index["routes"]) == {
                f"/{label}" for label in gw.BUILTIN_ROUTES
            }
            for label in gw.BUILTIN_ROUTES:
                with urllib.request.urlopen(
                    f"{server.base_url}/{label}", timeout=10
                ) as r:
                    body = r.read()
                    assert r.status == 200, label
                if label == "metrics":
                    continue  # prometheus text, not JSON
                payload = json.loads(body)
                assert isinstance(payload, dict), label
        finally:
            server.stop()

    def test_gateway_canary_snapshot_shape(self):
        from modal_examples_tpu.web.gateway import _canary_snapshot

        snap = _canary_snapshot(last=5)
        assert set(snap) == {"probes", "drift", "failing", "prober", "journal"}
        assert isinstance(snap["journal"], list)
