"""Distributed request tracing (observability/reqtrace.py, ISSUE 9): the
context/span unit surface (sampling, wire round trip, no-dangling-span
sweep), multi-store stitching + id-namespace resolution, the engine-level
trace of a unified request, `tpurun explain`, the replica-aware Perfetto
export, and the bench regression detector (`tpurun benchdiff`)."""

import json

import pytest

from modal_examples_tpu.observability import catalog as C
from modal_examples_tpu.observability import reqtrace as rt
from modal_examples_tpu.observability.trace import TraceStore


@pytest.fixture()
def store(tmp_path):
    return TraceStore(root=tmp_path / "traces")


# ---------------------------------------------------------------------------
# context / sampling / wire unit surface
# ---------------------------------------------------------------------------


class TestContext:
    def test_mint_records_nothing_until_a_span_lands(self, store, tmp_path):
        ctx = rt.start_request_trace(store=store)
        assert ctx is not None and ctx.trace_id.startswith("req-")
        assert list((tmp_path / "traces").glob("*.jsonl")) == []
        rt.event(ctx, "shed", reason="queue_full")
        assert store.read(ctx.trace_id), "event must land in the store"

    def test_sampling_is_deterministic_and_env_driven(self, monkeypatch):
        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "0")
        assert rt.start_request_trace("req-abc") is None
        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "1")
        assert rt.start_request_trace("req-abc") is not None
        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "0.5")
        # same id -> same decision, every time, everywhere
        decisions = {rt.sampled(f"req-{i:04d}") for i in range(64)}
        assert decisions == {True, False}  # a real split at 0.5
        for i in range(16):
            rid = f"req-{i:04d}"
            assert rt.sampled(rid) == rt.sampled(rid)

    def test_trace_disabled_kills_request_tracing(self, monkeypatch):
        monkeypatch.setenv("MTPU_TRACE", "0")
        assert rt.start_request_trace() is None

    def test_finish_root_sweeps_open_spans_and_is_idempotent(self, store):
        ctx = rt.start_request_trace(store=store)
        sp = rt.begin(ctx, "queue", priority="default")
        assert ctx.open_spans() == ["queue"]
        rt.finish_root(ctx, "error", finish_reason="error")
        assert ctx.open_spans() == []
        rt.finish_root(ctx, "ok", finish_reason="stop")  # no-op
        spans = store.read(ctx.trace_id)
        roots = [s for s in spans if s["name"] == "request"]
        assert len(roots) == 1
        assert roots[0]["attrs"]["finish_reason"] == "error"
        queue = [s for s in spans if s["name"] == "queue"]
        assert len(queue) == 1 and queue[0]["status"] == "error"
        # a finish after the sweep must not duplicate the record
        rt.finish(ctx, sp)
        assert len(store.read(ctx.trace_id)) == len(spans)

    def test_wire_round_trip_does_not_duplicate_the_root(self, store, tmp_path):
        ctx = rt.start_request_trace(store=store)
        mig = rt.begin(ctx, "migrate", source="a", target="b")
        w = rt.wire(ctx, parent=mig.span_id)
        assert w == {"trace_id": ctx.trace_id, "parent_id": mig.span_id}
        other = TraceStore(root=tmp_path / "other")
        remote = rt.from_wire(json.loads(json.dumps(w)), store=other)
        sp = rt.begin(remote, "adopt", replica="dec-0")
        rt.finish(remote, sp)
        rt.finish_root(remote, "ok", finish_reason="stop")
        remote_spans = other.read(ctx.trace_id)
        # the receiving side records its span PARENTED at the wire parent,
        # but never a second root — the minting side owns it
        assert [s["name"] for s in remote_spans] == ["adopt"]
        assert remote_spans[0]["parent_id"] == mig.span_id
        assert rt.wire(None) is None and rt.from_wire(None) is None

    def test_from_wire_rejects_hostile_trace_ids(self, store):
        """The wire is untrusted peer input and the trace id becomes a
        filename: ids that aren't request-id-shaped are rejected, never
        written."""
        for tid in ("../../../home/user/x", "in-abc", "", "req-a/b", None):
            assert rt.from_wire({"trace_id": tid}) is None, tid
        assert rt.from_wire(
            {"trace_id": "req-abc123", "parent_id": "sp-1"}, store=store
        ) is not None

    def test_ambient_frame_attaches_fault_events(self, store):
        ctx = rt.start_request_trace(store=store)
        rt.note_fault("engine.out_of_pages")  # no frame: no-op
        with rt.active(ctx, replica="rep-a"):
            rt.note_fault("engine.out_of_pages")
        with rt.active(None):
            rt.note_fault("engine.out_of_pages")  # unsampled: must not leak
        faults = [s for s in store.read(ctx.trace_id) if s["name"] == "fault"]
        assert len(faults) == 1
        assert faults[0]["attrs"] == {
            "replica": "rep-a", "point": "engine.out_of_pages",
        }


class TestStoresAndResolve:
    def _record(self, store, trace_id, name, span_id, parent=None, t=1.0):
        store.record({
            "trace_id": trace_id, "span_id": span_id, "parent_id": parent,
            "name": name, "start": t, "end": t + 0.1, "status": "ok",
            "attrs": {},
        })

    def test_read_trace_merges_and_dedupes_across_stores(self, tmp_path):
        a = TraceStore(root=tmp_path / "a")
        b = TraceStore(root=tmp_path / "b")
        self._record(a, "req-xyz", "request", "sp-1", t=1.0)
        self._record(a, "req-xyz", "prefill", "sp-2", "sp-1", t=2.0)
        self._record(b, "req-xyz", "decode", "sp-3", "sp-1", t=3.0)
        self._record(b, "req-xyz", "prefill", "sp-2", "sp-1", t=2.0)  # dup
        merged = rt.read_trace("req-xyz", stores=[a, b])
        assert [s["span_id"] for s in merged] == ["sp-1", "sp-2", "sp-3"]

    def test_resolve_either_namespace_and_unique_prefix(self, tmp_path):
        st = TraceStore(root=tmp_path)
        self._record(st, "in-aabbcc", "call", "sp-1")
        self._record(st, "req-ddeeff", "request", "sp-2")
        self._record(st, "req-ddee00", "request", "sp-3")
        assert st.resolve("in-aabbcc") == "in-aabbcc"
        assert st.resolve("in-aab") == "in-aabbcc"
        assert st.resolve("req-ddeeff") == "req-ddeeff"
        assert st.resolve("req-ddee") is None  # ambiguous prefix
        assert st.resolve("nope") is None
        # hostile tokens resolve to None, never a glob/path error — these
        # arrive straight off the gateway URL
        for evil in ("/etc/passwd", "**", "a/b", "..", "in-*", "req-["):
            assert st.resolve(evil) is None, evil
        assert rt.resolve("in-aab", stores=[st]) == "in-aabbcc"
        assert rt.trace_kind("in-aabbcc") == "call"
        assert rt.trace_kind("req-ddeeff") == "request"

    def test_merged_list_traces_covers_every_store(self, tmp_path):
        a = TraceStore(root=tmp_path / "a")
        b = TraceStore(root=tmp_path / "b")
        self._record(a, "req-aaa", "request", "sp-1")
        self._record(b, "req-bbb", "request", "sp-2")
        assert set(rt.list_traces(stores=[a, b])) == {"req-aaa", "req-bbb"}


# ---------------------------------------------------------------------------
# engine-level: a unified request leaves one complete, closed trace
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def traced_engine(jax_cpu):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    eng = LLMEngine(
        llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
        prefill_buckets=(16, 32), page_size=4,
    )
    yield eng
    eng.stop()


class TestEngineTrace:
    def test_unified_request_trace_tree(self, traced_engine):
        from modal_examples_tpu.serving import SamplingParams

        req = traced_engine.submit(
            "hello trace", SamplingParams(max_tokens=4, temperature=0.0),
            priority="interactive", tenant="t1",
        )
        "".join(traced_engine.stream(req))
        assert req.trace is not None
        assert req.trace.open_spans() == []
        spans = rt.read_trace(req.request_id)
        by = {}
        for s in spans:
            by.setdefault(s["name"], []).append(s)
        assert {"request", "queue", "prefill", "decode"} <= set(by)
        root = by["request"][0]
        assert root["parent_id"] is None
        assert root["attrs"]["finish_reason"] in ("stop", "length")
        assert root["attrs"]["n_generated"] == req.n_generated
        assert root["attrs"]["ttft_s"] > 0
        for name in ("queue", "prefill", "decode"):
            assert by[name][0]["parent_id"] == root["span_id"], name
            assert by[name][0]["end"] is not None
        assert by["queue"][0]["attrs"]["priority"] == "interactive"
        assert by["queue"][0]["attrs"]["tenant"] == "t1"

    def test_shed_finishes_the_root_with_status_shed(self, jax_cpu):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.scheduling.admission import (
            AdmissionConfig, AdmissionController, ShedError,
        )
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=1, max_model_len=32,
            prefill_buckets=(16,), page_size=4,
            admission=AdmissionController(
                AdmissionConfig(max_queue={
                    "interactive": 0, "default": 0, "batch": 0,
                })
            ),
        )
        with pytest.raises(ShedError):
            eng.submit("shed me", SamplingParams(max_tokens=2))
        # the request never entered a queue, but its trace closed honestly
        shed_traces = [
            tid for tid in rt.default_store.list_traces(limit=10)
            for s in rt.default_store.read(tid)
            if s["name"] == "request"
            and s["attrs"].get("finish_reason") == "shed"
        ]
        assert shed_traces

    def test_abort_of_queued_request_closes_the_queue_span(
        self, traced_engine
    ):
        from modal_examples_tpu.serving import SamplingParams

        # never start()ed scheduler? engine runs; submit then abort fast —
        # the queued-removal path releases the caller AND the spans
        req = traced_engine.make_request(
            "abort me", SamplingParams(max_tokens=4)
        )
        traced_engine.submit_request(req)
        traced_engine.abort(req)
        "".join(traced_engine.stream(req))
        assert req.trace is not None and req.trace.open_spans() == []
        spans = rt.read_trace(req.request_id)
        assert all(s["end"] is not None for s in spans)

    def test_sampled_out_request_serves_without_a_trace(
        self, traced_engine, monkeypatch
    ):
        from modal_examples_tpu.serving import SamplingParams

        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "0")
        req = traced_engine.submit("untraced", SamplingParams(max_tokens=2))
        out = "".join(traced_engine.stream(req))
        assert req.trace is None
        assert req.finish_reason in ("stop", "length")
        assert isinstance(out, str)

    def test_sampled_out_decision_propagates_without_a_reroll(
        self, traced_engine
    ):
        """An entry point that sampled the request OUT passes trace=None
        down the chain — no layer may re-mint (re-rolling would inflate
        the effective sample rate and split entry attribution)."""
        from modal_examples_tpu.serving import SamplingParams

        req = traced_engine.submit(
            "decided untraced", SamplingParams(max_tokens=2), trace=None
        )
        "".join(traced_engine.stream(req))
        assert req.trace is None
        # UNSET (the default) still mints at the engine
        req2 = traced_engine.submit("minted", SamplingParams(max_tokens=2))
        "".join(traced_engine.stream(req2))
        assert req2.trace is not None
        assert rt.resolve_entry_trace(None, "router") is None


# ---------------------------------------------------------------------------
# explain + CLI + perfetto export
# ---------------------------------------------------------------------------


class TestExplainAndExport:
    def test_explain_cli_renders_request_narrative(
        self, traced_engine, capsys
    ):
        from modal_examples_tpu.core.cli import main as cli_main
        from modal_examples_tpu.serving import SamplingParams

        req = traced_engine.submit(
            "explain me please", SamplingParams(max_tokens=3, temperature=0.0)
        )
        "".join(traced_engine.stream(req))
        assert cli_main(["explain", req.request_id]) == 0
        out = capsys.readouterr().out
        assert req.request_id in out and "serving request trace" in out
        assert "queued" in out and "prefill on" in out and "decode on" in out
        # unique-prefix resolution works too
        assert cli_main(["explain", req.request_id[:10]]) == 0
        assert req.request_id in capsys.readouterr().out

    def test_explain_says_which_kind_for_call_traces(self, tmp_path, capsys):
        from modal_examples_tpu.core.cli import main as cli_main

        st = TraceStore(root=tmp_path)
        st.record({
            "trace_id": "in-123456", "span_id": "sp-1", "parent_id": None,
            "name": "call", "start": 1.0, "end": 2.0, "status": "ok",
            "attrs": {"function": "f"},
        })
        assert cli_main(["explain", "in-123456", "--dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "executor call trace" in out

    def test_explain_unknown_id_exits_loudly(self):
        from modal_examples_tpu.core.cli import main as cli_main

        with pytest.raises(SystemExit):
            cli_main(["explain", "req-doesnotexist"])

    def test_perfetto_export_replica_tracks_are_deterministic(self):
        from modal_examples_tpu.observability.export import (
            spans_to_chrome_trace,
        )

        spans = [
            {"trace_id": "req-x", "span_id": "sp-1", "parent_id": None,
             "name": "request", "start": 1.0, "end": 2.0, "status": "ok",
             "attrs": {"replica": "gateway"}},
            {"trace_id": "req-x", "span_id": "sp-2", "parent_id": "sp-1",
             "name": "prefill", "start": 1.1, "end": 1.4, "status": "ok",
             "attrs": {"replica": "pre-0"}},
            {"trace_id": "req-x", "span_id": "sp-3", "parent_id": "sp-1",
             "name": "adopt", "start": 1.5, "end": 1.6, "status": "ok",
             "attrs": {"replica": "dec-0"}},
        ]
        doc1 = spans_to_chrome_trace(spans, "req-x")
        doc2 = spans_to_chrome_trace(list(reversed(spans)), "req-x")
        assert doc1 == doc2, "track assignment must be deterministic"
        tracks = {
            ev["args"]["name"]: ev["tid"]
            for ev in doc1["traceEvents"]
            if ev["ph"] == "M" and ev["name"] == "thread_name"
        }
        assert set(tracks) == {"gateway", "pre-0", "dec-0"}
        assert len(set(tracks.values())) == 3, "one track per replica"
        tid_of = {
            ev["args"]["span_id"]: ev["tid"]
            for ev in doc1["traceEvents"]
            if ev["ph"] == "X"
        }
        assert tid_of["sp-2"] == tracks["pre-0"]
        assert tid_of["sp-3"] == tracks["dec-0"]
        # migration span link: flow start on the prefill track, finish on
        # the adopt track, matching ids
        flows = [e for e in doc1["traceEvents"] if e["ph"] in ("s", "f")]
        assert len(flows) == 2
        s_ev = next(e for e in flows if e["ph"] == "s")
        f_ev = next(e for e in flows if e["ph"] == "f")
        assert s_ev["id"] == f_ev["id"]
        assert s_ev["tid"] == tracks["pre-0"]
        assert f_ev["tid"] == tracks["dec-0"]

    def test_perfetto_profile_ride_along_on_replica_tracks(self):
        """Hot-path profiler ride-along (docs/observability.md): tick-phase
        counter tracks and compile slices land on the OWNING replica's
        track — including a replica only the profile knows — with the
        deterministic ordering of the PR-9 layout preserved (same doc for
        reversed span input and reordered profile dicts)."""
        from modal_examples_tpu.observability.export import (
            spans_to_chrome_trace,
        )

        spans = [
            {"trace_id": "req-y", "span_id": "sp-1", "parent_id": None,
             "name": "request", "start": 10.0, "end": 12.0, "status": "ok",
             "attrs": {"replica": "dec-0"}},
            {"trace_id": "req-y", "span_id": "sp-2", "parent_id": "sp-1",
             "name": "prefill", "start": 10.1, "end": 10.4, "status": "ok",
             "attrs": {"replica": "pre-0"}},
        ]
        profile = {
            "dec-0": {
                "ticks": [
                    {"at": 11.0, "total": 0.004, "device": 0.001,
                     "phases": {"decode_dispatch": 0.003,
                                "harvest": 0.001}},
                ],
                "compiles": [
                    {"at": 10.9, "seconds": 0.5, "program": "block",
                     "shape_key": "s4k8", "event": "end", "cache": "miss"},
                ],
            },
            # a replica with NO spans in this trace still gets its own
            # deterministic track
            "pre-1": {"ticks": [
                {"at": 10.5, "total": 0.002, "device": 0.0,
                 "phases": {"prefill_dispatch": 0.002}},
            ], "compiles": []},
        }
        doc1 = spans_to_chrome_trace(spans, "req-y", profile=profile)
        doc2 = spans_to_chrome_trace(
            list(reversed(spans)),
            "req-y",
            profile=dict(reversed(list(profile.items()))),
        )
        assert doc1 == doc2, "profile ride-along must stay deterministic"
        tracks = {
            ev["args"]["name"]: ev["tid"]
            for ev in doc1["traceEvents"]
            if ev["ph"] == "M" and ev["name"] == "thread_name"
        }
        assert {"dec-0", "pre-0", "pre-1"} <= set(tracks)
        counters = [e for e in doc1["traceEvents"] if e["ph"] == "C"]
        assert len(counters) == 2
        dec_counter = next(
            e for e in counters if e["tid"] == tracks["dec-0"]
        )
        assert dec_counter["name"] == "tick_phase_ms"
        assert dec_counter["args"]["decode_dispatch"] == pytest.approx(3.0)
        pre1_counter = next(
            e for e in counters if e["tid"] == tracks["pre-1"]
        )
        assert pre1_counter["args"]["prefill_dispatch"] == pytest.approx(2.0)
        compile_slices = [
            e for e in doc1["traceEvents"]
            if e["ph"] == "X" and e["name"].startswith("compile:")
        ]
        assert len(compile_slices) == 1
        sl = compile_slices[0]
        assert sl["name"] == "compile:block"
        assert sl["tid"] == tracks["dec-0"]
        assert sl["dur"] == pytest.approx(0.5 * 1e6)
        assert sl["args"]["shape_key"] == "s4k8"
        # plain span export (no profile kwarg) is bit-for-bit unchanged
        assert spans_to_chrome_trace(spans, "req-y") == spans_to_chrome_trace(
            spans, "req-y", profile=None
        )

    def test_call_traces_keep_the_legacy_two_track_layout(self):
        from modal_examples_tpu.observability.export import (
            spans_to_chrome_trace,
        )

        spans = [
            {"trace_id": "in-1", "span_id": "a", "parent_id": None,
             "name": "call", "start": 1.0, "end": 2.0, "status": "ok",
             "attrs": {}},
            {"trace_id": "in-1", "span_id": "b", "parent_id": "a",
             "name": "execute", "start": 1.2, "end": 1.8, "status": "ok",
             "attrs": {}},
        ]
        doc = spans_to_chrome_trace(spans, "in-1")
        tid_of = {
            ev["args"]["span_id"]: ev["tid"]
            for ev in doc["traceEvents"]
            if ev["ph"] == "X"
        }
        assert tid_of["a"] == 1 and tid_of["b"] == 2


# ---------------------------------------------------------------------------
# bench regression detector (`tpurun benchdiff` / benchmarks/bench_diff.py)
# ---------------------------------------------------------------------------


def _bench_doc(tok_s, ttft_p95, shed_rate, mig_p95=None):
    doc = {
        "value": tok_s,
        "all_configs": {"tiny": tok_s, "llama2-7b": tok_s * 0.4},
        "token_latency": {
            "ttft": {"p50": ttft_p95 / 2, "p95": ttft_p95, "count": 8},
            "tpot": {"p50": 0.01, "p95": 0.02, "count": 100},
        },
        "scheduling": {"shed_rate": shed_rate},
    }
    if mig_p95 is not None:
        doc["disagg"] = {
            "migration_latency": {"p50": mig_p95 / 2, "p95": mig_p95}
        }
    return doc


class TestBenchDiff:
    def test_no_regression_exits_zero(self, tmp_path, capsys):
        from modal_examples_tpu.core.cli import main as cli_main

        old = tmp_path / "old.json"
        new = tmp_path / "new.json"
        old.write_text(json.dumps(_bench_doc(100.0, 0.5, 0.0, 0.010)))
        new.write_text(json.dumps(_bench_doc(104.0, 0.48, 0.0, 0.009)))
        assert cli_main(["benchdiff", str(old), str(new)]) == 0
        out = capsys.readouterr().out
        assert "no regressions" in out and "all_configs.tiny" in out

    def test_throughput_regression_exits_nonzero(self, tmp_path, capsys):
        from modal_examples_tpu.core.cli import main as cli_main

        old = tmp_path / "old.json"
        new = tmp_path / "new.json"
        old.write_text(json.dumps(_bench_doc(100.0, 0.5, 0.0)))
        new.write_text(json.dumps(_bench_doc(70.0, 0.5, 0.0)))
        assert cli_main(["benchdiff", str(old), str(new)]) == 1
        out = capsys.readouterr().out
        assert "REGRESSED" in out and "value" in out

    def test_latency_and_rate_regressions_detected(self, tmp_path):
        from modal_examples_tpu.utils.bench_diff import compare

        old = _bench_doc(100.0, 0.5, 0.0, 0.010)
        new = _bench_doc(100.0, 0.9, 0.25, 0.030)
        regressed = {
            r["metric"] for r in compare(old, new) if r["regressed"]
        }
        assert "token_latency.ttft.p95" in regressed
        assert "scheduling.shed_rate" in regressed  # abs: 0 -> 0.25
        assert "disagg.migration_latency.p95" in regressed

    def test_threshold_flag_and_wrapper_format(self, tmp_path):
        from modal_examples_tpu.utils.bench_diff import load_bench, run_diff

        # the BENCH_r*.json driver wrapper resolves through "parsed"
        wrapped = tmp_path / "wrapped.json"
        wrapped.write_text(json.dumps(
            {"n": 3, "parsed": _bench_doc(100.0, 0.5, 0.0)}
        ))
        assert load_bench(wrapped)["value"] == 100.0
        new = tmp_path / "new.json"
        new.write_text(json.dumps(_bench_doc(94.0, 0.5, 0.0)))
        # -6% tok/s: regression at 5%, fine at 10%
        assert run_diff([str(wrapped), str(new), "--threshold", "5"]) == 1
        assert run_diff([str(wrapped), str(new), "--threshold", "10"]) == 0

    def test_missing_sections_are_skipped_not_fatal(self):
        from modal_examples_tpu.utils.bench_diff import compare

        rows = compare({"value": 10.0}, _bench_doc(10.0, 0.5, 0.0))
        assert [r["metric"] for r in rows] == ["value"]

    def test_usage_errors_exit_two(self, tmp_path):
        from modal_examples_tpu.utils.bench_diff import run_diff

        assert run_diff([]) == 2
        assert run_diff([str(tmp_path / "nope.json"),
                         str(tmp_path / "nope2.json")]) == 2


# ---------------------------------------------------------------------------
# catalog hygiene (the span-side mirror of TestCatalog)
# ---------------------------------------------------------------------------


class TestSpanCatalog:
    def test_span_catalog_shape(self):
        for name, meta in C.SPAN_CATALOG.items():
            assert name.isidentifier(), name
            assert isinstance(meta["attrs"], list) and meta["help"], name
            assert "replica" in meta["attrs"] or name == "request", (
                f"{name}: every span should be replica-attributable"
            )
        assert C.ALL_SPAN_NAMES == frozenset(C.SPAN_CATALOG)
        assert rt.ROOT_SPAN in C.ALL_SPAN_NAMES
