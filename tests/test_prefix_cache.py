"""Prefix cache: trie mechanics, refcounting, eviction, and end-to-end
engine behavior (shared prompt pages + unchanged outputs)."""

import numpy as np
import pytest


class TestPrefixCacheUnit:
    def _mk(self, n_pages=32, page_size=4):
        from modal_examples_tpu.serving.kv_cache import PageAllocator
        from modal_examples_tpu.serving.prefix_cache import PrefixCache

        alloc = PageAllocator(n_pages)
        return PrefixCache(alloc, page_size), alloc

    def test_acquire_miss_insert_then_hit(self):
        pc, alloc = self._mk()
        tokens = list(range(10))  # 2 full pages + partial
        shared, n = pc.acquire(tokens)
        assert shared == [] and n == 0
        pages = alloc.alloc(3)
        final, displaced = pc.insert(tokens, pages[:2], 0)
        assert final == pages[:2] and displaced == []
        shared2, n2 = pc.acquire(tokens)
        assert shared2 == pages[:2] and n2 == 8
        # a different prompt with the same first page shares one page
        other = list(range(4)) + [99, 98, 97, 96]
        shared3, n3 = pc.acquire(other)
        assert shared3 == pages[:1] and n3 == 4

    def test_concurrent_insert_displaces_duplicate(self):
        pc, alloc = self._mk()
        tokens = list(range(8))
        a_pages = alloc.alloc(2)
        b_pages = alloc.alloc(2)
        fa, da = pc.insert(tokens, a_pages, 0)
        fb, db = pc.insert(tokens, b_pages, 0)
        assert fa == a_pages and da == []
        assert fb == a_pages and db == b_pages  # b adopts a's pages

    def test_release_and_evict(self):
        pc, alloc = self._mk(n_pages=8)
        tokens = list(range(8))
        pages = alloc.alloc(2)
        final, _ = pc.insert(tokens, pages, 0)
        before = alloc.available
        assert pc.evict(2) == 0  # refcount 1: not evictable
        pc.release(final)
        assert pc.evict(2) == 2  # now reclaimed
        assert alloc.available == before + 2
        # gone from the trie
        shared, _ = pc.acquire(tokens)
        assert shared == []

    def test_evict_leaves_before_parents(self):
        pc, alloc = self._mk()
        tokens = list(range(12))  # 3 full pages, nested chain
        pages = alloc.alloc(3)
        final, _ = pc.insert(tokens, pages, 0)
        pc.release(final)
        assert pc.evict(1) == 1
        # the leaf (page 3) went first; prefix still serves hits
        shared, n = pc.acquire(tokens)
        assert len(shared) == 2 and n == 8


class TestEnginePrefixSharing:
    @pytest.fixture(scope="class")
    def engine(self, jax_cpu):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine

        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=4, max_model_len=128,
            page_size=16, prefill_buckets=(64,), seed=0,
        )
        yield eng
        eng.stop()

    def test_same_prompt_shares_pages_and_output_unchanged(self, engine):
        from modal_examples_tpu.serving import SamplingParams

        # a prompt spanning >1 full page (page_size 16, bos + 40 bytes)
        prompt = "shared system prompt: answer briefly. " * 2
        p = SamplingParams(max_tokens=4, temperature=0.0)
        a = engine.generate(prompt, p)
        hits0 = engine.prefix_cache.hits
        b = engine.generate(prompt, p)
        assert engine.prefix_cache.hits > hits0  # second request hit the trie
        assert a == b  # sharing must not change greedy output
        assert engine.prefix_cache.cached_pages > 0

    def test_chunked_prefill_long_prompt(self, engine, jax_cpu):
        """A prompt beyond the largest bucket (64) prefills in chunks and
        must produce the same greedy completion as a single-shot prefill."""
        import dataclasses

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        prompt = "x" * 100  # 101 tokens with bos > bucket 64
        p = SamplingParams(max_tokens=4, temperature=0.0)
        chunked_out = engine.generate(prompt, p)

        wide = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=256,
            page_size=16, prefill_buckets=(128,), seed=0,
        )
        try:
            single_out = wide.generate(prompt, p)
        finally:
            wide.stop()
        assert chunked_out == single_out

    def test_allocator_balance_after_many_requests(self, engine):
        from modal_examples_tpu.serving import SamplingParams

        alloc = engine.cache.allocator
        for i in range(6):
            engine.generate(
                f"prompt variant {i} " * 3, SamplingParams(max_tokens=3)
            )
        # all pages either free or cached-with-zero-refs (no leaks)
        import time

        time.sleep(0.2)
        assert alloc.available + engine.prefix_cache.cached_pages == engine.cache.n_pages - 1
