"""Sandbox exec API + profiler wrapper tests."""

import sys

import pytest

import modal_examples_tpu as mtpu


class TestSandbox:
    def test_exec_streams_and_exit_codes(self):
        sb = mtpu.Sandbox.create(timeout=60)
        try:
            p = sb.exec(sys.executable, "-c", "print('out'); import sys; print('err', file=sys.stderr)")
            assert p.wait() == 0
            assert p.stdout.read().strip() == "out"
            assert p.stderr.read().strip() == "err"
            bad = sb.exec(sys.executable, "-c", "raise SystemExit(3)")
            assert bad.wait() == 3
        finally:
            sb.cleanup()

    def test_env_scrubbed(self):
        import os

        os.environ["SUPER_SECRET_TEST_VAR"] = "leak-me"
        try:
            sb = mtpu.Sandbox.create(timeout=30)
            p = sb.exec(
                sys.executable, "-c",
                "import os; print('SUPER_SECRET_TEST_VAR' in os.environ)",
            )
            p.wait()
            assert p.stdout.read().strip() == "False"
            sb.cleanup()
        finally:
            del os.environ["SUPER_SECRET_TEST_VAR"]

    def test_secrets_and_image_env_injected(self):
        img = mtpu.Image.debian_slim().env({"FROM_IMAGE": "yes"})
        sec = mtpu.Secret.from_dict({"FROM_SECRET": "yes"})
        sb = mtpu.Sandbox.create(image=img, secrets=[sec], timeout=30)
        p = sb.exec(
            sys.executable, "-c",
            "import os; print(os.environ['FROM_IMAGE'], os.environ['FROM_SECRET'])",
        )
        p.wait()
        assert p.stdout.read().strip() == "yes yes"
        sb.cleanup()

    def test_open_confined_to_sandbox(self):
        sb = mtpu.Sandbox.create(timeout=30)
        with sb.open("notes/x.txt", "w") as f:
            f.write("hi")
        with sb.open("notes/x.txt") as f:
            assert f.read() == "hi"
        with pytest.raises(PermissionError):
            sb.open("../../etc/passwd")
        sb.cleanup()

    def test_volume_mount(self):
        vol = mtpu.Volume.from_name("sb-test-vol", create_if_missing=True)
        vol.write_file("data.txt", b"volume-data")
        sb = mtpu.Sandbox.create(volumes={"/data": vol}, timeout=30)
        p = sb.exec(sys.executable, "-c", "print(open('data/data.txt').read())")
        p.wait()
        assert p.stdout.read().strip() == "volume-data"
        sb.cleanup()

    def test_terminate_kills_processes(self):
        import time

        sb = mtpu.Sandbox.create(timeout=60)
        p = sb.exec(sys.executable, "-c", "import time; time.sleep(60)")
        assert sb.poll() is None
        sb.terminate()
        time.sleep(0.3)
        assert p.poll() is not None
        sb.cleanup()

    def test_from_id_and_list(self):
        sb = mtpu.Sandbox.create(timeout=30)
        assert mtpu.Sandbox.from_id(sb.object_id) is sb
        assert sb in mtpu.Sandbox.list()
        sb.cleanup()
        assert sb not in mtpu.Sandbox.list()

    def test_forward_tunnel(self):
        with mtpu.forward(8123) as tunnel:
            assert tunnel.url == "http://127.0.0.1:8123"


class TestProfiling:
    def test_profile_call(self, jax_cpu, tmp_path):
        import jax.numpy as jnp

        from modal_examples_tpu.utils.profiling import profile_call

        jax = jax_cpu
        f = jax.jit(lambda x: x @ x)
        x = jnp.ones((64, 64))
        out, result = profile_call(
            f, x, warmup=1, iterations=3, trace_dir=tmp_path / "trace"
        )
        assert out.shape == (64, 64)
        assert result.iterations == 3
        assert result.per_iter_s > 0
        assert list((tmp_path / "trace").rglob("*")), "no trace written"
        assert "per-iteration" in result.summary()
