"""Training tests: sharded train step converges on a tiny overfit task;
checkpoint save/restore round-trips; graft dryrun path compiles and runs."""

import pytest

pytestmark = pytest.mark.slow  # heavyweight: excluded from the fast tier

import numpy as np


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


class TestTrainer:
    def test_overfit_tiny_batch(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.training import (
            Trainer,
            cross_entropy_loss,
            make_optimizer,
        )

        cfg = llama.LlamaConfig(
            vocab_size=64, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)

        def loss_fn(params, batch):
            logits = llama.forward(params, batch["tokens"], cfg, attn_impl="xla")
            return cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])

        trainer = Trainer(loss_fn, make_optimizer(1e-2))
        state = trainer.init_state(params)
        batch = {
            "tokens": jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 64)
        }
        first = None
        for _ in range(20):
            state, metrics = trainer.train_step(state, batch)
            if first is None:
                first = float(metrics["loss"])
        last = float(metrics["loss"])
        assert last < first * 0.7, (first, last)

    def test_fit_logs_and_closes_runlogger(self, jax, tmp_path):
        import json

        import jax.numpy as jnp

        from modal_examples_tpu.training import Trainer, make_optimizer

        def loss_fn(params, batch):
            return jnp.mean((params["w"] * batch["x"] - batch["y"]) ** 2)

        trainer = Trainer(loss_fn, make_optimizer(1e-1))
        state = trainer.init_state({"w": jnp.ones((4,))})
        batch = {"x": jnp.ones((4,)), "y": jnp.full((4,), 3.0)}
        run_dir = tmp_path / "fit-run"
        state = trainer.fit(
            state, [batch] * 5, run_dir=run_dir, log_every=1
        )
        assert int(state.step) == 5
        lines = (run_dir / "metrics.jsonl").read_text().splitlines()
        records = [json.loads(l) for l in lines]
        assert [r["step"] for r in records] == [1, 2, 3, 4, 5]
        assert records[-1]["loss"] < records[0]["loss"]
        # the loop owned the logger, so it closed it (handle released)
        import os

        open_fds = os.listdir("/proc/self/fd")
        paths = set()
        for fd in open_fds:
            try:
                paths.add(os.readlink(f"/proc/self/fd/{fd}"))
            except OSError:
                pass
        assert str(run_dir / "metrics.jsonl") not in paths

    def test_sharded_step_with_mesh(self, jax):
        from jax.sharding import PartitionSpec as P

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.training import (
            Trainer,
            cross_entropy_loss,
            make_optimizer,
        )

        mesh = make_mesh({"data": 4, "tensor": 2})
        cfg = llama.LlamaConfig(
            vocab_size=64, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)

        def loss_fn(params, batch):
            logits = llama.forward(params, batch["tokens"], cfg, attn_impl="xla")
            return cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])

        trainer = Trainer(
            loss_fn, make_optimizer(1e-3), mesh=mesh,
            param_specs=llama.partition_specs(cfg), batch_spec=P("data"),
        )
        state = trainer.init_state(params)
        batch = {
            "tokens": jax.random.randint(jax.random.PRNGKey(1), (8, 64), 0, 64)
        }
        state, metrics = trainer.train_step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        # params stayed tensor-sharded through the step
        assert state.params["layers"]["wq"].sharding.spec == P(None, None, "tensor")

    def test_remat_matches_plain(self, jax):
        """jax.checkpoint rematerialization must not change results."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.training import (
            Trainer, cross_entropy_loss, make_optimizer,
        )

        cfg = llama.LlamaConfig(
            vocab_size=64, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, max_seq_len=64, dtype="float32",
        )

        def loss_fn(p, batch):
            logits = llama.forward(p, batch["tokens"], cfg, attn_impl="xla")
            return cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])

        batch = {"tokens": jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)}
        outs = []
        for remat in (False, True):
            t = Trainer(loss_fn, make_optimizer(1e-2, grad_clip=1e9), remat=remat)
            state = t.init_state(llama.init_params(jax.random.PRNGKey(0), cfg))
            state, m = t.train_step(state, batch)
            outs.append((float(m["loss"]), state.params["final_norm"]))
        assert outs[0][0] == pytest.approx(outs[1][0], abs=1e-5)
        np.testing.assert_allclose(
            np.asarray(outs[0][1]), np.asarray(outs[1][1]), atol=1e-5
        )

    def test_grad_accum_equivalence(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.training import Trainer, make_optimizer

        def loss_fn(params, batch):
            pred = batch["x"] @ params["w"]
            return jnp.mean((pred - batch["y"]) ** 2)

        # train_step donates state: each trainer needs its own param arrays
        batch = {
            "x": jax.random.normal(jax.random.PRNGKey(0), (8, 4)),
            "y": jax.random.normal(jax.random.PRNGKey(1), (8, 1)),
        }
        t1 = Trainer(loss_fn, make_optimizer(1e-2, grad_clip=1e9), grad_accum=1)
        t2 = Trainer(loss_fn, make_optimizer(1e-2, grad_clip=1e9), grad_accum=4)
        s1 = t1.init_state({"w": jnp.ones((4, 1))})
        s2 = t2.init_state({"w": jnp.ones((4, 1))})
        s1, m1 = t1.train_step(s1, batch)
        s2, m2 = t2.train_step(s2, batch)
        np.testing.assert_allclose(
            np.asarray(s1.params["w"]), np.asarray(s2.params["w"]), atol=1e-5
        )


class TestCheckpoints:
    def test_save_restore_roundtrip(self, jax, tmp_path):
        import jax.numpy as jnp

        from modal_examples_tpu.training import CheckpointManager

        state = {
            "w": jnp.arange(8.0).reshape(2, 4),
            "step": jnp.asarray(3),
            "nested": {"b": jnp.ones((3,))},
        }
        mgr = CheckpointManager(tmp_path / "ckpts", keep_n=2)
        mgr.save(1, state)
        mgr.save(5, state)
        assert mgr.latest_step() == 5
        restored = mgr.restore(state)
        np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(state["w"]))

    def test_async_save(self, jax, tmp_path):
        """wait=False returns immediately; wait_until_finished makes the
        checkpoint durable (orbax async path)."""
        import jax.numpy as jnp

        from modal_examples_tpu.training import CheckpointManager

        mgr = CheckpointManager(tmp_path / "async", keep_n=2)
        state = {"w": jnp.ones((64, 64))}
        mgr.save(1, state, wait=False)
        mgr._ckptr.wait_until_finished()
        restored = mgr.restore(state)
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.asarray(state["w"])
        )

    def test_keep_n_prunes(self, jax, tmp_path):
        import jax.numpy as jnp

        from modal_examples_tpu.training import CheckpointManager

        mgr = CheckpointManager(tmp_path / "c2", keep_n=2)
        for s in (1, 2, 3, 4):
            mgr.save(s, {"x": jnp.ones(2) * s})
        assert mgr.steps() == [3, 4]

    def test_volume_commit_called(self, jax, tmp_path):
        import jax.numpy as jnp

        import modal_examples_tpu as mtpu
        from modal_examples_tpu.training import CheckpointManager

        vol = mtpu.Volume.from_name("ckpt-test-vol", create_if_missing=True)
        v0 = vol.version
        mgr = CheckpointManager(
            vol.local_path / "run1", keep_n=1, volume=vol
        )
        mgr.save(1, {"x": jnp.ones(2)})
        assert vol.version == v0 + 1


class TestResilience:
    def _tiny_setup(self, jax, lr=1e-2):
        import jax.numpy as jnp

        from modal_examples_tpu.training import Trainer, make_optimizer

        def loss_fn(params, batch):
            return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)

        t = Trainer(loss_fn, make_optimizer(lr, grad_clip=1e9))
        state = t.init_state({"w": jnp.ones((4, 1))})
        batch = {
            "x": jax.random.normal(jax.random.PRNGKey(0), (8, 4)),
            "y": jax.random.normal(jax.random.PRNGKey(1), (8, 1)),
        }
        return t, state, batch

    def test_preemption_triggers_emergency_checkpoint(self, jax_cpu, tmp_path):
        import itertools
        import os
        import signal

        from modal_examples_tpu.training import CheckpointManager, run_resilient

        t, state, batch = self._tiny_setup(jax_cpu)
        mgr = CheckpointManager(tmp_path / "resil", keep_n=3)

        def batches():
            for i in itertools.count():
                if i == 3:  # the "preemption notice" arrives mid-training
                    os.kill(os.getpid(), signal.SIGTERM)
                yield batch

        state, step, preempted = run_resilient(
            t, state, batches(), mgr, total_steps=100, save_every=50
        )
        assert preempted
        assert step < 100
        assert mgr.latest_step() == step  # emergency checkpoint landed

    def test_clean_run_periodic_saves(self, jax_cpu, tmp_path):
        from modal_examples_tpu.training import CheckpointManager, run_resilient

        t, state, batch = self._tiny_setup(jax_cpu)
        mgr = CheckpointManager(tmp_path / "clean", keep_n=5)
        state, step, preempted = run_resilient(
            t, state, iter([batch] * 10), mgr, total_steps=10, save_every=4
        )
        assert not preempted and step == 10
        assert mgr.steps() == [4, 8, 10]

    def test_device_health(self, jax_cpu):
        from modal_examples_tpu.training import device_health

        report = device_health()
        assert all(v == "ok" for v in report.values())


class TestGraftEntry:
    def test_dryrun_multichip(self, jax):
        import __graft_entry__ as g

        g.dryrun_multichip(8)

    def test_entry_compiles(self, jax):
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[0] == 2
