"""Fleet acceptance (ISSUE 11, docs/fleet.md): the closed loop over the
replica fleet. Deterministic controller tests run against fake replicas
(hysteresis, cooldown, drain-safe scale-in, role independence, the
warmth-aware KV signal); the live E2E fixture drives a real tiny fleet —
OpenAI server + prefix-affinity router + open-loop load generator — into
saturation and asserts the acceptance clauses: the autoscaler scales decode
replicas out (journaled, snapshot-restored warm boots) and back in on load
drop, the scaled fleet beats the pinned fleet on goodput and shed rate at
the knee-adjacent offered load, and no request wedges — including with a
chaos episode injected mid-sweep."""

import json
import os
import time
from types import SimpleNamespace

import pytest

from modal_examples_tpu.fleet import FleetAutoscaler, SnapshotWarmFactory
from modal_examples_tpu.fleet.loadgen import (
    LoadGenerator,
    RequestClass,
    ab_index,
    fleet_section,
)
from modal_examples_tpu.observability import catalog as C
from modal_examples_tpu.scheduling import PrefixAffinityRouter
from modal_examples_tpu.utils.prometheus import Registry


# -- fakes for the deterministic controller tests -----------------------------


class _FakePolicy:
    def __init__(self, engine):
        self._engine = engine

    def total_depth(self):
        return self._engine.queued


class _FakeEngine:
    def __init__(self):
        self.queued = 0
        self.pages_used = 0
        self.cached = 0
        self.reserved = 0
        self.started = False
        self.stopped = False
        self.params = {"w": 1.0}
        self.policy = _FakePolicy(self)
        self.prefix_cache = SimpleNamespace(cached_pages=0)
        self.admission = SimpleNamespace(reserved_pages=0)

    def start(self):
        self.started = True

    def stop(self):
        self.stopped = True

    @property
    def cache(self):
        eng = self

        class _Cache:
            def occupancy(self):
                return {
                    "pages_used": eng.pages_used,
                    "pages_free": 32 - eng.pages_used,
                    "pages_total": 32,
                    "occupancy": eng.pages_used / 32,
                }

        return _Cache()


class _FakeReplica:
    def __init__(self, name, role="unified"):
        self.name = name
        self.role = role
        self.engine = _FakeEngine()
        self._outstanding = 0
        self._healthy = True

    @property
    def serves_requests(self):
        return self.role != "prefill"

    def encode(self, text):
        return list(text.encode())

    def outstanding(self):
        return self._outstanding

    def capacity(self):
        return 4

    def healthy(self):
        return self._healthy

    def saturated(self):
        return False


class _Clock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now


def _controller(router, **kw):
    """A FleetAutoscaler over an isolated registry, burn signal off, with
    a fake-replica factory and an injectable clock."""
    clock = kw.pop("clock", _Clock())
    reg = kw.pop("registry", Registry())

    def factory(name, role):
        return _FakeReplica(name, role=role), "warm"

    kw.setdefault("journal_path", kw.pop("journal", None))
    auto = FleetAutoscaler(
        router,
        kw.pop("factory", factory),
        registry=reg,
        slos=(),
        clock=clock,
        **kw,
    )
    return auto, clock


class TestFleetController:
    def test_scale_up_needs_sustained_pressure_and_respects_cooldown(
        self, tmp_path
    ):
        seed = _FakeReplica("seed-0")
        router = PrefixAffinityRouter([seed])
        auto, clock = _controller(
            router, up_ticks=2, cooldown_s=5.0,
            max_replicas={"decode": 4}, journal=tmp_path / "j.jsonl",
        )
        seed.engine.queued = 10  # > queue_high per replica
        assert auto.tick() == []  # hysteresis: one pressured tick is noise
        acts = auto.tick()
        assert [a["action"] for a in acts] == ["scale_up"]
        assert acts[0]["trigger"] == "queue_pressure"
        assert acts[0]["boot"] == "warm"
        assert len(router.replicas) == 2
        new = router.replicas[-1]
        assert new.engine.started  # serving replica started before placement
        # cooldown: pressure persists but no further action until it lapses
        seed.engine.queued = 10
        auto.tick()
        assert auto.tick() == []
        assert len(router.replicas) == 2
        clock.now += 6.0  # cooldown lapsed; the sustained streak fires
        assert any(a["action"] == "scale_up" for a in auto.tick())
        assert len(router.replicas) == 3

    def test_min_replicas_floor_fills_without_pressure(self, tmp_path):
        seed = _FakeReplica("seed-0")
        router = PrefixAffinityRouter([seed])
        auto, _clock = _controller(
            router, up_ticks=3, cooldown_s=60.0,
            min_replicas={"decode": 3}, max_replicas={"decode": 4},
            journal=tmp_path / "j.jsonl",
        )
        # no pressure anywhere: the floor fills anyway, one per tick,
        # ignoring hysteresis and cooldown (it is a hard promise)
        acts = auto.tick() + auto.tick()
        assert [a["trigger"] for a in acts] == ["min_replicas"] * 2
        assert len(router.replicas) == 3
        assert auto.tick() == []  # at the floor: nothing more

    def test_max_replicas_caps_scale_out(self, tmp_path):
        seed = _FakeReplica("seed-0")
        router = PrefixAffinityRouter([seed])
        auto, clock = _controller(
            router, up_ticks=1, cooldown_s=0.0,
            max_replicas={"decode": 2}, journal=tmp_path / "j.jsonl",
        )
        seed.engine.queued = 50
        for _ in range(5):
            auto.tick()
            clock.now += 1.0
        assert len(router.replicas) == 2  # cap holds under sustained pressure

    def test_scale_down_is_drain_safe_and_never_reaps_the_seed(self, tmp_path):
        seed = _FakeReplica("seed-0")
        router = PrefixAffinityRouter([seed])
        auto, clock = _controller(
            router, up_ticks=1, down_ticks=2, cooldown_s=0.0,
            max_replicas={"decode": 2}, journal=tmp_path / "j.jsonl",
        )
        seed.engine.queued = 50
        auto.tick()
        assert len(router.replicas) == 2
        grown = router.replicas[-1]
        seed.engine.queued = 0
        auto.tick()
        acts = auto.tick()
        assert [a["action"] for a in acts] == ["scale_down"]
        assert acts[0]["replica"] == grown.name  # owned replica, not the seed
        assert grown.name not in [r.name for r in router.replicas]
        # the race the draining list exists for: a request placed between
        # the idle check and the removal keeps the engine alive
        grown._outstanding = 1
        auto.tick()
        assert not grown.engine.stopped  # out of placement but draining
        grown._outstanding = 0
        auto.tick()
        assert grown.engine.stopped  # drained -> engine reaped
        # the seed is the floor: no further scale-down ever picks it
        for _ in range(10):
            auto.tick()
            clock.now += 1.0
        assert [r.name for r in router.replicas] == ["seed-0"]

    def test_kv_pressure_ignores_prefix_cache_warmth(self, tmp_path):
        seed = _FakeReplica("seed-0")
        router = PrefixAffinityRouter([seed])
        auto, _clock = _controller(
            router, up_ticks=1, cooldown_s=0.0, kv_high=0.5,
            max_replicas={"decode": 2}, journal=tmp_path / "j.jsonl",
        )
        # a warm trie that absorbed the whole pool is NOT pressure
        seed.engine.pages_used = 30
        seed.engine.prefix_cache.cached_pages = 30
        assert auto.tick() == []
        # queued admissions' reservations ARE pressure
        seed.engine.admission.reserved_pages = 20
        acts = auto.tick()
        assert acts and acts[0]["trigger"] == "kv_pressure"

    def test_prefill_role_scales_independently(self, tmp_path):
        seed = _FakeReplica("seed-0")
        pre = _FakeReplica("pre-0", role="prefill")
        router = PrefixAffinityRouter([seed, pre])
        auto, _clock = _controller(
            router, up_ticks=1, cooldown_s=0.0,
            max_replicas={"decode": 2, "prefill": 2},
            journal=tmp_path / "j.jsonl",
        )
        pre._outstanding = 30  # prefill backlog; decode side is idle
        acts = auto.tick()
        assert [a["role"] for a in acts] == ["prefill"]
        added = router.replicas[-1]
        assert added.role == "prefill"
        assert not added.engine.started  # prefill engines never start a loop
        # decode side untouched
        assert sum(
            1 for r in router.replicas if r.role != "prefill"
        ) == 1

    def test_decisions_journaled_and_counted(self, tmp_path):
        seed = _FakeReplica("seed-0")
        router = PrefixAffinityRouter([seed])
        reg = Registry()
        auto, _clock = _controller(
            router, up_ticks=1, cooldown_s=0.0, registry=reg,
            max_replicas={"decode": 2}, journal=tmp_path / "fleet.jsonl",
        )
        seed.engine.queued = 50
        auto.tick()
        records = [
            json.loads(line)
            for line in (tmp_path / "fleet.jsonl").read_text().splitlines()
        ]
        assert records and records[-1]["action"] == "scale_up"
        assert records[-1]["boot"] == "warm"
        assert reg.total(
            C.FLEET_DECISIONS_TOTAL, {"action": "scale_up"}
        ) == 1
        assert reg.value(C.FLEET_REPLICAS, {"role": "unified"}) == 1
        assert reg.value(C.FLEET_REPLICAS, {"role": "decode"}) == 1


class TestRouterMembership:
    def test_add_replica_remaps_only_the_newcomers_keys(self):
        a, b = _FakeReplica("a"), _FakeReplica("b")
        router = PrefixAffinityRouter([a, b])
        # the affinity key is the FIRST prefix block (16 tokens = 16 bytes
        # here): the prompts must differ inside it to be distinct keys
        prompts = [f"{i:02d} system prompt " * 4 for i in range(24)]
        before = {p: router.route(p).name for p in prompts}
        c = _FakeReplica("c")
        router.add_replica(c)
        after = {p: router.route(p).name for p in prompts}
        moved = {p for p in prompts if before[p] != after[p]}
        # rendezvous: every move lands on the newcomer — nothing reshuffles
        # between the existing replicas (their prefix caches stay warm)
        assert all(after[p] == "c" for p in moved)
        assert moved  # with 24 keys over 3 replicas, some must move

    def test_add_rejects_duplicate_names(self):
        router = PrefixAffinityRouter([_FakeReplica("a")])
        with pytest.raises(ValueError):
            router.add_replica(_FakeReplica("a"))

    def test_remove_replica_semantics(self):
        a, b = _FakeReplica("a"), _FakeReplica("b")
        pre = _FakeReplica("p", role="prefill")
        router = PrefixAffinityRouter([a, b, pre])
        victim = router.remove_replica("b")
        assert victim is b
        assert [r.name for r in router.replicas] == ["a", "p"]
        with pytest.raises(KeyError):
            router.remove_replica("b")
        # a prefill replica may always go; the last serving replica may not
        router.remove_replica("p")
        with pytest.raises(ValueError):
            router.remove_replica("a")

    def test_removed_replica_leaves_the_down_list(self):
        a, b = _FakeReplica("a"), _FakeReplica("b")
        router = PrefixAffinityRouter([a, b], reprobe_s=60.0)
        b._healthy = False
        router.route("some prompt")  # observes b unhealthy -> down list
        assert router.stats()["replicas"]["b"]["down"]
        router.remove_replica("b")
        assert "b" not in router.stats()["replicas"]


class TestSnapshotWarmFactory:
    def test_cold_then_warm_roundtrip(self, jax_cpu, tmp_path):
        import jax.numpy as jnp

        from modal_examples_tpu.snapshot import SnapshotStore

        built = []

        def build(name, role, params=None):
            built.append(params)
            if params is None:
                params = {"w": jnp.arange(4.0), "b": jnp.ones(2)}
            return SimpleNamespace(
                name=name, role=role,
                engine=SimpleNamespace(params=params),
            )

        fac = SnapshotWarmFactory(
            build, snapshot_key="k1", store=SnapshotStore(root=tmp_path)
        )
        _r, boot = fac("a", "decode")
        assert boot == "cold" and built[0] is None
        _r2, boot2 = fac("b", "decode")
        assert boot2 == "warm"
        assert jnp.allclose(built[1]["w"], jnp.arange(4.0))
        assert jnp.allclose(built[1]["b"], jnp.ones(2))

    def test_prime_makes_the_first_build_warm(self, jax_cpu, tmp_path):
        import jax.numpy as jnp

        from modal_examples_tpu.snapshot import SnapshotStore

        seen = []

        def build(name, role, params=None):
            seen.append(params)
            return SimpleNamespace(
                name=name, role=role, engine=SimpleNamespace(params=params)
            )

        fac = SnapshotWarmFactory(
            build, snapshot_key="k2", store=SnapshotStore(root=tmp_path)
        )
        assert fac.prime(SimpleNamespace(params={"w": jnp.ones(3)}))
        _r, boot = fac("a", "decode")
        assert boot == "warm"
        assert jnp.allclose(seen[0]["w"], jnp.ones(3))


class TestLoadGenerator:
    def test_arrival_processes_are_seeded_and_mean_preserving(self):
        lg = LoadGenerator("http://127.0.0.1:9", seed=7)
        import random

        for proc in ("poisson", "heavy_tail"):
            lg.arrival = proc
            r1 = random.Random("x")
            r2 = random.Random("x")
            a = [lg._interarrival(r1, 10.0) for _ in range(4000)]
            b = [lg._interarrival(r2, 10.0) for _ in range(4000)]
            assert a == b, f"{proc} arrivals are not deterministic"
            mean = sum(a) / len(a)
            assert 0.05 < mean < 0.2, f"{proc} mean {mean} far from 1/rate"

    def test_shared_prefix_populations(self):
        lg = LoadGenerator(
            "http://127.0.0.1:9", seed=0, tenants=3, shared_prefixes=2
        )
        import random

        rng = random.Random("y")
        picked = [lg._pick(rng) for _ in range(60)]
        tenants = {t for _c, t, _p in picked}
        assert len(tenants) == 3
        # every prompt opens with one of the tenant's SHARED prefixes (the
        # affinity/prefix-cache unit), with a unique tail after it
        for _cls, tenant, prompt in picked:
            assert any(
                prompt.startswith(pre) for pre in lg.prefixes[tenant]
            ), prompt
        prompts = [p for _c, _t, p in picked]
        assert len(set(prompts)) == len(prompts)

    def test_rejects_unknown_arrival_process(self):
        with pytest.raises(ValueError):
            LoadGenerator("http://127.0.0.1:9", arrival="uniform")

    def test_fleet_section_shape_and_knee(self):
        def step(rate, good, tpot=0.01, duration=4.0, offered=None):
            offered = int(rate * duration) if offered is None else offered
            return {
                "label": f"{rate}rps", "offered_rps": rate,
                "duration_s": duration, "offered": offered,
                "completed": offered, "shed": 0, "errors": 0, "wedged": 0,
                "achieved_rps": good, "goodput_rps": good,
                "shed_rate": 0.1,
                "ttft": {"p50": 0.1, "p99": 0.5},
                "tpot": {"p50": tpot / 2, "p99": tpot},
                "per_class": {},
            }

        pinned = {
            "arrival": "poisson", "rates": [2.0, 5.0, 10.0],
            "steps": [step(2, 2.0), step(5, 4.8), step(10, 5.0)],
            "knee_index": 2, "knee_rps": 10.0,
        }
        autoscaled = dict(pinned)
        scaled = step(5, 5.0, tpot=0.005)
        sec = fleet_section(
            pinned, autoscaled,
            scale_events=[
                {"action": "scale_up", "boot": "warm"},
                {"action": "scale_down"},
            ],
            capacity_rps=5.0,
            scaled_step=scaled,
        )
        assert ab_index(pinned) == 1  # knee-adjacent: below the top step
        assert sec["ab"]["scaled_out"] is True
        assert sec["ab"]["offered_rps"] == 5
        assert sec["goodput"] == 5.0
        assert sec["p99_tpot_at_knee"] == 0.005
        assert sec["scale_events"] == {"up": 1, "down": 1, "warm_boots": 1}
        assert sec["ab"]["improvement_goodput"] == round(5.0 / 4.8, 3)


# -- the live E2E -------------------------------------------------------------

#: the bench's class trio sized for the byte tokenizer + tiny context
_E2E_CLASSES = (
    RequestClass("interactive", "interactive", 0.5, (1, 2), 16, 2.0, 0.5),
    RequestClass("streaming", "default", 0.3, (1, 3), 32, 4.0, 0.5),
    RequestClass("batch", "batch", 0.2, (2, 4), 24, 30.0, 2.0, stream=False),
)


@pytest.fixture(scope="module")
def fleet_run(jax_cpu, tmp_path_factory):
    """ONE live scenario, asserted clause-by-clause below: warm the fleet,
    measure the pinned arm at the knee-adjacent rate, let the autoscaler
    scale out under the same load WITH a chaos episode armed, re-measure
    the scaled fleet, then drop the load and watch it scale back in."""
    from modal_examples_tpu.faults.inject import FaultPlan, active
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.scheduling import EngineReplica
    from modal_examples_tpu.scheduling.admission import (
        AdmissionConfig,
        AdmissionController,
    )
    from modal_examples_tpu.scheduling.policy import PRIORITY_CLASSES
    from modal_examples_tpu.serving import LLMEngine
    from modal_examples_tpu.serving.openai_api import OpenAIServer
    from modal_examples_tpu.snapshot import SnapshotStore
    from modal_examples_tpu._internal import config as _config

    # sample the request tracer OUT for the load windows (hundreds of
    # requests; span files are not what this fixture measures) — restored
    # on teardown so later modules see the session default
    prev_sample = os.environ.get("MTPU_TRACE_SAMPLE")
    os.environ["MTPU_TRACE_SAMPLE"] = "0"
    cfg = llama.LlamaConfig.tiny()

    def mk(params=None):
        # ONE slot per replica: the pinned replica is slot-bound (requests
        # serialize) while the host still has CPU headroom, so a second
        # replica adds real serving capacity — the regime where closing
        # the loop is provable on a shared-CPU box (docs/fleet.md). The
        # page pool keeps multi-slot slack so prefix warmth survives.
        return LLMEngine(
            cfg, params=params, seed=0, max_slots=1, max_model_len=384,
            page_size=16, n_pages=1 + 4 * 24, prefill_buckets=(64, 128),
            # production admission shape: bounded queues turn sustained
            # overload into honest 429s instead of unbounded queue waits
            # (4/class: overload must overflow the queue space within one
            # 5 s step, or the pinned arm never sheds and the knee hides)
            admission=AdmissionController(AdmissionConfig(
                max_queue={c: 4 for c in PRIORITY_CLASSES}
            )),
        )

    t0 = time.monotonic()
    primary = mk()
    primary.warmup()
    cold_build_s = time.monotonic() - t0
    router = PrefixAffinityRouter(
        [EngineReplica(primary, "decode-0", role="unified")]
    )
    server = OpenAIServer(router=router, host="127.0.0.1", port=0).start()

    built_params = []

    def build(name, role, params=None):
        built_params.append(params)
        eng = mk(params=params)
        eng.warmup()
        # warmup() covers buckets + the decode block, NOT the chunk-offset
        # jits long prompts hit: serve one short and one chunking prompt
        # before joining the router, so the replica's first user request
        # never pays a compile inside a measurement window
        eng.start()
        from modal_examples_tpu.serving import SamplingParams

        for warm_prompt in ("warm " * 8, "boot warm long prompt " * 12):
            eng.generate(warm_prompt, SamplingParams(max_tokens=4))
        return EngineReplica(eng, name, role=role)

    store_root = tmp_path_factory.mktemp("fleet-snap")
    factory = SnapshotWarmFactory(
        build, snapshot_key="fleet-e2e", store=SnapshotStore(root=store_root)
    )
    assert factory.prime(primary)

    lg = LoadGenerator(
        f"http://127.0.0.1:{server.port}", classes=_E2E_CLASSES, seed=0,
        request_timeout_s=60.0,
    )
    lg.warm(n_per_class=1)
    lg.calibrate(duration_s=1.5)  # throwaway: flushes first-touch compiles
    # SEQUENTIAL service-rate probe (concurrency 1): with one slot per
    # replica, 1/service_time IS a replica's capacity, and a zero-queueing
    # probe has none of the GIL/queue noise a concurrent probe picks up
    capacity = lg.calibrate(duration_s=2.5, concurrency=1)
    # the high-utilization operating point: ~0.9 of one replica. Queueing
    # delay explodes as utilization -> 1 (M/M/1: W ~ rho/(1-rho)), so the
    # pinned arm's TTFT tail blows up while a two-replica fleet at ~0.45
    # utilization each serves at the service-time floor — and the host's
    # CPU is unsaturated in BOTH arms, so the direction is structural
    # queueing theory, not a contention coin-flip (docs/fleet.md).
    rate = 0.9 * capacity

    pinned = lg.run_step(rate, 6.0, label="pinned")

    journal_path = _config.state_dir() / "fleet.jsonl"
    auto = FleetAutoscaler(
        router, factory,
        max_replicas={"decode": 2},  # scaled replica shares the host's CPUs
        # queue_high 1: with one slot, any sustained queue IS the latency
        # the SLO pays for. down_ticks 15 (3 s of continuous emptiness):
        # momentary idles between arrivals at ~0.4 utilization must not
        # flap the fleet mid-step; the zero-traffic tail still triggers.
        queue_high=1.0, up_ticks=2, down_ticks=15, cooldown_s=1.0,
        tick_s=0.2, slos=(), journal_path=journal_path,
    )
    run_started_at = time.time()
    auto.start()
    # growth window: keep offering the same load until the controller has
    # scaled out — queue-depth bursts at high utilization trigger it
    # within a window or two, and the scaled A/B below must measure a
    # settled two-replica fleet, not the transition
    overload = lg.run_step(rate, 6.0, label="growth")
    for _ in range(2):
        if len(router.replicas) > 1:
            break
        overload = lg.run_step(rate, 4.0, label="growth-retry")
    replicas_at_peak = [r.name for r in router.replicas]
    scaled = lg.run_step(rate, 6.0, label="scaled")
    # chaos mid-sweep, fleet still scaled out: a health flap (the router
    # must evict and re-admit the flapped replica under traffic — with a
    # one-shot flap and two replicas the outage is one placement, never a
    # failed request) and an injected decode stall
    plan = FaultPlan(
        {"router.health_flap": {"on_hit": 2},
         "engine.slow_decode": {"on_hit": 5}},
        seed=0,
    )
    with active(plan):
        chaos_step = lg.run_step(rate, 4.0, label="scaled+chaos")
    # load drop: the controller must scale back in on idleness
    deadline = time.monotonic() + 30.0
    while len(router.replicas) > 1 and time.monotonic() < deadline:
        time.sleep(0.2)
    scaled_back = [r.name for r in router.replicas]
    auto.stop()

    engines = {"decode-0": primary}
    run = {
        "capacity": capacity,
        "rate": rate,
        "pinned": pinned,
        "overload": overload,
        "scaled": scaled,
        "chaos_step": chaos_step,
        "events": list(auto.events),
        "replicas_at_peak": replicas_at_peak,
        "scaled_back": scaled_back,
        "built_params": built_params,
        "cold_build_s": cold_build_s,
        "journal_path": journal_path,
        "run_started_at": run_started_at,
        "plan_fired": plan.fired(),
        "router": router,
        "engines": engines,
        "auto": auto,
    }
    yield run
    server.stop()
    if prev_sample is None:
        os.environ.pop("MTPU_TRACE_SAMPLE", None)
    else:
        os.environ["MTPU_TRACE_SAMPLE"] = prev_sample


class TestFleetE2E:
    def test_autoscaler_scaled_out_under_load(self, fleet_run):
        ups = [e for e in fleet_run["events"] if e["action"] == "scale_up"]
        assert ups, "the saturating sweep never triggered a scale-out"
        assert len(fleet_run["replicas_at_peak"]) == 2

    def test_scale_out_boots_are_snapshot_restored(self, fleet_run):
        ups = [e for e in fleet_run["events"] if e["action"] == "scale_up"]
        assert all(e["boot"] == "warm" for e in ups), ups
        # the restored tree is the PRIMED primary's params, not a re-init
        import jax.numpy as jnp

        assert fleet_run["built_params"], "factory never built a replica"
        restored = fleet_run["built_params"][0]
        assert restored is not None, "factory fell back to a cold init"
        primary = fleet_run["engines"]["decode-0"].params
        import jax

        r_leaves = jax.tree_util.tree_leaves(restored)
        p_leaves = jax.tree_util.tree_leaves(primary)
        assert len(r_leaves) == len(p_leaves)
        assert jnp.allclose(r_leaves[0], p_leaves[0])

    def test_scaled_back_in_on_load_drop(self, fleet_run):
        assert fleet_run["scaled_back"] == ["decode-0"]
        downs = [
            e for e in fleet_run["events"] if e["action"] == "scale_down"
        ]
        assert downs and all(e["trigger"] == "idle" for e in downs)

    def test_decisions_journaled_to_fleet_jsonl(self, fleet_run):
        path = fleet_run["journal_path"]
        assert path.exists()
        records = [
            json.loads(line)
            for line in path.read_text().splitlines()
            if line.strip()
        ]
        mine = [
            r for r in records
            if r.get("at", 0) >= fleet_run["run_started_at"] - 1
        ]
        actions = {r["action"] for r in mine}
        assert {"scale_up", "scale_down"} <= actions, mine
        for r in mine:
            if r["action"] == "scale_up":
                assert r["boot"] == "warm"
                assert r["boot_s"] > 0
                assert r["replicas_after"] == r["replicas_before"] + 1

    def test_scaled_fleet_ab_measured_and_bounded(self, fleet_run):
        """The A/B at the pre-knee operating point (~0.9 of one replica's
        capacity): both arms measured at the same offered load, TTFT/TPOT
        p99, goodput, and shed rate captured per arm — the numbers the
        BENCH ``fleet`` section headlines. The HARD direction assertion
        (autoscaling measurably beats pinned) lives in the on-chip
        revalidation stage behind the benchdiff gate, exactly like the
        PR-10 interference A/B: this suite runs two replicas on a shared
        noisy 2-core host where wall-clock latency direction is a
        coin-flip (measured; docs/fleet.md#cpu-path-proof). Here the
        scaled fleet must be measured, serving, and not collapsed."""
        pinned, scaled = fleet_run["pinned"], fleet_run["scaled"]
        for arm in (pinned, scaled):
            assert arm["completed"] > 0
            assert arm["ttft"]["p99"] > 0
            assert arm["goodput_rps"] > 0
        # no-collapse bound: adding a replica must never cost meaningful
        # goodput at the same offered load
        assert scaled["goodput_rps"] >= 0.5 * pinned["goodput_rps"], (
            pinned, scaled,
        )
        assert scaled["tpot"]["p99"] > 0  # TPOT measured, not degenerate

    def test_no_request_wedges_anywhere(self, fleet_run):
        for arm in ("pinned", "overload", "scaled", "chaos_step"):
            step = fleet_run[arm]
            assert step["wedged"] == 0, (arm, step)
            assert step["errors"] == 0, (arm, step)

    def test_chaos_episode_fired_and_fleet_recovered(self, fleet_run):
        from modal_examples_tpu.faults.chaos import (
            check_drained,
            check_router_recovered,
        )

        fired = fleet_run["plan_fired"]
        assert fired.get("router.health_flap"), fired
        assert fired.get("engine.slow_decode"), fired
        # the chaos window still served traffic and wedged nothing
        assert fleet_run["chaos_step"]["completed"] > 0
        assert fleet_run["chaos_step"]["wedged"] == 0
        # fleet invariants after the full run (PR 8's checkers)
        assert check_drained(fleet_run["engines"]) == []
        assert check_router_recovered(fleet_run["router"]) == []

    def test_fleet_cli_renders_the_journal(self, fleet_run, capsys):
        from modal_examples_tpu.core.cli import main

        assert main(["fleet", "--last", "20"]) == 0
        out = capsys.readouterr().out
        assert "scale_up" in out
        assert "warm" in out

    def test_gateway_fleet_snapshot_shape(self, fleet_run):
        from modal_examples_tpu.web.gateway import _fleet_snapshot

        snap = _fleet_snapshot()
        assert snap["journal"], "fleet journal must surface"
        assert "scale_up" in snap["decisions"], snap
        ups = snap["decisions"]["scale_up"]
        assert sum(ups.values()) >= 1
        assert snap["boot_seconds"].get("warm"), snap
