"""Observability tier: call-lifecycle traces stitched across the
supervisor/container boundary, prometheus histogram exposition validity,
the file-backed push gateway, and the `tpurun trace` / `tpurun metrics`
CLI — the acceptance surface of the tracing+histograms subsystem."""

import json
import math
import re
import urllib.request

import pytest

import modal_examples_tpu as mtpu
from modal_examples_tpu.core.cli import main as cli_main
from modal_examples_tpu.observability import span
from modal_examples_tpu.observability import catalog as C
from modal_examples_tpu.observability.trace import default_store
from modal_examples_tpu.utils.prometheus import (
    Registry,
    default_registry,
    merge_expositions,
)

app = mtpu.App("obs-test")


@app.function(timeout=30)
def traced_square(x: int) -> int:
    return x * x


@app.function(timeout=30)
def with_user_span(x: int) -> int:
    with span("user-phase", tag="inner"):
        return x + 1


@app.function(timeout=30)
@mtpu.fastapi_endpoint()
def hello_endpoint(name: str = "world") -> dict:
    return {"hello": name}


@pytest.fixture(scope="module", autouse=True)
def run_ctx():
    with app.run():
        yield


# ---------------------------------------------------------------------------
# trace stitching (the tier-1 acceptance criterion)
# ---------------------------------------------------------------------------


class TestTraceStitching:
    def _trace_of(self, call) -> list[dict]:
        assert call.call_id and call.call_id.startswith("in-")
        spans = default_store.read(call.call_id)
        assert spans, f"no trace file for {call.call_id}"
        return spans

    def test_remote_call_yields_stitched_phase_spans(self, capsys):
        """One .remote()-path call through the process backend produces a
        single trace holding the supervisor-side phases (queue, boot,
        dispatch) AND the container-side phases (execute, serialize) shipped
        back over the worker pipe — >= 4 stitched phases + the root."""
        call = traced_square.spawn(7)  # same submit path as .remote()
        assert call.get(timeout=30) == 49
        spans = self._trace_of(call)

        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        for phase in ("call", "queue", "boot", "dispatch", "execute",
                      "serialize"):
            assert phase in by_name, (phase, sorted(by_name))

        # every span belongs to ONE trace (the call id)
        assert {s["trace_id"] for s in spans} == {call.call_id}

        # stitching: supervisor phases parent under the root; the child
        # process's execute/serialize parent under the dispatch span
        root = by_name["call"][0]
        assert root["parent_id"] is None
        for phase in ("queue", "boot", "dispatch"):
            assert by_name[phase][0]["parent_id"] == root["span_id"]
        dispatch_id = by_name["dispatch"][0]["span_id"]
        assert by_name["execute"][0]["parent_id"] == dispatch_id
        assert by_name["serialize"][0]["parent_id"] == dispatch_id

        # statuses + ordering sanity
        assert all(s["status"] == "ok" for s in spans)
        assert root["end"] >= root["start"]
        assert by_name["execute"][0]["start"] >= by_name["queue"][0]["start"]

        # retrievable via the CLI: `tpurun trace <call_id>`
        assert cli_main(["trace", call.call_id]) == 0
        out = capsys.readouterr().out
        assert call.call_id in out
        for phase in ("queue", "boot", "execute", "serialize"):
            assert phase in out

    def test_trace_list_cli(self, capsys):
        call = traced_square.spawn(3)
        assert call.get(timeout=30) == 9
        assert cli_main(["trace", "list"]) == 0
        out = capsys.readouterr().out
        assert call.call_id in out

    def test_user_spans_ship_back_from_container(self):
        call = with_user_span.spawn(1)
        assert call.get(timeout=30) == 2
        spans = self._trace_of(call)
        user = [s for s in spans if s["name"] == "user-phase"]
        assert user and user[0]["attrs"]["tag"] == "inner"
        execute = [s for s in spans if s["name"] == "execute"][0]
        assert user[0]["parent_id"] == execute["span_id"]

    def test_call_feeds_latency_histograms(self):
        tag = traced_square.spec.tag
        before = default_registry.value(
            C.CALL_DURATION_SECONDS, labels={"function": tag, "phase": "total"}
        )
        assert traced_square.remote(5) == 25
        after = default_registry.value(
            C.CALL_DURATION_SECONDS, labels={"function": tag, "phase": "total"}
        )
        assert after == before + 1
        # dedicated queue-wait series observed too
        assert default_registry.value(
            C.QUEUE_WAIT_SECONDS, labels={"function": tag}
        ) >= 1

    def test_tracing_can_be_disabled(self, monkeypatch):
        from modal_examples_tpu.observability import trace as tr

        monkeypatch.setenv("MTPU_TRACE", "0")
        assert not tr.tracing_enabled()
        monkeypatch.setenv("MTPU_TRACE", "1")
        assert tr.tracing_enabled()


# ---------------------------------------------------------------------------
# prometheus histogram exposition (text-format validity)
# ---------------------------------------------------------------------------


def _parse_histogram(text: str, name: str, labels_contains: str = ""):
    """Collect (le, cum_count) pairs + sum/count for one histogram series."""
    buckets, total, sum_ = [], None, None
    for line in text.splitlines():
        if line.startswith("#") or labels_contains not in line:
            continue
        m = re.match(rf'^{name}_bucket\{{(.*)\}} (\S+)$', line)
        if m:
            le = re.search(r'le="([^"]+)"', m.group(1)).group(1)
            buckets.append(
                (math.inf if le == "+Inf" else float(le), float(m.group(2)))
            )
        elif line.startswith(f"{name}_sum"):
            sum_ = float(line.rsplit(" ", 1)[1])
        elif line.startswith(f"{name}_count"):
            total = float(line.rsplit(" ", 1)[1])
    return buckets, sum_, total


class TestHistogramExposition:
    def test_populated_histogram_parses_under_text_format_rules(self):
        reg = Registry()
        values = [0.003, 0.003, 0.04, 0.9, 2.0, 7.0, 500.0]
        for v in values:
            reg.histogram_observe(
                "mtpu_call_duration_seconds", v,
                labels={"function": "f", "phase": "execute"},
                help="per-phase latency",
            )
        text = reg.expose()
        assert text.count("# TYPE mtpu_call_duration_seconds histogram") == 1
        assert text.count("# HELP mtpu_call_duration_seconds") == 1
        buckets, sum_, total = _parse_histogram(
            text, "mtpu_call_duration_seconds"
        )
        assert buckets, text
        # bucket bounds ascending, counts cumulative (monotone nondecreasing)
        les = [le for le, _ in buckets]
        assert les == sorted(les) and les[-1] == math.inf
        counts = [c for _, c in buckets]
        assert counts == sorted(counts)
        # +Inf bucket equals _count; _sum matches the observations
        assert counts[-1] == total == len(values)
        assert sum_ == pytest.approx(sum(values))
        # a value past the largest finite bound lands only in +Inf
        finite_max = max(le for le in les if le != math.inf)
        assert 500.0 > finite_max and counts[-1] == counts[-2] + 1

    def test_label_values_escaped(self):
        reg = Registry()
        evil = 'a"b\\c\nd'
        reg.counter_inc("mtpu_retries_total", labels={"reason": evil})
        text = reg.expose()
        assert 'reason="a\\"b\\\\c\\nd"' in text
        # the exposition itself stays line-atomic: no raw newline mid-sample
        sample_lines = [l for l in text.splitlines() if not l.startswith("#")]
        assert len(sample_lines) == 1

    def test_histogram_quantiles(self):
        reg = Registry()
        for i in range(100):
            reg.histogram_observe("mtpu_queue_wait_seconds", 0.001 + i * 0.001)
        q = reg.histogram_quantiles("mtpu_queue_wait_seconds")
        assert q["count"] == 100
        assert 0.0 < q["p50"] <= q["p95"] <= q["p99"] <= 0.3

    def test_value_reads_histogram_count(self):
        reg = Registry()
        reg.histogram_observe("mtpu_queue_wait_seconds", 0.5)
        assert reg.value("mtpu_queue_wait_seconds") == 1.0


class TestHistogramQuantileEdgeCases:
    """ISSUE-3 satellite: empty histogram, all mass in +Inf, and single-
    bucket layouts must return None / a bucket bound — never raise, never
    extrapolate past the data."""

    def test_empty_histogram_returns_none(self):
        reg = Registry()
        assert reg.histogram_quantiles("mtpu_queue_wait_seconds") is None
        # labels that never observed anything are None too, even when a
        # sibling label set exists
        reg.histogram_observe(
            "mtpu_queue_wait_seconds", 0.1, labels={"function": "a"}
        )
        assert reg.histogram_quantiles(
            "mtpu_queue_wait_seconds", labels={"function": "b"}
        ) is None

    def test_all_mass_in_inf_clamps_to_largest_finite_bound(self):
        reg = Registry()
        for _ in range(10):
            reg.histogram_observe(
                "mtpu_queue_wait_seconds", 1e9, buckets=(0.1, 1.0)
            )
        q = reg.histogram_quantiles("mtpu_queue_wait_seconds")
        assert q["p50"] == q["p99"] == 1.0  # the largest finite bound

    def test_single_bucket_interpolates_within_bounds(self):
        reg = Registry()
        for _ in range(8):
            reg.histogram_observe(
                "mtpu_queue_wait_seconds", 0.05, buckets=(1.0,)
            )
        q = reg.histogram_quantiles("mtpu_queue_wait_seconds")
        for key in ("p50", "p95", "p99"):
            assert 0.0 <= q[key] <= 1.0

    def test_sparse_buckets_never_escape_the_winning_bucket(self):
        # observations split around an empty middle bucket: interpolation
        # fractions must clamp so values stay inside the bucket that holds
        # the rank
        reg = Registry()
        for v in (0.05, 0.05, 0.05, 5.0):
            reg.histogram_observe(
                "mtpu_queue_wait_seconds", v, buckets=(0.1, 1.0, 10.0)
            )
        q = reg.histogram_quantiles("mtpu_queue_wait_seconds")
        assert q["p50"] <= 0.1
        assert 1.0 <= q["p99"] <= 10.0

    def test_aggregate_sums_across_label_sets(self):
        reg = Registry()
        for i in range(50):
            reg.histogram_observe(
                "mtpu_call_duration_seconds", 0.01,
                labels={"function": "a", "phase": "total"},
            )
            reg.histogram_observe(
                "mtpu_call_duration_seconds", 10.0,
                labels={"function": "b", "phase": "total"},
            )
        q = reg.histogram_quantiles(
            "mtpu_call_duration_seconds", aggregate={"phase": "total"}
        )
        assert q["count"] == 100
        assert q["p50"] <= 0.025 and q["p95"] >= 5.0
        assert reg.total(
            "mtpu_call_duration_seconds", {"phase": "total"}
        ) == 100.0


class TestExpositionParser:
    def test_round_trips_counters_gauges_histograms(self):
        from modal_examples_tpu.utils.prometheus import parse_exposition

        reg = Registry()
        reg.counter_inc(
            "mtpu_retries_total", 3, labels={"reason": "timeout"},
            help="retries",
        )
        reg.gauge_set("mtpu_active_slots", 5.0)
        for v in (0.004, 0.2, 2.0, 700.0):
            reg.histogram_observe(
                "mtpu_queue_wait_seconds", v, labels={"function": "f"}
            )
        parsed = parse_exposition(reg.expose())
        assert parsed.value(
            "mtpu_retries_total", {"reason": "timeout"}
        ) == 3.0
        assert parsed.value("mtpu_active_slots") == 5.0
        assert parsed.histogram_quantiles(
            "mtpu_queue_wait_seconds", {"function": "f"}
        ) == reg.histogram_quantiles(
            "mtpu_queue_wait_seconds", {"function": "f"}
        )
        # the parsed registry re-exposes as valid text again
        assert "# TYPE mtpu_queue_wait_seconds histogram" in parsed.expose()


class TestTraceStoreBounds:
    """ISSUE-3 satellite: the traces directory must stay bounded (count +
    bytes, LRU-deleted oldest-first) on long-running gateways."""

    @staticmethod
    def _fill(store, n):
        import os
        import time as _time

        now = _time.time()
        for i in range(n):
            store.record({
                "trace_id": f"in-{i:05d}", "span_id": f"sp-{i}",
                "parent_id": None, "name": "call",
                "start": 1.0, "end": 2.0, "status": "ok", "attrs": {},
            })
            # distinct (recent) mtimes so LRU ordering is deterministic
            t = now - (n - i)
            os.utime(store.root / f"in-{i:05d}.jsonl", (t, t))

    def test_count_cap_deletes_oldest_first(self, tmp_path, monkeypatch):
        from modal_examples_tpu.observability import trace as tr

        monkeypatch.setattr(tr, "_MAX_TRACE_FILES", 10)
        store = tr.TraceStore(root=tmp_path)
        self._fill(store, 25)
        store._gc_sweep()
        left = sorted(p.stem for p in tmp_path.glob("*.jsonl"))
        assert len(left) == 10
        assert left[0] == "in-00015"  # the newest 10 survive

    def test_byte_cap(self, tmp_path, monkeypatch):
        from modal_examples_tpu.observability import trace as tr

        monkeypatch.setattr(tr, "_MAX_TRACE_BYTES", 600)
        store = tr.TraceStore(root=tmp_path)
        self._fill(store, 20)
        store._gc_sweep()
        total = sum(p.stat().st_size for p in tmp_path.glob("*.jsonl"))
        assert 0 < total <= 600

    def test_trace_list_limit_flag(self, tmp_path, capsys):
        from modal_examples_tpu.observability import trace as tr

        store = tr.TraceStore(root=tmp_path)
        self._fill(store, 6)
        assert cli_main(
            ["trace", "list", "--limit", "3", "--dir", str(tmp_path)]
        ) == 0
        out = capsys.readouterr().out
        assert out.count("in-000") == 3
        assert "in-00005" in out  # newest first


# ---------------------------------------------------------------------------
# merge/push gateway + `tpurun metrics`
# ---------------------------------------------------------------------------


class TestPushGateway:
    def test_merge_is_a_single_valid_exposition(self):
        r1, r2 = Registry(), Registry()
        r1.counter_inc("mtpu_retries_total", 2, help="retries")
        r1.histogram_observe("mtpu_queue_wait_seconds", 0.1)
        r2.counter_inc("mtpu_retries_total", 5)
        merged = merge_expositions({"job-a": r1.expose(), "job-b": r2.expose()})
        assert merged.count("# TYPE mtpu_retries_total counter") == 1
        assert "# job:" not in merged
        assert 'mtpu_retries_total{job="job-a"} 2.0' in merged
        assert 'mtpu_retries_total{job="job-b"} 5.0' in merged
        # histogram child series stay grouped under the parent's single header
        assert merged.count("# TYPE mtpu_queue_wait_seconds histogram") == 1
        assert 'le="+Inf",job="job-a"' in merged

    def test_push_and_cli_metrics(self, tmp_path, capsys):
        from modal_examples_tpu.observability.export import (
            push_metrics_file, read_pushed_metrics,
        )

        reg = Registry()
        reg.counter_inc("mtpu_retries_total", 3, labels={"reason": "timeout"})
        path = push_metrics_file("bench", reg, root=tmp_path)
        assert path is not None and path.exists()
        merged = read_pushed_metrics(tmp_path)
        assert 'reason="timeout"' in merged and 'job="bench"' in merged

        assert cli_main(["metrics", "--dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "mtpu_retries_total" in out

    def test_empty_registry_not_pushed(self, tmp_path):
        from modal_examples_tpu.observability.export import push_metrics_file

        assert push_metrics_file("empty", Registry(), root=tmp_path) is None


# ---------------------------------------------------------------------------
# gateway built-in endpoints
# ---------------------------------------------------------------------------


class TestGatewayEndpoints:
    def test_metrics_and_traces_endpoints(self):
        from modal_examples_tpu.web.gateway import Gateway

        call = traced_square.spawn(6)
        assert call.get(timeout=30) == 36

        gw = Gateway(app).start()
        try:
            # user route still wins
            with urllib.request.urlopen(
                f"{gw.base_url}/hello_endpoint?name=x", timeout=10
            ) as r:
                assert json.loads(r.read()) == {"hello": "x"}
            with urllib.request.urlopen(
                f"{gw.base_url}/metrics", timeout=10
            ) as r:
                body = r.read().decode()
                assert r.headers["content-type"].startswith("text/plain")
            assert "mtpu_call_duration_seconds" in body
            with urllib.request.urlopen(
                f"{gw.base_url}/traces/{call.call_id}", timeout=10
            ) as r:
                payload = json.loads(r.read())
            names = {s["name"] for s in payload["spans"]}
            assert {"call", "queue", "execute"} <= names
            with urllib.request.urlopen(
                f"{gw.base_url}/traces", timeout=10
            ) as r:
                listing = json.loads(r.read())
            assert call.call_id in listing["traces"]
        finally:
            gw.stop()


# ---------------------------------------------------------------------------
# catalog hygiene
# ---------------------------------------------------------------------------


class TestCatalog:
    def test_catalog_names_follow_conventions(self):
        for name, meta in C.CATALOG.items():
            assert name.startswith("mtpu_")
            if meta["type"] == "counter":
                assert name.endswith("_total"), name
            assert isinstance(meta["labels"], list)
            assert meta["help"]

    def test_all_metric_names_matches_catalog(self):
        assert C.ALL_METRIC_NAMES == frozenset(C.CATALOG)
